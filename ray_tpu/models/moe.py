"""Mixtral-family sparse MoE decoder LM with expert parallelism, TPU-first.

The reference has no native MoE/expert-parallel implementation — it passes
``enable_expert_parallel`` through to vLLM engine kwargs (SURVEY.md §2.4).
Here EP is a mesh axis: expert weights are sharded over ``ep`` and token
dispatch/combine are einsums against a static-capacity one-hot dispatch
tensor (GShard-style), so XLA emits the token all-to-all from the shardings
alone.  Everything is static-shape: top-k routing, capacity dropping, and
combine are MXU-friendly dense ops — no ragged gathers.

Attention/norm/rope are shared with the Llama block (models/llama.py).
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp

from ray_tpu.models.llama import _attention, rms_norm, rope
from ray_tpu.parallel.sharding import logical_spec as L


@dataclass(frozen=True)
class MoEConfig:
    vocab_size: int = 32000
    d_model: int = 4096
    n_layers: int = 32
    n_heads: int = 32
    n_kv_heads: int = 8
    d_ff: int = 14336
    n_experts: int = 8
    experts_per_token: int = 2
    capacity_factor: float = 1.25
    aux_loss_weight: float = 0.01
    max_seq_len: int = 32768
    rope_theta: float = 1_000_000.0
    norm_eps: float = 1e-5
    dtype: str = "bfloat16"
    remat: bool = True

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads

    @staticmethod
    def mixtral_8x7b() -> "MoEConfig":
        return MoEConfig()

    @staticmethod
    def tiny(vocab_size: int = 512) -> "MoEConfig":
        return MoEConfig(vocab_size=vocab_size, d_model=128, n_layers=2,
                         n_heads=4, n_kv_heads=2, d_ff=256, n_experts=4,
                         experts_per_token=2, max_seq_len=256, remat=False)


def param_logical_specs(cfg: MoEConfig):
    layer = {
        "attn": {
            "wq": L("layers", "embed", "heads"),
            "wk": L("layers", "embed", "kv_heads"),
            "wv": L("layers", "embed", "kv_heads"),
            "wo": L("layers", "heads", "embed"),
        },
        "router": L("layers", "embed", None),
        "experts": {
            "w_gate": L("layers", "experts", "embed", "expert_mlp"),
            "w_up": L("layers", "experts", "embed", "expert_mlp"),
            "w_down": L("layers", "experts", "expert_mlp", "embed"),
        },
        "attn_norm": L("layers", "norm"),
        "mlp_norm": L("layers", "norm"),
    }
    return {
        "embed": L("vocab", "embed"),
        "layers": layer,
        "final_norm": L("norm",),
        "lm_head": L("embed", "vocab"),
    }


def init(cfg: MoEConfig, key: jax.Array):
    k_embed, k_layers, k_head = jax.random.split(key, 3)
    d, nl, ne = cfg.d_model, cfg.n_layers, cfg.n_experts
    hq = cfg.n_heads * cfg.head_dim
    hkv = cfg.n_kv_heads * cfg.head_dim

    def dense(key, shape, fan_in):
        return jax.random.normal(key, shape, jnp.float32) * (fan_in ** -0.5)

    ks = jax.random.split(k_layers, 8)
    layers = {
        "attn": {
            "wq": dense(ks[0], (nl, d, hq), d),
            "wk": dense(ks[1], (nl, d, hkv), d),
            "wv": dense(ks[2], (nl, d, hkv), d),
            "wo": dense(ks[3], (nl, hq, d), hq),
        },
        "router": dense(ks[4], (nl, d, ne), d),
        "experts": {
            "w_gate": dense(ks[5], (nl, ne, d, cfg.d_ff), d),
            "w_up": dense(ks[6], (nl, ne, d, cfg.d_ff), d),
            "w_down": dense(ks[7], (nl, ne, cfg.d_ff, d), cfg.d_ff),
        },
        "attn_norm": jnp.ones((nl, d), jnp.float32),
        "mlp_norm": jnp.ones((nl, d), jnp.float32),
    }
    return {
        "embed": dense(k_embed, (cfg.vocab_size, d), d) * (d ** 0.5) * 0.02,
        "layers": layers,
        "final_norm": jnp.ones((d,), jnp.float32),
        "lm_head": dense(k_head, (d, cfg.vocab_size), d),
    }


def expert_capacity(cfg: MoEConfig, n_tokens: int) -> int:
    """Static per-expert token capacity, rounded up to a multiple of 8."""
    c = int(n_tokens * cfg.experts_per_token * cfg.capacity_factor
            / cfg.n_experts)
    return max(8, -(-c // 8) * 8)


def moe_mlp(cfg: MoEConfig, x, router_w, experts):
    """Top-k routed expert MLP.  x: (B, S, D) -> (out (B, S, D), aux_loss).

    Dispatch/combine are dense einsums against a (tokens, experts, capacity)
    one-hot; with experts sharded over ``ep`` XLA turns these contractions
    into the EP all-to-all.  Tokens over an expert's capacity are dropped
    (their residual stream passes through unchanged), as in GShard/Switch.
    """
    b, s, d = x.shape
    n = b * s
    e, k = cfg.n_experts, cfg.experts_per_token
    cap = expert_capacity(cfg, n)
    xf = x.reshape(n, d)

    logits = (xf.astype(jnp.float32) @ router_w.astype(jnp.float32))  # (N, E)
    probs = jax.nn.softmax(logits, axis=-1)
    top_p, top_idx = jax.lax.top_k(probs, k)  # (N, k)
    top_p = top_p / jnp.sum(top_p, axis=-1, keepdims=True)  # Mixtral renorm

    # Position of each (token, choice) in its expert's buffer.  Priority is
    # choice-major (all first choices before any second choice) so a token's
    # primary expert wins capacity contention.
    choice_onehot = jax.nn.one_hot(top_idx, e, dtype=jnp.float32)  # (N, k, E)
    flat = choice_onehot.transpose(1, 0, 2).reshape(k * n, e)
    pos_flat = jnp.cumsum(flat, axis=0) - flat  # (k*N, E) position per slot
    pos = pos_flat.reshape(k, n, e).transpose(1, 0, 2)  # (N, k, E)
    pos_in_expert = jnp.sum(pos * choice_onehot, axis=-1)  # (N, k)
    keep = pos_in_expert < cap  # capacity drop mask

    # (N, k, E, C) collapsed over k -> dispatch (N, E, C)
    cap_onehot = jax.nn.one_hot(pos_in_expert.astype(jnp.int32), cap,
                                dtype=jnp.float32)
    dispatch = jnp.einsum("nke,nkc,nk->nec", choice_onehot, cap_onehot,
                          keep.astype(jnp.float32))
    combine = jnp.einsum("nec,nke,nk->nec", dispatch, choice_onehot, top_p)

    compute_dtype = x.dtype
    expert_in = jnp.einsum("nec,nd->ecd", dispatch.astype(compute_dtype), xf)
    gate = jax.nn.silu(jnp.einsum(
        "ecd,edf->ecf", expert_in, experts["w_gate"].astype(compute_dtype)))
    up = jnp.einsum("ecd,edf->ecf", expert_in,
                    experts["w_up"].astype(compute_dtype))
    expert_out = jnp.einsum("ecf,efd->ecd", gate * up,
                            experts["w_down"].astype(compute_dtype))
    out = jnp.einsum("nec,ecd->nd", combine.astype(compute_dtype), expert_out)

    # Switch-style load-balancing auxiliary loss: E * sum_e f_e * p_e where
    # f_e = fraction of tokens whose TOP choice is e, p_e = mean router prob.
    top1 = jax.nn.one_hot(top_idx[:, 0], e, dtype=jnp.float32)
    f = jnp.mean(top1, axis=0)
    p = jnp.mean(probs, axis=0)
    aux = e * jnp.sum(f * p)
    return out.reshape(b, s, d), aux


def _layer(cfg: MoEConfig, carry, layer_params, positions, attn_impl, mesh,
           rules):
    x, aux_sum = carry
    p = layer_params
    b, s, d = x.shape
    h = rms_norm(x, p["attn_norm"], cfg.norm_eps)
    q = (h @ p["attn"]["wq"].astype(h.dtype)).reshape(
        b, s, cfg.n_heads, cfg.head_dim)
    k = (h @ p["attn"]["wk"].astype(h.dtype)).reshape(
        b, s, cfg.n_kv_heads, cfg.head_dim)
    v = (h @ p["attn"]["wv"].astype(h.dtype)).reshape(
        b, s, cfg.n_kv_heads, cfg.head_dim)
    q = rope(q, positions, cfg.rope_theta)
    k = rope(k, positions, cfg.rope_theta)
    attn = _attention(q, k, v, attn_impl, mesh, rules)
    attn = attn.reshape(b, s, cfg.n_heads * cfg.head_dim)
    x = x + attn @ p["attn"]["wo"].astype(h.dtype)

    h = rms_norm(x, p["mlp_norm"], cfg.norm_eps)
    moe_out, aux = moe_mlp(cfg, h, p["router"], p["experts"])
    return (x + moe_out, aux_sum + aux)


def apply(params, tokens, cfg: MoEConfig, attn_impl: str = "auto",
          mesh=None, rules=None, return_aux: bool = False):
    """Forward: tokens (B, S) -> logits (B, S, vocab) [, aux_loss]."""
    dtype = jnp.dtype(cfg.dtype)
    x = params["embed"][tokens].astype(dtype)
    positions = jnp.arange(tokens.shape[1])[None, :]

    step = partial(_layer, cfg, positions=positions, attn_impl=attn_impl,
                   mesh=mesh, rules=rules)
    if cfg.remat:
        step = jax.checkpoint(step)

    def scan_body(carry, layer_params):
        return step(carry, layer_params), None

    (x, aux), _ = jax.lax.scan(
        scan_body, (x, jnp.zeros((), jnp.float32)), params["layers"])
    x = rms_norm(x, params["final_norm"], cfg.norm_eps)
    logits = x.astype(jnp.float32) @ params["lm_head"]
    aux = aux / cfg.n_layers
    return (logits, aux) if return_aux else logits


def loss_fn(params, tokens, cfg: MoEConfig, attn_impl: str = "auto",
            mesh=None, rules=None):
    """Next-token CE + load-balancing aux loss."""
    logits, aux = apply(params, tokens[:, :-1], cfg, attn_impl, mesh=mesh,
                        rules=rules, return_aux=True)
    targets = tokens[:, 1:]
    logz = jax.scipy.special.logsumexp(logits, axis=-1)
    gold = jnp.take_along_axis(logits, targets[..., None], axis=-1)[..., 0]
    return jnp.mean(logz - gold) + cfg.aux_loss_weight * aux
