"""GPT-2 decoder LM (BASELINE config 1: 124M single-chip trainer).

Same functional conventions as models/llama.py: dict pytrees, scan-stacked
layers, logical sharding specs.  Learned positional embeddings, pre-LN,
GELU MLP, untied LM head off the tied embedding (GPT-2 ties them).
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp

from ray_tpu.ops.attention import flash_attention
from ray_tpu.parallel.sharding import logical_spec as L


@dataclass(frozen=True)
class GPT2Config:
    vocab_size: int = 50257
    d_model: int = 768
    n_layers: int = 12
    n_heads: int = 12
    max_seq_len: int = 1024
    norm_eps: float = 1e-5
    dtype: str = "bfloat16"
    remat: bool = False
    # sequence-chunked cross-entropy (models/losses.py): avoids the
    # (batch, seq, vocab) fp32 logits tensor; 0 disables chunking
    loss_chunk: int = 256

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads

    @property
    def d_ff(self) -> int:
        return 4 * self.d_model

    @staticmethod
    def gpt2_124m() -> "GPT2Config":
        return GPT2Config()

    @staticmethod
    def tiny(vocab_size: int = 512) -> "GPT2Config":
        return GPT2Config(vocab_size=vocab_size, d_model=64, n_layers=2,
                          n_heads=2, max_seq_len=128)


def param_logical_specs(cfg: GPT2Config):
    layer = {
        "attn": {
            "wqkv": L("layers", "embed", "heads"),
            "bqkv": L("layers", "heads"),
            "wo": L("layers", "heads", "embed"),
            "bo": L("layers", "norm"),
        },
        "mlp": {
            "w_in": L("layers", "embed", "mlp"),
            "b_in": L("layers", "mlp"),
            "w_out": L("layers", "mlp", "embed"),
            "b_out": L("layers", "norm"),
        },
        "ln1_g": L("layers", "norm"),
        "ln1_b": L("layers", "norm"),
        "ln2_g": L("layers", "norm"),
        "ln2_b": L("layers", "norm"),
    }
    return {
        "wte": L("vocab", "embed"),
        "wpe": L(None, "embed"),
        "layers": layer,
        "lnf_g": L("norm",),
        "lnf_b": L("norm",),
    }


def init(cfg: GPT2Config, key: jax.Array):
    kte, kpe, kl = jax.random.split(key, 3)
    d, nl = cfg.d_model, cfg.n_layers

    def dense(key, shape, std=0.02):
        return jax.random.normal(key, shape, jnp.float32) * std

    ks = jax.random.split(kl, 4)
    # GPT-2 scales residual-out projections by 1/sqrt(2*n_layers).
    res_std = 0.02 / (2 * nl) ** 0.5
    layers = {
        "attn": {
            "wqkv": dense(ks[0], (nl, d, 3 * d)),
            "bqkv": jnp.zeros((nl, 3 * d), jnp.float32),
            "wo": dense(ks[1], (nl, d, d), res_std),
            "bo": jnp.zeros((nl, d), jnp.float32),
        },
        "mlp": {
            "w_in": dense(ks[2], (nl, d, cfg.d_ff)),
            "b_in": jnp.zeros((nl, cfg.d_ff), jnp.float32),
            "w_out": dense(ks[3], (nl, cfg.d_ff, d), res_std),
            "b_out": jnp.zeros((nl, d), jnp.float32),
        },
        "ln1_g": jnp.ones((nl, d), jnp.float32),
        "ln1_b": jnp.zeros((nl, d), jnp.float32),
        "ln2_g": jnp.ones((nl, d), jnp.float32),
        "ln2_b": jnp.zeros((nl, d), jnp.float32),
    }
    return {
        "wte": dense(kte, (cfg.vocab_size, d)),
        "wpe": dense(kpe, (cfg.max_seq_len, d), 0.01),
        "layers": layers,
        "lnf_g": jnp.ones((d,), jnp.float32),
        "lnf_b": jnp.zeros((d,), jnp.float32),
    }


def layer_norm(x, g, b, eps):
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.var(xf, axis=-1, keepdims=True)
    out = (xf - mean) * jax.lax.rsqrt(var + eps)
    return (out * g + b).astype(x.dtype)


def _layer(cfg: GPT2Config, x, p, attn_impl):
    b, s, d = x.shape
    h = layer_norm(x, p["ln1_g"], p["ln1_b"], cfg.norm_eps)
    qkv = h @ p["attn"]["wqkv"].astype(h.dtype) + p["attn"]["bqkv"].astype(
        h.dtype)
    q, k, v = jnp.split(qkv, 3, axis=-1)
    shape = (b, s, cfg.n_heads, cfg.head_dim)
    attn = flash_attention(q.reshape(shape), k.reshape(shape),
                           v.reshape(shape), causal=True, impl=attn_impl)
    attn = attn.reshape(b, s, d)
    x = x + attn @ p["attn"]["wo"].astype(h.dtype) + p["attn"]["bo"].astype(
        h.dtype)

    h = layer_norm(x, p["ln2_g"], p["ln2_b"], cfg.norm_eps)
    h = jax.nn.gelu(h @ p["mlp"]["w_in"].astype(h.dtype)
                    + p["mlp"]["b_in"].astype(h.dtype), approximate=True)
    x = x + h @ p["mlp"]["w_out"].astype(h.dtype) + p["mlp"]["b_out"].astype(
        h.dtype)
    return x


def trunk(params, tokens, cfg: GPT2Config, attn_impl: str = "auto"):
    """Embeddings -> final layer norm, WITHOUT the LM head: (b, s, d)."""
    dtype = jnp.dtype(cfg.dtype)
    s = tokens.shape[1]
    x = (params["wte"][tokens] + params["wpe"][:s][None]).astype(dtype)

    step = partial(_layer, cfg, attn_impl=attn_impl)
    if cfg.remat:
        step = jax.checkpoint(step)

    def scan_body(x, layer_params):
        return step(x, layer_params), None

    x, _ = jax.lax.scan(scan_body, x, params["layers"])
    return layer_norm(x, params["lnf_g"], params["lnf_b"], cfg.norm_eps)


def apply(params, tokens, cfg: GPT2Config, attn_impl: str = "auto"):
    x = trunk(params, tokens, cfg, attn_impl)
    # tied LM head: bf16 operands with fp32 accumulation — the MXU's
    # native mode (an fp32 matmul here halves the headline throughput)
    return jnp.dot(x, params["wte"].T.astype(x.dtype),
                   preferred_element_type=jnp.float32)


def loss_fn(params, tokens, cfg: GPT2Config, attn_impl: str = "auto"):
    from ray_tpu.models.losses import chunked_softmax_xent

    x = trunk(params, tokens[:, :-1], cfg, attn_impl)
    return chunked_softmax_xent(x, params["wte"].T, tokens[:, 1:],
                                chunk=cfg.loss_chunk)
