"""Loss heads tuned for the TPU memory system.

The naive LM loss materializes fp32 logits of shape (batch, seq, vocab) —
for GPT-2 124M at batch 8 x seq 1024 that is a 1.6 GB tensor written to and
re-read from HBM, and the head matmul runs off the MXU's fast path when its
inputs are fp32.  ``chunked_softmax_xent`` instead:

- keeps the head matmul in bf16 with fp32 accumulation
  (``preferred_element_type``) — the MXU's native mode;
- scans over sequence chunks so only (batch, chunk, vocab) logits ever
  exist, with ``jax.checkpoint`` on the chunk so the backward pass
  recomputes chunk logits instead of storing them.

No reference counterpart: the reference delegates loss math to
torch/vLLM (SURVEY §2.4); this is TPU-native net-new.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def chunked_softmax_xent(x: jax.Array, head: jax.Array, targets: jax.Array,
                         chunk: int = 256) -> jax.Array:
    """Mean next-token cross-entropy without materializing full logits.

    x:       (batch, seq, d_model) activations (any float dtype; bf16 keeps
             the matmul on the MXU fast path)
    head:    (d_model, vocab) output projection (tied embeddings: pass
             ``wte.T`` — XLA folds the transpose into the dot)
    targets: (batch, seq) int32 gold next tokens
    """
    b, s, _ = x.shape

    def nll(xch, tch, mch):
        logits = jnp.dot(xch, head.astype(xch.dtype),
                         preferred_element_type=jnp.float32)
        logz = jax.scipy.special.logsumexp(logits, axis=-1)
        gold = jnp.take_along_axis(logits, tch[..., None], axis=-1)[..., 0]
        return jnp.sum((logz - gold) * mch)

    if chunk <= 0 or chunk >= s:
        # single pass: no recompute; fine whenever (b, s, vocab) fits HBM
        return nll(x, targets, jnp.ones((b, s), x.dtype)) / (b * s)
    # pad the sequence up to a chunk multiple (LM losses see seq-1 tokens,
    # which is odd for every even seq — a divisibility requirement would
    # make the chunked path dead code); pads are masked out of the sum
    pad = (-s) % chunk
    mask = jnp.ones((b, s), x.dtype)
    if pad:
        x = jnp.pad(x, ((0, 0), (0, pad), (0, 0)))
        targets = jnp.pad(targets, ((0, 0), (0, pad)))
        mask = jnp.pad(mask, ((0, 0), (0, pad)))
    n = (s + pad) // chunk
    xc = x.reshape(b, n, chunk, x.shape[-1]).swapaxes(0, 1)
    tc = targets.reshape(b, n, chunk).swapaxes(0, 1)
    mc = mask.reshape(b, n, chunk).swapaxes(0, 1)
    chunk_nll = jax.checkpoint(nll)

    def body(carry, xt):
        xch, tch, mch = xt
        return carry + chunk_nll(xch, tch, mch), None

    total, _ = jax.lax.scan(body, jnp.zeros((), jnp.float32), (xc, tc, mc))
    return total / (b * s)
