"""Llama-family decoder LM, TPU-first.

Plain functional JAX: params are nested-dict pytrees, layers are stacked on a
leading axis and iterated with ``lax.scan`` (O(1) compile time in depth), and
every parameter carries a *logical* sharding spec (parallel/sharding.py) so
the same definition runs single-chip, FSDP, TP, or any mesh combination.
The reference delegates this entire layer to torch/vLLM engines; here it is
native (SURVEY.md §2.4, §7 step 7).
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp

from ray_tpu.ops.attention import flash_attention
from ray_tpu.parallel.sharding import logical_spec as L


@dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 128256
    d_model: int = 4096
    n_layers: int = 32
    n_heads: int = 32
    n_kv_heads: int = 8
    d_ff: int = 14336
    max_seq_len: int = 8192
    rope_theta: float = 500_000.0
    norm_eps: float = 1e-5
    dtype: str = "bfloat16"
    remat: bool = True  # checkpoint each layer: recompute activations in bwd
    # sequence-chunked cross-entropy (models/losses.py): avoids the
    # (batch, seq, vocab) fp32 logits tensor; 0 disables chunking
    loss_chunk: int = 256

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads

    @staticmethod
    def llama3_8b() -> "LlamaConfig":
        return LlamaConfig()

    @staticmethod
    def llama3_70b() -> "LlamaConfig":
        return LlamaConfig(d_model=8192, n_layers=80, n_heads=64,
                           n_kv_heads=8, d_ff=28672)

    @staticmethod
    def tiny(vocab_size: int = 512) -> "LlamaConfig":
        """For tests and multichip dry runs."""
        return LlamaConfig(vocab_size=vocab_size, d_model=128, n_layers=2,
                           n_heads=4, n_kv_heads=2, d_ff=256,
                           max_seq_len=256, remat=False)

    @staticmethod
    def llama3_8b_dry(vocab_size: int = 512) -> "LlamaConfig":
        """8B-SHAPED dry config: the llama3_8b geometry ratios (4:1 GQA,
        3.5x FFN, head_dim 32) at tiny scale, so a dry run exercises the
        EXACT sharding structure of the v5e-16 8B recipe
        (train/llama3.py) without 8B of parameters."""
        return LlamaConfig(vocab_size=vocab_size, d_model=256, n_layers=4,
                           n_heads=8, n_kv_heads=2, d_ff=896,
                           max_seq_len=512, remat=True, loss_chunk=128)


def param_logical_specs(cfg: LlamaConfig):
    """Logical sharding spec tree, mirroring init()'s param tree."""
    layer = {
        "attn": {
            "wq": L("layers", "embed", "heads"),
            "wk": L("layers", "embed", "kv_heads"),
            "wv": L("layers", "embed", "kv_heads"),
            "wo": L("layers", "heads", "embed"),
        },
        "mlp": {
            "w_gate": L("layers", "embed", "mlp"),
            "w_up": L("layers", "embed", "mlp"),
            "w_down": L("layers", "mlp", "embed"),
        },
        "attn_norm": L("layers", "norm"),
        "mlp_norm": L("layers", "norm"),
    }
    return {
        "embed": L("vocab", "embed"),
        "layers": layer,
        "final_norm": L("norm",),
        "lm_head": L("embed", "vocab"),
    }


def init(cfg: LlamaConfig, key: jax.Array):
    """Initialize parameters (fp32 master weights)."""
    k_embed, k_layers, k_head = jax.random.split(key, 3)
    d, nl = cfg.d_model, cfg.n_layers
    hq = cfg.n_heads * cfg.head_dim
    hkv = cfg.n_kv_heads * cfg.head_dim

    def dense(key, shape, fan_in):
        return (jax.random.normal(key, shape, jnp.float32)
                * (fan_in ** -0.5))

    ks = jax.random.split(k_layers, 7)
    layers = {
        "attn": {
            "wq": dense(ks[0], (nl, d, hq), d),
            "wk": dense(ks[1], (nl, d, hkv), d),
            "wv": dense(ks[2], (nl, d, hkv), d),
            "wo": dense(ks[3], (nl, hq, d), hq),
        },
        "mlp": {
            "w_gate": dense(ks[4], (nl, d, cfg.d_ff), d),
            "w_up": dense(ks[5], (nl, d, cfg.d_ff), d),
            "w_down": dense(ks[6], (nl, cfg.d_ff, d), cfg.d_ff),
        },
        "attn_norm": jnp.ones((nl, d), jnp.float32),
        "mlp_norm": jnp.ones((nl, d), jnp.float32),
    }
    return {
        "embed": dense(k_embed, (cfg.vocab_size, d), d) * (d ** 0.5) * 0.02,
        "layers": layers,
        "final_norm": jnp.ones((d,), jnp.float32),
        "lm_head": dense(k_head, (d, cfg.vocab_size), d),
    }


def rms_norm(x, weight, eps):
    var = jnp.mean(jnp.square(x.astype(jnp.float32)), axis=-1, keepdims=True)
    return (x * jax.lax.rsqrt(var + eps)).astype(x.dtype) * weight.astype(
        x.dtype)


def rope(x, positions, theta):
    """Rotary embedding; x: (..., seq, heads, head_dim)."""
    head_dim = x.shape[-1]
    freqs = theta ** (-jnp.arange(0, head_dim // 2, dtype=jnp.float32)
                      / (head_dim // 2))
    angles = positions[..., None].astype(jnp.float32) * freqs  # (.., s, d/2)
    cos = jnp.cos(angles)[..., None, :]  # broadcast over heads
    sin = jnp.sin(angles)[..., None, :]
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate([x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1)
    return out.astype(x.dtype)


def _attention(q, k, v, attn_impl, mesh, rules=None):
    """Dispatch dense flash vs sequence-parallel attention
    (ring / zigzag-balanced ring / ulysses)."""
    if attn_impl in ("ring", "zigzag", "ulysses"):
        from ray_tpu.ops.ring_attention import sequence_parallel_attention

        if mesh is None:
            raise ValueError(f"attn_impl={attn_impl!r} requires a mesh")
        return sequence_parallel_attention(q, k, v, mesh, impl=attn_impl,
                                           causal=True, rules=rules)
    return flash_attention(q, k, v, causal=True, impl=attn_impl)


def _layer(cfg: LlamaConfig, x, layer_params, positions, attn_impl, mesh,
           rules):
    p = layer_params
    b, s, d = x.shape
    h = rms_norm(x, p["attn_norm"], cfg.norm_eps)
    q = (h @ p["attn"]["wq"].astype(h.dtype)).reshape(
        b, s, cfg.n_heads, cfg.head_dim)
    k = (h @ p["attn"]["wk"].astype(h.dtype)).reshape(
        b, s, cfg.n_kv_heads, cfg.head_dim)
    v = (h @ p["attn"]["wv"].astype(h.dtype)).reshape(
        b, s, cfg.n_kv_heads, cfg.head_dim)
    q = rope(q, positions, cfg.rope_theta)
    k = rope(k, positions, cfg.rope_theta)
    attn = _attention(q, k, v, attn_impl, mesh, rules)
    attn = attn.reshape(b, s, cfg.n_heads * cfg.head_dim)
    x = x + attn @ p["attn"]["wo"].astype(h.dtype)

    h = rms_norm(x, p["mlp_norm"], cfg.norm_eps)
    gate = jax.nn.silu(h @ p["mlp"]["w_gate"].astype(h.dtype))
    up = h @ p["mlp"]["w_up"].astype(h.dtype)
    x = x + (gate * up) @ p["mlp"]["w_down"].astype(h.dtype)
    return x


def trunk(params, tokens, cfg: LlamaConfig, attn_impl: str = "auto",
          mesh=None, rules=None):
    """Embeddings -> final RMS norm, WITHOUT the LM head: (b, s, d).

    Layers run under lax.scan over the stacked layer params; each step is
    optionally rematerialized (jax.checkpoint) to trade FLOPs for HBM.
    attn_impl "ring"/"ulysses" (with a mesh) enables sequence-parallel
    attention over the sp axis for long-context training.
    """
    dtype = jnp.dtype(cfg.dtype)
    x = params["embed"][tokens].astype(dtype)
    positions = jnp.arange(tokens.shape[1])[None, :]

    step = partial(_layer, cfg, positions=positions, attn_impl=attn_impl,
                   mesh=mesh, rules=rules)
    if cfg.remat:
        step = jax.checkpoint(step)

    def scan_body(x, layer_params):
        return step(x, layer_params), None

    x, _ = jax.lax.scan(scan_body, x, params["layers"])
    return rms_norm(x, params["final_norm"], cfg.norm_eps)


def apply(params, tokens, cfg: LlamaConfig, attn_impl: str = "auto",
          mesh=None, rules=None):
    """Forward pass: tokens (batch, seq) int32 -> logits (batch, seq, vocab)."""
    x = trunk(params, tokens, cfg, attn_impl, mesh=mesh, rules=rules)
    # bf16 operands, fp32 accumulation (preferred_element_type) — the
    # MXU's native mode; logits come out fp32 for a stable softmax.
    return jnp.dot(x, params["lm_head"].astype(x.dtype),
                   preferred_element_type=jnp.float32)


def loss_fn(params, tokens, cfg: LlamaConfig, attn_impl: str = "auto",
            mesh=None, rules=None):
    """Next-token cross-entropy; tokens (batch, seq)."""
    from ray_tpu.models.losses import chunked_softmax_xent

    x = trunk(params, tokens[:, :-1], cfg, attn_impl, mesh=mesh, rules=rules)
    return chunked_softmax_xent(x, params["lm_head"], tokens[:, 1:],
                                chunk=cfg.loss_chunk)
