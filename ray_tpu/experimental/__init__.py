"""ray_tpu.experimental: device objects (direct transport).

Counterpart of /root/reference/python/ray/experimental/ (GPU objects /
RDT surface).
"""

from ray_tpu._private.device_objects import (
    DeviceObjectMarker,
    get_device_object,
    free_device_object,
)

__all__ = ["DeviceObjectMarker", "free_device_object",
           "get_device_object"]
