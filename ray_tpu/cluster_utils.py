"""In-process multi-node cluster for tests and local experimentation.

Counterpart of the reference's test workhorse
(/root/reference/python/ray/cluster_utils.py:135 ``Cluster``): a head node
(GCS service + scheduler + store) plus N worker nodes, each with its OWN
object store (separate shm segment) and worker pool, joined through the
head's GCS address.  Two node flavors:

- in-process (default): node services run as threads in the calling
  process — workers are real subprocesses either way.
- external (``add_node(external=True)``): the whole node runs as a
  SEPARATE OS PROCESS (ray_tpu._private.node_main) joined over TCP —
  the same process/transport topology a multi-host deployment has
  (reference: ray start-launched raylet processes, SURVEY §3.1).
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import tempfile
import time
from typing import Optional, Union

from ray_tpu._private.node import Node


class ExternalNode:
    """Handle to a node running as its own OS process (node_main)."""

    def __init__(self, proc: subprocess.Popen, info: dict):
        self.proc = proc
        self.node_id = bytes.fromhex(info["node_id"])
        self.gcs_address = info["gcs_address"]
        self.sched_address = info["sched_address"]
        self.session_dir = info["session_dir"]

    def shutdown(self, timeout: float = 10.0):
        if self.proc.poll() is None:
            self.proc.send_signal(signal.SIGTERM)
            try:
                self.proc.wait(timeout=timeout)
            except subprocess.TimeoutExpired:
                self.proc.kill()

    def kill(self):
        """Hard-kill the node process (crash simulation — no cleanup)."""
        if self.proc.poll() is None:
            self.proc.kill()
            self.proc.wait(timeout=10)


class Cluster:
    def __init__(self, initialize_head: bool = True,
                 head_node_args: Optional[dict] = None):
        self.head_node: Optional[Node] = None
        self.worker_nodes: list[Union[Node, ExternalNode]] = []
        if initialize_head:
            self.add_node(**(head_node_args or {}))

    @property
    def gcs_address(self) -> str:
        return self.head_node.gcs_address

    def add_node(self, external: bool = False, **node_args) -> Union[
            Node, ExternalNode]:
        """Start one more node; the first becomes the head.

        external=True launches the node as a separate OS process over TCP
        (requires the head to listen on TCP too: pass
        head_node_args={"listen_host": "127.0.0.1"}).
        """
        if external:
            if self.head_node is None:
                raise ValueError("start the head in-process first "
                                 "(head drives the test)")
            node = self._spawn_external(**node_args)
            self.worker_nodes.append(node)
            return node
        if self.head_node is None:
            node = Node(head=True, **node_args)
            self.head_node = node
        else:
            node = Node(head=False, gcs_address=self.gcs_address,
                        **node_args)
            self.worker_nodes.append(node)
        return node

    def _spawn_external(self, resources: Optional[dict] = None,
                        min_workers: int = 1,
                        max_workers: Optional[int] = None,
                        object_store_memory: Optional[int] = None,
                        listen_host: Optional[str] = None,
                        **unsupported) -> ExternalNode:
        if unsupported:
            raise TypeError(
                f"external nodes do not support node args "
                f"{sorted(unsupported)}")
        ready = tempfile.mktemp(prefix="rtpu_node_ready_")
        host = listen_host or self.head_node.listen_host or "127.0.0.1"
        cmd = [sys.executable, "-m", "ray_tpu._private.node_main",
               "--address", self.gcs_address,
               "--listen-host", host,
               "--min-workers", str(min_workers),
               "--ready-file", ready]
        if max_workers is not None:
            cmd += ["--max-workers", str(max_workers)]
        if object_store_memory is not None:
            cmd += ["--object-store-memory", str(object_store_memory)]
        if resources:
            cmd += ["--resources", json.dumps(resources)]
        env = dict(os.environ)
        pkg_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        env["PYTHONPATH"] = pkg_root + os.pathsep + env.get("PYTHONPATH", "")
        proc = subprocess.Popen(cmd, env=env)
        deadline = time.monotonic() + 60.0
        while not os.path.exists(ready):
            if proc.poll() is not None:
                raise RuntimeError(
                    f"external node exited rc={proc.returncode} at startup")
            if time.monotonic() > deadline:
                proc.kill()
                raise TimeoutError("external node did not come up in 60s")
            time.sleep(0.05)
        with open(ready) as f:
            info = json.load(f)
        os.unlink(ready)
        return ExternalNode(proc, info)

    def remove_node(self, node: Union[Node, ExternalNode],
                    allow_graceful: bool = True):
        """Stop a node and broadcast its death (reference:
        Cluster.remove_node kills the raylet; GCS health checks notice).

        allow_graceful=False skips the immediate GCS notification so death
        is discovered by heartbeat timeout — the crash-like path."""
        if node is self.head_node:
            raise ValueError("removing the head node tears down the "
                             "cluster; use shutdown()")
        if node in self.worker_nodes:
            self.worker_nodes.remove(node)
        if isinstance(node, ExternalNode):
            if allow_graceful:
                node.shutdown()
            else:
                node.kill()
        else:
            node.shutdown()
        if allow_graceful and self.head_node is not None:
            self.head_node.gcs.mark_node_dead(node.node_id)

    def wait_for_nodes(self, timeout: float = 30.0) -> int:
        """Block until every added node is alive in the GCS; returns the
        live count."""
        want = 1 + len(self.worker_nodes)
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            alive = len([n for n in self.head_node.gcs.list_nodes()
                         if n.alive])
            if alive >= want:
                return alive
            time.sleep(0.05)
        raise TimeoutError(
            f"only {alive}/{want} nodes alive after {timeout}s")

    def shutdown(self):
        for node in self.worker_nodes:
            node.shutdown()
        self.worker_nodes = []
        if self.head_node is not None:
            self.head_node.shutdown()
            self.head_node = None


class AutoscalingCluster:
    """A head node + a live autoscaler over the fake (local-process) node
    provider — the reference's AutoscalingCluster
    (/root/reference/python/ray/cluster_utils.py:26) run against its fake
    multi-node provider, for autoscaling tests with no cloud."""

    def __init__(self, head_resources: Optional[dict] = None,
                 autoscaler_config=None, **node_args):
        from ray_tpu.autoscaler import (
            AutoscalerConfig,
            FakeNodeProvider,
            StandardAutoscaler,
        )

        self.cluster = Cluster(head_node_args={
            "resources": head_resources, **node_args})
        self.provider = FakeNodeProvider(self.cluster.gcs_address)
        self.autoscaler = StandardAutoscaler(
            self.cluster.head_node.gcs, self.provider,
            autoscaler_config or AutoscalerConfig())

    def start(self):
        self.autoscaler.start()

    def shutdown(self):
        self.autoscaler.shutdown()
        self.cluster.shutdown()
