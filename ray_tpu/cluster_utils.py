"""In-process multi-node cluster for tests and local experimentation.

Counterpart of the reference's test workhorse
(/root/reference/python/ray/cluster_utils.py:135 ``Cluster``): a head node
(GCS service + scheduler + store) plus N worker nodes, each with its OWN
object store (separate shm segment) and worker pool, joined through the
head's GCS socket.  Node services run as threads in the calling process —
workers are real subprocesses either way, so scheduling, spillback, object
transfer, and node-death recovery exercise the same code paths a multi-host
deployment would.
"""

from __future__ import annotations

import time
from typing import Optional

from ray_tpu._private.node import Node


class Cluster:
    def __init__(self, initialize_head: bool = True,
                 head_node_args: Optional[dict] = None):
        self.head_node: Optional[Node] = None
        self.worker_nodes: list[Node] = []
        if initialize_head:
            self.add_node(**(head_node_args or {}))

    @property
    def gcs_address(self) -> str:
        return self.head_node.gcs_address

    def add_node(self, **node_args) -> Node:
        """Start one more node; the first becomes the head."""
        if self.head_node is None:
            node = Node(head=True, **node_args)
            self.head_node = node
        else:
            node = Node(head=False, gcs_address=self.gcs_address,
                        **node_args)
            self.worker_nodes.append(node)
        return node

    def remove_node(self, node: Node, allow_graceful: bool = True):
        """Stop a node and broadcast its death (reference:
        Cluster.remove_node kills the raylet; GCS health checks notice).

        allow_graceful=False skips the immediate GCS notification so death
        is discovered by heartbeat timeout — the crash-like path."""
        if node is self.head_node:
            raise ValueError("removing the head node tears down the "
                             "cluster; use shutdown()")
        if node in self.worker_nodes:
            self.worker_nodes.remove(node)
        node.shutdown()
        if allow_graceful and self.head_node is not None:
            self.head_node.gcs.mark_node_dead(node.node_id)

    def wait_for_nodes(self, timeout: float = 30.0) -> int:
        """Block until every added node is alive in the GCS; returns the
        live count."""
        want = 1 + len(self.worker_nodes)
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            alive = len([n for n in self.head_node.gcs.list_nodes()
                         if n.alive])
            if alive >= want:
                return alive
            time.sleep(0.05)
        raise TimeoutError(
            f"only {alive}/{want} nodes alive after {timeout}s")

    def shutdown(self):
        for node in self.worker_nodes:
            node.shutdown()
        self.worker_nodes = []
        if self.head_node is not None:
            self.head_node.shutdown()
            self.head_node = None


class AutoscalingCluster:
    """A head node + a live autoscaler over the fake (local-process) node
    provider — the reference's AutoscalingCluster
    (/root/reference/python/ray/cluster_utils.py:26) run against its fake
    multi-node provider, for autoscaling tests with no cloud."""

    def __init__(self, head_resources: Optional[dict] = None,
                 autoscaler_config=None, **node_args):
        from ray_tpu.autoscaler import (
            AutoscalerConfig,
            FakeNodeProvider,
            StandardAutoscaler,
        )

        self.cluster = Cluster(head_node_args={
            "resources": head_resources, **node_args})
        self.provider = FakeNodeProvider(self.cluster.gcs_address)
        self.autoscaler = StandardAutoscaler(
            self.cluster.head_node.gcs, self.provider,
            autoscaler_config or AutoscalerConfig())

    def start(self):
        self.autoscaler.start()

    def shutdown(self):
        self.autoscaler.shutdown()
        self.cluster.shutdown()
