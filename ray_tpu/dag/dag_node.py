"""DAG node model: lazy graphs of actor-method calls.

Counterpart of the reference DAG API
(/root/reference/python/ray/dag/dag_node.py, input_node.py,
class_node.py): ``actor.method.bind(...)`` builds ``ClassMethodNode``s over
``InputNode``; ``dag.execute(x)`` runs eagerly through normal task
submission; ``dag.experimental_compile()`` lowers the graph onto
pre-allocated shm channels + resident per-actor execution loops
(ray_tpu.dag.compiled).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple


class DAGNode:
    """Base: a lazily-evaluated node. Subclasses define _eval."""

    def __init__(self, args: Tuple, kwargs: Dict[str, Any]):
        self._bound_args = tuple(args)
        self._bound_kwargs = dict(kwargs)

    # -- traversal ---------------------------------------------------------
    def _children(self) -> List["DAGNode"]:
        out = [a for a in self._bound_args if isinstance(a, DAGNode)]
        out += [v for v in self._bound_kwargs.values()
                if isinstance(v, DAGNode)]
        return out

    def topo_sort(self) -> List["DAGNode"]:
        order, seen = [], set()

        def visit(n: DAGNode):
            if id(n) in seen:
                return
            seen.add(id(n))
            for c in n._children():
                visit(c)
            order.append(n)

        visit(self)
        return order

    # -- eager execution ---------------------------------------------------
    def execute(self, *input_vals, _memo: Optional[dict] = None):
        """Run the DAG through normal task submission; returns ObjectRef(s)."""
        memo: dict = {} if _memo is None else _memo
        input_val = input_vals[0] if input_vals else None
        return _eval(self, input_val, memo)

    def experimental_compile(self, buffer_size: int = 16,
                             submit_timeout: Optional[float] = None):
        from ray_tpu.dag.compiled import CompiledDAG
        return CompiledDAG(self, buffer_size=buffer_size)


def _eval(node, input_val, memo):
    if not isinstance(node, DAGNode):
        return node
    if id(node) in memo:
        return memo[id(node)]
    result = node._eval(input_val, memo)
    memo[id(node)] = result
    return result


class InputNode(DAGNode):
    """The DAG's runtime input. Context-manager use mirrors the reference:

        with InputNode() as inp:
            dag = a.f.bind(inp)
    """

    def __init__(self):
        super().__init__((), {})

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def __getitem__(self, key):
        return InputAttributeNode(self, key)

    def __getattr__(self, key):
        if key.startswith("_"):
            raise AttributeError(key)
        return InputAttributeNode(self, key)

    def _eval(self, input_val, memo):
        return input_val


class InputAttributeNode(DAGNode):
    """inp[key] / inp.key — one field of a dict/sequence input."""

    def __init__(self, parent: InputNode, key):
        super().__init__((parent,), {})
        self._key = key

    def _eval(self, input_val, memo):
        base = _eval(self._bound_args[0], input_val, memo)
        if isinstance(self._key, str) and not isinstance(base, dict):
            return getattr(base, self._key)
        return base[self._key]


class ClassMethodNode(DAGNode):
    """actor.method.bind(*args, **kwargs)."""

    def __init__(self, actor_handle, method_name: str, args, kwargs):
        super().__init__(args, kwargs)
        self._actor = actor_handle
        self._method_name = method_name

    def _eval(self, input_val, memo):
        args = [_eval(a, input_val, memo) for a in self._bound_args]
        kwargs = {k: _eval(v, input_val, memo)
                  for k, v in self._bound_kwargs.items()}
        method = getattr(self._actor, self._method_name)
        return method.remote(*args, **kwargs)

    def __repr__(self):
        return (f"ClassMethodNode({self._actor._class_name}."
                f"{self._method_name})")


class MultiOutputNode(DAGNode):
    """Bundle several leaves into one DAG output (list of results)."""

    def __init__(self, outputs: List[DAGNode]):
        super().__init__(tuple(outputs), {})

    def _eval(self, input_val, memo):
        return [_eval(a, input_val, memo) for a in self._bound_args]
