"""Shared-memory channels: the compiled-DAG data plane.

Counterpart of the reference's mutable-object channels
(/root/reference/src/ray/core_worker/experimental_mutable_object_manager.h:44,
python/ray/experimental/channel/shared_memory_channel.py). The reference
implements a writer/reader semaphore protocol over one mutable plasma buffer;
here a channel is a bounded ring of *immutable* store objects — write ``seq``
seals object ``h(chan_id, seq)``, read ``seq`` gets (and frees) it — which
keeps the store's single immutability invariant and still moves arrays
zero-copy through shm. Backpressure: the reader acks its read sequence into
the GCS KV; the writer blocks once it is ``capacity`` messages ahead (the
KV round-trip is only paid when the ring is actually full). Cross-node reads
ride the normal object-transfer pull path, so a channel between actors on
different hosts needs no extra machinery.
"""

from __future__ import annotations

import hashlib
import time
from typing import Optional

import numpy as np

from ray_tpu._private import worker as worker_mod
from ray_tpu.core.object_ref import ObjectRef

_KV_NS = "dag_channel"
_POLL_S = 0.001


class ChannelClosed(Exception):
    pass


class _Stop:
    """Sentinel flowing through channels on teardown."""

    def __repr__(self):
        return "<dag stop>"


STOP = _Stop()


class _Spill:
    """Marker streamed through a native ring when the payload was too large
    and spilled through the object store. A dedicated class (not a dict key)
    so no user payload can ever be mistaken for it."""

    __slots__ = ("oid",)

    def __init__(self, oid: bytes):
        self.oid = oid

    def __repr__(self):
        return f"<dag spill {self.oid.hex()[:8]}>"


def _ctx():
    w = worker_mod.global_worker()
    if w is None:
        raise RuntimeError("ray_tpu not initialized in this process")
    return w


class Channel:
    """One writer, one reader, bounded capacity. Pickles to the same channel
    (id + capacity travel; seq state is per-process endpoint state).

    Two transports, chosen at compile time per edge:
    - ``native=True`` (both endpoints on one node): the C++ mutable shm
      ring (native/mutable_channel.cc) — kernel-blocking, one memcpy per
      side, no store or KV traffic. Messages larger than the ring spill
      through the object store transparently.
    - ``native=False`` (cross-node): immutable store objects + KV-acked
      ring backpressure; reads ride the normal object-transfer pull path.
    """

    def __init__(self, chan_id: bytes, capacity: int = 16,
                 native: bool = False):
        self.chan_id = chan_id
        self.capacity = capacity
        self.native = native
        self._wseq = 0
        self._rseq = 0
        self._acked = -1
        self._native_chan = None

    def __reduce__(self):
        return (Channel, (self.chan_id, self.capacity, self.native))

    def _native(self):
        if self._native_chan is None:
            from ray_tpu.dag.native_channel import NativeChannel

            self._native_chan = NativeChannel(
                f"/rtpu_chan_{self.chan_id.hex()}")
        return self._native_chan

    def unlink_native(self) -> None:
        """Reclaim this channel's shm segment on THIS host (no-op for
        store-transport channels or if never created here)."""
        if not self.native:
            return
        try:
            from ray_tpu.dag.native_channel import _load

            _load().mc_unlink(f"/rtpu_chan_{self.chan_id.hex()}".encode())
        except Exception:
            pass

    def _oid(self, seq: int) -> bytes:
        return hashlib.sha1(
            self.chan_id + seq.to_bytes(8, "little")).digest()[:20]

    def _ack_key(self) -> bytes:
        return b"ack/" + self.chan_id

    # -- writer end --------------------------------------------------------
    def write(self, value, timeout: Optional[float] = None) -> None:
        ctx = _ctx()
        if self.native:
            try:
                self._native().write(value, timeout=timeout)
            except ValueError:
                # larger than the ring: spill payload through the store,
                # stream a small marker so ordering is preserved
                ref = ctx.put_object(value)
                self._native().write(_Spill(ref.binary()), timeout=timeout)
            return
        if self._wseq - self._acked > self.capacity:
            deadline = None if timeout is None else time.monotonic() + timeout
            while True:
                raw = ctx.rpc("kv_get", {"namespace": _KV_NS,
                                         "key": self._ack_key()})
                if raw is not None:
                    ack = int.from_bytes(raw, "little", signed=True)
                    if ack > self._acked:
                        # reader consumed up to ack: reclaim our local copies
                        for s in range(max(0, self._acked), ack + 1):
                            try:
                                ctx.store.delete(self._oid(s))
                            except Exception:
                                pass
                        self._acked = ack
                if self._wseq - self._acked <= self.capacity:
                    break
                if deadline is not None and time.monotonic() > deadline:
                    raise TimeoutError(
                        f"channel {self.chan_id.hex()[:8]} write timed out "
                        f"(reader {self._wseq - self._acked} behind)")
                time.sleep(_POLL_S)
        ctx.put_object(value, oid=self._oid(self._wseq))
        self._wseq += 1

    # -- reader end --------------------------------------------------------
    def read(self, timeout: Optional[float] = None):
        ctx = _ctx()
        if self.native:
            value = self._native().read(timeout=timeout)
            if isinstance(value, _Spill):
                oid = value.oid
                value = ctx.get_object(ObjectRef(oid), timeout=timeout)
                try:
                    ctx.store.delete(oid)
                except Exception:
                    pass
            if isinstance(value, _Stop):
                raise ChannelClosed()
            return value
        value = ctx.get_object(ObjectRef(self._oid(self._rseq)),
                               timeout=timeout)
        if isinstance(value, np.ndarray):
            # Own the data before the backing shm buffer can be reclaimed by
            # the writer once we ack.
            value = np.array(value)
        try:
            ctx.store.delete(self._oid(self._rseq))
        except Exception:
            pass
        ctx.rpc("kv_put", {
            "namespace": _KV_NS, "key": self._ack_key(),
            "value": self._rseq.to_bytes(8, "little", signed=True)})
        self._rseq += 1
        if isinstance(value, _Stop):
            raise ChannelClosed()
        return value
