"""ctypes binding for the native mutable shm channel.

See ray_tpu/native/mutable_channel.cc (counterpart of the reference's
mutable-object channels). One writer, one reader, same host. Payloads are
the same serialization the object store uses (tagged pickle/array bytes),
so arrays ride through with a single memcpy each side.
"""

from __future__ import annotations

import ctypes
import os
from typing import Optional

from ray_tpu._private.serialization import deserialize, serialized_size, write_payload
# The C side stamps CHANNEL_MAGIC ("RTPUCHA") into the segment header
# last, so mc_open rejects half-initialized segments; the drift pass
# (`rtpu check`) pins mutable_channel.cc's kMagic to this anchor.
from ray_tpu._private.wire_constants import CHANNEL_MAGIC


class NativeChannelClosed(Exception):
    pass


_lib = None


def _load():
    global _lib
    if _lib is None:
        from ray_tpu.native.build import binary_path

        lib = ctypes.CDLL(binary_path("libmutable_channel"))
        lib.mc_create.restype = ctypes.c_void_p
        lib.mc_create.argtypes = [ctypes.c_char_p, ctypes.c_uint64]
        lib.mc_open.restype = ctypes.c_void_p
        lib.mc_open.argtypes = [ctypes.c_char_p]
        lib.mc_write.restype = ctypes.c_int
        lib.mc_write.argtypes = [ctypes.c_void_p, ctypes.c_void_p,
                                 ctypes.c_uint64, ctypes.c_int]
        lib.mc_read.restype = ctypes.c_int64
        lib.mc_read.argtypes = [ctypes.c_void_p, ctypes.c_void_p,
                                ctypes.c_uint64, ctypes.c_int]
        lib.mc_next_len.restype = ctypes.c_int64
        lib.mc_next_len.argtypes = [ctypes.c_void_p]
        lib.mc_close_channel.argtypes = [ctypes.c_void_p]
        lib.mc_release.argtypes = [ctypes.c_void_p]
        lib.mc_unlink.restype = ctypes.c_int
        lib.mc_unlink.argtypes = [ctypes.c_char_p]
        _lib = lib
    return _lib


class NativeChannel:
    """Open (creating if first) a named mutable channel."""

    def __init__(self, name: str, capacity: int = 1 << 22):
        self._name = name.encode()
        self._lib = _load()
        handle = self._lib.mc_create(self._name, capacity)
        if not handle:
            # creator may still be mid-init (magic not yet set): brief retry
            import time as _time

            for _ in range(200):
                handle = self._lib.mc_open(self._name)
                if handle:
                    break
                _time.sleep(0.005)
        if not handle:
            raise OSError(
                f"could not create/open native channel {name} (header "
                f"magic {CHANNEL_MAGIC:#x} never appeared: creator died "
                "mid-init or the segment is foreign)")
        self._handle = handle
        self._buf = ctypes.create_string_buffer(1 << 16)

    _CHUNK_MS = 60_000  # timeout=None waits forever in bounded C-side slices

    def write(self, value, timeout: Optional[float] = None) -> None:
        size, token = serialized_size(value)
        payload = bytearray(size)
        write_payload(memoryview(payload), token)
        # zero-copy hand-off: C memcpys straight out of the bytearray
        buf = (ctypes.c_char * size).from_buffer(payload)
        ms = None if timeout is None else int(timeout * 1000)
        while True:
            rc = self._lib.mc_write(
                self._handle, buf, size,
                self._CHUNK_MS if ms is None else ms)
            if rc == -1 and ms is None:
                continue  # infinite wait: keep blocking in bounded slices
            break
        if rc == -1:
            raise TimeoutError("native channel write timed out")
        if rc == -2:
            raise NativeChannelClosed()
        if rc == -3:
            raise ValueError(f"message of {size} bytes exceeds channel "
                             f"capacity")

    def read(self, timeout: Optional[float] = None):
        ms = None if timeout is None else int(timeout * 1000)
        while True:
            n = self._lib.mc_read(
                self._handle, self._buf, len(self._buf),
                self._CHUNK_MS if ms is None else ms)
            if n == -4:
                need = self._lib.mc_next_len(self._handle)
                if need > 0:
                    self._buf = ctypes.create_string_buffer(int(need))
                    continue
                continue
            if n == -1 and ms is None:
                continue  # infinite wait: keep blocking in bounded slices
            break
        if n == -1:
            raise TimeoutError("native channel read timed out")
        if n == -2:
            raise NativeChannelClosed()
        # own the bytes before the ring buffer slot is reused: arrays
        # deserialize zero-copy over this immutable copy
        payload = self._buf.raw[: int(n)]
        return deserialize(memoryview(payload))

    def close(self) -> None:
        self._lib.mc_close_channel(self._handle)

    def release(self) -> None:
        if self._handle:
            self._lib.mc_release(self._handle)
            self._handle = None

    def unlink(self) -> None:
        self._lib.mc_unlink(self._name)

    def __del__(self):
        try:
            self.release()
        except Exception:
            pass
