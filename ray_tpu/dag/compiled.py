"""Compiled DAG execution: resident actor loops over shm channels.

Counterpart of the reference's CompiledDAG
(/root/reference/python/ray/dag/compiled_dag_node.py:808, ExecutableTask
:481): compilation pre-allocates one channel per data edge and starts a
background execution loop *inside* each participating actor (via the hidden
``__rtpu_apply__`` method), so steady-state execution moves data
driver→actors→driver purely through the shm channel plane — no per-call task
submission, no scheduler round-trips. This is the substrate pipeline
parallelism uses for cross-stage hand-off (SURVEY.md §2.4 PP row).

Error semantics: an exception in one stage flows downstream as an
``_ExcPayload`` and is raised at ``ref.get()``; the loops keep running, so a
bad input doesn't wedge the pipeline (teardown() stops everything).
"""

from __future__ import annotations

import os
import threading
import traceback
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu.dag.channel import STOP, Channel, ChannelClosed
from ray_tpu.dag.dag_node import (
    ClassMethodNode,
    DAGNode,
    InputAttributeNode,
    InputNode,
    MultiOutputNode,
)


class _ExcPayload:
    def __init__(self, exc: BaseException, tb: str):
        self.exc = exc
        self.tb = tb


def _dag_actor_loop(instance, method_name: str,
                    arg_specs: List[Tuple[str, Any]],
                    kwarg_specs: Dict[str, Tuple[str, Any]],
                    out_channels: List[Channel]) -> None:
    """Runs inside the actor process: start the resident loop thread."""

    def loop():
        method = getattr(instance, method_name)
        while True:
            try:
                args, kwargs, poisoned = [], {}, None
                try:
                    for kind, v in arg_specs:
                        val = v.read() if kind == "chan" else v
                        if isinstance(val, _ExcPayload):
                            poisoned = val
                        args.append(val)
                    for k, (kind, v) in kwarg_specs.items():
                        val = v.read() if kind == "chan" else v
                        if isinstance(val, _ExcPayload):
                            poisoned = val
                        kwargs[k] = val
                except ChannelClosed:
                    for ch in out_channels:
                        try:
                            # bounded: a dead downstream with a full ring
                            # must not wedge this thread forever (cleanup
                            # below still has to run)
                            ch.write(STOP, timeout=5.0)
                        except Exception:
                            pass
                    # reader-side shm cleanup: the driver can only unlink
                    # segments on ITS host, so each loop reclaims its own
                    # node's in-edges (unlink keeps live mappings valid)
                    for kind, v in list(arg_specs) + list(
                            kwarg_specs.values()):
                        if kind == "chan":
                            v.unlink_native()
                    return
                if poisoned is not None:
                    result = poisoned  # propagate, don't execute
                else:
                    try:
                        result = method(*args, **kwargs)
                    except BaseException as e:  # noqa: BLE001
                        result = _ExcPayload(e, traceback.format_exc())
                for ch in out_channels:
                    ch.write(result)
            except BaseException:  # loop must survive transient store errors
                traceback.print_exc()
                return

    t = threading.Thread(target=loop, name=f"dag-loop-{method_name}",
                         daemon=True)
    t.start()
    loops = getattr(instance, "_rtpu_dag_loops", None)
    if loops is None:
        loops = []
        try:
            instance._rtpu_dag_loops = loops
        except Exception:
            pass
    loops.append(t)


def _dag_noop(_instance):
    return None


class CompiledDAGRef:
    """Result handle for one CompiledDAG.execute call."""

    def __init__(self, dag: "CompiledDAG", seq: int):
        self._dag = dag
        self._seq = seq

    def get(self, timeout: Optional[float] = None):
        return self._dag._fetch(self._seq, timeout)


class CompiledDAG:
    def __init__(self, root: DAGNode, buffer_size: int = 16):
        self._root = root
        self._buffer_size = buffer_size
        self._seq = 0
        self._results: Dict[int, Any] = {}
        self._next_read = 0
        self._torn_down = False
        self._lock = threading.Lock()
        self._compile()

    # -- compilation -------------------------------------------------------
    def _new_channel(self, writer_node, reader_node) -> Channel:
        # same-node edges ride the native mutable shm ring; cross-node (or
        # unknown, e.g. client-mode driver) edges use the store transport
        native = (writer_node is not None and writer_node == reader_node)
        return Channel(os.urandom(16), capacity=self._buffer_size,
                       native=native)

    def _compile(self):
        order = self._root.topo_sort()

        # Resolve actor placement first (channel transport selection):
        # one no-op round also guarantees every actor finished creation.
        from ray_tpu import api
        from ray_tpu._private.worker import global_worker

        actor_handles = {}
        for n in order:
            if isinstance(n, ClassMethodNode):
                actor_handles[n._actor.actor_id] = n._actor
        actor_node: dict = {}
        if actor_handles:
            api.get([a.__rtpu_apply__.remote(_dag_noop)
                     for a in actor_handles.values()])
            for row in global_worker().rpc("list_actors", {}):
                actor_node[row["actor_id"]] = row["node_id"]
        drv = getattr(global_worker(), "node", None)
        driver_node = drv.node_id if drv is not None else None
        self._input_node = None
        for n in order:
            if isinstance(n, InputNode):
                if self._input_node is not None and n is not self._input_node:
                    raise ValueError("a DAG can have only one InputNode")
                self._input_node = n

        # Output leaves: MultiOutputNode's children, else the root itself.
        if isinstance(self._root, MultiOutputNode):
            leaves = list(self._root._bound_args)
            self._multi_output = True
        else:
            leaves = [self._root]
            self._multi_output = False
        for leaf in leaves:
            if not isinstance(leaf, ClassMethodNode):
                raise ValueError(
                    f"compiled DAG outputs must be actor method calls, got "
                    f"{type(leaf).__name__}")

        # One channel per (consumer, slot) dynamic edge; writers fan out.
        # node id -> list of channels its result feeds
        fanout: Dict[int, List[Channel]] = {}
        # channels the driver writes each execute(): (channel, key-or-None)
        self._input_feeds: List[Tuple[Channel, Any]] = []
        node_specs: Dict[int, Tuple[ClassMethodNode, list, dict]] = {}

        def spec_for(value, consumer_node) -> Tuple[str, Any]:
            if isinstance(value, InputNode):
                ch = self._new_channel(driver_node, consumer_node)
                self._input_feeds.append((ch, None))
                return ("chan", ch)
            if isinstance(value, InputAttributeNode):
                ch = self._new_channel(driver_node, consumer_node)
                self._input_feeds.append((ch, value._key))
                return ("chan", ch)
            if isinstance(value, ClassMethodNode):
                ch = self._new_channel(
                    actor_node.get(value._actor.actor_id), consumer_node)
                fanout.setdefault(id(value), []).append(ch)
                return ("chan", ch)
            if isinstance(value, DAGNode):
                raise ValueError(
                    f"unsupported node in compiled DAG: {type(value).__name__}")
            return ("const", value)

        for n in order:
            if isinstance(n, ClassMethodNode):
                consumer = actor_node.get(n._actor.actor_id)
                arg_specs = [spec_for(a, consumer) for a in n._bound_args]
                kwarg_specs = {k: spec_for(v, consumer)
                               for k, v in n._bound_kwargs.items()}
                node_specs[id(n)] = (n, arg_specs, kwarg_specs)

        # Driver-read output channels, one per leaf.
        self._output_channels: List[Channel] = []
        for leaf in leaves:
            ch = self._new_channel(
                actor_node.get(leaf._actor.actor_id), driver_node)
            fanout.setdefault(id(leaf), []).append(ch)
            self._output_channels.append(ch)

        # Start the resident loops (one __rtpu_apply__ round, await all).
        self._stop_feeds = [ch for ch, _ in self._input_feeds]
        self._all_channels = (
            [ch for ch, _ in self._input_feeds]
            + self._output_channels
            + [ch for chans in fanout.values() for ch in chans])
        refs = []
        for _, (node, arg_specs, kwarg_specs) in node_specs.items():
            outs = fanout.get(id(node), [])
            refs.append(node._actor.__rtpu_apply__.remote(
                _dag_actor_loop, node._method_name, arg_specs, kwarg_specs,
                outs))
        api.get(refs)

    # -- execution ---------------------------------------------------------
    def execute(self, *input_vals) -> CompiledDAGRef:
        if self._torn_down:
            raise RuntimeError("compiled DAG was torn down")
        input_val = input_vals[0] if input_vals else None
        with self._lock:
            for ch, key in self._input_feeds:
                if key is None:
                    ch.write(input_val)
                elif isinstance(key, str) and not isinstance(input_val, dict):
                    ch.write(getattr(input_val, key))
                else:
                    ch.write(input_val[key])
            ref = CompiledDAGRef(self, self._seq)
            self._seq += 1
        return ref

    def _fetch(self, seq: int, timeout: Optional[float]):
        with self._lock:
            while seq not in self._results:
                vals = [ch.read(timeout=timeout)
                        for ch in self._output_channels]
                self._results[self._next_read] = (
                    vals if self._multi_output else vals[0])
                self._next_read += 1
            result = self._results.pop(seq)
        payloads = result if isinstance(result, list) else [result]
        for p in payloads:
            if isinstance(p, _ExcPayload):
                raise p.exc
        return result

    def teardown(self):
        if self._torn_down:
            return
        self._torn_down = True
        for ch in self._stop_feeds:
            try:
                ch.write(STOP, timeout=5.0)
            except Exception:
                pass
        # reclaim driver-host shm segments once the stop has flowed
        # through; each actor loop unlinks its own node's in-edges on exit
        def _unlink_later(channels=list({id(c): c
                                         for c in self._all_channels
                                         }.values())):
            import time as _time

            _time.sleep(0.2)
            for ch in channels:
                ch.unlink_native()

        threading.Thread(target=_unlink_later, daemon=True).start()
