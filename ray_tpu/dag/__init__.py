"""ray_tpu.dag: lazy DAGs of actor calls + compiled channel execution.

Counterpart of /root/reference/python/ray/dag/ (aDAG / compiled graphs).
"""

from ray_tpu.dag.channel import Channel, ChannelClosed
from ray_tpu.dag.compiled import CompiledDAG, CompiledDAGRef
from ray_tpu.dag.dag_node import (
    ClassMethodNode,
    DAGNode,
    InputAttributeNode,
    InputNode,
    MultiOutputNode,
)

__all__ = [
    "Channel",
    "ChannelClosed",
    "ClassMethodNode",
    "CompiledDAG",
    "CompiledDAGRef",
    "DAGNode",
    "InputAttributeNode",
    "InputNode",
    "MultiOutputNode",
]
