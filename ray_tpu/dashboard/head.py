"""Dashboard head: REST + Prometheus over the state API.

Counterpart of /root/reference/python/ray/dashboard/head.py:48 (aiohttp REST
aggregating GCS + per-node sources) — without the React SPA: endpoints
return JSON (the reference's own /api payloads are JSON too), plus a tiny
HTML index for humans and a /metrics Prometheus scrape target that merges
every node's runtime gauges with app metrics pushed from workers
(ray_tpu.util.metrics).
"""

from __future__ import annotations

import asyncio
import json
import threading
from typing import Optional

from ray_tpu._private import protocol

_INDEX_HTML = """<!doctype html><title>ray_tpu dashboard</title>
<h1>ray_tpu dashboard</h1>
<ul>
<li><a href="/api/nodes">/api/nodes</a></li>
<li><a href="/api/actors">/api/actors</a></li>
<li><a href="/api/placement_groups">/api/placement_groups</a></li>
<li><a href="/api/jobs">/api/jobs</a></li>
<li><a href="/api/tasks/summary">/api/tasks/summary</a></li>
<li><a href="/api/cluster_status">/api/cluster_status</a></li>
<li><a href="/metrics">/metrics (Prometheus)</a></li>
</ul>"""


def _node_rpc(sock: str, method: str, params: Optional[dict] = None):
    conn = protocol.connect_addr(sock)
    try:
        conn.send({"t": "rpc", "method": method, "params": params or {}})
        resp = conn.recv()
    finally:
        conn.close()
    if resp is None or not resp.get("ok"):
        raise RuntimeError(f"dashboard rpc {method} failed")
    return resp["result"]


def _prom_escape(s: str) -> str:
    return s.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _render_prometheus(per_node: list[dict]) -> str:
    lines: list[str] = []
    # Node runtime gauges.
    for snap in per_node:
        rt = snap["runtime"]
        node = rt["node_id"].hex()[:12]
        for key in ("tasks_pending", "workers", "store_used_bytes",
                    "store_num_objects"):
            lines.append(
                f'ray_tpu_node_{key}{{node_id="{node}"}} {rt[key]}')
        for res, total in rt["resources"].items():
            avail = rt["available"].get(res, 0)
            rname = _prom_escape(str(res))
            lines.append(
                f'ray_tpu_resource_total{{node_id="{node}",'
                f'resource="{rname}"}} {total}')
            lines.append(
                f'ray_tpu_resource_available{{node_id="{node}",'
                f'resource="{rname}"}} {avail}')
        # App metrics pushed by this node's processes.
        for source in snap["app"]:
            for m in source:
                name = "ray_tpu_" + m["name"]
                if m["kind"] == "histogram":
                    for tagvals, h in m.get("hist", {}).items():
                        labels = _labels(m["tag_keys"], tagvals)
                        cum = 0
                        for b, c in zip(m["boundaries"], h):
                            cum += c
                            lines.append(
                                f'{name}_bucket{{{labels}le="{b}"}} {cum}')
                        cum += h[len(m["boundaries"])]
                        lines.append(
                            f'{name}_bucket{{{labels}le="+Inf"}} {cum}')
                        lines.append(f"{name}_count{{{labels[:-1]}}} {cum}"
                                     if labels else f"{name}_count {cum}")
                        lines.append(
                            f"{name}_sum{{{labels[:-1]}}} {h[-1]}"
                            if labels else f"{name}_sum {h[-1]}")
                else:
                    for tagvals, v in m.get("values", {}).items():
                        labels = _labels(m["tag_keys"], tagvals)
                        if labels:
                            lines.append(f"{name}{{{labels[:-1]}}} {v}")
                        else:
                            lines.append(f"{name} {v}")
    return "\n".join(lines) + "\n"


def _labels(tag_keys, tagvals) -> str:
    if not tag_keys:
        return ""
    pairs = ",".join(f'{k}="{_prom_escape(v)}"'
                     for k, v in zip(tag_keys, tagvals))
    return pairs + ","


class DashboardHead:
    """Serves on 127.0.0.1:<port> from a daemon thread with its own loop."""

    def __init__(self, gcs, head_sched_socket: str, port: int = 0):
        import aiohttp  # noqa: F401 — fail HERE, in the caller's thread

        self._gcs = gcs
        self._head_sock = head_sched_socket
        self._port = port
        self.url: Optional[str] = None
        self._started = threading.Event()
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._thread = threading.Thread(
            target=self._run, name="dashboard-head", daemon=True)
        self._thread.start()
        self._started.wait(timeout=10)
        if self.url is None:
            raise RuntimeError("dashboard server failed to start")

    # -- data sources ------------------------------------------------------
    def _sched_socks(self) -> list[str]:
        return [n.sched_socket for n in self._gcs.list_nodes() if n.alive]

    def _nodes(self):
        return [{
            "node_id": n.node_id.hex(), "alive": n.alive,
            "is_head": n.is_head, "resources": n.resources,
            "available": getattr(n, "available", {}),
        } for n in self._gcs.list_nodes()]

    def _actors(self):
        return [{
            "actor_id": a.actor_id.hex(), "name": a.name,
            "class_name": a.class_name, "state": a.state,
            "node_id": a.node_id.hex() if a.node_id else None,
            "num_restarts": a.num_restarts,
        } for a in self._gcs.list_actors()]

    def _pgs(self):
        out = []
        for pg_id, info in _node_rpc(self._head_sock, "pg_table").items():
            row = {"placement_group_id": pg_id.hex(), **info}
            if "assignment" in row:
                row["assignment"] = [
                    n.hex() if isinstance(n, bytes) else n
                    for n in row["assignment"]]
            out.append(row)
        return out

    def _jobs(self):
        try:
            return _node_rpc(self._head_sock, "job_list")
        except Exception:
            return []

    def _task_summary(self):
        from ray_tpu.util.state import summarize_events

        events = []
        for sock in self._sched_socks():
            try:
                events.extend(_node_rpc(sock, "list_task_events"))
            except Exception:
                continue
        return summarize_events(events)

    def _cluster_status(self):
        return _node_rpc(self._head_sock, "cluster_state")

    def _metrics_text(self):
        snaps = []
        for sock in self._sched_socks():
            try:
                snaps.append(_node_rpc(sock, "metrics_snapshot"))
            except Exception:
                continue
        return _render_prometheus(snaps)

    # -- server ------------------------------------------------------------
    def _run(self):
        from aiohttp import web

        loop = asyncio.new_event_loop()
        asyncio.set_event_loop(loop)
        self._loop = loop

        def json_handler(fn):
            async def handler(request):
                data = await loop.run_in_executor(None, fn)
                return web.Response(
                    text=json.dumps(data, default=str),
                    content_type="application/json")
            return handler

        async def index(request):
            return web.Response(text=_INDEX_HTML, content_type="text/html")

        async def metrics(request):
            text = await loop.run_in_executor(None, self._metrics_text)
            return web.Response(text=text, content_type="text/plain")

        app = web.Application()
        app.router.add_get("/", index)
        app.router.add_get("/api/nodes", json_handler(self._nodes))
        app.router.add_get("/api/actors", json_handler(self._actors))
        app.router.add_get("/api/placement_groups", json_handler(self._pgs))
        app.router.add_get("/api/jobs", json_handler(self._jobs))
        app.router.add_get("/api/tasks/summary",
                           json_handler(self._task_summary))
        app.router.add_get("/api/cluster_status",
                           json_handler(self._cluster_status))
        app.router.add_get("/metrics", metrics)

        async def start():
            runner = web.AppRunner(app, access_log=None)
            await runner.setup()
            site = web.TCPSite(runner, "127.0.0.1", self._port)
            await site.start()
            port = site._server.sockets[0].getsockname()[1]
            self.url = f"http://127.0.0.1:{port}"
            self._runner = runner
            self._started.set()

        try:
            loop.run_until_complete(start())
        except BaseException:
            self._started.set()  # unblock __init__, which raises on url=None
            loop.close()
            return
        try:
            loop.run_forever()
        finally:
            loop.run_until_complete(self._runner.cleanup())
            loop.close()

    def shutdown(self):
        if self._loop is not None and self._loop.is_running():
            self._loop.call_soon_threadsafe(self._loop.stop)
        self._thread.join(timeout=5)
