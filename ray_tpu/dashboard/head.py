"""Dashboard head: REST + Prometheus over the state API.

Counterpart of /root/reference/python/ray/dashboard/head.py:48 (aiohttp REST
aggregating GCS + per-node sources) — without the React SPA: endpoints
return JSON (the reference's own /api payloads are JSON too), plus a tiny
HTML index for humans and a /metrics Prometheus scrape target that merges
every node's runtime gauges with app metrics pushed from workers
(ray_tpu.util.metrics).
"""

from __future__ import annotations

import asyncio
import json
import os
import re
import threading
import time
from collections import deque
from typing import Optional

from ray_tpu._private import protocol

# The SPA (reference: python/ray/dashboard/client/ — a React/TS app; ours
# is a framework-free client in dashboard/client/) is served at "/"; the
# server-rendered /status page stays for curl/noscript use.
_CLIENT_DIR = os.path.join(os.path.dirname(__file__), "client")

_INDEX_HTML = """<!doctype html><title>ray_tpu dashboard API</title>
<h1>ray_tpu dashboard API</h1>
<ul>
<li><a href="/">/ (dashboard SPA)</a></li>
<li><a href="/status">/status (server-rendered cluster page)</a></li>
<li><a href="/api/nodes">/api/nodes</a></li>
<li><a href="/api/node_stats">/api/node_stats</a></li>
<li><a href="/api/actors">/api/actors</a></li>
<li><a href="/api/placement_groups">/api/placement_groups</a></li>
<li><a href="/api/jobs">/api/jobs</a></li>
<li><a href="/api/tasks/summary">/api/tasks/summary</a></li>
<li><a href="/api/cluster_status">/api/cluster_status</a></li>
<li><a href="/api/serve">/api/serve</a></li>
<li><a href="/api/serve/routing">/api/serve/routing (request-router stats: policy, queue depths, prefix-cache)</a></li>
<li><a href="/api/data/jobs">/api/data/jobs (data-service jobs; ?job=&lt;name&gt; for one)</a></li>
<li><a href="/api/traces">/api/traces (distributed traces; ?trace_id=&lt;hex&gt; for one tree)</a></li>
<li><a href="/api/profile">/api/profile (CPU profiles; ?id=&lt;profile_id&gt;&amp;format=speedscope|folded|raw)</a></li>
<li><a href="/api/goodput">/api/goodput (training goodput/step anatomy; ?run=&lt;name&gt; for one run)</a></li>
<li><a href="/api/memory">/api/memory (cluster objects by creation call site, store occupancy, leak report)</a></li>
<li><a href="/api/events">/api/events (cluster incident timeline; ?kind=&lt;prefix&gt;&amp;severity=&lt;s&gt;&amp;limit=&lt;n&gt;)</a></li>
<li><a href="/api/timeseries">/api/timeseries (metrics history ring; ?family=&lt;name&gt;&amp;window=&lt;sec&gt;)</a></li>
<li><a href="/api/slo">/api/slo (SLO rule table + burn rates)</a></li>
<li><a href="/metrics">/metrics (Prometheus)</a></li>
</ul>"""

_STATUS_CSS = """<style>
body{font-family:system-ui,sans-serif;margin:2em;color:#222}
table{border-collapse:collapse;margin:0 0 1.5em}
th,td{border:1px solid #ccc;padding:4px 10px;text-align:left;font-size:14px}
th{background:#f0f0f0}
h2{margin-bottom:.3em}
.dead{color:#b00}.alive{color:#080}
</style>"""


class _Raw(str):
    """A cell whose HTML is intentional (everything else gets escaped)."""


def _table(headers: list, rows: list) -> str:
    import html as _html

    def cell(c):
        return c if isinstance(c, _Raw) else _html.escape(str(c))

    head = "".join(f"<th>{_html.escape(str(h))}</th>" for h in headers)
    body = "".join(
        "<tr>" + "".join(f"<td>{cell(c)}</td>" for c in row) + "</tr>"
        for row in rows)
    return f"<table><tr>{head}</tr>{body}</table>"


def _node_rpc(sock: str, method: str, params: Optional[dict] = None):
    conn = protocol.connect_addr(sock)
    try:
        conn.send({"t": "rpc", "method": method, "params": params or {}})
        resp = conn.recv()
    finally:
        conn.close()
    if resp is None or not resp.get("ok"):
        raise RuntimeError(f"dashboard rpc {method} failed")
    return resp["result"]


def _prom_escape(s: str) -> str:
    return s.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _help_escape(s: str) -> str:
    # HELP text escapes only backslash and line feed (exposition format)
    return s.replace("\\", "\\\\").replace("\n", "\\n")


_NAME_BAD = re.compile(r"[^a-zA-Z0-9_:]")
_LABEL_BAD = re.compile(r"[^a-zA-Z0-9_]")


def _prom_name(name: str) -> str:
    """Sanitize to the exposition-format name charset
    ([a-zA-Z_:][a-zA-Z0-9_:]*): dots/dashes become underscores."""
    name = _NAME_BAD.sub("_", str(name))
    if not name or name[0].isdigit():
        name = "_" + name
    return name


def _label_name(name: str) -> str:
    name = _LABEL_BAD.sub("_", str(name))
    if not name or name[0].isdigit():
        name = "_" + name
    return name


def _label_str(pairs) -> str:
    if not pairs:
        return ""
    return ("{" + ",".join(f'{k}="{_prom_escape(str(v))}"'
                           for k, v in pairs) + "}")


def _render_prometheus(per_node: list[dict]) -> str:
    """Valid Prometheus exposition text: one # HELP/# TYPE header per
    metric family, sanitized names, and same-name series from different
    processes/nodes MERGED (counters/histograms sum, matching what a
    single registry would report) — duplicate series are a parse error."""
    fams: dict[str, dict] = {}

    def fam(name: str, kind: str, help_: str) -> dict:
        f = fams.get(name)
        if f is None:
            f = fams[name] = {"kind": kind, "help": help_,
                              "series": {}, "hist": {}, "boundaries": None}
        return f

    def add_series(f: dict, labels: tuple, value):
        f["series"][labels] = f["series"].get(labels, 0) + value

    _NODE_GAUGES = {
        "tasks_pending": "Tasks queued on the node scheduler",
        "workers": "Alive worker processes on the node",
        "store_used_bytes": "Object store bytes in use on the node",
        "store_num_objects": "Objects resident in the node's store",
        "store_capacity_bytes": "Object store capacity on the node",
        "store_occupancy": "Object store used/capacity fraction",
        "store_fragmentation":
            "Free-space fragmentation (1 - largest_free/free)",
        "store_free_blocks": "Free-list blocks in the node's store",
        "store_largest_free_bytes":
            "Largest contiguous free block in the node's store",
        "store_evictions_total": "Objects lossily evicted (no spill copy)",
        "store_spills_total": "Objects spilled to disk under pressure",
        "store_spilled_bytes": "Bytes currently spilled to disk",
    }
    for snap in per_node:
        rt = snap["runtime"]
        node = rt["node_id"].hex()[:12]
        for key, help_ in _NODE_GAUGES.items():
            if key not in rt:  # audit gauges are best-effort per scrape
                continue
            f = fam(f"ray_tpu_node_{key}", "gauge", help_)
            # node_id makes these unique per node: set, don't sum
            f["series"][(("node_id", node),)] = rt[key]
        for res, total in rt["resources"].items():
            ft = fam("ray_tpu_resource_total", "gauge",
                     "Total resource capacity per node")
            fa = fam("ray_tpu_resource_available", "gauge",
                     "Currently available resource per node")
            lbl = (("node_id", node), ("resource", str(res)))
            ft["series"][lbl] = total
            fa["series"][lbl] = rt["available"].get(res, 0)
        # App metrics pushed by this node's processes.
        for source in snap["app"]:
            for m in source:
                name = _prom_name(m["name"])
                if not name.startswith("ray_tpu_"):
                    name = "ray_tpu_" + name
                kind = m.get("kind")
                if kind not in ("counter", "gauge", "histogram"):
                    kind = "untyped"
                f = fam(name, kind, m.get("description") or "")
                keys = tuple(_label_name(k)
                             for k in (m.get("tag_keys") or ()))
                if kind == "histogram":
                    b = tuple(m.get("boundaries") or ())
                    if f["boundaries"] is None:
                        f["boundaries"] = b
                    elif f["boundaries"] != b:
                        continue  # conflicting redeclaration: first wins
                    for tagvals, h in m.get("hist", {}).items():
                        lbl = tuple(zip(keys, tuple(tagvals)))
                        cur = f["hist"].get(lbl)
                        if cur is None:
                            f["hist"][lbl] = list(h)
                        elif len(cur) == len(h):
                            for i, c in enumerate(h):
                                cur[i] += c
                else:
                    for tagvals, v in m.get("values", {}).items():
                        add_series(f, tuple(zip(keys, tuple(tagvals))), v)

    lines: list[str] = []
    for name, f in fams.items():
        lines.append(f"# HELP {name} {_help_escape(f['help'])}")
        lines.append(f"# TYPE {name} {f['kind']}")
        if f["kind"] == "histogram":
            bounds = f["boundaries"] or ()
            for lbl, h in f["hist"].items():
                cum = 0
                for b, c in zip(bounds, h):
                    cum += c
                    lines.append(
                        f"{name}_bucket"
                        f"{_label_str(lbl + (('le', b),))} {cum}")
                cum += h[len(bounds)]
                lines.append(
                    f"{name}_bucket{_label_str(lbl + (('le', '+Inf'),))}"
                    f" {cum}")
                lines.append(f"{name}_count{_label_str(lbl)} {cum}")
                lines.append(f"{name}_sum{_label_str(lbl)} {h[-1]}")
        else:
            for lbl, v in f["series"].items():
                lines.append(f"{name}{_label_str(lbl)} {v}")
    return "\n".join(lines) + "\n"


class MetricsSampler:
    """The retained-signal plane: head-side sampling thread that turns
    point-in-time scrapes into queryable history and judged health.

    Every ``RTPU_TSDB_SAMPLE_S`` it (1) polls each alive node's
    ``metrics_snapshot`` into the ring TSDB (_private/tsdb.py), (2)
    drains each node's banked cluster events (incremental, per-node seq
    cursors) into one merged incident ring, (3) runs the SLO engine's
    burn-rate tick — alert transitions are pushed back onto the event
    plane (head scheduler bank: they hit the file exporter and the rings
    like any other incident) with the nearest recent incident's trace id
    stamped on a fire, and (4) exports current burn state as the
    ``slo_burn_rate``/``slo_healthy`` gauges via a plain metrics_push.

    Registers itself as tsdb.set_global_plane so the head scheduler's
    control socket serves query_timeseries/slo_status/tsdb_overview/
    tsdb_stats to the CLI and state API without HTTP in the loop.
    """

    def __init__(self, gcs, head_sched_socket: str):
        from ray_tpu._private import flags
        from ray_tpu._private import slo as slo_mod
        from ray_tpu._private import tsdb as tsdb_mod

        self._gcs = gcs
        self._head_sock = head_sched_socket
        self.sample_s = max(0.05, float(flags.get("RTPU_TSDB_SAMPLE_S")))
        self.tsdb = tsdb_mod.TSDB(
            points_per_series=max(2, int(flags.get("RTPU_TSDB_CAP"))),
            max_series=max(1, int(flags.get("RTPU_TSDB_MAX_SERIES"))))
        self.engine = slo_mod.SLOEngine(sample_s=self.sample_s)
        self._events: deque = deque(
            maxlen=max(1, int(flags.get("RTPU_EVENTS_CAP"))))
        self._cursors: dict[str, int] = {}  # node hex -> last seq seen
        self._lock = threading.Lock()
        self._stop = threading.Event()
        tsdb_mod.set_global_plane(self)
        self._thread = threading.Thread(
            target=self._loop, name="metrics-sampler", daemon=True)
        self._thread.start()

    def _loop(self):
        while not self._stop.wait(self.sample_s):
            try:
                self.tick()
            except Exception:
                pass  # a sick node or mid-shutdown GCS must not kill it

    def tick(self, now: Optional[float] = None):
        now = time.time() if now is None else now
        nodes = []
        try:
            nodes = [(n.node_id.hex(), n.sched_socket)
                     for n in self._gcs.list_nodes() if n.alive]
        except Exception:
            pass
        for node_hex, sock in nodes:
            try:
                self.tsdb.ingest(_node_rpc(sock, "metrics_snapshot"), now)
            except Exception:
                pass
            try:
                evs = _node_rpc(sock, "list_events", {
                    "since_seq": self._cursors.get(node_hex, 0)})
            except Exception:
                evs = []
            if evs:
                with self._lock:
                    for ev in evs:
                        self._cursors[node_hex] = max(
                            self._cursors.get(node_hex, 0),
                            int(ev.get("seq") or 0))
                        self._events.append(ev)
        transitions = self.engine.tick(self.tsdb, now)
        for tr in transitions:
            if tr["kind"] == "slo.fire":
                self._correlate(tr)
                self._attribute(tr, nodes)
        if transitions:
            try:
                _node_rpc(self._head_sock, "events_push",
                          {"events": transitions})
            except Exception:
                pass
        # A fire can race ahead of the engines' span flush cadence: while
        # a serving rule burns without a phase decomposition, retry the
        # attribution each tick until the banked spans yield one.
        for row in self.engine.status()["rules"]:
            a = row.get("attribution")
            if row["firing"] and (a is None
                                  or a.get("verdict") == "unattributed"):
                self._attribute({"ts": now, "data": {"rule": row["rule"]}},
                                nodes)
        from ray_tpu._private import slo as slo_mod

        try:
            _node_rpc(self._head_sock, "metrics_push", {
                "source": b"slo-engine",
                "metrics": slo_mod.status_metrics(self.engine.status())})
        except Exception:
            pass

    def _correlate(self, alert: dict):
        """Stamp a firing alert with the newest recent incident's trace id
        so `rtpu events` links the event->alert pair into the trace tree."""
        horizon = alert["ts"] - max(
            30.0, self.engine.fast_window(
                next((r for r in self.engine.rules
                      if r.name == alert["data"]["rule"]), None)
                or self.engine.rules[0]) * 2)
        with self._lock:
            recent = list(self._events)
        for ev in reversed(recent):
            if (ev.get("ts", 0) >= horizon
                    and ev.get("trace_id")
                    and ev.get("severity") in ("warning", "error",
                                               "critical")
                    and not str(ev.get("kind", "")).startswith("slo.")):
                alert["trace_id"] = ev["trace_id"]
                alert["data"]["correlated_event"] = {
                    "kind": ev.get("kind"), "ts": ev.get("ts"),
                    "node_id": ev.get("node_id"), "seq": ev.get("seq")}
                return

    def _attribute(self, alert: dict, nodes):
        """Burn attribution for serving-latency fires: pull every node's
        banked engine spans over the breaching window, decompose the
        latency into phase shares (queue vs cold-prefill vs kv-pull vs
        decode contention), and stamp verdict + exemplar trace ids on the
        alert — `rtpu slo --explain` replays the verdict from the engine
        state afterwards."""
        from ray_tpu._private import slo as slo_mod
        from ray_tpu.util import metrics as metrics_mod

        rule = next((r for r in self.engine.rules
                     if r.name == alert["data"].get("rule")), None)
        if rule is None:
            return
        if not set(rule.families()) & set(metrics_mod.EXEMPLAR_FAMILIES):
            return  # not a serving-latency objective: nothing to decompose
        since = alert["ts"] - max(rule.window_s, 30.0)
        spans: list = []
        for _node_hex, sock in nodes:
            try:
                spans.extend(_node_rpc(sock, "spans_window", {
                    "since_ts": since, "name_prefix": "llm."}))
            except Exception:
                continue
        attr = slo_mod.attribute_burn(spans)
        if attr is None:
            # no banked engine spans (sampling off, or a serving path
            # without the LLM engine): still answer "which request was
            # the p99" from the TSDB's banked histogram exemplar
            tid = self.tsdb.exemplar(rule.num.family, 0.99, rule.window_s)
            if tid is None:
                return
            attr = {"phases": {}, "verdict": "unattributed",
                    "exemplar_trace_ids": [tid], "traces": 0}
        alert["data"]["phases"] = attr["phases"]
        alert["data"]["verdict"] = attr["verdict"]
        alert["data"]["exemplar_trace_ids"] = attr["exemplar_trace_ids"]
        if not alert.get("trace_id") and attr["exemplar_trace_ids"]:
            alert["trace_id"] = attr["exemplar_trace_ids"][0]
        self.engine.note_attribution(rule.name, attr)

    # -- plane interface (scheduler control-socket delegation) -----------
    def query_timeseries(self, params: dict) -> dict:
        family = params.get("family") or ""
        window_s = float(params.get("window_s") or 300.0)
        if not family:
            return {"families": self.tsdb.families()}
        return {"family": family, "window_s": window_s,
                "series": self.tsdb.query(family, window_s)}

    def slo_status(self) -> dict:
        status = self.engine.status()
        status["sample_s"] = self.sample_s
        return status

    def tsdb_overview(self, params: dict) -> list:
        return self.tsdb.overview(float(params.get("window_s") or 60.0))

    def tsdb_stats(self) -> dict:
        return self.tsdb.stats()

    def merged_events(self, kind: str = "", severity: str = "",
                      limit: int = 500) -> list[dict]:
        with self._lock:
            ring = list(self._events)
        out = [dict(ev) for ev in ring
               if (not kind or str(ev.get("kind", "")).startswith(kind))
               and (not severity or ev.get("severity") == severity)]
        out.sort(key=lambda e: e.get("ts", 0.0))
        return out[-max(1, int(limit)):]

    def shutdown(self):
        from ray_tpu._private import tsdb as tsdb_mod

        self._stop.set()
        self._thread.join(timeout=5)
        if tsdb_mod.global_plane() is self:
            tsdb_mod.set_global_plane(None)


class DashboardHead:
    """Serves on 127.0.0.1:<port> from a daemon thread with its own loop."""

    def __init__(self, gcs, head_sched_socket: str, port: int = 0):
        import aiohttp  # noqa: F401 — fail HERE, in the caller's thread

        self._gcs = gcs
        self._head_sock = head_sched_socket
        self._port = port
        self.url: Optional[str] = None
        self._started = threading.Event()
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._thread = threading.Thread(
            target=self._run, name="dashboard-head", daemon=True)
        self._thread.start()
        self._started.wait(timeout=10)
        if self.url is None:
            raise RuntimeError("dashboard server failed to start")
        # Retained-signal plane (TSDB + event ring + SLO engine); off
        # when RTPU_TSDB_SAMPLE_S <= 0.
        from ray_tpu._private import flags

        self.sampler = None
        if float(flags.get("RTPU_TSDB_SAMPLE_S")) > 0:
            self.sampler = MetricsSampler(gcs, head_sched_socket)

    # -- data sources ------------------------------------------------------
    def _sched_socks(self) -> list[str]:
        return [n.sched_socket for n in self._gcs.list_nodes() if n.alive]

    def _nodes(self):
        return [{
            "node_id": n.node_id.hex(), "alive": n.alive,
            "is_head": n.is_head, "resources": n.resources,
            "available": getattr(n, "available", {}),
        } for n in self._gcs.list_nodes()]

    def _actors(self):
        return [{
            "actor_id": a.actor_id.hex(), "name": a.name,
            "class_name": a.class_name, "state": a.state,
            "node_id": a.node_id.hex() if a.node_id else None,
            "num_restarts": a.num_restarts,
        } for a in self._gcs.list_actors()]

    def _pgs(self):
        out = []
        for pg_id, info in _node_rpc(self._head_sock, "pg_table").items():
            row = {"placement_group_id": pg_id.hex(), **info}
            if "assignment" in row:
                row["assignment"] = [
                    n.hex() if isinstance(n, bytes) else n
                    for n in row["assignment"]]
            out.append(row)
        return out

    def _jobs(self):
        try:
            return _node_rpc(self._head_sock, "job_list")
        except Exception:
            return []

    def _task_summary(self):
        from ray_tpu.util.state import summarize_events

        events = []
        for sock in self._sched_socks():
            try:
                events.extend(_node_rpc(sock, "list_task_events"))
            except Exception:
                continue
        return summarize_events(events)

    def _cluster_status(self):
        return _node_rpc(self._head_sock, "cluster_state")

    def _node_stats(self):
        """Aggregate every alive node's physical stats (per-node agent
        reporter — dashboard/agent.py)."""
        out = []
        for n in self._gcs.list_nodes():
            if not n.alive:
                continue
            try:
                out.append(_node_rpc(n.sched_socket, "node_physical_stats"))
            except Exception:
                continue
        return {"nodes": out}

    def _serve_status(self):
        """Best-effort Serve app/deployment status.  Works when the head
        process has a driver context (in-process clusters and `rtpu
        start` heads both do); degrades to a structured error otherwise."""
        try:
            from ray_tpu.serve import api as serve_api

            return serve_api.status()
        except Exception as e:
            return {"error": f"serve not running: {type(e).__name__}"}

    def _serve_routing(self):
        """Request-router snapshots straight from the controller's GCS KV
        records (namespace serve_routing) — no driver context needed."""
        import json as json_mod

        out = []
        for key in self._gcs.kv_keys("serve_routing"):
            blob = self._gcs.kv_get("serve_routing", bytes(key))
            if blob is None:
                continue
            try:
                out.append(json_mod.loads(bytes(blob).decode()))
            except (ValueError, UnicodeDecodeError):
                continue
        return sorted(out, key=lambda d: (d.get("app", ""),
                                          d.get("deployment", "")))

    def _data_jobs(self, job: Optional[str] = None):
        """Data-service job snapshots straight from the coordinator's GCS
        KV records (namespace data_jobs) — no driver context needed."""
        import json as json_mod

        out = []
        keys = ([job.encode()] if job
                else self._gcs.kv_keys("data_jobs"))
        for key in keys:
            blob = self._gcs.kv_get("data_jobs", bytes(key))
            if blob is None:
                continue
            try:
                out.append(json_mod.loads(bytes(blob).decode()))
            except (ValueError, UnicodeDecodeError):
                continue
        if job:
            return out[0] if out else {"error": f"unknown data job {job!r}"}
        return sorted(out, key=lambda j: j.get("name", ""))

    def _job_logs(self, submission_id: str):
        try:
            return {"logs": _node_rpc(self._head_sock, "job_logs",
                                      {"submission_id": submission_id})}
        except Exception as e:
            return {"error": repr(e)}

    def _status_html(self) -> str:
        """One server-rendered, self-refreshing cluster status page
        (reference: the dashboard SPA's cluster view, rendered without the
        40k-LoC React client)."""
        nodes = self._nodes()
        totals: dict = {}
        avail: dict = {}
        for n in nodes:
            if not n["alive"]:
                continue
            for k, v in n["resources"].items():
                totals[k] = totals.get(k, 0) + v
            for k, v in n["available"].items():
                avail[k] = avail.get(k, 0) + v
        res_rows = [(k, f"{avail.get(k, 0):g}", f"{v:g}")
                    for k, v in sorted(totals.items())]
        node_rows = [(
            n["node_id"][:12],
            "head" if n["is_head"] else "worker",
            _Raw(f'<span class="{"alive" if n["alive"] else "dead"}">'
                 f'{"ALIVE" if n["alive"] else "DEAD"}</span>'),
            " ".join(f"{k}:{n['available'].get(k, 0):g}/{v:g}"
                     for k, v in sorted(n["resources"].items())),
        ) for n in nodes]
        actors = self._actors()
        actor_rows = [(a["actor_id"][:12], a["name"] or "",
                       a["class_name"], a["state"],
                       (a["node_id"] or "")[:12], a["num_restarts"])
                      for a in actors]
        by_state: dict = {}
        for a in actors:
            by_state[a["state"]] = by_state.get(a["state"], 0) + 1
        task_rows = [(name, " ".join(f"{k}={v}"
                                     for k, v in sorted(states.items())))
                     for name, states in
                     sorted(self._task_summary().items())]
        jobs = self._jobs()
        job_rows = [(j.get("submission_id", ""), j.get("status", ""),
                     j.get("entrypoint", "")[:80]) for j in jobs]
        parts = [
            "<!doctype html><title>ray_tpu status</title>",
            '<meta http-equiv="refresh" content="5">', _STATUS_CSS,
            "<h1>ray_tpu cluster</h1>",
            f"<p>{sum(n['alive'] for n in nodes)}/{len(nodes)} nodes "
            f"alive &middot; {len(actors)} actors ("
            + " ".join(f"{k}={v}"
                       for k, v in sorted(by_state.items()))
            + ") &middot; auto-refreshes every 5s</p>",  # states are
            # framework enums; every user-controlled string renders via
            # _table, which escapes
            "<h2>Resources</h2>",
            _table(["resource", "available", "total"], res_rows),
            "<h2>Nodes</h2>",
            _table(["node", "role", "state", "resources"], node_rows),
            "<h2>Actors</h2>",
            _table(["actor", "name", "class", "state", "node",
                    "restarts"], actor_rows[:200]),
            "<h2>Tasks</h2>",
            _table(["task", "states"], task_rows[:200]),
        ]
        if job_rows:
            parts += ["<h2>Jobs</h2>",
                      _table(["job", "status", "entrypoint"], job_rows)]
        return "".join(parts)

    def _metrics_text(self):
        snaps = []
        for sock in self._sched_socks():
            try:
                snaps.append(_node_rpc(sock, "metrics_snapshot"))
            except Exception:
                continue
        return _render_prometheus(snaps)

    def _traces(self, trace_id: Optional[str] = None):
        """No trace_id: merged per-trace summary rows from every node.
        With trace_id: the assembled cluster-wide tree + critical path
        (same shape as ray_tpu.util.state.get_trace)."""
        from ray_tpu.util import tracing

        if trace_id:
            spans = []
            for sock in self._sched_socks():
                try:
                    spans.extend(_node_rpc(sock, "get_trace_spans",
                                           {"trace_id": trace_id}))
                except Exception:
                    continue
            return tracing.assemble_trace(trace_id, spans)
        rows: dict = {}
        for sock in self._sched_socks():
            try:
                node_rows = _node_rpc(sock, "list_traces")
            except Exception:
                continue
            for r in node_rows:
                agg = rows.get(r["trace_id"])
                if agg is None:
                    rows[r["trace_id"]] = dict(r)
                else:
                    agg["num_spans"] += r["num_spans"]
                    agg["first_ts"] = min(agg["first_ts"], r["first_ts"])
                    agg["last_ts"] = max(agg["last_ts"], r["last_ts"])
                    if not agg.get("root"):
                        agg["root"] = r.get("root")
        return sorted(rows.values(), key=lambda r: r["last_ts"],
                      reverse=True)

    def _profile_rows(self):
        """Merged per-profile summary rows from every node (the always-on
        "continuous" profile plus on-demand captures)."""
        from ray_tpu._private import profiling

        rows = []
        for sock in self._sched_socks():
            try:
                rows.extend(_node_rpc(sock, "list_profiles"))
            except Exception:
                continue
        return profiling.merge_profile_rows(rows)

    def _profile_get(self, profile_id: str):
        """One profile assembled cluster-wide (same shape as
        ray_tpu.util.state.get_profile)."""
        from ray_tpu._private import profiling

        parts = []
        for sock in self._sched_socks():
            try:
                parts.append(_node_rpc(sock, "get_profile",
                                       {"profile_id": profile_id}))
            except Exception:
                continue
        return profiling.merge_profiles(parts)

    def _goodput_rows(self):
        """Merged per-run goodput summary rows from every node."""
        from ray_tpu.util import goodput as goodput_mod

        rows = []
        for sock in self._sched_socks():
            try:
                rows.extend(_node_rpc(sock, "list_goodput"))
            except Exception:
                continue
        return goodput_mod.merge_goodput_rows(rows)

    def _memory(self):
        """The `ray memory` view over HTTP: cluster objects grouped by
        creation call site + per-node store occupancy + the leak report.
        Runs through the state API, which needs a driver context — the
        head process has one (same caveat as /api/serve)."""
        try:
            from ray_tpu.util.state import memory_summary

            return memory_summary()
        except Exception as e:
            return {"error": f"memory view unavailable: {e!r}"}

    def _goodput_get(self, run: str):
        """One run's records assembled cluster-wide (same shape as
        ray_tpu.util.state.get_goodput)."""
        from ray_tpu.util import goodput as goodput_mod

        records = []
        for sock in self._sched_socks():
            try:
                records.extend(_node_rpc(sock, "get_goodput",
                                         {"run": run}))
            except Exception:
                continue
        return goodput_mod.merge_records(records)

    def _events_rows(self, kind: str, severity: str, limit: int):
        """Merged incident timeline.  With the sampler running this is
        its (already drained + cap-bounded) ring; without it, fan in the
        per-node banks directly."""
        if getattr(self, "sampler", None) is not None:
            return self.sampler.merged_events(kind, severity, limit)
        rows = []
        for sock in self._sched_socks():
            try:
                rows.extend(_node_rpc(sock, "list_events", {
                    "kind": kind, "severity": severity, "limit": limit}))
            except Exception:
                continue
        rows.sort(key=lambda e: e.get("ts", 0.0))
        return rows[-max(1, limit):]

    def _slo_api(self):
        if getattr(self, "sampler", None) is None:
            return {"error": "SLO engine disabled (RTPU_TSDB_SAMPLE_S=0)"}
        return self.sampler.slo_status()

    def _timeseries_api(self, family: str, window_s: float):
        if getattr(self, "sampler", None) is None:
            return {"error": "TSDB disabled (RTPU_TSDB_SAMPLE_S=0)"}
        return self.sampler.query_timeseries(
            {"family": family, "window_s": window_s})

    # -- server ------------------------------------------------------------
    def _run(self):
        from aiohttp import web

        loop = asyncio.new_event_loop()
        asyncio.set_event_loop(loop)
        self._loop = loop

        def json_handler(fn):
            async def handler(request):
                data = await loop.run_in_executor(None, fn)
                return web.Response(
                    text=json.dumps(data, default=str),
                    content_type="application/json")
            return handler

        async def index(request):
            return web.Response(text=_INDEX_HTML, content_type="text/html")

        async def metrics(request):
            text = await loop.run_in_executor(None, self._metrics_text)
            return web.Response(text=text, content_type="text/plain")

        async def status_page(request):
            text = await loop.run_in_executor(None, self._status_html)
            return web.Response(text=text, content_type="text/html")

        async def logs(request):
            # /api/logs?node_id=<hex>            -> list that node's logs
            # /api/logs?node_id=<hex>&file=F&tail=N -> tail one log
            # (reference: dashboard modules/log, served per node by its
            # agent — here each node's scheduler plays the agent)
            node_hex = request.query.get("node_id", "")
            fname = request.query.get("file")
            try:
                tail = int(request.query.get("tail", "200"))
            except ValueError:
                tail = 200  # structured JSON beats a 500 on ?tail=abc

            def fetch():
                for n in self._gcs.list_nodes():
                    if n.alive and n.node_id.hex() == node_hex:
                        if fname:
                            return _node_rpc(n.sched_socket, "read_log",
                                             {"file": fname, "tail": tail})
                        return _node_rpc(n.sched_socket, "list_logs")
                if not node_hex:  # default: the head node's logs
                    if fname:
                        return _node_rpc(self._head_sock, "read_log",
                                         {"file": fname, "tail": tail})
                    return _node_rpc(self._head_sock, "list_logs")
                return {"error": f"no alive node {node_hex}"}

            data = await loop.run_in_executor(None, fetch)
            return web.Response(text=json.dumps(data, default=str),
                                content_type="application/json")

        async def spa(request):
            return web.FileResponse(os.path.join(_CLIENT_DIR, "index.html"))

        async def job_logs(request):
            sid = request.query.get("submission_id", "")
            data = await loop.run_in_executor(None, self._job_logs, sid)
            return web.Response(text=json.dumps(data, default=str),
                                content_type="application/json")

        async def profile(request):
            # /api/profile                         -> profile summary rows
            # /api/profile?id=<profile_id>         -> speedscope JSON
            # /api/profile?id=<pid>&format=folded  -> folded-stack text
            # /api/profile?id=<pid>&format=raw     -> merged profile JSON
            from ray_tpu._private import profiling

            pid_ = (request.query.get("id")
                    or request.query.get("profile_id") or None)
            if pid_ is None:
                rows = await loop.run_in_executor(None, self._profile_rows)
                return web.Response(text=json.dumps(rows, default=str),
                                    content_type="application/json")
            prof = await loop.run_in_executor(None, self._profile_get, pid_)
            if prof is None:
                return web.Response(
                    text=json.dumps({"error": f"no profile {pid_}"}),
                    content_type="application/json", status=404)
            fmt = request.query.get("format") or "speedscope"
            if fmt == "folded":
                return web.Response(
                    text=profiling.profile_to_folded(prof),
                    content_type="text/plain")
            if fmt == "raw":
                return web.Response(text=json.dumps(prof, default=str),
                                    content_type="application/json")
            return web.Response(
                text=json.dumps(profiling.profile_to_speedscope(prof)),
                content_type="application/json")

        async def traces(request):
            # /api/traces                  -> per-trace summary rows
            # /api/traces?trace_id=<hex>   -> one assembled span tree
            tid = request.query.get("trace_id") or None
            data = await loop.run_in_executor(None, self._traces, tid)
            return web.Response(text=json.dumps(data, default=str),
                                content_type="application/json")

        app = web.Application()
        app.router.add_get("/api/logs", logs)
        app.router.add_get("/", spa)
        app.router.add_get("/api", index)
        app.router.add_static("/ui/", _CLIENT_DIR)
        app.router.add_get("/api/jobs/logs", job_logs)
        app.router.add_get("/api/node_stats", json_handler(self._node_stats))
        app.router.add_get("/api/serve", json_handler(self._serve_status))
        app.router.add_get("/api/serve/routing",
                           json_handler(self._serve_routing))
        app.router.add_get("/status", status_page)
        app.router.add_get("/api/nodes", json_handler(self._nodes))
        app.router.add_get("/api/actors", json_handler(self._actors))
        app.router.add_get("/api/placement_groups", json_handler(self._pgs))
        app.router.add_get("/api/jobs", json_handler(self._jobs))
        app.router.add_get("/api/tasks/summary",
                           json_handler(self._task_summary))
        app.router.add_get("/api/cluster_status",
                           json_handler(self._cluster_status))
        async def data_jobs(request):
            # /api/data/jobs              -> every job's status snapshot
            # /api/data/jobs?job=<name>   -> one job
            name = request.query.get("job") or None
            data = await loop.run_in_executor(None, self._data_jobs, name)
            return web.Response(text=json.dumps(data, default=str),
                                content_type="application/json")

        async def goodput(request):
            # /api/goodput              -> per-run summary rows
            # /api/goodput?run=<name>   -> one run merged cluster-wide
            run = request.query.get("run") or None
            if run is None:
                rows = await loop.run_in_executor(None, self._goodput_rows)
                return web.Response(text=json.dumps(rows, default=str),
                                    content_type="application/json")
            rec = await loop.run_in_executor(None, self._goodput_get, run)
            if rec is None:
                return web.Response(
                    text=json.dumps({"error": f"no goodput run {run}"}),
                    content_type="application/json", status=404)
            return web.Response(text=json.dumps(rec, default=str),
                                content_type="application/json")

        async def events(request):
            # /api/events?kind=<prefix>&severity=<s>&limit=<n>
            kind = request.query.get("kind") or ""
            severity = request.query.get("severity") or ""
            try:
                limit = int(request.query.get("limit", "500"))
            except ValueError:
                limit = 500
            rows = await loop.run_in_executor(
                None, self._events_rows, kind, severity, limit)
            return web.Response(text=json.dumps(rows, default=str),
                                content_type="application/json")

        async def timeseries(request):
            # /api/timeseries                    -> known families
            # /api/timeseries?family=F&window=N  -> in-window points
            family = request.query.get("family") or ""
            try:
                window = float(request.query.get("window", "300"))
            except ValueError:
                window = 300.0
            data = await loop.run_in_executor(
                None, self._timeseries_api, family, window)
            return web.Response(text=json.dumps(data, default=str),
                                content_type="application/json")

        app.router.add_get("/api/events", events)
        app.router.add_get("/api/timeseries", timeseries)
        app.router.add_get("/api/slo", json_handler(self._slo_api))
        app.router.add_get("/api/data/jobs", data_jobs)
        app.router.add_get("/api/traces", traces)
        app.router.add_get("/api/profile", profile)
        app.router.add_get("/api/goodput", goodput)
        app.router.add_get("/api/memory", json_handler(self._memory))
        app.router.add_get("/metrics", metrics)

        async def start():
            runner = web.AppRunner(app, access_log=None)
            await runner.setup()
            site = web.TCPSite(runner, "127.0.0.1", self._port)
            await site.start()
            port = site._server.sockets[0].getsockname()[1]
            self.url = f"http://127.0.0.1:{port}"
            self._runner = runner
            self._started.set()

        try:
            loop.run_until_complete(start())
        except BaseException:
            self._started.set()  # unblock __init__, which raises on url=None
            loop.close()
            return
        try:
            loop.run_forever()
        finally:
            loop.run_until_complete(self._runner.cleanup())
            loop.close()

    def shutdown(self):
        if getattr(self, "sampler", None) is not None:
            self.sampler.shutdown()
        if self._loop is not None and self._loop.is_running():
            self._loop.call_soon_threadsafe(self._loop.stop)
        self._thread.join(timeout=5)
