"""Per-node dashboard agent: physical stats reporter.

Counterpart of the reference's per-node ``DashboardAgent``
(/root/reference/python/ray/dashboard/agent.py:22) — specifically its
reporter module (dashboard/modules/reporter/), which samples node CPU /
memory / disk / network and per-worker RSS and ships them to the head.

Here the agent is a sampling thread owned by each node's scheduler (the
scheduler already plays the agent's other roles: log serving, runtime-env
install, metrics snapshot).  The head aggregates every node's latest
sample via the ``node_physical_stats`` RPC into ``/api/node_stats`` and
the SPA's charts.  A short in-memory history ring lets the UI draw
utilization over time without a real TSDB.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from typing import Callable, Iterable, Optional

from ray_tpu._private.memory_monitor import node_memory_usage, process_rss

_SAMPLE_PERIOD_S = 2.0
_HISTORY = 150  # 5 min at 2s


def _read_cpu_times() -> tuple[float, float]:
    """(busy_jiffies, total_jiffies) from /proc/stat line 1."""
    try:
        with open("/proc/stat") as f:
            parts = f.readline().split()[1:]
        nums = [float(x) for x in parts]
        idle = nums[3] + (nums[4] if len(nums) > 4 else 0.0)  # idle+iowait
        total = sum(nums)
        return total - idle, total
    except (OSError, IndexError, ValueError):
        return 0.0, 0.0


def _read_net_bytes() -> tuple[int, int]:
    """(rx_bytes, tx_bytes) summed over non-loopback interfaces."""
    rx = tx = 0
    try:
        with open("/proc/net/dev") as f:
            for line in f.readlines()[2:]:
                name, _, rest = line.partition(":")
                if name.strip() == "lo":
                    continue
                cols = rest.split()
                rx += int(cols[0])
                tx += int(cols[8])
    except (OSError, IndexError, ValueError):
        pass
    return rx, tx


def _proc_cmd_name(pid: int) -> str:
    try:
        with open(f"/proc/{pid}/comm") as f:
            return f.read().strip()
    except OSError:
        return ""


class NodeStatsReporter:
    """Samples node physical stats on a timer; ``latest()`` is the RPC body.

    ``workers_fn`` yields ``(pid, description)`` pairs for live workers so
    each sample carries per-worker RSS (what the reference's reporter gets
    from psutil; here straight from /proc).
    """

    def __init__(self, node_id: bytes,
                 workers_fn: Optional[Callable[[], Iterable]] = None,
                 mm_threshold: float = 0.0):
        self._node_id = node_id
        self._workers_fn = workers_fn or (lambda: ())
        self._mm_threshold = mm_threshold
        # Memory pressure as util.metrics gauges: the memory monitor's
        # inputs are visible on /metrics BEFORE a kill fires (node_id /
        # pid tags keep series from different nodes and processes
        # distinct — the dashboard's renderer sums same-label series).
        from ray_tpu.util import metrics as metrics_mod

        nid = node_id.hex()[:12]
        self._g_mem_used = metrics_mod.Gauge(
            "node_mem_used_bytes", "Node memory in use (MemAvailable "
            "subtracted from MemTotal, what the memory monitor sees)",
            ("node_id",)).set_default_tags({"node_id": nid})
        self._g_mem_total = metrics_mod.Gauge(
            "node_mem_total_bytes", "Node memory capacity",
            ("node_id",)).set_default_tags({"node_id": nid})
        self._g_mm_threshold = metrics_mod.Gauge(
            "node_memory_monitor_threshold",
            "Memory-usage fraction above which the node kills a worker "
            "(RTPU_MEMORY_MONITOR_THRESHOLD; 0 = monitor disabled)",
            ("node_id",)).set_default_tags({"node_id": nid})
        self._g_worker_rss = metrics_mod.Gauge(
            "worker_rss_bytes", "Resident set size per live worker",
            ("node_id", "pid")).set_default_tags({"node_id": nid})
        self._lock = threading.Lock()
        self._history: deque = deque(maxlen=_HISTORY)
        self._latest: dict = {}
        self._prev_cpu = _read_cpu_times()
        self._prev_net = _read_net_bytes()
        self._prev_t = time.monotonic()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.sample()  # a snapshot is available immediately

    def start(self):
        self._thread = threading.Thread(
            target=self._loop, name="node-stats-reporter", daemon=True)
        self._thread.start()

    def shutdown(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)

    def _loop(self):
        while not self._stop.wait(_SAMPLE_PERIOD_S):
            try:
                self.sample()
            except Exception:
                pass  # a bad /proc read must never kill the reporter

    def sample(self) -> dict:
        now = time.monotonic()
        busy, total = _read_cpu_times()
        pbusy, ptotal = self._prev_cpu
        dtotal = total - ptotal
        cpu_pct = 100.0 * (busy - pbusy) / dtotal if dtotal > 0 else 0.0
        self._prev_cpu = (busy, total)

        rx, tx = _read_net_bytes()
        dt = max(now - self._prev_t, 1e-6)
        rx_s = max(0, rx - self._prev_net[0]) / dt
        tx_s = max(0, tx - self._prev_net[1]) / dt
        self._prev_net = (rx, tx)
        self._prev_t = now

        mem_used, mem_total = node_memory_usage()
        try:
            st = os.statvfs("/")
            disk = {"total": st.f_blocks * st.f_frsize,
                    "free": st.f_bavail * st.f_frsize}
        except OSError:
            disk = {"total": 0, "free": 0}

        workers = []
        try:
            for pid, desc in self._workers_fn():
                workers.append({"pid": pid, "rss": process_rss(pid),
                                "comm": _proc_cmd_name(pid),
                                "task": desc})
        except Exception:
            pass

        self._g_mem_used.set(float(mem_used))
        self._g_mem_total.set(float(mem_total))
        self._g_mm_threshold.set(float(self._mm_threshold))
        # reset-then-set: exited workers' series must not linger
        self._g_worker_rss.clear()
        for w in workers:
            self._g_worker_rss.set(float(w["rss"]), {"pid": str(w["pid"])})

        snap = {
            "node_id": self._node_id.hex(),
            "ts": time.time(),
            "cpu_percent": round(cpu_pct, 1),
            "mem_used": mem_used,
            "mem_total": mem_total,
            "disk": disk,
            "net_rx_bytes_per_s": int(rx_s),
            "net_tx_bytes_per_s": int(tx_s),
            "workers": workers,
        }
        with self._lock:
            self._latest = snap
            self._history.append((snap["ts"], snap["cpu_percent"],
                                  mem_used, int(rx_s), int(tx_s)))
        return snap

    def latest(self) -> dict:
        with self._lock:
            out = dict(self._latest)
            out["history"] = [
                {"ts": t, "cpu_percent": c, "mem_used": m,
                 "net_rx_bytes_per_s": r, "net_tx_bytes_per_s": x}
                for t, c, m, r, x in self._history]
        return out
