/* ray_tpu dashboard SPA: hash-routed views over the head's JSON APIs.
   No framework — tables, stat tiles, and SVG line charts with a hover
   crosshair.  Counterpart of the reference's React client
   (python/ray/dashboard/client/), scoped to the views that matter for a
   TPU cluster: overview, nodes, actors, tasks, jobs, PGs, serve, logs,
   metrics. */
"use strict";

const $view = document.getElementById("view");
const $tooltip = document.getElementById("tooltip");
let refreshTimer = null;

/* ---------------- helpers ---------------- */

async function getJSON(url) {
  const r = await fetch(url);
  if (!r.ok) throw new Error(`${url}: HTTP ${r.status}`);
  return r.json();
}

function esc(s) {
  return String(s).replace(/[&<>"']/g, c => ({
    "&": "&amp;", "<": "&lt;", ">": "&gt;", '"': "&quot;", "'": "&#39;",
  }[c]));
}

function el(html) {
  const t = document.createElement("template");
  t.innerHTML = html.trim();
  return t.content.firstChild;
}

function fmtBytes(n) {
  if (n == null) return "";
  const u = ["B", "KB", "MB", "GB", "TB"];
  let i = 0;
  while (n >= 1024 && i < u.length - 1) { n /= 1024; i++; }
  return `${n.toFixed(n >= 100 || i === 0 ? 0 : 1)} ${u[i]}`;
}

function fmtNum(n) {
  if (n == null) return "";
  return Number(n).toLocaleString();
}

function shortId(hex) { return hex ? hex.slice(0, 12) : ""; }

function stateSpan(s) {
  return `<span class="state ${esc(s)}">${esc(s)}</span>`;
}

function bar(frac) {
  const pct = Math.max(0, Math.min(100, frac * 100));
  const cls = pct > 90 ? "crit" : pct > 75 ? "warn" : "";
  return `<span class="bar-outer"><span class="bar-inner ${cls}"
    style="width:${pct.toFixed(1)}%"></span></span>
    <span class="mono">${pct.toFixed(0)}%</span>`;
}

/* table(headers, rows): rows are arrays; cells wrapped in raw() are
   pre-escaped HTML (state spans / bars); everything else is escaped. */
function table(headers, rows, numCols) {
  numCols = numCols || [];
  const h = headers.map((x, i) =>
    `<th class="${numCols.includes(i) ? "num" : ""}">${esc(x)}</th>`).join("");
  const body = rows.map(r => "<tr>" + r.map((c, i) => {
    const cls = numCols.includes(i) ? "num" : "";
    if (c !== null && typeof c === "object" && c.__html !== undefined)
      return `<td class="${cls}">${c.__html}</td>`;
    return `<td class="${cls}">${esc(c == null ? "" : c)}</td>`;
  }).join("") + "</tr>").join("");
  if (!rows.length) return `<div class="empty">none</div>`;
  return `<table><tr>${h}</tr>${body}</table>`;
}

const raw = html => ({ __html: html });

/* ---------------- line chart (SVG + crosshair tooltip) ---------------- */

/* series: [{name, colorVar, points: [[tSec, value], ...]}] */
function lineChart(parent, { title, series, yFmt = fmtNum, height = 150 }) {
  const W = 420, H = height, padL = 46, padR = 10, padT = 8, padB = 20;
  const box = el(`<div class="chart-box"><div class="title">${esc(title)}
    </div></div>`);
  const all = series.flatMap(s => s.points);
  if (!all.length) {
    box.appendChild(el(`<div class="empty">no data yet</div>`));
    parent.appendChild(box);
    return;
  }
  const t0 = Math.min(...all.map(p => p[0]));
  const t1 = Math.max(...all.map(p => p[0]), t0 + 1);
  let vMax = Math.max(...all.map(p => p[1]), 1e-9);
  vMax *= 1.08;
  const x = t => padL + (W - padL - padR) * (t - t0) / (t1 - t0);
  const y = v => padT + (H - padT - padB) * (1 - v / vMax);

  let g = "";
  for (let i = 0; i <= 3; i++) {  // recessive horizontal grid, 4 lines
    const v = vMax * i / 3;
    g += `<line x1="${padL}" x2="${W - padR}" y1="${y(v)}" y2="${y(v)}"
      stroke="var(--grid)" stroke-width="1"/>
      <text x="${padL - 6}" y="${y(v) + 4}" text-anchor="end" font-size="10"
      fill="var(--text-muted)">${esc(yFmt(v))}</text>`;
  }
  const t2hm = t => new Date(t * 1000).toLocaleTimeString(
    [], { hour: "2-digit", minute: "2-digit", second: "2-digit" });
  g += `<text x="${padL}" y="${H - 5}" font-size="10"
    fill="var(--text-muted)">${t2hm(t0)}</text>
    <text x="${W - padR}" y="${H - 5}" text-anchor="end" font-size="10"
    fill="var(--text-muted)">${t2hm(t1)}</text>`;
  for (const s of series) {
    const pts = s.points.map(p => `${x(p[0]).toFixed(1)},${y(p[1]).toFixed(1)}`)
      .join(" ");
    g += `<polyline points="${pts}" fill="none"
      stroke="var(${s.colorVar})" stroke-width="2"
      stroke-linejoin="round" stroke-linecap="round"/>`;
  }
  const svg = el(`<svg viewBox="0 0 ${W} ${H}"
    role="img" aria-label="${esc(title)}">${g}
    <line class="xh" y1="${padT}" y2="${H - padB}" stroke="var(--text-muted)"
      stroke-width="1" stroke-dasharray="3,3" visibility="hidden"/>
  </svg>`);
  const xh = svg.querySelector(".xh");
  svg.addEventListener("mousemove", ev => {
    const r = svg.getBoundingClientRect();
    const mx = (ev.clientX - r.left) * W / r.width;
    if (mx < padL || mx > W - padR) { xh.setAttribute("visibility", "hidden");
      $tooltip.hidden = true; return; }
    const t = t0 + (mx - padL) / (W - padL - padR) * (t1 - t0);
    xh.setAttribute("x1", mx); xh.setAttribute("x2", mx);
    xh.setAttribute("visibility", "visible");
    let rowsHtml = "";
    for (const s of series) {
      let best = null, bd = Infinity;
      for (const p of s.points) {
        const d = Math.abs(p[0] - t);
        if (d < bd) { bd = d; best = p; }
      }
      if (best) rowsHtml += `<div class="t-row"><span class="swatch"
        style="background:var(${s.colorVar});width:9px;height:9px;
        display:inline-block;border-radius:3px"></span>
        ${esc(s.name)}: <b>${esc(yFmt(best[1]))}</b></div>`;
    }
    $tooltip.innerHTML = `<div class="t-time">${esc(t2hm(t))}</div>${rowsHtml}`;
    $tooltip.hidden = false;
    $tooltip.style.left = Math.min(ev.clientX + 14,
      window.innerWidth - $tooltip.offsetWidth - 8) + "px";
    $tooltip.style.top = (ev.clientY + 12) + "px";
  });
  svg.addEventListener("mouseleave", () => {
    xh.setAttribute("visibility", "hidden"); $tooltip.hidden = true;
  });
  box.appendChild(svg);
  if (series.length >= 2) {
    box.appendChild(el(`<div class="legend">` + series.map(s =>
      `<span><span class="swatch" style="background:var(${s.colorVar})">
      </span>${esc(s.name)}</span>`).join("") + `</div>`));
  }
  parent.appendChild(box);
}

/* ---------------- views ---------------- */

async function viewOverview(root) {
  const [nodes, actors, stats, tasks] = await Promise.all([
    getJSON("/api/nodes"), getJSON("/api/actors"),
    getJSON("/api/node_stats"), getJSON("/api/tasks/summary"),
  ]);
  const alive = nodes.filter(n => n.alive);
  const byState = {};
  for (const a of actors) byState[a.state] = (byState[a.state] || 0) + 1;
  let running = 0, pending = 0, finished = 0, failed = 0;
  for (const states of Object.values(tasks)) {
    running += states.RUNNING || 0;
    pending += (states.PENDING_SCHEDULING || 0) + (states.PENDING_ARGS || 0)
      + (states.QUEUED || 0);
    finished += states.FINISHED || 0;
    failed += states.FAILED || 0;
  }
  const totals = {}, avail = {};
  for (const n of alive) {
    for (const [k, v] of Object.entries(n.resources || {}))
      totals[k] = (totals[k] || 0) + v;
    for (const [k, v] of Object.entries(n.available || {}))
      avail[k] = (avail[k] || 0) + v;
  }
  root.innerHTML = "<h1>Cluster overview</h1>";
  const cards = el(`<div class="cards"></div>`);
  const card = (label, value, sub) => cards.appendChild(el(
    `<div class="card"><div class="label">${esc(label)}</div>
     <div class="value">${esc(value)}</div>
     <div class="sub">${esc(sub || "")}</div></div>`));
  card("Nodes alive", `${alive.length}/${nodes.length}`);
  card("Actors", actors.length, Object.entries(byState)
    .map(([k, v]) => `${k} ${v}`).join("  "));
  card("Tasks running", fmtNum(running), `${fmtNum(pending)} pending`);
  card("Tasks finished", fmtNum(finished),
    failed ? `${fmtNum(failed)} failed` : "");
  for (const res of ["CPU", "TPU"]) {
    if (totals[res] != null)
      card(`${res} in use`,
        `${(totals[res] - (avail[res] || 0)).toFixed(0)}/${totals[res]}`);
  }
  root.appendChild(cards);

  // cluster utilization charts from the per-node history rings
  const charts = el(`<div class="charts"></div>`);
  const perNode = stats.nodes || [];
  const cpuSeries = [], memSeries = [];
  const colors = ["--series-1", "--series-2", "--series-3"];
  perNode.slice(0, 3).forEach((s, i) => {
    const hist = s.history || [];
    cpuSeries.push({ name: `node ${shortId(s.node_id)}`, colorVar: colors[i],
      points: hist.map(h => [h.ts, h.cpu_percent]) });
    memSeries.push({ name: `node ${shortId(s.node_id)}`, colorVar: colors[i],
      points: hist.map(h => [h.ts, h.mem_used]) });
  });
  lineChart(charts, { title: "Node CPU %", series: cpuSeries,
    yFmt: v => v.toFixed(0) + "%" });
  lineChart(charts, { title: "Node memory used", series: memSeries,
    yFmt: fmtBytes });
  if (perNode.length > 3)
    charts.appendChild(el(`<div class="empty">showing 3 of ${perNode.length}
      nodes — see Nodes for the rest</div>`));
  root.appendChild(charts);

  root.appendChild(el("<h2>Resources</h2>"));
  root.appendChild(el(table(["resource", "available", "total"],
    Object.entries(totals).sort().map(([k, v]) =>
      [k, fmtNum(avail[k] || 0), fmtNum(v)]), [1, 2])));
}

async function viewNodes(root) {
  const [nodes, stats] = await Promise.all([
    getJSON("/api/nodes"), getJSON("/api/node_stats")]);
  const statByNode = {};
  for (const s of stats.nodes || []) statByNode[s.node_id] = s;
  root.innerHTML = "<h1>Nodes</h1>";
  root.appendChild(el(table(
    ["node", "role", "state", "CPU", "memory", "net rx/s", "tx/s",
     "workers", "resources"],
    nodes.map(n => {
      const s = statByNode[n.node_id] || {};
      return [
        raw(`<code>${esc(shortId(n.node_id))}</code>`),
        n.is_head ? "head" : "worker",
        raw(stateSpan(n.alive ? "ALIVE" : "DEAD")),
        raw(s.cpu_percent != null ? bar(s.cpu_percent / 100) : ""),
        raw(s.mem_total ? bar(s.mem_used / s.mem_total) + " " +
          esc(fmtBytes(s.mem_used)) : ""),
        fmtBytes(s.net_rx_bytes_per_s), fmtBytes(s.net_tx_bytes_per_s),
        (s.workers || []).length,
        Object.entries(n.resources || {}).sort().map(([k, v]) =>
          `${k}:${(n.available || {})[k] ?? 0}/${v}`).join(" "),
      ];
    }), [5, 6, 7])));

  for (const s of stats.nodes || []) {
    if (!(s.workers || []).length) continue;
    root.appendChild(el(`<h2>Workers on ${esc(shortId(s.node_id))}</h2>`));
    root.appendChild(el(table(["pid", "process", "running task", "RSS"],
      s.workers.map(w => [w.pid, w.comm, w.task || "(idle)",
        fmtBytes(w.rss)]), [3])));
  }
}

async function viewActors(root) {
  const actors = await getJSON("/api/actors");
  root.innerHTML = `<h1>Actors</h1>
    <div class="toolbar"><input type="text" id="flt"
      placeholder="filter by class/name/state"></div>
    <div id="tbl"></div>`;
  const tbl = root.querySelector("#tbl");
  const render = q => {
    q = (q || "").toLowerCase();
    const rows = actors.filter(a => !q ||
      `${a.class_name} ${a.name} ${a.state}`.toLowerCase().includes(q));
    tbl.innerHTML = table(
      ["actor", "name", "class", "state", "node", "restarts"],
      rows.slice(0, 500).map(a => [
        raw(`<code>${esc(shortId(a.actor_id))}</code>`), a.name || "",
        a.class_name, raw(stateSpan(a.state)), shortId(a.node_id || ""),
        a.num_restarts]), [5]);
  };
  render("");
  root.querySelector("#flt").addEventListener("input",
    e => render(e.target.value));
}

async function viewTasks(root) {
  const tasks = await getJSON("/api/tasks/summary");
  root.innerHTML = "<h1>Tasks</h1>";
  root.appendChild(el(table(["task", "states"],
    Object.entries(tasks).sort().map(([name, states]) =>
      [name, Object.entries(states).sort().map(([k, v]) =>
        `${k}=${v}`).join("  ")]))));
}

async function viewJobs(root) {
  const jobs = await getJSON("/api/jobs");
  root.innerHTML = `<h1>Jobs</h1><div id="tbl"></div>
    <h2 id="lh" hidden>Job logs</h2><pre class="logview" id="jlog" hidden></pre>`;
  root.querySelector("#tbl").innerHTML = table(
    ["job", "status", "entrypoint", ""],
    jobs.map(j => [j.submission_id, raw(stateSpan(j.status || "")),
      (j.entrypoint || "").slice(0, 90),
      raw(`<button data-job="${esc(j.submission_id)}">logs</button>`)]));
  root.addEventListener("click", async ev => {
    const id = ev.target.dataset && ev.target.dataset.job;
    if (!id) return;
    const data = await getJSON(`/api/jobs/logs?submission_id=${
      encodeURIComponent(id)}`);
    root.querySelector("#lh").hidden = false;
    const pre = root.querySelector("#jlog");
    pre.hidden = false;
    pre.textContent = typeof data === "string" ? data
      : (data.logs || JSON.stringify(data, null, 2));
  });
}

async function viewPGs(root) {
  const pgs = await getJSON("/api/placement_groups");
  root.innerHTML = "<h1>Placement groups</h1>";
  root.appendChild(el(table(["id", "state", "strategy", "bundles", "nodes"],
    pgs.map(p => [raw(`<code>${esc(shortId(p.placement_group_id))}</code>`),
      raw(stateSpan(p.state || "")), p.strategy || "",
      JSON.stringify(p.bundles || []),
      (p.assignment || []).map(shortId).join(" ")]))));
}

async function viewServe(root) {
  root.innerHTML = "<h1>Serve</h1>";
  let st;
  try { st = await getJSON("/api/serve"); }
  catch (e) { root.appendChild(el(
    `<div class="empty">serve status unavailable: ${esc(e)}</div>`)); return; }
  if (st.error) {
    root.appendChild(el(`<div class="empty">${esc(st.error)}</div>`));
    return;
  }
  const apps = Object.entries(st);
  if (!apps.length) {
    root.appendChild(el(`<div class="empty">no applications deployed</div>`));
    return;
  }
  for (const [name, app] of apps) {
    root.appendChild(el(`<h2>${esc(name)}
      <span class="mono">${esc(app.route_prefix || "")}</span></h2>`));
    const deps = Object.entries(app.deployments || {});
    root.appendChild(el(table(
      ["deployment", "status", "replicas", "target"],
      deps.map(([dn, d]) => [dn, raw(stateSpan(d.status || "")),
        d.num_replicas ?? d.replicas ?? "",
        d.target_num_replicas ?? ""]), [2, 3])));
  }
}

async function viewLogs(root) {
  const nodes = await getJSON("/api/nodes");
  const alive = nodes.filter(n => n.alive);
  root.innerHTML = `<h1>Logs</h1>
    <div class="toolbar">
      <select id="node">${alive.map(n =>
        `<option value="${esc(n.node_id)}">${esc(shortId(n.node_id))}
         ${n.is_head ? "(head)" : ""}</option>`).join("")}</select>
      <select id="file"><option value="">select a log…</option></select>
      <select id="tail"><option>200</option><option>1000</option>
        <option>5000</option></select>
      <button id="reload">refresh</button>
    </div>
    <pre class="logview" id="content">select a node and file</pre>`;
  const nodeSel = root.querySelector("#node");
  const fileSel = root.querySelector("#file");
  const loadFiles = async () => {
    const files = await getJSON(`/api/logs?node_id=${nodeSel.value}`);
    fileSel.innerHTML = `<option value="">select a log…</option>` +
      (files || []).map(f => `<option value="${esc(f.file)}">${esc(f.file)}
        (${esc(fmtBytes(f.size))})</option>`).join("");
  };
  const loadContent = async () => {
    if (!fileSel.value) return;
    const tail = root.querySelector("#tail").value;
    const data = await getJSON(`/api/logs?node_id=${nodeSel.value}` +
      `&file=${encodeURIComponent(fileSel.value)}&tail=${tail}`);
    root.querySelector("#content").textContent =
      data.error ? data.error : (data.lines || []).join("\n");
  };
  nodeSel.addEventListener("change", loadFiles);
  fileSel.addEventListener("change", loadContent);
  root.querySelector("#reload").addEventListener("click", loadContent);
  await loadFiles();
}

/* metrics view keeps a client-side ring of scrape samples while open */
const metricsRing = { name: null, samples: [] };

function parseProm(text) {
  const out = {};  // name -> [{labels, value}]
  for (const line of text.split("\n")) {
    if (!line || line.startsWith("#")) continue;
    const m = line.match(/^([a-zA-Z_:][\w:]*)(\{[^}]*\})?\s+([-\d.eE+]+)$/);
    if (!m) continue;
    (out[m[1]] = out[m[1]] || []).push(
      { labels: m[2] || "", value: parseFloat(m[3]) });
  }
  return out;
}

async function viewMetrics(root) {
  const text = await (await fetch("/metrics")).text();
  const metrics = parseProm(text);
  const names = Object.keys(metrics).sort();
  const sel = metricsRing.name && names.includes(metricsRing.name)
    ? metricsRing.name : names[0];
  if (sel !== metricsRing.name) { metricsRing.name = sel;
    metricsRing.samples = []; }
  if (sel) {
    const now = Date.now() / 1000;
    const byLabel = {};
    for (const { labels, value } of metrics[sel])
      byLabel[labels] = (byLabel[labels] || 0) + value;
    metricsRing.samples.push({ ts: now, byLabel });
    if (metricsRing.samples.length > 240) metricsRing.samples.shift();
  }
  root.innerHTML = `<h1>Metrics</h1>
    <div class="toolbar"><select id="metric">${names.map(n =>
      `<option ${n === sel ? "selected" : ""}>${esc(n)}</option>`).join("")}
    </select>
    <span class="mono">${metricsRing.samples.length} samples (5s scrape
    while this view is open)</span></div>
    <div class="charts" id="chart"></div><h2>Current values</h2>
    <div id="cur"></div>`;
  root.querySelector("#metric").addEventListener("change", ev => {
    metricsRing.name = ev.target.value;
    metricsRing.samples = [];
    render(location.hash);
  });
  if (sel) {
    const labelSets = [...new Set(metricsRing.samples.flatMap(
      s => Object.keys(s.byLabel)))].slice(0, 3);
    const colors = ["--series-1", "--series-2", "--series-3"];
    lineChart(root.querySelector("#chart"), {
      title: sel,
      series: labelSets.map((ls, i) => ({
        name: ls || "value", colorVar: colors[i],
        points: metricsRing.samples
          .filter(s => s.byLabel[ls] != null)
          .map(s => [s.ts, s.byLabel[ls]]),
      })),
    });
    root.querySelector("#cur").innerHTML = table(
      ["labels", "value"], metrics[sel].slice(0, 100).map(
        r => [r.labels || "(none)", fmtNum(r.value)]), [1]);
  }
}

/* ---------------- router ---------------- */

const routes = {
  "#/overview": viewOverview, "#/nodes": viewNodes, "#/actors": viewActors,
  "#/tasks": viewTasks, "#/jobs": viewJobs, "#/pgs": viewPGs,
  "#/serve": viewServe, "#/logs": viewLogs, "#/metrics": viewMetrics,
};
/* views safe to re-render on a timer (no user-held UI state) */
const autoRefresh = new Set(["#/overview", "#/nodes", "#/tasks", "#/pgs",
  "#/serve", "#/metrics"]);

async function render(hash) {
  const route = routes[hash] ? hash : "#/overview";
  for (const a of document.querySelectorAll("#nav a"))
    a.classList.toggle("active", a.getAttribute("href") === route);
  const root = document.createElement("div");
  try {
    await routes[route](root);
    // insert root itself: view closures and delegated listeners hold it
    $view.replaceChildren(root);
  } catch (e) {
    $view.innerHTML = `<div class="err">failed to load: ${esc(e)}</div>`;
  }
  clearInterval(refreshTimer);
  if (autoRefresh.has(route))
    refreshTimer = setInterval(() => render(route), 5000);
  try {
    const nodes = await getJSON("/api/nodes");
    document.getElementById("cluster-pill").textContent =
      `${nodes.filter(n => n.alive).length}/${nodes.length} nodes`;
  } catch (e) { /* pill is cosmetic */ }
}

window.addEventListener("hashchange", () => render(location.hash));
render(location.hash || "#/overview");
