"""ray_tpu.dashboard: REST + Prometheus observability head.

Counterpart of /root/reference/python/ray/dashboard/ (head process only;
JSON API instead of the React SPA).
"""

from ray_tpu.dashboard.head import DashboardHead

__all__ = ["DashboardHead"]
