"""ray_tpu: a TPU-native distributed AI framework.

A ground-up re-design of the reference system (iamjustinhsu/ray) for TPU
hardware: the core task/actor/object runtime schedules work onto TPU hosts
with a native shared-memory object store as the host staging tier for HBM,
and the AI libraries (train/data/serve/tune) express parallelism as JAX mesh
axes (dp/fsdp/tp/sp/ep) + pjit/shard_map with XLA collectives over ICI,
rather than NCCL process groups.
"""

from ray_tpu._version import __version__
from ray_tpu.api import (
    available_resources,
    cancel,
    cluster_resources,
    get,
    get_actor,
    get_runtime_context,
    init,
    is_initialized,
    kill,
    nodes,
    put,
    remote,
    shutdown,
    wait,
)
from ray_tpu.core.actor import ActorClass, ActorHandle
from ray_tpu.core.object_ref import ObjectRef
from ray_tpu import exceptions

__all__ = [
    "__version__",
    "ActorClass",
    "ActorHandle",
    "ObjectRef",
    "available_resources",
    "cancel",
    "cluster_resources",
    "exceptions",
    "get",
    "get_actor",
    "get_runtime_context",
    "init",
    "is_initialized",
    "kill",
    "nodes",
    "put",
    "remote",
    "shutdown",
    "wait",
]
