"""ray_tpu.air: shared AIR commons for Train and Tune.

Counterpart of /root/reference/python/ray/air/: the run/checkpoint/failure
configs and Result type shared by the AI libraries (re-exported from their
canonical homes here), plus the execution layer
(``air.execution.ActorManager`` — the reference's ``RayActorManager``,
python/ray/air/execution/_internal/actor_manager.py:22) that Tune's trial
loop runs on.
"""

from ray_tpu.air.execution import ActorManager, TrackedActor
from ray_tpu.train.checkpoint import Checkpoint
from ray_tpu.train.config import (
    CheckpointConfig,
    FailureConfig,
    RunConfig,
    ScalingConfig,
)
from ray_tpu.train.controller import Result

__all__ = [
    "ActorManager",
    "Checkpoint",
    "CheckpointConfig",
    "FailureConfig",
    "Result",
    "RunConfig",
    "ScalingConfig",
    "TrackedActor",
]
