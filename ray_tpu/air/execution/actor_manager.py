"""Event-driven actor lifecycle management for experiment controllers.

Counterpart of the reference's ``RayActorManager``
(/root/reference/python/ray/air/execution/_internal/actor_manager.py:22):
a controller (Tune's trial loop; Train controllers could ride it too)
registers actors and method calls with callbacks; ``wait`` processes
whatever completed — actor task results route to their ``on_result``,
failures to ``on_error``, and an actor whose task dies with
``ActorDiedError`` is marked dead and reported via its ``on_actor_dead``
hook.  The controller never blocks on one specific actor, so one slow
trial cannot stall the event loop.
"""

from __future__ import annotations

import itertools
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

import ray_tpu
from ray_tpu.exceptions import ActorDiedError, RayTpuError


@dataclass
class TrackedActor:
    actor_id: int
    handle: Any = None
    state: str = "ALIVE"  # ALIVE | DEAD | STOPPED
    on_actor_dead: Optional[Callable[["TrackedActor", str], None]] = None
    data: Any = None  # controller payload (e.g. the Trial)
    in_flight: int = 0

    def __hash__(self):
        return self.actor_id


@dataclass
class _PendingTask:
    tracked: TrackedActor
    on_result: Optional[Callable]
    on_error: Optional[Callable]


class ActorManager:
    """Tracks actors + routes their task completions to callbacks."""

    def __init__(self):
        self._ids = itertools.count()
        self._actors: List[TrackedActor] = []
        self._pending: Dict[Any, _PendingTask] = {}  # ObjectRef -> meta

    # -- actors ------------------------------------------------------------

    def add_actor(self, actor_cls, *, options: Optional[dict] = None,
                  init_args: tuple = (), init_kwargs: Optional[dict] = None,
                  on_actor_dead: Optional[Callable] = None,
                  data: Any = None) -> TrackedActor:
        """Create and track an actor.  ``actor_cls`` is a plain class (it
        is wrapped with ``ray_tpu.remote``) or an existing remote class."""
        remote_cls = (actor_cls if hasattr(actor_cls, "remote")
                      else ray_tpu.remote(actor_cls))
        if options:
            remote_cls = remote_cls.options(**options)
        handle = remote_cls.remote(*init_args, **(init_kwargs or {}))
        tracked = TrackedActor(actor_id=next(self._ids), handle=handle,
                               on_actor_dead=on_actor_dead, data=data)
        self._actors.append(tracked)
        return tracked

    def remove_actor(self, tracked: TrackedActor, kill: bool = True) -> None:
        """Stop tracking (and by default kill) an actor.  Pending tasks on
        it are dropped without callbacks — the controller decided."""
        if tracked.state == "ALIVE":
            tracked.state = "STOPPED"
        for ref in [r for r, p in self._pending.items()
                    if p.tracked is tracked]:
            del self._pending[ref]
        tracked.in_flight = 0
        if kill and tracked.handle is not None:
            try:
                ray_tpu.kill(tracked.handle)
            except Exception:
                pass
        tracked.handle = None
        if tracked in self._actors:
            self._actors.remove(tracked)

    @property
    def live_actors(self) -> List[TrackedActor]:
        return [a for a in self._actors if a.state == "ALIVE"]

    def num_pending_tasks(self, tracked: Optional[TrackedActor] = None) -> int:
        if tracked is None:
            return len(self._pending)
        return tracked.in_flight

    # -- tasks -------------------------------------------------------------

    def schedule_actor_task(self, tracked: TrackedActor, method: str,
                            args: tuple = (), kwargs: Optional[dict] = None,
                            on_result: Optional[Callable] = None,
                            on_error: Optional[Callable] = None) -> bool:
        """Submit ``handle.method(*args)``; completion routes to the
        callbacks at the next ``wait``.  False if the actor is gone."""
        if tracked.state != "ALIVE" or tracked.handle is None:
            return False
        ref = getattr(tracked.handle, method).remote(
            *args, **(kwargs or {}))
        self._pending[ref] = _PendingTask(tracked, on_result, on_error)
        tracked.in_flight += 1
        return True

    def wait(self, timeout: Optional[float] = 0.05,
             max_events: int = 64) -> int:
        """Process up to ``max_events`` completed tasks; returns how many
        fired.  Callbacks run on the calling thread (the controller's
        event loop — reference semantics: RayActorManager.next)."""
        if not self._pending:
            # nothing in flight: honor the timeout anyway so controller
            # loops built on wait() never busy-spin
            if timeout:
                time.sleep(timeout)
            return 0
        refs = list(self._pending.keys())
        ready, _ = ray_tpu.wait(refs, num_returns=min(max_events, len(refs)),
                                timeout=timeout)
        fired = 0
        for ref in ready:
            meta = self._pending.pop(ref, None)
            if meta is None:
                continue
            meta.tracked.in_flight = max(0, meta.tracked.in_flight - 1)
            try:
                value = ray_tpu.get(ref)
            except RayTpuError as e:
                self._on_task_error(meta, e)
                fired += 1
                continue
            except Exception as e:  # user exception from the method
                self._on_task_error(meta, e)
                fired += 1
                continue
            if meta.on_result is not None:
                meta.on_result(meta.tracked, value)
            fired += 1
        return fired

    def _on_task_error(self, meta: _PendingTask, exc: BaseException) -> None:
        tracked = meta.tracked
        if isinstance(exc, ActorDiedError) and tracked.state == "ALIVE":
            tracked.state = "DEAD"
            # drop other pending tasks on the dead actor: each would raise
            # the same death; one notification is the contract
            for ref in [r for r, p in self._pending.items()
                        if p.tracked is tracked]:
                del self._pending[ref]
            tracked.in_flight = 0
            if tracked.on_actor_dead is not None:
                tracked.on_actor_dead(tracked, str(exc))
                return
        if meta.on_error is not None:
            meta.on_error(tracked, exc)
