from ray_tpu.air.execution.actor_manager import ActorManager, TrackedActor

__all__ = ["ActorManager", "TrackedActor"]
