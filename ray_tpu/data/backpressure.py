"""Pluggable backpressure policies for the streaming executor.

Counterpart of the reference's backpressure policy plugins
(/root/reference/python/ray/data/_internal/execution/backpressure_policy/:
ConcurrencyCapBackpressurePolicy, StreamingOutputBackpressurePolicy).  The
pull-based generator executor gives coarse backpressure for free (an op
launches at most ``window`` tasks and only refills when downstream
consumes); policies refine WHEN the window may refill:

- ``ConcurrencyCapPolicy``: the classic in-flight task cap (the default).
- ``OutputBytesPolicy``: bound the estimated bytes of unconsumed output an
  op may hold in the object store — ops producing huge blocks throttle
  below their concurrency cap so the store isn't flooded (the reference's
  streaming-output policy plays this role).

Custom policies subclass ``BackpressurePolicy`` and are installed on the
``DataContext``::

    ctx = DataContext.get_current()
    ctx.backpressure_policies = [MyPolicy(), ConcurrencyCapPolicy()]
"""

from __future__ import annotations

from dataclasses import dataclass


@dataclass
class OpSnapshot:
    """What a policy sees before each launch decision."""

    op_name: str
    in_flight: int            # tasks currently running
    window: int               # the op's configured concurrency cap
    bytes_per_task: float     # rolling estimate of output bytes per task
    outstanding_bytes: float  # estimated unconsumed output in the store


class BackpressurePolicy:
    """Decide whether an operator may launch one more task."""

    def can_launch(self, snap: OpSnapshot) -> bool:
        raise NotImplementedError


class ConcurrencyCapPolicy(BackpressurePolicy):
    """At most ``window`` tasks in flight (reference:
    ConcurrencyCapBackpressurePolicy)."""

    def can_launch(self, snap: OpSnapshot) -> bool:
        return snap.in_flight < snap.window


class OutputBytesPolicy(BackpressurePolicy):
    """Bound estimated unconsumed output bytes per op (reference:
    StreamingOutputBackpressurePolicy).  Always admits the first task —
    the estimate needs one completed task to calibrate."""

    def __init__(self, max_outstanding_bytes: int = 512 * 1024 * 1024):
        self.max_outstanding_bytes = max_outstanding_bytes

    def can_launch(self, snap: OpSnapshot) -> bool:
        if snap.in_flight == 0:
            return True
        if snap.bytes_per_task <= 0:
            # uncalibrated (no task has completed): hold concurrency low
            # instead of flooding the window before the first estimate
            return snap.in_flight < 2
        return snap.outstanding_bytes < self.max_outstanding_bytes


def default_policies() -> list:
    return [ConcurrencyCapPolicy(), OutputBytesPolicy()]
