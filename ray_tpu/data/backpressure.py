"""Pluggable backpressure policies for the streaming executor.

Counterpart of the reference's backpressure policy plugins
(/root/reference/python/ray/data/_internal/execution/backpressure_policy/:
ConcurrencyCapBackpressurePolicy, StreamingOutputBackpressurePolicy).  The
pull-based generator executor gives coarse backpressure for free (an op
launches at most ``window`` tasks and only refills when downstream
consumes); policies refine WHEN the window may refill:

- ``ConcurrencyCapPolicy``: the classic in-flight task cap (the default).
- ``OutputBytesPolicy``: bound the estimated bytes of unconsumed output an
  op may hold in the object store — ops producing huge blocks throttle
  below their concurrency cap so the store isn't flooded (the reference's
  streaming-output policy plays this role).

Custom policies subclass ``BackpressurePolicy`` and are installed on the
``DataContext``::

    ctx = DataContext.get_current()
    ctx.backpressure_policies = [MyPolicy(), ConcurrencyCapPolicy()]
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional


@dataclass
class OpSnapshot:
    """What a policy sees before each launch decision."""

    op_name: str
    in_flight: int            # tasks currently running
    window: int               # the op's configured concurrency cap
    bytes_per_task: float     # rolling estimate of output bytes per task
    outstanding_bytes: float  # estimated unconsumed output in the store
    # unique per OPERATOR EXECUTION: two concurrent ops can share a
    # display name ("Map(<lambda>)"), and identity-keyed accounting must
    # not alias them
    op_token: str = ""


class BackpressurePolicy:
    """Decide whether an operator may launch one more task.

    ``on_launch``/``on_complete`` let stateful policies account across
    operators (a policy instance installed on the DataContext is SHARED
    by every op in the process — that sharing is what makes a global
    resource manager possible)."""

    def can_launch(self, snap: OpSnapshot) -> bool:
        raise NotImplementedError

    def on_launch(self, snap: OpSnapshot) -> None:
        pass

    def on_complete(self, op_token: str, out_bytes: int) -> None:
        """op_token is the UNIQUE execution token (OpSnapshot.op_token),
        matching on_launch's snap.op_token — not the display name."""
        pass


class ConcurrencyCapPolicy(BackpressurePolicy):
    """At most ``window`` tasks in flight (reference:
    ConcurrencyCapBackpressurePolicy)."""

    def can_launch(self, snap: OpSnapshot) -> bool:
        return snap.in_flight < snap.window


class OutputBytesPolicy(BackpressurePolicy):
    """Bound estimated unconsumed output bytes per op (reference:
    StreamingOutputBackpressurePolicy).  Always admits the first task —
    the estimate needs one completed task to calibrate."""

    def __init__(self, max_outstanding_bytes: int = 512 * 1024 * 1024):
        self.max_outstanding_bytes = max_outstanding_bytes

    def can_launch(self, snap: OpSnapshot) -> bool:
        if snap.in_flight == 0:
            return True
        if snap.bytes_per_task <= 0:
            # uncalibrated (no task has completed): hold concurrency low
            # instead of flooding the window before the first estimate
            return snap.in_flight < 2
        return snap.outstanding_bytes < self.max_outstanding_bytes


class ResourceManagerPolicy(BackpressurePolicy):
    """Execution-wide task budget across ALL operators (reference:
    _internal/execution/resource_manager.py — the streaming executor's
    per-op resource bookkeeping feeding global limits).  A pipeline of N
    ops each honoring its own window can still oversubscribe the cluster
    N-fold; this policy caps their SUM."""

    def __init__(self, max_total_tasks: Optional[int] = None):
        import os as _os
        import threading as _threading

        self.max_total_tasks = max_total_tasks or max(
            8, 2 * (_os.cpu_count() or 4))
        self._lock = _threading.Lock()
        self._in_flight: dict = {}

    def total_in_flight(self) -> int:
        with self._lock:
            return sum(self._in_flight.values())

    def can_launch(self, snap: OpSnapshot) -> bool:
        with self._lock:
            other = sum(v for k, v in self._in_flight.items()
                        if k != snap.op_token)
        # this op's own count comes from the snapshot (authoritative)
        return other + snap.in_flight < self.max_total_tasks

    def on_launch(self, snap: OpSnapshot) -> None:
        with self._lock:
            self._in_flight[snap.op_token] = \
                self._in_flight.get(snap.op_token, 0) + 1

    def on_complete(self, op_token: str, out_bytes: int) -> None:
        with self._lock:
            n = self._in_flight.get(op_token, 0) - 1
            if n > 0:
                self._in_flight[op_token] = n
            else:
                self._in_flight.pop(op_token, None)


def default_policies() -> list:
    return [ConcurrencyCapPolicy(), OutputBytesPolicy()]
