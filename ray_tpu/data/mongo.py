"""MongoDB datasource over the raw wire protocol — no pymongo.

Counterpart of the reference's mongo datasource
(/root/reference/python/ray/data/_internal/datasource/mongo_datasource.py,
a pymongo + pymongoarrow wrapper).  The TPU image carries no client
wheels, so this module speaks the modern wire protocol directly:
OP_MSG (opcode 2013, MongoDB 3.6+) frames carrying BSON command
documents over a plain TCP socket — `find` with `_id`-range filters for
partitioned parallel reads, `getMore` for cursor batches.

The BSON subset implemented covers the types a read path round-trips:
double, string, document, array, binary, ObjectId, bool, UTC datetime
(surfaced as int64 millis), null, int32, int64, and Decimal128 /
regex / timestamp are surfaced as raw bytes rather than dropped.

Read: ``ray_tpu.data.read_mongo(uri, database, collection, ...)`` —
partition bounds come from one `find` on the extreme `_id`s, then each
read task runs an independent range query on its own connection.
"""

from __future__ import annotations

import functools
import socket
import struct
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple

import pyarrow as pa

# ---------------------------------------------------------------------
# BSON (subset) — https://bsonspec.org
# ---------------------------------------------------------------------

_I32 = struct.Struct("<i")
_I64 = struct.Struct("<q")
_F64 = struct.Struct("<d")


@functools.total_ordering
class ObjectId:
    """12-byte document id; totally ordered by big-endian byte order."""

    __slots__ = ("raw",)

    def __init__(self, raw: bytes):
        if len(raw) != 12:
            raise ValueError("ObjectId is 12 bytes")
        self.raw = raw

    def __repr__(self):
        return f"ObjectId({self.raw.hex()})"

    def __eq__(self, other):
        return isinstance(other, ObjectId) and self.raw == other.raw

    def __lt__(self, other):
        return self.raw < other.raw

    def __hash__(self):
        return hash(self.raw)


def _enc_cstr(s: str) -> bytes:
    b = s.encode("utf-8")
    if b"\x00" in b:
        raise ValueError("embedded NUL in key")
    return b + b"\x00"


def _enc_value(key: str, v: Any) -> bytes:
    k = _enc_cstr(key)
    if isinstance(v, bool):  # before int: bool is an int subclass
        return b"\x08" + k + (b"\x01" if v else b"\x00")
    if isinstance(v, float):
        return b"\x01" + k + _F64.pack(v)
    if isinstance(v, str):
        b = v.encode("utf-8") + b"\x00"
        return b"\x02" + k + _I32.pack(len(b)) + b
    if isinstance(v, dict):
        return b"\x03" + k + encode_document(v)
    if isinstance(v, (list, tuple)):
        doc = {str(i): item for i, item in enumerate(v)}
        return b"\x04" + k + encode_document(doc)
    if isinstance(v, (bytes, bytearray)):
        return (b"\x05" + k + _I32.pack(len(v)) + b"\x00" + bytes(v))
    if isinstance(v, ObjectId):
        return b"\x07" + k + v.raw
    if v is None:
        return b"\x0a" + k
    if isinstance(v, int):
        if -(1 << 31) <= v < (1 << 31):
            return b"\x10" + k + _I32.pack(v)
        return b"\x12" + k + _I64.pack(v)
    raise TypeError(f"cannot BSON-encode {type(v).__name__}")


def encode_document(doc: Dict[str, Any]) -> bytes:
    body = b"".join(_enc_value(k, v) for k, v in doc.items())
    return _I32.pack(len(body) + 5) + body + b"\x00"


def _dec_cstr(buf: memoryview, pos: int) -> Tuple[str, int]:
    end = pos
    while buf[end] != 0:
        end += 1
    return bytes(buf[pos:end]).decode("utf-8"), end + 1


def decode_document(buf, pos: int = 0) -> Tuple[Dict[str, Any], int]:
    buf = memoryview(buf)
    (size,) = _I32.unpack_from(buf, pos)
    end = pos + size
    pos += 4
    out: Dict[str, Any] = {}
    while pos < end - 1:
        tag = buf[pos]
        pos += 1
        key, pos = _dec_cstr(buf, pos)
        if tag == 0x01:
            (out[key],) = _F64.unpack_from(buf, pos)
            pos += 8
        elif tag == 0x02:
            (n,) = _I32.unpack_from(buf, pos)
            out[key] = bytes(buf[pos + 4:pos + 4 + n - 1]).decode("utf-8")
            pos += 4 + n
        elif tag == 0x03:
            out[key], pos = decode_document(buf, pos)
        elif tag == 0x04:
            arr_doc, pos = decode_document(buf, pos)
            out[key] = list(arr_doc.values())
        elif tag == 0x05:
            (n,) = _I32.unpack_from(buf, pos)
            out[key] = bytes(buf[pos + 5:pos + 5 + n])
            pos += 5 + n
        elif tag == 0x07:
            out[key] = ObjectId(bytes(buf[pos:pos + 12]))
            pos += 12
        elif tag == 0x08:
            out[key] = buf[pos] != 0
            pos += 1
        elif tag == 0x09:  # UTC datetime: surfaced as int64 millis
            (out[key],) = _I64.unpack_from(buf, pos)
            pos += 8
        elif tag == 0x0A:
            out[key] = None
        elif tag == 0x0B:  # regex: two cstrings, surfaced as a tuple
            pat, pos = _dec_cstr(buf, pos)
            opts, pos = _dec_cstr(buf, pos)
            out[key] = (pat, opts)
        elif tag == 0x10:
            (out[key],) = _I32.unpack_from(buf, pos)
            pos += 4
        elif tag == 0x11:  # timestamp: surfaced as raw u64
            (out[key],) = struct.unpack_from("<Q", buf, pos)
            pos += 8
        elif tag == 0x12:
            (out[key],) = _I64.unpack_from(buf, pos)
            pos += 8
        elif tag == 0x13:  # Decimal128: surfaced as raw 16 bytes
            out[key] = bytes(buf[pos:pos + 16])
            pos += 16
        else:
            raise ValueError(f"unsupported BSON tag 0x{tag:02x} "
                             f"for key {key!r}")
    return out, end


# ---------------------------------------------------------------------
# OP_MSG transport
# ---------------------------------------------------------------------

_OP_MSG = 2013
_HDR = struct.Struct("<iiii")  # messageLength, requestID, responseTo, opCode


class MongoWire:
    """One connection speaking OP_MSG command round trips."""

    def __init__(self, host: str, port: int = 27017,
                 timeout: float = 30.0):
        self._sock = socket.create_connection((host, port),
                                              timeout=timeout)
        self._req_id = 0

    def close(self):
        try:
            self._sock.close()
        except OSError:
            pass

    def _recv_exact(self, n: int) -> bytes:
        buf = b""
        while len(buf) < n:
            chunk = self._sock.recv(n - len(buf))
            if not chunk:
                raise ConnectionError("mongod closed the connection")
            buf += chunk
        return buf

    def command(self, doc: Dict[str, Any]) -> Dict[str, Any]:
        """One OP_MSG round trip; raises on {ok: 0} replies."""
        self._req_id += 1
        body = b"\x00" + encode_document(doc)  # flags=0, section kind 0
        msg = _HDR.pack(16 + 4 + len(body), self._req_id, 0, _OP_MSG)
        msg += b"\x00\x00\x00\x00" + body  # flagBits
        self._sock.sendall(msg)
        (length, _rid, _rto, opcode) = _HDR.unpack(self._recv_exact(16))
        payload = self._recv_exact(length - 16)
        if opcode != _OP_MSG:
            raise ValueError(f"unexpected reply opcode {opcode}")
        if payload[4] != 0:
            raise ValueError("unsupported OP_MSG reply section kind")
        reply, _ = decode_document(payload, 5)
        if not reply.get("ok"):
            raise RuntimeError(
                f"mongod error: {reply.get('errmsg', reply)}")
        return reply

    def find(self, db: str, collection: str,
             filter: Optional[dict] = None,
             projection: Optional[dict] = None,
             sort: Optional[dict] = None, limit: int = 0,
             batch_size: int = 1000) -> Iterator[dict]:
        """Stream matching documents (find + getMore)."""
        cmd: Dict[str, Any] = {"find": collection, "$db": db,
                               "batchSize": batch_size}
        if filter:
            cmd["filter"] = filter
        if projection:
            cmd["projection"] = projection
        if sort:
            cmd["sort"] = sort
        if limit:
            cmd["limit"] = limit
        reply = self.command(cmd)
        cursor = reply["cursor"]
        yield from cursor["firstBatch"]
        cid = cursor["id"]
        while cid:
            reply = self.command({"getMore": cid, "$db": db,
                                  "collection": collection,
                                  "batchSize": batch_size})
            cursor = reply["cursor"]
            yield from cursor["nextBatch"]
            cid = cursor["id"]


def parse_uri(uri: str) -> Tuple[str, int]:
    """host, port from mongodb://host[:port][/...].

    Credentials and multi-host replica-set lists are NOT supported by
    this wire client — fail up front with a clear error rather than
    connecting unauthenticated or misparsing a host list."""
    if uri.startswith("mongodb://"):
        uri = uri[len("mongodb://"):]
    hostpart = uri.split("/", 1)[0]
    if "@" in hostpart:
        raise ValueError(
            "read_mongo's wire client does not support authentication "
            "credentials in the URI; connect to an auth-free endpoint "
            "(e.g. a local replica / tunnel)")
    if "," in hostpart:
        raise ValueError(
            "read_mongo's wire client takes a single host, not a "
            "replica-set list; point it at one member")
    if ":" in hostpart:
        host, port_s = hostpart.rsplit(":", 1)
        return host, int(port_s)
    return hostpart, 27017


# ---------------------------------------------------------------------
# Read tasks
# ---------------------------------------------------------------------

def _to_table(docs: List[dict]) -> pa.Table:
    if not docs:
        return pa.table({})
    cols: Dict[str, list] = {}
    keys: List[str] = []
    for d in docs:
        for k in d:
            if k not in cols:
                cols[k] = []
                keys.append(k)
    for d in docs:
        for k in keys:
            v = d.get(k)
            if isinstance(v, ObjectId):
                v = v.raw.hex()
            elif isinstance(v, dict) or isinstance(v, tuple):
                v = repr(v)
            cols[k].append(v)
    arrays = {}
    for k in keys:
        try:
            arrays[k] = pa.array(cols[k])
        except (pa.ArrowInvalid, pa.ArrowTypeError):
            # schemaless collection: a field holds different BSON types
            # across documents — degrade that column to strings rather
            # than failing the read task
            arrays[k] = pa.array(
                [None if v is None else str(v) for v in cols[k]])
    return pa.table(arrays)


def mongo_tasks(uri: str, database: str, collection: str,
                parallelism: int,
                filter: Optional[dict] = None,
                projection: Optional[dict] = None,
                batch_size: int = 1000) -> List[Callable]:
    """Partitioned read tasks: `_id`-range slices of the collection.

    Planning runs two 1-document finds for the extreme `_id`s, then cuts
    the ObjectId space into ``parallelism`` even byte-ranges — the same
    strategy the reference datasource delegates to pymongoarrow's
    partitioner."""
    host, port = parse_uri(uri)
    conn = MongoWire(host, port)
    try:
        lo = list(conn.find(database, collection, filter=filter,
                            projection={"_id": 1}, sort={"_id": 1},
                            limit=1))
        hi = list(conn.find(database, collection, filter=filter,
                            projection={"_id": 1}, sort={"_id": -1},
                            limit=1))
    finally:
        conn.close()
    if not lo or not hi:
        return []
    lo_id, hi_id = lo[0]["_id"], hi[0]["_id"]
    n = max(1, parallelism)
    bounds: List[Tuple[Any, Any]] = []
    if isinstance(lo_id, ObjectId) and isinstance(hi_id, ObjectId) and n > 1:
        lo_i = int.from_bytes(lo_id.raw, "big")
        hi_i = int.from_bytes(hi_id.raw, "big")
        cuts = [lo_i + (hi_i - lo_i) * i // n for i in range(n + 1)]
        edges = [ObjectId(c.to_bytes(12, "big")) for c in cuts]
        bounds = list(zip(edges[:-1], edges[1:]))
    else:
        bounds = [(lo_id, hi_id)]

    def make_task(lo_b, hi_b, last: bool):
        def task() -> Iterator[pa.Table]:
            rng: Dict[str, Any] = {"$gte": lo_b}
            rng["$lte" if last else "$lt"] = hi_b
            if filter and "_id" in filter:
                # never clobber a user _id predicate ($in/$ne/...): AND
                # the partition range with the whole filter instead
                q: Dict[str, Any] = {"$and": [dict(filter),
                                              {"_id": rng}]}
            else:
                q = dict(filter or {})
                q["_id"] = rng
            c = MongoWire(host, port)
            try:
                docs = list(c.find(database, collection, filter=q,
                                   projection=projection,
                                   batch_size=batch_size))
            finally:
                c.close()
            if docs:
                yield _to_table(docs)
        return task

    return [make_task(lo_b, hi_b, i == len(bounds) - 1)
            for i, (lo_b, hi_b) in enumerate(bounds)]
