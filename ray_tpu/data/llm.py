"""Batch LLM inference as Dataset stages (``ray_tpu.data.llm``).

Counterpart of the reference's Data LLM processor pipeline
(/root/reference/python/ray/llm/_internal/batch/processor/: tokenize →
(chat template) → engine stage → detokenize, each a Dataset UDF stage with
actor pools). The engine stage is a class UDF — one continuous-batching
``LLMEngine`` per actor, TPU-resident across batches — and rows flow
through ``map_batches``, so the streaming executor overlaps tokenization,
generation, and downstream stages.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Optional

import numpy as np

from ray_tpu.llm.engine import EngineConfig, SamplingParams


@dataclass
class ProcessorConfig:
    """Reference: batch/processor/vllm_engine_proc.py config shape."""

    model_loader: Optional[Callable] = None  # () -> (params, LlamaConfig)
    tokenizer: Optional[str] = None  # None/"byte" or HF name
    engine_config: EngineConfig = field(default_factory=EngineConfig)
    # concurrency = engine actors; each holds model weights on its device
    concurrency: int = 1
    batch_size: int = 16
    apply_chat_template: bool = False
    sampling: Dict[str, Any] = field(default_factory=dict)
    # device ask per engine actor (1.0 = one TPU chip; 0 for CPU tests)
    num_tpus: float = 0.0


class _EngineStage:
    """Class UDF: engine lives for the actor's lifetime."""

    def __init__(self, config: ProcessorConfig):
        from ray_tpu.llm.engine import LLMEngine
        from ray_tpu.llm.tokenizer import get_tokenizer

        params, model_cfg = config.model_loader()
        self._tok = get_tokenizer(config.tokenizer)
        self._engine = LLMEngine(params, model_cfg, config.engine_config)
        self._engine.start()
        self._config = config

    def __call__(self, batch: Dict[str, np.ndarray]) -> Dict[str, Any]:
        cfg = self._config
        sp = SamplingParams(**cfg.sampling) if cfg.sampling else (
            SamplingParams(max_tokens=32))
        eos = getattr(self._tok, "eos_id", None)
        if eos is not None:
            sp = SamplingParams(
                max_tokens=sp.max_tokens, temperature=sp.temperature,
                top_p=sp.top_p, stop_token_ids=tuple(sp.stop_token_ids)
                + (eos,), seed=sp.seed)
        prompts = [str(p) for p in batch["prompt"].tolist()]
        if cfg.apply_chat_template:
            prompts = [self._tok.apply_chat_template(
                [{"role": "user", "content": p}]) for p in prompts]
        # Submit the whole batch; the engine's continuous batcher packs
        # them into one decode schedule (no per-row serialization).
        reqs = [self._engine.submit(self._tok.encode(p), sp)
                for p in prompts]
        token_lists = []
        for req in reqs:
            toks = []
            while True:
                item = req.out_queue.get(timeout=300)
                if item is None:
                    break
                if isinstance(item, Exception):
                    raise item
                toks.append(item)
            token_lists.append(toks)
        out = dict(batch)
        out["generated_tokens"] = np.array(
            [np.array(t, dtype=np.int64) for t in token_lists],
            dtype=object)
        out["generated_text"] = np.array(
            [self._tok.decode(list(t)) for t in token_lists], dtype=object)
        return out


def build_llm_processor(
    config: ProcessorConfig,
    preprocess: Optional[Callable] = None,
    postprocess: Optional[Callable] = None,
) -> Callable:
    """Return ``process(ds) -> ds`` appending the LLM stages.

    ``preprocess``/``postprocess`` are row-wise hooks, as in the reference
    (build_llm_processor in batch/processor/__init__.py): preprocess maps a
    row to one with a "prompt" column; postprocess consumes
    "generated_text"/"generated_tokens".
    """
    if config.model_loader is None:
        raise ValueError("ProcessorConfig.model_loader is required")

    def process(ds):
        if preprocess is not None:
            ds = ds.map(preprocess)
        ds = ds.map_batches(
            _EngineStage,
            fn_constructor_args=(config,),
            batch_size=config.batch_size,
            batch_format="numpy",
            concurrency=config.concurrency,
            num_tpus=config.num_tpus or None,
        )
        if postprocess is not None:
            ds = ds.map(postprocess)
        return ds

    return process
