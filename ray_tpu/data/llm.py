"""``ray_tpu.data.llm``: the reference's ``ray.data.llm`` import path.

The implementation lives in ray_tpu.llm.batch (engine + stages are LLM
concerns); this alias mirrors the reference's public module layout
(/root/reference/python/ray/data/llm.py re-exporting _internal/batch).
"""

from ray_tpu.llm.batch import ProcessorConfig, build_llm_processor

__all__ = ["ProcessorConfig", "build_llm_processor"]
