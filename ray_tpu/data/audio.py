"""Audio datasource: WAV decoding with the stdlib, no client wheels.

Counterpart of the reference's audio datasource
(/root/reference/python/ray/data/_internal/datasource/audio_datasource.py,
which delegates decoding to ``soundfile``).  The TPU image has no
libsndfile, so PCM WAV — the dominant training-corpus container — is
decoded natively (stdlib ``wave`` + numpy: 8/16/32-bit int and IEEE
float frames); other containers use ``soundfile`` when present and fail
with an actionable error when not.

Rows: {"amplitude": float32[n_channels, n_samples], "sample_rate": int,
"path": str} — amplitude normalized to [-1, 1] like the reference.
"""

from __future__ import annotations

import struct
import wave
from typing import Callable, Iterator, List

import numpy as np
import pyarrow as pa

from ray_tpu.data.datasource import Block, _file_tasks, expand_paths


def _decode_wav(path: str):
    with wave.open(path, "rb") as w:
        n_ch = w.getnchannels()
        width = w.getsampwidth()
        rate = w.getframerate()
        raw = w.readframes(w.getnframes())
    if width == 1:  # unsigned 8-bit
        x = np.frombuffer(raw, np.uint8).astype(np.float32)
        x = (x - 128.0) / 128.0
    elif width == 2:
        x = np.frombuffer(raw, "<i2").astype(np.float32) / 32768.0
    elif width == 3:  # packed 24-bit: widen to i4
        b = np.frombuffer(raw, np.uint8).reshape(-1, 3)
        widened = np.zeros((b.shape[0], 4), np.uint8)
        widened[:, 1:] = b
        x = widened.view("<i4").ravel().astype(np.float32) / 2147483648.0
    elif width == 4:
        x = np.frombuffer(raw, "<i4").astype(np.float32) / 2147483648.0
    else:
        raise ValueError(f"unsupported WAV sample width {width} ({path})")
    return x.reshape(-1, n_ch).T, rate


def _walk_riff(data: bytes):
    """Yield (chunk_id, payload_offset, size) — encoders commonly prepend
    JUNK/LIST chunks, so fmt/data are found by walking, never by fixed
    offsets."""
    if data[:4] != b"RIFF" or data[8:12] != b"WAVE":
        return
    pos = 12
    while pos + 8 <= len(data):
        cid = data[pos:pos + 4]
        size, = struct.unpack_from("<I", data, pos + 4)
        yield cid, pos + 8, size
        pos += 8 + size + (size & 1)


def _is_float_wav(path: str) -> bool:
    """IEEE-float WAVs (fmt tag 3) — stdlib wave rejects them, so sniff
    the fmt chunk (wherever it sits) and decode the frames directly."""
    try:
        with open(path, "rb") as f:
            data = f.read(1 << 16)
        for cid, off, _size in _walk_riff(data):
            if cid == b"fmt ":
                return struct.unpack_from("<H", data, off)[0] == 3
    except (OSError, struct.error):
        pass
    return False


def _decode_float_wav(path: str):
    with open(path, "rb") as f:
        data = f.read()
    n_ch = rate = width = None
    for cid, off, size in _walk_riff(data):
        if cid == b"fmt ":
            n_ch, = struct.unpack_from("<H", data, off + 2)
            rate, = struct.unpack_from("<I", data, off + 4)
            width, = struct.unpack_from("<H", data, off + 14)
        elif cid == b"data":
            if n_ch is None:
                break  # fmt must precede data per spec
            raw = data[off:off + size]
            dt = "<f4" if width == 32 else "<f8"
            x = np.frombuffer(raw, dt).astype(np.float32)
            return x.reshape(-1, n_ch).T, rate
    raise ValueError(f"malformed float WAV {path}")


def decode_audio(path: str):
    """(float32[channels, samples], sample_rate) for one audio file."""
    if path.lower().endswith(".wav"):
        if _is_float_wav(path):
            return _decode_float_wav(path)
        return _decode_wav(path)
    try:
        import soundfile  # noqa: F401  (not in the TPU image)
    except ImportError:
        raise ImportError(
            f"decoding {path!r} needs the `soundfile` wheel (not in the "
            f"TPU image); PCM/float WAV decodes natively") from None
    data, rate = soundfile.read(path, always_2d=True, dtype="float32")
    return data.T, rate


def audio_tasks(paths, parallelism: int) -> List[Callable]:
    files = expand_paths(paths)

    def read_file(f: str) -> Iterator[Block]:
        amp, rate = decode_audio(f)
        # tensor-column path (same layout as images/video frames): the
        # (ch, samples) array rides a fixed-size-list column zero-copy
        from ray_tpu.data import block as block_mod

        yield block_mod.from_batch({
            "amplitude": amp[None, ...],
            "sample_rate": np.array([rate], np.int64),
            "path": [f],
        })

    return _file_tasks(files, parallelism, read_file)
