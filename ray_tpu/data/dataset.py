"""Dataset: lazy, streaming, distributed data over Arrow blocks.

Counterpart of the reference's Dataset
(/root/reference/python/ray/data/dataset.py:160 — map_batches :449,
streaming_split :1731, iter_batches :4652, materialize :5614): transforms
append logical ops; consumption plans + runs the streaming executor.  TPU
relevance: ``iter_batches`` feeds numpy batches sized for ``jax.device_put``
and ``streaming_split`` hands each train worker its own shard iterator.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple, Union

import numpy as np
import pyarrow as pa

import ray_tpu
from ray_tpu.data import block as block_mod
from ray_tpu.data import logical as L
from ray_tpu.data import shuffle as shuffle_mod
from ray_tpu.data.block import Block, BlockMetadata
from ray_tpu.data.context import DataContext
from ray_tpu.data.executor import ExecStats, execute_streaming
from ray_tpu.data.iterator import DataIterator, _BundleIterable


def _batch_transform(fn: Callable, batch_format: str, batch_size: Optional[int],
                     fn_args: tuple, fn_kwargs: dict) -> Callable:
    """Wrap a user batch UDF into a block transform iter[Block]->iter[Block]."""

    def transform(blocks: Iterator[Block]) -> Iterator[Block]:
        def batches():
            if batch_size is None:
                for b in blocks:
                    if b.num_rows:
                        yield b
                return
            # re-slice the stream into exact batch_size chunks
            buf: List[Block] = []
            have = 0
            for b in blocks:
                while b.num_rows:
                    need = batch_size - have
                    take = min(need, b.num_rows)
                    buf.append(b.slice(0, take))
                    b = b.slice(take, b.num_rows - take)
                    have += take
                    if have == batch_size:
                        yield block_mod.concat(buf)
                        buf, have = [], 0
            if buf:
                yield block_mod.concat(buf)

        for batch_block in batches():
            batch = block_mod.to_batch(batch_block, batch_format)
            out = fn(batch, *fn_args, **fn_kwargs)
            yield block_mod.from_batch(out)

    return transform


def _row_transform(kind: str, fn: Callable) -> Callable:
    def transform(blocks: Iterator[Block]) -> Iterator[Block]:
        for b in blocks:
            rows = b.to_pylist()
            if kind == "map":
                out = [fn(r) for r in rows]
            elif kind == "flat_map":
                out = [o for r in rows for o in fn(r)]
            elif kind == "filter":
                out = [r for r in rows if fn(r)]
            else:
                raise ValueError(kind)
            yield block_mod.from_rows(out)

    return transform


class Dataset:
    def __init__(self, plan: L.LogicalPlan):
        self._plan = plan
        self._last_stats: Optional[ExecStats] = None

    # ------------------------- transforms --------------------------------

    def _one_to_one(self, name: str, block_fn=None, **kw) -> "Dataset":
        op = L.OneToOne(name=name, block_fn=block_fn, **kw)
        return Dataset(self._plan.with_op(op))

    def map_batches(self, fn: Union[Callable, type], *,
                    batch_size: Optional[int] = "default",
                    batch_format: str = "numpy",
                    compute: Optional[str] = None,
                    fn_args: tuple = (), fn_kwargs: Optional[dict] = None,
                    fn_constructor_args: tuple = (),
                    fn_constructor_kwargs: Optional[dict] = None,
                    concurrency: Optional[int] = None,
                    num_cpus: Optional[float] = None,
                    num_tpus: Optional[float] = None,
                    memory: Optional[float] = None,
                    **_ignored) -> "Dataset":
        """Reference: dataset.py:449.  A class UDF selects actor compute —
        the pool constructs one instance per actor (dataset.py 'Stateful
        Transforms')."""
        if batch_size == "default":
            batch_size = DataContext.get_current().target_batch_size
        fn_kwargs = fn_kwargs or {}
        is_class = isinstance(fn, type)
        name = f"MapBatches({getattr(fn, '__name__', 'fn')})"
        if not is_class and compute == "actors":
            # Plain function with actor compute: wrap it so the pool's
            # per-actor "constructor" just captures the function.
            user_fn = fn

            class _FnWrapper:  # noqa: N801 — internal
                def __call__(self, batch, *a, **k):
                    return user_fn(batch, *a, **k)

            fn = _FnWrapper
            is_class = True
        if is_class:
            def make_fn(udf, _bs=batch_size, _bf=batch_format,
                        _a=fn_args, _k=fn_kwargs):
                return _batch_transform(udf, _bf, _bs, _a, _k)

            return self._one_to_one(
                name, block_fn=make_fn, compute="actors", udf_cls=fn,
                udf_args=fn_constructor_args,
                udf_kwargs=fn_constructor_kwargs or {},
                concurrency=concurrency, num_cpus=num_cpus,
                num_tpus=num_tpus, memory=memory)
        return self._one_to_one(
            name,
            block_fn=_batch_transform(fn, batch_format, batch_size,
                                      fn_args, fn_kwargs),
            concurrency=concurrency, num_cpus=num_cpus, num_tpus=num_tpus,
            memory=memory)

    def map(self, fn: Callable, **kw) -> "Dataset":
        return self._one_to_one(f"Map({getattr(fn, '__name__', 'fn')})",
                                block_fn=_row_transform("map", fn))

    def flat_map(self, fn: Callable, **kw) -> "Dataset":
        return self._one_to_one(f"FlatMap({getattr(fn, '__name__', 'fn')})",
                                block_fn=_row_transform("flat_map", fn))

    def filter(self, fn: Callable, **kw) -> "Dataset":
        return self._one_to_one(f"Filter({getattr(fn, '__name__', 'fn')})",
                                block_fn=_row_transform("filter", fn))

    def select_columns(self, cols: List[str]) -> "Dataset":
        def transform(blocks):
            for b in blocks:
                yield b.select(cols)

        return self._one_to_one(f"Select{cols}", block_fn=transform)

    def drop_columns(self, cols: List[str]) -> "Dataset":
        def transform(blocks):
            for b in blocks:
                keep = [c for c in b.column_names if c not in cols]
                yield b.select(keep)

        return self._one_to_one(f"Drop{cols}", block_fn=transform)

    def add_column(self, name: str, fn: Callable) -> "Dataset":
        def transform(blocks):
            for b in blocks:
                batch = block_mod.to_batch(b, "numpy")
                col = fn(batch)
                yield b.append_column(name, pa.array(np.asarray(col)))

        return self._one_to_one(f"AddColumn[{name}]", block_fn=transform)

    def rename_columns(self, mapping: Dict[str, str]) -> "Dataset":
        def transform(blocks):
            for b in blocks:
                yield b.rename_columns(
                    [mapping.get(c, c) for c in b.column_names])

        return self._one_to_one("RenameColumns", block_fn=transform)

    # ------------------------- all-to-all --------------------------------

    def repartition(self, num_blocks: int) -> "Dataset":
        op = L.AllToAll(name=f"Repartition[{num_blocks}]",
                        bulk_fn=shuffle_mod.repartition_fn(num_blocks))
        return Dataset(self._plan.with_op(op))

    def random_shuffle(self, *, seed: Optional[int] = None) -> "Dataset":
        op = L.AllToAll(name="RandomShuffle",
                        bulk_fn=shuffle_mod.random_shuffle_fn(seed))
        return Dataset(self._plan.with_op(op))

    def randomize_block_order(self, *, seed: Optional[int] = None
                              ) -> "Dataset":
        def bulk(bundles, ctx):
            rng = np.random.default_rng(seed)
            order = rng.permutation(len(bundles))
            return [bundles[i] for i in order]

        return Dataset(self._plan.with_op(
            L.AllToAll(name="RandomizeBlockOrder", bulk_fn=bulk)))

    def sort(self, key: str, descending: bool = False) -> "Dataset":
        op = L.AllToAll(name=f"Sort[{key}]",
                        bulk_fn=shuffle_mod.sort_fn(key, descending))
        return Dataset(self._plan.with_op(op))

    def groupby(self, key: Optional[str]) -> "GroupedData":
        return GroupedData(self, key)

    def limit(self, n: int) -> "Dataset":
        return Dataset(self._plan.with_op(L.Limit(name=f"Limit[{n}]",
                                                  limit=n)))

    def union(self, *others: "Dataset") -> "Dataset":
        op = L.Union(name="Union", others=[o._plan for o in others])
        return Dataset(self._plan.with_op(op))

    def zip(self, other: "Dataset") -> "Dataset":
        return Dataset(self._plan.with_op(
            L.Zip(name="Zip", other=other._plan)))

    def join(self, other: "Dataset", on, *, how: str = "inner",
             num_partitions: Optional[int] = None) -> "Dataset":
        """Distributed hash join on key column(s) (reference:
        dataset join via _internal/execution/operators/join.py).

        how: "inner" | "left" | "right" | "outer".
        """
        keys = (on,) if isinstance(on, str) else tuple(on)
        return Dataset(self._plan.with_op(L.Join(
            name=f"Join[{','.join(keys)}]", other=other._plan, on=keys,
            how=how, num_partitions=num_partitions)))

    # global aggregations (reference dataset.py sum/min/max/mean/std)
    def _scalar(self, col: str):
        rows = self.take_all()
        return rows[0][col] if rows else None

    def sum(self, on: str):
        return self.groupby(None).sum(on)._scalar(f"sum({on})")

    def min(self, on: str):
        return self.groupby(None).min(on)._scalar(f"min({on})")

    def max(self, on: str):
        return self.groupby(None).max(on)._scalar(f"max({on})")

    def mean(self, on: str):
        return self.groupby(None).mean(on)._scalar(f"mean({on})")

    def std(self, on: str):
        return self.groupby(None).std(on)._scalar(f"std({on})")

    # ------------------------- execution ---------------------------------

    def _execute(self) -> Iterator[List[Tuple[Any, BlockMetadata]]]:
        self._last_stats = ExecStats()
        return execute_streaming(self._plan, stats_out=self._last_stats)

    def iter_bundles(self) -> Iterator[Tuple[Any, BlockMetadata]]:
        for bundle in self._execute():
            yield from bundle

    def materialize(self) -> "MaterializedDataset":
        bundles = list(self.iter_bundles())
        return MaterializedDataset(
            L.LogicalPlan([L.InputData(name="Input", bundles=bundles)]),
            bundles)

    def count(self) -> int:
        return sum(m.num_rows for _, m in self.iter_bundles())

    def schema(self) -> Optional[pa.Schema]:
        for ref, meta in self.iter_bundles():
            b = ray_tpu.get(ref)
            if b.num_rows or b.schema.names:
                return b.schema
        return None

    def columns(self) -> List[str]:
        s = self.schema()
        return list(s.names) if s else []

    def take(self, n: int = 20) -> List[Dict[str, Any]]:
        out: List[Dict[str, Any]] = []
        for ref, meta in self.limit(n).iter_bundles():
            out.extend(block_mod.rows_of(ray_tpu.get(ref)))
            if len(out) >= n:
                break
        return out[:n]

    def take_all(self) -> List[Dict[str, Any]]:
        out: List[Dict[str, Any]] = []
        for ref, _ in self.iter_bundles():
            out.extend(block_mod.rows_of(ray_tpu.get(ref)))
        return out

    def take_batch(self, n: int = 20, batch_format: str = "numpy"):
        # Stay in Arrow (no row round-trip) so tensor-column shape metadata
        # survives to the batch.
        blocks = [ray_tpu.get(ref)
                  for ref, _ in self.limit(n).iter_bundles()]
        if not blocks:
            return {}
        tbl = block_mod.concat(blocks).slice(0, n)
        return block_mod.to_batch(tbl, batch_format)

    def show(self, n: int = 20) -> None:
        for row in self.take(n):
            print(row)

    def to_pandas(self):
        tables = [ray_tpu.get(ref) for ref, _ in self.iter_bundles()]
        return block_mod.concat(tables).to_pandas() if tables else None

    def to_arrow(self) -> Optional[pa.Table]:
        tables = [ray_tpu.get(ref) for ref, _ in self.iter_bundles()]
        return block_mod.concat(tables) if tables else None

    def stats(self) -> str:
        if self._last_stats is None:
            return "(not executed yet)"
        return self._last_stats.summary()

    # ------------------------- iteration ---------------------------------

    def iterator(self) -> DataIterator:
        return DataIterator(_BundleIterable(self.iter_bundles))

    def iter_rows(self) -> Iterator[Dict[str, Any]]:
        for ref, _ in self.iter_bundles():
            yield from block_mod.rows_of(ray_tpu.get(ref))

    def iter_batches(self, **kw) -> Iterator[Any]:
        return self.iterator().iter_batches(**kw)

    def iter_jax_batches(self, **kw) -> Iterator[Any]:
        return self.iterator().iter_jax_batches(**kw)

    def iter_torch_batches(self, **kw) -> Iterator[Any]:
        return self.iterator().iter_torch_batches(**kw)

    def iter_tf_batches(self, **kw) -> Iterator[Any]:
        return self.iterator().iter_tf_batches(**kw)

    def to_tf(self, feature_columns, label_columns, **kw):
        return self.iterator().to_tf(feature_columns, label_columns, **kw)

    def streaming_split(self, n: int, *, equal: bool = False,
                        locality_hints=None) -> List[DataIterator]:
        """Reference: dataset.py:1731 — a coordinator actor executes the plan
        once and round-robins output bundles to n consumer shards."""
        from ray_tpu.data.split import SplitCoordinator, ShardIterable

        coord = ray_tpu.remote(SplitCoordinator).options(
            num_cpus=0, max_concurrency=2 * n + 2).remote(self._plan, n)
        ray_tpu.get(coord.start.remote())
        return [DataIterator(ShardIterable(coord, i)) for i in range(n)]

    # ------------------------- writes ------------------------------------

    def _write(self, path: str, fmt: str, **kw) -> None:
        from ray_tpu.data.datasource import make_write_fn

        ds = self._one_to_one(f"Write[{fmt}]",
                              block_fn=make_write_fn(path, fmt, kw))
        for _ in ds.iter_bundles():
            pass

    def write_parquet(self, path: str, **kw) -> None:
        self._write(path, "parquet", **kw)

    def write_csv(self, path: str, **kw) -> None:
        self._write(path, "csv", **kw)

    def write_tfrecords(self, path: str, **kw) -> None:
        self._write(path, "tfrecords", **kw)

    def write_json(self, path: str, **kw) -> None:
        self._write(path, "json", **kw)

    def write_avro(self, path: str, **kw) -> None:
        self._write(path, "avro", **kw)

    def __repr__(self):
        return f"Dataset({self._plan!r})"


class MaterializedDataset(Dataset):
    """Execution already happened; blocks are pinned in the object store
    (reference: dataset.py MaterializedDataset)."""

    def __init__(self, plan: L.LogicalPlan, bundles):
        super().__init__(plan)
        self._bundles = bundles

    def count(self) -> int:
        return sum(m.num_rows for _, m in self._bundles)

    def num_blocks(self) -> int:
        return len(self._bundles)


class GroupedData:
    """Reference: python/ray/data/grouped_data.py."""

    def __init__(self, ds: Dataset, key: Optional[str]):
        self._ds = ds
        self._key = key

    def _agg(self, aggs: List[Tuple[str, Optional[str]]]) -> Dataset:
        op = L.AllToAll(
            name=f"Aggregate[{self._key}]",
            bulk_fn=shuffle_mod.groupby_agg_fn(self._key, aggs))
        return Dataset(self._ds._plan.with_op(op))

    def count(self) -> Dataset:
        return self._agg([("count", None)])

    def sum(self, on: str) -> Dataset:
        return self._agg([("sum", on)])

    def min(self, on: str) -> Dataset:
        return self._agg([("min", on)])

    def max(self, on: str) -> Dataset:
        return self._agg([("max", on)])

    def mean(self, on: str) -> Dataset:
        return self._agg([("mean", on)])

    def std(self, on: str) -> Dataset:
        return self._agg([("std", on)])

    def aggregate(self, *aggs: Tuple[str, Optional[str]]) -> Dataset:
        return self._agg(list(aggs))

    def map_groups(self, fn: Callable, *, batch_format: str = "numpy"
                   ) -> Dataset:
        """Sort by key, then apply fn per group (reference:
        grouped_data.py map_groups)."""
        key = self._key
        sorted_ds = self._ds.sort(key)

        def transform(blocks: Iterator[Block]) -> Iterator[Block]:
            tbl = block_mod.concat(list(blocks))
            if tbl.num_rows == 0:
                return
            vals = tbl.column(key).to_pylist()
            start = 0
            for i in range(1, len(vals) + 1):
                if i == len(vals) or vals[i] != vals[start]:
                    group = tbl.slice(start, i - start)
                    out = fn(block_mod.to_batch(group, batch_format))
                    yield block_mod.from_batch(out)
                    start = i

        # group boundaries can span blocks → repartition to 1 block per
        # boundary-run is overkill; concat everything in one task instead.
        return Dataset(sorted_ds._plan.with_op(L.AllToAll(
            name="MapGroups",
            bulk_fn=_map_groups_bulk(transform))))


def _map_groups_bulk(transform):
    def bulk(bundles, ctx):
        def run(refs):
            blocks = list(ray_tpu.get(list(refs)))
            out = list(transform(iter(blocks)))
            return [(ray_tpu.put(b), BlockMetadata.of(b)) for b in out]

        task = ray_tpu.remote(run).options(name="MapGroups")
        return ray_tpu.get(task.remote([r for r, _ in bundles]))

    return bulk
