"""streaming_split: one execution shared by n consumer shards.

Counterpart of the reference's StreamSplitDataIterator + OutputSplitter
(/root/reference/python/ray/data/_internal/execution/operators/
output_splitter.py, dataset.py:1731): a coordinator actor runs the plan on a
background thread and round-robins output bundles into per-shard queues with
bounded depth (backpressure: a slow shard stalls only its own queue, and
eventually the shared executor).  Train workers each pull their shard —
reference Train does exactly this per worker (_internal/data_config.py:119).
"""

from __future__ import annotations

import queue as queue_mod
import threading
from typing import List

import ray_tpu

_DONE = "__done__"
_ERR = "__err__"


class SplitCoordinator:
    """Actor: executes the plan once, feeds n shard queues."""

    def __init__(self, plan, n: int, max_queued_per_shard: int = 8):
        self._plan = plan
        self._n = n
        self._queues: List[queue_mod.Queue] = [
            queue_mod.Queue(maxsize=max_queued_per_shard) for _ in range(n)]
        self._started = False
        self._error: str = ""

    def start(self) -> str:
        if self._started:
            return "ok"
        self._started = True

        def feed():
            try:
                from ray_tpu.data.executor import execute_streaming

                i = 0
                for bundle in execute_streaming(self._plan):
                    for pair in bundle:
                        self._queues[i % self._n].put(pair)
                        i += 1
                for q in self._queues:
                    q.put(_DONE)
            except BaseException as e:  # noqa: BLE001
                # Record the error out-of-band (a full shard queue must not
                # block the broadcast), then nudge each queue best-effort.
                self._error = repr(e)
                for q in self._queues:
                    try:
                        q.put_nowait((_ERR, self._error))
                    except queue_mod.Full:
                        pass

        threading.Thread(target=feed, daemon=True).start()
        return "ok"

    def get_next(self, shard: int):
        """Blocking pop; returns (ref, meta) or the _DONE sentinel.  Runs on
        the actor's thread pool (max_concurrency > n) so shards can block
        concurrently."""
        while True:
            try:
                item = self._queues[shard].get(timeout=0.5)
            except queue_mod.Empty:
                if self._error:
                    raise RuntimeError(
                        f"streaming_split execution failed: {self._error}")
                continue
            if (isinstance(item, tuple) and len(item) == 2
                    and item[0] == _ERR):
                raise RuntimeError(
                    f"streaming_split execution failed: {item[1]}")
            return item


class ShardIterable:
    """Iterable over one shard's bundles; handed to a DataIterator."""

    def __init__(self, coordinator, shard: int):
        self._coord = coordinator
        self._shard = shard

    def __iter__(self):
        while True:
            item = ray_tpu.get(self._coord.get_next.remote(self._shard))
            if item == _DONE:
                return
            yield item
