"""Datasources: read-task generation and file writes.

Counterpart of the reference's read API + datasources
(/root/reference/python/ray/data/read_api.py: read_parquet :786, read_json
:1260, read_datasource :344; _internal/datasource/*): a read is a list of
zero-arg callables, each yielding pyarrow Tables, scheduled as ordinary tasks
by the streaming executor.  File reads split the file list across tasks.
"""

from __future__ import annotations

import glob as glob_mod
import os
from typing import Any, Callable, Dict, Iterator, List, Optional

import numpy as np
import pyarrow as pa

from ray_tpu.data import block as block_mod
from ray_tpu.data.block import VALUE_COL, Block


def _chunk(items: List[Any], n: int) -> List[List[Any]]:
    n = max(1, min(n, len(items)))
    size, rem = divmod(len(items), n)
    out, i = [], 0
    for k in range(n):
        take = size + (1 if k < rem else 0)
        if take:
            out.append(items[i:i + take])
        i += take
    return out


def range_tasks(n: int, parallelism: int) -> List[Callable]:
    """ray_tpu.data.range — integer column "id" like the reference's
    read_api.range."""
    tasks = []
    bounds = np.linspace(0, n, max(1, min(parallelism, n or 1)) + 1,
                         dtype=np.int64)
    for lo, hi in zip(bounds[:-1], bounds[1:]):
        lo_i, hi_i = int(lo), int(hi)

        def read(lo=lo_i, hi=hi_i) -> Iterator[Block]:
            yield pa.table({"id": np.arange(lo, hi, dtype=np.int64)})

        tasks.append(read)
    return tasks


def expand_paths(paths, suffixes: Optional[List[str]] = None) -> List[str]:
    if isinstance(paths, str):
        paths = [paths]
    files: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            for root, _, names in os.walk(p):
                files.extend(os.path.join(root, f) for f in sorted(names))
        elif any(c in p for c in "*?["):
            files.extend(sorted(glob_mod.glob(p)))
        else:
            files.append(p)
    if suffixes:
        files = [f for f in files
                 if any(f.endswith(s) for s in suffixes)]
    if not files:
        raise FileNotFoundError(f"no files matched {paths}")
    return files


def _file_tasks(files: List[str], parallelism: int,
                read_file: Callable[[str], Iterator[Block]]
                ) -> List[Callable]:
    tasks = []
    for group in _chunk(files, parallelism):
        def read(group=group) -> Iterator[Block]:
            for f in group:
                yield from read_file(f)

        tasks.append(read)
    return tasks


def parquet_tasks(paths, parallelism: int,
                  columns: Optional[List[str]] = None) -> List[Callable]:
    files = expand_paths(paths, [".parquet"])

    def read_file(f: str) -> Iterator[Block]:
        import pyarrow.parquet as pq

        yield pq.read_table(f, columns=columns)

    return _file_tasks(files, parallelism, read_file)


def csv_tasks(paths, parallelism: int) -> List[Callable]:
    files = expand_paths(paths)

    def read_file(f: str) -> Iterator[Block]:
        import pyarrow.csv as pacsv

        yield pacsv.read_csv(f)

    return _file_tasks(files, parallelism, read_file)


def json_tasks(paths, parallelism: int) -> List[Callable]:
    """JSONL files (reference read_json handles jsonl via pyarrow.json)."""
    files = expand_paths(paths)

    def read_file(f: str) -> Iterator[Block]:
        import pyarrow.json as pajson

        yield pajson.read_json(f)

    return _file_tasks(files, parallelism, read_file)


def text_tasks(paths, parallelism: int) -> List[Callable]:
    files = expand_paths(paths)

    def read_file(f: str) -> Iterator[Block]:
        with open(f, "r") as fh:
            lines = [ln.rstrip("\n") for ln in fh]
        yield pa.table({"text": lines})

    return _file_tasks(files, parallelism, read_file)


def binary_tasks(paths, parallelism: int,
                 include_paths: bool = False) -> List[Callable]:
    files = expand_paths(paths)

    def read_file(f: str) -> Iterator[Block]:
        with open(f, "rb") as fh:
            data = fh.read()
        cols: Dict[str, Any] = {"bytes": pa.array([data], pa.binary())}
        if include_paths:
            cols["path"] = pa.array([f])
        yield pa.table(cols)

    return _file_tasks(files, parallelism, read_file)


def numpy_tasks(paths, parallelism: int) -> List[Callable]:
    files = expand_paths(paths, [".npy"])

    def read_file(f: str) -> Iterator[Block]:
        arr = np.load(f)
        yield block_mod.from_batch({VALUE_COL: arr})

    return _file_tasks(files, parallelism, read_file)


IMAGE_SUFFIXES = [".png", ".jpg", ".jpeg", ".bmp", ".gif", ".webp"]


def image_tasks(paths, parallelism: int, size=None, mode: str = "RGB",
                include_paths: bool = False) -> List[Callable]:
    """Reference: _internal/datasource/image_datasource.py — decode to
    fixed-shape numpy ("image" column) ready for device batching.

    Without ``size``, all images must share one resolution (static shapes
    are what the device pipeline consumes); mixed sizes raise a clear
    error instead of a downstream ArrowInvalid on concat.
    """
    files = expand_paths(paths, IMAGE_SUFFIXES)

    # The shape check must span ALL files (groups run in different worker
    # processes): probe the first file's header on the driver and hold
    # every group to that expectation.
    expected_shape = None
    if size is None and files:
        from PIL import Image

        with Image.open(files[0]) as probe:
            if mode:
                probe = probe.convert(mode)
            expected_shape = np.asarray(probe).shape

    def read_group(group: List[str]) -> Iterator[Block]:
        from PIL import Image

        for f in group:
            img = Image.open(f)
            if mode:
                img = img.convert(mode)
            if size is not None:
                img = img.resize(tuple(size))
            arr = np.asarray(img)
            if expected_shape is not None and arr.shape != expected_shape:
                raise ValueError(
                    f"read_images: mixed image shapes {expected_shape} vs "
                    f"{arr.shape} ({f}); pass size=(w, h) to resize to "
                    f"a common resolution")
            batch: Dict[str, Any] = {"image": arr[None]}
            if include_paths:
                batch["path"] = np.array([f])
            yield block_mod.from_batch(batch)

    tasks = []
    for group in _chunk(files, parallelism):
        def read(group=group) -> Iterator[Block]:
            yield from read_group(group)

        tasks.append(read)
    return tasks


def huggingface_tasks(hf_dataset, parallelism: int) -> List[Callable]:
    """Reference: read_api.py from_huggingface — zero-copy over the HF
    dataset's arrow shards."""
    table = hf_dataset.data.table.combine_chunks()
    n = max(1, table.num_rows)
    per = -(-n // parallelism)
    tasks = []
    for lo in range(0, n, per):
        hi = min(n, lo + per)
        # capture the SLICE, not the whole table: each task closure is
        # pickled and shipped, so capturing `table` would serialize the
        # full dataset once per task
        shard = table.slice(lo, hi - lo)

        def read(shard=shard) -> Iterator[Block]:
            yield shard

        tasks.append(read)
    return tasks


def items_tasks(items: List[Any], parallelism: int) -> List[Callable]:
    tasks = []
    for group in _chunk(list(items), parallelism):
        def read(group=group) -> Iterator[Block]:
            yield block_mod.from_rows(group)

        tasks.append(read)
    return tasks


# ----------------------------- writes ---------------------------------------


def make_write_fn(path: str, fmt: str, write_kwargs: Optional[dict] = None):
    """Per-block write transform: writes one file per block under ``path``,
    emits a single-row block of written paths (reference: the Write logical
    op plans to map tasks, _internal/planner/plan_write_op.py)."""
    os.makedirs(path, exist_ok=True)
    write_kwargs = write_kwargs or {}

    def write_blocks(blocks: Iterator[Block]) -> Iterator[Block]:
        import uuid

        for b in blocks:
            name = f"{uuid.uuid4().hex[:12]}"
            if fmt == "parquet":
                import pyarrow.parquet as pq

                out = os.path.join(path, name + ".parquet")
                pq.write_table(b, out, **write_kwargs)
            elif fmt == "csv":
                import pyarrow.csv as pacsv

                out = os.path.join(path, name + ".csv")
                pacsv.write_csv(b, out)
            elif fmt == "json":
                out = os.path.join(path, name + ".jsonl")
                with open(out, "w") as fh:
                    import json as json_mod

                    for row in b.to_pylist():
                        fh.write(json_mod.dumps(row, default=str) + "\n")
            elif fmt == "tfrecords":
                out = os.path.join(path, name + ".tfrecords")
                write_tfrecord_file(b.to_pylist(), out)
            elif fmt == "avro":
                out = os.path.join(path, name + ".avro")
                write_avro_file(b.to_pylist(), out)
            else:
                raise ValueError(f"unknown write format {fmt!r}")
            yield pa.table({"path": [out], "num_rows": [b.num_rows]})

    return write_blocks


# -- tfrecords ---------------------------------------------------------------

def _iter_tfrecord_frames(path: str) -> Iterator[bytes]:
    """TFRecord wire framing: u64 length | u32 masked-crc(len) | payload |
    u32 masked-crc(payload).  CRCs are not verified (the reference's reader
    delegates verification to tf.data as well)."""
    import struct

    with open(path, "rb") as f:
        while True:
            header = f.read(12)
            if len(header) < 12:
                return
            (length,) = struct.unpack("<Q", header[:8])
            payload = f.read(length)
            if len(payload) < length:
                return  # truncated trailing record
            f.read(4)  # payload crc
            yield payload


def _example_to_row(payload: bytes) -> Dict[str, Any]:
    """Decode a tf.train.Example into a row of LISTS (unwrapping happens
    per column over the whole chunk — see _unwrap_singletons — so a
    variable-length feature can never be a scalar in one row and a list
    in another)."""
    import tensorflow as tf  # baked in; decode only

    ex = tf.train.Example.FromString(payload)
    row: Dict[str, Any] = {}
    for name, feat in ex.features.feature.items():
        kind = feat.WhichOneof("kind")
        if kind == "bytes_list":
            row[name] = list(feat.bytes_list.value)
        elif kind == "int64_list":
            row[name] = list(feat.int64_list.value)
        elif kind == "float_list":
            row[name] = list(feat.float_list.value)
        else:
            row[name] = []
    return row


def _unwrap_singletons(rows: List[Dict[str, Any]]) -> List[Dict[str, Any]]:
    """Per COLUMN: if every present value is a one-element list, unwrap to
    scalars (reference tfrecords datasource semantics)."""
    unwrap = set()
    seen: Dict[str, bool] = {}
    for r in rows:
        for k, v in r.items():
            ok = isinstance(v, list) and len(v) == 1
            seen[k] = seen.get(k, True) and ok
    unwrap = {k for k, ok in seen.items() if ok}
    if not unwrap:
        return rows
    return [{k: (v[0] if k in unwrap else v) for k, v in r.items()}
            for r in rows]


def tfrecord_tasks(paths, parallelism: int,
                   raw_bytes: bool = False) -> List[Callable]:
    """reference: _internal/datasource/tfrecords_datasource.py — rows from
    tf.train.Example records (raw_bytes=True skips proto decoding)."""
    files = expand_paths(paths)

    def read_file(f: str) -> Iterator[Block]:
        rows: List[Dict[str, Any]] = []
        for payload in _iter_tfrecord_frames(f):
            if raw_bytes:
                rows.append({"bytes": payload})
            else:
                rows.append(_example_to_row(payload))
            if len(rows) >= 4096:
                yield block_mod.from_rows(_unwrap_singletons(rows))
                rows = []
        if rows:
            yield block_mod.from_rows(_unwrap_singletons(rows))

    return _file_tasks(files, parallelism, read_file)


# -- webdataset --------------------------------------------------------------

def webdataset_tasks(paths, parallelism: int) -> List[Callable]:
    """reference: _internal/datasource/webdataset_datasource.py — tar
    shards of samples; files sharing a basename form one row keyed
    "__key__", one column per extension.  .txt/.cls decode to str; other
    payloads stay bytes."""
    import tarfile

    files = expand_paths(paths, [".tar"])

    def read_file(f: str) -> Iterator[Block]:
        samples: Dict[str, Dict[str, Any]] = {}
        order: List[str] = []
        with tarfile.open(f) as tar:
            for member in tar:
                if not member.isfile():
                    continue
                # key = full path up to the basename's first dot: samples
                # with equal basenames in different tar directories are
                # distinct (reference webdataset keying)
                dirname, base = os.path.split(member.name)
                stem, _, ext = base.partition(".")
                key = os.path.join(dirname, stem) if dirname else stem
                data = tar.extractfile(member).read()
                if ext in ("txt", "cls"):
                    data = data.decode("utf-8", "replace")
                if key not in samples:
                    samples[key] = {"__key__": key}
                    order.append(key)
                samples[key][ext] = data
        rows = [samples[k] for k in order]
        if rows:
            yield block_mod.from_rows(rows)

    return _file_tasks(files, parallelism, read_file)


# -- sql ---------------------------------------------------------------------

def sql_tasks(sql: str, connection_factory: Callable[[], Any],
              fetch_size: int = 4096) -> List[Callable]:
    """reference: _internal/datasource/sql_datasource.py — any DB-API 2.0
    connection (sqlite3, psycopg2, ...).  The query runs in one read task
    (partitioned SQL reads need a splittable predicate, which plain SQL
    doesn't give us); rows stream out in fetch_size blocks."""

    def read() -> Iterator[Block]:
        conn = connection_factory()
        try:
            cur = conn.cursor()
            cur.execute(sql)
            names = [d[0] for d in cur.description]
            while True:
                chunk = cur.fetchmany(fetch_size)
                if not chunk:
                    break
                yield block_mod.from_rows(
                    [dict(zip(names, row)) for row in chunk])
        finally:
            conn.close()

    return [read]


def clickhouse_tasks(query: str, dsn: str, parallelism: int,
                     partition_key: Optional[str] = None,
                     user: Optional[str] = None,
                     password: Optional[str] = None) -> List[Callable]:
    """Native ClickHouse reader over the server's HTTP interface.

    The reference delegates to the `clickhouse-connect` wheel
    (_internal/datasource/clickhouse_datasource.py); that wheel just
    speaks HTTP to port 8123, so the dependency is skipped: each read
    task POSTs its partition of the query with ``FORMAT JSONEachRow``
    and parses a line per row.  With ``partition_key`` (a numeric
    column) the query fans out over ``parallelism`` tasks via
    ``modulo(key, N) = i`` (the wheel's intDiv strategy); without one
    the query runs as a single task.
    """
    import urllib.parse
    import urllib.request

    base = query.strip().rstrip(";")
    # positiveModulo: ClickHouse modulo is C-style (negative for negative
    # keys, so those rows would match no shard); NULL keys match no
    # comparison at all, so shard 0 sweeps them up explicitly.
    def shard_pred(i: int) -> str:
        pred = f"positiveModulo({partition_key}, {parallelism}) = {i}"
        if i == 0:
            pred = f"({pred} OR {partition_key} IS NULL)"
        return pred

    shards = ([f"SELECT * FROM ({base}) WHERE {shard_pred(i)}"
               for i in range(parallelism)]
              if partition_key and parallelism > 1 else [base])

    def make(shard_sql: str) -> Callable:
        def read() -> Iterator[Block]:
            import json as json_mod

            url = dsn.rstrip("/") + "/?" + urllib.parse.urlencode(
                {"query": shard_sql + " FORMAT JSONEachRow"})
            req = urllib.request.Request(url, method="POST")
            if user:
                req.add_header("X-ClickHouse-User", user)
            if password:
                req.add_header("X-ClickHouse-Key", password)
            with urllib.request.urlopen(req) as resp:
                rows = [json_mod.loads(line)
                        for line in resp.read().decode().splitlines()
                        if line.strip()]
            if rows:
                yield block_mod.from_rows(rows)

        return read

    return [make(s) for s in shards]


# -- avro --------------------------------------------------------------------

class _AvroDecoder:
    """Minimal Avro binary decoder (spec: container file + core types).
    reference: _internal/datasource/avro_datasource.py delegates to the
    `fastavro` wheel; this image has none, so the codec is implemented
    directly — null/deflate codecs, all core schema types, named-type
    references.  Logical types decode as their base type."""

    def __init__(self, buf: bytes):
        self.buf = buf
        self.pos = 0

    def read(self, n: int) -> bytes:
        b = self.buf[self.pos:self.pos + n]
        if len(b) < n:
            raise EOFError("truncated avro data")
        self.pos += n
        return b

    def long(self) -> int:
        shift = 0
        acc = 0
        while True:
            if self.pos >= len(self.buf):
                raise EOFError("truncated avro data")
            b = self.buf[self.pos]
            self.pos += 1
            acc |= (b & 0x7F) << shift
            if not b & 0x80:
                break
            shift += 7
        return (acc >> 1) ^ -(acc & 1)  # zigzag

    def decode(self, schema, names: Dict[str, Any]):
        import struct as _struct

        if isinstance(schema, list):  # union
            return self.decode(schema[self.long()], names)
        if isinstance(schema, dict):
            t = schema["type"]
            if t == "record":
                return {f["name"]: self.decode(f["type"], names)
                        for f in schema["fields"]}
            if t == "enum":
                return schema["symbols"][self.long()]
            if t == "array":
                out = []
                while True:
                    n = self.long()
                    if n == 0:
                        break
                    if n < 0:
                        n = -n
                        self.long()  # block byte size, unused
                    out.extend(self.decode(schema["items"], names)
                               for _ in range(n))
                return out
            if t == "map":
                out = {}
                while True:
                    n = self.long()
                    if n == 0:
                        break
                    if n < 0:
                        n = -n
                        self.long()
                    for _ in range(n):
                        k = self.read(self.long()).decode()
                        out[k] = self.decode(schema["values"], names)
                return out
            if t == "fixed":
                return self.read(schema["size"])
            return self.decode(t, names)  # {"type": "string", ...} wrapper
        if schema == "null":
            return None
        if schema == "boolean":
            return self.read(1) != b"\x00"
        if schema in ("int", "long"):
            return self.long()
        if schema == "float":
            return _struct.unpack("<f", self.read(4))[0]
        if schema == "double":
            return _struct.unpack("<d", self.read(8))[0]
        if schema == "bytes":
            return self.read(self.long())
        if schema == "string":
            return self.read(self.long()).decode()
        if schema in names:  # named-type reference
            return self.decode(names[schema], names)
        raise ValueError(f"unsupported avro schema {schema!r}")


def _collect_named(schema, names: Dict[str, Any], namespace: str = ""):
    """Register record/enum/fixed types under BOTH short name and fullname
    (avro spec: a name in a namespaced schema may be referenced either
    way; nested names inherit the enclosing namespace)."""
    if isinstance(schema, dict):
        ns = schema.get("namespace", namespace)
        if schema.get("type") in ("record", "enum", "fixed"):
            name = schema["name"]
            names[name] = schema
            if "." in name:  # name given as fullname
                ns, _, short = name.rpartition(".")
                names[short] = schema
            elif ns:
                names[f"{ns}.{name}"] = schema
        for f in schema.get("fields", []):
            _collect_named(f["type"], names, ns)
        for k in ("items", "values"):
            if k in schema:
                _collect_named(schema[k], names, ns)
    elif isinstance(schema, list):
        for s in schema:
            _collect_named(s, names, namespace)


def avro_tasks(paths, parallelism: int) -> List[Callable]:
    """Avro Object Container Files → rows (one per record)."""
    files = expand_paths(paths, [".avro"])

    def read_file(f: str) -> Iterator[Block]:
        for rows in _avro_file_blocks(f):
            if rows:
                yield block_mod.from_rows(rows)

    return _file_tasks(files, parallelism, read_file)


class _AvroEncoder:
    """Minimal Avro binary encoder — the write half of ``_AvroDecoder``
    (null codec, core types).  Powers ``write_avro`` and the hand-built
    manifest files in the native Iceberg reader's tests; the reference
    delegates both halves to the `fastavro` wheel, absent here."""

    def __init__(self):
        self.out = bytearray()

    def long(self, v: int):
        v = (v << 1) ^ (v >> 63)  # zigzag (Python >> floors, so -1 for <0)
        while True:
            b = v & 0x7F
            v >>= 7
            if v:
                self.out.append(b | 0x80)
            else:
                self.out.append(b)
                break

    def encode(self, value, schema, names: Dict[str, Any]):
        import struct as _struct

        if isinstance(schema, list):  # union
            # exact-type branch first (an int must bind to a long branch
            # before a double one, or precision silently drops), then the
            # lenient pass (int widening into a double-only union)
            for lenient in (False, True):
                for i, branch in enumerate(schema):
                    if _avro_union_match(value, branch, names, lenient):
                        self.long(i)
                        return self.encode(value, branch, names)
            raise ValueError(f"no union branch for {type(value)} in {schema}")
        if isinstance(schema, dict):
            t = schema["type"]
            if t == "record":
                for f in schema["fields"]:
                    self.encode(value.get(f["name"]), f["type"], names)
                return
            if t == "enum":
                self.long(schema["symbols"].index(value))
                return
            if t == "array":
                if value:
                    self.long(len(value))
                    for item in value:
                        self.encode(item, schema["items"], names)
                self.long(0)
                return
            if t == "map":
                if value:
                    self.long(len(value))
                    for k, v in value.items():
                        kb = k.encode()
                        self.long(len(kb))
                        self.out += kb
                        self.encode(v, schema["values"], names)
                self.long(0)
                return
            if t == "fixed":
                self.out += value
                return
            return self.encode(value, t, names)
        if schema == "null":
            return
        if schema == "boolean":
            self.out.append(1 if value else 0)
            return
        if schema in ("int", "long"):
            self.long(int(value))
            return
        if schema == "float":
            self.out += _struct.pack("<f", value)
            return
        if schema == "double":
            self.out += _struct.pack("<d", float(value))
            return
        if schema == "bytes":
            self.long(len(value))
            self.out += value
            return
        if schema == "string":
            b = value.encode() if isinstance(value, str) else bytes(value)
            self.long(len(b))
            self.out += b
            return
        if schema in names:
            return self.encode(value, names[schema], names)
        raise ValueError(f"unsupported avro schema {schema!r}")


def _avro_union_match(value, branch, names: Dict[str, Any],
                      lenient: bool = False) -> bool:
    b = branch["type"] if isinstance(branch, dict) else branch
    if b in names and not isinstance(branch, dict):
        branch = names[b]
        b = branch["type"]
    if b == "null":
        return value is None
    if value is None:
        return False
    if b == "boolean":
        return isinstance(value, bool)
    if b in ("int", "long"):
        return isinstance(value, int) and not isinstance(value, bool)
    if b == "double":
        # lenient: an int may widen into a double branch (a nullable
        # column inferred as ["null","double"] still holds ints) — but
        # only after the exact pass proved there is no integer branch
        if lenient:
            return (isinstance(value, (int, float))
                    and not isinstance(value, bool))
        return isinstance(value, float)
    if b == "float":
        # never bind ints to float32 — silent precision loss
        return isinstance(value, float)
    if b == "string":
        return isinstance(value, str)
    if b in ("bytes", "fixed"):
        return isinstance(value, (bytes, bytearray))
    if b == "record":
        return isinstance(value, dict)
    if b == "array":
        return isinstance(value, (list, tuple))
    if b == "map":
        return isinstance(value, dict)
    if b == "enum":
        return isinstance(value, str)
    return False


def _infer_avro_schema(rows: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Schema inference for write_avro: per-field type widened across ALL
    values (long + double -> double), never just the first — typing from
    one sample would silently truncate 2.5 to 2 under a 'long' schema.
    Fields that are ever None become nullable unions; non-promotable
    mixes raise instead of coercing."""

    def of(v):
        if isinstance(v, bool):
            return "boolean"
        if isinstance(v, int):
            return "long"
        if isinstance(v, float):
            return "double"
        if isinstance(v, (bytes, bytearray)):
            return "bytes"
        if isinstance(v, (list, tuple)):
            item = _widen((of(x) for x in v), "array item") if len(v) \
                else "string"
            return {"type": "array", "items": item}
        if isinstance(v, dict):
            vals = list(v.values())
            values = _widen((of(x) for x in vals), "map value") if vals \
                else "string"
            return {"type": "map", "values": values}
        return "string"

    def _widen(types, what: str):
        out = None
        for t in types:
            if out is None or out == t:
                out = t
            elif out in ("long", "double") and t in ("long", "double"):
                out = "double"
            else:
                raise ValueError(
                    f"write_avro: mixed {what} types {out!r} vs {t!r} "
                    "cannot be widened; cast the column first")
        return out if out is not None else "string"

    fields = []
    keys: List[str] = []
    for r in rows:
        for k in r:
            if k not in keys:
                keys.append(k)
    for k in keys:
        vals = [v for r in rows if (v := r.get(k)) is not None]
        t = _widen((of(v) for v in vals), f"values for field {k!r}") \
            if vals else "string"
        if len(vals) < len(rows):
            t = ["null", t]
        fields.append({"name": k, "type": t})
    return {"type": "record", "name": "row", "fields": fields}


def write_avro_file(rows: List[Dict[str, Any]], out: str,
                    schema: Optional[Dict[str, Any]] = None) -> None:
    """Write an Avro Object Container File (null codec)."""
    import json as json_mod

    schema = schema or _infer_avro_schema(rows)
    names: Dict[str, Any] = {}
    _collect_named(schema, names)
    enc = _AvroEncoder()
    enc.out += b"Obj\x01"
    meta = {"avro.schema": json_mod.dumps(schema).encode(),
            "avro.codec": b"null"}
    enc.long(len(meta))
    for k, v in meta.items():
        kb = k.encode()
        enc.long(len(kb))
        enc.out += kb
        enc.long(len(v))
        enc.out += v
    enc.long(0)
    sync = os.urandom(16)
    enc.out += sync
    if rows:
        block = _AvroEncoder()
        for r in rows:
            block.encode(r, schema, names)
        enc.long(len(rows))
        enc.long(len(block.out))
        enc.out += block.out
        enc.out += sync
    with open(out, "wb") as fh:
        fh.write(bytes(enc.out))


def read_avro_rows(path: str) -> List[Dict[str, Any]]:
    """All rows of one avro container file (helper for the Iceberg
    manifest chain, which needs rows eagerly, not as read tasks)."""
    rows: List[Dict[str, Any]] = []
    for block in _avro_file_blocks(path):
        rows.extend(block)
    return rows


def _avro_file_blocks(f: str) -> Iterator[List[Dict[str, Any]]]:
    import json as json_mod
    import zlib

    with open(f, "rb") as fh:
        data = fh.read()
    if data[:4] != b"Obj\x01":
        raise ValueError(f"{f}: not an avro container file")
    d = _AvroDecoder(data)
    d.pos = 4
    meta: Dict[str, bytes] = {}
    while True:
        n = d.long()
        if n == 0:
            break
        if n < 0:
            n = -n
            d.long()
        for _ in range(n):
            k = d.read(d.long()).decode()
            meta[k] = d.read(d.long())
    schema = json_mod.loads(meta["avro.schema"])
    codec = meta.get("avro.codec", b"null").decode()
    names: Dict[str, Any] = {}
    _collect_named(schema, names)
    sync = d.read(16)
    while d.pos < len(d.buf):
        count = d.long()
        size = d.long()
        payload = d.read(size)
        if codec == "deflate":
            payload = zlib.decompress(payload, -15)
        elif codec != "null":
            raise ValueError(f"unsupported avro codec {codec!r}")
        bd = _AvroDecoder(payload)
        rows = [bd.decode(schema, names) for _ in range(count)]
        if rows and not isinstance(rows[0], dict):
            rows = [{"value": r} for r in rows]  # non-record schema
        yield rows
        if d.read(16) != sync:
            raise ValueError(f"{f}: sync marker mismatch")


# -- torch / tf ingestion ----------------------------------------------------

def torch_tasks(torch_dataset, parallelism: int) -> List[Callable]:
    """reference: read_api.py from_torch (:3334) — map-style datasets are
    index-sharded across tasks; iterable datasets read in one task."""
    if hasattr(torch_dataset, "__len__") and hasattr(torch_dataset,
                                                     "__getitem__"):
        indices = list(range(len(torch_dataset)))

        def make(idx_group):
            def read() -> Iterator[Block]:
                rows = [{"item": torch_dataset[i]} for i in idx_group]
                if rows:
                    yield block_mod.from_rows(rows)
            return read

        return [make(g) for g in _chunk(indices, parallelism)]

    def read_iterable() -> Iterator[Block]:
        rows = []
        for item in torch_dataset:
            rows.append({"item": item})
            if len(rows) >= 4096:
                yield block_mod.from_rows(rows)
                rows = []
        if rows:
            yield block_mod.from_rows(rows)

    return [read_iterable]


# -- tfrecord writing --------------------------------------------------------

def _row_to_example_bytes(row: Dict[str, Any]) -> bytes:
    """Encode one row as a tf.train.Example (tensorflow is baked in)."""
    import numpy as np
    import tensorflow as tf

    feats = {}
    for k, v in row.items():
        vals = v if isinstance(v, (list, np.ndarray)) else [v]
        if any(x is None for x in vals):
            raise ValueError(
                f"write_tfrecords: column {k!r} contains a null; "
                f"tf.train.Example has no null representation — drop or "
                f"impute the column first (e.g. SimpleImputer)")
        first = vals[0] if len(vals) else 0
        if isinstance(first, (bytes, str)):
            bs = [x.encode() if isinstance(x, str) else bytes(x)
                  for x in vals]
            feats[k] = tf.train.Feature(
                bytes_list=tf.train.BytesList(value=bs))
        elif isinstance(first, (int, np.integer)):
            feats[k] = tf.train.Feature(
                int64_list=tf.train.Int64List(value=[int(x) for x in vals]))
        else:
            feats[k] = tf.train.Feature(
                float_list=tf.train.FloatList(
                    value=[float(x) for x in vals]))
    ex = tf.train.Example(features=tf.train.Features(feature=feats))
    return ex.SerializeToString()


def write_tfrecord_file(rows: List[Dict[str, Any]], out: str) -> None:
    # tf.io.TFRecordWriter does the framing (length + masked CRC32C) with
    # native checksums — _row_to_example_bytes already requires tensorflow
    # for the proto encode, so there is no extra dependency.
    import tensorflow as tf

    with tf.io.TFRecordWriter(out) as w:
        for row in rows:
            w.write(_row_to_example_bytes(row))
