"""DataContext: per-session execution configuration for ray_tpu.data.

Counterpart of the reference's DataContext
(/root/reference/python/ray/data/context.py): a process-wide singleton of
execution knobs consulted by the planner and the streaming executor.
"""

from __future__ import annotations

import os
import threading
from dataclasses import dataclass, field
from typing import Optional


@dataclass
class DataContext:
    # Target size for output blocks produced by map tasks; oversized outputs
    # are sliced (reference: context.py target_max_block_size = 128 MiB).
    target_max_block_size: int = 128 * 1024 * 1024
    # Default number of output blocks for reads when not specified.
    default_parallelism: int = field(
        default_factory=lambda: max(4, (os.cpu_count() or 4)))
    # Bound on concurrently running tasks per map operator — the streaming
    # executor's backpressure window (reference: backpressure_policy/
    # concurrency_cap_backpressure_policy.py).
    max_tasks_in_flight_per_op: int = field(
        default_factory=lambda: max(4, (os.cpu_count() or 4)))
    # In-flight method calls allowed per actor in actor-pool map ops
    # (reference: _max_tasks_in_flight_per_actor, actor_pool_map_operator.py).
    max_tasks_in_flight_per_actor: int = 2
    # Default rows per batch for map_batches / iter_batches.
    target_batch_size: int = 1024
    # Seconds to wait for an actor pool to become ready.
    wait_for_min_actors_s: int = 60
    # Retries for data tasks (transient worker crashes).
    task_max_retries: int = 2
    # Pluggable launch-gating policies consulted by every task-launching
    # operator (reference: _internal/execution/backpressure_policy/).
    # None = data.backpressure.default_policies() (concurrency cap +
    # output-bytes bound); install custom BackpressurePolicy instances to
    # change admission behavior.
    backpressure_policies: Optional[list] = None

    _instance = None
    _lock = threading.Lock()

    @staticmethod
    def get_current() -> "DataContext":
        # Process-wide singleton: executor generators may be pulled from
        # prefetch threads, so knobs set on the main thread must be visible
        # everywhere (reference: context.py get_current).
        if DataContext._instance is None:
            with DataContext._lock:
                if DataContext._instance is None:
                    DataContext._instance = DataContext()
        return DataContext._instance
