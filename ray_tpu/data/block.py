"""Blocks: the unit of data movement — a pyarrow.Table.

Counterpart of the reference's block layer
(/root/reference/python/ray/data/block.py, _internal/arrow_block.py,
_internal/pandas_block.py): every Dataset is a stream of blocks; here a block
is always a pyarrow Table (columnar, zero-copy slicing, cheap concat), and
batch formats ("numpy" | "pandas" | "pyarrow") are views converted at the
edges.  TPU relevance: numpy batches feed ``jax.device_put`` without copies
for fixed-width types.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, Iterable, Iterator, List, Optional

import json as json_mod

import numpy as np
import pyarrow as pa

Block = pa.Table

# Column name used when data has no schema (e.g. range of ints, list of
# scalars) — reference uses "item" for the same purpose
# (python/ray/data/_internal/arrow_block.py TENSOR_COLUMN/item semantics).
VALUE_COL = "item"


@dataclass
class BlockMetadata:
    """Sidecar facts about a block, computed where the block was produced so
    the driver never has to fetch the block to plan (reference:
    block.py BlockMetadata)."""

    num_rows: int
    size_bytes: int
    schema_str: str = ""

    @staticmethod
    def of(block: Block) -> "BlockMetadata":
        return BlockMetadata(
            num_rows=block.num_rows,
            size_bytes=block.nbytes,
            schema_str=str(block.schema),
        )


def _normalize_value(v: Any) -> Any:
    return v


def from_rows(rows: Iterable[Dict[str, Any]]) -> Block:
    rows = list(rows)
    if not rows:
        return pa.table({})
    if not isinstance(rows[0], dict):
        rows = [{VALUE_COL: r} for r in rows]
    # Union of ALL rows' keys (insertion-ordered): sparse rows (tfrecord
    # features, webdataset extensions) must not silently drop columns that
    # the first row happens to lack; absent values become nulls.
    cols: Dict[str, List[Any]] = {}
    for r in rows:
        for k in r:
            if k not in cols:
                cols[k] = []
    for r in rows:
        for k in cols:
            cols[k].append(r.get(k))
    return from_batch(cols)


def from_batch(batch: Any) -> Block:
    """Build a block from any supported batch format."""
    if isinstance(batch, pa.Table):
        return batch
    if isinstance(batch, dict):
        arrays = {}
        fields = []
        for k, v in batch.items():
            if isinstance(v, np.ndarray) and v.ndim > 1:
                # Multi-dim arrays (images, tokens) → fixed-size-list column
                # with the trailing shape recorded in field metadata so
                # to_batch can reconstruct the exact ndarray.
                import json as json_mod

                n = v.shape[0]
                inner = int(np.prod(v.shape[1:]))
                flat = pa.array(np.ascontiguousarray(v).reshape(-1))
                arr = pa.FixedSizeListArray.from_arrays(flat, inner)
                arrays[k] = arr
                fields.append(pa.field(
                    k, arr.type,
                    metadata={b"np_shape": json_mod.dumps(
                        list(v.shape[1:])).encode()}))
            else:
                arr = pa.array(v)
                arrays[k] = arr
                fields.append(pa.field(k, arr.type))
        return pa.Table.from_arrays(list(arrays.values()),
                                    schema=pa.schema(fields))
    try:
        import pandas as pd

        if isinstance(batch, pd.DataFrame):
            return pa.Table.from_pandas(batch, preserve_index=False)
    except ImportError:
        pass
    if isinstance(batch, (list, np.ndarray)):
        return from_rows(list(batch))
    raise TypeError(f"unsupported batch type: {type(batch)}")


def to_batch(block: Block, batch_format: str = "numpy") -> Any:
    if batch_format in ("pyarrow", "arrow"):
        return block
    if batch_format == "pandas":
        return block.to_pandas()
    if batch_format in ("numpy", "default", None):
        import json as json_mod

        out: Dict[str, np.ndarray] = {}
        for i, name in enumerate(block.column_names):
            col = block.column(name)
            field = block.schema.field(i)
            meta = field.metadata or {}
            if b"np_shape" in meta and pa.types.is_fixed_size_list(
                    field.type):
                shape = json_mod.loads(meta[b"np_shape"].decode())
                flat = col.combine_chunks().flatten().to_numpy(
                    zero_copy_only=False)
                out[name] = flat.reshape([block.num_rows] + shape)
                continue
            try:
                out[name] = col.to_numpy(zero_copy_only=False)
            except (pa.ArrowInvalid, pa.ArrowNotImplementedError):
                out[name] = np.asarray(col.to_pylist(), dtype=object)
        return out
    raise ValueError(f"unknown batch_format: {batch_format!r}")


def rows_of(block: Block) -> Iterator[Dict[str, Any]]:
    # Fixed-size-list columns carrying an np_shape annotation (multi-dim
    # arrays, e.g. images) reshape back per row instead of leaking flat
    # python lists.
    shaped = {}
    for field in block.schema:
        meta = field.metadata or {}
        if b"np_shape" in meta:
            shaped[field.name] = json_mod.loads(meta[b"np_shape"].decode())
    for r in block.to_pylist():
        for name, shape in shaped.items():
            if r.get(name) is not None:
                r[name] = np.asarray(r[name]).reshape(shape)
        yield r


def concat(blocks: List[Block]) -> Block:
    blocks = [b for b in blocks if b.num_rows > 0] or blocks[:1]
    if not blocks:
        return pa.table({})
    if len(blocks) == 1:
        return blocks[0]
    return pa.concat_tables(blocks, promote_options="default")


def slice_block(block: Block, start: int, stop: int) -> Block:
    return block.slice(start, stop - start)


def split_by_bytes(block: Block, target_bytes: int) -> List[Block]:
    """Slice an oversized output block to ~target_bytes chunks (reference:
    map tasks yield blocks bounded by target_max_block_size)."""
    if block.num_rows == 0 or block.nbytes <= target_bytes:
        return [block]
    per_row = max(1, block.nbytes // max(1, block.num_rows))
    rows_per = max(1, target_bytes // per_row)
    return [
        block.slice(i, min(rows_per, block.num_rows - i))
        for i in range(0, block.num_rows, rows_per)
    ]


def empty_like(block: Optional[Block]) -> Block:
    if block is None:
        return pa.table({})
    return block.schema.empty_table()
