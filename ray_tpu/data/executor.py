"""Streaming executor: lowers a logical plan to physical ops and runs it.

Counterpart of the reference's streaming execution stack
(/root/reference/python/ray/data/_internal/execution/streaming_executor.py:52,
streaming_executor_state.py:631 select_operator_to_run,
operators/map_operator.py, task_pool_map_operator.py,
actor_pool_map_operator.py): here each physical operator is a *generator
transformer* over streams of (block_ref, metadata) bundles.  Pull-based
generators give backpressure for free — an operator launches at most
``window`` concurrent tasks and only launches more when a downstream consumer
pulls — which is the same steady-state behavior as the reference's push-based
scheduling loop + concurrency-cap backpressure, with far less machinery.

Map fusion (reference _internal/logical/rules/operator_fusion.py) happens in
``plan_physical``: adjacent task-compute OneToOne ops compose into a single
task; a task-compute chain feeding an actor-compute op is folded into the
actor's transform.
"""

from __future__ import annotations

import itertools
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple

import cloudpickle

import ray_tpu
from ray_tpu.core.object_ref import ObjectRef
from ray_tpu.data import block as block_mod
from ray_tpu.data.block import Block, BlockMetadata
from ray_tpu.data.context import DataContext
from ray_tpu.data import logical as L

RefBundle = Tuple[ObjectRef, BlockMetadata]

# unique-per-execution operator tokens (see _window_run)
_op_token_counter = itertools.count()

_exec_metrics_lock = threading.Lock()
_exec_metrics_cache: Optional[dict] = None


def _exec_metrics() -> dict:
    """Per-op executor counters on the /metrics plane (util.metrics):
    OpStats/ExecStats are per-execution and invisible to Prometheus, so
    operators also feed these process-wide families, tagged by op name."""
    global _exec_metrics_cache
    with _exec_metrics_lock:
        if _exec_metrics_cache is None:
            from ray_tpu.util import metrics as M

            _exec_metrics_cache = {
                "rows": M.Counter(
                    "data_op_rows_total",
                    "Rows produced per physical data operator", ("op",)),
                "bytes": M.Counter(
                    "data_op_output_bytes_total",
                    "Output bytes produced per physical data operator",
                    ("op",)),
                "tasks": M.Counter(
                    "data_op_tasks_total",
                    "Task launches per physical data operator", ("op",)),
                "stalls": M.Counter(
                    "data_op_backpressure_stalls_total",
                    "Launch attempts denied by a backpressure policy",
                    ("op",)),
            }
    return _exec_metrics_cache


@dataclass
class OpStats:
    name: str
    tasks: int = 0
    rows: int = 0
    wall_s: float = 0.0


@dataclass
class ExecStats:
    ops: List[OpStats] = field(default_factory=list)

    def summary(self) -> str:
        lines = []
        for s in self.ops:
            lines.append(
                f"{s.name}: {s.tasks} tasks, {s.rows} rows, "
                f"{s.wall_s:.2f}s")
        return "\n".join(lines)


def _put_blocks(blocks: List[Block], target_bytes: int) -> List[RefBundle]:
    out = []
    for b in blocks:
        for piece in block_mod.split_by_bytes(b, target_bytes):
            out.append((ray_tpu.put(piece), BlockMetadata.of(piece)))
    return out


def make_map_task(chain_blob: bytes, target_bytes: int):
    """Build the remote task body for a fused task-compute map stage.  The
    chain is shipped as a cloudpickle blob so one generic task body serves
    every stage (reference: map_operator.py _map_task)."""

    def _map_task(*blocks):
        chain = cloudpickle.loads(chain_blob)
        out = list(chain(iter(blocks)))
        return _put_blocks(out, target_bytes)

    return _map_task


class _MapWorker:
    """Actor-pool UDF host: constructs the user's class once, reuses it for
    every block (reference: actor_pool_map_operator.py _MapWorker)."""

    def __init__(self, udf_blob: bytes, make_fn_blob: bytes,
                 target_bytes: int):
        udf_cls, args, kwargs = cloudpickle.loads(udf_blob)
        self._udf = udf_cls(*args, **kwargs)
        self._chain = cloudpickle.loads(make_fn_blob)(self._udf)
        self._target_bytes = target_bytes

    def ready(self) -> str:
        return "ok"

    def map(self, *blocks):
        out = list(self._chain(iter(blocks)))
        return _put_blocks(out, self._target_bytes)


class PhysicalOp:
    name = "op"

    def execute(self, inp: Iterator[List[RefBundle]],
                stats: OpStats) -> Iterator[List[RefBundle]]:
        raise NotImplementedError


class InputOp(PhysicalOp):
    def __init__(self, bundles: List[RefBundle]):
        self.name = "Input"
        self._bundles = bundles

    def execute(self, inp, stats):
        for b in self._bundles:
            stats.rows += b[1].num_rows
            yield [b]


def _window_run(submit: Callable[[], Optional[ObjectRef]],
                window: int, stats: OpStats,
                policies: Optional[list] = None,
                op_name: str = "") -> Iterator[List[RefBundle]]:
    """Core streaming loop for task-launching ops: keep tasks in flight up
    to the concurrency window AND every backpressure policy's consent
    (data/backpressure.py); yield results in FIFO order."""
    from ray_tpu.data.backpressure import OpSnapshot, default_policies

    if policies is None:
        policies = default_policies()
    # identity token: concurrent ops may share a display name, and
    # identity-keyed policies (ResourceManagerPolicy) must not alias them
    op_token = f"{op_name}#{next(_op_token_counter)}"
    metrics = _exec_metrics()
    op_tag = {"op": op_name or "op"}
    pending: deque = deque()
    exhausted = False
    bytes_per_task = 0.0  # rolling estimate from completed tasks
    completed = 0
    launched = 0
    released = 0
    try:
        while True:
            while not exhausted and len(pending) < window:
                snap = OpSnapshot(
                    op_name=op_name, in_flight=len(pending), window=window,
                    bytes_per_task=bytes_per_task,
                    outstanding_bytes=bytes_per_task * len(pending),
                    op_token=op_token)
                if not all(p.can_launch(snap) for p in policies):
                    metrics["stalls"].inc(1, op_tag)
                    break
                ref = submit()
                if ref is None:
                    exhausted = True
                    break
                pending.append(ref)
                stats.tasks += 1
                metrics["tasks"].inc(1, op_tag)
                launched += 1
                for p in policies:
                    p.on_launch(snap)
            if not pending:
                if exhausted:
                    return
                # a policy denied the launch with NOTHING in flight: input
                # remains, so returning would silently truncate the dataset
                # — wait for whatever external condition the policy watches
                time.sleep(0.02)
                continue
            # Yield in submission (FIFO) order so dataset order is
            # deterministic (reference: streaming executor preserves block
            # order).  Later tasks in the window keep running while we
            # wait on the head.
            head = pending.popleft()
            result = ray_tpu.get(head)
            out_bytes = 0
            out_rows = 0
            for _, meta in result:
                stats.rows += meta.num_rows
                out_rows += meta.num_rows
                out_bytes += meta.size_bytes or 0
            metrics["rows"].inc(out_rows, op_tag)
            metrics["bytes"].inc(out_bytes, op_tag)
            completed += 1
            # exponential moving average keeps the estimate fresh across
            # size regimes without storing per-task history
            alpha = 1.0 if completed == 1 else 0.25
            bytes_per_task += alpha * (out_bytes - bytes_per_task)
            released += 1
            for p in policies:
                p.on_complete(op_token, out_bytes)
            yield result
    finally:
        # Abandoned or failed stream (take()/limit(), a task exception —
        # including the popped head ray_tpu.get raised on): release the
        # accounting for every launch not yet released, or a
        # process-shared policy leaks budget forever and eventually
        # wedges every later execution.
        for _ in range(launched - released):
            for p in policies:
                try:
                    p.on_complete(op_token, 0)
                except Exception:
                    pass



class TaskMapOp(PhysicalOp):
    def __init__(self, name: str, chain: Callable, resources: dict,
                 ctx: DataContext, concurrency: Optional[int] = None):
        self.name = name
        self._chain_blob = cloudpickle.dumps(chain)
        self._resources = resources
        self._ctx = ctx
        self._window = concurrency or ctx.max_tasks_in_flight_per_op

    def execute(self, inp, stats):
        task = ray_tpu.remote(
            make_map_task(self._chain_blob, self._ctx.target_max_block_size)
        ).options(name=self.name, max_retries=self._ctx.task_max_retries,
                  **self._resources)
        it = iter(inp)

        def submit():
            bundle = next(it, None)
            if bundle is None:
                return None
            return task.remote(*[ref for ref, _ in bundle])

        t0 = time.perf_counter()
        yield from _window_run(submit, self._window, stats,
                               policies=self._ctx.backpressure_policies,
                               op_name=self.name)
        stats.wall_s += time.perf_counter() - t0


class ReadOp(PhysicalOp):
    """Reads are maps over zero-input read tasks (reference:
    planner/plan_read_op.py)."""

    def __init__(self, read_tasks: List[Callable], ctx: DataContext):
        self.name = "Read"
        self._read_tasks = read_tasks
        self._ctx = ctx

    def execute(self, inp, stats):
        target = self._ctx.target_max_block_size

        def run_read(task_blob):
            fn = cloudpickle.loads(task_blob)
            return _put_blocks(list(fn()), target)

        task = ray_tpu.remote(run_read).options(
            name="Read", max_retries=self._ctx.task_max_retries)
        queue = deque(cloudpickle.dumps(t) for t in self._read_tasks)

        def submit():
            if not queue:
                return None
            return task.remote(queue.popleft())

        t0 = time.perf_counter()
        yield from _window_run(
            submit, self._ctx.max_tasks_in_flight_per_op, stats,
            policies=self._ctx.backpressure_policies, op_name=self.name)
        stats.wall_s += time.perf_counter() - t0


class ActorMapOp(PhysicalOp):
    """Actor-pool map with per-op autoscaling (reference:
    actor_pool_map_operator.py + autoscaler/default_autoscaler.py).

    ``concurrency`` is a fixed pool size (int) or an elastic (min, max)
    range: the pool grows one actor at a time whenever every actor is at
    its in-flight cap and input is still pending — the same queue-pressure
    signal the reference's per-op autoscaler uses.
    """

    def __init__(self, name: str, udf_cls, udf_args, udf_kwargs,
                 make_fn: Callable, resources: dict, ctx: DataContext,
                 concurrency):
        self.name = name
        self._udf_blob = cloudpickle.dumps((udf_cls, udf_args, udf_kwargs))
        self._make_fn_blob = cloudpickle.dumps(make_fn)
        self._resources = resources
        self._ctx = ctx
        if isinstance(concurrency, (tuple, list)):
            self._min_pool, self._max_pool = int(concurrency[0]), int(
                concurrency[1])
            if not (1 <= self._min_pool <= self._max_pool):
                raise ValueError(
                    f"concurrency range must satisfy 1 <= min <= max, "
                    f"got {concurrency}")
        else:
            self._min_pool = self._max_pool = concurrency or 2

    def execute(self, inp, stats):
        ctx = self._ctx
        actor_cls = ray_tpu.remote(_MapWorker).options(**self._resources)

        def spawn():
            return actor_cls.remote(self._udf_blob, self._make_fn_blob,
                                    ctx.target_max_block_size)

        actors = [spawn() for _ in range(self._min_pool)]
        ray_tpu.get([a.ready.remote() for a in actors],
                    timeout=ctx.wait_for_min_actors_s)
        in_flight: deque = deque()  # (ref, actor_idx), FIFO for ordering
        load: Dict[int, int] = {i: 0 for i in range(len(actors))}
        it = iter(inp)
        cap = ctx.max_tasks_in_flight_per_actor
        metrics = _exec_metrics()
        op_tag = {"op": self.name}
        t0 = time.perf_counter()
        try:
            done_in = False
            while True:
                while (not done_in
                       and len(in_flight) < len(actors) * cap):
                    bundle = next(it, None)
                    if bundle is None:
                        done_in = True
                        break
                    # least-loaded actor (reference: actor pool picks the
                    # actor with fewest in-flight tasks)
                    i = min(load, key=load.get)
                    ref = actors[i].map.remote(
                        *[r for r, _ in bundle])
                    in_flight.append((ref, i))
                    load[i] += 1
                    stats.tasks += 1
                    metrics["tasks"].inc(1, op_tag)
                if (not done_in and len(actors) < self._max_pool
                        and len(in_flight) >= len(actors) * cap):
                    # Scale only on a REAL utilization signal: the queue is
                    # full, input is pending, AND the oldest task is still
                    # running after a short grace — a pool keeping pace
                    # never grows (the fill loop alone always leaves the
                    # queue full, so queue depth by itself proves nothing).
                    ready, _ = ray_tpu.wait(
                        [in_flight[0][0]], num_returns=1, timeout=0.1)
                    if not ready:
                        actors.append(spawn())
                        load[len(actors) - 1] = 0
                        stats.actors_scaled_up = getattr(
                            stats, "actors_scaled_up", 0) + 1
                        continue
                if not in_flight:
                    return
                head, i = in_flight.popleft()
                load[i] -= 1
                result = ray_tpu.get(head)
                out_rows = out_bytes = 0
                for _, meta in result:
                    stats.rows += meta.num_rows
                    out_rows += meta.num_rows
                    out_bytes += meta.size_bytes or 0
                metrics["rows"].inc(out_rows, op_tag)
                metrics["bytes"].inc(out_bytes, op_tag)
                yield result
        finally:
            stats.wall_s += time.perf_counter() - t0
            for a in actors:
                try:
                    ray_tpu.kill(a)
                except Exception:
                    pass


class LimitOp(PhysicalOp):
    def __init__(self, limit: int):
        self.name = f"Limit[{limit}]"
        self._limit = limit

    def execute(self, inp, stats):
        remaining = self._limit

        def truncate(b, n):
            t = b.slice(0, n)
            return [(ray_tpu.put(t), BlockMetadata.of(t))]

        trunc = ray_tpu.remote(truncate)
        for bundle in inp:
            out = []
            for ref, meta in bundle:
                if remaining <= 0:
                    break
                if meta.num_rows <= remaining:
                    out.append((ref, meta))
                    remaining -= meta.num_rows
                else:
                    out.extend(ray_tpu.get(trunc.remote(ref, remaining)))
                    remaining = 0
            if out:
                stats.rows += sum(m.num_rows for _, m in out)
                yield out
            if remaining <= 0:
                return


class AllToAllOp(PhysicalOp):
    """Barrier: materialize upstream, hand the full bundle list to bulk_fn
    (reference: _internal/planner/exchange/* shuffle task schedulers)."""

    def __init__(self, name: str, bulk_fn: Callable, ctx: DataContext):
        self.name = name
        self._bulk_fn = bulk_fn
        self._ctx = ctx

    def execute(self, inp, stats):
        bundles: List[RefBundle] = []
        for b in inp:
            bundles.extend(b)
        t0 = time.perf_counter()
        out = self._bulk_fn(bundles, self._ctx)
        stats.wall_s += time.perf_counter() - t0
        stats.tasks += len(out)
        for pair in out:
            stats.rows += pair[1].num_rows
            yield [pair]


def _compose(f, g):
    def chained(blocks):
        return g(f(blocks))

    return chained


def plan_physical(plan: "L.LogicalPlan", ctx: DataContext
                  ) -> List[PhysicalOp]:
    """Lower logical → physical with map fusion."""
    ops: List[PhysicalOp] = []
    pending_chain: Optional[Callable] = None
    pending_names: List[str] = []
    pending_res: dict = {}

    def flush_chain():
        nonlocal pending_chain, pending_names, pending_res
        if pending_chain is not None:
            ops.append(TaskMapOp("+".join(pending_names), pending_chain,
                                 pending_res, ctx))
            pending_chain, pending_names, pending_res = None, [], {}

    for op in plan.ops:
        if isinstance(op, L.InputData):
            flush_chain()
            ops.append(InputOp(op.bundles))
        elif isinstance(op, L.Read):
            flush_chain()
            ops.append(ReadOp(op.read_tasks, ctx))
        elif isinstance(op, L.OneToOne):
            res = {}
            if op.num_cpus:
                res["num_cpus"] = op.num_cpus
            if op.num_tpus:
                res["num_tpus"] = op.num_tpus
            if op.memory:
                res["memory"] = op.memory
            if op.compute == "actors":
                prefix = pending_chain
                make_user_fn = op.block_fn  # factory: udf -> block_fn

                def make_fn(udf, _prefix=prefix, _make=make_user_fn):
                    fn = _make(udf)
                    return fn if _prefix is None else _compose(_prefix, fn)

                pending_chain, pending_names, pending_res = None, [], {}
                ops.append(ActorMapOp(op.name, op.udf_cls, op.udf_args,
                                      op.udf_kwargs, make_fn, res, ctx,
                                      op.concurrency))
            else:
                if pending_chain is None:
                    pending_chain = op.block_fn
                else:
                    pending_chain = _compose(pending_chain, op.block_fn)
                pending_names.append(op.name)
                pending_res.update(res)
        elif isinstance(op, L.AllToAll):
            flush_chain()
            ops.append(AllToAllOp(op.name, op.bulk_fn, ctx))
        elif isinstance(op, L.Limit):
            flush_chain()
            ops.append(LimitOp(op.limit))
        elif isinstance(op, L.Union):
            flush_chain()
            ops.append(UnionOp(op.others, ctx))
        elif isinstance(op, L.Join):
            flush_chain()
            ops.append(JoinOp(op.other, op.on, op.how, op.num_partitions,
                              ctx))
        elif isinstance(op, L.Zip):
            flush_chain()
            ops.append(ZipOp(op.other, ctx))
        else:
            raise TypeError(f"unknown logical op: {op}")
    flush_chain()
    return ops


class UnionOp(PhysicalOp):
    def __init__(self, other_plans, ctx):
        self.name = "Union"
        self._others = other_plans
        self._ctx = ctx

    def execute(self, inp, stats):
        for bundle in inp:
            yield bundle
        for plan in self._others:
            for bundle in execute_streaming(plan, self._ctx):
                stats.rows += sum(m.num_rows for _, m in bundle)
                yield bundle


class JoinOp(PhysicalOp):
    """Distributed hash join (reference: operators/join.py over the hash
    shuffle): both sides hash-partition by the key columns; one reduce
    task per partition runs the pyarrow join."""

    _HOW = {"inner": "inner", "left": "left outer",
            "right": "right outer", "outer": "full outer"}

    def __init__(self, other_plan, on, how, num_partitions, ctx):
        self.name = f"Join[{','.join(on)}]"
        self._other = other_plan
        self._on = tuple(on)
        if how not in self._HOW:
            raise ValueError(
                f"how must be one of {sorted(self._HOW)}, got {how!r}")
        self._how = self._HOW[how]
        self._num_partitions = num_partitions
        self._ctx = ctx

    def execute(self, inp, stats):
        from ray_tpu.data.shuffle import hash_partition_submit

        left: List[RefBundle] = [p for b in inp for p in b]
        right: List[RefBundle] = [
            p for b in execute_streaming(self._other, self._ctx) for p in b]
        if not left or not right:
            # A zero-BLOCK side carries no schema to join against.  Joins
            # that discard unmatched rows of the surviving side are simply
            # empty; joins that keep them yield the surviving side's rows
            # unchanged (the missing side's columns cannot be synthesized
            # without a schema).
            keep_left = self._how in ("left outer", "full outer")
            keep_right = self._how in ("right outer", "full outer")
            survivors = (left if (not right and keep_left)
                         else right if (not left and keep_right) else [])
            for p in survivors:
                yield [p]
            return
        n = self._num_partitions or max(
            1, min(8, max(len(left), len(right), 1)))
        lparts = hash_partition_submit(left, self._on, n, "JoinMapLeft")
        rparts = hash_partition_submit(right, self._on, n, "JoinMapRight")

        on, how = self._on, self._how
        max_block = self._ctx.target_max_block_size

        def join_task(lrefs, rrefs):
            import pyarrow as _pa

            # schema-less empties (a filtered-to-nothing upstream block)
            # must not poison the concat schema
            lts = [b for b in ray_tpu.get(list(lrefs))
                   if b is not None and b.num_columns > 0]
            rts = [b for b in ray_tpu.get(list(rrefs))
                   if b is not None and b.num_columns > 0]
            if not lts and not rts:
                return _put_blocks([_pa.table({})], max_block)
            if not rts or not lts:
                # one side has no schema in this partition: joins keeping
                # the surviving side pass its rows through (the missing
                # side's columns cannot be synthesized); others are empty
                surv = block_mod.concat(lts or rts)
                keep = (how in ("left outer", "full outer") if lts
                        else how in ("right outer", "full outer"))
                return _put_blocks(
                    [surv if keep else surv.slice(0, 0)], max_block)
            lt = block_mod.concat(lts)
            rt = block_mod.concat(rts)
            joined = lt.join(rt, keys=list(on), join_type=how)
            return _put_blocks([joined], max_block)

        task = ray_tpu.remote(join_task).options(name="JoinReduce")
        futs = [task.remote([pl[j] for pl in lparts],
                            [pr[j] for pr in rparts]) for j in range(n)]
        t0 = time.perf_counter()
        for f in futs:
            bundle = ray_tpu.get(f)
            stats.tasks += 1
            for _, meta in bundle:
                stats.rows += meta.num_rows
            yield bundle
        stats.wall_s += time.perf_counter() - t0


class ZipOp(PhysicalOp):
    def __init__(self, other_plan, ctx):
        self.name = "Zip"
        self._other = other_plan
        self._ctx = ctx

    def execute(self, inp, stats):
        left: List[RefBundle] = [p for b in inp for p in b]
        right: List[RefBundle] = [
            p for b in execute_streaming(self._other, self._ctx) for p in b]

        def zip_all(refs_l, refs_r):
            lt = block_mod.concat(list(ray_tpu.get(refs_l)))
            rt = block_mod.concat(list(ray_tpu.get(refs_r)))
            if lt.num_rows != rt.num_rows:
                raise ValueError(
                    f"zip requires equal row counts: {lt.num_rows} vs "
                    f"{rt.num_rows}")
            for name in rt.column_names:
                col = name if name not in lt.column_names else name + "_1"
                lt = lt.append_column(col, rt.column(name))
            return _put_blocks([lt], DataContext.get_current(
            ).target_max_block_size)

        task = ray_tpu.remote(zip_all)
        result = ray_tpu.get(task.remote([r for r, _ in left],
                                         [r for r, _ in right]))
        stats.tasks += 1
        for pair in result:
            stats.rows += pair[1].num_rows
            yield [pair]


def execute_streaming(plan: "L.LogicalPlan", ctx: Optional[DataContext]
                      = None, stats_out: Optional[ExecStats] = None
                      ) -> Iterator[List[RefBundle]]:
    """Execute a logical plan, yielding output bundles as they materialize."""
    ctx = ctx or DataContext.get_current()
    phys = plan_physical(plan, ctx)
    stream: Iterator[List[RefBundle]] = iter(())
    stats = stats_out or ExecStats()
    for op in phys:
        s = OpStats(name=op.name)
        stats.ops.append(s)
        stream = op.execute(stream, s)
    return stream
