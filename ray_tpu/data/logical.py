"""Logical plan: declarative description of a Dataset's computation.

Counterpart of the reference's logical operators + plan
(/root/reference/python/ray/data/_internal/logical/operators/*,
_internal/plan.py ExecutionPlan): Dataset methods append logical ops; nothing
executes until consumption, when the planner lowers the logical chain to
physical operators (fusing adjacent maps — reference
_internal/logical/rules/operator_fusion.py).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, List, Optional, Tuple


@dataclass
class LogicalOp:
    name: str = "op"


@dataclass
class InputData(LogicalOp):
    """Already-materialized (block_ref, metadata) pairs."""

    bundles: List[Tuple[Any, Any]] = field(default_factory=list)


@dataclass
class Read(LogicalOp):
    """A list of read tasks, each a zero-arg callable yielding blocks
    (reference: planner/plan_read_op.py over Datasource.get_read_tasks)."""

    read_tasks: List[Callable] = field(default_factory=list)


@dataclass
class OneToOne(LogicalOp):
    """A per-block transform: fn(iter[Block], TaskContext-ish) -> iter[Block].

    Covers MapBatches / MapRows / Filter / FlatMap / Project — all are just
    block-level generator transforms, which makes fusion trivial (compose).
    """

    block_fn: Optional[Callable] = None
    # "tasks" or "actors" (reference: compute strategies, map_operator.py)
    compute: str = "tasks"
    # For actor compute: the UDF class + constructor args; workers construct
    # one instance per actor and reuse it across calls.
    udf_cls: Any = None
    udf_args: tuple = ()
    udf_kwargs: dict = field(default_factory=dict)
    concurrency: Optional[int] = None
    num_cpus: Optional[float] = None
    num_tpus: Optional[float] = None
    memory: Optional[float] = None


@dataclass
class AllToAll(LogicalOp):
    """A barrier op: fn(list[(ref, meta)], ctx) -> list[(ref, meta)].

    Covers repartition / random_shuffle / sort / groupby-aggregate
    (reference: _internal/planner/exchange/*).
    """

    bulk_fn: Optional[Callable] = None


@dataclass
class Union(LogicalOp):
    others: List[Any] = field(default_factory=list)  # list[LogicalPlan]


@dataclass
class Zip(LogicalOp):
    other: Any = None  # LogicalPlan


@dataclass
class Join(LogicalOp):
    """Hash join with another plan (reference:
    _internal/execution/operators/join.py + hash_shuffle.py)."""

    other: Any = None  # LogicalPlan
    on: tuple = ()  # join key column(s)
    how: str = "inner"  # inner | left | right | outer
    num_partitions: Optional[int] = None


@dataclass
class Limit(LogicalOp):
    limit: int = 0


class LogicalPlan:
    def __init__(self, ops: Optional[List[LogicalOp]] = None):
        self.ops: List[LogicalOp] = ops or []

    def with_op(self, op: LogicalOp) -> "LogicalPlan":
        return LogicalPlan(self.ops + [op])

    def __repr__(self):
        return " -> ".join(op.name for op in self.ops) or "(empty)"
