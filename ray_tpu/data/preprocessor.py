"""Fittable preprocessors over Datasets.

Counterpart of /root/reference/python/ray/data/preprocessor.py:28
(Preprocessor ABC: fit/transform/fit_transform/transform_batch) and
python/ray/data/preprocessors/ (scalers, encoders, imputer, concatenator).
Fitting is one streaming pass over numpy batches — no materialization — and
the fitted state is plain data, so a preprocessor pickles into Train
workers and Serve replicas (the reference's checkpointable-preprocessor
pattern).
"""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np


class PreprocessorNotFittedError(RuntimeError):
    pass


class Preprocessor:
    _is_fittable = True

    def __init__(self):
        self._fitted = False

    # -- API ---------------------------------------------------------------
    def fit(self, ds) -> "Preprocessor":
        self._fit(ds)
        self._fitted = True
        return self

    def fit_transform(self, ds):
        return self.fit(ds).transform(ds)

    def transform(self, ds):
        if self._is_fittable and not self._fitted:
            raise PreprocessorNotFittedError(
                f"{type(self).__name__} must be fit before transform")
        return ds.map_batches(self.transform_batch, batch_format="numpy")

    def transform_batch(self, batch: Dict[str, np.ndarray]):
        raise NotImplementedError

    def _fit(self, ds):
        raise NotImplementedError

    # -- shared fitting pass ----------------------------------------------
    @staticmethod
    def _numeric_stats(ds, columns: List[str]) -> Dict[str, dict]:
        """One streaming pass: count/sum/sumsq/min/max per column."""
        stats = {c: {"n": 0, "sum": 0.0, "sumsq": 0.0,
                     "min": np.inf, "max": -np.inf} for c in columns}
        for batch in ds.iter_batches(batch_format="numpy"):
            for c in columns:
                col = np.asarray(batch[c], dtype=np.float64)
                s = stats[c]
                s["n"] += col.size
                s["sum"] += float(col.sum())
                s["sumsq"] += float((col * col).sum())
                if col.size:
                    s["min"] = min(s["min"], float(col.min()))
                    s["max"] = max(s["max"], float(col.max()))
        return stats

    @staticmethod
    def _uniques(ds, columns: List[str]) -> Dict[str, list]:
        vals: Dict[str, set] = {c: set() for c in columns}
        for batch in ds.iter_batches(batch_format="numpy"):
            for c in columns:
                vals[c].update(np.asarray(batch[c]).tolist())
        return {c: sorted(v) for c, v in vals.items()}


class StandardScaler(Preprocessor):
    """(x - mean) / std per column (reference preprocessors/scaler.py)."""

    def __init__(self, columns: List[str]):
        super().__init__()
        self.columns = columns
        self.stats_: Dict[str, tuple] = {}

    def _fit(self, ds):
        raw = self._numeric_stats(ds, self.columns)
        for c, s in raw.items():
            mean = s["sum"] / max(1, s["n"])
            var = max(0.0, s["sumsq"] / max(1, s["n"]) - mean * mean)
            self.stats_[c] = (mean, float(np.sqrt(var)) or 1.0)

    def transform_batch(self, batch):
        out = dict(batch)
        for c, (mean, std) in self.stats_.items():
            out[c] = (np.asarray(batch[c], np.float64) - mean) / (std or 1.0)
        return out


class MinMaxScaler(Preprocessor):
    """(x - min) / (max - min) per column."""

    def __init__(self, columns: List[str]):
        super().__init__()
        self.columns = columns
        self.stats_: Dict[str, tuple] = {}

    def _fit(self, ds):
        raw = self._numeric_stats(ds, self.columns)
        for c, s in raw.items():
            self.stats_[c] = (s["min"], s["max"])

    def transform_batch(self, batch):
        out = dict(batch)
        for c, (lo, hi) in self.stats_.items():
            rng = (hi - lo) or 1.0
            out[c] = (np.asarray(batch[c], np.float64) - lo) / rng
        return out


class LabelEncoder(Preprocessor):
    """Category -> int index for one label column."""

    def __init__(self, label_column: str):
        super().__init__()
        self.label_column = label_column
        self.classes_: list = []

    def _fit(self, ds):
        self.classes_ = self._uniques(ds, [self.label_column])[
            self.label_column]
        self._index_ = {v: i for i, v in enumerate(self.classes_)}

    def transform_batch(self, batch):
        index = getattr(self, "_index_", None)
        if index is None:  # fitted instance unpickled from an older state
            index = self._index_ = {v: i for i, v in enumerate(self.classes_)}
        out = dict(batch)
        vals = np.asarray(batch[self.label_column]).tolist()
        unseen = [v for v in vals if v not in index]
        if unseen:
            raise ValueError(
                f"LabelEncoder saw unseen label(s) {sorted(set(unseen))!r} "
                f"at transform time; fitted classes: {self.classes_!r}")
        out[self.label_column] = np.array([index[v] for v in vals],
                                          dtype=np.int64)
        return out


class OneHotEncoder(Preprocessor):
    """Category columns -> one {col}_{value} 0/1 column per category."""

    def __init__(self, columns: List[str]):
        super().__init__()
        self.columns = columns
        self.categories_: Dict[str, list] = {}

    def _fit(self, ds):
        self.categories_ = self._uniques(ds, self.columns)

    def transform_batch(self, batch):
        out = {k: v for k, v in batch.items() if k not in self.columns}
        for c in self.columns:
            col = np.asarray(batch[c])
            for cat in self.categories_[c]:
                out[f"{c}_{cat}"] = (col == cat).astype(np.int8)
        return out


class SimpleImputer(Preprocessor):
    """Fill NaNs with the column mean (strategy='mean') or a constant."""

    def __init__(self, columns: List[str], strategy: str = "mean",
                 fill_value: Optional[float] = None):
        super().__init__()
        if strategy not in ("mean", "constant"):
            raise ValueError(f"unknown strategy {strategy!r}")
        self.columns = columns
        self.strategy = strategy
        self.fill_value = fill_value
        self.fills_: Dict[str, float] = {}

    def _fit(self, ds):
        if self.strategy == "constant":
            self.fills_ = {c: float(self.fill_value or 0.0)
                           for c in self.columns}
            return
        # mean over non-NaN values, single pass
        acc = {c: [0.0, 0] for c in self.columns}
        for batch in ds.iter_batches(batch_format="numpy"):
            for c in self.columns:
                col = np.asarray(batch[c], np.float64)
                mask = ~np.isnan(col)
                acc[c][0] += float(col[mask].sum())
                acc[c][1] += int(mask.sum())
        self.fills_ = {c: (s / n if n else 0.0) for c, (s, n) in acc.items()}

    def transform_batch(self, batch):
        out = dict(batch)
        for c, fill in self.fills_.items():
            col = np.asarray(batch[c], np.float64).copy()
            col[np.isnan(col)] = fill
            out[c] = col
        return out


class Concatenator(Preprocessor):
    """Merge feature columns into one float vector column — the shape JAX
    train loops consume (reference preprocessors/concatenator.py)."""

    _is_fittable = False

    def __init__(self, columns: List[str], output_column_name: str = "features",
                 dtype=np.float32):
        super().__init__()
        self.columns = columns
        self.output_column_name = output_column_name
        self.dtype = dtype
        self._fitted = True

    def _fit(self, ds):
        return self

    def transform_batch(self, batch):
        out = {k: v for k, v in batch.items() if k not in self.columns}
        cols = [np.asarray(batch[c]).reshape(len(batch[c]), -1)
                for c in self.columns]
        out[self.output_column_name] = np.concatenate(
            cols, axis=1).astype(self.dtype)
        return out


class Chain(Preprocessor):
    """Apply preprocessors in sequence (reference: preprocessor.Chain)."""

    def __init__(self, *preprocessors: Preprocessor):
        super().__init__()
        self.preprocessors = list(preprocessors)

    def fit(self, ds):
        for p in self.preprocessors:
            if p._is_fittable:
                p.fit(ds)
            ds = p.transform(ds)
        self._fitted = True
        return self

    def transform(self, ds):
        for p in self.preprocessors:
            ds = p.transform(ds)
        return ds

    def transform_batch(self, batch):
        for p in self.preprocessors:
            batch = p.transform_batch(batch)
        return batch

    def _fit(self, ds):
        raise AssertionError("Chain overrides fit()")
