"""Delta Sharing datasource over the open REST protocol — no client wheel.

Counterpart of the reference's delta-sharing datasource
(/root/reference/python/ray/data/_internal/datasource/
delta_sharing_datasource.py, which wraps the `delta-sharing` client).
The protocol itself (github.com/delta-io/delta-sharing/blob/main/
PROTOCOL.md) is a small REST surface, so this module speaks it
directly with urllib:

  POST {endpoint}/shares/{share}/schemas/{schema}/tables/{table}/query
    -> NDJSON: a `protocol` line, a `metaData` line, then one `file`
       line per data file with a presigned parquet URL.

Each file becomes one read task that downloads its parquet bytes and
decodes them with pyarrow — the same per-file parallelism the reference
datasource derives from the client's `load_as_pandas` plumbing.

URL form (reference-compatible): ``<profile-file>#<share>.<schema>.<table>``
where the profile file is the standard JSON
``{"endpoint": ..., "bearerToken": ...}``.
"""

from __future__ import annotations

import io
import json
import urllib.request
from typing import Callable, Iterator, List, Optional

import pyarrow as pa
import pyarrow.parquet as pq


def parse_url(url: str):
    """(profile_path, share, schema, table) from profile#share.schema.table."""
    if "#" not in url:
        raise ValueError(
            "delta-sharing URL must be '<profile-file>#share.schema.table'")
    profile_path, triple = url.rsplit("#", 1)
    parts = triple.split(".")
    if len(parts) != 3:
        raise ValueError(f"bad share triple {triple!r} "
                         "(want share.schema.table)")
    return profile_path, parts[0], parts[1], parts[2]


def load_profile(profile_path: str) -> dict:
    with open(profile_path) as f:
        prof = json.load(f)
    if "endpoint" not in prof:
        raise ValueError(f"profile {profile_path} has no endpoint")
    return prof


def query_table_files(prof: dict, share: str, schema: str, table: str,
                      limit: Optional[int] = None,
                      timeout: float = 60.0):
    """(file entries, metaData) for the table's snapshot."""
    endpoint = prof["endpoint"].rstrip("/")
    url = (f"{endpoint}/shares/{share}/schemas/{schema}"
           f"/tables/{table}/query")
    body: dict = {}
    if limit is not None:
        body["limitHint"] = int(limit)
    req = urllib.request.Request(
        url, data=json.dumps(body).encode("utf-8"), method="POST",
        headers={"Content-Type": "application/json",
                 "Authorization": f"Bearer {prof.get('bearerToken', '')}"})
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        lines = resp.read().decode("utf-8").splitlines()
    files = []
    meta = {}
    for line in lines:
        line = line.strip()
        if not line:
            continue
        entry = json.loads(line)
        if "file" in entry:
            files.append(entry["file"])
        elif "metaData" in entry:
            meta = entry["metaData"]
    return files, meta


def _partition_types(meta: dict) -> dict:
    """partition column -> arrow type, from metaData.schemaString (a
    Spark schema).  Unknown/complex types surface as strings."""
    simple = {"long": pa.int64(), "integer": pa.int32(),
              "short": pa.int16(), "byte": pa.int8(),
              "double": pa.float64(), "float": pa.float32(),
              "boolean": pa.bool_(), "string": pa.string()}
    out = {}
    try:
        fields = json.loads(meta.get("schemaString", "{}")).get("fields", [])
        for f in fields:
            t = f.get("type")
            if isinstance(t, str) and t in simple:
                out[f.get("name")] = simple[t]
    except (ValueError, AttributeError):
        pass
    return out


def _cast_partition(value, typ):
    if value is None:
        return None
    if pa.types.is_boolean(typ):
        return value in ("true", "True", True)
    if pa.types.is_integer(typ):
        return int(value)
    if pa.types.is_floating(typ):
        return float(value)
    return str(value)


def _fetch_parquet(url: str, partition_values: Optional[dict] = None,
                   ptypes: Optional[dict] = None,
                   timeout: float = 120.0) -> pa.Table:
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        raw = resp.read()
    t = pq.read_table(io.BytesIO(raw))
    # Delta data files physically LACK partition columns: the protocol
    # requires clients to reconstruct them from each file entry's
    # partitionValues (the reference client does the same).
    for col, sval in (partition_values or {}).items():
        if col in t.column_names:
            continue
        typ = (ptypes or {}).get(col, pa.string())
        t = t.append_column(
            pa.field(col, typ),
            pa.array([_cast_partition(sval, typ)] * len(t), typ))
    return t


def delta_sharing_tasks(url: str, parallelism: int,
                        limit: Optional[int] = None) -> List[Callable]:
    profile_path, share, schema, table = parse_url(url)
    prof = load_profile(profile_path)
    files, meta = query_table_files(prof, share, schema, table,
                                    limit=limit)
    ptypes = _partition_types(meta)

    def make_task(batch: List[dict]):
        def task() -> Iterator[pa.Table]:
            for f in batch:
                yield _fetch_parquet(f["url"],
                                     f.get("partitionValues"), ptypes)
        return task

    n = max(1, min(parallelism, len(files))) if files else 0
    buckets: List[List[dict]] = [[] for _ in range(n)]
    for i, f in enumerate(files):
        buckets[i % n].append(f)
    return [make_task(b) for b in buckets if b]
