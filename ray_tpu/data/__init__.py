"""ray_tpu.data: lazy, streaming, distributed datasets over Arrow blocks.

Counterpart of Ray Data (/root/reference/python/ray/data/): read_* build a
lazy logical plan; transforms append ops; consumption lowers to physical
operators run by a pull-based streaming executor on the core task/actor
runtime.  See dataset.py / executor.py for the design.
"""

from __future__ import annotations

from typing import Any, List, Optional

from ray_tpu.data import block as _block
from ray_tpu.data import datasource as _ds
from ray_tpu.data import logical as _L
from ray_tpu.data.block import Block, BlockMetadata
from ray_tpu.data.context import DataContext
from ray_tpu.data.dataset import Dataset, GroupedData, MaterializedDataset
from ray_tpu.data.iterator import DataIterator
from ray_tpu.data.preprocessor import (
    Chain,
    Concatenator,
    LabelEncoder,
    MinMaxScaler,
    OneHotEncoder,
    Preprocessor,
    SimpleImputer,
    StandardScaler,
)


def _read(name: str, tasks) -> Dataset:
    return Dataset(_L.LogicalPlan([_L.Read(name=name, read_tasks=tasks)]))


def _par(override: Optional[int]) -> int:
    return override or DataContext.get_current().default_parallelism


def range(n: int, *, override_num_blocks: Optional[int] = None) -> Dataset:  # noqa: A001
    return _read("Range", _ds.range_tasks(n, _par(override_num_blocks)))


def from_items(items: List[Any], *,
               override_num_blocks: Optional[int] = None) -> Dataset:
    return _read("FromItems", _ds.items_tasks(items, _par(override_num_blocks)))


def from_numpy(arr, column: str = "item") -> Dataset:
    import numpy as np

    block = _block.from_batch({column: np.asarray(arr)})
    import ray_tpu

    bundles = [(ray_tpu.put(block), BlockMetadata.of(block))]
    return Dataset(_L.LogicalPlan([_L.InputData(name="FromNumpy",
                                                bundles=bundles)]))


def from_pandas(df) -> Dataset:
    import pyarrow as pa

    import ray_tpu

    block = pa.Table.from_pandas(df, preserve_index=False)
    bundles = [(ray_tpu.put(block), BlockMetadata.of(block))]
    return Dataset(_L.LogicalPlan([_L.InputData(name="FromPandas",
                                                bundles=bundles)]))


def from_arrow(table) -> Dataset:
    import ray_tpu

    bundles = [(ray_tpu.put(table), BlockMetadata.of(table))]
    return Dataset(_L.LogicalPlan([_L.InputData(name="FromArrow",
                                                bundles=bundles)]))


def read_parquet(paths, *, columns: Optional[List[str]] = None,
                 override_num_blocks: Optional[int] = None) -> Dataset:
    return _read("ReadParquet",
                 _ds.parquet_tasks(paths, _par(override_num_blocks), columns))


def read_csv(paths, *, override_num_blocks: Optional[int] = None) -> Dataset:
    return _read("ReadCSV", _ds.csv_tasks(paths, _par(override_num_blocks)))


def read_json(paths, *, override_num_blocks: Optional[int] = None) -> Dataset:
    return _read("ReadJSON", _ds.json_tasks(paths, _par(override_num_blocks)))


def read_text(paths, *, override_num_blocks: Optional[int] = None) -> Dataset:
    return _read("ReadText", _ds.text_tasks(paths, _par(override_num_blocks)))


def read_images(paths, *, size=None, mode: str = "RGB",
                include_paths: bool = False,
                override_num_blocks: Optional[int] = None) -> Dataset:
    return _read("ReadImages", _ds.image_tasks(
        paths, _par(override_num_blocks), size=size, mode=mode,
        include_paths=include_paths))


def from_huggingface(hf_dataset, *,
                     override_num_blocks: Optional[int] = None) -> Dataset:
    """Zero-copy over a `datasets.Dataset`'s arrow shards."""
    return _read("FromHuggingFace", _ds.huggingface_tasks(
        hf_dataset, _par(override_num_blocks)))


def read_binary_files(paths, *, include_paths: bool = False,
                      override_num_blocks: Optional[int] = None) -> Dataset:
    return _read("ReadBinary",
                 _ds.binary_tasks(paths, _par(override_num_blocks),
                                  include_paths))


def read_numpy(paths, *, override_num_blocks: Optional[int] = None) -> Dataset:
    return _read("ReadNumpy", _ds.numpy_tasks(paths, _par(override_num_blocks)))


def read_tfrecords(paths, *, raw_bytes: bool = False,
                   override_num_blocks: Optional[int] = None) -> Dataset:
    """Rows from tf.train.Example records (reference: read_tfrecords)."""
    return _read("ReadTFRecords", _ds.tfrecord_tasks(
        paths, _par(override_num_blocks), raw_bytes=raw_bytes))


def read_webdataset(paths, *,
                    override_num_blocks: Optional[int] = None) -> Dataset:
    """Samples from webdataset tar shards (reference: read_webdataset)."""
    return _read("ReadWebDataset", _ds.webdataset_tasks(
        paths, _par(override_num_blocks)))


def read_sql(sql: str, connection_factory, *,
             fetch_size: int = 4096) -> Dataset:
    """Rows from any DB-API 2.0 query (reference: read_sql)."""
    return _read("ReadSQL", _ds.sql_tasks(
        sql, connection_factory, fetch_size=fetch_size))


__all__ = [
    "Block",
    "BlockMetadata",
    "Chain",
    "Concatenator",
    "DataContext",
    "DataIterator",
    "Dataset",
    "GroupedData",
    "LabelEncoder",
    "MaterializedDataset",
    "MinMaxScaler",
    "OneHotEncoder",
    "Preprocessor",
    "SimpleImputer",
    "StandardScaler",
    "from_arrow",
    "from_huggingface",
    "from_items",
    "from_numpy",
    "from_pandas",
    "range",
    "read_binary_files",
    "read_csv",
    "read_images",
    "read_json",
    "read_numpy",
    "read_parquet",
    "read_sql",
    "read_tfrecords",
    "read_webdataset",
    "read_text",
]
