"""ray_tpu.data: lazy, streaming, distributed datasets over Arrow blocks.

Counterpart of Ray Data (/root/reference/python/ray/data/): read_* build a
lazy logical plan; transforms append ops; consumption lowers to physical
operators run by a pull-based streaming executor on the core task/actor
runtime.  See dataset.py / executor.py for the design.
"""

from __future__ import annotations

from typing import Any, List, Optional

from ray_tpu.data import block as _block
from ray_tpu.data import datasource as _ds
from ray_tpu.data import logical as _L
from ray_tpu.data.block import Block, BlockMetadata
from ray_tpu.data.context import DataContext
from ray_tpu.data.dataset import Dataset, GroupedData, MaterializedDataset
from ray_tpu.data.iterator import DataIterator
from ray_tpu.data.preprocessor import (
    Chain,
    Concatenator,
    LabelEncoder,
    MinMaxScaler,
    OneHotEncoder,
    Preprocessor,
    SimpleImputer,
    StandardScaler,
)
from ray_tpu.data import service  # noqa: E402 — cluster-level data service


def _read(name: str, tasks) -> Dataset:
    return Dataset(_L.LogicalPlan([_L.Read(name=name, read_tasks=tasks)]))


def _par(override: Optional[int]) -> int:
    return override or DataContext.get_current().default_parallelism


def range(n: int, *, override_num_blocks: Optional[int] = None) -> Dataset:  # noqa: A001
    return _read("Range", _ds.range_tasks(n, _par(override_num_blocks)))


def from_items(items: List[Any], *,
               override_num_blocks: Optional[int] = None) -> Dataset:
    return _read("FromItems", _ds.items_tasks(items, _par(override_num_blocks)))


def from_numpy(arr, column: str = "item") -> Dataset:
    import numpy as np

    block = _block.from_batch({column: np.asarray(arr)})
    import ray_tpu

    bundles = [(ray_tpu.put(block), BlockMetadata.of(block))]
    return Dataset(_L.LogicalPlan([_L.InputData(name="FromNumpy",
                                                bundles=bundles)]))


def from_pandas(df) -> Dataset:
    import pyarrow as pa

    import ray_tpu

    block = pa.Table.from_pandas(df, preserve_index=False)
    bundles = [(ray_tpu.put(block), BlockMetadata.of(block))]
    return Dataset(_L.LogicalPlan([_L.InputData(name="FromPandas",
                                                bundles=bundles)]))


def from_arrow(table) -> Dataset:
    import ray_tpu

    bundles = [(ray_tpu.put(table), BlockMetadata.of(table))]
    return Dataset(_L.LogicalPlan([_L.InputData(name="FromArrow",
                                                bundles=bundles)]))


def read_parquet(paths, *, columns: Optional[List[str]] = None,
                 override_num_blocks: Optional[int] = None) -> Dataset:
    return _read("ReadParquet",
                 _ds.parquet_tasks(paths, _par(override_num_blocks), columns))


def read_csv(paths, *, override_num_blocks: Optional[int] = None) -> Dataset:
    return _read("ReadCSV", _ds.csv_tasks(paths, _par(override_num_blocks)))


def read_json(paths, *, override_num_blocks: Optional[int] = None) -> Dataset:
    return _read("ReadJSON", _ds.json_tasks(paths, _par(override_num_blocks)))


def read_text(paths, *, override_num_blocks: Optional[int] = None) -> Dataset:
    return _read("ReadText", _ds.text_tasks(paths, _par(override_num_blocks)))


def read_images(paths, *, size=None, mode: str = "RGB",
                include_paths: bool = False,
                override_num_blocks: Optional[int] = None) -> Dataset:
    return _read("ReadImages", _ds.image_tasks(
        paths, _par(override_num_blocks), size=size, mode=mode,
        include_paths=include_paths))


def from_huggingface(hf_dataset, *,
                     override_num_blocks: Optional[int] = None) -> Dataset:
    """Zero-copy over a `datasets.Dataset`'s arrow shards."""
    return _read("FromHuggingFace", _ds.huggingface_tasks(
        hf_dataset, _par(override_num_blocks)))


def read_binary_files(paths, *, include_paths: bool = False,
                      override_num_blocks: Optional[int] = None) -> Dataset:
    return _read("ReadBinary",
                 _ds.binary_tasks(paths, _par(override_num_blocks),
                                  include_paths))


def read_numpy(paths, *, override_num_blocks: Optional[int] = None) -> Dataset:
    return _read("ReadNumpy", _ds.numpy_tasks(paths, _par(override_num_blocks)))


def read_tfrecords(paths, *, raw_bytes: bool = False,
                   override_num_blocks: Optional[int] = None) -> Dataset:
    """Rows from tf.train.Example records (reference: read_tfrecords)."""
    return _read("ReadTFRecords", _ds.tfrecord_tasks(
        paths, _par(override_num_blocks), raw_bytes=raw_bytes))


def read_webdataset(paths, *,
                    override_num_blocks: Optional[int] = None) -> Dataset:
    """Samples from webdataset tar shards (reference: read_webdataset)."""
    return _read("ReadWebDataset", _ds.webdataset_tasks(
        paths, _par(override_num_blocks)))


def read_sql(sql: str, connection_factory, *,
             fetch_size: int = 4096) -> Dataset:
    """Rows from any DB-API 2.0 query (reference: read_sql)."""
    return _read("ReadSQL", _ds.sql_tasks(
        sql, connection_factory, fetch_size=fetch_size))


def read_avro(paths, *, override_num_blocks: Optional[int] = None) -> Dataset:
    """Avro Object Container Files via a built-in pure-python decoder
    (reference: read_avro over fastavro)."""
    return _read("ReadAvro", _ds.avro_tasks(paths, _par(override_num_blocks)))


def from_torch(torch_dataset, *,
               override_num_blocks: Optional[int] = None) -> Dataset:
    """Rows ({"item": sample}) from a torch Dataset (reference:
    read_api.py from_torch :3334); map-style datasets shard by index."""
    return _read("FromTorch", _ds.torch_tasks(
        torch_dataset, _par(override_num_blocks)))


def from_tf(tf_dataset) -> Dataset:
    """Rows from a tf.data.Dataset (reference: read_api.py from_tf, which
    materializes eagerly too — a tf.data graph cannot cross process
    boundaries, so rows are drawn on the driver and put to the store)."""
    rows = []
    for elem in tf_dataset.as_numpy_iterator():
        if isinstance(elem, dict):
            rows.append(dict(elem))
        elif isinstance(elem, tuple):
            rows.append({f"item_{i}": v for i, v in enumerate(elem)})
        else:
            rows.append({"item": elem})
    return from_items(rows)


def _gated_reader(api_name: str, pip_pkg: str, sketch: str,
                  import_name: Optional[str] = None):
    """Cloud/warehouse datasources whose client wheels are not in the TPU
    image (reference ships them in _internal/datasource/).  Each raises a
    precise ImportError naming the wheel rather than pretending — the
    gating itself is tested (tests/test_data_extras.py).  import_name is
    the module to probe when it differs from the pip name (cv2 vs
    opencv-python etc.)."""
    mod = import_name or pip_pkg.replace("-", "_")

    def reader(*args, **kwargs):
        try:
            __import__(mod)
        except ImportError as e:
            raise ImportError(
                f"{api_name} requires the `{pip_pkg}` package (not in the "
                f"TPU image).  Once installed: {sketch}") from e
        raise NotImplementedError(
            f"{api_name}: client wheel present but the TPU-image build "
            f"gates this path; read via an exported format "
            f"(read_parquet/read_sql) or file an issue")

    reader.__name__ = api_name
    reader.__qualname__ = api_name
    reader.__doc__ = (f"{api_name} (gated: needs `{pip_pkg}`). {sketch}")
    return reader


read_bigquery = _gated_reader(
    "read_bigquery", "google-cloud-bigquery",
    "runs a BQ Storage API read session, one stream per read task",
    import_name="google.cloud.bigquery")
def read_mongo(uri: str, database: str, collection: str, *,
               filter: Optional[dict] = None,
               projection: Optional[dict] = None,
               override_num_blocks: Optional[int] = None) -> Dataset:
    """Rows of a MongoDB collection, partitioned by `_id` ranges — one
    independent range cursor per read task, spoken over the raw OP_MSG
    wire protocol (data/mongo.py), no pymongo."""
    from ray_tpu.data.mongo import mongo_tasks

    return _read("ReadMongo",
                 mongo_tasks(uri, database, collection,
                             _par(override_num_blocks), filter=filter,
                             projection=projection))
read_lance = _gated_reader(
    "read_lance", "pylance",
    "reads dataset fragments, one per read task", import_name="lance")
read_hudi = _gated_reader(
    "read_hudi", "hudi",
    "reads file slices from the latest commit timeline")
def read_delta_sharing(url: str, *, limit: Optional[int] = None,
                       override_num_blocks: Optional[int] = None) -> Dataset:
    """Rows of a Delta Sharing table snapshot
    (``<profile-file>#share.schema.table``), spoken over the open REST
    protocol directly — presigned parquet files decode per read task
    (data/delta_sharing.py, no `delta-sharing` wheel)."""
    from ray_tpu.data.delta_sharing import delta_sharing_tasks

    ds = _read("ReadDeltaSharing",
               delta_sharing_tasks(url, _par(override_num_blocks),
                                   limit=limit))
    # limitHint is advisory (servers MAY ignore it): enforce client-side
    return ds.limit(limit) if limit is not None else ds
read_databricks_tables = _gated_reader(
    "read_databricks_tables", "databricks-sql-connector",
    "pages results through the Databricks SQL statement API",
    import_name="databricks.sql")
def read_audio(paths, *,
               override_num_blocks: Optional[int] = None) -> Dataset:
    """Decoded audio per file: {amplitude float32[ch, samples],
    sample_rate, path}.  PCM/float WAV decodes natively (stdlib);
    other containers use `soundfile` when installed (data/audio.py)."""
    from ray_tpu.data.audio import audio_tasks

    return _read("ReadAudio",
                 audio_tasks(paths, _par(override_num_blocks)))


def read_iceberg(table_dir: str, *, snapshot_id: Optional[int] = None,
                 columns: Optional[List[str]] = None,
                 override_num_blocks: Optional[int] = None) -> Dataset:
    """Rows of an Iceberg table's current (or named) snapshot, one read
    task per live parquet data file — native metadata-chain walk, no
    pyiceberg (reference: _internal/datasource/iceberg_datasource.py;
    see data/lakehouse.py for scope)."""
    from ray_tpu.data.lakehouse import iceberg_tasks

    return _read("ReadIceberg", iceberg_tasks(
        table_dir, _par(override_num_blocks), snapshot_id=snapshot_id,
        columns=columns))


def read_videos(paths, *, override_num_blocks: Optional[int] = None
                ) -> Dataset:
    """One row per decoded frame ({"frame": HxWx3 uint8 RGB,
    "frame_index", "path"}); AVI/MJPEG + raw-DIB decode natively via
    PIL, other containers fall back to cv2 when importable (reference:
    _internal/datasource/video_datasource.py over opencv)."""
    from ray_tpu.data.video import video_tasks

    return _read("ReadVideos", video_tasks(paths, _par(override_num_blocks)))


def read_clickhouse(query: str, *, dsn: str = "http://localhost:8123",
                    partition_key: Optional[str] = None,
                    user: Optional[str] = None,
                    password: Optional[str] = None,
                    override_num_blocks: Optional[int] = None) -> Dataset:
    """Rows of a ClickHouse query over the server's HTTP interface
    (FORMAT JSONEachRow), fanned out by modulo(partition_key, N) when a
    numeric partition key is given — no client wheel needed (reference:
    _internal/datasource/clickhouse_datasource.py over
    clickhouse-connect)."""
    return _read("ReadClickHouse", _ds.clickhouse_tasks(
        query, dsn, _par(override_num_blocks),
        partition_key=partition_key, user=user, password=password))


__all__ = [
    "Block",
    "BlockMetadata",
    "Chain",
    "Concatenator",
    "DataContext",
    "DataIterator",
    "Dataset",
    "GroupedData",
    "LabelEncoder",
    "MaterializedDataset",
    "MinMaxScaler",
    "OneHotEncoder",
    "Preprocessor",
    "SimpleImputer",
    "StandardScaler",
    "from_arrow",
    "from_huggingface",
    "from_items",
    "from_numpy",
    "from_pandas",
    "range",
    "from_tf",
    "from_torch",
    "read_audio",
    "read_avro",
    "read_binary_files",
    "read_clickhouse",
    "read_iceberg",
    "read_delta_sharing",
    "read_mongo",
    "read_videos",
    "read_csv",
    "read_images",
    "read_json",
    "read_numpy",
    "read_parquet",
    "read_sql",
    "read_tfrecords",
    "read_webdataset",
    "read_text",
    "service",
]
