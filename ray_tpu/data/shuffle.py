"""All-to-all exchanges: repartition, random_shuffle, sort, groupby.

Counterpart of the reference's exchange planners
(/root/reference/python/ray/data/_internal/planner/exchange/
shuffle_task_scheduler.py, sort_task_spec.py, and the hash_shuffle /
hash_aggregate physical operators): two-phase map/reduce over object-store
refs — map tasks partition each input block and ``put`` the pieces, reduce
tasks fetch their partition's pieces and combine.  All phases are ordinary
tasks on the core runtime, so the scheduler's backpressure and retries apply.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np
import pyarrow as pa
import pyarrow.compute as pc

import ray_tpu
from ray_tpu.data import block as block_mod
from ray_tpu.data.block import Block, BlockMetadata


def _reduce_submit(parts_lists, num_parts: int, combine: Callable,
                   name: str) -> List[Tuple[Any, BlockMetadata]]:
    """Fan reduce tasks over partitions; parts_lists[i][j] = ref of input i's
    piece for partition j."""

    def reduce_task(piece_refs):
        blocks = [b for b in ray_tpu.get(list(piece_refs))
                  if b is not None and b.num_rows >= 0]
        out = combine(block_mod.concat(blocks)) if blocks else pa.table({})
        return ray_tpu.put(out), BlockMetadata.of(out)

    task = ray_tpu.remote(reduce_task).options(name=name)
    futs = [task.remote([plist[j] for plist in parts_lists])
            for j in range(num_parts)]
    return [ray_tpu.get(f) for f in futs]


def _map_submit(bundles, map_fn: Callable, name: str) -> List[List[Any]]:
    """map_fn(block) -> list of blocks (one per partition); tasks put each
    piece and return its refs."""

    def map_task(b):
        return [ray_tpu.put(piece) for piece in map_fn(b)]

    task = ray_tpu.remote(map_task).options(name=name)
    futs = [task.remote(ref) for ref, _ in bundles]
    return [ray_tpu.get(f) for f in futs]


def repartition_fn(num_blocks: int):
    def bulk(bundles, ctx):
        total = sum(m.num_rows for _, m in bundles)
        bounds = np.linspace(0, total, num_blocks + 1, dtype=np.int64)
        # Assign each output block a global row range; map tasks slice out
        # the overlap of their input block with each range.
        starts = []
        acc = 0
        for _, m in bundles:
            starts.append(acc)
            acc += m.num_rows

        def make_map(start_row):
            def fn(b):
                pieces = []
                for j in range(num_blocks):
                    lo = int(max(bounds[j] - start_row, 0))
                    hi = int(min(bounds[j + 1] - start_row, b.num_rows))
                    pieces.append(b.slice(lo, max(0, hi - lo)))
                return pieces

            return fn

        def map_task(b, start_row):
            return [ray_tpu.put(p) for p in make_map(start_row)(b)]

        task = ray_tpu.remote(map_task).options(name="RepartitionMap")
        parts = [ray_tpu.get(task.remote(ref, starts[i]))
                 for i, (ref, _) in enumerate(bundles)]
        return _reduce_submit(parts, num_blocks, lambda t: t,
                              "RepartitionReduce")

    return bulk


def random_shuffle_fn(seed: Optional[int] = None,
                      num_blocks: Optional[int] = None):
    def bulk(bundles, ctx):
        n_out = num_blocks or max(1, len(bundles))
        # Fresh entropy per unseeded shuffle so per-epoch shuffles differ.
        rng_seed = seed if seed is not None else int(
            np.random.SeedSequence().entropy % (2 ** 31))

        def map_fn_for(i):
            def fn(b):
                rng = np.random.default_rng(rng_seed + 7919 * i)
                idx = rng.permutation(b.num_rows)
                assign = rng.integers(0, n_out, size=b.num_rows)
                shuffled = b.take(pa.array(idx))
                return [shuffled.filter(pa.array(assign == j))
                        for j in range(n_out)]

            return fn

        def map_task(b, i):
            return [ray_tpu.put(p) for p in map_fn_for(i)(b)]

        task = ray_tpu.remote(map_task).options(name="ShuffleMap")
        parts = [ray_tpu.get(task.remote(ref, i))
                 for i, (ref, _) in enumerate(bundles)]

        def combine(t, _seed=rng_seed):
            rng = np.random.default_rng(_seed ^ 0xABCDEF)
            if t.num_rows == 0:
                return t
            return t.take(pa.array(rng.permutation(t.num_rows)))

        return _reduce_submit(parts, n_out, combine, "ShuffleReduce")

    return bulk


def hash_partition_submit(bundles, keys: Tuple[str, ...], n_parts: int,
                          name: str) -> List[List[Any]]:
    """Hash-partition every bundle's block by key columns; returns
    parts[i][j] = ref of input i's piece for partition j (the map half of
    a hash shuffle — reference: operators/hash_shuffle.py)."""
    import zlib

    def map_fn(b: Block) -> List[Block]:
        if b.num_rows == 0:
            return [b] * n_parts
        cols = [b.column(k).to_pylist() for k in keys]
        hashed = np.asarray(
            [zlib.crc32(repr(vals).encode()) % n_parts
             for vals in zip(*cols)], dtype=np.int64)
        return [b.filter(pa.array(hashed == j)) for j in range(n_parts)]

    return _map_submit(bundles, map_fn, name)


def _sample_boundaries(bundles, key: str, n_parts: int) -> List[Any]:
    """Sample input blocks to pick range-partition boundaries (reference:
    sort_task_spec.py SortTaskSpec.sample_boundaries)."""

    def sample(b):
        col = b.column(key)
        k = min(100, b.num_rows)
        if k == 0:
            return []
        idx = np.linspace(0, b.num_rows - 1, k, dtype=np.int64)
        return b.take(pa.array(idx)).column(key).to_pylist()

    task = ray_tpu.remote(sample).options(name="SortSample")
    samples: List[Any] = []
    for vals in ray_tpu.get([task.remote(ref) for ref, _ in bundles]):
        samples.extend(vals)
    if not samples:
        return []
    samples.sort()
    return [samples[int(len(samples) * (j + 1) / n_parts) - 1]
            for j in range(n_parts - 1)]


def sort_fn(key: str, descending: bool = False):
    def bulk(bundles, ctx):
        if not bundles:
            return []
        n_out = max(1, len(bundles))
        bounds = _sample_boundaries(bundles, key, n_out)

        def map_task(b):
            col = b.column(key).to_pylist()
            if not bounds:
                assign = np.zeros(b.num_rows, dtype=np.int64)
            else:
                try:
                    assign = np.searchsorted(np.asarray(bounds), col,
                                             side="left")
                except (TypeError, ValueError):
                    assign = np.asarray(
                        [sum(1 for bd in bounds if v > bd) for v in col],
                        dtype=np.int64)
            return [ray_tpu.put(b.filter(pa.array(assign == j)))
                    for j in range(n_out)]

        task = ray_tpu.remote(map_task).options(name="SortMap")
        parts = [ray_tpu.get(task.remote(ref)) for ref, _ in bundles]

        def combine(t):
            order = "descending" if descending else "ascending"
            return t.sort_by([(key, order)])

        out = _reduce_submit(parts, n_out, combine, "SortReduce")
        return list(reversed(out)) if descending else out

    return bulk


# name -> (pyarrow aggregate function, output column suffix); mirrors the
# reference's AggregateFn zoo (python/ray/data/aggregate.py).
_AGGS = {
    "count": ("count", "count()"),
    "sum": ("sum", "sum"),
    "min": ("min", "min"),
    "max": ("max", "max"),
    "mean": ("mean", "mean"),
    "std": ("stddev", "std"),
}


def groupby_agg_fn(key: Optional[str], aggs: List[Tuple[str, Optional[str]]]):
    """aggs: list of (agg_name, on_column).  key=None → global aggregation."""

    def bulk(bundles, ctx):
        n_out = max(1, min(len(bundles), 8)) if key else 1

        def map_task(b):
            if key is None:
                return [ray_tpu.put(b)]
            arr = b.column(key)
            # Deterministic cross-process hash — Python's hash() is salted
            # per process and map tasks run in different workers.
            import zlib

            hashed = np.asarray(
                [zlib.crc32(repr(v).encode()) % n_out
                 for v in arr.to_pylist()], dtype=np.int64)
            return [ray_tpu.put(b.filter(pa.array(hashed == j)))
                    for j in range(n_out)]

        task = ray_tpu.remote(map_task).options(name="AggMap")
        parts = [ray_tpu.get(task.remote(ref)) for ref, _ in bundles]

        def combine(t):
            if t.num_rows == 0:
                return t
            specs = []
            for agg_name, on in aggs:
                pa_fn, _ = _AGGS[agg_name]
                col = on
                if col is None:
                    col = key or t.column_names[0]
                specs.append((col, pa_fn))
            if key is None:
                cols = {}
                for (col, pa_fn), (agg_name, on) in zip(specs, aggs):
                    val = pc.count(t.column(col)) if pa_fn == "count" else \
                        getattr(pc, pa_fn)(t.column(col))
                    label = (f"{agg_name}({on})" if on
                             else f"{agg_name}()")
                    cols[label] = [val.as_py()]
                return pa.table(cols)
            grouped = t.group_by(key).aggregate(specs)
            # normalize pyarrow's "<col>_<fn>" names to "<fn>(<col>)"
            renames = {}
            for (col, pa_fn), (agg_name, on) in zip(specs, aggs):
                src = f"{col}_{pa_fn}"
                dst = f"{agg_name}({on})" if on else f"{agg_name}()"
                renames[src] = dst
            names = [renames.get(n, n) for n in grouped.column_names]
            return grouped.rename_columns(names)

        out = _reduce_submit(parts, n_out, combine, "AggReduce")
        return [(r, m) for r, m in out if m.num_rows > 0] or out[:1]

    return bulk
