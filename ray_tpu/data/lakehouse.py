"""Native Iceberg table reader.

The reference delegates to the `pyiceberg` wheel
(/root/reference/python/ray/data/_internal/datasource/iceberg_datasource.py);
that wheel is not in the TPU image, so the metadata chain is walked
directly — it is just JSON + Avro + Parquet, all of which this package
already decodes natively:

    table/metadata/v{N}.metadata.json   (JSON: snapshots, schemas)
        -> snapshot.manifest-list       (Avro: one row per manifest)
        -> manifest.avro                (Avro: one row per data file)
        -> data/*.parquet               (pyarrow)

One read task per live data file, so a large table fans out across the
cluster exactly like ``read_parquet`` on a directory.  Scope: reads the
current (or a named) snapshot of a v1/v2 table with parquet data files;
positional/equality deletes (v2 row-level deletes) are detected and
rejected with a clear error rather than silently mis-read.
"""

from __future__ import annotations

import json
import os
from typing import Callable, Iterator, List, Optional

from ray_tpu.data import datasource as _ds
from ray_tpu.data.block import Block


def _local_path(uri: str, table_dir: str) -> str:
    """Resolve a metadata-recorded URI to a local path.

    Iceberg metadata records absolute URIs from write time; a copied or
    downloaded table lives somewhere else, so when the recorded path does
    not exist the tail of the URI is re-anchored at the actual table dir
    (matching pyiceberg's behavior for relocated file:// tables).
    """
    path = uri
    for scheme in ("file://", "s3://", "gs://", "abfs://"):
        if path.startswith(scheme):
            path = path[len(scheme):]
            if not path.startswith("/"):
                path = "/" + path
            break
    if os.path.exists(path):
        return path
    # re-anchor: .../<table>/{metadata,data}/... under table_dir
    parts = path.split("/")
    for anchor in ("metadata", "data"):
        if anchor in parts:
            idx = len(parts) - 1 - parts[::-1].index(anchor)
            cand = os.path.join(table_dir, *parts[idx:])
            if os.path.exists(cand):
                return cand
    return path  # let the open() raise a precise FileNotFoundError


def _table_metadata(table_dir: str) -> dict:
    meta_dir = os.path.join(table_dir, "metadata")
    hint = os.path.join(meta_dir, "version-hint.text")
    if os.path.exists(hint):
        with open(hint) as fh:
            v = fh.read().strip()
        path = os.path.join(meta_dir, f"v{v}.metadata.json")
    else:
        def version_of(f: str) -> int:
            # numeric sort on the LEADING digit run only: names are
            # v{N}.metadata.json or {NNNNN}-{uuid}.metadata.json, and
            # digits inside the uuid must not contaminate the version
            stem = f[:-len(".metadata.json")].lstrip("v")
            n = 0
            while n < len(stem) and stem[n].isdigit():
                n += 1
            return int(stem[:n]) if n else -1

        cands = sorted(
            (f for f in os.listdir(meta_dir)
             if f.endswith(".metadata.json")), key=version_of)
        if not cands:
            raise FileNotFoundError(
                f"no *.metadata.json under {meta_dir}: not an Iceberg table")
        path = os.path.join(meta_dir, cands[-1])
    with open(path) as fh:
        return json.load(fh)


def iceberg_tasks(table_dir: str, parallelism: int,
                  snapshot_id: Optional[int] = None,
                  columns: Optional[List[str]] = None) -> List[Callable]:
    table_dir = os.path.abspath(table_dir)
    meta = _table_metadata(table_dir)
    snapshots = meta.get("snapshots", [])
    if snapshot_id is None:
        snapshot_id = meta.get("current-snapshot-id")
    snap = next(
        (s for s in snapshots if s.get("snapshot-id") == snapshot_id), None)
    if snap is None:
        if snapshot_id in (None, -1):
            return []  # empty table: metadata exists, no snapshot yet
        raise ValueError(
            f"snapshot {snapshot_id} not found in {table_dir} "
            f"(have: {[s.get('snapshot-id') for s in snapshots]})")

    # manifest list -> manifests -> live parquet data files
    mlist = _local_path(snap["manifest-list"], table_dir)
    data_files: List[str] = []
    for mrow in _ds.read_avro_rows(mlist):
        manifest = _local_path(mrow["manifest_path"], table_dir)
        if mrow.get("content", 0) == 1:
            raise NotImplementedError(
                f"{manifest}: delete manifest (v2 row-level deletes) — "
                "compact the table (rewrite_data_files) before reading")
        for entry in _ds.read_avro_rows(manifest):
            if entry.get("status") == 2:  # DELETED entry
                continue
            df = entry.get("data_file") or {}
            if df.get("content", 0) != 0:  # position/equality deletes
                raise NotImplementedError(
                    f"{manifest}: delete files present — compact first")
            data_files.append(_local_path(df["file_path"], table_dir))

    def read_file(f: str) -> Iterator[Block]:
        import pyarrow.parquet as pq

        yield pq.read_table(f, columns=columns)

    return _ds._file_tasks(data_files, parallelism, read_file)
