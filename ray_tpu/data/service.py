"""ray_tpu.data.service: the client face of the disaggregated data service.

Counterpart of tf.data service's client API (PAPERS.md 2210.14826:
``register_dataset`` + ``from_dataset_id``): a driver registers a NAMED
dataset job once; any number of trainers — same driver or other drivers on
the cluster — attach to a split and iterate batches produced by the shared
elastic worker tier (coordination, failover, caching:
_private/data_service.py).

    service.register("imagenet", ds, num_splits=4)
    it = service.attach("imagenet", split_id=0)   # a DataIterator
    for epoch in range(epochs):
        for batch in it.iter_batches(batch_size=256):
            ...

Each ``__iter__`` over the attached iterator is one EPOCH: the coordinator
holds an epoch barrier (epoch e+1 starts when every live consumer finished
epoch e) and serves epoch >= 1 from the first-epoch cache where it fits
``RTPU_DATA_CACHE_BYTES``.

Only ``Read``/``InputData`` sources followed by ``OneToOne`` chains can be
registered — barrier ops (shuffle/sort/join/...) need a materialization
boundary, so ``.materialize()`` first and register the result.
"""

from __future__ import annotations

from typing import Any, List, Optional

import cloudpickle

import ray_tpu
from ray_tpu._private.data_service import (
    COORDINATOR_NAME,
    DataServiceCoordinator,
)
from ray_tpu.data import logical as L
from ray_tpu.data.context import DataContext
from ray_tpu.data.iterator import DataIterator


def _decompose(plan: L.LogicalPlan) -> dict:
    """Lower a dataset plan into the service's chunked job spec: the source
    defines the chunks (one per read task / input bundle — the unit of
    lease, failover, and caching), the OneToOne chain runs inline on the
    feeding workers."""
    if not plan.ops:
        raise ValueError("cannot register an empty dataset plan")
    head, rest = plan.ops[0], plan.ops[1:]
    spec: dict = {"target_bytes": DataContext.get_current()
                  .target_max_block_size}
    if isinstance(head, L.Read):
        spec["kind"] = "read"
        spec["tasks"] = [cloudpickle.dumps(t) for t in head.read_tasks]
    elif isinstance(head, L.InputData):
        spec["kind"] = "input"
        spec["bundles"] = list(head.bundles)
    else:
        raise ValueError(
            f"data service jobs must start from a Read or InputData source, "
            f"got {type(head).__name__} ({head.name})")
    stages: List[dict] = []
    for op in rest:
        if not isinstance(op, L.OneToOne):
            raise ValueError(
                f"data service jobs support per-chunk (OneToOne) transforms "
                f"only; {op.name} ({type(op).__name__}) is a barrier op — "
                f"call .materialize() before register() to fold it in")
        if op.compute == "actors":
            stages.append({
                "kind": "actors", "name": op.name,
                "udf": cloudpickle.dumps(
                    (op.udf_cls, op.udf_args, op.udf_kwargs)),
                "make_fn": cloudpickle.dumps(op.block_fn)})
        else:
            stages.append({"kind": "tasks", "name": op.name,
                           "fn": cloudpickle.dumps(op.block_fn)})
    spec["stages"] = stages
    return spec


def _coordinator(create: bool = True):
    """Get the cluster's dispatcher actor, creating it on first use.  The
    create race (two drivers registering concurrently) resolves by retrying
    the named lookup."""
    try:
        return ray_tpu.get_actor(COORDINATOR_NAME)
    except ValueError:
        if not create:
            raise
    try:
        coord = ray_tpu.remote(DataServiceCoordinator).options(
            name=COORDINATOR_NAME, num_cpus=0, max_concurrency=32).remote()
        ray_tpu.get(coord.list_jobs.remote())  # force creation/readiness
        return coord
    except Exception:
        return ray_tpu.get_actor(COORDINATOR_NAME)


def register(name: str, dataset: Any, num_splits: int = 1, *,
             min_workers: Optional[int] = None,
             max_workers: Optional[int] = None) -> dict:
    """Register ``dataset`` as the named job ``name`` served by the data
    service.  Splits are disjoint chunk sets (chunk i -> split i % n); the
    worker pool scales between min/max (defaults:
    RTPU_DATA_WORKERS_MIN/MAX)."""
    spec = _decompose(dataset._plan)
    coord = _coordinator()
    return ray_tpu.get(coord.register_job.remote(
        name, cloudpickle.dumps(spec), num_splits,
        min_workers, max_workers))


class _ServiceSplit:
    """Re-iterable bundle source for one split: each ``__iter__`` is one
    epoch (the coordinator's barrier gates when it actually starts)."""

    def __init__(self, coord, name: str, split: int, consumer_id: str):
        self._coord = coord
        self._name = name
        self._split = split
        self._cid = consumer_id
        self._epoch = 0

    def __iter__(self):
        epoch = self._epoch
        self._epoch += 1
        while True:
            resp = ray_tpu.get(self._coord.next_bundles.remote(
                self._name, self._split, self._cid, epoch))
            if resp.get("eof"):
                return
            if resp.get("pending"):
                continue  # server already blocked its timeout slice
            for ref, meta in resp["bundles"]:
                yield (ref, meta)


def attach(name: str, split_id: int) -> DataIterator:
    """Attach to one split of a registered job; returns a ``DataIterator``
    (iter_batches / iter_rows / iter_jax_batches...).  The consumer lease
    is refreshed by consumption and expires after RTPU_DATA_LEASE_S of
    silence."""
    coord = _coordinator(create=False)
    lease = ray_tpu.get(coord.attach.remote(name, split_id))
    return DataIterator(_ServiceSplit(coord, name, split_id,
                                      lease["consumer_id"]))


def unregister(name: str) -> bool:
    """Stop a job: kill its workers, drop its plan and cache pins."""
    coord = _coordinator(create=False)
    return ray_tpu.get(coord.unregister.remote(name))


def scale(name: str, min_workers: Optional[int] = None,
          max_workers: Optional[int] = None) -> dict:
    """Adjust a job's worker-pool bounds (driver-side twin of
    ``rtpu data scale``)."""
    coord = _coordinator(create=False)
    return ray_tpu.get(coord.scale.remote(name, min_workers, max_workers))


def describe(name: str) -> dict:
    """Live status snapshot of one job (splits, workers, queue depths,
    cache hit/miss, failovers)."""
    coord = _coordinator(create=False)
    return ray_tpu.get(coord.stats.remote(name))


def jobs() -> list:
    """Status snapshots of every registered job."""
    try:
        coord = _coordinator(create=False)
    except ValueError:
        return []
    return ray_tpu.get(coord.list_jobs.remote())
