"""Native video frame reader (AVI containers).

The reference delegates to `opencv-python`
(/root/reference/python/ray/data/_internal/datasource/video_datasource.py);
cv2 is not in the TPU image, so the two codecs that matter for ML corpora
shipped as AVI are decoded directly:

  * MJPEG ('00dc' chunks that are whole JPEGs) — decoded with PIL, which
    IS in the image (it already backs read_images)
  * uncompressed BI_RGB DIB ('00db' chunks) — bottom-up BGR rows

Each video file is one read task emitting one row per frame
({"frame": HxWx3 uint8 RGB, "frame_index": i, "path": f}) — frames from
one file stay ordered, files fan out across the cluster.  Other codecs
(H.264 etc.) need a real decoder: if cv2 happens to be importable it is
used, otherwise the error names the codec and the wheel.
"""

from __future__ import annotations

import io
import struct
from typing import Callable, Iterator, List, Tuple

import numpy as np

from ray_tpu.data import block as block_mod
from ray_tpu.data import datasource as _ds
from ray_tpu.data.block import Block


def _riff_chunks(buf: bytes, start: int, end: int):
    """Yield (fourcc, payload_start, payload_size) for a chunk run."""
    pos = start
    while pos + 8 <= end:
        fourcc = buf[pos:pos + 4]
        (size,) = struct.unpack_from("<I", buf, pos + 4)
        yield fourcc, pos + 8, size
        pos += 8 + size + (size & 1)  # chunks are word-aligned


def _parse_avi(buf: bytes) -> Tuple[List[bytes], dict]:
    """Return (video frame chunks in stream order, stream format info)."""
    if buf[:4] != b"RIFF" or buf[8:12] != b"AVI ":
        raise ValueError("not an AVI (RIFF/'AVI ') file")
    frames: List[bytes] = []
    fmt = {"compression": None, "width": 0, "height": 0, "bpp": 24}
    # strf binds to the PRECEDING strh's stream type: in a file whose
    # first stream is audio, the first strf is a WAVEFORMATEX, not the
    # video BITMAPINFOHEADER
    cur_stream = {"is_video": False}

    def walk(start: int, end: int):
        for fourcc, off, size in _riff_chunks(buf, start, end):
            if fourcc == b"LIST":
                ltype = buf[off:off + 4]
                if ltype in (b"hdrl", b"movi", b"strl", b"rec "):
                    walk(off + 4, off + size)
            elif fourcc == b"strh":
                cur_stream["is_video"] = buf[off:off + 4] == b"vids"
            elif fourcc == b"strf" and cur_stream["is_video"] \
                    and fmt["compression"] is None:
                # BITMAPINFOHEADER: width i32 @4, height i32 @8,
                # bitcount u16 @14, compression u32 @16
                if size >= 20:
                    fmt["width"] = struct.unpack_from("<i", buf, off + 4)[0]
                    fmt["height"] = struct.unpack_from("<i", buf, off + 8)[0]
                    fmt["bpp"] = struct.unpack_from("<H", buf, off + 14)[0]
                    fmt["compression"] = buf[off + 16:off + 20]
            elif fourcc[2:] in (b"dc", b"db") and size > 0:
                frames.append(buf[off:off + size])

    walk(12, len(buf))
    return frames, fmt


def _decode_frame(chunk: bytes, fmt: dict) -> np.ndarray:
    if chunk[:2] == b"\xff\xd8":  # JPEG SOI: MJPEG frame
        from PIL import Image

        return np.asarray(Image.open(io.BytesIO(chunk)).convert("RGB"))
    comp = fmt.get("compression") or b"\x00\x00\x00\x00"
    if comp == b"\x00\x00\x00\x00" and fmt["bpp"] == 24:
        w, h = fmt["width"], abs(fmt["height"])
        stride = (w * 3 + 3) & ~3  # DIB rows pad to 4 bytes
        rows = np.frombuffer(chunk[: stride * h], np.uint8)
        rows = rows.reshape(h, stride)[:, : w * 3].reshape(h, w, 3)
        if fmt["height"] > 0:  # positive height = bottom-up
            rows = rows[::-1]
        return rows[..., ::-1].copy()  # BGR -> RGB
    name = comp.decode("ascii", "replace")
    raise NotImplementedError(
        f"AVI codec {name!r} needs a real decoder: install "
        "`opencv-python` (used automatically when importable) or "
        "transcode to MJPEG")


def video_tasks(paths, parallelism: int) -> List[Callable]:
    files = _ds.expand_paths(paths, [".avi", ".mp4", ".mkv", ".mov"])

    def _emit(frames: List[np.ndarray], first_idx: int, f: str) -> Block:
        # frames of one clip share a shape: stack into a device-ready
        # tensor column (same layout as read_images)
        return block_mod.from_batch({
            "frame": np.stack(frames),
            "frame_index": np.arange(first_idx, first_idx + len(frames)),
            "path": np.array([f] * len(frames)),
        })

    def read_file(f: str) -> Iterator[Block]:
        if not f.lower().endswith(".avi"):
            yield from _cv2_frames(f)
            return
        with open(f, "rb") as fh:
            buf = fh.read()
        chunks, fmt = _parse_avi(buf)
        pend: List[np.ndarray] = []
        for i, chunk in enumerate(chunks):
            pend.append(_decode_frame(chunk, fmt))
            if len(pend) >= 64:  # bound block size for long clips
                yield _emit(pend, i + 1 - len(pend), f)
                pend = []
        if pend:
            yield _emit(pend, len(chunks) - len(pend), f)

    def _cv2_frames(f: str) -> Iterator[Block]:
        try:
            import cv2
        except ImportError as e:
            raise ImportError(
                f"{f}: non-AVI containers need `opencv-python` "
                "(not in the TPU image); AVI/MJPEG decodes natively"
            ) from e
        cap = cv2.VideoCapture(f)
        pend, i = [], 0
        while True:
            ok, frame = cap.read()
            if not ok:
                break
            pend.append(frame[..., ::-1].copy())
            i += 1
            if len(pend) >= 64:
                yield _emit(pend, i - len(pend), f)
                pend = []
        cap.release()
        if pend:
            yield _emit(pend, i - len(pend), f)

    return _ds._file_tasks(files, parallelism, read_file)
