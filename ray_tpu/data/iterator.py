"""DataIterator: batched consumption with prefetch and device hand-off.

Counterpart of the reference's DataIterator + block batching
(/root/reference/python/ray/data/iterator.py:71,
_internal/block_batching/iter_batches.py): slices a stream of blocks into
fixed-size batches with format conversion, an optional local shuffle buffer,
and background prefetch.  ``iter_jax_batches`` double-buffers
``jax.device_put`` so host→HBM DMA of batch N+1 overlaps the step on batch N
— the TPU input pipeline the reference delegates to torch DataLoaders.
"""

from __future__ import annotations

import queue as queue_mod
import threading
from typing import Any, Callable, Dict, Iterator, List, Optional

import numpy as np

import ray_tpu
from ray_tpu.data import block as block_mod
from ray_tpu.data.block import Block


class _BundleIterable:
    """Re-runnable source of (ref, meta) bundles from a dataset plan."""

    def __init__(self, make_iter: Callable[[], Iterator]):
        self._make_iter = make_iter

    def __iter__(self):
        return self._make_iter()


def _batch_blocks(blocks: Iterator[Block], batch_size: Optional[int],
                  drop_last: bool) -> Iterator[Block]:
    if batch_size is None:
        yield from (b for b in blocks if b.num_rows)
        return
    buf: List[Block] = []
    have = 0
    for b in blocks:
        while b.num_rows:
            take = min(batch_size - have, b.num_rows)
            buf.append(b.slice(0, take))
            b = b.slice(take, b.num_rows - take)
            have += take
            if have == batch_size:
                yield block_mod.concat(buf)
                buf, have = [], 0
    if buf and not drop_last:
        yield block_mod.concat(buf)


def _shuffled(blocks: Iterator[Block], buffer_rows: int,
              seed: Optional[int]) -> Iterator[Block]:
    """Local shuffle buffer (reference: local_shuffle_buffer_size)."""
    import pyarrow as pa

    rng = np.random.default_rng(seed)
    buf: List[Block] = []
    have = 0
    for b in blocks:
        buf.append(b)
        have += b.num_rows
        if have >= buffer_rows:
            tbl = block_mod.concat(buf)
            perm = rng.permutation(tbl.num_rows)
            yield tbl.take(pa.array(perm))
            buf, have = [], 0
    if buf:
        tbl = block_mod.concat(buf)
        perm = rng.permutation(tbl.num_rows)
        yield tbl.take(pa.array(perm))


def _prefetched(it: Iterator, depth: int) -> Iterator:
    """Run the upstream iterator on a thread, keep ``depth`` items ready.
    The feed thread watches a stop flag so an abandoned consumer (early
    ``break`` from a train loop) releases the upstream pipeline instead of
    blocking forever on a full queue."""
    q: queue_mod.Queue = queue_mod.Queue(maxsize=depth)
    DONE, ERR = object(), object()
    stop = threading.Event()

    def offer(item) -> bool:
        while not stop.is_set():
            try:
                q.put(item, timeout=0.1)
                return True
            except queue_mod.Full:
                continue
        return False

    def feed():
        try:
            for item in it:
                if not offer(item):
                    return
            offer(DONE)
        except BaseException as e:  # noqa: BLE001 — propagate to consumer
            offer((ERR, e))

    t = threading.Thread(target=feed, daemon=True)
    t.start()
    try:
        while True:
            item = q.get()
            if item is DONE:
                return
            if (isinstance(item, tuple) and len(item) == 2
                    and item[0] is ERR):
                raise item[1]
            yield item
    finally:
        stop.set()


class DataIterator:
    def __init__(self, bundles: Any):
        self._bundles = bundles

    def _blocks(self) -> Iterator[Block]:
        # Fetch on a feed thread with a small window so the NEXT block's
        # store get overlaps consumption of the current one — strictly
        # serial get-then-consume left the consumer idle for every fetch
        # round trip.
        def fetch():
            for ref, _meta in self._bundles:
                yield ray_tpu.get(ref)

        return _prefetched(fetch(), 3)

    def iter_batches(self, *, batch_size: Optional[int] = 256,
                     batch_format: str = "numpy",
                     drop_last: bool = False,
                     local_shuffle_buffer_size: Optional[int] = None,
                     local_shuffle_seed: Optional[int] = None,
                     prefetch_batches: int = 2) -> Iterator[Any]:
        blocks = self._blocks()
        if local_shuffle_buffer_size:
            blocks = _shuffled(blocks, local_shuffle_buffer_size,
                               local_shuffle_seed)
        batches = _batch_blocks(blocks, batch_size, drop_last)
        out = (block_mod.to_batch(b, batch_format) for b in batches)
        if prefetch_batches and prefetch_batches > 0:
            out = _prefetched(out, prefetch_batches)
        return out

    def iter_rows(self) -> Iterator[Dict[str, Any]]:
        for b in self._blocks():
            yield from block_mod.rows_of(b)

    def iter_jax_batches(self, *, batch_size: Optional[int] = 256,
                         sharding=None, dtype=None,
                         drop_last: bool = True,
                         **kw) -> Iterator[Any]:
        """numpy batches → jax.Arrays on device, double-buffered so the DMA
        of the next batch overlaps the current step."""
        import jax

        def to_device(batch):
            def put(x):
                if dtype is not None and np.issubdtype(x.dtype, np.floating):
                    x = x.astype(dtype)
                if sharding is not None:
                    return jax.device_put(x, sharding)
                return jax.device_put(x)

            return {k: put(v) for k, v in batch.items()}

        host = self.iter_batches(batch_size=batch_size, batch_format="numpy",
                                 drop_last=drop_last, **kw)
        dev = (to_device(b) for b in host)
        return _prefetched(dev, 2)

    def iter_torch_batches(self, *, batch_size: Optional[int] = 256,
                           dtypes=None, device: str = "cpu",
                           **kw) -> Iterator[Any]:
        import torch

        def convert(batch):
            out = {}
            for k, v in batch.items():
                t = torch.as_tensor(v)
                if dtypes is not None:
                    t = t.to(dtypes if not isinstance(dtypes, dict)
                             else dtypes.get(k, t.dtype))
                out[k] = t.to(device)
            return out

        host = self.iter_batches(batch_size=batch_size,
                                 batch_format="numpy", **kw)
        return (convert(b) for b in host)

    @staticmethod
    def _densify(v):
        """Object columns (arrow variable lists) → stacked dense arrays
        (tf/torch reject object dtype)."""
        import numpy as np

        arr = np.asarray(v)
        if arr.dtype == object:
            try:
                return np.stack([np.asarray(x) for x in arr])
            except ValueError:
                return arr  # genuinely ragged: caller's problem
        return arr

    def iter_tf_batches(self, *, batch_size: Optional[int] = 256,
                        **kw) -> Iterator[Any]:
        """numpy batches as dicts of tf.Tensors (reference:
        iterator.iter_tf_batches)."""
        import tensorflow as tf

        for batch in self.iter_batches(batch_size=batch_size,
                                       batch_format="numpy", **kw):
            yield {k: tf.convert_to_tensor(self._densify(v))
                   for k, v in batch.items()}

    def to_tf(self, feature_columns, label_columns, *,
              batch_size: int = 256, **kw):
        """A tf.data.Dataset of (features, labels) (reference:
        dataset.to_tf). Column args may be a name or list of names."""
        import tensorflow as tf

        feats = ([feature_columns] if isinstance(feature_columns, str)
                 else list(feature_columns))
        labels = ([label_columns] if isinstance(label_columns, str)
                  else list(label_columns))

        def pick(batch, cols):
            if len(cols) == 1:
                return self._densify(batch[cols[0]])
            return {c: self._densify(batch[c]) for c in cols}

        try:
            probe = next(iter(self.iter_batches(
                batch_size=2, batch_format="numpy", **kw)))
        except StopIteration:
            raise ValueError(
                "to_tf: dataset is empty (no batches to infer the "
                "tf.TensorSpec from)") from None
        probe = {k: self._densify(v) for k, v in probe.items()}

        def spec_of(cols):
            if len(cols) == 1:
                v = probe[cols[0]]
                return tf.TensorSpec(
                    shape=(None,) + v.shape[1:], dtype=v.dtype)
            return {c: tf.TensorSpec(shape=(None,) + probe[c].shape[1:],
                                     dtype=probe[c].dtype) for c in cols}

        def gen():
            for batch in self.iter_batches(batch_size=batch_size,
                                           batch_format="numpy", **kw):
                yield pick(batch, feats), pick(batch, labels)

        return tf.data.Dataset.from_generator(
            gen, output_signature=(spec_of(feats), spec_of(labels)))

    def materialize(self):
        from ray_tpu.data import logical as L
        from ray_tpu.data.dataset import MaterializedDataset

        bundles = list(self._bundles)
        return MaterializedDataset(
            L.LogicalPlan([L.InputData(name="Input", bundles=bundles)]),
            bundles)
