"""Public API: init/shutdown/remote/get/put/wait/kill/cancel/...

Counterpart of the reference's top-level API surface
(/root/reference/python/ray/_private/worker.py: init :1330, get/put/wait, and
python/ray/__init__.py re-exports).
"""

from __future__ import annotations

import atexit
import glob
import inspect
import os
import time
from typing import Optional, Sequence, Union

from ray_tpu._private import worker as worker_mod
from ray_tpu._private.node import Node
from ray_tpu._private.worker import WorkerContext, global_worker
from ray_tpu.core.actor import ActorClass, ActorHandle
from ray_tpu.core.object_ref import ObjectRef
from ray_tpu.core.remote_function import RemoteFunction

_global_node: Optional[Node] = None


def init(
    address: Optional[str] = None,
    *,
    resources: Optional[dict] = None,
    object_store_memory: Optional[int] = None,
    num_cpus: Optional[float] = None,
    num_tpus: Optional[float] = None,
    min_workers: Optional[int] = None,  # default: 2 head / 0 attached
    max_workers: Optional[int] = None,
    ignore_reinit_error: bool = False,
    _existing_node: Optional["Node"] = None,
) -> "Node":
    """Start a cluster (or attach the driver to an existing head node —
    used by cluster_utils.Cluster, which owns that node's lifecycle)."""
    global _global_node
    if worker_mod.is_initialized():
        if ignore_reinit_error:
            return _global_node
        raise RuntimeError("ray_tpu.init() called twice; pass "
                           "ignore_reinit_error=True to ignore")
    res = dict(resources or {})
    if num_cpus is not None:
        res["CPU"] = float(num_cpus)
    if num_tpus is not None:
        res["TPU"] = float(num_tpus)
    address = address or os.environ.get("RAY_TPU_ADDRESS")
    if address is not None and address.startswith("rtpu://"):
        # Remote-driver client mode (reference: ray://): no local node at
        # all — every operation proxies over TCP (util/client).
        from ray_tpu.util.client import connect_client

        ctx = connect_client(address)
        worker_mod.set_global_worker(ctx)
        atexit.register(shutdown)
        return None
    if address is not None:
        # Attach this process to an existing cluster as a driver: start a
        # local (non-head) node joined through the head's gcs.sock
        # (reference: ray.init(address=...) connecting a driver,
        # python/ray/_private/worker.py:1330). By default the attached
        # driver contributes no resources — its tasks spill to the
        # cluster's nodes — so a transient job driver doesn't distort the
        # cluster's capacity.
        if address == "auto":
            address = _find_gcs_address()
        node = Node(
            head=False,
            gcs_address=address,
            resources=res or {"CPU": 0.0, "TPU": 0.0},
            object_store_memory=object_store_memory,
            min_workers=0 if min_workers is None else min_workers,
            max_workers=max_workers,
        )
    else:
        node = _existing_node or Node(
            resources=res or None,
            object_store_memory=object_store_memory,
            min_workers=2 if min_workers is None else min_workers,
            max_workers=max_workers,
        )
    _global_node = node
    _attach_driver(node)
    if _existing_node is None:
        atexit.register(shutdown)
    return node


def _find_gcs_address() -> str:
    """Newest LIVE session's gcs.sock (address="auto"): crashed clusters
    leave stale sockets on disk, so probe before choosing."""
    import socket as socket_mod

    socks = sorted(glob.glob("/tmp/ray_tpu/session_*/gcs.sock"),
                   key=os.path.getmtime, reverse=True)
    for path in socks:
        s = socket_mod.socket(socket_mod.AF_UNIX, socket_mod.SOCK_STREAM)
        s.settimeout(1.0)
        try:
            s.connect(path)
            return path
        except OSError:
            continue
        finally:
            s.close()
    raise ConnectionError(
        "address='auto' found no live ray_tpu cluster "
        "(no connectable /tmp/ray_tpu/session_*/gcs.sock)")


def _attach_driver(node: Node):
    """Wire the driver-side WorkerContext to a (head) node's services."""
    scheduler = node.scheduler

    def driver_rpc(method: str, params: dict):
        return scheduler._handle_rpc(method, params)

    ctx = WorkerContext(
        mode="driver",
        store=node.new_store_client(),
        submit_fn=scheduler.submit,
        rpc_fn=driver_rpc,
        worker_id=os.urandom(8),  # so runtime-context ids are non-empty
        node=node,
        seal_notify_fn=scheduler.note_sealed,
        gcs_address=node.gcs_address,
    )
    ctx.init_direct(driver_rpc)
    # Worker print()/stderr lines from every node surface on the driver's
    # stdout, prefixed with the producing worker (reference: log monitor ->
    # GCS pubsub -> driver).  RTPU_LOG_TO_DRIVER=0 disables.
    if os.environ.get("RTPU_LOG_TO_DRIVER", "1") != "0":
        import sys as _sys

        def _print_worker_lines(lines):
            for line in lines:
                print(line, file=_sys.stdout, flush=True)

        scheduler.log_sink = _print_worker_lines
    worker_mod.set_global_worker(ctx)
    # Driver-side sampling profiler (workers start theirs in worker_main):
    # the driver's own CPU time shows up in "continuous" profiles too.
    from ray_tpu._private import profiling

    profiling.ensure_sampler()
    return ctx


def shutdown():
    global _global_node
    if _global_node is not None:
        node, _global_node = _global_node, None
        # Final profile flush needs the driver context: stop the sampler
        # BEFORE detaching it (a later init() resumes via ensure_sampler).
        from ray_tpu._private import profiling
        from ray_tpu._private import ref_tracker

        profiling.shutdown_sampler(flush=True)
        ref_tracker.shutdown_flusher(flush=False)  # driver refs die here
        ref_tracker.clear()
        worker_mod.set_global_worker(None)
        node.shutdown()
    else:
        # client mode: just drop the TCP connection
        ctx = worker_mod.global_worker_or_none()
        if ctx is not None:
            worker_mod.set_global_worker(None)
            close = getattr(ctx, "close", None)
            if close is not None:
                close()


def is_initialized() -> bool:
    return worker_mod.is_initialized()


def remote(*args, **options):
    """Decorator turning a function into a RemoteFunction or a class into an
    ActorClass.  Usable bare (``@remote``) or with options
    (``@remote(num_tpus=1)``)."""

    def make(obj):
        if inspect.isclass(obj):
            return ActorClass(obj, options)
        return RemoteFunction(obj, options)

    if len(args) == 1 and not options and (
        callable(args[0]) or inspect.isclass(args[0])
    ):
        return make(args[0])
    if args:
        raise TypeError("remote() takes keyword options only")
    return make


def get(
    refs: Union[ObjectRef, Sequence[ObjectRef]],
    *,
    timeout: Optional[float] = None,
):
    worker = global_worker()
    if isinstance(refs, ObjectRef):
        return worker.get_object(refs, timeout=timeout)
    if isinstance(refs, (list, tuple)):
        # The timeout bounds the whole call, not each ref.
        deadline = None if timeout is None else time.monotonic() + timeout
        out = []
        for r in refs:
            remaining = (None if deadline is None
                         else max(0.0, deadline - time.monotonic()))
            out.append(worker.get_object(r, timeout=remaining))
        return out
    raise TypeError(f"get expects ObjectRef or list, got {type(refs)}")


def put(value) -> ObjectRef:
    return global_worker().put_object(value)


def wait(
    refs: Sequence[ObjectRef],
    *,
    num_returns: int = 1,
    timeout: Optional[float] = None,
    fetch_local: bool = True,
):
    if isinstance(refs, ObjectRef):
        raise TypeError("wait expects a list of ObjectRefs")
    if num_returns > len(refs):
        raise ValueError("num_returns exceeds the number of refs")
    return global_worker().wait(refs, num_returns, timeout, fetch_local)


def kill(actor: ActorHandle, *, no_restart: bool = True):
    if not isinstance(actor, ActorHandle):
        raise TypeError("kill expects an ActorHandle")
    global_worker().rpc("kill_actor", {"actor_id": actor.actor_id,
                                       "no_restart": no_restart})


def cancel(ref: ObjectRef, *, force: bool = False):
    # A return object id is task_id (16B) + return index (4B).
    task_id = ref.binary()[:16]
    global_worker().rpc("cancel", {"task_id": task_id, "force": force})


def get_actor(name: str) -> ActorHandle:
    info = global_worker().rpc("get_actor_by_name", {"name": name})
    if info is None:
        raise ValueError(f"no live actor named {name!r}")
    return ActorHandle(info["actor_id"], info["class_name"])


def nodes() -> list:
    """Cluster node table (reference: ray.nodes()): one dict per node with
    NodeID, Alive, Resources, and head flag."""
    raw = global_worker().rpc("list_nodes", {})
    return [{"NodeID": n["node_id"].hex(), "Alive": n["alive"],
             "Resources": n["resources"], "Available": n["available"],
             "IsHead": n["is_head"]} for n in raw]


def cluster_resources() -> dict:
    """Total resources summed over all live nodes."""
    total: dict = {}
    for n in global_worker().rpc("list_nodes", {}):
        if n["alive"]:
            for k, v in n["resources"].items():
                total[k] = total.get(k, 0) + v
    return total


def available_resources() -> dict:
    """Currently-available resources summed over all live nodes.

    The local node's view is authoritative (live counters); peers are as
    of their last heartbeat."""
    local = global_worker().rpc("cluster_state", {})
    avail = dict(local["available_resources"])
    local_id = local.get("node_id")
    for n in global_worker().rpc("list_nodes", {}):
        if n["alive"] and n["node_id"] != local_id:
            for k, v in n["available"].items():
                avail[k] = avail.get(k, 0) + v
    return avail


class RuntimeContext:
    def __init__(self, worker: WorkerContext):
        self._worker = worker

    @property
    def was_current_actor_reconstructed(self) -> bool:
        return False

    def get_actor_id(self) -> Optional[str]:
        aid = self._worker.current_actor_id
        return aid.hex() if aid else None

    def get_task_id(self) -> Optional[str]:
        tid = self._worker.current_task_id
        return tid.hex() if tid else None

    def node_id_hex(self) -> str:
        """Hex id of the node this process runs on."""
        import os

        if self._worker.node is not None:  # driver
            return self._worker.node.node_id.hex()
        return os.environ.get("RAY_TPU_NODE_ID", "")

    def get_worker_id(self) -> str:
        return self._worker.worker_id.hex()


def get_runtime_context() -> RuntimeContext:
    return RuntimeContext(global_worker())
