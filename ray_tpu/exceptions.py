"""User-facing exceptions (counterpart of /root/reference/python/ray/exceptions.py)."""

from __future__ import annotations


class RayTpuError(Exception):
    """Base class for framework errors."""


class TaskError(RayTpuError):
    """A remote task raised; re-raised at ``get`` with the remote traceback."""

    def __init__(self, cause: BaseException, remote_traceback: str = ""):
        self.cause = cause
        self.remote_traceback = remote_traceback
        super().__init__(
            f"{type(cause).__name__}: {cause}\n"
            f"--- remote traceback ---\n{remote_traceback}"
        )


class WorkerCrashedError(RayTpuError):
    """The worker executing the task died unexpectedly."""


class OutOfMemoryError(WorkerCrashedError):
    """The worker was killed by the node memory monitor (reference:
    ray.exceptions.OutOfMemoryError raised by the raylet's worker-killing
    policy under memory pressure).  The message carries provenance: the
    worker's RSS at kill time and the node usage that tripped the
    threshold."""


class ActorDiedError(RayTpuError):
    """The actor owning this method call has died."""


class ActorUnavailableError(RayTpuError):
    """The actor exists but cannot currently serve calls."""


class GetTimeoutError(RayTpuError, TimeoutError):
    """``get`` exceeded its timeout."""


class StoreDiedError(RayTpuError):
    """The local shm store daemon stayed unreachable past the reconnect
    budget (``RTPU_STORE_RETRY_S``).

    ``StoreClient`` transparently redials through daemon restarts (the
    node supervisor respawns a crashed daemon on the same socket within
    a second), so this only surfaces when supervision itself is gone —
    an in-flight task failing with it is retried like any worker crash,
    and lost objects recover via lineage.
    """


class ObjectLostError(RayTpuError):
    """Object is no longer available (lost with its node, or evicted).

    ``oid`` (when known) identifies the lost object so an owner holding
    its lineage can re-execute the producing task (reference:
    src/ray/core_worker/object_recovery_manager.h:43).
    """

    def __init__(self, *args, oid: bytes = b""):
        super().__init__(*args)
        self.oid = oid

    def __reduce__(self):
        if type(self) is not ObjectLostError:
            # dynamic TaskError duals (serialization._as_raisable) subclass
            # this — they must keep their own pickling, not collapse to a
            # bare ObjectLostError
            return super().__reduce__()
        return (_rebuild_object_lost, (self.args, self.oid))


def _rebuild_object_lost(args, oid):
    return ObjectLostError(*args, oid=oid)


class TaskCancelledError(RayTpuError):
    """The task was cancelled before/while running."""


class RuntimeEnvSetupError(RayTpuError):
    """Runtime environment could not be set up for the task/actor."""


class PlacementGroupUnavailableError(RayTpuError):
    """Placement group resources could not be reserved."""
