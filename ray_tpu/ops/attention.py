"""Fused attention for TPU: Pallas flash-attention forward + custom VJP.

Net-new relative to the reference, which delegates attention math to
torch/vLLM (SURVEY.md §2.4): here it is a first-class op.  The forward pass
is a Pallas kernel — online-softmax over KV blocks, O(seq) memory, bf16
inputs with f32 accumulation on the MXU; the backward pass rematerializes
attention with standard XLA ops (saves only out + logsumexp from forward).

Layout: (batch*heads, seq, head_dim) inside the kernel; the public API takes
(batch, seq, heads, head_dim) and handles GQA by repeating KV heads.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # TPU-specific memory spaces; absent when running CPU interpret mode
    from jax.experimental.pallas import tpu as pltpu

    _VMEM = pltpu.VMEM
except ImportError:  # pragma: no cover
    pltpu = None
    _VMEM = None

DEFAULT_BLOCK_Q = 256
DEFAULT_BLOCK_K = 256
NEG_INF = -1e30


def repeat_kv_heads(k, v, num_heads):
    """Expand GQA K/V (..., kv_heads, d) to num_heads along axis 2."""
    kv_heads = k.shape[2]
    if kv_heads != num_heads:
        reps = num_heads // kv_heads
        k = jnp.repeat(k, reps, axis=2)
        v = jnp.repeat(v, reps, axis=2)
    return k, v


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, *, block_k: int,
                  causal: bool, sm_scale: float):
    """One (bh, q_block) program: stream KV blocks with online softmax."""
    block_q = q_ref.shape[1]
    head_dim = q_ref.shape[2]
    seq_k = k_ref.shape[1]
    qi = pl.program_id(1)

    q = q_ref[0].astype(jnp.float32) * sm_scale  # (block_q, d)

    q_offset = qi * block_q
    if causal:
        # Only KV blocks at or before this Q block's last row participate.
        num_kv = jnp.minimum(
            pl.cdiv(q_offset + block_q, block_k), pl.cdiv(seq_k, block_k))
    else:
        num_kv = pl.cdiv(seq_k, block_k)

    def body(j, carry):
        acc, m_prev, l_prev = carry
        k = k_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)  # (block_q, block_k)
        if causal:
            row = q_offset + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            col = j * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(row >= col, s, NEG_INF)
        m_cur = jnp.max(s, axis=1)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new[:, None])
        alpha = jnp.exp(m_prev - m_new)
        l_new = l_prev * alpha + jnp.sum(p, axis=1)
        acc = acc * alpha[:, None] + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return acc, m_new, l_new

    acc0 = jnp.zeros((block_q, head_dim), jnp.float32)
    m0 = jnp.full((block_q,), NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q,), jnp.float32)
    acc, m, l = jax.lax.fori_loop(0, num_kv, body, (acc0, m0, l0))

    l_safe = jnp.where(l == 0.0, 1.0, l)
    o_ref[0] = (acc / l_safe[:, None]).astype(o_ref.dtype)


@functools.partial(
    jax.jit, static_argnames=("causal", "sm_scale", "block_q", "block_k",
                              "interpret"))
def _flash_forward(q, k, v, *, causal: bool, sm_scale: float,
                   block_q: int, block_k: int, interpret: bool):
    """q,k,v: (bh, seq, head_dim). Returns (out, lse)."""
    bh, seq_q, head_dim = q.shape
    seq_k = k.shape[1]
    block_q = min(block_q, seq_q)
    block_k = min(block_k, seq_k)
    num_q_blocks = pl.cdiv(seq_q, block_q)

    kernel = functools.partial(
        _flash_kernel, block_k=block_k, causal=causal, sm_scale=sm_scale)
    mem = {} if _VMEM is None else {"memory_space": _VMEM}
    out = pl.pallas_call(
        kernel,
        grid=(bh, num_q_blocks),
        in_specs=[
            pl.BlockSpec((1, block_q, head_dim),
                         lambda b, i: (b, i, 0), **mem),
            pl.BlockSpec((1, seq_k, head_dim), lambda b, i: (b, 0, 0), **mem),
            pl.BlockSpec((1, seq_k, head_dim), lambda b, i: (b, 0, 0), **mem),
        ],
        out_specs=pl.BlockSpec((1, block_q, head_dim),
                               lambda b, i: (b, i, 0), **mem),
        out_shape=jax.ShapeDtypeStruct((bh, seq_q, head_dim), q.dtype),
        interpret=interpret,
    )(q, k, v)
    return out


def _reference_attention(q, k, v, causal: bool, sm_scale: float):
    """Plain XLA attention (used for backward rematerialization + fallback)."""
    s = jnp.einsum("bqd,bkd->bqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * sm_scale
    if causal:
        seq_q, seq_k = s.shape[-2], s.shape[-1]
        row = jnp.arange(seq_q)[:, None]
        col = jnp.arange(seq_k)[None, :]
        s = jnp.where(row >= col, s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bqk,bkd->bqd", p, v.astype(jnp.float32)).astype(q.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash_attention(q, k, v, causal, sm_scale, block_q, block_k, interpret):
    return _flash_forward(q, k, v, causal=causal, sm_scale=sm_scale,
                          block_q=block_q, block_k=block_k,
                          interpret=interpret)


def _fwd(q, k, v, causal, sm_scale, block_q, block_k, interpret):
    out = _flash_forward(q, k, v, causal=causal, sm_scale=sm_scale,
                         block_q=block_q, block_k=block_k,
                         interpret=interpret)
    return out, (q, k, v, out)


def _bwd(causal, sm_scale, block_q, block_k, interpret, res, d_out):
    q, k, v, out = res
    qf = q.astype(jnp.float32)
    kf = k.astype(jnp.float32)
    vf = v.astype(jnp.float32)
    do = d_out.astype(jnp.float32)
    s = jnp.einsum("bqd,bkd->bqk", qf, kf) * sm_scale
    if causal:
        row = jnp.arange(s.shape[-2])[:, None]
        col = jnp.arange(s.shape[-1])[None, :]
        s = jnp.where(row >= col, s, NEG_INF)
    lse = jax.scipy.special.logsumexp(s, axis=-1, keepdims=True)
    p = jnp.exp(s - lse)  # rematerialized softmax
    dv = jnp.einsum("bqk,bqd->bkd", p, do)
    dp = jnp.einsum("bqd,bkd->bqk", do, vf)
    delta = jnp.sum(do * out.astype(jnp.float32), axis=-1, keepdims=True)
    ds = p * (dp - delta) * sm_scale
    dq = jnp.einsum("bqk,bkd->bqd", ds, kf)
    dk = jnp.einsum("bqk,bqd->bkd", ds, qf)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


_flash_attention.defvjp(_fwd, _bwd)


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    sm_scale: float | None = None,
    block_q: int = DEFAULT_BLOCK_Q,
    block_k: int = DEFAULT_BLOCK_K,
    impl: str = "auto",  # auto | pallas | xla
) -> jax.Array:
    """Multi-head attention with GQA support.

    Shapes: q (batch, seq, heads, head_dim); k/v (batch, seq, kv_heads,
    head_dim) with heads % kv_heads == 0.  Returns (batch, seq, heads,
    head_dim) in q's dtype.
    """
    batch, seq_q, num_heads, head_dim = q.shape
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(head_dim)
    k, v = repeat_kv_heads(k, v, num_heads)

    # (b, s, h, d) -> (b*h, s, d)
    def pack(x):
        return x.transpose(0, 2, 1, 3).reshape(
            batch * num_heads, x.shape[1], head_dim)

    qp, kp, vp = pack(q), pack(k), pack(v)

    if impl == "auto":
        # Backend query, not array query: works under tracing.
        impl = "pallas" if jax.default_backend() == "tpu" else "xla"
    if impl == "xla":
        out = _reference_attention(qp, kp, vp, causal, sm_scale)
    else:
        interpret = jax.default_backend() != "tpu"
        out = _flash_attention(qp, kp, vp, causal, sm_scale, block_q,
                               block_k, interpret)
    return out.reshape(batch, num_heads, seq_q, head_dim).transpose(0, 2, 1, 3)
