"""Fused attention for TPU: Pallas flash-attention forward AND backward.

Net-new relative to the reference, which delegates attention math to
torch/vLLM (SURVEY.md §2.4): here it is a first-class op.  Forward is a
Pallas kernel — online-softmax over KV blocks, O(seq) memory, bf16 inputs
with f32 accumulation on the MXU — and saves the per-row logsumexp.  The
backward is the FlashAttention-2 split, also in Pallas: a dK/dV kernel
gridded over KV blocks and a dQ kernel gridded over Q blocks, each
recomputing p = exp(s - lse) blockwise from the saved statistics, so
activation memory stays O(seq) end to end (the round-2 backward
rematerialized the full (q, k) score matrix in XLA — O(seq^2)).

Layout: (batch*heads, seq, head_dim) inside the kernels; the public API
takes (batch, seq, heads, head_dim) and handles GQA by repeating KV heads.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # TPU-specific memory spaces; absent when running CPU interpret mode
    from jax.experimental.pallas import tpu as pltpu

    _VMEM = pltpu.VMEM
except ImportError:  # pragma: no cover
    pltpu = None
    _VMEM = None

DEFAULT_BLOCK_Q = 256
DEFAULT_BLOCK_K = 256
NEG_INF = -1e30


def repeat_kv_heads(k, v, num_heads):
    """Expand GQA K/V (..., kv_heads, d) to num_heads along axis 2."""
    kv_heads = k.shape[2]
    if kv_heads != num_heads:
        reps = num_heads // kv_heads
        k = jnp.repeat(k, reps, axis=2)
        v = jnp.repeat(v, reps, axis=2)
    return k, v


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, block_k: int,
                  causal: bool, sm_scale: float):
    """One (bh, q_block) program: stream KV blocks with online softmax."""
    block_q = q_ref.shape[1]
    head_dim = q_ref.shape[2]
    seq_k = k_ref.shape[1]
    qi = pl.program_id(1)

    q = q_ref[0].astype(jnp.float32) * sm_scale  # (block_q, d)

    q_offset = qi * block_q
    if causal:
        # Only KV blocks at or before this Q block's last row participate.
        num_kv = jnp.minimum(
            pl.cdiv(q_offset + block_q, block_k), pl.cdiv(seq_k, block_k))
    else:
        num_kv = pl.cdiv(seq_k, block_k)

    def body(j, carry):
        acc, m_prev, l_prev = carry
        k = k_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)  # (block_q, block_k)
        if causal:
            row = q_offset + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            col = j * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(row >= col, s, NEG_INF)
        m_cur = jnp.max(s, axis=1)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new[:, None])
        alpha = jnp.exp(m_prev - m_new)
        l_new = l_prev * alpha + jnp.sum(p, axis=1)
        acc = acc * alpha[:, None] + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return acc, m_new, l_new

    acc0 = jnp.zeros((block_q, head_dim), jnp.float32)
    m0 = jnp.full((block_q,), NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q,), jnp.float32)
    acc, m, l = jax.lax.fori_loop(0, num_kv, body, (acc0, m0, l0))

    l_safe = jnp.where(l == 0.0, 1.0, l)
    o_ref[0] = (acc / l_safe[:, None]).astype(o_ref.dtype)
    # Per-row logsumexp, saved for the Pallas backward: p = exp(s - lse)
    # reconstructs softmax blockwise without the O(seq^2) score matrix.
    # Rows with no unmasked column get +inf-ish so backward p == 0.
    lse = jnp.where(l == 0.0, -NEG_INF, m + jnp.log(l_safe))
    lse_ref[0] = lse[:, None]  # (block_q, 1): TPU block-shape rules
    # want the trailing dim equal to the array's (1), so lse rides as
    # a 3D (bh, seq, 1) array rather than a 2D row vector


@functools.partial(
    jax.jit, static_argnames=("causal", "sm_scale", "block_q", "block_k",
                              "interpret"))
def _flash_forward(q, k, v, *, causal: bool, sm_scale: float,
                   block_q: int, block_k: int, interpret: bool):
    """q,k,v: (bh, seq, head_dim). Returns (out, lse)."""
    bh, seq_q, head_dim = q.shape
    seq_k = k.shape[1]
    block_q = min(block_q, seq_q)
    block_k = min(block_k, seq_k)
    num_q_blocks = pl.cdiv(seq_q, block_q)

    kernel = functools.partial(
        _flash_kernel, block_k=block_k, causal=causal, sm_scale=sm_scale)
    mem = {} if _VMEM is None else {"memory_space": _VMEM}
    out, lse = pl.pallas_call(
        kernel,
        grid=(bh, num_q_blocks),
        in_specs=[
            pl.BlockSpec((1, block_q, head_dim),
                         lambda b, i: (b, i, 0), **mem),
            pl.BlockSpec((1, seq_k, head_dim), lambda b, i: (b, 0, 0), **mem),
            pl.BlockSpec((1, seq_k, head_dim), lambda b, i: (b, 0, 0), **mem),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, head_dim),
                         lambda b, i: (b, i, 0), **mem),
            pl.BlockSpec((1, block_q, 1), lambda b, i: (b, i, 0),
                         **mem),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, seq_q, head_dim), q.dtype),
            jax.ShapeDtypeStruct((bh, seq_q, 1), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v)
    return out, lse


def _reference_attention(q, k, v, causal: bool, sm_scale: float):
    """Plain XLA attention (used for backward rematerialization + fallback)."""
    s = jnp.einsum("bqd,bkd->bqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * sm_scale
    if causal:
        seq_q, seq_k = s.shape[-2], s.shape[-1]
        row = jnp.arange(seq_q)[:, None]
        col = jnp.arange(seq_k)[None, :]
        s = jnp.where(row >= col, s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bqk,bkd->bqd", p, v.astype(jnp.float32)).astype(q.dtype)


def _flash_bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                          dk_ref, dv_ref, *, block_q: int, causal: bool,
                          sm_scale: float):
    """One (bh, k_block) program: accumulate dK/dV over the Q blocks that
    attend to this KV block (FlashAttention-2 backward, column pass)."""
    block_k = k_ref.shape[1]
    head_dim = k_ref.shape[2]
    seq_q = q_ref.shape[1]
    ki = pl.program_id(1)
    k_offset = ki * block_k

    k = k_ref[0].astype(jnp.float32)  # (block_k, d)
    v = v_ref[0].astype(jnp.float32)

    num_q = pl.cdiv(seq_q, block_q)
    # causal: rows before this KV block's first row never attend to it
    start_q = (k_offset // block_q) if causal else 0

    def body(j, carry):
        dk_acc, dv_acc = carry
        q = q_ref[0, pl.ds(j * block_q, block_q), :].astype(jnp.float32)
        do = do_ref[0, pl.ds(j * block_q, block_q), :].astype(jnp.float32)
        lse = lse_ref[0, pl.ds(j * block_q, block_q), 0]
        delta = delta_ref[0, pl.ds(j * block_q, block_q), 0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale  # (bq, bk)
        if causal:
            row = j * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            col = k_offset + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(row >= col, s, NEG_INF)
        p = jnp.exp(s - lse[:, None])  # (bq, bk), masked entries -> 0
        dv_acc = dv_acc + jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)  # p^T @ do
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)  # (bq, bk)
        ds = p * (dp - delta[:, None]) * sm_scale
        dk_acc = dk_acc + jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)  # ds^T @ q
        return dk_acc, dv_acc

    zeros = jnp.zeros((block_k, head_dim), jnp.float32)
    dk, dv = jax.lax.fori_loop(start_q, num_q, body, (zeros, zeros))
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


def _flash_bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                         dq_ref, *, block_k: int, causal: bool,
                         sm_scale: float):
    """One (bh, q_block) program: accumulate dQ over this block's KV range
    (FlashAttention-2 backward, row pass)."""
    block_q = q_ref.shape[1]
    head_dim = q_ref.shape[2]
    seq_k = k_ref.shape[1]
    qi = pl.program_id(1)
    q_offset = qi * block_q

    q = q_ref[0].astype(jnp.float32)
    do = do_ref[0].astype(jnp.float32)
    lse = lse_ref[0, :, 0]
    delta = delta_ref[0, :, 0]

    if causal:
        num_kv = jnp.minimum(
            pl.cdiv(q_offset + block_q, block_k), pl.cdiv(seq_k, block_k))
    else:
        num_kv = pl.cdiv(seq_k, block_k)

    def body(j, dq_acc):
        k = k_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale
        if causal:
            row = q_offset + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            col = j * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(row >= col, s, NEG_INF)
        p = jnp.exp(s - lse[:, None])
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta[:, None]) * sm_scale
        return dq_acc + jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    dq0 = jnp.zeros((block_q, head_dim), jnp.float32)
    dq = jax.lax.fori_loop(0, num_kv, body, dq0)
    dq_ref[0] = dq.astype(dq_ref.dtype)


@functools.partial(
    jax.jit, static_argnames=("causal", "sm_scale", "block_q", "block_k",
                              "interpret"))
def _flash_backward(q, k, v, out, lse, d_out, *, causal: bool,
                    sm_scale: float, block_q: int, block_k: int,
                    interpret: bool):
    bh, seq_q, head_dim = q.shape
    seq_k = k.shape[1]
    block_q = min(block_q, seq_q)
    block_k = min(block_k, seq_k)
    mem = {} if _VMEM is None else {"memory_space": _VMEM}
    # delta = rowsum(do * o): one fused elementwise+reduce, O(seq) memory
    delta = jnp.sum(d_out.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1)[..., None]  # (bh, seq_q, 1)

    full_q = pl.BlockSpec((1, seq_q, head_dim), lambda b, i: (b, 0, 0),
                          **mem)
    full_k = pl.BlockSpec((1, seq_k, head_dim), lambda b, i: (b, 0, 0),
                          **mem)
    row_stats = pl.BlockSpec((1, seq_q, 1), lambda b, i: (b, 0, 0),
                             **mem)

    dk, dv = pl.pallas_call(
        functools.partial(_flash_bwd_dkv_kernel, block_q=block_q,
                          causal=causal, sm_scale=sm_scale),
        grid=(bh, pl.cdiv(seq_k, block_k)),
        in_specs=[full_q,
                  pl.BlockSpec((1, block_k, head_dim),
                               lambda b, i: (b, i, 0), **mem),
                  pl.BlockSpec((1, block_k, head_dim),
                               lambda b, i: (b, i, 0), **mem),
                  full_q, row_stats, row_stats],
        out_specs=[
            pl.BlockSpec((1, block_k, head_dim),
                         lambda b, i: (b, i, 0), **mem),
            pl.BlockSpec((1, block_k, head_dim),
                         lambda b, i: (b, i, 0), **mem),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, seq_k, head_dim), k.dtype),
            jax.ShapeDtypeStruct((bh, seq_k, head_dim), v.dtype),
        ],
        interpret=interpret,
    )(q, k, v, d_out, lse, delta)

    dq = pl.pallas_call(
        functools.partial(_flash_bwd_dq_kernel, block_k=block_k,
                          causal=causal, sm_scale=sm_scale),
        grid=(bh, pl.cdiv(seq_q, block_q)),
        in_specs=[
            pl.BlockSpec((1, block_q, head_dim),
                         lambda b, i: (b, i, 0), **mem),
            full_k, full_k,
            pl.BlockSpec((1, block_q, head_dim),
                         lambda b, i: (b, i, 0), **mem),
            pl.BlockSpec((1, block_q, 1), lambda b, i: (b, i, 0),
                         **mem),
            pl.BlockSpec((1, block_q, 1), lambda b, i: (b, i, 0),
                         **mem),
        ],
        out_specs=pl.BlockSpec((1, block_q, head_dim),
                               lambda b, i: (b, i, 0), **mem),
        out_shape=jax.ShapeDtypeStruct((bh, seq_q, head_dim), q.dtype),
        interpret=interpret,
    )(q, k, v, d_out, lse, delta)
    return dq, dk, dv


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash_attention(q, k, v, causal, sm_scale, block_q, block_k, interpret):
    out, _ = _flash_forward(q, k, v, causal=causal, sm_scale=sm_scale,
                            block_q=block_q, block_k=block_k,
                            interpret=interpret)
    return out


def _fwd(q, k, v, causal, sm_scale, block_q, block_k, interpret):
    out, lse = _flash_forward(q, k, v, causal=causal, sm_scale=sm_scale,
                              block_q=block_q, block_k=block_k,
                              interpret=interpret)
    return out, (q, k, v, out, lse)


def _bwd(causal, sm_scale, block_q, block_k, interpret, res, d_out):
    q, k, v, out, lse = res
    return _flash_backward(q, k, v, out, lse, d_out, causal=causal,
                           sm_scale=sm_scale, block_q=block_q,
                           block_k=block_k, interpret=interpret)


_flash_attention.defvjp(_fwd, _bwd)


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    sm_scale: float | None = None,
    block_q: int = DEFAULT_BLOCK_Q,
    block_k: int = DEFAULT_BLOCK_K,
    impl: str = "auto",  # auto | pallas | xla
) -> jax.Array:
    """Multi-head attention with GQA support.

    Shapes: q (batch, seq, heads, head_dim); k/v (batch, seq, kv_heads,
    head_dim) with heads % kv_heads == 0.  Returns (batch, seq, heads,
    head_dim) in q's dtype.
    """
    batch, seq_q, num_heads, head_dim = q.shape
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(head_dim)
    k, v = repeat_kv_heads(k, v, num_heads)

    # (b, s, h, d) -> (b*h, s, d)
    def pack(x):
        return x.transpose(0, 2, 1, 3).reshape(
            batch * num_heads, x.shape[1], head_dim)

    qp, kp, vp = pack(q), pack(k), pack(v)

    if impl == "auto":
        # Backend query, not array query: works under tracing.
        impl = "pallas" if jax.default_backend() == "tpu" else "xla"
    if impl == "xla":
        out = _reference_attention(qp, kp, vp, causal, sm_scale)
    else:
        interpret = jax.default_backend() != "tpu"
        out = _flash_attention(qp, kp, vp, causal, sm_scale, block_q,
                               block_k, interpret)
    return out.reshape(batch, num_heads, seq_q, head_dim).transpose(0, 2, 1, 3)
