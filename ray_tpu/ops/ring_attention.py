"""Sequence/context parallelism: ring attention + Ulysses all-to-all.

Net-new relative to the reference, which has NO sequence parallelism anywhere
(SURVEY.md §2.4: `grep -ri 'ring_attention|context_parallel|ulysses'` over
/root/reference/python returns nothing — long context is delegated to vLLM
engine kwargs).  Here it is a first-class mesh axis (``sp``):

* **Ring attention** (`ring_attention`): each device holds a sequence shard
  of Q/K/V.  KV shards rotate around the ``sp`` ring via ``lax.ppermute``
  (nearest-neighbour ICI hops) while each device accumulates online-softmax
  partial attention for its local Q shard — full-sequence attention with
  O(seq/sp) activation memory per chip and no all-gather.  Causal masking is
  computed against *global* positions, so cross-ring-step causality is exact.

* **Ulysses** (`ulysses_attention`): ``lax.all_to_all`` swaps the sharded
  axis from sequence to heads (each device gets the full sequence for
  heads/sp heads), runs dense local flash attention, and swaps back.  One
  all-to-all each way; preferable when heads % sp == 0 and seq is moderate.

Both run *inside* ``jax.shard_map`` over the mesh; `sequence_parallel_attention`
is the public wrapper that binds mesh + partition specs.  Differentiation is
plain JAX AD through the scan/ppermute (the transpose of a ppermute is the
reverse ppermute, so the backward pass is also a ring).
"""

from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ray_tpu.ops.attention import NEG_INF, flash_attention, repeat_kv_heads
from ray_tpu.parallel.sharding import shard_map, to_partition_spec


def _axis_size(axis_name: str) -> int:
    """``jax.lax.axis_size`` across versions: older jax lacks it; there
    ``psum(1, axis)`` is statically resolved to the same number."""
    fn = getattr(jax.lax, "axis_size", None)
    if fn is not None:
        return fn(axis_name)
    return jax.lax.psum(1, axis_name)


def _shard_positions(idx, s_loc: int, sp: int, layout: str):
    """Global sequence positions held by ring shard ``idx``.

    contiguous: shard i holds [i*s_loc, (i+1)*s_loc).
    zigzag: shard i holds the PAIR of chunks (i, 2*sp-1-i), each of size
    s_loc/2 — the standard fix for causal ring imbalance: every shard owns
    one early chunk and one late chunk, so the unmasked area each shard
    computes per ring step is near-uniform (spread <= 1 block instead of
    sp-1; see tests/test_ring_attention.py balance test).
    """
    if layout == "zigzag":
        c = s_loc // 2
        lo = idx * c + jnp.arange(c)
        hi = (2 * sp - 1 - idx) * c + jnp.arange(c)
        return jnp.concatenate([lo, hi])
    return idx * s_loc + jnp.arange(s_loc)


def zigzag_permutation(seq: int, sp: int):
    """Index arrays mapping contiguous -> zigzag layout and back.

    zigzag layout order: shard 0's chunks (0, 2sp-1), shard 1's (1, 2sp-2),
    ...  ``perm`` gathers a contiguous-layout sequence axis into zigzag
    order (``x_zig = x[:, perm]``); ``inv`` undoes it.
    """
    import numpy as np

    c = seq // (2 * sp)
    order = []
    for i in range(sp):
        order.append(np.arange(i * c, (i + 1) * c))
        order.append(np.arange((2 * sp - 1 - i) * c, (2 * sp - i) * c))
    perm = np.concatenate(order)
    inv = np.empty_like(perm)
    inv[perm] = np.arange(seq)
    return perm, inv


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    axis_name: str,
    *,
    causal: bool = True,
    sm_scale: Optional[float] = None,
    layout: str = "contiguous",  # contiguous | zigzag
) -> jax.Array:
    """Ring attention over the ``axis_name`` device ring.

    Must be called inside ``shard_map``.  Local shapes: q/k/v
    (batch, seq_local, heads, head_dim) — k/v may have fewer (GQA) heads.
    Global sequence = seq_local * ring size.  ``layout`` names how global
    positions map onto shards (see _shard_positions): "zigzag" balances
    causal work across the ring and is what sequence_parallel_attention's
    ``impl="zigzag"`` uses; correctness is exact for both layouts (masks
    compare true global positions).
    """
    sp = _axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    b, s_loc, h, d = q.shape
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(d)

    qf = q.astype(jnp.float32) * sm_scale
    rows = _shard_positions(idx, s_loc, sp, layout)  # global q positions

    # KV rotates "upward": device i sends to i+1, so after t steps device i
    # holds the shard originally at (i - t) mod sp.  GQA K/V rotate in their
    # raw (kv_heads) form — heads are repeated locally per block so each hop
    # moves only the necessary bytes.
    perm = [(i, (i + 1) % sp) for i in range(sp)]

    def block(k_cur, v_cur, src, acc, m_prev, l_prev):
        """Fold one KV shard (originally at ring position src) into the
        online-softmax accumulator."""
        k_rep, v_rep = repeat_kv_heads(k_cur, v_cur, h)
        s = jnp.einsum("bqhd,bkhd->bhqk", qf, k_rep.astype(jnp.float32))
        if causal:
            cols = _shard_positions(src, s_loc, sp, layout)
            mask = rows[:, None] >= cols[None, :]
            s = jnp.where(mask[None, None], s, NEG_INF)
        m_cur = jnp.max(s, axis=-1)  # (b, h, q)
        m_new = jnp.maximum(m_prev, m_cur)
        # Fully-masked blocks keep m == NEG_INF; exp(s - m) would be 1 for
        # every masked entry, so zero them explicitly.
        p = jnp.where(s <= NEG_INF / 2, 0.0, jnp.exp(s - m_new[..., None]))
        alpha = jnp.exp(m_prev - m_new)
        l_new = l_prev * alpha + jnp.sum(p, axis=-1)
        acc = acc * alpha[..., None] + jnp.einsum(
            "bhqk,bkhd->bhqd", p, v_rep.astype(jnp.float32))
        return acc, m_new, l_new

    def body(carry, t):
        k_cur, v_cur, acc, m_prev, l_prev = carry
        acc, m_new, l_new = block(k_cur, v_cur, (idx - t) % sp,
                                  acc, m_prev, l_prev)
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        return (k_nxt, v_nxt, acc, m_new, l_new), None

    acc0 = jnp.zeros((b, h, s_loc, d), jnp.float32)
    m0 = jnp.full((b, h, s_loc), NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, h, s_loc), jnp.float32)
    # Scan covers the first sp-1 steps (each ends with a rotation); the last
    # shard is folded outside the scan so no rotation result is discarded.
    (k_last, v_last, acc, m, l), _ = jax.lax.scan(
        body, (k, v, acc0, m0, l0), jnp.arange(sp - 1))
    acc, m, l = block(k_last, v_last, (idx - (sp - 1)) % sp, acc, m, l)

    l_safe = jnp.where(l == 0.0, 1.0, l)
    out = acc / l_safe[..., None]  # (b, h, q, d)
    return out.transpose(0, 2, 1, 3).astype(q.dtype)


def ulysses_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    axis_name: str,
    *,
    causal: bool = True,
    sm_scale: Optional[float] = None,
    attn_impl: str = "auto",
) -> jax.Array:
    """Ulysses sequence parallelism: all-to-all heads<->sequence swap.

    Must be called inside ``shard_map``.  Local q: (batch, seq_local, heads,
    head_dim); requires heads % ring_size == 0.  After the swap each device
    holds the FULL sequence for heads/sp heads and runs dense (flash)
    attention locally; a reverse all-to-all restores sequence sharding.
    """
    sp = _axis_size(axis_name)
    h = q.shape[2]
    if h % sp != 0:
        raise ValueError(f"ulysses needs heads ({h}) % sp ({sp}) == 0")

    def fwd(x):  # (b, s/sp, h, d) -> (b, s, h/sp, d)
        return jax.lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                                  tiled=True)

    def rev(x):  # (b, s, h/sp, d) -> (b, s/sp, h, d)
        return jax.lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                                  tiled=True)

    # When the kv_heads axis itself splits over sp, swap the raw GQA K/V
    # (fewer bytes over ICI) and expand to full heads locally afterwards.
    if k.shape[2] % sp == 0:
        kg, vg = fwd(k), fwd(v)
        kg, vg = repeat_kv_heads(kg, vg, h // sp)
    else:
        k, v = repeat_kv_heads(k, v, h)
        kg, vg = fwd(k), fwd(v)

    out = flash_attention(fwd(q), kg, vg, causal=causal,
                          sm_scale=sm_scale, impl=attn_impl)
    return rev(out)


def sequence_parallel_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    *,
    impl: str = "ring",  # ring | ulysses
    causal: bool = True,
    sm_scale: Optional[float] = None,
    rules: Optional[dict] = None,
    sp_axis: str = "sp",
) -> jax.Array:
    """Sequence-parallel attention bound to a mesh (callable inside jit).

    Global shapes: q (batch, seq, heads, head_dim), k/v (batch, seq,
    kv_heads, head_dim).  Batch/heads follow the logical sharding rules
    (batch over dp+fsdp, heads over tp); sequence is sharded over ``sp``.
    Falls back to plain flash attention when the sp axis has size 1.

    impl="zigzag": causal-balanced ring.  Inputs arrive in natural
    (contiguous) sequence order; a global zigzag gather re-shards them so
    every ring shard holds one early + one late chunk, the balanced ring
    runs, and the inverse gather restores natural order.  Trainers that
    keep activations in zigzag layout end-to-end (permute once at the
    embedding, with zigzag position ids for RoPE) can call ring_attention
    with layout="zigzag" directly and skip both gathers.
    """
    if mesh.shape.get(sp_axis, 1) == 1:
        return flash_attention(q, k, v, causal=causal, sm_scale=sm_scale)

    q_spec = to_partition_spec(("batch", "seq", "heads", "head_dim"), rules)
    kv_spec = to_partition_spec(("batch", "seq", "kv_heads", "head_dim"),
                                rules)

    if impl == "zigzag":
        sp = mesh.shape[sp_axis]
        seq = q.shape[1]
        if seq % (2 * sp) != 0:
            raise ValueError(
                f"zigzag needs seq ({seq}) % 2*sp ({2 * sp}) == 0")
        perm, inv = zigzag_permutation(seq, sp)
        q, k, v = (jnp.take(x, perm, axis=1) for x in (q, k, v))

    def local(ql, kl, vl):
        if impl == "ulysses":
            return ulysses_attention(ql, kl, vl, sp_axis, causal=causal,
                                     sm_scale=sm_scale)
        return ring_attention(
            ql, kl, vl, sp_axis, causal=causal, sm_scale=sm_scale,
            layout="zigzag" if impl == "zigzag" else "contiguous")

    out = shard_map(
        local, mesh=mesh,
        in_specs=(q_spec, kv_spec, kv_spec),
        out_specs=q_spec,
        check_vma=False,
    )(q, k, v)
    if impl == "zigzag":
        out = jnp.take(out, inv, axis=1)
    return out
