"""Build helpers for ray_tpu native (C++) components.

Compiles the shared-memory object store daemon (``shm_store.cc``) and other
native binaries on first use, caching the result under
``ray_tpu/native/_build/``.  The cache key is a hash of the source file so
edits trigger a rebuild.  g++ is part of the baked toolchain; there is no
runtime dependency beyond libc/pthread/rt.
"""

from __future__ import annotations

import hashlib
import os
import subprocess

_NATIVE_DIR = os.path.dirname(os.path.abspath(__file__))
_BUILD_DIR = os.path.join(_NATIVE_DIR, "_build")

_BINARIES = {
    "shm_store": {
        "sources": ["shm_store.cc"],
        "flags": ["-O2", "-std=c++17", "-pthread"],
        "libs": ["-lrt"],
    },
    "libmutable_channel": {
        "sources": ["mutable_channel.cc"],
        "flags": ["-O2", "-std=c++17", "-pthread", "-shared", "-fPIC"],
        "libs": ["-lrt"],
        "suffix": ".so",
    },
    "gcs_server": {
        "sources": ["gcs_server.cc"],
        "headers": ["wire.h"],
        "flags": ["-O2", "-std=c++17", "-pthread"],
        "libs": [],
    },
    # CPython extension module (direct-call transport core).  Compiled
    # against this interpreter's headers; symbols resolve at import time,
    # so no -lpython is needed on Linux.
    "_rtpu_core": {
        "sources": ["core_worker.cc"],
        "flags": ["-O2", "-std=c++17", "-pthread", "-shared", "-fPIC"],
        "libs": [],
        "suffix": ".so",
        "python_ext": True,
    },
}


def _source_hash(sources: list[str]) -> str:
    h = hashlib.sha256()
    for src in sources:
        with open(os.path.join(_NATIVE_DIR, src), "rb") as f:
            h.update(f.read())
    return h.hexdigest()[:16]


# RTPU_SANITIZE selects an instrumented build (separate cache namespace,
# so sanitized and fast binaries coexist):
#   address (or the legacy "1") -> ASan+UBSan   (`make sanitize`)
#   thread                      -> TSan         (`make sanitize-store`)
def _sanitize_mode() -> str:
    raw = os.environ.get("RTPU_SANITIZE", "").strip().lower()
    if raw in ("", "0", "false", "off"):
        return ""
    if raw in ("1", "address", "asan"):
        return "asan"
    if raw in ("thread", "tsan"):
        return "tsan"
    raise ValueError(
        f"RTPU_SANITIZE={raw!r}: expected 'address' (or legacy '1') "
        "or 'thread'")


_SANITIZE = _sanitize_mode()
_SAN_FLAGS = {
    "asan": ["-fsanitize=address,undefined", "-fno-omit-frame-pointer",
             "-g", "-O1"],
    "tsan": ["-fsanitize=thread", "-fno-omit-frame-pointer", "-g", "-O1"],
}


def binary_path(name: str) -> str:
    """Return the path to a built native binary, compiling it if needed."""
    spec = _BINARIES[name]
    # headers participate in the cache key but not the compile line
    tag = _source_hash(spec["sources"] + spec.get("headers", []))
    if _SANITIZE:
        tag += f"-{_SANITIZE}"
    out = os.path.join(_BUILD_DIR,
                       f"{name}-{tag}{spec.get('suffix', '')}")
    if _SANITIZE and spec.get("suffix") == ".so" \
            and _SANITIZE not in os.environ.get("LD_PRELOAD", ""):
        # Loading a sanitizer-linked DSO into an uninstrumented
        # interpreter aborts the process with a cryptic "runtime does
        # not come first" — fail actionably instead.  Standalone daemon
        # binaries (shm_store, gcs_server) need no preload: the runtime
        # links into the executable itself.
        lib = "libasan/libubsan" if _SANITIZE == "asan" else "libtsan"
        raise RuntimeError(
            f"RTPU_SANITIZE={_SANITIZE} requires {lib} in LD_PRELOAD to "
            "load instrumented extension modules; use `make sanitize` / "
            "`make sanitize-store`")
    if os.path.exists(out):
        return out
    os.makedirs(_BUILD_DIR, exist_ok=True)
    srcs = [os.path.join(_NATIVE_DIR, s) for s in spec["sources"]]
    tmp = out + f".tmp.{os.getpid()}"
    flags = list(spec["flags"])
    if _SANITIZE:
        flags = ([f for f in flags if not f.startswith("-O")]
                 + _SAN_FLAGS[_SANITIZE])
    if spec.get("python_ext"):
        import sysconfig

        flags.append(f"-I{sysconfig.get_paths()['include']}")
    cmd = ["g++", *flags, *srcs, "-o", tmp, *spec["libs"]]
    subprocess.run(cmd, check=True, capture_output=True)
    os.replace(tmp, out)  # atomic: concurrent builders race benignly
    return out


def load_extension(name: str):
    """Import a compiled CPython extension module by build name."""
    import importlib.util

    path = binary_path(name)
    spec = importlib.util.spec_from_file_location(name, path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod
