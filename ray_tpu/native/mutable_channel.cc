// Mutable shared-memory channel: the native substrate for compiled-DAG
// channels on one host.
//
// Counterpart of the reference's native mutable objects
// (/root/reference/src/ray/core_worker/experimental_mutable_object_manager.h:44
// and the shared_memory_channel built on them): a fixed shm segment holding a
// circular byte ring with a process-shared mutex + condvars, so writer and
// reader block in the kernel (no polling) and payloads move with exactly one
// memcpy per side — no sockets, no store round-trips, no per-message object
// ids. Built as a shared library driven through ctypes
// (ray_tpu/dag/native_channel.py); Python↔C boundary is plain C.
//
// Layout: [Header][ring bytes]. Messages are [u32 len][payload] with wrap.
// One writer + one reader (the compiled-DAG edge contract).

#include <cerrno>
#include <cstdint>
#include <cstring>

#include <fcntl.h>
#include <pthread.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <time.h>
#include <unistd.h>

namespace {

constexpr uint64_t kMagic = 0x52545055434841ULL;  // "RTPUCHA"

struct Header {
  uint64_t magic;
  uint64_t capacity;   // ring data bytes
  uint64_t head;       // read offset  (consumed bytes, monotonic)
  uint64_t tail;       // write offset (produced bytes, monotonic)
  uint32_t closed;
  pthread_mutex_t mu;
  pthread_cond_t nonempty;
  pthread_cond_t nonfull;
};

struct Channel {
  Header* h;
  uint8_t* data;
  uint64_t map_len;
};

uint64_t used(const Header* h) { return h->tail - h->head; }

void abs_deadline(timespec* ts, int timeout_ms) {
  clock_gettime(CLOCK_REALTIME, ts);
  ts->tv_sec += timeout_ms / 1000;
  ts->tv_nsec += (timeout_ms % 1000) * 1000000L;
  if (ts->tv_nsec >= 1000000000L) {
    ts->tv_sec += 1;
    ts->tv_nsec -= 1000000000L;
  }
}

void copy_in(Channel* c, uint64_t off, const uint8_t* src, uint64_t n) {
  uint64_t cap = c->h->capacity;
  uint64_t pos = off % cap;
  uint64_t first = (pos + n <= cap) ? n : cap - pos;
  memcpy(c->data + pos, src, first);
  if (n > first) memcpy(c->data, src + first, n - first);
}

void copy_out(Channel* c, uint64_t off, uint8_t* dst, uint64_t n) {
  uint64_t cap = c->h->capacity;
  uint64_t pos = off % cap;
  uint64_t first = (pos + n <= cap) ? n : cap - pos;
  memcpy(dst, c->data + pos, first);
  if (n > first) memcpy(dst + first, c->data, n - first);
}

}  // namespace

extern "C" {

// Create (O_EXCL) a channel of `capacity` ring bytes; returns handle or null.
void* mc_create(const char* name, uint64_t capacity) {
  uint64_t map_len = sizeof(Header) + capacity;
  int fd = shm_open(name, O_CREAT | O_EXCL | O_RDWR, 0600);
  if (fd < 0) return nullptr;
  if (ftruncate(fd, static_cast<off_t>(map_len)) != 0) {
    close(fd);
    shm_unlink(name);
    return nullptr;
  }
  void* mem =
      mmap(nullptr, map_len, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  close(fd);
  if (mem == MAP_FAILED) {
    shm_unlink(name);
    return nullptr;
  }
  auto* h = static_cast<Header*>(mem);
  h->capacity = capacity;
  h->head = h->tail = 0;
  h->closed = 0;
  pthread_mutexattr_t ma;
  pthread_mutexattr_init(&ma);
  pthread_mutexattr_setpshared(&ma, PTHREAD_PROCESS_SHARED);
  // a process can die mid-critical-section; robust mutexes let the peer
  // recover instead of deadlocking
  pthread_mutexattr_setrobust(&ma, PTHREAD_MUTEX_ROBUST);
  pthread_mutex_init(&h->mu, &ma);
  pthread_condattr_t ca;
  pthread_condattr_init(&ca);
  pthread_condattr_setpshared(&ca, PTHREAD_PROCESS_SHARED);
  pthread_cond_init(&h->nonempty, &ca);
  pthread_cond_init(&h->nonfull, &ca);
  h->magic = kMagic;  // last: marks fully-initialized
  auto* c = new Channel{h, reinterpret_cast<uint8_t*>(mem) + sizeof(Header),
                        map_len};
  return c;
}

void* mc_open(const char* name) {
  int fd = shm_open(name, O_RDWR, 0600);
  if (fd < 0) return nullptr;
  struct stat st;
  if (fstat(fd, &st) != 0 || st.st_size < static_cast<off_t>(sizeof(Header))) {
    close(fd);
    return nullptr;
  }
  void* mem = mmap(nullptr, static_cast<size_t>(st.st_size),
                   PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  close(fd);
  if (mem == MAP_FAILED) return nullptr;
  auto* h = static_cast<Header*>(mem);
  if (h->magic != kMagic) {  // creator not done initializing (or junk)
    munmap(mem, static_cast<size_t>(st.st_size));
    return nullptr;
  }
  auto* c = new Channel{h, reinterpret_cast<uint8_t*>(mem) + sizeof(Header),
                        static_cast<uint64_t>(st.st_size)};
  return c;
}

static int lock_robust(Header* h) {
  int rc = pthread_mutex_lock(&h->mu);
  if (rc == EOWNERDEAD) {
    // previous owner died holding the lock; state is still consistent for
    // our ring (offsets only advance after their copy completes)
    pthread_mutex_consistent(&h->mu);
    rc = 0;
  }
  return rc;
}

// pthread_cond_timedwait re-acquires the mutex on return, so the peer dying
// while we were blocked surfaces as EOWNERDEAD here too — it must be marked
// consistent exactly like lock_robust, or the next unlock/lock goes
// ENOTRECOVERABLE and wedges the channel for good.
static int timedwait_robust(pthread_cond_t* cv, Header* h,
                            const timespec* ts) {
  int rc = pthread_cond_timedwait(cv, &h->mu, ts);
  if (rc == EOWNERDEAD) {
    pthread_mutex_consistent(&h->mu);
    rc = 0;
  }
  return rc;
}

// Returns 0 ok, -1 timeout, -2 closed, -3 message larger than ring.
int mc_write(void* handle, const uint8_t* buf, uint64_t len, int timeout_ms) {
  auto* c = static_cast<Channel*>(handle);
  Header* h = c->h;
  uint64_t need = len + 4;
  if (need > h->capacity) return -3;
  timespec ts;
  abs_deadline(&ts, timeout_ms);
  if (lock_robust(h) != 0) return -2;
  while (h->capacity - used(h) < need && !h->closed) {
    if (timedwait_robust(&h->nonfull, h, &ts) == ETIMEDOUT) {
      pthread_mutex_unlock(&h->mu);
      return -1;
    }
  }
  if (h->closed) {
    pthread_mutex_unlock(&h->mu);
    return -2;
  }
  uint32_t len32 = static_cast<uint32_t>(len);
  copy_in(c, h->tail, reinterpret_cast<uint8_t*>(&len32), 4);
  copy_in(c, h->tail + 4, buf, len);
  h->tail += need;
  pthread_cond_signal(&h->nonempty);
  pthread_mutex_unlock(&h->mu);
  return 0;
}

// Returns payload length (copied into out, up to out_cap), -1 timeout,
// -2 closed-and-drained, -4 out_cap too small (message left in place; call
// mc_next_len to size the buffer).
int64_t mc_read(void* handle, uint8_t* out, uint64_t out_cap,
                int timeout_ms) {
  auto* c = static_cast<Channel*>(handle);
  Header* h = c->h;
  timespec ts;
  abs_deadline(&ts, timeout_ms);
  if (lock_robust(h) != 0) return -2;
  while (used(h) == 0) {
    if (h->closed) {
      pthread_mutex_unlock(&h->mu);
      return -2;
    }
    if (timedwait_robust(&h->nonempty, h, &ts) == ETIMEDOUT) {
      pthread_mutex_unlock(&h->mu);
      return -1;
    }
  }
  uint32_t len32 = 0;
  copy_out(c, h->head, reinterpret_cast<uint8_t*>(&len32), 4);
  if (len32 > out_cap) {
    pthread_mutex_unlock(&h->mu);
    return -4;
  }
  copy_out(c, h->head + 4, out, len32);
  h->head += len32 + 4;
  pthread_cond_signal(&h->nonfull);
  pthread_mutex_unlock(&h->mu);
  return static_cast<int64_t>(len32);
}

// Length of the next queued message, -1 if empty, -2 closed-and-drained.
int64_t mc_next_len(void* handle) {
  auto* c = static_cast<Channel*>(handle);
  Header* h = c->h;
  if (lock_robust(h) != 0) return -2;
  int64_t out;
  if (used(h) == 0) {
    out = h->closed ? -2 : -1;
  } else {
    uint32_t len32 = 0;
    copy_out(c, h->head, reinterpret_cast<uint8_t*>(&len32), 4);
    out = static_cast<int64_t>(len32);
  }
  pthread_mutex_unlock(&h->mu);
  return out;
}

void mc_close_channel(void* handle) {
  auto* c = static_cast<Channel*>(handle);
  Header* h = c->h;
  if (lock_robust(h) == 0) {
    h->closed = 1;
    pthread_cond_broadcast(&h->nonempty);
    pthread_cond_broadcast(&h->nonfull);
    pthread_mutex_unlock(&h->mu);
  }
}

void mc_release(void* handle) {
  auto* c = static_cast<Channel*>(handle);
  munmap(c->h, c->map_len);
  delete c;
}

int mc_unlink(const char* name) { return shm_unlink(name); }

}  // extern "C"
