// _rtpu_core: native transport core for direct actor calls.
//
// Counterpart of the reference's C++ core-worker transport
// (/root/reference/src/ray/core_worker/transport/actor_task_submitter.cc +
// task_receiver.cc): framing, socket I/O, and frame parsing in C++ with the
// GIL released.  Round-2's pure-Python direct path paid for pickled frame
// envelopes and a Python thread-per-connection; on a single-core host that
// overhead IS the actor-call ceiling (BENCH_core n:n at 0.41x reference).
//
// Design: THREADLESS.  The extension spawns no threads at all — on a
// one-core box every extra hop between threads is pure scheduling latency:
//
//   caller:  Channel.submit(frame)        — sendall on the calling thread
//            Channel.recv_reply(ms)       — recv+parse on the calling
//                                           thread (the Python drain
//                                           thread), GIL released while
//                                           blocked.  One wake per reply,
//                                           exactly like a plain socket
//                                           reader, but parsing is C++.
//   callee:  Server.next(ms)              — epoll accept/read/parse on the
//                                           calling thread (the single
//                                           Python executor); returns one
//                                           complete call frame.
//            Server.reply(conn_id, frame) — sendall on the same thread.
//
// Frames are the 4-byte-LE length-prefixed format of _private/protocol.py;
// bodies are the records built by _private/direct.py (0x01/0x02/0x03
// binary dialect; 0x80-first-byte legacy pickles from Python-fallback
// peers pass through opaquely — the Python layer handles both).
//
// Build: CPython C API (no pybind11 in this image) — see native/build.py.

#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <errno.h>
#include <fcntl.h>
#include <netinet/in.h>
#include <sys/eventfd.h>
#include <netinet/tcp.h>
#include <poll.h>
#include <stdint.h>
#include <string.h>
#include <sys/epoll.h>
#include <sys/socket.h>
#include <unistd.h>

#include <atomic>
#include <deque>
#include <map>
#include <mutex>
#include <set>
#include <string>
#include <unordered_map>
#include <vector>

namespace {

constexpr uint32_t kMaxFrame = 1u << 28;

bool send_all(int fd, const char* p, size_t n) {
  while (n > 0) {
    ssize_t k = ::send(fd, p, n, MSG_NOSIGNAL);
    if (k < 0) {
      if (errno == EINTR) continue;
      if (errno == EAGAIN || errno == EWOULDBLOCK) {
        // Non-blocking fd (server-accepted conns) with a full buffer:
        // wait for drain.  Bailing here would truncate mid-frame and
        // permanently desync the stream.
        struct pollfd pfd{fd, POLLOUT, 0};
        if (::poll(&pfd, 1, 10000) <= 0) return false;
        continue;
      }
      return false;
    }
    p += k;
    n -= size_t(k);
  }
  return true;
}

// Framed send, caller already holds the send lock: one writev-ish call
// (header copied into a stack prefix for small frames to keep it a
// single syscall).
bool send_frame_locked(int fd, const char* body, size_t n) {
  uint32_t len = uint32_t(n);
  if (n <= 65536 - 4) {
    char buf[65536];
    memcpy(buf, &len, 4);
    memcpy(buf + 4, body, n);
    return send_all(fd, buf, n + 4);
  }
  char hdr[4];
  memcpy(hdr, &len, 4);
  return send_all(fd, hdr, 4) && send_all(fd, body, n);
}

bool send_frame(int fd, std::mutex& mu, const char* body, size_t n) {
  std::lock_guard<std::mutex> g(mu);
  return send_frame_locked(fd, body, n);
}

// Incremental frame extraction: 1 = frame out, 0 = need more bytes,
// -1 = poisoned stream (oversize length) — the caller MUST drop the
// connection; after a bogus length no later byte boundary can be trusted.
int extract_frame(std::string& acc, std::string* out) {
  if (acc.size() < 4) return 0;
  uint32_t len;
  memcpy(&len, acc.data(), 4);
  if (len > kMaxFrame) return -1;
  if (acc.size() < 4 + size_t(len)) return 0;
  out->assign(acc, 4, len);
  acc.erase(0, 4 + size_t(len));
  return 1;
}

// ---------- Channel (caller side) ----------

struct ChannelCore {
  int fd = -1;
  std::mutex send_mu;
  std::string in;  // recv accumulation (single reader thread by contract)
  std::string out;  // submit_buffered coalescing (flushed by flush())
  bool dead = false;
};

// The coalescing cap: past this, submit_buffered flushes inline so a
// burst of large frames cannot balloon the buffer.
constexpr size_t kSubmitBufferCap = 256 * 1024;

typedef struct {
  PyObject_HEAD
  ChannelCore* core;
} ChannelObject;

static PyObject* Channel_new(PyTypeObject* type, PyObject* args,
                             PyObject* kwds) {
  int fd;
  if (!PyArg_ParseTuple(args, "i", &fd)) return nullptr;
  ChannelObject* self = (ChannelObject*)type->tp_alloc(type, 0);
  if (!self) return nullptr;
  self->core = new ChannelCore();
  self->core->fd = fd;
  int one = 1;
  setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof one);
  return (PyObject*)self;
}

static void Channel_dealloc(ChannelObject* self) {
  if (self->core) {
    ::shutdown(self->core->fd, SHUT_RDWR);
    ::close(self->core->fd);
    delete self->core;
    self->core = nullptr;
  }
  Py_TYPE(self)->tp_free((PyObject*)self);
}

static PyObject* Channel_submit(ChannelObject* self, PyObject* args) {
  Py_buffer frame;
  if (!PyArg_ParseTuple(args, "y*", &frame)) return nullptr;
  ChannelCore* c = self->core;
  bool ok = true;
  Py_BEGIN_ALLOW_THREADS
  {
    std::lock_guard<std::mutex> g(c->send_mu);
    if (c->dead) {
      ok = false;
    } else {
      // drain any coalesced frames first: mixing submit_buffered and
      // submit on one channel must preserve submission order
      if (!c->out.empty()) {
        ok = send_all(c->fd, c->out.data(), c->out.size());
        c->out.clear();
      }
      if (ok)
        ok = send_frame_locked(c->fd, (const char*)frame.buf,
                               size_t(frame.len));
    }
  }
  Py_END_ALLOW_THREADS
  PyBuffer_Release(&frame);
  return PyBool_FromLong(ok);
}

// submit_buffered(frame) -> bool: append to the coalescing buffer with NO
// syscall; a later flush() (or hitting the cap) writes every pending
// frame in one send.  Halves the per-call syscall budget on the n:n
// fan-in path (reference batches the same way via gRPC streams).
static PyObject* Channel_submit_buffered(ChannelObject* self,
                                         PyObject* args) {
  Py_buffer frame;
  if (!PyArg_ParseTuple(args, "y*", &frame)) return nullptr;
  ChannelCore* c = self->core;
  bool ok = true;
  Py_BEGIN_ALLOW_THREADS
  {
    std::lock_guard<std::mutex> g(c->send_mu);
    if (c->dead) {
      ok = false;
    } else {
      uint32_t len = uint32_t(frame.len);
      c->out.append((const char*)&len, 4);
      c->out.append((const char*)frame.buf, size_t(frame.len));
      if (c->out.size() >= kSubmitBufferCap) {
        ok = send_all(c->fd, c->out.data(), c->out.size());
        c->out.clear();
      }
    }
  }
  Py_END_ALLOW_THREADS
  PyBuffer_Release(&frame);
  return PyBool_FromLong(ok);
}

static PyObject* Channel_flush(ChannelObject* self, PyObject*) {
  ChannelCore* c = self->core;
  bool ok = true;
  Py_BEGIN_ALLOW_THREADS
  {
    std::lock_guard<std::mutex> g(c->send_mu);
    if (!c->out.empty()) {
      ok = !c->dead && send_all(c->fd, c->out.data(), c->out.size());
      c->out.clear();
    }
  }
  Py_END_ALLOW_THREADS
  return PyBool_FromLong(ok);
}

// recv_reply(timeout_ms) -> (task_id, flags, payload) | None on timeout;
// raises ConnectionError on EOF/reset.  Non-0x02 frames are skipped.
static PyObject* Channel_recv_reply(ChannelObject* self, PyObject* args) {
  long timeout_ms;
  if (!PyArg_ParseTuple(args, "l", &timeout_ms)) return nullptr;
  ChannelCore* c = self->core;
  std::string frame;
  bool got = false;
  Py_BEGIN_ALLOW_THREADS
  for (;;) {
    int fr = extract_frame(c->in, &frame);
    if (fr < 0) {  // poisoned framing: the channel is unusable
      c->dead = true;
      ::shutdown(c->fd, SHUT_RDWR);
      break;
    }
    if (fr > 0) {
      if (frame.size() >= 3 && uint8_t(frame[0]) == 0x02) {
        got = true;
        break;
      }
      continue;  // not a reply frame: skip
    }
    if (c->dead) break;
    struct pollfd pfd{c->fd, POLLIN, 0};
    int pr = ::poll(&pfd, 1, int(timeout_ms));
    if (pr == 0) break;  // timeout
    if (pr < 0) {
      if (errno == EINTR) continue;
      c->dead = true;
      break;
    }
    char buf[1 << 16];
    ssize_t k = ::recv(c->fd, buf, sizeof buf, 0);
    if (k <= 0) {
      if (k < 0 && errno == EINTR) continue;
      c->dead = true;
      break;
    }
    c->in.append(buf, size_t(k));
  }
  Py_END_ALLOW_THREADS
  if (got) {
    uint8_t tl = uint8_t(frame[1]);
    if (frame.size() < size_t(2 + tl + 1)) Py_RETURN_NONE;
    uint8_t flags = uint8_t(frame[2 + tl]);
    return Py_BuildValue("(y#iy#)", frame.data() + 2, Py_ssize_t(tl),
                         int(flags), frame.data() + 2 + tl + 1,
                         Py_ssize_t(frame.size() - 2 - tl - 1));
  }
  if (self->core->dead) {
    PyErr_SetString(PyExc_ConnectionError, "direct channel lost");
    return nullptr;
  }
  Py_RETURN_NONE;
}

// recv_replies(timeout_ms) -> [(task_id, flags, payload), ...] | None on
// timeout.  Blocks for the FIRST reply, then drains every further frame
// already buffered/readable without blocking — one Python call (and one
// GIL acquisition) per burst instead of per reply.
static PyObject* Channel_recv_replies(ChannelObject* self, PyObject* args) {
  long timeout_ms;
  if (!PyArg_ParseTuple(args, "l", &timeout_ms)) return nullptr;
  ChannelCore* c = self->core;
  std::deque<std::string> frames;
  Py_BEGIN_ALLOW_THREADS
  bool blocking_done = false;
  for (;;) {
    std::string frame;
    int fr = extract_frame(c->in, &frame);
    if (fr < 0) {
      c->dead = true;
      ::shutdown(c->fd, SHUT_RDWR);
      break;
    }
    if (fr > 0) {
      if (frame.size() >= 3 && uint8_t(frame[0]) == 0x02)
        frames.push_back(std::move(frame));
      continue;
    }
    if (c->dead) break;
    // buffer exhausted: block only while we have nothing to hand back
    int wait_ms = frames.empty() && !blocking_done ? int(timeout_ms) : 0;
    struct pollfd pfd{c->fd, POLLIN, 0};
    int pr = ::poll(&pfd, 1, wait_ms);
    if (pr == 0) {
      if (wait_ms != 0) blocking_done = true;
      break;
    }
    if (pr < 0) {
      if (errno == EINTR) continue;
      c->dead = true;
      break;
    }
    char buf[1 << 16];
    ssize_t k = ::recv(c->fd, buf, sizeof buf, 0);
    if (k <= 0) {
      if (k < 0 && errno == EINTR) continue;
      c->dead = true;
      break;
    }
    c->in.append(buf, size_t(k));
  }
  Py_END_ALLOW_THREADS
  if (!frames.empty()) {
    PyObject* list = PyList_New(Py_ssize_t(frames.size()));
    if (!list) return nullptr;
    Py_ssize_t i = 0;
    for (const std::string& frame : frames) {
      uint8_t tl = uint8_t(frame[1]);
      PyObject* item;
      if (frame.size() < size_t(2 + tl + 1)) {
        item = Py_None;
        Py_INCREF(item);
      } else {
        uint8_t flags = uint8_t(frame[2 + tl]);
        item = Py_BuildValue("(y#iy#)", frame.data() + 2, Py_ssize_t(tl),
                             int(flags), frame.data() + 2 + tl + 1,
                             Py_ssize_t(frame.size() - 2 - tl - 1));
        if (!item) {
          Py_DECREF(list);
          return nullptr;
        }
      }
      PyList_SET_ITEM(list, i++, item);
    }
    return list;
  }
  if (c->dead) {
    PyErr_SetString(PyExc_ConnectionError, "direct channel lost");
    return nullptr;
  }
  Py_RETURN_NONE;
}

static PyObject* Channel_is_dead(ChannelObject* self, PyObject*) {
  return PyBool_FromLong(self->core->dead);
}

static PyObject* Channel_close(ChannelObject* self, PyObject*) {
  self->core->dead = true;
  ::shutdown(self->core->fd, SHUT_RDWR);
  Py_RETURN_NONE;
}

static PyMethodDef Channel_methods[] = {
    {"submit", (PyCFunction)Channel_submit, METH_VARARGS,
     "submit(frame) -> bool (False when the connection is gone)"},
    {"submit_buffered", (PyCFunction)Channel_submit_buffered, METH_VARARGS,
     "submit_buffered(frame) -> bool (no syscall until flush/cap)"},
    {"flush", (PyCFunction)Channel_flush, METH_NOARGS,
     "flush() -> bool: one send for every buffered frame"},
    {"recv_reply", (PyCFunction)Channel_recv_reply, METH_VARARGS,
     "recv_reply(timeout_ms) -> (task_id, flags, payload) | None; raises "
     "ConnectionError when the channel is dead"},
    {"recv_replies", (PyCFunction)Channel_recv_replies, METH_VARARGS,
     "recv_replies(timeout_ms) -> list of replies | None; drains the "
     "whole readable burst per call"},
    {"is_dead", (PyCFunction)Channel_is_dead, METH_NOARGS, ""},
    {"close", (PyCFunction)Channel_close, METH_NOARGS, ""},
    {nullptr, nullptr, 0, nullptr}};

static PyTypeObject ChannelType = {
    PyVarObject_HEAD_INIT(nullptr, 0)
};

// ---------- Raylet core (native local dispatch) ----------
//
// C++ counterpart of the reference raylet's local task manager + worker
// lease grant (/root/reference/src/ray/raylet/local_task_manager.cc,
// node_manager.cc HandleRequestWorkerLease:1892): the steady-state
// dispatch cycle for plain stateless tasks —
//
//   caller 0x10 SUBMIT -> [queue] -> resource deduct + idle-worker pick
//     -> 0x11 ASSIGN to the worker -> worker 0x12 DONE -> resource return
//     -> next dispatch; 0x13 batches sealed-object ids
//
// runs entirely inside the Server's epoll thread with the GIL released.
// Python stays the OWNER of policy (placement groups, affinity, labels,
// runtime envs, actor lifecycle, retries on worker death, multi-node
// spillback) and calls in through the raylet_* methods; the ledger here
// is the single owner of node resources so the two lanes cannot drift.
//
// Binary node-service frames (first byte; pickled frames start 0x80):
//   0x10 SUBMIT : [u8 tl][tid][f64 cpu][payload = pickled TaskSpec]
//   0x11 ASSIGN : [u8 tl][tid][payload]            (raylet -> worker)
//   0x12 DONE   : [u8 tl][tid][u8 ok]              (worker -> raylet)
//   0x13 SEALED : [u8 n]{[u8 len][oid]}*n          (worker -> raylet)

struct ServerCore;

struct RayletCore {
  std::mutex mu;  // guards everything below (serve thread + Python threads)
  std::map<std::string, double> avail;  // the node resource ledger
  std::deque<uint64_t> idle;            // native-capable idle workers
  std::set<uint64_t> idle_set;
  std::set<uint64_t> bound;             // all native-bound worker conns
  struct Pending {
    std::string tid;
    std::string name;
    double cpu;
    std::string assign;  // pre-built 0x11 frame body
  };
  std::deque<Pending> pending;
  struct InFlight {
    double cpu;
    std::string assign;  // kept for worker-death orphan recovery
    std::string name;
    bool blocked = false;  // CPU released while the task blocks in get()
  };
  std::unordered_map<uint64_t, std::map<std::string, InFlight>> inflight;
  // Per-dead-conn assign frames (keyed so OOM provenance of ONE worker's
  // kill is never applied to another's orphans).
  std::map<uint64_t, std::vector<std::string>> orphans;
  // Assign frames of tasks whose demand exceeds node TOTALS — can never
  // dispatch; Python fails them with a clear error.
  std::vector<std::string> infeasible;
  bool infeasible_marker = false;
  std::map<std::string, double> total;  // node totals (infeasibility)
  std::vector<std::string> sealed;   // oid batch for Python to publish
  bool sealed_marker = false;  // a drain marker is already queued to Python
  // Task-event ring for the state API / timeline (reference:
  // GcsTaskManager): Python drains + merges lazily on state queries, so
  // the steady state writes a struct, never wakes Python.
  // state: 0=PENDING 1=RUNNING 2=FINISHED 3=FAILED
  struct Event {
    std::string tid;
    std::string name;
    uint8_t state;
    double ts;
  };
  std::deque<Event> events;
  // flag-registry tunable (RTPU_RAYLET_EVENT_CAP, _private/flags.py)
  size_t max_events = [] {
    const char* v = getenv("RTPU_RAYLET_EVENT_CAP");
    if (!v || !*v) return size_t(50000);
    char* end = nullptr;
    long long n = strtoll(v, &end, 10);
    // garbage/non-positive falls back (registry _coerce contract)
    return (end && *end == '\0' && n > 0) ? size_t(n) : size_t(50000);
  }();

  void push_event_locked(const std::string& tid, const std::string& name,
                         uint8_t state) {
    struct timespec t;
    clock_gettime(CLOCK_REALTIME, &t);
    events.push_back({tid, name, state, double(t.tv_sec) +
                                            double(t.tv_nsec) * 1e-9});
    while (events.size() > max_events) events.pop_front();
  }
  uint64_t n_dispatched = 0, n_done = 0, n_submitted = 0;
  bool enabled = false;
  bool accept_submits = true;  // false: 0x10 falls through to Python
                               // (multi-node policy path)

  bool try_acquire_locked(const std::map<std::string, double>& need) {
    for (const auto& [k, v] : need) {
      auto it = avail.find(k);
      if ((it == avail.end() ? 0.0 : it->second) < v) return false;
    }
    for (const auto& [k, v] : need) avail[k] -= v;
    return true;
  }

  void release_locked(const std::map<std::string, double>& res) {
    for (const auto& [k, v] : res) avail[k] += v;
  }

  void remove_worker_locked(uint64_t id) {
    bound.erase(id);
    if (idle_set.erase(id)) {
      for (auto it = idle.begin(); it != idle.end(); ++it) {
        if (*it == id) {
          idle.erase(it);
          break;
        }
      }
    }
    auto inf = inflight.find(id);
    if (inf != inflight.end()) {
      for (auto& [tid, fl] : inf->second) {
        if (!fl.blocked) avail["CPU"] += fl.cpu;  // blocked already returned
        orphans[id].push_back(std::move(fl.assign));
      }
      inflight.erase(inf);
    }
  }
};

// ---------- Server (callee side) ----------

struct ConnState {
  int fd;
  std::string in;
  enum Phase { AUTH, READY } phase = READY;
};

struct ServerCore {
  // Threadless contract: the conns map and every socket write/read/close
  // happen ONLY on the thread inside Server_next (the Python executor).
  // Other threads (max_concurrency>1 pool callbacks) hand replies over
  // through out_queue + an eventfd wake — they never touch sockets, so
  // there is no map race and no send-to-recycled-fd window.
  int epfd = -1;
  int listen_fd = -1;
  int wake_fd = -1;  // eventfd: reply producers wake the epoll loop
  bool is_tcp = false;
  bool closed = false;
  std::string token;
  std::map<uint64_t, ConnState> conns;
  uint64_t next_conn_id = 1;
  std::map<int, uint64_t> by_fd;
  std::deque<std::pair<uint64_t, std::string>> ready;  // parsed call frames
  std::mutex out_mu;  // guards out_queue only
  std::deque<std::pair<uint64_t, std::string>> out_queue;
  std::mutex dummy_send_mu;  // sends are single-threaded; kept for helpers
  RayletCore* raylet = nullptr;
  // Native memory monitor (reference: src/ray/common/memory_monitor.h —
  // a C++ timer sampling cgroup/meminfo usage).  Sampling + threshold
  // detection run here in the epoll loop (no GIL, no Python thread); on
  // a crossing, a 0x7e marker frame wakes Python, which owns the victim
  // policy and the kill (our C++/Python split everywhere).
  // atomics: enable/ack are called from Python threads while the serve
  // thread reads these GIL-free inside Server_next
  std::atomic<double> mm_threshold{0};  // 0 = disabled
  std::atomic<double> mm_interval_s{1.0};
  std::atomic<double> mm_cooldown_s{5.0};
  std::atomic<double> mm_next_check{0};
  std::atomic<double> mm_last_fire{0};

  static bool node_mem_usage(uint64_t* used, uint64_t* total) {
    // cgroup v2 first (containerized nodes), /proc/meminfo fallback
    FILE* f = fopen("/sys/fs/cgroup/memory.max", "r");
    if (f) {
      char buf[64] = {0};
      bool have = fgets(buf, sizeof buf, f) != nullptr;
      fclose(f);
      if (have && strncmp(buf, "max", 3) != 0) {
        uint64_t limit = strtoull(buf, nullptr, 10);
        FILE* g = fopen("/sys/fs/cgroup/memory.current", "r");
        if (g && limit > 0) {
          char cur[64] = {0};
          bool ok = fgets(cur, sizeof cur, g) != nullptr;
          fclose(g);
          if (ok) {
            *used = strtoull(cur, nullptr, 10);
            *total = limit;
            return true;
          }
        } else if (g) {
          fclose(g);
        }
      }
    }
    f = fopen("/proc/meminfo", "r");
    if (!f) return false;
    uint64_t total_kb = 0, avail_kb = 0;
    char line[256];
    while (fgets(line, sizeof line, f)) {
      if (sscanf(line, "MemTotal: %lu kB", &total_kb) == 1) continue;
      if (sscanf(line, "MemAvailable: %lu kB", &avail_kb) == 1) continue;
    }
    fclose(f);
    if (total_kb == 0) return false;
    *total = total_kb * 1024;
    *used = (total_kb > avail_kb ? total_kb - avail_kb : 0) * 1024;
    return true;
  }

  static double mono_now() {
    struct timespec ts;
    clock_gettime(CLOCK_MONOTONIC, &ts);
    return double(ts.tv_sec) + double(ts.tv_nsec) * 1e-9;
  }

  // Serve-thread only: sample on the interval; emit ONE 0x7e marker
  // (u64 used | u64 total, LE) per crossing, rate-limited by cooldown.
  void memory_check() {
    double thr = mm_threshold.load();
    if (thr <= 0) return;
    double now = mono_now();
    if (now < mm_next_check.load()) return;
    mm_next_check.store(now + mm_interval_s.load());
    uint64_t used = 0, total = 0;
    if (!node_mem_usage(&used, &total) || total == 0) return;
    if (double(used) / double(total) < thr) return;
    if (now - mm_last_fire.load() < mm_cooldown_s.load()) return;
    mm_last_fire.store(now);
    std::string frame(17, '\0');
    frame[0] = char(0x7e);
    memcpy(frame.data() + 1, &used, 8);
    memcpy(frame.data() + 9, &total, 8);
    ready.emplace_back(0, std::move(frame));
  }
  std::vector<uint64_t> pending_drops;  // conns to drop after event loop

  void drop(uint64_t id) {
    auto it = conns.find(id);
    if (it == conns.end()) return;
    epoll_ctl(epfd, EPOLL_CTL_DEL, it->second.fd, nullptr);
    by_fd.erase(it->second.fd);
    ::close(it->second.fd);
    bool was_ready = it->second.phase == ConnState::READY;
    conns.erase(it);
    if (raylet) {
      std::lock_guard<std::mutex> g(raylet->mu);
      raylet->remove_worker_locked(id);
    }
    // surface the disconnect to Python as an EMPTY frame (never legal on
    // the wire) so the consumer can run its death/cleanup handler — the
    // raylet-mode consumer requeues the dead worker's in-flight tasks
    if (was_ready) ready.emplace_back(id, std::string());
  }

  // Serve-thread only: dispatch queued plain tasks onto idle workers.
  void raylet_pump() {
    RayletCore* r = raylet;
    if (!r || !r->enabled) return;
    std::vector<std::pair<uint64_t, std::string>> sends;
    bool emit_sealed = false, emit_infeasible = false;
    {
      std::lock_guard<std::mutex> g(r->mu);
      if (!r->sealed.empty() && !r->sealed_marker) {
        // wake Python exactly once per batch to publish locations
        r->sealed_marker = true;
        emit_sealed = true;
      }
      // Infeasible tasks are routed at SUBMIT time (node totals are
      // immutable after raylet_enable, so the check is O(1) per task and
      // never needs a queue scan); the pump only publishes the wake-up so
      // Python fails them — unconditionally, NOT gated on idle workers.
      if (!r->infeasible.empty() && !r->infeasible_marker) {
        r->infeasible_marker = true;
        emit_infeasible = true;
      }
      // First-fit over the WHOLE queue: a head task waiting for capacity
      // must not wedge smaller tasks behind it (the Python lane requeues
      // unschedulable specs and keeps going — same semantics here).
      for (auto it = r->pending.begin();
           it != r->pending.end() && !r->idle.empty();) {
        RayletCore::Pending& p = *it;
        if (p.cpu > 0) {
          std::map<std::string, double> need{{"CPU", p.cpu}};
          if (!r->try_acquire_locked(need)) {
            ++it;  // not now; later (smaller) tasks may still fit
            continue;
          }
        }
        uint64_t w = r->idle.front();
        r->idle.pop_front();
        r->idle_set.erase(w);
        r->push_event_locked(p.tid, p.name, 1);
        r->inflight[w].emplace(
            p.tid, RayletCore::InFlight{p.cpu, p.assign, p.name});
        sends.emplace_back(w, std::move(p.assign));
        r->n_dispatched++;
        it = r->pending.erase(it);
      }
    }
    if (emit_sealed) ready.emplace_back(0, std::string("\x13"));
    if (emit_infeasible) ready.emplace_back(0, std::string("\x7f"));
    for (auto& [w, frame] : sends) {
      auto it = conns.find(w);
      bool ok = it != conns.end() &&
                send_frame(it->second.fd, dummy_send_mu, frame.data(),
                           frame.size());
      if (!ok) {
        // worker vanished mid-dispatch: orphan the task for Python's
        // retry path and schedule the connection drop
        std::lock_guard<std::mutex> g(r->mu);
        size_t tl = frame.size() >= 2 ? uint8_t(frame[1]) : 0;
        std::string tid = frame.size() >= 2 + tl ? frame.substr(2, tl)
                                                 : std::string();
        auto inf = r->inflight.find(w);
        if (inf != r->inflight.end()) {
          auto t = inf->second.find(tid);
          if (t != inf->second.end()) {
            r->avail["CPU"] += t->second.cpu;
            r->orphans[w].push_back(std::move(t->second.assign));
            inf->second.erase(t);
          }
        }
        pending_drops.push_back(w);
      }
    }
  }

  // Serve-thread only: true when the frame was a raylet-lane frame.
  bool raylet_handle(uint64_t id, const std::string& f) {
    RayletCore* r = raylet;
    if (!r || !r->enabled || f.size() < 2) return false;
    uint8_t k = uint8_t(f[0]);
    if (k == 0x10) {  // SUBMIT from a worker/driver connection
      if (!r->accept_submits) return false;  // Python policy path takes it
      size_t tl = uint8_t(f[1]);
      if (f.size() < 2 + tl + 8 + 2) return true;  // malformed: swallow
      std::string tid = f.substr(2, tl);
      double cpu;
      memcpy(&cpu, f.data() + 2 + tl, 8);
      uint16_t nl;
      memcpy(&nl, f.data() + 2 + tl + 8, 2);
      size_t off = 2 + tl + 8 + 2;
      if (f.size() < off + nl) return true;
      std::string name = f.substr(off, nl);
      off += nl;
      std::string assign;
      assign.reserve(f.size() - off + 2 + tl);
      assign.push_back(char(0x11));
      assign.push_back(char(tl));
      assign += tid;
      assign.append(f, off, std::string::npos);
      std::lock_guard<std::mutex> g(r->mu);
      r->n_submitted++;
      r->push_event_locked(tid, name, 0);
      auto tot = r->total.find("CPU");
      if (cpu > (tot == r->total.end() ? 0.0 : tot->second)) {
        // demand exceeds node totals: fail fast even with zero idle
        // workers — never queue what can never run
        r->infeasible.push_back(std::move(assign));
      } else {
        r->pending.push_back(
            {std::move(tid), std::move(name), cpu, std::move(assign)});
      }
      return true;
    }
    if (k == 0x12) {  // DONE
      size_t tl = uint8_t(f[1]);
      if (f.size() < 2 + tl) return true;
      std::lock_guard<std::mutex> g(r->mu);
      auto inf = r->inflight.find(id);
      if (inf != r->inflight.end()) {
        std::string tid = f.substr(2, tl);
        auto t = inf->second.find(tid);
        if (t != inf->second.end()) {
          if (!t->second.blocked) r->avail["CPU"] += t->second.cpu;
          bool ok = f.size() > 2 + tl && f[2 + tl] != 0;
          r->push_event_locked(tid, t->second.name, ok ? 2 : 3);
          inf->second.erase(t);
          r->n_done++;
        }
      }
      if (r->bound.count(id) && !r->idle_set.count(id) &&
          (inf == r->inflight.end() || inf->second.empty())) {
        r->idle.push_back(id);
        r->idle_set.insert(id);
      }
      return true;
    }
    if (k == 0x13) {  // SEALED oid batch
      size_t n = uint8_t(f[1]);
      size_t pos = 2;
      std::lock_guard<std::mutex> g(r->mu);
      for (size_t i = 0; i < n && pos < f.size(); ++i) {
        size_t l = uint8_t(f[pos]);
        pos += 1;
        if (pos + l > f.size()) break;
        r->sealed.emplace_back(f, pos, l);
        pos += l;
      }
      return true;
    }
    return false;
  }

  // Exec-thread only: drain queued replies onto their sockets.  An empty
  // queued frame is the close command (Server.kick).
  void flush_replies() {
    for (;;) {
      uint64_t id;
      std::string frame;
      {
        std::lock_guard<std::mutex> g(out_mu);
        if (out_queue.empty()) return;
        id = out_queue.front().first;
        frame = std::move(out_queue.front().second);
        out_queue.pop_front();
      }
      auto it = conns.find(id);
      if (it == conns.end()) continue;  // caller hung up; it will resend
      if (frame.empty()) {
        drop(id);
        continue;
      }
      if (!send_frame(it->second.fd, dummy_send_mu, frame.data(),
                      frame.size()))
        drop(id);
    }
  }

  void accept_ready() {
    for (;;) {
      int fd = ::accept4(listen_fd, nullptr, nullptr, SOCK_NONBLOCK);
      if (fd < 0) return;
      if (is_tcp) {
        int one = 1;
        setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof one);
      }
      uint64_t id = next_conn_id++;  // starts at 2 (0=listener, 1=wake)
      ConnState cs;
      cs.fd = fd;
      cs.phase = is_tcp ? ConnState::AUTH : ConnState::READY;
      conns.emplace(id, std::move(cs));
      by_fd[fd] = id;
      struct epoll_event ev;
      ev.events = EPOLLIN;
      ev.data.u64 = id;
      epoll_ctl(epfd, EPOLL_CTL_ADD, fd, &ev);
    }
  }

  // Read everything available on conn `id`; parse complete frames into
  // `ready`.  Returns false when the conn died.
  bool read_conn(uint64_t id) {
    auto it = conns.find(id);
    if (it == conns.end()) return false;
    ConnState& cs = it->second;
    char buf[1 << 16];
    for (;;) {
      ssize_t k = ::recv(cs.fd, buf, sizeof buf, 0);
      if (k > 0) {
        cs.in.append(buf, size_t(k));
        if (cs.in.size() > kMaxFrame + 4) return false;
      } else if (k == 0) {
        return false;
      } else if (errno == EAGAIN || errno == EWOULDBLOCK) {
        break;
      } else if (errno != EINTR) {
        return false;
      }
    }
    std::string frame;
    int fr;
    while ((fr = extract_frame(cs.in, &frame)) != 0) {
      if (fr < 0) return false;  // poisoned framing: drop the connection
      if (cs.phase == ConnState::AUTH) {
        // cluster-token handshake (reference of record:
        // protocol.py authenticate_server_side), constant-time-ish
        unsigned char d = frame.size() == token.size() ? 0 : 1;
        for (size_t i = 0; i < frame.size() && i < token.size(); ++i)
          d |= (unsigned char)(frame[i]) ^ (unsigned char)(token[i]);
        if (d != 0) {
          send_frame(cs.fd, dummy_send_mu, "NO", 2);
          return false;
        }
        if (!send_frame(cs.fd, dummy_send_mu, "OK", 2)) return false;
        cs.phase = ConnState::READY;
        continue;
      }
      if (frame.empty()) continue;  // empty frames are reserved markers
      if (raylet && raylet_handle(id, frame)) {
        frame.clear();
        continue;  // consumed natively: Python never sees it
      }
      ready.emplace_back(id, std::move(frame));
      frame.clear();
    }
    return true;
  }
};

typedef struct {
  PyObject_HEAD
  ServerCore* core;
} ServerObject;

static PyObject* Server_new(PyTypeObject* type, PyObject* args,
                            PyObject* kwds) {
  int fd, is_tcp;
  const char* token;
  Py_ssize_t token_len;
  if (!PyArg_ParseTuple(args, "ipy#", &fd, &is_tcp, &token, &token_len))
    return nullptr;
  ServerObject* self = (ServerObject*)type->tp_alloc(type, 0);
  if (!self) return nullptr;
  ServerCore* c = new ServerCore();
  self->core = c;
  c->listen_fd = fd;
  c->is_tcp = is_tcp != 0;
  c->token.assign(token, size_t(token_len));
  c->next_conn_id = 2;  // 0 = listener sentinel, 1 = wake sentinel
  int flags = fcntl(fd, F_GETFL, 0);
  fcntl(fd, F_SETFL, flags | O_NONBLOCK);
  c->epfd = epoll_create1(0);
  struct epoll_event ev;
  ev.events = EPOLLIN;
  ev.data.u64 = 0;  // 0 = the listener
  epoll_ctl(c->epfd, EPOLL_CTL_ADD, fd, &ev);
  c->wake_fd = eventfd(0, EFD_NONBLOCK);
  struct epoll_event wev;
  wev.events = EPOLLIN;
  wev.data.u64 = 1;  // 1 = reply-queue wake
  epoll_ctl(c->epfd, EPOLL_CTL_ADD, c->wake_fd, &wev);
  return (PyObject*)self;
}

static void Server_dealloc(ServerObject* self) {
  ServerCore* c = self->core;
  if (c) {
    for (auto& [id, cs] : c->conns) ::close(cs.fd);
    ::close(c->listen_fd);
    ::close(c->wake_fd);
    ::close(c->epfd);
    delete c->raylet;
    delete c;
    self->core = nullptr;
  }
  Py_TYPE(self)->tp_free((PyObject*)self);
}

// ---------- raylet_* methods (Python-facing; touch state, never sockets) --

static bool dict_to_resmap(PyObject* d, std::map<std::string, double>* out) {
  PyObject *key, *value;
  Py_ssize_t pos = 0;
  while (PyDict_Next(d, &pos, &key, &value)) {
    const char* k = PyUnicode_AsUTF8(key);
    double v = PyFloat_AsDouble(value);
    if (!k || (v == -1.0 && PyErr_Occurred())) return false;
    (*out)[k] = v;
  }
  return true;
}

static void raylet_wake(ServerCore* c) {
  uint64_t one = 1;
  (void)!::write(c->wake_fd, &one, 8);
}

static RayletCore* raylet_of(ServerObject* self) {
  ServerCore* c = self->core;
  if (!c->raylet) {
    PyErr_SetString(PyExc_RuntimeError, "raylet not enabled");
    return nullptr;
  }
  return c->raylet;
}

static PyObject* Server_raylet_enable(ServerObject* self, PyObject* args) {
  PyObject* resources;
  if (!PyArg_ParseTuple(args, "O!", &PyDict_Type, &resources))
    return nullptr;
  ServerCore* c = self->core;
  if (!c->raylet) c->raylet = new RayletCore();
  std::map<std::string, double> res;
  if (!dict_to_resmap(resources, &res)) return nullptr;
  {
    std::lock_guard<std::mutex> g(c->raylet->mu);
    c->raylet->total = res;
    c->raylet->avail = std::move(res);
    c->raylet->enabled = true;
  }
  Py_RETURN_NONE;
}

static PyObject* Server_raylet_try_acquire(ServerObject* self,
                                           PyObject* args) {
  PyObject* d;
  if (!PyArg_ParseTuple(args, "O!", &PyDict_Type, &d)) return nullptr;
  RayletCore* r = raylet_of(self);
  if (!r) return nullptr;
  std::map<std::string, double> need;
  if (!dict_to_resmap(d, &need)) return nullptr;
  bool ok;
  {
    std::lock_guard<std::mutex> g(r->mu);
    ok = r->try_acquire_locked(need);
  }
  return PyBool_FromLong(ok);
}

static PyObject* Server_raylet_release(ServerObject* self, PyObject* args) {
  PyObject* d;
  if (!PyArg_ParseTuple(args, "O!", &PyDict_Type, &d)) return nullptr;
  RayletCore* r = raylet_of(self);
  if (!r) return nullptr;
  std::map<std::string, double> res;
  if (!dict_to_resmap(d, &res)) return nullptr;
  {
    std::lock_guard<std::mutex> g(r->mu);
    r->release_locked(res);
  }
  raylet_wake(self->core);  // freed capacity may unblock queued dispatch
  Py_RETURN_NONE;
}

static PyObject* Server_raylet_force_acquire(ServerObject* self,
                                             PyObject* args) {
  // Unconditional deduct (may go negative): the unblock path accepts
  // transient oversubscription, matching the Python scheduler's rule.
  PyObject* d;
  if (!PyArg_ParseTuple(args, "O!", &PyDict_Type, &d)) return nullptr;
  RayletCore* r = raylet_of(self);
  if (!r) return nullptr;
  std::map<std::string, double> res;
  if (!dict_to_resmap(d, &res)) return nullptr;
  {
    std::lock_guard<std::mutex> g(r->mu);
    for (const auto& [k, v] : res) r->avail[k] -= v;
  }
  Py_RETURN_NONE;
}

static PyObject* Server_raylet_snapshot(ServerObject* self, PyObject*) {
  RayletCore* r = raylet_of(self);
  if (!r) return nullptr;
  std::map<std::string, double> copy;
  {
    std::lock_guard<std::mutex> g(r->mu);
    copy = r->avail;
  }
  PyObject* d = PyDict_New();
  if (!d) return nullptr;
  for (const auto& [k, v] : copy) {
    PyObject* val = PyFloat_FromDouble(v);
    if (!val || PyDict_SetItemString(d, k.c_str(), val) < 0) {
      Py_XDECREF(val);
      Py_DECREF(d);
      return nullptr;
    }
    Py_DECREF(val);
  }
  return d;
}

static PyObject* Server_memory_monitor_enable(ServerObject* self,
                                              PyObject* args) {
  // (threshold_fraction, interval_s, cooldown_s); threshold 0 disables.
  double threshold, interval, cooldown;
  if (!PyArg_ParseTuple(args, "ddd", &threshold, &interval, &cooldown))
    return nullptr;
  ServerCore* c = self->core;
  c->mm_threshold.store(threshold);
  c->mm_interval_s.store(interval > 0 ? interval : 1.0);
  c->mm_cooldown_s.store(cooldown >= 0 ? cooldown : 5.0);
  c->mm_next_check.store(0);
  raylet_wake(c);  // re-enter epoll with the capped timeout
  Py_RETURN_NONE;
}

static PyObject* Server_memory_monitor_ack(ServerObject* self,
                                           PyObject* args) {
  // Python reports the crossing's outcome.  No victim killed -> clear
  // the cooldown so the next interval can fire again: a no-op crossing
  // must not suppress pressure response while memory keeps climbing
  // (Python's check_once only cooled down after a SUCCESSFUL kill).
  int killed;
  if (!PyArg_ParseTuple(args, "p", &killed)) return nullptr;
  if (!killed) self->core->mm_last_fire.store(0);
  Py_RETURN_NONE;
}

static PyObject* Server_raylet_debug(ServerObject* self, PyObject*) {
  // Introspection for tests/diagnosis: (idle ids, bound ids,
  // {conn: [task ids]} inflight).  Not a hot path.
  RayletCore* r = raylet_of(self);
  if (!r) return nullptr;
  std::vector<uint64_t> idle, bound;
  std::vector<std::pair<uint64_t, std::vector<std::string>>> inflight;
  {
    std::lock_guard<std::mutex> g(r->mu);
    idle.assign(r->idle.begin(), r->idle.end());
    bound.assign(r->bound.begin(), r->bound.end());
    for (auto& [cid, tasks] : r->inflight) {
      std::vector<std::string> tids;
      for (auto& [tid, _] : tasks) tids.push_back(tid);
      if (!tids.empty()) inflight.emplace_back(cid, std::move(tids));
    }
  }
  PyObject* d = PyDict_New();
  PyObject* li = PyList_New(0);
  for (auto v : idle) {
    PyObject* o = PyLong_FromUnsignedLongLong(v);
    PyList_Append(li, o);
    Py_DECREF(o);
  }
  PyDict_SetItemString(d, "idle", li);
  Py_DECREF(li);
  PyObject* lb = PyList_New(0);
  for (auto v : bound) {
    PyObject* o = PyLong_FromUnsignedLongLong(v);
    PyList_Append(lb, o);
    Py_DECREF(o);
  }
  PyDict_SetItemString(d, "bound", lb);
  Py_DECREF(lb);
  PyObject* linf = PyDict_New();
  for (auto& [cid, tids] : inflight) {
    PyObject* key = PyLong_FromUnsignedLongLong(cid);
    PyObject* tl = PyList_New(0);
    for (auto& t : tids) {
      PyObject* b = PyBytes_FromStringAndSize(t.data(),
                                              Py_ssize_t(t.size()));
      PyList_Append(tl, b);
      Py_DECREF(b);
    }
    PyDict_SetItem(linf, key, tl);
    Py_DECREF(key);
    Py_DECREF(tl);
  }
  PyDict_SetItemString(d, "inflight", linf);
  Py_DECREF(linf);
  return d;
}

static PyObject* Server_raylet_bind_worker(ServerObject* self,
                                           PyObject* args) {
  unsigned long long conn_id;
  if (!PyArg_ParseTuple(args, "K", &conn_id)) return nullptr;
  RayletCore* r = raylet_of(self);
  if (!r) return nullptr;
  {
    std::lock_guard<std::mutex> g(r->mu);
    r->bound.insert(conn_id);
    if (!r->idle_set.count(conn_id)) {
      r->idle.push_back(conn_id);
      r->idle_set.insert(conn_id);
    }
  }
  raylet_wake(self->core);
  Py_RETURN_NONE;
}

static PyObject* Server_raylet_acquire_worker(ServerObject* self,
                                              PyObject*) {
  // Python-lane lease: pop an idle worker for a non-plain task (PG /
  // actor / custom-resource); the caller dispatches + releases it.
  RayletCore* r = raylet_of(self);
  if (!r) return nullptr;
  uint64_t id = 0;
  {
    std::lock_guard<std::mutex> g(r->mu);
    if (r->idle.empty()) Py_RETURN_NONE;
    id = r->idle.front();
    r->idle.pop_front();
    r->idle_set.erase(id);
  }
  return PyLong_FromUnsignedLongLong(id);
}

static PyObject* Server_raylet_release_worker(ServerObject* self,
                                              PyObject* args) {
  unsigned long long conn_id;
  if (!PyArg_ParseTuple(args, "K", &conn_id)) return nullptr;
  RayletCore* r = raylet_of(self);
  if (!r) return nullptr;
  {
    std::lock_guard<std::mutex> g(r->mu);
    if (r->bound.count(conn_id) && !r->idle_set.count(conn_id)) {
      r->idle.push_back(conn_id);
      r->idle_set.insert(conn_id);
    }
  }
  raylet_wake(self->core);
  Py_RETURN_NONE;
}

static PyObject* Server_raylet_submit(ServerObject* self, PyObject* args) {
  // In-process submit (the driver on the head node): same lane as a 0x10
  // frame, without a socket hop.
  Py_buffer tid, payload;
  double cpu;
  const char* name;
  Py_ssize_t name_len;
  if (!PyArg_ParseTuple(args, "y*ds#y*", &tid, &cpu, &name, &name_len,
                        &payload))
    return nullptr;
  RayletCore* r = raylet_of(self);
  if (!r) {
    PyBuffer_Release(&tid);
    PyBuffer_Release(&payload);
    return nullptr;
  }
  std::string t((const char*)tid.buf, size_t(tid.len));
  std::string assign;
  assign.reserve(2 + t.size() + size_t(payload.len));
  assign.push_back(char(0x11));
  assign.push_back(char(uint8_t(t.size())));
  assign += t;
  assign.append((const char*)payload.buf, size_t(payload.len));
  {
    std::lock_guard<std::mutex> g(r->mu);
    r->n_submitted++;
    r->push_event_locked(t, std::string(name, size_t(name_len)), 0);
    auto tot = r->total.find("CPU");
    if (cpu > (tot == r->total.end() ? 0.0 : tot->second)) {
      r->infeasible.push_back(std::move(assign));
    } else {
      r->pending.push_back({std::move(t),
                            std::string(name, size_t(name_len)), cpu,
                            std::move(assign)});
    }
  }
  PyBuffer_Release(&tid);
  PyBuffer_Release(&payload);
  raylet_wake(self->core);
  Py_RETURN_NONE;
}

static PyObject* Server_raylet_native_inflight(ServerObject* self,
                                               PyObject*) {
  // {conn_id: in-flight native task count} — the OOM killer's victim
  // policy needs to see native-lane busyness (Python's WorkerState
  // in_flight only tracks the policy lane).
  RayletCore* r = raylet_of(self);
  if (!r) return nullptr;
  PyObject* d = PyDict_New();
  if (!d) return nullptr;
  std::vector<std::pair<uint64_t, size_t>> rows;
  {
    std::lock_guard<std::mutex> g(r->mu);
    for (const auto& [w, m] : r->inflight)
      if (!m.empty()) rows.emplace_back(w, m.size());
  }
  for (const auto& [w, n] : rows) {
    PyObject* key = PyLong_FromUnsignedLongLong(w);
    PyObject* val = PyLong_FromSize_t(n);
    if (!key || !val || PyDict_SetItem(d, key, val) < 0) {
      Py_XDECREF(key);
      Py_XDECREF(val);
      Py_DECREF(d);
      return nullptr;
    }
    Py_DECREF(key);
    Py_DECREF(val);
  }
  return d;
}

static PyObject* Server_raylet_drain_events(ServerObject* self, PyObject*) {
  // [(task_id, name, state, ts), ...]; state 0=PENDING 1=RUNNING
  // 2=FINISHED 3=FAILED.  Python merges into its task-event table on
  // state-API queries.
  RayletCore* r = raylet_of(self);
  if (!r) return nullptr;
  std::deque<RayletCore::Event> out;
  {
    std::lock_guard<std::mutex> g(r->mu);
    out.swap(r->events);
  }
  PyObject* list = PyList_New(Py_ssize_t(out.size()));
  if (!list) return nullptr;
  Py_ssize_t i = 0;
  for (const auto& e : out) {
    // lenient name decode: a truncated/garbled UTF-8 name must not
    // poison the whole drained batch
    PyObject* name = PyUnicode_DecodeUTF8(
        e.name.data(), Py_ssize_t(e.name.size()), "replace");
    if (!name) {
      Py_DECREF(list);
      return nullptr;
    }
    PyObject* item = Py_BuildValue(
        "(y#Nid)", e.tid.data(), Py_ssize_t(e.tid.size()), name,
        int(e.state), e.ts);  // N: item owns `name`
    if (!item) {
      Py_DECREF(list);
      return nullptr;
    }
    PyList_SET_ITEM(list, i++, item);
  }
  return list;
}

static PyObject* Server_raylet_set_accept(ServerObject* self,
                                          PyObject* args) {
  // false: 0x10 SUBMITs fall through to Python (multi-node spillback
  // policy applies); DONE/SEALED stay native either way.
  int accept;
  if (!PyArg_ParseTuple(args, "p", &accept)) return nullptr;
  RayletCore* r = raylet_of(self);
  if (!r) return nullptr;
  {
    std::lock_guard<std::mutex> g(r->mu);
    r->accept_submits = accept != 0;
  }
  Py_RETURN_NONE;
}

static PyObject* Server_raylet_block_worker(ServerObject* self,
                                            PyObject* args) {
  // The worker's running native task entered a blocking get: release its
  // CPU back to the ledger so dependency chains cannot deadlock the node
  // (reference: NotifyDirectCallTaskBlocked, node_manager.cc).  When the
  // notification names the blocking task, only that task's CPU is
  // released — a stale "blocked" arriving after C++ already completed the
  // task and dispatched a new one to the same conn must not credit the
  // NEW task's CPU.
  unsigned long long conn_id;
  const char* tid_buf = nullptr;
  Py_ssize_t tid_len = 0;
  if (!PyArg_ParseTuple(args, "K|y#", &conn_id, &tid_buf, &tid_len))
    return nullptr;
  std::string want(tid_buf ? tid_buf : "", (size_t)tid_len);
  RayletCore* r = raylet_of(self);
  if (!r) return nullptr;
  {
    std::lock_guard<std::mutex> g(r->mu);
    auto inf = r->inflight.find(conn_id);
    if (inf != r->inflight.end()) {
      for (auto& [tid, fl] : inf->second) {
        if (!want.empty() && tid != want) continue;
        if (!fl.blocked) {
          fl.blocked = true;
          r->avail["CPU"] += fl.cpu;
        }
      }
    }
  }
  raylet_wake(self->core);
  Py_RETURN_NONE;
}

static PyObject* Server_raylet_unblock_worker(ServerObject* self,
                                              PyObject* args) {
  // Unconditional re-deduct (transient oversubscription accepted).
  // Matches the task-scoped release in raylet_block_worker.
  unsigned long long conn_id;
  const char* tid_buf = nullptr;
  Py_ssize_t tid_len = 0;
  if (!PyArg_ParseTuple(args, "K|y#", &conn_id, &tid_buf, &tid_len))
    return nullptr;
  std::string want(tid_buf ? tid_buf : "", (size_t)tid_len);
  RayletCore* r = raylet_of(self);
  if (!r) return nullptr;
  {
    std::lock_guard<std::mutex> g(r->mu);
    auto inf = r->inflight.find(conn_id);
    if (inf != r->inflight.end()) {
      for (auto& [tid, fl] : inf->second) {
        if (!want.empty() && tid != want) continue;
        if (fl.blocked) {
          fl.blocked = false;
          r->avail["CPU"] -= fl.cpu;
        }
      }
    }
  }
  Py_RETURN_NONE;
}

static PyObject* Server_raylet_reap_orphans(ServerObject* self,
                                            PyObject* args) {
  // Assign frames ([0x11][tl][tid][payload]) of tasks whose worker died
  // before DONE; Python unpickles the payload and runs its retry policy.
  // Keyed by the dead connection so one worker's death provenance (e.g.
  // an OOM kill) is never applied to another's tasks.
  unsigned long long conn_id;
  if (!PyArg_ParseTuple(args, "K", &conn_id)) return nullptr;
  RayletCore* r = raylet_of(self);
  if (!r) return nullptr;
  std::vector<std::string> out;
  {
    std::lock_guard<std::mutex> g(r->mu);
    auto it = r->orphans.find(conn_id);
    if (it != r->orphans.end()) {
      out = std::move(it->second);
      r->orphans.erase(it);
    }
  }
  PyObject* list = PyList_New(Py_ssize_t(out.size()));
  if (!list) return nullptr;
  for (size_t i = 0; i < out.size(); ++i) {
    PyObject* b =
        PyBytes_FromStringAndSize(out[i].data(), Py_ssize_t(out[i].size()));
    if (!b) {
      Py_DECREF(list);
      return nullptr;
    }
    PyList_SET_ITEM(list, Py_ssize_t(i), b);
  }
  return list;
}

static PyObject* Server_raylet_drain_infeasible(ServerObject* self,
                                                PyObject*) {
  // Assign frames of tasks whose demand exceeds node totals — Python
  // fails them with a precise error instead of queueing forever.
  RayletCore* r = raylet_of(self);
  if (!r) return nullptr;
  std::vector<std::string> out;
  {
    std::lock_guard<std::mutex> g(r->mu);
    out.swap(r->infeasible);
    r->infeasible_marker = false;
  }
  PyObject* list = PyList_New(Py_ssize_t(out.size()));
  if (!list) return nullptr;
  for (size_t i = 0; i < out.size(); ++i) {
    PyObject* b =
        PyBytes_FromStringAndSize(out[i].data(), Py_ssize_t(out[i].size()));
    if (!b) {
      Py_DECREF(list);
      return nullptr;
    }
    PyList_SET_ITEM(list, Py_ssize_t(i), b);
  }
  return list;
}

static PyObject* Server_raylet_steal_pending(ServerObject* self,
                                             PyObject* args) {
  // Move queued tasks back to Python (assign frames).  With no argument
  // the whole queue drains (lane shutdown / drain).  With max_n, up to
  // max_n tasks are stolen from the BACK of the queue — the newest
  // submissions, which are the ones a saturated node's balancer spills
  // to peers while the oldest keep their local dispatch position.
  long long max_n = -1;
  if (!PyArg_ParseTuple(args, "|L", &max_n)) return nullptr;
  RayletCore* r = raylet_of(self);
  if (!r) return nullptr;
  std::deque<RayletCore::Pending> out;
  {
    std::lock_guard<std::mutex> g(r->mu);
    if (max_n < 0 || size_t(max_n) >= r->pending.size()) {
      out.swap(r->pending);
    } else {
      for (long long i = 0; i < max_n; ++i) {
        out.push_front(std::move(r->pending.back()));
        r->pending.pop_back();
      }
    }
  }
  PyObject* list = PyList_New(Py_ssize_t(out.size()));
  if (!list) return nullptr;
  Py_ssize_t i = 0;
  for (auto& p : out) {
    PyObject* b = PyBytes_FromStringAndSize(p.assign.data(),
                                            Py_ssize_t(p.assign.size()));
    if (!b) {
      Py_DECREF(list);
      return nullptr;
    }
    PyList_SET_ITEM(list, i++, b);
  }
  return list;
}

static PyObject* Server_raylet_cancel(ServerObject* self, PyObject* args) {
  // cancel(tid) -> (state, conn_id, frame|None)
  //   state 0: unknown here; 1: removed from the queue (frame returned
  //   so Python can fail the spec's return objects); 2: running on
  //   conn_id (force-cancel kills that worker from Python).
  Py_buffer tid;
  if (!PyArg_ParseTuple(args, "y*", &tid)) return nullptr;
  RayletCore* r = raylet_of(self);
  if (!r) {
    PyBuffer_Release(&tid);
    return nullptr;
  }
  std::string t((const char*)tid.buf, size_t(tid.len));
  PyBuffer_Release(&tid);
  int state = 0;
  uint64_t conn = 0;
  std::string frame;
  {
    std::lock_guard<std::mutex> g(r->mu);
    for (auto it = r->pending.begin(); it != r->pending.end(); ++it) {
      if (it->tid == t) {
        frame = std::move(it->assign);
        r->pending.erase(it);
        state = 1;
        break;
      }
    }
    if (state == 0) {
      for (auto& [w, m] : r->inflight) {
        if (m.count(t)) {
          state = 2;
          conn = w;
          break;
        }
      }
    }
  }
  if (state == 1)
    return Py_BuildValue("(iKy#)", state, (unsigned long long)conn,
                         frame.data(), Py_ssize_t(frame.size()));
  return Py_BuildValue("(iKO)", state, (unsigned long long)conn, Py_None);
}

static PyObject* Server_raylet_drain_sealed(ServerObject* self, PyObject*) {
  RayletCore* r = raylet_of(self);
  if (!r) return nullptr;
  std::vector<std::string> out;
  {
    std::lock_guard<std::mutex> g(r->mu);
    out.swap(r->sealed);
    r->sealed_marker = false;
  }
  PyObject* list = PyList_New(Py_ssize_t(out.size()));
  if (!list) return nullptr;
  for (size_t i = 0; i < out.size(); ++i) {
    PyObject* b =
        PyBytes_FromStringAndSize(out[i].data(), Py_ssize_t(out[i].size()));
    if (!b) {
      Py_DECREF(list);
      return nullptr;
    }
    PyList_SET_ITEM(list, Py_ssize_t(i), b);
  }
  return list;
}

static PyObject* Server_raylet_stats(ServerObject* self, PyObject*) {
  RayletCore* r = raylet_of(self);
  if (!r) return nullptr;
  uint64_t pending, idle, inflight = 0, dispatched, done, submitted;
  double cpu;
  {
    std::lock_guard<std::mutex> g(r->mu);
    pending = r->pending.size();
    idle = r->idle.size();
    for (auto& [w, m] : r->inflight) inflight += m.size();
    dispatched = r->n_dispatched;
    done = r->n_done;
    submitted = r->n_submitted;
    auto it = r->avail.find("CPU");
    cpu = it == r->avail.end() ? 0.0 : it->second;
  }
  return Py_BuildValue(
      "{s:K,s:K,s:K,s:K,s:K,s:K,s:d}", "pending",
      (unsigned long long)pending, "idle", (unsigned long long)idle,
      "inflight", (unsigned long long)inflight, "dispatched",
      (unsigned long long)dispatched, "done", (unsigned long long)done,
      "submitted", (unsigned long long)submitted, "cpu_available", cpu);
}

// next(timeout_ms) -> (conn_id, frame) | None; raises ConnectionError
// after close().  Runs accept/read/parse inline on the calling thread.
static PyObject* Server_next(ServerObject* self, PyObject* args) {
  long timeout_ms;
  if (!PyArg_ParseTuple(args, "l", &timeout_ms)) return nullptr;
  ServerCore* c = self->core;
  if (c->closed) {
    PyErr_SetString(PyExc_ConnectionError, "server closed");
    return nullptr;
  }
  uint64_t conn_id = 0;
  std::string frame;
  bool got = false;
  // absolute caller deadline so monitor ticks never extend a finite wait
  double deadline = timeout_ms >= 0
                        ? ServerCore::mono_now() + timeout_ms / 1000.0
                        : -1.0;
  Py_BEGIN_ALLOW_THREADS
  for (;;) {
    c->flush_replies();  // pool-thread replies drain on THIS thread
    c->raylet_pump();    // dispatch queued plain tasks to idle workers
    c->memory_check();   // native memory monitor (emits 0x7e markers)
    for (uint64_t did : c->pending_drops) c->drop(did);
    c->pending_drops.clear();
    if (!c->ready.empty()) {
      conn_id = c->ready.front().first;
      frame = std::move(c->ready.front().second);
      c->ready.pop_front();
      got = true;
      break;
    }
    struct epoll_event evs[32];
    // the memory monitor needs periodic wakeups even when the caller
    // waits forever: cap the block at the sampling interval and treat
    // that expiry as a tick, not a caller timeout
    long eff_ms;
    if (deadline < 0) {
      eff_ms = -1;
    } else {
      double rem = (deadline - ServerCore::mono_now()) * 1000.0;
      eff_ms = rem > 0 ? long(rem) + 1 : 0;
    }
    bool tick_only = false;
    if (c->mm_threshold.load() > 0) {
      long mm_ms = long(c->mm_interval_s.load() * 1000);
      if (mm_ms < 1) mm_ms = 1;
      if (eff_ms < 0 || eff_ms > mm_ms) {
        eff_ms = mm_ms;
        tick_only = true;
      }
    }
    int n = epoll_wait(c->epfd, evs, 32, int(eff_ms));
    if (n == 0) {
      if (tick_only) continue;  // monitor tick, caller budget remains
      break;  // caller timeout
    }
    if (n < 0) {
      if (errno == EINTR) continue;
      c->closed = true;
      break;
    }
    for (int i = 0; i < n; ++i) {
      if (evs[i].data.u64 == 0) {
        if (evs[i].events & (EPOLLHUP | EPOLLERR)) {
          c->closed = true;
        } else {
          c->accept_ready();
        }
      } else if (evs[i].data.u64 == 1) {
        uint64_t junk;
        while (::read(c->wake_fd, &junk, 8) == 8) {
        }
        // replies flushed at loop top
      } else {
        // read even on HUP: there may be buffered final frames
        uint64_t id = evs[i].data.u64;
        if (!c->read_conn(id)) c->drop(id);
      }
    }
    if (c->closed) break;
  }
  Py_END_ALLOW_THREADS
  if (got)
    return Py_BuildValue("(Ky#)", (unsigned long long)conn_id, frame.data(),
                         Py_ssize_t(frame.size()));
  if (c->closed) {
    PyErr_SetString(PyExc_ConnectionError, "server closed");
    return nullptr;
  }
  Py_RETURN_NONE;
}

static PyObject* Server_reply(ServerObject* self, PyObject* args) {
  // Callable from ANY thread (the exec thread or max_concurrency pool
  // callbacks): only enqueues — the exec thread owns the sockets.
  unsigned long long conn_id;
  Py_buffer frame;
  if (!PyArg_ParseTuple(args, "Ky*", &conn_id, &frame)) return nullptr;
  ServerCore* c = self->core;
  {
    std::lock_guard<std::mutex> g(c->out_mu);
    c->out_queue.emplace_back(
        conn_id, std::string((const char*)frame.buf, size_t(frame.len)));
  }
  uint64_t one = 1;
  (void)!::write(c->wake_fd, &one, 8);  // wake the epoll loop
  PyBuffer_Release(&frame);
  Py_RETURN_TRUE;
}

static PyObject* Server_close(ServerObject* self, PyObject*) {
  ServerCore* c = self->core;
  c->closed = true;
  ::shutdown(c->listen_fd, SHUT_RDWR);
  uint64_t one = 1;
  (void)!::write(c->wake_fd, &one, 8);  // wake a parked next()
  Py_RETURN_NONE;
}

static PyObject* Server_kick(ServerObject* self, PyObject* args) {
  // Close a connection from any thread (processed by the exec thread).
  unsigned long long conn_id;
  if (!PyArg_ParseTuple(args, "K", &conn_id)) return nullptr;
  ServerCore* c = self->core;
  {
    std::lock_guard<std::mutex> g(c->out_mu);
    c->out_queue.emplace_back(conn_id, std::string());
  }
  uint64_t one = 1;
  (void)!::write(c->wake_fd, &one, 8);
  Py_RETURN_NONE;
}

static PyMethodDef Server_methods[] = {
    {"next", (PyCFunction)Server_next, METH_VARARGS,
     "next(timeout_ms) -> (conn_id, frame) | None; an EMPTY frame means "
     "the connection closed; raises ConnectionError after close()"},
    {"reply", (PyCFunction)Server_reply, METH_VARARGS,
     "reply(conn_id, frame) -> bool (enqueued; exec thread flushes)"},
    {"kick", (PyCFunction)Server_kick, METH_VARARGS,
     "kick(conn_id): close a connection"},
    {"close", (PyCFunction)Server_close, METH_NOARGS, ""},
    {"raylet_enable", (PyCFunction)Server_raylet_enable, METH_VARARGS,
     "raylet_enable(resources): turn on native plain-task dispatch; the "
     "resource dict becomes the node ledger (single owner)"},
    {"raylet_try_acquire", (PyCFunction)Server_raylet_try_acquire,
     METH_VARARGS, "raylet_try_acquire({name: amount}) -> bool (atomic)"},
    {"raylet_release", (PyCFunction)Server_raylet_release, METH_VARARGS,
     "raylet_release({name: amount})"},
    {"raylet_force_acquire", (PyCFunction)Server_raylet_force_acquire,
     METH_VARARGS,
     "raylet_force_acquire({name: amount}): unconditional deduct"},
    {"raylet_snapshot", (PyCFunction)Server_raylet_snapshot, METH_NOARGS,
     "raylet_snapshot() -> {name: available}"},
    {"raylet_bind_worker", (PyCFunction)Server_raylet_bind_worker,
     METH_VARARGS, "raylet_bind_worker(conn_id): register + mark idle"},
    {"raylet_debug", (PyCFunction)Server_raylet_debug, METH_NOARGS,
     "raylet_debug() -> {idle, bound, inflight} introspection"},
    {"memory_monitor_enable", (PyCFunction)Server_memory_monitor_enable,
     METH_VARARGS,
     "memory_monitor_enable(threshold, interval_s, cooldown_s): native "
     "usage sampling in the epoll loop; 0x7e markers wake Python"},
    {"memory_monitor_ack", (PyCFunction)Server_memory_monitor_ack,
     METH_VARARGS,
     "memory_monitor_ack(killed): no-kill crossings clear the cooldown"},
    {"raylet_acquire_worker", (PyCFunction)Server_raylet_acquire_worker,
     METH_NOARGS, "raylet_acquire_worker() -> conn_id | None"},
    {"raylet_release_worker", (PyCFunction)Server_raylet_release_worker,
     METH_VARARGS, "raylet_release_worker(conn_id): return to idle pool"},
    {"raylet_submit", (PyCFunction)Server_raylet_submit, METH_VARARGS,
     "raylet_submit(task_id, cpu, payload): enqueue a plain task"},
    {"raylet_set_accept", (PyCFunction)Server_raylet_set_accept,
     METH_VARARGS,
     "raylet_set_accept(bool): route 0x10 SUBMITs natively or to Python"},
    {"raylet_block_worker", (PyCFunction)Server_raylet_block_worker,
     METH_VARARGS,
     "raylet_block_worker(conn_id[, task_id]): release the blocking "
     "task's CPU (all of the conn's tasks when task_id is omitted)"},
    {"raylet_unblock_worker", (PyCFunction)Server_raylet_unblock_worker,
     METH_VARARGS,
     "raylet_unblock_worker(conn_id[, task_id]): re-deduct the matching "
     "task's CPU"},
    {"raylet_reap_orphans", (PyCFunction)Server_raylet_reap_orphans,
     METH_VARARGS,
     "raylet_reap_orphans(conn_id) -> [assign frames of that dead "
     "worker's tasks]"},
    {"raylet_drain_infeasible",
     (PyCFunction)Server_raylet_drain_infeasible, METH_NOARGS,
     "raylet_drain_infeasible() -> [assign frames exceeding node totals]"},
    {"raylet_cancel", (PyCFunction)Server_raylet_cancel, METH_VARARGS,
     "raylet_cancel(task_id) -> (state, conn_id, frame|None)"},
    {"raylet_steal_pending", (PyCFunction)Server_raylet_steal_pending,
     METH_VARARGS,
     "raylet_steal_pending([max_n]) -> [assign frames]; no arg drains "
     "all, max_n steals the newest from the queue back"},
    {"raylet_drain_sealed", (PyCFunction)Server_raylet_drain_sealed,
     METH_NOARGS, "raylet_drain_sealed() -> [oid, ...]"},
    {"raylet_drain_events", (PyCFunction)Server_raylet_drain_events,
     METH_NOARGS,
     "raylet_drain_events() -> [(task_id, name, state, ts), ...]"},
    {"raylet_native_inflight",
     (PyCFunction)Server_raylet_native_inflight, METH_NOARGS,
     "raylet_native_inflight() -> {conn_id: task count}"},
    {"raylet_stats", (PyCFunction)Server_raylet_stats, METH_NOARGS,
     "raylet_stats() -> dispatch counters + ledger CPU"},
    {nullptr, nullptr, 0, nullptr}};

static PyTypeObject ServerType = {
    PyVarObject_HEAD_INIT(nullptr, 0)
};

// ---------- StoreConn (native shm-store client op layer) ----------
//
// One pooled connection to the shm_store daemon (protocol of
// shm_store.cc: fixed 37-byte request / 17-byte response, with OP_PUT
// payload streaming and OP_GET_INLINE payload returns).  The Python
// StoreClient keeps the pool + mmap; each checked-out socket is wrapped
// in a StoreConn so the per-op pack/send/recv runs in C with the GIL
// released — on the multi-client put path the Python per-op overhead is
// comparable to the daemon round trip itself.

struct StoreConnCore {
  int fd = -1;
  bool dead = false;
};

typedef struct {
  PyObject_HEAD
  StoreConnCore* core;
} StoreConnObject;

static PyObject* StoreConn_new(PyTypeObject* type, PyObject* args,
                               PyObject* kwds) {
  int fd;
  if (!PyArg_ParseTuple(args, "i", &fd)) return nullptr;
  StoreConnObject* self = (StoreConnObject*)type->tp_alloc(type, 0);
  if (!self) return nullptr;
  self->core = new StoreConnCore();
  self->core->fd = fd;
  return (PyObject*)self;
}

static void StoreConn_dealloc(StoreConnObject* self) {
  if (self->core) {
    // fd ownership stays with the Python socket object that dialed it
    delete self->core;
    self->core = nullptr;
  }
  Py_TYPE(self)->tp_free((PyObject*)self);
}

static bool recv_full(int fd, char* p, size_t n) {
  while (n > 0) {
    ssize_t k = ::recv(fd, p, n, 0);
    if (k <= 0) {
      if (k < 0 && errno == EINTR) continue;
      return false;
    }
    p += k;
    n -= size_t(k);
  }
  return true;
}

constexpr size_t kStoreIdLen = 20;
constexpr size_t kStoreReqLen = 1 + kStoreIdLen + 8 + 8;
constexpr size_t kStoreRespLen = 1 + 8 + 8;

static void pack_store_req(char* req, uint8_t op, const char* oid,
                           uint64_t a0, uint64_t a1) {
  req[0] = char(op);
  memcpy(req + 1, oid, kStoreIdLen);
  memcpy(req + 1 + kStoreIdLen, &a0, 8);
  memcpy(req + 1 + kStoreIdLen + 8, &a1, 8);
}

// call(op, oid, a0, a1) -> (status, r0, r1)
static PyObject* StoreConn_call(StoreConnObject* self, PyObject* args) {
  int op;
  Py_buffer oid;
  unsigned long long a0, a1;
  if (!PyArg_ParseTuple(args, "iy*KK", &op, &oid, &a0, &a1)) return nullptr;
  if (oid.len != Py_ssize_t(kStoreIdLen)) {
    PyBuffer_Release(&oid);
    PyErr_SetString(PyExc_ValueError, "oid must be 20 bytes");
    return nullptr;
  }
  StoreConnCore* c = self->core;
  char req[kStoreReqLen], resp[kStoreRespLen];
  pack_store_req(req, uint8_t(op), (const char*)oid.buf, a0, a1);
  bool ok = false;
  Py_BEGIN_ALLOW_THREADS
  ok = !c->dead && send_all(c->fd, req, kStoreReqLen) &&
       recv_full(c->fd, resp, kStoreRespLen);
  if (!ok) c->dead = true;
  Py_END_ALLOW_THREADS
  PyBuffer_Release(&oid);
  if (!ok) {
    PyErr_SetString(PyExc_ConnectionError, "object store connection closed");
    return nullptr;
  }
  uint64_t r0, r1;
  memcpy(&r0, resp + 1, 8);
  memcpy(&r1, resp + 1 + 8, 8);
  return Py_BuildValue("(iKK)", int(uint8_t(resp[0])),
                       (unsigned long long)r0, (unsigned long long)r1);
}

// put(oid, payload) -> status  (request + payload in one send when small)
static PyObject* StoreConn_put(StoreConnObject* self, PyObject* args) {
  Py_buffer oid, payload;
  if (!PyArg_ParseTuple(args, "y*y*", &oid, &payload)) return nullptr;
  if (oid.len != Py_ssize_t(kStoreIdLen)) {
    PyBuffer_Release(&oid);
    PyBuffer_Release(&payload);
    PyErr_SetString(PyExc_ValueError, "oid must be 20 bytes");
    return nullptr;
  }
  StoreConnCore* c = self->core;
  bool ok = false;
  char resp[kStoreRespLen];
  Py_BEGIN_ALLOW_THREADS
  if (!c->dead) {
    if (size_t(payload.len) <= 65536 - kStoreReqLen) {
      char buf[65536];
      pack_store_req(buf, 9 /*OP_PUT*/, (const char*)oid.buf,
                     uint64_t(payload.len), 0);
      memcpy(buf + kStoreReqLen, payload.buf, size_t(payload.len));
      ok = send_all(c->fd, buf, kStoreReqLen + size_t(payload.len));
    } else {
      char req[kStoreReqLen];
      pack_store_req(req, 9, (const char*)oid.buf, uint64_t(payload.len), 0);
      ok = send_all(c->fd, req, kStoreReqLen) &&
           send_all(c->fd, (const char*)payload.buf, size_t(payload.len));
    }
    ok = ok && recv_full(c->fd, resp, kStoreRespLen);
    if (!ok) c->dead = true;
  }
  Py_END_ALLOW_THREADS
  PyBuffer_Release(&oid);
  PyBuffer_Release(&payload);
  if (!ok) {
    PyErr_SetString(PyExc_ConnectionError, "object store connection closed");
    return nullptr;
  }
  return PyLong_FromLong(long(uint8_t(resp[0])));
}

// get_inline(oid, timeout_ms, cap) -> (status, r0, r1, payload|None)
static PyObject* StoreConn_get_inline(StoreConnObject* self, PyObject* args) {
  Py_buffer oid;
  unsigned long long timeout_ms, cap;
  if (!PyArg_ParseTuple(args, "y*KK", &oid, &timeout_ms, &cap))
    return nullptr;
  if (oid.len != Py_ssize_t(kStoreIdLen)) {
    PyBuffer_Release(&oid);
    PyErr_SetString(PyExc_ValueError, "oid must be 20 bytes");
    return nullptr;
  }
  StoreConnCore* c = self->core;
  char req[kStoreReqLen], resp[kStoreRespLen];
  pack_store_req(req, 10 /*OP_GET_INLINE*/, (const char*)oid.buf,
                 timeout_ms, cap);
  bool ok = false;
  Py_BEGIN_ALLOW_THREADS
  ok = !c->dead && send_all(c->fd, req, kStoreReqLen) &&
       recv_full(c->fd, resp, kStoreRespLen);
  if (!ok) c->dead = true;
  Py_END_ALLOW_THREADS
  PyBuffer_Release(&oid);
  if (!ok) {
    PyErr_SetString(PyExc_ConnectionError, "object store connection closed");
    return nullptr;
  }
  int status = int(uint8_t(resp[0]));
  uint64_t r0, r1;
  memcpy(&r0, resp + 1, 8);
  memcpy(&r1, resp + 1 + 8, 8);
  if (status == 0 /*ST_OK*/ && r0 == 1) {
    // inline payload follows: read straight into a fresh bytes object
    PyObject* data = PyBytes_FromStringAndSize(nullptr, Py_ssize_t(r1));
    if (!data) return nullptr;
    bool ok2 = false;
    char* dst = PyBytes_AS_STRING(data);
    Py_BEGIN_ALLOW_THREADS
    ok2 = recv_full(c->fd, dst, size_t(r1));
    if (!ok2) c->dead = true;
    Py_END_ALLOW_THREADS
    if (!ok2) {
      Py_DECREF(data);
      PyErr_SetString(PyExc_ConnectionError,
                      "object store connection closed");
      return nullptr;
    }
    PyObject* out = Py_BuildValue("(iKKN)", status, (unsigned long long)r0,
                                  (unsigned long long)r1, data);
    return out;
  }
  return Py_BuildValue("(iKKO)", status, (unsigned long long)r0,
                       (unsigned long long)r1, Py_None);
}

static PyObject* StoreConn_is_dead(StoreConnObject* self, PyObject*) {
  return PyBool_FromLong(self->core->dead);
}

static PyMethodDef StoreConn_methods[] = {
    {"call", (PyCFunction)StoreConn_call, METH_VARARGS,
     "call(op, oid, a0, a1) -> (status, r0, r1)"},
    {"put", (PyCFunction)StoreConn_put, METH_VARARGS,
     "put(oid, payload) -> status (create+copy+seal, one round trip)"},
    {"get_inline", (PyCFunction)StoreConn_get_inline, METH_VARARGS,
     "get_inline(oid, timeout_ms, cap) -> (status, r0, r1, bytes|None)"},
    {"is_dead", (PyCFunction)StoreConn_is_dead, METH_NOARGS, ""},
    {nullptr, nullptr, 0, nullptr}};

static PyTypeObject StoreConnType = {
    PyVarObject_HEAD_INIT(nullptr, 0)
};

// ---------- module ----------

static PyModuleDef rtpu_core_module = {
    PyModuleDef_HEAD_INIT, "_rtpu_core",
    "Native transport core for direct actor calls (threadless)", -1,
    nullptr, nullptr, nullptr, nullptr, nullptr};

}  // namespace

PyMODINIT_FUNC PyInit__rtpu_core(void) {
  ChannelType.tp_name = "_rtpu_core.Channel";
  ChannelType.tp_basicsize = sizeof(ChannelObject);
  ChannelType.tp_flags = Py_TPFLAGS_DEFAULT;
  ChannelType.tp_new = Channel_new;
  ChannelType.tp_dealloc = (destructor)Channel_dealloc;
  ChannelType.tp_methods = Channel_methods;
  ChannelType.tp_doc = "Caller-side direct channel (C++ framed I/O)";
  if (PyType_Ready(&ChannelType) < 0) return nullptr;

  ServerType.tp_name = "_rtpu_core.Server";
  ServerType.tp_basicsize = sizeof(ServerObject);
  ServerType.tp_flags = Py_TPFLAGS_DEFAULT;
  ServerType.tp_new = Server_new;
  ServerType.tp_dealloc = (destructor)Server_dealloc;
  ServerType.tp_methods = Server_methods;
  ServerType.tp_doc = "Callee-side epoll frame server (threadless)";
  if (PyType_Ready(&ServerType) < 0) return nullptr;

  StoreConnType.tp_name = "_rtpu_core.StoreConn";
  StoreConnType.tp_basicsize = sizeof(StoreConnObject);
  StoreConnType.tp_flags = Py_TPFLAGS_DEFAULT;
  StoreConnType.tp_new = StoreConn_new;
  StoreConnType.tp_dealloc = (destructor)StoreConn_dealloc;
  StoreConnType.tp_methods = StoreConn_methods;
  StoreConnType.tp_doc = "Native shm-store client op layer (GIL-free I/O)";
  if (PyType_Ready(&StoreConnType) < 0) return nullptr;

  PyObject* m = PyModule_Create(&rtpu_core_module);
  if (!m) return nullptr;
  Py_INCREF(&ChannelType);
  PyModule_AddObject(m, "Channel", (PyObject*)&ChannelType);
  Py_INCREF(&ServerType);
  PyModule_AddObject(m, "Server", (PyObject*)&ServerType);
  Py_INCREF(&StoreConnType);
  PyModule_AddObject(m, "StoreConn", (PyObject*)&StoreConnType);
  return m;
}
