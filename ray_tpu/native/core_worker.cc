// _rtpu_core: native transport core for direct actor calls.
//
// Counterpart of the reference's C++ core-worker transport
// (/root/reference/src/ray/core_worker/transport/actor_task_submitter.cc +
// task_receiver.cc): the reference executes Python user code but keeps
// framing, socket I/O, queueing, and reply matching in C++ threads that
// never hold the GIL.  Round-2's pure-Python direct path paid for pickled
// frame envelopes and 3+ Python thread wakeups per call — on a single-core
// host that Python overhead IS the n:n actor-call ceiling (BENCH_core
// 0.41x reference).  This extension moves the transport half of every call
// off the GIL:
//
//   caller:  Channel.submit(tid, frame)  — C++ enqueue + sendall
//            Channel.wait(tid, ms)       — blocks on a C++ condvar (GIL
//                                          released); the C++ reader thread
//                                          parses replies and signals it.
//            No Python reader thread exists at all.
//   callee:  Server accepts connections, C++ reader threads parse frames
//            into one arrival-ordered queue; ONE Python executor thread
//            drains Server.next(), runs the user method, Server.reply().
//
// Frames are the 4-byte-LE length-prefixed format of _private/protocol.py;
// frame BODIES here are the binary call/reply records built by
// _private/direct.py (first byte 0x01/0x02/0x03; a 0x80 first byte is a
// legacy pickled-dict frame from a Python-fallback peer, which the Python
// executor still understands — one port, both dialects).
//
// Build: CPython C API (no pybind11 in this image) — see native/build.py.

#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <arpa/inet.h>
#include <errno.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <poll.h>
#include <pthread.h>
#include <stdint.h>
#include <string.h>
#include <sys/socket.h>
#include <sys/un.h>
#include <unistd.h>

#include <condition_variable>
#include <deque>
#include <map>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

namespace {

// ---------- low-level framed I/O ----------

bool send_all(int fd, const char* p, size_t n) {
  while (n > 0) {
    ssize_t k = ::send(fd, p, n, MSG_NOSIGNAL);
    if (k < 0) {
      if (errno == EINTR) continue;
      return false;
    }
    p += k;
    n -= size_t(k);
  }
  return true;
}

bool recv_all(int fd, char* p, size_t n) {
  while (n > 0) {
    ssize_t k = ::recv(fd, p, n, 0);
    if (k <= 0) {
      if (k < 0 && errno == EINTR) continue;
      return false;
    }
    p += k;
    n -= size_t(k);
  }
  return true;
}

constexpr uint32_t kMaxFrame = 1u << 28;

bool send_frame(int fd, std::mutex& mu, const char* body, size_t n) {
  char hdr[4];
  uint32_t len = uint32_t(n);
  memcpy(hdr, &len, 4);
  std::lock_guard<std::mutex> g(mu);
  return send_all(fd, hdr, 4) && send_all(fd, body, n);
}

bool recv_frame(int fd, std::string* out) {
  char hdr[4];
  if (!recv_all(fd, hdr, 4)) return false;
  uint32_t len;
  memcpy(&len, hdr, 4);
  if (len > kMaxFrame) return false;
  out->resize(len);
  return len == 0 || recv_all(fd, &(*out)[0], len);
}

// ---------- Channel (caller side) ----------

struct ChannelCore {
  int fd = -1;
  std::mutex send_mu;
  std::mutex mu;  // guards results/outstanding/dead
  std::condition_variable cv;
  std::map<std::string, std::pair<uint8_t, std::string>> results;
  std::deque<std::string> outstanding;  // submit order
  bool dead = false;
  std::thread reader;

  void reader_loop() {
    std::string body;
    for (;;) {
      if (!recv_frame(fd, &body)) break;
      // reply frame: 0x02 | u8 tid_len | tid | u8 flags | payload
      if (body.size() < 3 || uint8_t(body[0]) != 0x02) continue;
      uint8_t tl = uint8_t(body[1]);
      if (body.size() < size_t(2 + tl + 1)) continue;
      std::string tid = body.substr(2, tl);
      uint8_t flags = uint8_t(body[2 + tl]);
      std::string payload = body.substr(2 + tl + 1);
      {
        std::lock_guard<std::mutex> g(mu);
        results[tid] = {flags, std::move(payload)};
        for (auto it = outstanding.begin(); it != outstanding.end(); ++it)
          if (*it == tid) {
            outstanding.erase(it);
            break;
          }
      }
      cv.notify_all();
    }
    {
      std::lock_guard<std::mutex> g(mu);
      dead = true;
    }
    cv.notify_all();
  }
};

typedef struct {
  PyObject_HEAD
  ChannelCore* core;
} ChannelObject;

static PyObject* Channel_new(PyTypeObject* type, PyObject* args,
                             PyObject* kwds) {
  int fd;
  if (!PyArg_ParseTuple(args, "i", &fd)) return nullptr;
  ChannelObject* self = (ChannelObject*)type->tp_alloc(type, 0);
  if (!self) return nullptr;
  self->core = new ChannelCore();
  self->core->fd = fd;
  int one = 1;
  setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof one);
  self->core->reader = std::thread([c = self->core] { c->reader_loop(); });
  return (PyObject*)self;
}

static void Channel_dealloc(ChannelObject* self) {
  ChannelCore* c = self->core;
  if (c) {
    ::shutdown(c->fd, SHUT_RDWR);
    Py_BEGIN_ALLOW_THREADS
    if (c->reader.joinable()) c->reader.join();
    Py_END_ALLOW_THREADS
    ::close(c->fd);
    delete c;
  }
  Py_TYPE(self)->tp_free((PyObject*)self);
}

static PyObject* Channel_submit(ChannelObject* self, PyObject* args) {
  const char *tid, *frame;
  Py_ssize_t tid_len, frame_len;
  if (!PyArg_ParseTuple(args, "y#y#", &tid, &tid_len, &frame, &frame_len))
    return nullptr;
  ChannelCore* c = self->core;
  {
    std::lock_guard<std::mutex> g(c->mu);
    if (c->dead) Py_RETURN_FALSE;
    c->outstanding.emplace_back(tid, size_t(tid_len));
  }
  bool ok;
  Py_BEGIN_ALLOW_THREADS
  ok = send_frame(c->fd, c->send_mu, frame, size_t(frame_len));
  Py_END_ALLOW_THREADS
  if (!ok) {
    // the reader will observe EOF and flip dead; the frame stays in
    // outstanding so the repair path resends it
    Py_RETURN_FALSE;
  }
  Py_RETURN_TRUE;
}

static PyObject* Channel_wait(ChannelObject* self, PyObject* args) {
  const char* tid;
  Py_ssize_t tid_len;
  long timeout_ms;
  if (!PyArg_ParseTuple(args, "y#l", &tid, &tid_len, &timeout_ms))
    return nullptr;
  ChannelCore* c = self->core;
  std::string key(tid, size_t(tid_len));
  std::pair<uint8_t, std::string> result;
  bool found = false, is_dead = false;
  Py_BEGIN_ALLOW_THREADS
  {
    std::unique_lock<std::mutex> lk(c->mu);
    auto ready = [&] { return c->dead || c->results.count(key); };
    if (timeout_ms < 0) {
      c->cv.wait(lk, ready);
    } else {
      c->cv.wait_for(lk, std::chrono::milliseconds(timeout_ms), ready);
    }
    auto it = c->results.find(key);
    if (it != c->results.end()) {
      result = std::move(it->second);
      c->results.erase(it);
      found = true;
    }
    is_dead = c->dead;
  }
  Py_END_ALLOW_THREADS
  if (found)
    return Py_BuildValue("(iy#)", int(result.first), result.second.data(),
                         Py_ssize_t(result.second.size()));
  if (is_dead) {
    PyErr_SetString(PyExc_ConnectionError, "direct channel lost");
    return nullptr;
  }
  Py_RETURN_NONE;  // timeout
}

static PyObject* Channel_wait_any(ChannelObject* self, PyObject* args) {
  // Any ready result (delivery-thread draining): replies can complete out
  // of caller order on concurrent actors, so the drain must not pick a tid.
  long timeout_ms;
  if (!PyArg_ParseTuple(args, "l", &timeout_ms)) return nullptr;
  ChannelCore* c = self->core;
  std::string tid;
  std::pair<uint8_t, std::string> result;
  bool found = false, is_dead = false;
  Py_BEGIN_ALLOW_THREADS
  {
    std::unique_lock<std::mutex> lk(c->mu);
    auto ready = [&] { return c->dead || !c->results.empty(); };
    if (timeout_ms < 0) {
      c->cv.wait(lk, ready);
    } else {
      c->cv.wait_for(lk, std::chrono::milliseconds(timeout_ms), ready);
    }
    if (!c->results.empty()) {
      auto it = c->results.begin();
      tid = it->first;
      result = std::move(it->second);
      c->results.erase(it);
      found = true;
    }
    is_dead = c->dead;
  }
  Py_END_ALLOW_THREADS
  if (found)
    return Py_BuildValue("(y#iy#)", tid.data(), Py_ssize_t(tid.size()),
                         int(result.first), result.second.data(),
                         Py_ssize_t(result.second.size()));
  if (is_dead) {
    PyErr_SetString(PyExc_ConnectionError, "direct channel lost");
    return nullptr;
  }
  Py_RETURN_NONE;  // timeout
}

static PyObject* Channel_outstanding(ChannelObject* self, PyObject*) {
  ChannelCore* c = self->core;
  std::vector<std::string> tids;
  {
    std::lock_guard<std::mutex> g(c->mu);
    tids.assign(c->outstanding.begin(), c->outstanding.end());
  }
  PyObject* list = PyList_New(Py_ssize_t(tids.size()));
  for (size_t i = 0; i < tids.size(); ++i)
    PyList_SET_ITEM(list, i, PyBytes_FromStringAndSize(
                                  tids[i].data(), tids[i].size()));
  return list;
}

static PyObject* Channel_is_dead(ChannelObject* self, PyObject*) {
  std::lock_guard<std::mutex> g(self->core->mu);
  return PyBool_FromLong(self->core->dead);
}

static PyObject* Channel_close(ChannelObject* self, PyObject*) {
  ::shutdown(self->core->fd, SHUT_RDWR);
  Py_RETURN_NONE;
}

static PyMethodDef Channel_methods[] = {
    {"submit", (PyCFunction)Channel_submit, METH_VARARGS,
     "submit(task_id, frame) -> bool"},
    {"wait", (PyCFunction)Channel_wait, METH_VARARGS,
     "wait(task_id, timeout_ms) -> (flags, payload) | None; raises "
     "ConnectionError when the channel is dead"},
    {"wait_any", (PyCFunction)Channel_wait_any, METH_VARARGS,
     "wait_any(timeout_ms) -> (task_id, flags, payload) | None; raises "
     "ConnectionError when the channel is dead"},
    {"outstanding", (PyCFunction)Channel_outstanding, METH_NOARGS,
     "task ids submitted but not yet answered, in send order"},
    {"is_dead", (PyCFunction)Channel_is_dead, METH_NOARGS, ""},
    {"close", (PyCFunction)Channel_close, METH_NOARGS, ""},
    {nullptr, nullptr, 0, nullptr}};

static PyTypeObject ChannelType = {
    PyVarObject_HEAD_INIT(nullptr, 0)
};

// ---------- Server (callee side) ----------

struct ServerCore {
  int listen_fd = -1;
  bool is_tcp = false;
  std::string token;  // TCP peers must present this before frame 1
  std::mutex mu;
  std::condition_variable cv;
  std::deque<std::pair<uint64_t, std::string>> queue;  // (conn_id, frame)
  std::map<uint64_t, int> conns;          // conn_id -> fd
  std::map<uint64_t, std::mutex*> send_mus;
  uint64_t next_conn_id = 1;
  bool closed = false;
  std::thread acceptor;
  std::vector<std::thread> readers;

  void accept_loop() {
    for (;;) {
      int fd = ::accept(listen_fd, nullptr, nullptr);
      if (fd < 0) {
        if (errno == EINTR) continue;
        break;  // listener closed
      }
      if (is_tcp) {
        int one = 1;
        setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof one);
      }
      uint64_t id;
      std::mutex* smu = new std::mutex();
      {
        std::lock_guard<std::mutex> g(mu);
        if (closed) {
          ::close(fd);
          delete smu;
          return;
        }
        id = next_conn_id++;
        conns[id] = fd;
        send_mus[id] = smu;
        readers.emplace_back([this, id, fd] { reader_loop(id, fd); });
      }
    }
    std::lock_guard<std::mutex> g(mu);
    closed = true;
    cv.notify_all();
  }

  void reader_loop(uint64_t id, int fd) {
    std::string body;
    if (is_tcp) {
      // cluster-token handshake (reference of record: protocol.py
      // authenticate_server_side) — constant-time-ish compare
      if (!recv_frame(fd, &body) || body.size() != token.size()) {
        drop(id, fd);
        return;
      }
      unsigned char d = 0;
      for (size_t i = 0; i < body.size(); ++i)
        d |= (unsigned char)(body[i]) ^ (unsigned char)(token[i]);
      if (d != 0) {
        std::mutex* smu;
        {
          std::lock_guard<std::mutex> g(mu);
          smu = send_mus[id];
        }
        send_frame(fd, *smu, "NO", 2);
        drop(id, fd);
        return;
      }
      std::mutex* smu;
      {
        std::lock_guard<std::mutex> g(mu);
        smu = send_mus[id];
      }
      if (!send_frame(fd, *smu, "OK", 2)) {
        drop(id, fd);
        return;
      }
    }
    for (;;) {
      if (!recv_frame(fd, &body)) break;
      {
        std::lock_guard<std::mutex> g(mu);
        queue.emplace_back(id, std::move(body));
      }
      cv.notify_one();
      body.clear();
    }
    drop(id, fd);
  }

  void drop(uint64_t id, int fd) {
    ::close(fd);
    std::lock_guard<std::mutex> g(mu);
    conns.erase(id);
    // send_mus entry leaks intentionally until shutdown: a reply racing
    // the disconnect may still hold the mutex
  }
};

typedef struct {
  PyObject_HEAD
  ServerCore* core;
} ServerObject;

static PyObject* Server_new(PyTypeObject* type, PyObject* args,
                            PyObject* kwds) {
  int fd, is_tcp;
  const char* token;
  Py_ssize_t token_len;
  if (!PyArg_ParseTuple(args, "ipy#", &fd, &is_tcp, &token, &token_len))
    return nullptr;
  ServerObject* self = (ServerObject*)type->tp_alloc(type, 0);
  if (!self) return nullptr;
  self->core = new ServerCore();
  self->core->listen_fd = fd;
  self->core->is_tcp = is_tcp != 0;
  self->core->token.assign(token, size_t(token_len));
  self->core->acceptor =
      std::thread([c = self->core] { c->accept_loop(); });
  return (PyObject*)self;
}

static void Server_dealloc(ServerObject* self) {
  ServerCore* c = self->core;
  if (c) {
    {
      std::lock_guard<std::mutex> g(c->mu);
      c->closed = true;
      for (auto& [id, fd] : c->conns) ::shutdown(fd, SHUT_RDWR);
    }
    ::shutdown(c->listen_fd, SHUT_RDWR);
    ::close(c->listen_fd);
    c->cv.notify_all();
    Py_BEGIN_ALLOW_THREADS
    if (c->acceptor.joinable()) c->acceptor.join();
    {
      std::lock_guard<std::mutex> g(c->mu);
      for (auto& t : c->readers)
        if (t.joinable()) t.detach();  // readers exit on their closed fds
    }
    Py_END_ALLOW_THREADS
    // send_mus / core leak a few bytes at process teardown by design:
    // joining every reader here could deadlock against a reply in flight
    self->core = nullptr;
  }
  Py_TYPE(self)->tp_free((PyObject*)self);
}

static PyObject* Server_next(ServerObject* self, PyObject* args) {
  long timeout_ms;
  if (!PyArg_ParseTuple(args, "l", &timeout_ms)) return nullptr;
  ServerCore* c = self->core;
  uint64_t conn_id = 0;
  std::string frame;
  bool got = false, closed = false;
  Py_BEGIN_ALLOW_THREADS
  {
    std::unique_lock<std::mutex> lk(c->mu);
    auto ready = [&] { return c->closed || !c->queue.empty(); };
    if (timeout_ms < 0) {
      c->cv.wait(lk, ready);
    } else {
      c->cv.wait_for(lk, std::chrono::milliseconds(timeout_ms), ready);
    }
    if (!c->queue.empty()) {
      conn_id = c->queue.front().first;
      frame = std::move(c->queue.front().second);
      c->queue.pop_front();
      got = true;
    }
    closed = c->closed;
  }
  Py_END_ALLOW_THREADS
  if (got)
    return Py_BuildValue("(Ky#)", (unsigned long long)conn_id, frame.data(),
                         Py_ssize_t(frame.size()));
  if (closed) {
    PyErr_SetString(PyExc_ConnectionError, "server closed");
    return nullptr;
  }
  Py_RETURN_NONE;
}

static PyObject* Server_reply(ServerObject* self, PyObject* args) {
  unsigned long long conn_id;
  const char* frame;
  Py_ssize_t frame_len;
  if (!PyArg_ParseTuple(args, "Ky#", &conn_id, &frame, &frame_len))
    return nullptr;
  ServerCore* c = self->core;
  int fd = -1;
  std::mutex* smu = nullptr;
  {
    std::lock_guard<std::mutex> g(c->mu);
    auto it = c->conns.find(conn_id);
    if (it != c->conns.end()) {
      fd = it->second;
      smu = c->send_mus[conn_id];
    }
  }
  if (fd < 0) Py_RETURN_FALSE;  // caller hung up; it will resend elsewhere
  bool ok;
  Py_BEGIN_ALLOW_THREADS
  ok = send_frame(fd, *smu, frame, size_t(frame_len));
  Py_END_ALLOW_THREADS
  return PyBool_FromLong(ok);
}

static PyObject* Server_close(ServerObject* self, PyObject*) {
  ServerCore* c = self->core;
  {
    std::lock_guard<std::mutex> g(c->mu);
    c->closed = true;
    for (auto& [id, fd] : c->conns) ::shutdown(fd, SHUT_RDWR);
  }
  ::shutdown(c->listen_fd, SHUT_RDWR);
  c->cv.notify_all();
  Py_RETURN_NONE;
}

static PyMethodDef Server_methods[] = {
    {"next", (PyCFunction)Server_next, METH_VARARGS,
     "next(timeout_ms) -> (conn_id, frame) | None; raises ConnectionError "
     "after close()"},
    {"reply", (PyCFunction)Server_reply, METH_VARARGS,
     "reply(conn_id, frame) -> bool"},
    {"close", (PyCFunction)Server_close, METH_NOARGS, ""},
    {nullptr, nullptr, 0, nullptr}};

static PyTypeObject ServerType = {
    PyVarObject_HEAD_INIT(nullptr, 0)
};

// ---------- module ----------

static PyModuleDef rtpu_core_module = {
    PyModuleDef_HEAD_INIT, "_rtpu_core",
    "Native transport core for direct actor calls", -1,
    nullptr, nullptr, nullptr, nullptr, nullptr};

}  // namespace

PyMODINIT_FUNC PyInit__rtpu_core(void) {
  ChannelType.tp_name = "_rtpu_core.Channel";
  ChannelType.tp_basicsize = sizeof(ChannelObject);
  ChannelType.tp_flags = Py_TPFLAGS_DEFAULT;
  ChannelType.tp_new = Channel_new;
  ChannelType.tp_dealloc = (destructor)Channel_dealloc;
  ChannelType.tp_methods = Channel_methods;
  ChannelType.tp_doc = "Caller-side direct channel (C++ I/O + reply match)";
  if (PyType_Ready(&ChannelType) < 0) return nullptr;

  ServerType.tp_name = "_rtpu_core.Server";
  ServerType.tp_basicsize = sizeof(ServerObject);
  ServerType.tp_flags = Py_TPFLAGS_DEFAULT;
  ServerType.tp_new = Server_new;
  ServerType.tp_dealloc = (destructor)Server_dealloc;
  ServerType.tp_methods = Server_methods;
  ServerType.tp_doc = "Callee-side frame server (C++ accept/read/reply)";
  if (PyType_Ready(&ServerType) < 0) return nullptr;

  PyObject* m = PyModule_Create(&rtpu_core_module);
  if (!m) return nullptr;
  Py_INCREF(&ChannelType);
  PyModule_AddObject(m, "Channel", (PyObject*)&ChannelType);
  Py_INCREF(&ServerType);
  PyModule_AddObject(m, "Server", (PyObject*)&ServerType);
  return m;
}
