// Native GCS server daemon.
//
// Counterpart of the reference's C++ GCS (/root/reference/src/ray/gcs/
// gcs_server/gcs_server.cc): the cluster control plane — actor registry with
// lifecycle FSM + named-actor index, node table with liveness, per-node load
// view, internal KV, placement-group table, object location directory, and
// (net new vs the round-2 Python GCS) a pubsub event log with long-poll
// subscriptions (reference: src/ray/pubsub/publisher.h:300 +
// gcs_server/pubsub_handler.cc) so clients subscribe to actor/node/object/KV
// changes instead of sleep-polling.
//
// Speaks the frame protocol of _private/protocol.py (u32-LE length prefix)
// with wire-codec bodies (_private/wire.py / native/wire.h) — the Python
// GcsClient works unchanged against this daemon or the Python GcsServer.
//
// Design: one thread, one epoll loop (the reference pins GCS handlers to a
// single asio io_context for the same reason — lock-free tables,
// deterministic ordering).  Long-poll subscribers park their reply inside
// the loop; publishes and timeouts complete them.  Durable tables (actors,
// named actors, KV, placement groups) snapshot to --persist with a debounce,
// same file format as the Python Gcs (wire-encoded state dict), so a head
// restart can hand the tables between implementations in either direction.

#include <arpa/inet.h>
#include <errno.h>
#include <fcntl.h>
#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <signal.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/epoll.h>
#include <sys/socket.h>
#include <sys/stat.h>
#include <sys/un.h>
#include <time.h>
#include <unistd.h>

#include <algorithm>
#include <memory>
#include <deque>
#include <map>
#include <set>
#include <string>
#include <vector>

#include "wire.h"

using wire::Value;

static volatile sig_atomic_t g_stop = 0;
static void on_stop_signal(int) { g_stop = 1; }

static double now_s() {
  struct timespec ts;
  clock_gettime(CLOCK_REALTIME, &ts);
  return double(ts.tv_sec) + double(ts.tv_nsec) * 1e-9;
}

static double mono_s() {
  struct timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return double(ts.tv_sec) + double(ts.tv_nsec) * 1e-9;
}

// ---------------------------------------------------------------------------
// Tables (mirror of _private/gcs.py Gcs)
// ---------------------------------------------------------------------------

static const char* kStateDead = "DEAD";
static const char* kStateRestarting = "RESTARTING";

struct Event {
  uint64_t seq;
  std::string channel;
  Value payload;
};

struct Waiter {  // a parked sub_poll long-poll
  int fd;
  std::vector<std::string> channels;
  uint64_t cursor;
  double deadline_mono;  // <=0: no timeout (shouldn't happen; client sends one)
};

// ---------------------------------------------------------------------------
// Pluggable persistence backends (reference:
// src/ray/gcs/store_client/redis_store_client.h — the GCS tables behind
// a swappable store client).  The snapshot blob is identical across
// backends (wire-encoded state dict), so a head can move between them.
// ---------------------------------------------------------------------------

struct PersistBackend {
  virtual ~PersistBackend() = default;
  virtual bool store(const std::string& blob) = 0;
  // false = backend unreachable (NOT the same as "no snapshot": a head
  // must never start empty and overwrite durable state just because the
  // store was briefly down); true with empty *blob = genuinely absent.
  virtual bool load(std::string* blob) = 0;
};

struct FilePersist : PersistBackend {
  std::string path;
  explicit FilePersist(std::string p) : path(std::move(p)) {}

  bool store(const std::string& blob) override {
    std::string tmp = path + ".tmp";
    FILE* f = fopen(tmp.c_str(), "wb");
    if (!f) return false;
    bool ok = fwrite(blob.data(), 1, blob.size(), f) == blob.size();
    ok = fclose(f) == 0 && ok;
    if (ok) rename(tmp.c_str(), path.c_str());  // atomic swap
    return ok;
  }

  bool load(std::string* blob) override {
    blob->clear();
    FILE* f = fopen(path.c_str(), "rb");
    if (!f) return true;  // absent: a fresh cluster
    char buf[1 << 16];
    size_t n;
    while ((n = fread(buf, 1, sizeof buf, f)) > 0) blob->append(buf, n);
    fclose(f);
    return true;
  }
};

// RESP (Redis Serialization Protocol) backend: SET/GET of the snapshot
// blob against any Redis-compatible server — the durable external
// control-plane store the reference uses for GCS fault tolerance.
// URL: redis://host:port[/key]
struct RedisPersist : PersistBackend {
  std::string host, key;
  int port;
  int fd = -1;

  RedisPersist(std::string h, int p, std::string k)
      : host(std::move(h)), key(std::move(k)), port(p) {}
  ~RedisPersist() override {
    if (fd >= 0) close(fd);
  }

  static constexpr int kIoTimeoutS = 5;

  bool ensure() {
    if (fd >= 0) return true;
    // hostname or numeric address (getaddrinfo covers both); timeouts
    // are set BEFORE connect — this runs on the single epoll control
    // thread, and a blackholed Redis must degrade, never hang the GCS
    struct addrinfo hints, *res = nullptr;
    memset(&hints, 0, sizeof hints);
    hints.ai_family = AF_INET;
    hints.ai_socktype = SOCK_STREAM;
    std::string port_s = std::to_string(port);
    if (getaddrinfo(host.c_str(), port_s.c_str(), &hints, &res) != 0)
      return false;
    for (struct addrinfo* ai = res; ai; ai = ai->ai_next) {
      fd = socket(ai->ai_family, ai->ai_socktype, ai->ai_protocol);
      if (fd < 0) continue;
      struct timeval tv = {kIoTimeoutS, 0};
      setsockopt(fd, SOL_SOCKET, SO_SNDTIMEO, &tv, sizeof tv);
      setsockopt(fd, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof tv);
      if (connect(fd, ai->ai_addr, ai->ai_addrlen) == 0) break;
      close(fd);
      fd = -1;
    }
    freeaddrinfo(res);
    return fd >= 0;
  }

  bool write_all(const std::string& data) {
    size_t off = 0;
    while (off < data.size()) {
      ssize_t n = send(fd, data.data() + off, data.size() - off,
                       MSG_NOSIGNAL);
      if (n <= 0) return false;
      off += size_t(n);
    }
    return true;
  }

  bool read_line(std::string* line) {
    line->clear();
    char c;
    while (true) {
      ssize_t n = recv(fd, &c, 1, 0);
      if (n <= 0) return false;
      if (c == '\r') {
        if (recv(fd, &c, 1, 0) <= 0) return false;  // consume \n
        return true;
      }
      line->push_back(c);
    }
  }

  bool read_exact(std::string* out, size_t n) {
    out->resize(n);
    size_t off = 0;
    while (off < n) {
      ssize_t r = recv(fd, out->data() + off, n - off, 0);
      if (r <= 0) return false;
      off += size_t(r);
    }
    char crlf[2];
    return recv(fd, crlf, 2, MSG_WAITALL) == 2;
  }

  static std::string cmd(const std::vector<std::string>& parts) {
    std::string out = "*" + std::to_string(parts.size()) + "\r\n";
    for (auto& p : parts)
      out += "$" + std::to_string(p.size()) + "\r\n" + p + "\r\n";
    return out;
  }

  bool store(const std::string& blob) override {
    for (int attempt = 0; attempt < 2; ++attempt) {
      if (!ensure()) return false;
      std::string reply;
      if (write_all(cmd({"SET", key, blob})) && read_line(&reply) &&
          !reply.empty() && reply[0] == '+')
        return true;
      close(fd);  // stale/broken conn: one reconnect attempt
      fd = -1;
    }
    return false;
  }

  bool load(std::string* blob) override {
    blob->clear();
    // a few connect attempts: a briefly-restarting Redis at head boot
    // must not be mistaken for "no snapshot"
    for (int attempt = 0; attempt < 5; ++attempt) {
      if (ensure()) break;
      struct timespec ts = {0, 300 * 1000 * 1000};
      nanosleep(&ts, nullptr);
    }
    if (fd < 0) return false;  // unreachable: caller decides (fatal)
    std::string reply;
    if (!write_all(cmd({"GET", key})) || !read_line(&reply) ||
        reply.empty() || reply[0] != '$') {
      close(fd);
      fd = -1;
      return false;
    }
    long long n = atoll(reply.c_str() + 1);
    if (n < 0) return true;  // $-1: key absent — fresh cluster
    if (!read_exact(blob, size_t(n))) {
      close(fd);
      fd = -1;
      blob->clear();
      return false;
    }
    return true;
  }
};

std::unique_ptr<PersistBackend> make_persist(const std::string& spec) {
  if (spec.empty()) return nullptr;
  if (spec.rfind("redis://", 0) == 0) {
    std::string rest = spec.substr(8);
    std::string key = "rtpu:gcs";
    auto slash = rest.find('/');
    if (slash != std::string::npos) {
      if (slash + 1 < rest.size()) key = rest.substr(slash + 1);
      rest = rest.substr(0, slash);
    }
    auto colon = rest.rfind(':');
    int port = 6379;
    std::string host = rest;
    if (colon != std::string::npos) {
      host = rest.substr(0, colon);
      port = atoi(rest.c_str() + colon + 1);
    }
    return std::make_unique<RedisPersist>(host, port, key);
  }
  return std::make_unique<FilePersist>(spec);
}

struct Gcs {
  std::map<std::string, Value> actors;       // actor_id -> STRUCT(1)
  std::map<std::string, std::string> named;  // name -> actor_id
  std::map<std::string, Value> nodes;        // node_id -> STRUCT(2)
  std::map<std::pair<std::string, std::string>, std::string> kv;
  std::map<std::string, std::set<std::string>> obj_locs;
  std::set<std::string> lost_objects;
  std::map<std::string, Value> pgs;  // pg_id -> DICT
  // First-class job / worker / task-event tables (reference:
  // gcs_service.proto JobInfoGcsService:68, WorkerInfoGcsService:363,
  // TaskInfoGcsService:860) — head-side Python holds NO copy, so jobs
  // and task events survive a head restart with the snapshot.
  std::map<std::string, Value> jobs;     // submission_id -> DICT
  std::map<std::string, Value> workers;  // worker_id -> DICT
  std::deque<Value> task_events;         // bounded ring of DICTs
  size_t task_event_cap = env_size("RTPU_GCS_TASK_EVENT_CAP", 1 << 16);
  size_t max_dead_workers = env_size("RTPU_GCS_MAX_DEAD_WORKERS", 4096);
  // task events are telemetry: persist them on a slow cadence, never at
  // the heartbeat-flush rate (the ring alone can be multi-MB)
  double tev_last_persist_mono = 0;
  double tev_persist_every_s = env_f("RTPU_GCS_TEV_PERSIST_S", 5.0);
  double death_timeout_s = 5.0;

  // Env-tunable caps/intervals (flag registry: _private/flags.py; the
  // daemon inherits the head's env, which carries cluster-level flags)
  // Garbage or non-positive values fall back to the default, matching
  // the Python registry's _coerce contract — a typo must never unbound
  // a ring or zero a timeout.
  static size_t env_size(const char* name, size_t dflt) {
    const char* v = getenv(name);
    if (!v || !*v) return dflt;
    char* end = nullptr;
    long long n = strtoll(v, &end, 10);
    return (end && *end == '\0' && n > 0) ? size_t(n) : dflt;
  }
  static double env_f(const char* name, double dflt) {
    const char* v = getenv(name);
    if (!v || !*v) return dflt;
    char* end = nullptr;
    double x = strtod(v, &end);
    return (end && *end == '\0' && x > 0) ? x : dflt;
  }
  // pubsub event log
  std::deque<Event> events;
  uint64_t next_seq = 1;
  size_t ring_cap = env_size("RTPU_GCS_RING_CAP", 16384);

  // persistence (pluggable: file | redis — see make_persist)
  std::unique_ptr<PersistBackend> persist;
  bool dirty = false;
  double snapshot_due_mono = 0;  // 0 = none pending
  double debounce_s = env_f("RTPU_GCS_SNAPSHOT_DEBOUNCE_S", 0.2);

  void publish(const std::string& channel, Value payload) {
    events.push_back(Event{next_seq++, channel, std::move(payload)});
    while (events.size() > ring_cap) events.pop_front();
  }

  void mutated() {
    if (!persist) return;
    dirty = true;
    if (snapshot_due_mono == 0) snapshot_due_mono = mono_s() + debounce_s;
  }

  void snapshot() {
    snapshot_due_mono = 0;
    if (!persist || !dirty) return;
    dirty = false;
    Value state = Value::Dict();
    Value va = Value::Dict();
    for (auto& [id, info] : actors)
      va.pairs->emplace_back(Value::Bytes(id), info);
    state.set("actors", va);
    Value vn = Value::Dict();
    for (auto& [name, id] : named)
      vn.pairs->emplace_back(Value::Str(name), Value::Bytes(id));
    state.set("named_actors", vn);
    Value vk = Value::Dict();
    for (auto& [key, val] : kv) {
      Value t = Value::Tuple();
      t.push(Value::Str(key.first));
      t.push(Value::Bytes(key.second));
      vk.pairs->emplace_back(std::move(t), Value::Bytes(val));
    }
    state.set("kv", vk);
    Value vp = Value::Dict();
    for (auto& [id, pg] : pgs)
      vp.pairs->emplace_back(Value::Bytes(id), pg);
    state.set("placement_groups", vp);
    Value vj = Value::Dict();
    for (auto& [id, job] : jobs)
      vj.pairs->emplace_back(Value::Str(id), job);
    state.set("jobs", vj);
    Value vw = Value::Dict();
    for (auto& [id, w] : workers)
      vw.pairs->emplace_back(Value::Bytes(id), w);
    state.set("workers", vw);
    Value vt = Value::List();
    for (auto& ev : task_events) vt.push(ev);
    state.set("task_events", vt);

    std::string data = wire::encode(state);
    if (!persist->store(data)) {
      // re-arm the timer OURSELVES: with a network backend a transient
      // failure must retry even if no further mutation ever arrives
      dirty = true;
      snapshot_due_mono = mono_s() + 1.0;
    }
  }

  void restore() {
    std::string data;
    if (!persist->load(&data)) {
      // the durable store exists but is unreachable: starting EMPTY and
      // later overwriting it would destroy the persisted control plane
      fprintf(stderr,
              "FATAL: GCS persistence backend unreachable at startup\n");
      exit(1);
    }
    if (data.empty()) return;
    Value state;
    try {
      state = wire::decode(data);
    } catch (const wire::WireError&) {
      return;  // torn/corrupt snapshot: start empty
    }
    if (state.kind != Value::DICT) return;
    if (const Value* va = state.get("actors"); va && va->pairs)
      for (auto& [k, v] : *va->pairs)
        if (k.kind == Value::BYTES) actors[k.s] = v;
    if (const Value* vn = state.get("named_actors"); vn && vn->pairs)
      for (auto& [k, v] : *vn->pairs)
        if (k.kind == Value::STR && v.kind == Value::BYTES) named[k.s] = v.s;
    if (const Value* vk = state.get("kv"); vk && vk->pairs)
      for (auto& [k, v] : *vk->pairs)
        if (k.kind == Value::TUPLE && k.items && k.items->size() == 2 &&
            v.kind == Value::BYTES)
          kv[{(*k.items)[0].s, (*k.items)[1].s}] = v.s;
    if (const Value* vp = state.get("placement_groups"); vp && vp->pairs)
      for (auto& [k, v] : *vp->pairs)
        if (k.kind == Value::BYTES) pgs[k.s] = v;
    if (const Value* vj = state.get("jobs"); vj && vj->pairs)
      for (auto& [k, v] : *vj->pairs)
        if (k.kind == Value::STR) jobs[k.s] = v;
    if (const Value* vw = state.get("workers"); vw && vw->pairs)
      for (auto& [k, v] : *vw->pairs)
        if (k.kind == Value::BYTES) workers[k.s] = v;
    if (const Value* vt = state.get("task_events"); vt && vt->items)
      for (auto& ev : *vt->items) task_events.push_back(ev);

    // Restored workers belonged to the previous incarnation's processes:
    // they are gone (the reference's WorkerTable reports them DEAD on
    // GCS failover the same way).
    for (auto& [id, w] : workers) {
      const Value* st = w.get("state");
      if (!st || st->kind != Value::STR || st->s != kStateDead) {
        w.set("state", Value::Str(kStateDead));
        w.set("exit_detail",
              Value::Str("GCS restarted; worker process lost"));
      }
    }

    // Restored actors lived on nodes that predate this incarnation: mark
    // restartable ones RESTARTING so the head scheduler recreates them,
    // DEAD otherwise (reference: gcs_actor_manager restart-on-GCS-recovery).
    for (auto& [id, info] : actors) {
      const Value* st = info.get("state");
      if (st && st->kind == Value::STR && st->s == kStateDead) continue;
      int64_t max_r = info.get("max_restarts") ? info.get("max_restarts")->as_i() : 0;
      int64_t num_r = info.get("num_restarts") ? info.get("num_restarts")->as_i() : 0;
      if (max_r == -1 || num_r < max_r) {
        info.set("state", Value::Str(kStateRestarting));
        info.set("num_restarts", Value::Int(num_r + 1));
        info.set("worker_id", Value::None());
        info.set("node_id", Value::None());
        info.set("addr", Value::None());
      } else {
        info.set("state", Value::Str(kStateDead));
        info.set("death_cause",
                 Value::Str("GCS restarted; actor not restartable"));
        const Value* nm = info.get("name");
        if (nm && nm->kind == Value::STR) named.erase(nm->s);
      }
    }
    dirty = true;
    snapshot();  // restart transitions must survive ANOTHER crash
  }
};

// ---------------------------------------------------------------------------
// Method dispatch
// ---------------------------------------------------------------------------

static const Value* arg(const wire::Request& req, size_t i,
                        const char* name = nullptr) {
  if (req.args.items && i < req.args.items->size())
    return &(*req.args.items)[i];
  if (name) return req.kwargs.get(name);
  return nullptr;
}

static std::string arg_bytes(const wire::Request& req, size_t i,
                             const char* name) {
  const Value* v = arg(req, i, name);
  if (!v || (v->kind != Value::BYTES && v->kind != Value::STR))
    throw wire::WireError(std::string("bad argument: ") + name);
  return v->s;
}

static Value actor_event(const Value& info) {
  Value ev = Value::Dict();
  ev.set("ch", Value::Str("actors"));
  const Value* id = info.get("actor_id");
  ev.set("actor_id", id ? *id : Value::None());
  const Value* st = info.get("state");
  ev.set("state", st ? *st : Value::None());
  const Value* ad = info.get("addr");
  ev.set("addr", ad ? *ad : Value::None());
  return ev;
}

// Drops a node from every object's location set; objects losing their last
// copy are tombstoned LOST (+ published) so owners re-execute lineage.
// Shared by mark_node_dead (node died) and drop_node_objects (the node is
// alive but its store daemon restarted empty under supervision).  Returns
// how many objects lost their last copy.
static int64_t do_drop_node_objects(Gcs& g, const std::string& node_id) {
  int64_t lost = 0;
  for (auto oit = g.obj_locs.begin(); oit != g.obj_locs.end();) {
    oit->second.erase(node_id);
    if (oit->second.empty()) {
      if (g.lost_objects.size() >= 1000000)
        g.lost_objects.erase(g.lost_objects.begin());
      g.lost_objects.insert(oit->first);
      lost++;
      Value ev = Value::Dict();
      ev.set("ch", Value::Str("objects"));
      ev.set("oid", Value::Bytes(oit->first));
      ev.set("lost", Value::Bool(true));
      g.publish("objects", std::move(ev));
      oit = g.obj_locs.erase(oit);
    } else {
      ++oit;
    }
  }
  return lost;
}

// Marks a node dead; returns true on alive->dead transition.  Mirrors
// gcs.py mark_node_dead including the object-location cleanup + LOST
// tombstones that let owners trigger lineage re-execution.
static bool do_mark_node_dead(Gcs& g, const std::string& node_id) {
  auto it = g.nodes.find(node_id);
  if (it == g.nodes.end()) return false;
  Value& info = it->second;
  const Value* alive = info.get("alive");
  if (!alive || !alive->truthy()) return false;
  info.set("alive", Value::Bool(false));
  do_drop_node_objects(g, node_id);
  Value ev = Value::Dict();
  ev.set("ch", Value::Str("nodes"));
  ev.set("node_id", Value::Bytes(node_id));
  ev.set("alive", Value::Bool(false));
  g.publish("nodes", std::move(ev));
  return true;
}

struct PendingSub {
  bool parked = false;
  std::vector<std::string> channels;
  uint64_t cursor = 0;
  double deadline_mono = 0;
};

// Builds the sub_poll reply for a cursor; returns false if nothing to send
// yet (caller may park).
static bool sub_reply(Gcs& g, const std::vector<std::string>& channels,
                      uint64_t cursor, Value* out) {
  uint64_t oldest = g.events.empty() ? g.next_seq : g.events.front().seq;
  Value reply = Value::Dict();
  if (cursor < oldest) {
    // events the subscriber hasn't seen were evicted from the ring:
    // signal a gap so it re-reads table state instead of trusting events
    reply.set("cursor", Value::Int(int64_t(g.next_seq)));
    reply.set("events", Value::List());
    reply.set("gap", Value::Bool(true));
    *out = std::move(reply);
    return true;
  }
  Value evs = Value::List();
  uint64_t next_cursor = cursor;
  for (const Event& e : g.events) {
    if (e.seq < cursor) continue;
    next_cursor = e.seq + 1;
    bool match = channels.empty();
    for (const std::string& ch : channels)
      if (e.channel == ch) { match = true; break; }
    if (match) evs.push(e.payload);
  }
  if (evs.items->empty()) return false;
  reply.set("cursor", Value::Int(int64_t(next_cursor)));
  reply.set("events", std::move(evs));
  reply.set("gap", Value::Bool(false));
  *out = std::move(reply);
  return true;
}

// Dispatch one request.  Returns the response frame body; sets *park when
// the request is a long-poll that must wait (no frame is sent yet).
static std::string dispatch(Gcs& g, const wire::Request& req,
                            PendingSub* park) {
  const std::string& m = req.method;
  Value r = Value::None();
  try {
    if (m == "kv_put") {
      std::string ns = arg_bytes(req, 0, "namespace");
      std::string key = arg_bytes(req, 1, "key");
      g.kv[{ns, key}] = arg_bytes(req, 2, "value");
      Value ev = Value::Dict();
      ev.set("ch", Value::Str("kv:" + ns));
      ev.set("key", Value::Bytes(key));
      g.publish("kv:" + ns, std::move(ev));
      g.mutated();
    } else if (m == "kv_get") {
      auto it = g.kv.find({arg_bytes(req, 0, "namespace"),
                           arg_bytes(req, 1, "key")});
      if (it != g.kv.end()) r = Value::Bytes(it->second);
    } else if (m == "kv_del") {
      g.kv.erase({arg_bytes(req, 0, "namespace"), arg_bytes(req, 1, "key")});
      g.mutated();
    } else if (m == "kv_keys") {
      std::string ns = arg_bytes(req, 0, "namespace");
      r = Value::List();
      for (auto& [key, _] : g.kv)
        if (key.first == ns) r.push(Value::Bytes(key.second));
    } else if (m == "register_actor") {
      const Value* info = arg(req, 0, "info");
      if (!info || info->kind != Value::STRUCT)
        throw wire::WireError("register_actor needs ActorInfo");
      Value copy = *info;
      copy.pairs = std::make_shared<wire::ValuePairs>(*info->pairs);
      const Value* aid = copy.get("actor_id");
      if (!aid || aid->kind != Value::BYTES)
        throw wire::WireError("register_actor: missing actor_id");
      const Value* nm = copy.get("name");
      if (nm && nm->kind == Value::STR && !nm->s.empty()) {
        if (g.named.count(nm->s))
          return wire::encode_response(
              false, Value::Error("ValueError", "actor name '" + nm->s +
                                                    "' already taken"));
        g.named[nm->s] = aid->s;
      }
      g.actors[aid->s] = copy;
      g.publish("actors", actor_event(copy));
      g.mutated();
    } else if (m == "update_actor") {
      std::string id = arg_bytes(req, 0, "actor_id");
      auto it = g.actors.find(id);
      if (it != g.actors.end()) {
        Value& info = it->second;
        // fields arrive as kwargs (plus any positional dict is ignored —
        // the Python surface is update_actor(actor_id, **fields))
        if (req.kwargs.pairs)
          for (auto& [k, v] : *req.kwargs.pairs)
            if (k.kind == Value::STR) info.set(k.s, v);
        const Value* st = info.get("state");
        if (st && st->kind == Value::STR && st->s == kStateDead) {
          const Value* nm = info.get("name");
          if (nm && nm->kind == Value::STR) {
            auto nit = g.named.find(nm->s);
            if (nit != g.named.end() && nit->second == id)
              g.named.erase(nit);
          }
        }
        g.publish("actors", actor_event(info));
        g.mutated();
      }
    } else if (m == "get_actor") {
      auto it = g.actors.find(arg_bytes(req, 0, "actor_id"));
      if (it != g.actors.end()) r = it->second;
    } else if (m == "get_actor_by_name") {
      const Value* nm = arg(req, 0, "name");
      if (nm && nm->kind == Value::STR) {
        auto nit = g.named.find(nm->s);
        if (nit != g.named.end()) {
          auto it = g.actors.find(nit->second);
          if (it != g.actors.end()) r = it->second;
        }
      }
    } else if (m == "list_actors") {
      r = Value::List();
      for (auto& [_, info] : g.actors) r.push(info);
    } else if (m == "register_node") {
      const Value* info = arg(req, 0, "info");
      if (!info || info->kind != Value::STRUCT)
        throw wire::WireError("register_node needs NodeInfo");
      Value copy = *info;
      copy.pairs = std::make_shared<wire::ValuePairs>(*info->pairs);
      const Value* nid = copy.get("node_id");
      if (!nid || nid->kind != Value::BYTES)
        throw wire::WireError("register_node: missing node_id");
      const Value* res = copy.get("resources");
      copy.set("available", res ? *res : Value::Dict());
      if (!copy.get("ts")) copy.set("ts", Value::Float(now_s()));
      g.nodes[nid->s] = copy;
      Value ev = Value::Dict();
      ev.set("ch", Value::Str("nodes"));
      ev.set("node_id", Value::Bytes(nid->s));
      ev.set("alive", Value::Bool(true));
      g.publish("nodes", std::move(ev));
    } else if (m == "list_nodes") {
      r = Value::List();
      for (auto& [_, info] : g.nodes) r.push(info);
    } else if (m == "get_node") {
      auto it = g.nodes.find(arg_bytes(req, 0, "node_id"));
      if (it != g.nodes.end()) r = it->second;
    } else if (m == "heartbeat") {
      auto it = g.nodes.find(arg_bytes(req, 0, "node_id"));
      if (it != g.nodes.end()) {
        Value& info = it->second;
        const Value* alive = info.get("alive");
        if (alive && alive->truthy()) {
          info.set("ts", Value::Float(now_s()));
          const Value* av = arg(req, 1, "available");
          if (av) info.set("available", *av);
          const Value* q = arg(req, 2, "queued");
          if (q) info.set("queued", *q);
        }
      }
    } else if (m == "mark_node_dead") {
      r = Value::Bool(do_mark_node_dead(g, arg_bytes(req, 0, "node_id")));
    } else if (m == "drop_node_objects") {
      r = Value::Int(do_drop_node_objects(g, arg_bytes(req, 0, "node_id")));
    } else if (m == "check_node_health") {
      double now = now_s();
      std::vector<std::string> stale;
      for (auto& [id, info] : g.nodes) {
        const Value* alive = info.get("alive");
        const Value* is_head = info.get("is_head");
        const Value* ts = info.get("ts");
        if (alive && alive->truthy() && !(is_head && is_head->truthy()) &&
            ts && now - ts->as_f() > g.death_timeout_s)
          stale.push_back(id);
      }
      r = Value::List();
      for (const std::string& id : stale)
        if (do_mark_node_dead(g, id)) r.push(Value::Bytes(id));
    } else if (m == "add_object_location") {
      std::string oid = arg_bytes(req, 0, "oid");
      g.obj_locs[oid].insert(arg_bytes(req, 1, "node_id"));
      g.lost_objects.erase(oid);
      Value ev = Value::Dict();
      ev.set("ch", Value::Str("objects"));
      ev.set("oid", Value::Bytes(oid));
      ev.set("lost", Value::Bool(false));
      g.publish("objects", std::move(ev));
    } else if (m == "add_object_locations") {
      // batched seal-notification flush: one RPC, many locations
      const Value* pairs = arg(req, 0, "pairs");
      if (pairs && pairs->items) {
        for (const Value& p : *pairs->items) {
          if (!p.items || p.items->size() != 2) continue;
          const Value& oid = (*p.items)[0];
          const Value& nid = (*p.items)[1];
          if (oid.kind != Value::BYTES || nid.kind != Value::BYTES)
            continue;
          g.obj_locs[oid.s].insert(nid.s);
          g.lost_objects.erase(oid.s);
          Value ev = Value::Dict();
          ev.set("ch", Value::Str("objects"));
          ev.set("oid", Value::Bytes(oid.s));
          ev.set("lost", Value::Bool(false));
          g.publish("objects", std::move(ev));
        }
      }
    } else if (m == "remove_object_location") {
      std::string oid = arg_bytes(req, 0, "oid");
      auto it = g.obj_locs.find(oid);
      if (it != g.obj_locs.end()) {
        it->second.erase(arg_bytes(req, 1, "node_id"));
        if (it->second.empty()) g.obj_locs.erase(it);
      }
    } else if (m == "get_object_locations") {
      r = Value::List();
      auto it = g.obj_locs.find(arg_bytes(req, 0, "oid"));
      if (it != g.obj_locs.end())
        for (const std::string& nid : it->second) r.push(Value::Bytes(nid));
    } else if (m == "all_object_locations") {
      r = Value::Dict();
      for (auto& [oid, locs] : g.obj_locs) {
        Value l = Value::List();
        for (const std::string& nid : locs) l.push(Value::Bytes(nid));
        r.pairs->emplace_back(Value::Bytes(oid), std::move(l));
      }
    } else if (m == "object_lost") {
      r = Value::Bool(g.lost_objects.count(arg_bytes(req, 0, "oid")) > 0);
    } else if (m == "clear_object_lost") {
      g.lost_objects.erase(arg_bytes(req, 0, "oid"));
    } else if (m == "register_pg") {
      Value pg = Value::Dict();
      const Value* bundles = arg(req, 1, "bundles");
      const Value* strategy = arg(req, 2, "strategy");
      const Value* assignment = arg(req, 3, "assignment");
      pg.set("bundles", bundles ? *bundles : Value::List());
      pg.set("strategy", strategy ? *strategy : Value::Str("PACK"));
      pg.set("assignment", assignment ? *assignment : Value::List());
      g.pgs[arg_bytes(req, 0, "pg_id")] = std::move(pg);
      g.mutated();
    } else if (m == "get_pg") {
      auto it = g.pgs.find(arg_bytes(req, 0, "pg_id"));
      if (it != g.pgs.end()) r = it->second;
    } else if (m == "remove_pg") {
      g.pgs.erase(arg_bytes(req, 0, "pg_id"));
      g.mutated();
    } else if (m == "list_pgs") {
      r = Value::Dict();
      for (auto& [id, pg] : g.pgs)
        r.pairs->emplace_back(Value::Bytes(id), pg);
    } else if (m == "add_job") {
      // (job_id, info DICT) — full record insert; publishes on "jobs"
      std::string jid = arg_bytes(req, 0, "job_id");
      const Value* info = arg(req, 1, "info");
      if (!info || (info->kind != Value::DICT &&
                    info->kind != Value::STRUCT))
        throw wire::WireError("add_job needs an info dict");
      g.jobs[jid] = *info;
      Value ev = Value::Dict();
      ev.set("ch", Value::Str("jobs"));
      ev.set("job_id", Value::Str(jid));
      g.publish("jobs", std::move(ev));
      g.mutated();
    } else if (m == "update_job") {
      // (job_id, fields DICT) — merge; missing job returns False
      std::string jid = arg_bytes(req, 0, "job_id");
      auto it = g.jobs.find(jid);
      if (it == g.jobs.end()) {
        r = Value::Bool(false);
      } else {
        const Value* fields = arg(req, 1, "fields");
        if (fields && fields->pairs) {
          Value copy = it->second;
          copy.pairs = std::make_shared<wire::ValuePairs>(
              *it->second.pairs);
          for (auto& [k, v] : *fields->pairs)
            if (k.kind == Value::STR) copy.set(k.s.c_str(), v);
          it->second = std::move(copy);
        }
        Value ev = Value::Dict();
        ev.set("ch", Value::Str("jobs"));
        ev.set("job_id", Value::Str(jid));
        g.publish("jobs", std::move(ev));
        g.mutated();
        r = Value::Bool(true);
      }
    } else if (m == "get_job") {
      auto it = g.jobs.find(arg_bytes(req, 0, "job_id"));
      if (it != g.jobs.end()) r = it->second;
    } else if (m == "list_jobs") {
      r = Value::List();
      for (auto& [_, job] : g.jobs) r.push(job);
    } else if (m == "add_worker") {
      std::string wid = arg_bytes(req, 0, "worker_id");
      const Value* info = arg(req, 1, "info");
      if (!info || (info->kind != Value::DICT &&
                    info->kind != Value::STRUCT))
        throw wire::WireError("add_worker needs an info dict");
      g.workers[wid] = *info;
      // bound the table: evict the oldest DEAD records past the cap
      if (g.workers.size() > 2 * g.max_dead_workers) {
        std::vector<std::pair<double, std::string>> dead;
        for (auto& [id, w] : g.workers) {
          const Value* st = w.get("state");
          if (st && st->kind == Value::STR && st->s == kStateDead) {
            const Value* ts = w.get("end_ts");
            dead.emplace_back(ts ? ts->as_f() : 0.0, id);
          }
        }
        std::sort(dead.begin(), dead.end());
        size_t drop = dead.size() > g.max_dead_workers
                          ? dead.size() - g.max_dead_workers
                          : 0;
        for (size_t i = 0; i < drop; ++i) g.workers.erase(dead[i].second);
      }
      g.mutated();
    } else if (m == "update_worker") {
      std::string wid = arg_bytes(req, 0, "worker_id");
      auto it = g.workers.find(wid);
      if (it == g.workers.end()) {
        r = Value::Bool(false);
      } else {
        const Value* fields = arg(req, 1, "fields");
        if (fields && fields->pairs) {
          Value copy = it->second;
          copy.pairs = std::make_shared<wire::ValuePairs>(
              *it->second.pairs);
          for (auto& [k, v] : *fields->pairs)
            if (k.kind == Value::STR) copy.set(k.s.c_str(), v);
          it->second = std::move(copy);
        }
        g.mutated();
        r = Value::Bool(true);
      }
    } else if (m == "list_workers") {
      r = Value::List();
      for (auto& [_, w] : g.workers) r.push(w);
    } else if (m == "add_task_events") {
      // (events LIST of DICT): batch append into the bounded ring —
      // one RPC per flusher wakeup, mirroring the reference's
      // task_event_buffer batching
      const Value* evs = arg(req, 0, "events");
      if (evs && evs->items) {
        for (auto& ev : *evs->items) g.task_events.push_back(ev);
        while (g.task_events.size() > g.task_event_cap)
          g.task_events.pop_front();
        double now = mono_s();
        if (now - g.tev_last_persist_mono > g.tev_persist_every_s) {
          g.tev_last_persist_mono = now;
          g.mutated();
        }
      }
      r = Value::Int(int64_t(g.task_events.size()));
    } else if (m == "list_task_events") {
      // (limit) — newest-last window of the ring
      const Value* lim = arg(req, 0, "limit");
      size_t limit = lim ? size_t(lim->as_i()) : size_t(1000);
      r = Value::List();
      size_t start = g.task_events.size() > limit
                         ? g.task_events.size() - limit
                         : 0;
      for (size_t i = start; i < g.task_events.size(); ++i)
        r.push(g.task_events[i]);
    } else if (m == "broadcast_command") {
      // syncer COMMANDS channel (reference: ray_syncer.h:83): publish the
      // payload cluster-wide; schedulers subscribed to "commands" act
      const Value* payload = arg(req, 0, "payload");
      Value ev = Value::Dict();
      ev.set("ch", Value::Str("commands"));
      if (payload && payload->pairs)
        for (auto& [k, v] : *payload->pairs)
          if (k.kind == Value::STR && k.s != "ch") ev.set(k.s, v);
      g.publish("commands", std::move(ev));
    } else if (m == "sub_poll") {
      // sub_poll(channels, cursor, timeout_ms) -> {cursor, events, gap}
      const Value* chv = arg(req, 0, "channels");
      std::vector<std::string> channels;
      if (chv && chv->items)
        for (const Value& c : *chv->items)
          if (c.kind == Value::STR) channels.push_back(c.s);
      const Value* cur = arg(req, 1, "cursor");
      int64_t cursor = cur ? cur->as_i() : -1;
      const Value* tmo = arg(req, 2, "timeout_ms");
      int64_t timeout_ms = tmo ? tmo->as_i() : 0;
      if (cursor < 0) {  // tail: hand back the current end of the log
        Value reply = Value::Dict();
        reply.set("cursor", Value::Int(int64_t(g.next_seq)));
        reply.set("events", Value::List());
        reply.set("gap", Value::Bool(false));
        return wire::encode_response(true, reply);
      }
      Value reply;
      if (sub_reply(g, channels, uint64_t(cursor), &reply))
        return wire::encode_response(true, reply);
      if (timeout_ms > 0) {  // park until publish or timeout
        park->parked = true;
        park->channels = std::move(channels);
        park->cursor = uint64_t(cursor);
        park->deadline_mono = mono_s() + double(timeout_ms) / 1000.0;
        return std::string();
      }
      // nothing matched in the scanned range: advance to the end, or
      // unrelated-channel churn would evict the stale position and turn
      // every later poll into a spurious gap
      reply = Value::Dict();
      reply.set("cursor", Value::Int(int64_t(g.next_seq)));
      reply.set("events", Value::List());
      reply.set("gap", Value::Bool(false));
      return wire::encode_response(true, reply);
    } else {
      return wire::encode_response(
          false,
          Value::Error("ValueError", "unknown GCS method '" + m + "'"));
    }
  } catch (const wire::WireError& e) {
    return wire::encode_response(false,
                                 Value::Error("ValueError", e.what()));
  }
  return wire::encode_response(true, r);
}

// ---------------------------------------------------------------------------
// Event loop: epoll, nonblocking conns, length-prefixed frames
// ---------------------------------------------------------------------------

struct Conn {
  int fd;
  bool is_tcp;
  enum Phase { AUTH, HELLO, READY } phase;
  std::string in;    // read accumulation
  std::string out;   // pending writes
  PendingSub sub;    // parked long-poll (at most one per conn)
  bool closing = false;
};

static constexpr size_t kMaxFrame = 1u << 28;

static void set_nonblock(int fd) {
  fcntl(fd, F_SETFL, fcntl(fd, F_GETFL, 0) | O_NONBLOCK);
}

struct Server {
  Gcs gcs;
  int epfd = -1;
  int listen_fd = -1;
  bool listen_tcp = false;
  std::string token;  // TCP peers must present this before frame 1
  std::map<int, Conn> conns;

  void add_frame(Conn& c, const std::string& body) {
    uint32_t n = uint32_t(body.size());
    char hdr[4];
    memcpy(hdr, &n, 4);
    c.out.append(hdr, 4);
    c.out.append(body);
  }

  void want_write(Conn& c) {
    struct epoll_event ev;
    ev.events = EPOLLIN | (c.out.empty() ? 0 : EPOLLOUT);
    ev.data.fd = c.fd;
    epoll_ctl(epfd, EPOLL_CTL_MOD, c.fd, &ev);
  }

  void close_conn(int fd) {
    epoll_ctl(epfd, EPOLL_CTL_DEL, fd, nullptr);
    close(fd);
    conns.erase(fd);
  }

  void flush(Conn& c) {
    while (!c.out.empty()) {
      ssize_t n = write(c.fd, c.out.data(), c.out.size());
      if (n > 0) {
        c.out.erase(0, size_t(n));
      } else if (n < 0 && (errno == EAGAIN || errno == EWOULDBLOCK)) {
        break;
      } else {
        c.closing = true;
        return;
      }
    }
    if (c.closing && c.out.empty()) return;
    want_write(c);
  }

  // Completes parked long-polls that now have matching events (called
  // after every dispatch that may have published).
  void wake_subscribers() {
    for (auto& [fd, c] : conns) {
      if (!c.sub.parked) continue;
      Value reply;
      if (sub_reply(gcs, c.sub.channels, c.sub.cursor, &reply)) {
        c.sub.parked = false;
        add_frame(c, wire::encode_response(true, reply));
        flush(c);
      }
    }
  }

  void expire_subscribers(double now_mono) {
    for (auto& [fd, c] : conns) {
      if (!c.sub.parked || c.sub.deadline_mono > now_mono) continue;
      c.sub.parked = false;
      Value reply = Value::Dict();
      // every event < next_seq was scanned (wake_subscribers runs after
      // each publish): none matched, so the cursor can safely advance —
      // leaving it behind would rot into spurious gaps under churn
      reply.set("cursor", Value::Int(int64_t(gcs.next_seq)));
      reply.set("events", Value::List());
      reply.set("gap", Value::Bool(false));
      add_frame(c, wire::encode_response(true, reply));
      flush(c);
    }
  }

  // Pulls complete frames out of c.in; returns false when the connection
  // must close.
  bool on_readable(Conn& c) {
    char buf[1 << 16];
    for (;;) {
      ssize_t n = read(c.fd, buf, sizeof buf);
      if (n > 0) {
        c.in.append(buf, size_t(n));
        if (c.in.size() > kMaxFrame + 4) return false;  // flooding
      } else if (n == 0) {
        return false;  // clean EOF
      } else if (errno == EAGAIN || errno == EWOULDBLOCK) {
        break;
      } else {
        return false;
      }
    }
    for (;;) {
      if (c.in.size() < 4) return true;
      uint32_t len;
      memcpy(&len, c.in.data(), 4);
      if (len > kMaxFrame) return false;
      if (c.in.size() < 4 + size_t(len)) return true;
      std::string body = c.in.substr(4, len);
      c.in.erase(0, 4 + size_t(len));
      if (!on_frame(c, body)) return false;
    }
  }

  bool on_frame(Conn& c, const std::string& body) {
    switch (c.phase) {
      case Conn::AUTH:
        // constant-time-ish compare (reference: token-authenticated TCP
        // control plane; see protocol.py authenticate_server_side)
        if (body.size() != token.size() ||
            CRYPTO_memcmp(body, token) != 0) {
          add_frame(c, "NO");
          flush(c);
          return false;
        }
        add_frame(c, "OK");
        c.phase = Conn::HELLO;
        flush(c);
        return true;
      case Conn::HELLO:
        if (body != wire::kHello) return false;  // version mismatch: hang up
        add_frame(c, wire::kHelloOk);
        c.phase = Conn::READY;
        flush(c);
        return true;
      case Conn::READY: {
        wire::Request req;
        try {
          req = wire::decode_request(body);
        } catch (const wire::WireError& e) {
          add_frame(c, wire::encode_response(
                           false, Value::Error("ValueError", e.what())));
          flush(c);
          return true;  // framing is intact; keep serving
        }
        PendingSub park;
        std::string resp = dispatch(gcs, req, &park);
        if (park.parked) {
          c.sub = std::move(park);
          return true;
        }
        add_frame(c, resp);
        flush(c);
        wake_subscribers();
        return true;
      }
    }
    return false;
  }

  static int CRYPTO_memcmp(const std::string& a, const std::string& b) {
    unsigned char d = 0;
    for (size_t i = 0; i < a.size(); ++i)
      d |= (unsigned char)(a[i]) ^ (unsigned char)(b[i]);
    return d;
  }

  void accept_all() {
    for (;;) {
      int fd = accept(listen_fd, nullptr, nullptr);
      if (fd < 0) return;
      set_nonblock(fd);
      if (listen_tcp) {
        int one = 1;
        setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof one);
      }
      Conn c;
      c.fd = fd;
      c.is_tcp = listen_tcp;
      c.phase = listen_tcp ? Conn::AUTH : Conn::HELLO;
      conns.emplace(fd, std::move(c));
      struct epoll_event ev;
      ev.events = EPOLLIN;
      ev.data.fd = fd;
      epoll_ctl(epfd, EPOLL_CTL_ADD, fd, &ev);
    }
  }

  pid_t parent_pid = 0;  // exit when the spawning head process dies
  double next_parent_check = 0;

  int run() {
    struct epoll_event evs[64];
    for (;;) {
      if (g_stop) {  // SIGTERM/SIGINT: flush durable state, then exit
        gcs.dirty = gcs.dirty || bool(gcs.persist);
        gcs.snapshot();
        return 0;
      }
      // epoll timeout = nearest of (snapshot debounce, sub deadlines)
      double now = mono_s();
      if (parent_pid > 0 && now >= next_parent_check) {
        next_parent_check = now + 1.0;
        if (kill(parent_pid, 0) != 0 && errno == ESRCH) {
          gcs.snapshot();  // flush durable state before orphan exit
          return 0;
        }
      }
      double next = now + 1.0;
      if (gcs.snapshot_due_mono > 0 && gcs.snapshot_due_mono < next)
        next = gcs.snapshot_due_mono;
      for (auto& [fd, c] : conns)
        if (c.sub.parked && c.sub.deadline_mono < next)
          next = c.sub.deadline_mono;
      int timeout_ms = int((next - now) * 1000.0);
      if (timeout_ms < 0) timeout_ms = 0;
      int n = epoll_wait(epfd, evs, 64, timeout_ms);
      if (n < 0 && errno == EINTR) continue;  // signal: loop re-checks g_stop
      now = mono_s();
      if (gcs.snapshot_due_mono > 0 && now >= gcs.snapshot_due_mono)
        gcs.snapshot();
      expire_subscribers(now);
      for (int i = 0; i < n; ++i) {
        int fd = evs[i].data.fd;
        if (fd == listen_fd) {
          accept_all();
          continue;
        }
        auto it = conns.find(fd);
        if (it == conns.end()) continue;
        Conn& c = it->second;
        bool ok = true;
        if (evs[i].events & (EPOLLHUP | EPOLLERR))
          ok = false;
        else {
          if (evs[i].events & EPOLLIN) ok = on_readable(c);
          if (ok && (evs[i].events & EPOLLOUT)) flush(c);
          if (c.closing) ok = false;
        }
        if (!ok) close_conn(fd);
      }
    }
  }
};

int main(int argc, char** argv) {
  std::string bind_addr, advertise_file, persist;
  double death_timeout = 5.0;
  int parent_pid_arg = 0;
  for (int i = 1; i < argc - 1; ++i) {
    std::string a = argv[i];
    if (a == "--bind") bind_addr = argv[++i];
    else if (a == "--advertise-file") advertise_file = argv[++i];
    else if (a == "--persist") persist = argv[++i];
    else if (a == "--death-timeout-s") death_timeout = atof(argv[++i]);
    else if (a == "--parent-pid") parent_pid_arg = atoi(argv[++i]);
  }
  if (bind_addr.empty()) {
    fprintf(stderr, "usage: gcs_server --bind <unix path|host:port> "
                    "[--advertise-file F] [--persist F] "
                    "[--death-timeout-s S]\n");
    return 2;
  }
  signal(SIGPIPE, SIG_IGN);
  struct sigaction sa_stop;
  memset(&sa_stop, 0, sizeof sa_stop);
  sa_stop.sa_handler = on_stop_signal;  // no SA_RESTART: epoll must EINTR
  sigaction(SIGTERM, &sa_stop, nullptr);
  sigaction(SIGINT, &sa_stop, nullptr);

  Server srv;
  srv.parent_pid = parent_pid_arg;
  srv.gcs.death_timeout_s = death_timeout;
  srv.gcs.persist = make_persist(persist);
  if (srv.gcs.persist) srv.gcs.restore();
  const char* tok = getenv("RTPU_CLUSTER_TOKEN");
  srv.token = tok ? tok : "";

  // TCP address = has a ':' and doesn't start with '/' or '.'
  size_t colon = bind_addr.rfind(':');
  srv.listen_tcp = bind_addr[0] != '/' && bind_addr[0] != '.' &&
                   colon != std::string::npos;
  std::string advertised = bind_addr;
  if (srv.listen_tcp) {
    std::string host = bind_addr.substr(0, colon);
    int port = atoi(bind_addr.c_str() + colon + 1);
    srv.listen_fd = socket(AF_INET, SOCK_STREAM, 0);
    int one = 1;
    setsockopt(srv.listen_fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof one);
    struct sockaddr_in sa;
    memset(&sa, 0, sizeof sa);
    sa.sin_family = AF_INET;
    sa.sin_port = htons(uint16_t(port));
    if (host.empty() || host == "0.0.0.0")
      sa.sin_addr.s_addr = INADDR_ANY;
    else if (inet_pton(AF_INET, host.c_str(), &sa.sin_addr) != 1)
      sa.sin_addr.s_addr = INADDR_ANY;
    if (bind(srv.listen_fd, (struct sockaddr*)&sa, sizeof sa) != 0 ||
        listen(srv.listen_fd, 512) != 0) {
      perror("bind/listen");
      return 1;
    }
    socklen_t slen = sizeof sa;
    getsockname(srv.listen_fd, (struct sockaddr*)&sa, &slen);
    advertised = host + ":" + std::to_string(ntohs(sa.sin_port));
  } else {
    srv.listen_fd = socket(AF_UNIX, SOCK_STREAM, 0);
    struct sockaddr_un sa;
    memset(&sa, 0, sizeof sa);
    sa.sun_family = AF_UNIX;
    strncpy(sa.sun_path, bind_addr.c_str(), sizeof sa.sun_path - 1);
    unlink(bind_addr.c_str());
    if (bind(srv.listen_fd, (struct sockaddr*)&sa, sizeof sa) != 0 ||
        listen(srv.listen_fd, 512) != 0) {
      perror("bind/listen");
      return 1;
    }
  }
  set_nonblock(srv.listen_fd);
  srv.epfd = epoll_create1(0);
  struct epoll_event ev;
  ev.events = EPOLLIN;
  ev.data.fd = srv.listen_fd;
  epoll_ctl(srv.epfd, EPOLL_CTL_ADD, srv.listen_fd, &ev);

  if (!advertise_file.empty()) {
    std::string tmp = advertise_file + ".tmp";
    FILE* f = fopen(tmp.c_str(), "w");
    if (f) {
      fprintf(f, "%s\n", advertised.c_str());
      fclose(f);
      rename(tmp.c_str(), advertise_file.c_str());
    }
  }
  return srv.run();
}
