// Wire codec: C++ mirror of ray_tpu/_private/wire.py.
//
// Counterpart of the reference's protobuf layer (/root/reference/src/ray/
// protobuf/) scaled to this runtime: one tagged, length-delimited value tree
// per frame, identical byte-for-byte to the Python codec so the native GCS /
// raylet daemons and the Python workers interoperate.  Tags:
//
//   0x00 None    0x01 False   0x02 True    0x03 int64   0x04 float64
//   0x05 str     0x06 bytes   0x07 list    0x08 tuple   0x09 dict
//   0x0A struct (u8 id + field dict)       0x0B error (type, message)
//
// Values are held as a small tagged tree (wire::Value).  Structs are kept
// generically as (id + field dict) — the daemons read/update fields by name,
// so a Python-side dataclass gaining a field is never a wire break here.

#pragma once

#include <cstdint>
#include <cstring>
#include <map>
#include <memory>
#include <stdexcept>
#include <string>
#include <utility>
#include <vector>

namespace wire {

constexpr uint8_t kVersion = 1;
inline const std::string kHello = std::string("RTPUWIRE") + char(kVersion);
inline const std::string kHelloOk =
    std::string("RTPUWIRE-OK") + char(kVersion);

constexpr int kMaxDepth = 32;
constexpr uint32_t kMaxItems = 1u << 22;

struct WireError : std::runtime_error {
  explicit WireError(const std::string& m) : std::runtime_error(m) {}
};

struct Value;
using ValueList = std::vector<Value>;
using ValuePairs = std::vector<std::pair<Value, Value>>;

struct Value {
  enum Kind : uint8_t {
    NIL, BOOL, INT, FLOAT, STR, BYTES, LIST, TUPLE, DICT, STRUCT, ERROR
  };
  Kind kind = NIL;
  bool b = false;
  int64_t i = 0;
  double f = 0.0;
  std::string s;   // STR/BYTES payload; ERROR: type name
  std::string s2;  // ERROR: message
  uint8_t struct_id = 0;
  std::shared_ptr<ValueList> items;   // LIST/TUPLE
  std::shared_ptr<ValuePairs> pairs;  // DICT / STRUCT fields

  Value() = default;
  static Value None() { return Value(); }
  static Value Bool(bool v) { Value x; x.kind = BOOL; x.b = v; return x; }
  static Value Int(int64_t v) { Value x; x.kind = INT; x.i = v; return x; }
  static Value Float(double v) { Value x; x.kind = FLOAT; x.f = v; return x; }
  static Value Str(std::string v) {
    Value x; x.kind = STR; x.s = std::move(v); return x;
  }
  static Value Bytes(std::string v) {
    Value x; x.kind = BYTES; x.s = std::move(v); return x;
  }
  static Value List() {
    Value x; x.kind = LIST; x.items = std::make_shared<ValueList>(); return x;
  }
  static Value Tuple() {
    Value x; x.kind = TUPLE; x.items = std::make_shared<ValueList>();
    return x;
  }
  static Value Dict() {
    Value x; x.kind = DICT; x.pairs = std::make_shared<ValuePairs>();
    return x;
  }
  static Value Struct(uint8_t id) {
    Value x; x.kind = STRUCT; x.struct_id = id;
    x.pairs = std::make_shared<ValuePairs>();
    return x;
  }
  static Value Error(std::string type, std::string msg) {
    Value x; x.kind = ERROR; x.s = std::move(type); x.s2 = std::move(msg);
    return x;
  }

  bool is_none() const { return kind == NIL; }
  bool truthy() const {
    switch (kind) {
      case NIL: return false;
      case BOOL: return b;
      case INT: return i != 0;
      case FLOAT: return f != 0.0;
      case STR: case BYTES: return !s.empty();
      case LIST: case TUPLE: return items && !items->empty();
      case DICT: case STRUCT: return pairs && !pairs->empty();
      default: return true;
    }
  }
  // numeric coercion (heartbeat payloads may carry ints where floats live)
  double as_f() const { return kind == INT ? double(i) : f; }
  int64_t as_i() const { return kind == FLOAT ? int64_t(f) : i; }

  // dict/struct field access by string key (linear scan: control-plane
  // dicts are tiny). Returns nullptr when absent.
  const Value* get(const std::string& key) const {
    if (!pairs) return nullptr;
    for (auto& kv : *pairs)
      if (kv.first.kind == STR && kv.first.s == key) return &kv.second;
    return nullptr;
  }
  Value* get_mut(const std::string& key) {
    if (!pairs) return nullptr;
    for (auto& kv : *pairs)
      if (kv.first.kind == STR && kv.first.s == key) return &kv.second;
    return nullptr;
  }
  void set(const std::string& key, Value v) {
    if (!pairs) pairs = std::make_shared<ValuePairs>();
    for (auto& kv : *pairs)
      if (kv.first.kind == STR && kv.first.s == key) {
        kv.second = std::move(v);
        return;
      }
    pairs->emplace_back(Value::Str(key), std::move(v));
  }
  void push(Value v) {
    if (!items) items = std::make_shared<ValueList>();
    items->push_back(std::move(v));
  }
};

// ---------------------------------------------------------------------------
// Encoding
// ---------------------------------------------------------------------------

inline void put_u32(std::string& out, uint32_t v) {
  char b[4];
  std::memcpy(b, &v, 4);  // little-endian hosts only (x86/ARM)
  out.append(b, 4);
}

inline void encode_into(std::string& out, const Value& v, int depth = 0) {
  if (depth > kMaxDepth) throw WireError("encode: nesting too deep");
  switch (v.kind) {
    case Value::NIL: out.push_back(0x00); break;
    case Value::BOOL: out.push_back(v.b ? 0x02 : 0x01); break;
    case Value::INT: {
      out.push_back(0x03);
      char b[8];
      std::memcpy(b, &v.i, 8);
      out.append(b, 8);
      break;
    }
    case Value::FLOAT: {
      out.push_back(0x04);
      char b[8];
      std::memcpy(b, &v.f, 8);
      out.append(b, 8);
      break;
    }
    case Value::STR:
    case Value::BYTES:
      out.push_back(v.kind == Value::STR ? 0x05 : 0x06);
      put_u32(out, uint32_t(v.s.size()));
      out.append(v.s);
      break;
    case Value::LIST:
    case Value::TUPLE: {
      out.push_back(v.kind == Value::LIST ? 0x07 : 0x08);
      size_t n = v.items ? v.items->size() : 0;
      put_u32(out, uint32_t(n));
      for (size_t k = 0; k < n; ++k)
        encode_into(out, (*v.items)[k], depth + 1);
      break;
    }
    case Value::DICT: {
      out.push_back(0x09);
      size_t n = v.pairs ? v.pairs->size() : 0;
      put_u32(out, uint32_t(n));
      for (size_t k = 0; k < n; ++k) {
        encode_into(out, (*v.pairs)[k].first, depth + 1);
        encode_into(out, (*v.pairs)[k].second, depth + 1);
      }
      break;
    }
    case Value::STRUCT: {
      out.push_back(0x0A);
      out.push_back(char(v.struct_id));
      out.push_back(0x09);  // field dict
      size_t n = v.pairs ? v.pairs->size() : 0;
      put_u32(out, uint32_t(n));
      for (size_t k = 0; k < n; ++k) {
        encode_into(out, (*v.pairs)[k].first, depth + 1);
        encode_into(out, (*v.pairs)[k].second, depth + 1);
      }
      break;
    }
    case Value::ERROR:
      out.push_back(0x0B);
      encode_into(out, Value::Str(v.s), depth + 1);
      encode_into(out, Value::Str(v.s2), depth + 1);
      break;
  }
}

inline std::string encode(const Value& v) {
  std::string out;
  encode_into(out, v);
  return out;
}

// ---------------------------------------------------------------------------
// Decoding
// ---------------------------------------------------------------------------

struct Reader {
  const char* p;
  size_t len;
  size_t pos = 0;

  uint8_t u8() {
    if (pos >= len) throw WireError("truncated frame");
    return uint8_t(p[pos++]);
  }
  uint32_t u32() {
    if (pos + 4 > len) throw WireError("truncated length");
    uint32_t v;
    std::memcpy(&v, p + pos, 4);
    pos += 4;
    return v;
  }
};

inline Value decode_one(Reader& r, int depth) {
  if (depth > kMaxDepth) throw WireError("decode: nesting too deep");
  uint8_t tag = r.u8();
  switch (tag) {
    case 0x00: return Value::None();
    case 0x01: return Value::Bool(false);
    case 0x02: return Value::Bool(true);
    case 0x03: {
      if (r.pos + 8 > r.len) throw WireError("truncated int64");
      int64_t v;
      std::memcpy(&v, r.p + r.pos, 8);
      r.pos += 8;
      return Value::Int(v);
    }
    case 0x04: {
      if (r.pos + 8 > r.len) throw WireError("truncated float64");
      double v;
      std::memcpy(&v, r.p + r.pos, 8);
      r.pos += 8;
      return Value::Float(v);
    }
    case 0x05:
    case 0x06: {
      uint32_t n = r.u32();
      if (r.pos + n > r.len) throw WireError("truncated string/bytes");
      std::string s(r.p + r.pos, n);
      r.pos += n;
      Value v = tag == 0x05 ? Value::Str(std::move(s))
                            : Value::Bytes(std::move(s));
      return v;
    }
    case 0x07:
    case 0x08: {
      uint32_t n = r.u32();
      if (n > kMaxItems || n > r.len - r.pos)
        throw WireError("collection count exceeds frame");
      Value v = tag == 0x07 ? Value::List() : Value::Tuple();
      v.items->reserve(n);
      for (uint32_t k = 0; k < n; ++k)
        v.items->push_back(decode_one(r, depth + 1));
      return v;
    }
    case 0x09: {
      uint32_t n = r.u32();
      if (n > kMaxItems || n > r.len - r.pos)
        throw WireError("collection count exceeds frame");
      Value v = Value::Dict();
      v.pairs->reserve(n);
      for (uint32_t k = 0; k < n; ++k) {
        Value key = decode_one(r, depth + 1);
        Value val = decode_one(r, depth + 1);
        v.pairs->emplace_back(std::move(key), std::move(val));
      }
      return v;
    }
    case 0x0A: {
      uint8_t sid = r.u8();
      Value body = decode_one(r, depth + 1);
      if (body.kind != Value::DICT)
        throw WireError("struct body must be a dict");
      Value v = Value::Struct(sid);
      v.pairs = body.pairs;
      return v;
    }
    case 0x0B: {
      Value name = decode_one(r, depth + 1);
      Value msg = decode_one(r, depth + 1);
      if (name.kind != Value::STR || msg.kind != Value::STR)
        throw WireError("error frame fields must be strings");
      return Value::Error(std::move(name.s), std::move(msg.s));
    }
    default:
      throw WireError("unknown tag");
  }
}

inline Value decode(const std::string& data) {
  Reader r{data.data(), data.size()};
  Value v = decode_one(r, 0);
  if (r.pos != r.len) throw WireError("trailing bytes after value");
  return v;
}

// Request envelope: (method:str, args:tuple, kwargs:dict)
struct Request {
  std::string method;
  Value args;    // TUPLE
  Value kwargs;  // DICT
};

inline Request decode_request(const std::string& data) {
  Value v = decode(data);
  if (v.kind != Value::TUPLE || !v.items || v.items->size() != 3)
    throw WireError("malformed request envelope");
  Value& m = (*v.items)[0];
  if (m.kind != Value::STR || (*v.items)[1].kind != Value::TUPLE ||
      (*v.items)[2].kind != Value::DICT)
    throw WireError("malformed request envelope");
  return Request{std::move(m.s), std::move((*v.items)[1]),
                 std::move((*v.items)[2])};
}

inline std::string encode_response(bool ok, const Value& payload) {
  Value t = Value::Tuple();
  t.push(Value::Bool(ok));
  t.push(payload);
  return encode(t);
}

}  // namespace wire
