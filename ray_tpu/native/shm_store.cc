// ray_tpu native shared-memory object store ("plasma equivalent").
//
// TPU-native re-design of the reference's plasma store
// (/root/reference/src/ray/object_manager/plasma/store.cc): a per-node daemon
// that owns one large POSIX shared-memory segment, hands out offsets to
// clients (which mmap the same segment for zero-copy reads/writes), tracks
// object lifecycle (CREATED -> SEALED -> released/evicted) and performs LRU
// eviction of unreferenced sealed objects under memory pressure.  Unlike the
// reference we do not use fd-passing + flatbuffers; clients address the
// segment by name (`/dev/shm/<name>`) and the wire protocol is fixed-size
// binary frames over a unix domain socket, which keeps the client mappable
// from Python via mmap + struct with no codegen.
//
// The host segment doubles as the staging tier for TPU HBM transfers: numpy
// views of sealed objects feed jax.device_put without an intermediate copy.
//
// Usage: shm_store <socket_path> <shm_name> <capacity_bytes>
//
// Wire protocol (all little-endian):
//   request:  u8 op | u8[20] object_id | u64 arg0 | u64 arg1
//   response: u8 status | u64 offset | u64 size
// Ops: 1=CREATE(size,timeout) 2=SEAL 3=GET(timeout_ms) 4=RELEASE 5=DELETE
//      6=CONTAINS 7=STATS 8=ABORT 9=PUT(size) 10=GET_INLINE(timeout,cap)
// Status: 0=OK 1=NOT_FOUND 2=EXISTS 3=OOM 4=TIMEOUT 5=NOT_SEALED 6=ERR
//
// PUT: `size` payload bytes follow the request; the daemon writes them
// straight into the fresh extent and seals — create+write+seal in ONE
// round trip (the dominant cost of a small put is the client<->daemon
// context switch on a 1-core host, so halving round trips ~doubles small
// put throughput; the reference's plasma CreateAndSealRequest exists for
// the same reason, plasma/protocol.fbs).
// GET_INLINE: blocks like GET; when the sealed object is <= cap (arg1)
// the response is status=OK, r0=1, r1=size followed by the payload bytes
// (no pin left behind — the daemon pins, copies, releases).  A larger
// object answers status=VIEW with r0=offset, r1=size and the pin KEPT:
// the client maps its zero-copy view immediately (it owes a RELEASE,
// exactly like GET).  Either way a get is ONE round trip.

#include <algorithm>
#include <chrono>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <cstdlib>
#include <string>
#include <deque>
#include <map>
#include <unordered_map>
#include <unordered_set>
#include <atomic>
#include <list>
#include <memory>
#include <mutex>
#include <condition_variable>
#include <random>
#include <thread>
#include <vector>
#include <array>

#include <arpa/inet.h>
#include <cerrno>
#include <fcntl.h>
#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/mman.h>
#include <sys/socket.h>
#include <sys/stat.h>
#include <sys/un.h>
#include <unistd.h>
#include <signal.h>

namespace {

constexpr uint8_t OP_CREATE = 1, OP_SEAL = 2, OP_GET = 3, OP_RELEASE = 4,
                  OP_DELETE = 5, OP_CONTAINS = 6, OP_STATS = 7, OP_ABORT = 8,
                  OP_PUT = 9, OP_GET_INLINE = 10, OP_PULL = 11, OP_PUSH = 12,
                  OP_AUDIT = 13;
// Daemon-to-daemon transfer ops (TCP peer listener).  XFER_PULL_RANGE is
// the striped plane: <u64 offset | u64 length> follows the id and the
// response carries only that byte range (length 0 = size probe, no
// payload) — K such connections in parallel saturate the link where one
// stream is window/cpu-bound (cf. tf.data service's parallel streams).
constexpr uint8_t XFER_PULL = 1, XFER_PUSH = 2, XFER_PULL_RANGE = 3;
constexpr uint8_t ST_OK = 0, ST_NOT_FOUND = 1, ST_EXISTS = 2, ST_OOM = 3,
                  ST_TIMEOUT = 4, ST_NOT_SEALED = 5, ST_ERR = 6,
                  ST_EVICTED = 7, ST_VIEW = 8;

constexpr size_t kIdLen = 20;
constexpr size_t kReqLen = 1 + kIdLen + 8 + 8;
constexpr size_t kRespLen = 1 + 8 + 8;
constexpr uint64_t kAlign = 64;  // cache-line align allocations
// Extent sentinel for husk entries (aborted recreation whose old readers
// are still pinned): never a valid segment offset, and FreeListAllocator
// ignores offsets it does not own.
constexpr uint64_t kInvalidOffset = ~0ull;
// OP_PULL/OP_PUSH addr payload ("host:port") sanity cap: anything longer
// is a corrupt/hostile frame, answered ST_ERR instead of allocated
// (an unbounded client-supplied length here was a one-frame daemon kill:
// std::string(arg0, '\0') -> bad_alloc -> std::terminate in a detached
// thread).
constexpr uint64_t kMaxAddrLen = 512;

using ObjectId = std::array<uint8_t, kIdLen>;

struct IdHash {
  size_t operator()(const ObjectId& id) const {
    // FNV-1a over every byte: ids are an 8-byte process prefix + a
    // monotonic counter (_private/ids.py), so any fixed-window hash
    // collapses one producer's ids into one bucket and turns the table
    // O(n) — the full mix costs ~20 cheap ops and is layout-proof.
    size_t h = 1469598103934665603ull;
    for (unsigned char ch : id) {
      h ^= ch;
      h *= 1099511628211ull;
    }
    return h;
  }
};

bool ReadFull(int fd, void* buf, size_t n);
bool WriteFull(int fd, const void* buf, size_t n);

// Coarse monotonic clock for per-object create/access stamps.  One
// steady_clock read per Create/Get — nanoseconds against a syscall-bearing
// op, so the audit accounting never taxes the zero-copy hot path.
uint64_t NowMs() {
  return static_cast<uint64_t>(
      std::chrono::duration_cast<std::chrono::milliseconds>(
          std::chrono::steady_clock::now().time_since_epoch())
          .count());
}

struct ObjectEntry {
  uint64_t offset = 0;
  uint64_t size = 0;
  bool sealed = false;
  int refcount = 0;  // pinned while > 0 (creator or active getters)
  uint64_t create_ms = 0;       // NowMs() at Create (or restore)
  uint64_t last_access_ms = 0;  // NowMs() at the most recent sealed Get
  // Delete() arrived while pinned: the extent is freed on the LAST
  // Release instead — freeing under an active zero-copy Get view would
  // let the next Create scribble over live reader memory.
  bool delete_pending = false;
  // Extents of prior incarnations deleted-while-pinned and then recreated
  // under the same id.  Their readers' pins are folded into `refcount`, so
  // they are freed when refcount drains to 0 — never while any reader of
  // any incarnation might still hold a zero-copy view.
  std::vector<uint64_t> zombie_extents;
  std::list<ObjectId>::iterator lru_it;
  bool in_lru = false;
};

// First-fit free-list allocator over [0, capacity). Offsets are segment-
// relative; the table lives host-side (not in the segment), so a crashed
// client cannot corrupt allocator metadata.
class FreeListAllocator {
 public:
  explicit FreeListAllocator(uint64_t capacity) : capacity_(capacity) {
    free_[0] = capacity;
  }
  bool Alloc(uint64_t size, uint64_t* out) {
    size = (size + kAlign - 1) / kAlign * kAlign;
    if (size == 0) size = kAlign;
    for (auto it = free_.begin(); it != free_.end(); ++it) {
      if (it->second >= size) {
        *out = it->first;
        uint64_t rem = it->second - size;
        uint64_t new_off = it->first + size;
        free_.erase(it);
        if (rem > 0) free_[new_off] = rem;
        used_ += size;
        sizes_[*out] = size;
        return true;
      }
    }
    return false;
  }
  void Free(uint64_t off) {
    auto sit = sizes_.find(off);
    if (sit == sizes_.end()) return;
    uint64_t size = sit->second;
    sizes_.erase(sit);
    used_ -= size;
    auto it = free_.emplace(off, size).first;
    // merge with next
    auto next = std::next(it);
    if (next != free_.end() && it->first + it->second == next->first) {
      it->second += next->second;
      free_.erase(next);
    }
    // merge with prev
    if (it != free_.begin()) {
      auto prev = std::prev(it);
      if (prev->first + prev->second == it->first) {
        prev->second += it->second;
        free_.erase(it);
      }
    }
  }
  uint64_t used() const { return used_; }
  uint64_t capacity() const { return capacity_; }
  // Fragmentation view for the audit plane: how many free extents the
  // arena has shattered into, and the biggest contiguous allocation that
  // can still succeed (the number that actually gates a large Create).
  uint64_t free_blocks() const { return free_.size(); }
  uint64_t largest_free() const {
    uint64_t best = 0;
    for (const auto& kv : free_)
      if (kv.second > best) best = kv.second;
    return best;
  }

 private:
  uint64_t capacity_;
  uint64_t used_ = 0;
  std::map<uint64_t, uint64_t> free_;           // offset -> size
  std::unordered_map<uint64_t, uint64_t> sizes_;  // offset -> alloc size
};

class Store {
 public:
  // base: the daemon's own mapping of the segment (spill IO); spill_dir:
  // empty string disables spilling (eviction then drops data, pre-spill
  // behavior). Reference: plasma fallback allocation + the raylet's
  // LocalObjectManager::SpillObjects (local_object_manager.h:112) — here
  // spill/restore live inside the store daemon itself, so clients need no
  // protocol change: a Get on a spilled object transparently restores it.
  Store(uint64_t capacity, uint8_t* base, std::string spill_dir)
      : alloc_(capacity), base_(base), spill_dir_(std::move(spill_dir)) {}

  uint64_t Capacity() const { return alloc_.capacity(); }

  uint8_t Create(const ObjectId& id, uint64_t size, uint64_t* offset) {
    std::unique_lock<std::mutex> lk(mu_);
    auto it = objects_.find(id);
    // An entry with delete_pending is logically GONE (Delete tombstoned it;
    // only a reader's pin keeps the extent alive) — recreation (task retry /
    // lineage reconstruction) must succeed, not bounce off ST_EXISTS.
    if (it != objects_.end() && !it->second.delete_pending) return ST_EXISTS;
    evicted_.erase(id);  // recreation (e.g. task retry) clears the tombstone
    DropSpilledLocked(id);  // recreation supersedes a spilled copy
    uint64_t off;
    while (!alloc_.Alloc(size, &off)) {
      if (!EvictOneLocked()) return ST_OOM;
    }
    // NOTE: EvictOneLocked above cannot have erased `it` — delete_pending
    // entries are never in the LRU (Delete removed them).
    if (it != objects_.end()) {
      // Fresh incarnation under the same id: old extent stays zombie-pinned
      // until its readers drain (pins folded into refcount).
      ObjectEntry& e = it->second;
      if (e.offset != kInvalidOffset) e.zombie_extents.push_back(e.offset);
      e.offset = off;
      e.size = size;
      e.sealed = false;
      e.delete_pending = false;
      e.refcount += 1;  // creator pin, on top of surviving old-reader pins
      e.create_ms = NowMs();
      e.last_access_ms = e.create_ms;
      *offset = off;
      return ST_OK;
    }
    ObjectEntry e;
    e.offset = off;
    e.size = size;
    e.refcount = 1;  // creator holds a ref until seal
    e.create_ms = NowMs();
    e.last_access_ms = e.create_ms;
    objects_[id] = e;
    *offset = off;
    return ST_OK;
  }

  uint8_t Seal(const ObjectId& id) {
    std::unique_lock<std::mutex> lk(mu_);
    auto it = objects_.find(id);
    if (it == objects_.end()) return ST_NOT_FOUND;
    it->second.sealed = true;
    DecrefLocked(it->second, id);
    cv_.notify_all();
    return ST_OK;
  }

  uint8_t Get(const ObjectId& id, uint64_t timeout_ms, uint64_t* offset,
              uint64_t* size) {
    std::unique_lock<std::mutex> lk(mu_);
    auto deadline = std::chrono::steady_clock::now() +
                    std::chrono::milliseconds(timeout_ms);
    for (;;) {
      // spilled copy: restore into shm (may spill others to make room)
      if (objects_.find(id) == objects_.end() && spilled_.count(id)) {
        uint8_t rc = RestoreLocked(id);
        if (rc != ST_OK) return rc;
      }
      if (evicted_.count(id)) return ST_EVICTED;
      auto it = objects_.find(id);
      // A deferred Delete keeps the entry until the last Release, but the
      // object is GONE to new observers (mirror Contains): do not depend on
      // the bounded tombstone ring to hide it.
      if (it != objects_.end() && it->second.delete_pending) return ST_EVICTED;
      if (it != objects_.end() && it->second.sealed) {
        it->second.refcount++;
        it->second.last_access_ms = NowMs();
        if (it->second.in_lru) {
          lru_.erase(it->second.lru_it);
          it->second.in_lru = false;
        }
        *offset = it->second.offset;
        *size = it->second.size;
        return ST_OK;
      }
      if (timeout_ms == 0) return it == objects_.end() ? ST_NOT_FOUND
                                                       : ST_NOT_SEALED;
      if (cv_.wait_until(lk, deadline) == std::cv_status::timeout)
        return ST_TIMEOUT;
    }
  }

  uint8_t Release(const ObjectId& id) {
    std::unique_lock<std::mutex> lk(mu_);
    auto it = objects_.find(id);
    if (it == objects_.end()) return ST_NOT_FOUND;
    DecrefLocked(it->second, id);
    return ST_OK;
  }

  uint8_t Delete(const ObjectId& id) {
    std::unique_lock<std::mutex> lk(mu_);
    if (objects_.find(id) == objects_.end() && spilled_.count(id)) {
      // spilled-only copy: tombstone so waiters fail fast, like the
      // resident-delete path below
      DropSpilledLocked(id);
      RecordEvictedLocked(id);
      return ST_OK;
    }
    // (no DropSpilled here: an id is never resident AND spilled at once)
    auto it = objects_.find(id);
    if (it == objects_.end()) return ST_NOT_FOUND;
    if (it->second.refcount > 0) {
      // pinned by an active getter's zero-copy view: defer the free to
      // the last Release (the id is tombstoned NOW so new Gets miss)
      it->second.delete_pending = true;
      if (it->second.in_lru) {
        lru_.erase(it->second.lru_it);
        it->second.in_lru = false;
      }
      RecordEvictedLocked(id);
      return ST_OK;
    }
    if (it->second.in_lru) lru_.erase(it->second.lru_it);
    alloc_.Free(it->second.offset);
    objects_.erase(it);
    RecordEvictedLocked(id);  // waiters fail fast instead of hanging
    return ST_OK;
  }

  // Abort an unsealed create (client died or errored mid-write).
  uint8_t Abort(const ObjectId& id) {
    std::unique_lock<std::mutex> lk(mu_);
    auto it = objects_.find(id);
    if (it == objects_.end()) return ST_NOT_FOUND;
    ObjectEntry& e = it->second;
    if (e.sealed) return ST_ERR;
    if (e.offset != kInvalidOffset) alloc_.Free(e.offset);
    if (e.refcount > 1) {
      // Aborted recreation while old-incarnation readers are still pinned:
      // keep a husk entry to receive their Releases (invisible to
      // Get/Contains via delete_pending); zombies free on the last one.
      e.offset = kInvalidOffset;
      e.size = 0;
      e.delete_pending = true;
      e.refcount--;  // drop the creator pin
      RecordEvictedLocked(id);
      return ST_OK;
    }
    for (uint64_t off : e.zombie_extents) alloc_.Free(off);
    objects_.erase(it);
    return ST_OK;
  }

  uint8_t Contains(const ObjectId& id, uint64_t* sealed, uint64_t* size) {
    std::unique_lock<std::mutex> lk(mu_);
    auto it = objects_.find(id);
    // a deferred Delete (extent pinned by a reader) keeps the entry until
    // the last Release, but the object is GONE to new observers — report
    // what Get would (the evicted tombstone), not "present"
    if (it != objects_.end() && it->second.delete_pending)
      return ST_NOT_FOUND;
    if (it == objects_.end()) {
      auto sp = spilled_.find(id);
      if (sp != spilled_.end()) {  // spilled objects are still "present"
        *sealed = 1;
        *size = sp->second;
        return ST_OK;
      }
      return ST_NOT_FOUND;
    }
    *sealed = it->second.sealed ? 1 : 0;
    *size = it->second.size;
    return ST_OK;
  }

  void Stats(uint64_t* used, uint64_t* num_objects) {
    std::unique_lock<std::mutex> lk(mu_);
    *used = alloc_.used();
    *num_objects = objects_.size();
  }

  // Full-store audit as one JSON document: an occupancy/fragmentation
  // summary plus one row per resident or spilled object (size, seal
  // state, pin count, create age, idle time) and a capped slice of the
  // eviction tombstones.  Built under the store mutex — the audit is a
  // cold diagnostic path; serializing it against mutations keeps every
  // row a consistent point-in-time snapshot.  Rows beyond `max_rows`
  // are counted, not silently dropped.
  std::string AuditJson(uint64_t max_rows, uint64_t max_tombstones) {
    std::unique_lock<std::mutex> lk(mu_);
    uint64_t now = NowMs();
    uint64_t spilled_bytes = 0;
    for (const auto& kv : spilled_) spilled_bytes += kv.second;
    std::string out;
    out.reserve(256 + 160 * std::min<uint64_t>(
                          max_rows, objects_.size() + spilled_.size()));
    char buf[256];
    snprintf(buf, sizeof(buf),
             "{\"summary\":{\"capacity\":%llu,\"used\":%llu,"
             "\"num_objects\":%llu,\"free_blocks\":%llu,"
             "\"largest_free\":%llu,\"evictions\":%llu,\"spills\":%llu,"
             "\"restores\":%llu,\"spilled_objects\":%llu,"
             "\"spilled_bytes\":%llu,\"tombstones\":%llu},",
             (unsigned long long)alloc_.capacity(),
             (unsigned long long)alloc_.used(),
             (unsigned long long)objects_.size(),
             (unsigned long long)alloc_.free_blocks(),
             (unsigned long long)alloc_.largest_free(),
             (unsigned long long)evictions_, (unsigned long long)spills_,
             (unsigned long long)restores_,
             (unsigned long long)spilled_.size(),
             (unsigned long long)spilled_bytes,
             (unsigned long long)evicted_.size());
    out += buf;
    out += "\"objects\":[";
    uint64_t rows = 0, dropped = 0;
    for (const auto& kv : objects_) {
      const ObjectEntry& e = kv.second;
      if (e.delete_pending) continue;  // logically gone, awaiting Release
      if (rows >= max_rows) {
        dropped++;
        continue;
      }
      snprintf(buf, sizeof(buf),
               "%s{\"id\":\"%s\",\"size\":%llu,\"sealed\":%d,"
               "\"refcount\":%d,\"age_ms\":%llu,\"idle_ms\":%llu,"
               "\"spilled\":0}",
               rows ? "," : "", HexId(kv.first).c_str(),
               (unsigned long long)e.size, e.sealed ? 1 : 0, e.refcount,
               (unsigned long long)(now - std::min(e.create_ms, now)),
               (unsigned long long)(now - std::min(e.last_access_ms, now)));
      out += buf;
      rows++;
    }
    for (const auto& kv : spilled_) {
      if (rows >= max_rows) {
        dropped++;
        continue;
      }
      snprintf(buf, sizeof(buf),
               "%s{\"id\":\"%s\",\"size\":%llu,\"sealed\":1,"
               "\"refcount\":0,\"age_ms\":0,\"idle_ms\":0,\"spilled\":1}",
               rows ? "," : "", HexId(kv.first).c_str(),
               (unsigned long long)kv.second);
      out += buf;
      rows++;
    }
    out += "],\"objects_dropped\":";
    out += std::to_string(dropped);
    out += ",\"tombstone_ids\":[";
    uint64_t nt = 0;
    // newest-first: post-restart leak triage cares about the most recent
    // losses, and the ring can hold up to a million ids
    for (auto it = evicted_order_.rbegin();
         it != evicted_order_.rend() && nt < max_tombstones; ++it, ++nt) {
      if (nt) out += ",";
      out += "\"";
      out += HexId(*it);
      out += "\"";
    }
    out += "]}";
    return out;
  }

 private:
  void DecrefLocked(ObjectEntry& e, const ObjectId& id) {
    if (e.refcount > 0) e.refcount--;
    if (e.refcount == 0 && !e.zombie_extents.empty()) {
      // last pin of any incarnation gone: old extents are now unreferenced
      for (uint64_t off : e.zombie_extents) alloc_.Free(off);
      e.zombie_extents.clear();
    }
    if (e.refcount == 0 && e.delete_pending) {
      if (e.offset != kInvalidOffset) alloc_.Free(e.offset);
      objects_.erase(id);  // e is dangling after this — return at once
      return;
    }
    if (e.refcount == 0 && e.sealed && !e.in_lru) {
      lru_.push_back(id);
      e.lru_it = std::prev(lru_.end());
      e.in_lru = true;
    }
  }

  bool EvictOneLocked() {
    if (lru_.empty()) return false;
    ObjectId victim = lru_.front();
    lru_.pop_front();
    auto it = objects_.find(victim);
    if (it != objects_.end()) {
      it->second.in_lru = false;
      if (!spill_dir_.empty() && SpillLocked(victim, it->second)) {
        // data preserved on disk; a later Get restores transparently
        alloc_.Free(it->second.offset);
        objects_.erase(it);
        spills_++;
        return true;
      }
      alloc_.Free(it->second.offset);
      objects_.erase(it);
      RecordEvictedLocked(victim);
      evictions_++;
    }
    return true;
  }

  static std::string HexId(const ObjectId& id) {
    static const char* kHex = "0123456789abcdef";
    std::string out;
    out.reserve(kIdLen * 2);
    for (uint8_t b : id) {
      out.push_back(kHex[b >> 4]);
      out.push_back(kHex[b & 0xf]);
    }
    return out;
  }

  std::string SpillPath(const ObjectId& id) const {
    return spill_dir_ + "/" + HexId(id);
  }

  // Disk IO under the store mutex: eviction is already the slow path, and
  // serializing spills keeps restore/create races trivially correct.
  bool SpillLocked(const ObjectId& id, const ObjectEntry& e) {
    std::string path = SpillPath(id);
    int fd = open(path.c_str(), O_CREAT | O_TRUNC | O_WRONLY, 0600);
    if (fd < 0) return false;
    bool ok = WriteFull(fd, base_ + e.offset, e.size);
    close(fd);
    if (!ok) {
      unlink(path.c_str());
      return false;  // disk full: fall through to lossy eviction
    }
    spilled_[id] = e.size;
    return true;
  }

  uint8_t RestoreLocked(const ObjectId& id) {
    uint64_t size = spilled_[id];
    uint64_t off;
    while (!alloc_.Alloc(size, &off)) {
      if (!EvictOneLocked()) return ST_OOM;
    }
    std::string path = SpillPath(id);
    int fd = open(path.c_str(), O_RDONLY);
    bool ok = fd >= 0 && ReadFull(fd, base_ + off, size);
    if (fd >= 0) close(fd);
    if (!ok) {
      alloc_.Free(off);
      DropSpilledLocked(id);
      RecordEvictedLocked(id);  // spill file lost: surface as evicted
      return ST_EVICTED;
    }
    ObjectEntry e;
    e.offset = off;
    e.size = size;
    e.sealed = true;
    e.refcount = 0;  // Get's fast path takes the caller's ref
    e.create_ms = NowMs();  // restore time: the in-shm age restarts
    e.last_access_ms = e.create_ms;
    objects_[id] = e;
    DropSpilledLocked(id);
    restores_++;
    return ST_OK;
  }

  void DropSpilledLocked(const ObjectId& id) {
    auto it = spilled_.find(id);
    if (it == spilled_.end()) return;
    spilled_.erase(it);
    unlink(SpillPath(id).c_str());
  }

  // Bounded tombstone set so a GET on an evicted object fails fast with
  // ST_EVICTED instead of blocking forever as if the object were pending.
  void RecordEvictedLocked(const ObjectId& id) {
    evicted_.insert(id);
    evicted_order_.push_back(id);
    while (evicted_order_.size() > kMaxTombstones) {
      evicted_.erase(evicted_order_.front());
      evicted_order_.pop_front();
    }
    cv_.notify_all();
  }

  static constexpr size_t kMaxTombstones = 1 << 20;
  // Lifetime pressure counters (monotonic since daemon start; a restart
  // zeroes them, which the incarnation bump already makes visible).
  uint64_t evictions_ = 0;  // lossy evictions (data dropped, tombstoned)
  uint64_t spills_ = 0;     // evictions that preserved data on disk
  uint64_t restores_ = 0;   // spilled objects pulled back into shm
  std::mutex mu_;
  std::condition_variable cv_;
  FreeListAllocator alloc_;
  uint8_t* base_;            // daemon-side mapping (spill/restore IO)
  std::string spill_dir_;    // empty = spilling disabled
  std::unordered_map<ObjectId, ObjectEntry, IdHash> objects_;
  std::unordered_map<ObjectId, uint64_t, IdHash> spilled_;  // id -> size
  std::list<ObjectId> lru_;  // sealed, refcount==0, eviction candidates
  std::unordered_set<ObjectId, IdHash> evicted_;
  std::deque<ObjectId> evicted_order_;
};

bool ReadFull(int fd, void* buf, size_t n) {
  auto* p = static_cast<uint8_t*>(buf);
  while (n > 0) {
    ssize_t r = read(fd, p, n);
    if (r <= 0) return false;
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

bool WriteFull(int fd, const void* buf, size_t n) {
  const auto* p = static_cast<const uint8_t*>(buf);
  while (n > 0) {
    ssize_t r = write(fd, p, n);
    if (r <= 0) return false;
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

// Consume n payload bytes to keep the request stream framed after a
// failed PUT (the client already committed to sending them).
bool DrainBytes(int fd, uint64_t n) {
  char buf[4096];
  while (n > 0) {
    size_t want = n < sizeof buf ? size_t(n) : sizeof buf;
    ssize_t r = read(fd, buf, want);
    if (r <= 0) return false;
    n -= uint64_t(r);
  }
  return true;
}

// ---------------------------------------------------------------------
// Daemon-to-daemon object transfer (TCP peer plane).
//
// TPU-native redesign of the reference object manager
// (/root/reference/src/ray/object_manager/object_manager.h:53,132 —
// chunked gRPC Push/Pull through an ObjectBufferPool): here the two
// store daemons stream the extent DIRECTLY between their shm segments
// over one TCP connection — sender reads from its mapping, receiver
// writes into a freshly created extent — so there is no chunk buffer
// pool because there are no intermediate buffers at all, and no Python
// byte ever touches the data plane.  Policy (location lookup, retry,
// ban, dedup) stays host-side; see _private/object_transfer.py.
//
// Peer wire protocol (connector speaks first):
//   auth:    u8 token_len | token bytes
//   request: u8 xfer_op | u8[20] object_id
//   XFER_PULL: response u8 status | u64 size | payload bytes
//   XFER_PUSH: request continues u64 size; response u8 status; on OK the
//              connector streams the payload, then reads u8 final status.
// ---------------------------------------------------------------------

std::string g_xfer_token;  // RTPU_STORE_TOKEN (empty = no auth)
// flag-registry tunable (RTPU_XFER_TIMEOUT_S, _private/flags.py)
int g_xfer_timeout_s = [] {
  const char* v = getenv("RTPU_XFER_TIMEOUT_S");
  if (!v || !*v) return 30;
  char* end = nullptr;
  long n = strtol(v, &end, 10);
  // garbage/non-positive would mean timeval{0,0} = NO timeout — the
  // opposite of intent; fall back to the default instead
  return (end && *end == '\0' && n > 0) ? int(n) : 30;
}();
// flag-registry tunable (RTPU_TRANSFER_STRIPES): parallel range streams
// per large pull.  Clamped — each stripe is a thread + connection on the
// responder too, so an unbounded value is a self-DoS knob.
int g_xfer_stripes = [] {
  const char* v = getenv("RTPU_TRANSFER_STRIPES");
  if (!v || !*v) return 4;
  char* end = nullptr;
  long n = strtol(v, &end, 10);
  if (!end || *end != '\0' || n < 1) return 4;
  return n > 16 ? 16 : int(n);
}();
// Objects below this pull over the single probe connection; striping's
// extra dials + thread spawns only pay off once per-stream cost matters.
constexpr uint64_t kStripeMin = 1 << 20;

void SetSockTimeouts(int fd) {
  timeval tv{g_xfer_timeout_s, 0};
  setsockopt(fd, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof tv);
  setsockopt(fd, SOL_SOCKET, SO_SNDTIMEO, &tv, sizeof tv);
  int one = 1;
  setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof one);
}

// One peer request per connection (transfers are large; setup cost is
// noise, and per-connection framing keeps failure recovery trivial).
void ServeTransferPeer(Store* store, uint8_t* base, int fd) {
  SetSockTimeouts(fd);
  uint8_t tl = 0;
  if (!ReadFull(fd, &tl, 1)) { close(fd); return; }
  std::string token(tl, '\0');
  if (tl && !ReadFull(fd, token.data(), tl)) { close(fd); return; }
  if (token != g_xfer_token) { close(fd); return; }
  uint8_t hdr[1 + kIdLen];
  if (!ReadFull(fd, hdr, sizeof hdr)) { close(fd); return; }
  ObjectId id;
  memcpy(id.data(), hdr + 1, kIdLen);
  if (hdr[0] == XFER_PULL) {
    uint64_t off = 0, size = 0;
    uint8_t status = store->Get(id, 0, &off, &size);  // non-blocking probe
    uint8_t resp[1 + 8];
    resp[0] = status;
    memcpy(resp + 1, &size, 8);
    if (status != ST_OK) {
      WriteFull(fd, resp, sizeof resp);
      close(fd);
      return;
    }
    // pin held across the stream: the extent cannot be evicted under us
    bool ok = WriteFull(fd, resp, sizeof resp) &&
              WriteFull(fd, base + off, size);
    (void)ok;
    store->Release(id);
  } else if (hdr[0] == XFER_PULL_RANGE) {
    // <u64 offset | u64 length> follows; response echoes the TOTAL size
    // so the puller can cross-check every stripe against the incarnation
    // it probed (a recreate between ranges would otherwise interleave
    // two objects' bytes).  length 0 = probe: header only, no payload.
    uint64_t range[2];
    if (!ReadFull(fd, range, sizeof range)) { close(fd); return; }
    uint64_t off = 0, size = 0;
    uint8_t status = store->Get(id, 0, &off, &size);
    uint8_t resp[1 + 8];
    resp[0] = status;
    memcpy(resp + 1, &size, 8);
    if (status != ST_OK) {
      WriteFull(fd, resp, sizeof resp);
      close(fd);
      return;
    }
    uint64_t roff = range[0];
    uint64_t rlen = roff > size ? 0 : range[1];
    if (rlen > size - roff) rlen = size - roff;
    // pin held across the range stream, like full XFER_PULL
    bool ok = WriteFull(fd, resp, sizeof resp) &&
              (rlen == 0 || WriteFull(fd, base + off + roff, rlen));
    (void)ok;
    store->Release(id);
  } else if (hdr[0] == XFER_PUSH) {
    uint64_t size = 0;
    if (!ReadFull(fd, &size, 8)) { close(fd); return; }
    uint64_t off = 0;
    uint8_t status = store->Create(id, size, &off);
    if (status == ST_EXISTS) {
      // only report "already have it" when the copy is SEALED; an
      // unsealed husk from a dying concurrent transfer is ST_ERR so
      // the sender does not count the push as delivered
      uint64_t sealed = 0, sz = 0;
      if (!(store->Contains(id, &sealed, &sz) == ST_OK && sealed))
        status = ST_ERR;
    }
    uint8_t st_byte = status;
    if (!WriteFull(fd, &st_byte, 1) || status != ST_OK) {
      close(fd);  // EXISTS/OOM: decline — the sender stops, no stream
      return;
    }
    if (!ReadFull(fd, base + off, size)) {
      store->Abort(id);  // half-written push: never leave a husk
      close(fd);
      return;
    }
    store->Seal(id);
    st_byte = ST_OK;
    WriteFull(fd, &st_byte, 1);
  }
  close(fd);
}

int DialPeer(const std::string& host, uint16_t port) {
  addrinfo hints{}, *res = nullptr;
  hints.ai_family = AF_UNSPEC;
  hints.ai_socktype = SOCK_STREAM;
  std::string port_s = std::to_string(port);
  if (getaddrinfo(host.c_str(), port_s.c_str(), &hints, &res) != 0)
    return -1;
  int fd = -1;
  for (addrinfo* ai = res; ai; ai = ai->ai_next) {
    fd = socket(ai->ai_family, ai->ai_socktype, ai->ai_protocol);
    if (fd < 0) continue;
    SetSockTimeouts(fd);
    if (connect(fd, ai->ai_addr, ai->ai_addrlen) == 0) break;
    close(fd);
    fd = -1;
  }
  freeaddrinfo(res);
  return fd;
}

bool SendAuthAndHeader(int fd, uint8_t op, const ObjectId& id) {
  std::string pre;
  pre.push_back(char(uint8_t(g_xfer_token.size())));
  pre += g_xfer_token;
  pre.push_back(char(op));
  pre.append(reinterpret_cast<const char*>(id.data()), kIdLen);
  return WriteFull(fd, pre.data(), pre.size());
}

// One stripe of a striped pull: dial its own connection, request
// [roff, roff+rlen) of id, and land the bytes directly at dst.  The
// responder echoes the object's TOTAL size in every range response; a
// mismatch against the size the probe saw means the object was deleted
// and recreated between stripes, so the stripe must fail rather than
// splice two incarnations' bytes together.
bool PullRange(const std::string& host, uint16_t port, const ObjectId& id,
               uint64_t expect_size, uint64_t roff, uint64_t rlen,
               uint8_t* dst) {
  int fd = DialPeer(host, port);
  if (fd < 0) return false;
  bool ok = false;
  uint64_t range[2] = {roff, rlen};
  uint8_t resp[1 + 8];
  if (SendAuthAndHeader(fd, XFER_PULL_RANGE, id) &&
      WriteFull(fd, range, sizeof range) &&
      ReadFull(fd, resp, sizeof resp)) {
    uint64_t total = 0;
    memcpy(&total, resp + 1, 8);
    if (resp[0] == ST_OK && total == expect_size)
      ok = ReadFull(fd, dst, rlen);
  }
  close(fd);
  return ok;
}

// Local client asked us to PULL id from a peer daemon straight into our
// segment.  Returns (status, size).
//
// The first connection doubles as the size probe: it requests range
// [0, kStripeMin) and the response header carries the total size.  Small
// objects therefore complete on that single connection with the same
// round-trip count as the old whole-object pull; larger ones fan the
// remainder out over g_xfer_stripes parallel range connections, all
// writing into the one pre-created extent, sealed only once every
// stripe lands (any failure aborts — never a half-written husk).
std::pair<uint8_t, uint64_t> PullFromPeer(Store* store, uint8_t* base,
                                          const ObjectId& id,
                                          const std::string& host,
                                          uint16_t port) {
  {
    uint64_t sealed = 0, size = 0;
    if (store->Contains(id, &sealed, &size) == ST_OK && sealed)
      return {ST_OK, size};  // raced: already local
  }
  int fd = DialPeer(host, port);
  if (fd < 0) return {ST_ERR, 0};
  uint64_t first_range[2] = {0, kStripeMin};
  if (!SendAuthAndHeader(fd, XFER_PULL_RANGE, id) ||
      !WriteFull(fd, first_range, sizeof first_range)) {
    close(fd);
    return {ST_ERR, 0};
  }
  uint8_t resp[1 + 8];
  if (!ReadFull(fd, resp, sizeof resp)) { close(fd); return {ST_ERR, 0}; }
  uint64_t size = 0;
  memcpy(&size, resp + 1, 8);
  if (resp[0] != ST_OK) { close(fd); return {resp[0], 0}; }
  uint64_t off = 0;
  uint8_t status = store->Create(id, size, &off);
  if (status == ST_EXISTS) {
    close(fd);  // concurrent pull/compute won; drop the stream —
    // but only claim success if that copy is actually SEALED (a
    // half-written concurrent transfer that later aborts must not
    // let us advertise a location we do not hold)
    uint64_t sealed = 0, sz = 0;
    if (store->Contains(id, &sealed, &sz) == ST_OK && sealed)
      return {ST_OK, sz};
    return {ST_NOT_SEALED, 0};
  }
  if (status != ST_OK) { close(fd); return {status, 0}; }
  uint64_t first_len = size < kStripeMin ? size : kStripeMin;
  if (!ReadFull(fd, base + off, first_len)) {
    store->Abort(id);
    close(fd);
    return {ST_ERR, 0};
  }
  close(fd);
  uint64_t rest = size - first_len;
  if (rest > 0) {
    int nstripes = g_xfer_stripes;
    uint64_t per = (rest + nstripes - 1) / uint64_t(nstripes);
    std::atomic<bool> failed{false};
    std::vector<std::thread> threads;
    for (uint64_t o = first_len; o < size; o += per) {
      uint64_t len = size - o < per ? size - o : per;
      threads.emplace_back([&, o, len] {
        if (!PullRange(host, port, id, size, o, len, base + off + o))
          failed.store(true, std::memory_order_relaxed);
      });
    }
    for (auto& t : threads) t.join();
    if (failed.load(std::memory_order_relaxed)) {
      store->Abort(id);
      return {ST_ERR, 0};
    }
  }
  store->Seal(id);
  return {ST_OK, size};
}

// Local client asked us to PUSH a sealed local object to a peer daemon.
uint8_t PushToPeer(Store* store, uint8_t* base, const ObjectId& id,
                   const std::string& host, uint16_t port) {
  uint64_t off = 0, size = 0;
  uint8_t status = store->Get(id, 0, &off, &size);
  if (status != ST_OK) return status;  // evicted since scheduling the push
  int fd = DialPeer(host, port);
  if (fd < 0) { store->Release(id); return ST_ERR; }
  uint8_t final_st = ST_ERR;
  if (SendAuthAndHeader(fd, XFER_PUSH, id) &&
      WriteFull(fd, &size, 8)) {
    uint8_t st = ST_ERR;
    if (ReadFull(fd, &st, 1)) {
      if (st == ST_OK) {
        if (WriteFull(fd, base + off, size) && ReadFull(fd, &st, 1))
          final_st = st;
      } else if (st == ST_EXISTS) {
        final_st = ST_OK;  // receiver already has it: push satisfied
      }
    }
  }
  close(fd);
  store->Release(id);
  return final_st;
}

void TransferListener(Store* store, uint8_t* base, int srv_fd) {
  for (;;) {
    int fd = accept(srv_fd, nullptr, nullptr);
    if (fd < 0) {
      // persistent failure (EMFILE under transfer fan-in): back off
      // instead of busy-spinning the core the daemon shares with its
      // own client threads
      if (errno != EINTR) usleep(10'000);
      continue;
    }
    // an escaped exception in a detached thread is std::terminate for the
    // whole daemon — contain per-connection failures to their connection
    std::thread([store, base, fd] {
      try {
        ServeTransferPeer(store, base, fd);
      } catch (...) {
        close(fd);
      }
    }).detach();
  }
}

// ---- store chaos (testing) -------------------------------------------------
// RTPU_TESTING_STORE_FAILURE="<drop%>:<kill%>": before serving each client
// request the daemon rolls once; drop% closes the offending connection (the
// client sees a reset mid-op and must reconnect-retry), kill% _exit(1)s the
// whole daemon (the node supervisor must restart it and lineage must rebuild
// the lost contents).  Mirrors the RPC chaos flag in _private/protocol.py.
int g_chaos_drop_pct = 0;
int g_chaos_kill_pct = 0;
std::mutex g_chaos_mu;
std::mt19937 g_chaos_rng;

void InitChaos() {
  const char* spec = getenv("RTPU_TESTING_STORE_FAILURE");
  if (!spec || !*spec) return;
  int drop = 0, kill_pct = 0;
  if (sscanf(spec, "%d:%d", &drop, &kill_pct) < 1) return;
  g_chaos_drop_pct = drop < 0 ? 0 : drop;
  g_chaos_kill_pct = kill_pct < 0 ? 0 : kill_pct;
  unsigned seed = static_cast<unsigned>(getpid());
  if (const char* s = getenv("RTPU_TESTING_STORE_SEED"))
    seed = static_cast<unsigned>(strtoul(s, nullptr, 10));
  g_chaos_rng.seed(seed);
}

// 0 = proceed, 1 = drop this connection (may not return at all: kill).
int ChaosGate() {
  if (g_chaos_drop_pct == 0 && g_chaos_kill_pct == 0) return 0;
  int roll;
  {
    std::lock_guard<std::mutex> lk(g_chaos_mu);
    roll = static_cast<int>(g_chaos_rng() % 100);
  }
  if (roll < g_chaos_kill_pct) {
    fprintf(stderr, "[shm_store] chaos: killing daemon\n");
    _exit(1);
  }
  if (roll < g_chaos_kill_pct + g_chaos_drop_pct) return 1;
  return 0;
}

// Per-client (not per-connection) ref bookkeeping: a client process may pool
// several sockets, so a GET on one connection can be RELEASEd on another.
// Pins are reclaimed when the client's last connection closes.
//
// Sharded locking: each ClientState carries its own mutex for the hot
// per-op bookkeeping (GET/RELEASE/CREATE/SEAL), so N clients' traffic
// never cross-serializes on one global lock.  g_clients_mu guards only
// map membership and the conns count — taken once per connection at
// handshake/teardown, never per op.
struct ClientState {
  std::mutex mu;  // guards held + creating
  int conns = 0;  // guarded by g_clients_mu
  std::unordered_map<ObjectId, int, IdHash> held;
  std::unordered_map<ObjectId, bool, IdHash> creating;  // unsealed creates
};

std::mutex g_clients_mu;
std::unordered_map<ObjectId, std::shared_ptr<ClientState>, IdHash> g_clients;

void ServeClient(Store* store, uint8_t* base, int fd) {
  uint8_t req[kReqLen];
  bool conn_broken = false;
  // Handshake: first 20 bytes are the client id.
  ObjectId client_id;
  if (!ReadFull(fd, client_id.data(), kIdLen)) {
    close(fd);
    return;
  }
  std::shared_ptr<ClientState> cs;
  {
    std::lock_guard<std::mutex> lk(g_clients_mu);
    auto& slot = g_clients[client_id];
    if (!slot) slot = std::make_shared<ClientState>();
    slot->conns++;
    cs = slot;
  }
  while (ReadFull(fd, req, kReqLen)) {
    if (ChaosGate()) break;
    uint8_t op = req[0];
    ObjectId id;
    memcpy(id.data(), req + 1, kIdLen);
    uint64_t arg0, arg1;
    memcpy(&arg0, req + 1 + kIdLen, 8);
    memcpy(&arg1, req + 1 + kIdLen + 8, 8);

    uint8_t status = ST_ERR;
    uint64_t r0 = 0, r1 = 0;
    switch (op) {
      case OP_CREATE:
        if (arg0 > store->Capacity()) {
          status = ST_OOM;  // can never fit: reject without eviction churn
          break;
        }
        status = store->Create(id, arg0, &r0);
        if (status == ST_OK) {
          std::lock_guard<std::mutex> lk(cs->mu);
          cs->creating[id] = true;
        }
        r1 = arg0;
        break;
      case OP_SEAL:
        status = store->Seal(id);
        if (status == ST_OK) {
          std::lock_guard<std::mutex> lk(cs->mu);
          cs->creating.erase(id);
        }
        break;
      case OP_GET:
        status = store->Get(id, arg0, &r0, &r1);
        if (status == ST_OK) {
          std::lock_guard<std::mutex> lk(cs->mu);
          cs->held[id]++;
        }
        break;
      case OP_RELEASE:
        status = store->Release(id);
        if (status == ST_OK) {
          std::lock_guard<std::mutex> lk(cs->mu);
          auto it = cs->held.find(id);
          if (it != cs->held.end() && --it->second <= 0) cs->held.erase(it);
        }
        break;
      case OP_DELETE:
        status = store->Delete(id);
        break;
      case OP_CONTAINS:
        status = store->Contains(id, &r0, &r1);
        break;
      case OP_STATS:
        store->Stats(&r0, &r1);
        status = ST_OK;
        break;
      case OP_ABORT:
        status = store->Abort(id);
        break;
      case OP_PUT: {
        // create + payload copy + seal in one round trip (arg0 = size)
        if (arg0 > store->Capacity()) {
          // can never fit — and draining a hostile multi-GB claimed size
          // would stall this thread; reply and drop the connection (the
          // unread payload poisons the framing)
          uint8_t resp[kRespLen] = {ST_OOM};
          WriteFull(fd, resp, kRespLen);
          conn_broken = true;
          break;
        }
        status = store->Create(id, arg0, &r0);
        if (status == ST_OK) {
          if (!ReadFull(fd, base + r0, arg0)) {
            store->Abort(id);
            conn_broken = true;
            break;
          }
          status = store->Seal(id);
        } else if (!DrainBytes(fd, arg0)) {
          conn_broken = true;
          break;
        }
        r1 = arg0;
        break;
      }
      case OP_PULL:
      case OP_PUSH: {
        // arg0 = addr payload length; payload is "host:port".  The
        // transfer runs in THIS connection's thread — the client checked
        // the conn out of its pool, so control traffic on other conns is
        // never head-of-line-blocked by a large transfer.
        if (arg0 > kMaxAddrLen) {
          // corrupt/hostile length: never allocate it (bad_alloc in a
          // detached thread is std::terminate); answer and drop the conn
          uint8_t resp[kRespLen] = {ST_ERR};
          WriteFull(fd, resp, kRespLen);
          conn_broken = true;
          break;
        }
        std::string addr(arg0, '\0');
        if (!ReadFull(fd, addr.data(), arg0)) {
          conn_broken = true;
          break;
        }
        size_t colon = addr.rfind(':');
        if (colon == std::string::npos) {
          status = ST_ERR;
          break;
        }
        std::string host = addr.substr(0, colon);
        uint16_t port = uint16_t(strtoul(addr.c_str() + colon + 1,
                                         nullptr, 10));
        if (op == OP_PULL) {
          auto [st, sz] = PullFromPeer(store, base, id, host, port);
          status = st;
          r1 = sz;
        } else {
          status = PushToPeer(store, base, id, host, port);
        }
        break;
      }
      case OP_AUDIT: {
        // arg0 = max object rows, arg1 = max tombstone ids.  Response is
        // the 17-byte header (r0 = payload length, r1 = resident object
        // count) followed by the JSON payload — the same variable-length
        // framing as an inline GET, so it rides the existing socket pool.
        uint64_t used = 0, nobj = 0;
        store->Stats(&used, &nobj);
        std::string payload = store->AuditJson(
            std::min<uint64_t>(arg0, 1u << 20),
            std::min<uint64_t>(arg1, 1u << 20));
        r0 = payload.size();
        r1 = nobj;
        uint8_t resp[kRespLen];
        resp[0] = ST_OK;
        memcpy(resp + 1, &r0, 8);
        memcpy(resp + 1 + 8, &r1, 8);
        if (!WriteFull(fd, resp, kRespLen) ||
            !WriteFull(fd, payload.data(), payload.size()))
          conn_broken = true;
        continue;  // response already written
      }
      case OP_GET_INLINE: {
        // arg0 = timeout_ms, arg1 = client's inline size cap
        status = store->Get(id, arg0, &r0, &r1);
        if (status == ST_OK) {
          uint64_t off = r0, sz = r1;
          if (sz <= arg1) {
            r0 = 1;
            uint8_t resp[kRespLen];
            resp[0] = status;
            memcpy(resp + 1, &r0, 8);
            memcpy(resp + 1 + 8, &r1, 8);
            // copy while pinned, then drop the pin — the client gets
            // bytes, not a view, so there is nothing to RELEASE later
            bool ok = WriteFull(fd, resp, kRespLen) &&
                      WriteFull(fd, base + off, sz);
            store->Release(id);
            if (!ok) conn_broken = true;
            continue;  // response already written
          }
          // too big for inline: KEEP the pin and hand back the extent —
          // the client maps its zero-copy view from (offset, size) with
          // no second GET round trip; it owes a RELEASE like plain GET
          status = ST_VIEW;
          {
            std::lock_guard<std::mutex> lk(cs->mu);
            cs->held[id]++;
          }
        }
        break;
      }
      default:
        status = ST_ERR;
    }
    if (conn_broken) break;
    uint8_t resp[kRespLen];
    resp[0] = status;
    memcpy(resp + 1, &r0, 8);
    memcpy(resp + 1 + 8, &r1, 8);
    if (!WriteFull(fd, resp, kRespLen)) break;
  }
  // Connection closed: if this was the client's last connection, release its
  // leaked pins and abort half-written creates.
  bool last_conn = false;
  {
    std::lock_guard<std::mutex> lk(g_clients_mu);
    auto it = g_clients.find(client_id);
    if (it != g_clients.end() && it->second == cs && --cs->conns == 0) {
      g_clients.erase(it);
      last_conn = true;
    }
  }
  if (last_conn) {
    // cs is now unreachable from the map, but a racing op on another
    // (already-drained) connection could still hold cs->mu — swap the
    // books out under it rather than reading them unlocked.
    std::unordered_map<ObjectId, int, IdHash> held;
    std::unordered_map<ObjectId, bool, IdHash> creating;
    {
      std::lock_guard<std::mutex> lk(cs->mu);
      held.swap(cs->held);
      creating.swap(cs->creating);
    }
    for (auto& kv : held)
      for (int i = 0; i < kv.second; i++) store->Release(kv.first);
    for (auto& kv : creating) store->Abort(kv.first);
  }
  close(fd);
}

}  // namespace

int main(int argc, char** argv) {
  if (argc < 4 || argc > 6) {
    fprintf(stderr,
            "usage: %s <socket_path> <shm_name> <capacity_bytes> "
            "[spill_dir] [xfer_host]\n",
            argv[0]);
    return 2;
  }
  signal(SIGPIPE, SIG_IGN);
  InitChaos();
  const char* sock_path = argv[1];
  const char* shm_name = argv[2];
  uint64_t capacity = strtoull(argv[3], nullptr, 10);
  std::string spill_dir = argc >= 5 ? argv[4] : "";
  // Optional TCP transfer listener (daemon-to-daemon data plane): bind
  // an ephemeral port on xfer_host; the chosen port rides the READY
  // line.  Auth token comes via env, never argv (ps-visible).
  std::string xfer_host = argc == 6 ? argv[5] : "";
  if (const char* tok = getenv("RTPU_STORE_TOKEN")) g_xfer_token = tok;

  // Create + size the shared memory segment.
  shm_unlink(shm_name);
  int shm_fd = shm_open(shm_name, O_CREAT | O_RDWR | O_EXCL, 0600);
  if (shm_fd < 0) {
    perror("shm_open");
    return 1;
  }
  if (ftruncate(shm_fd, static_cast<off_t>(capacity)) != 0) {
    perror("ftruncate");
    return 1;
  }
  // The daemon maps the segment too: spilling reads object bytes out and
  // restore writes them back (clients still address by offset).
  void* base = mmap(nullptr, capacity, PROT_READ | PROT_WRITE, MAP_SHARED,
                    shm_fd, 0);
  close(shm_fd);
  if (base == MAP_FAILED) {
    perror("mmap");
    return 1;
  }
  if (!spill_dir.empty()) {
    mkdir(spill_dir.c_str(), 0700);  // EEXIST fine
  }

  Store store(capacity, static_cast<uint8_t*>(base), spill_dir);

  unlink(sock_path);
  int srv = socket(AF_UNIX, SOCK_STREAM, 0);
  sockaddr_un addr{};
  addr.sun_family = AF_UNIX;
  strncpy(addr.sun_path, sock_path, sizeof(addr.sun_path) - 1);
  if (bind(srv, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0) {
    perror("bind");
    return 1;
  }
  listen(srv, 128);

  int xfer_port = 0;
  if (!xfer_host.empty()) {
    int tsrv = socket(AF_INET, SOCK_STREAM, 0);
    int one = 1;
    setsockopt(tsrv, SOL_SOCKET, SO_REUSEADDR, &one, sizeof one);
    sockaddr_in tin{};
    tin.sin_family = AF_INET;
    tin.sin_port = 0;  // ephemeral
    if (inet_pton(AF_INET, xfer_host.c_str(), &tin.sin_addr) != 1)
      tin.sin_addr.s_addr = htonl(INADDR_ANY);
    if (bind(tsrv, reinterpret_cast<sockaddr*>(&tin), sizeof tin) == 0 &&
        listen(tsrv, 64) == 0) {
      sockaddr_in got{};
      socklen_t glen = sizeof got;
      getsockname(tsrv, reinterpret_cast<sockaddr*>(&got), &glen);
      xfer_port = ntohs(got.sin_port);
      std::thread(TransferListener, &store, static_cast<uint8_t*>(base),
                  tsrv)
          .detach();
    } else {
      close(tsrv);
    }
  }

  // Signal readiness (+ transfer port) on stdout for the parent bootstrap.
  printf("READY %d\n", xfer_port);
  fflush(stdout);

  for (;;) {
    int fd = accept(srv, nullptr, nullptr);
    if (fd < 0) {
      if (errno != EINTR) usleep(10'000);  // EMFILE: no busy-spin
      continue;
    }
    std::thread([&store, base, fd] {
      try {
        ServeClient(&store, static_cast<uint8_t*>(base), fd);
      } catch (...) {
        // never let a per-connection failure std::terminate the daemon;
        // the client observes a reset and reconnect-retries
        close(fd);
      }
    }).detach();
  }
  return 0;
}
