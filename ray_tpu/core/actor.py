"""Actor classes and handles: ``@ray_tpu.remote`` on a class.

Counterpart of /root/reference/python/ray/actor.py (ActorClass/ActorHandle):
``ActorClass.remote()`` submits an actor-creation task that dedicates a pooled
worker process to the instance; ``handle.method.remote()`` submits ordered
method-call tasks routed to that worker.  Handles are plain data (actor id)
and can be pickled into tasks; named actors are resolved via the GCS.
"""

from __future__ import annotations

import pickle
from typing import Optional

import cloudpickle

from ray_tpu._private import ids
from ray_tpu._private import ref_tracker
from ray_tpu._private.task_spec import ACTOR_CREATION, ACTOR_METHOD, TaskSpec
from ray_tpu._private.worker import global_worker
from ray_tpu.core.object_ref import ObjectRef
from ray_tpu._private.runtime_env import package as package_runtime_env
from ray_tpu.core.remote_function import resolve_resources, strategy_fields
from ray_tpu.util import tracing


def dumps_args(payload) -> bytes:
    """The argument-serialization policy, shared by the full submit path
    and the worker's actor fastlane: stdlib pickle first (its C
    implementation is ~3x cloudpickle for plain-data args and runs the
    same ObjectRef escape hooks via __reduce__), cloudpickle when pickle
    can't (closures/lambdas) or when the blob references __main__ —
    stdlib pickles driver-script classes BY REFERENCE, which a worker
    process cannot resolve (cloudpickle ships them by value).  The
    b"__main__" scan is conservative: a false positive merely costs the
    cloudpickle path."""
    try:
        blob = pickle.dumps(payload, protocol=5)
        if b"__main__" in blob:
            return cloudpickle.dumps(payload)
        return blob
    except Exception:
        return cloudpickle.dumps(payload)


class ActorMethod:
    def __init__(self, handle: "ActorHandle", method_name: str):
        self._handle = handle
        self._method_name = method_name
        self._fast = None  # worker.actor_fastlane closure, installed lazily

    def remote(self, *args, **kwargs):
        # Hot path: a fused submit over the cached direct channel
        # (worker.actor_fastlane).  A None result means "not eligible
        # right now" (no channel yet, channel dead, scheduler-path calls
        # draining) — drop to the full path, which handles every case,
        # and re-install on the next call in case the worker changed.
        fast = self._fast
        if fast is not None:
            ref = fast(args, kwargs)
            if ref is not None:
                return ref
            self._fast = None
        ref = self._handle._submit_method(
            self._method_name, args, kwargs, num_returns=1)
        if self._fast is None:
            make = getattr(global_worker(), "actor_fastlane", None)
            if make is not None:
                h = self._handle
                self._fast = make(
                    h._actor_id, self._method_name,
                    f"{h._class_name}.{self._method_name}")
        return ref

    def bind(self, *args, **kwargs):
        """Build a lazy DAG node (reference: python/ray/dag class_node)."""
        from ray_tpu.dag.dag_node import ClassMethodNode
        return ClassMethodNode(self._handle, self._method_name, args, kwargs)

    def options(self, num_returns: int = 1,
                tensor_transport: Optional[str] = None, **_ignored):
        handle, name = self._handle, self._method_name
        if tensor_transport not in (None, "device", "object_store"):
            raise ValueError(
                f"tensor_transport must be 'device' or 'object_store', "
                f"got {tensor_transport!r}")

        class _Bound:
            def remote(self, *args, **kwargs):
                return handle._submit_method(
                    name, args, kwargs, num_returns=num_returns,
                    tensor_transport=(tensor_transport
                                      if tensor_transport != "object_store"
                                      else None))

        return _Bound()


class ActorHandle:
    def __init__(self, actor_id: bytes, class_name: str = ""):
        self._actor_id = actor_id
        self._class_name = class_name

    @property
    def actor_id(self) -> bytes:
        return self._actor_id

    def __getattr__(self, item):
        # __rtpu_apply__ is the universal hidden method (reference parity:
        # __ray_call__) — any other underscore name is a real miss.
        if item.startswith("_") and item != "__rtpu_apply__":
            raise AttributeError(item)
        method = ActorMethod(self, item)
        # cache on the instance: later `handle.method` accesses skip
        # __getattr__ entirely (the hot actor-call path pays for this)
        self.__dict__[item] = method
        return method

    def _submit_method(self, method_name, args, kwargs, num_returns=1,
                       tensor_transport=None):
        worker = global_worker()
        task_id = ids.new_task_id()
        return_ids = [ids.object_id_for_return(task_id, i)
                      for i in range(num_returns)]
        args_blob = dumps_args((list(args), dict(kwargs)))
        spec = TaskSpec(
            task_id=task_id,
            kind=ACTOR_METHOD,
            fn_id=b"",
            args_blob=args_blob,
            return_ids=return_ids,
            actor_id=self._actor_id,
            method_name=method_name,
            name=f"{self._class_name}.{method_name}",
            tensor_transport=tensor_transport,
        )
        tracing.attach_trace(spec)
        # Direct push when available (driver/worker contexts); the client
        # proxy context only has the plain submit path.
        submit_method = getattr(worker, "submit_actor_method", None)
        if submit_method is not None:
            submit_method(spec)
        else:
            worker.submit(spec)
        refs = [ObjectRef(oid) for oid in return_ids]
        for oid in return_ids:
            ref_tracker.annotate(oid, kind="actor_return")
        return refs[0] if num_returns == 1 else refs

    def __reduce__(self):
        return (ActorHandle, (self._actor_id, self._class_name))

    def __repr__(self):
        return f"ActorHandle({self._class_name}, {self._actor_id.hex()[:8]})"


class ActorClass:
    def __init__(self, cls, options: Optional[dict] = None):
        self._cls = cls
        self._options = options or {}
        self.__name__ = getattr(cls, "__name__", "Actor")

    def options(self, **actor_options) -> "ActorClass":
        merged = dict(self._options)
        merged.update(actor_options)
        return ActorClass(self._cls, merged)

    def remote(self, *args, **kwargs) -> ActorHandle:
        worker = global_worker()
        opts = self._options
        fn_id = worker.register_function(self._cls)
        actor_id = ids.new_actor_id()
        task_id = ids.new_task_id()
        spec = TaskSpec(
            task_id=task_id,
            kind=ACTOR_CREATION,
            fn_id=fn_id,
            args_blob=cloudpickle.dumps((list(args), dict(kwargs))),
            return_ids=[ids.object_id_for_return(task_id, 0)],
            resources=resolve_resources(opts, default_num_cpus=0),
            actor_id=actor_id,
            name=self.__name__,
            max_restarts=opts.get("max_restarts", 0),
            max_concurrency=opts.get("max_concurrency", 1),
            actor_name=opts.get("name"),
            runtime_env=package_runtime_env(
                opts.get("runtime_env"), worker),
            **strategy_fields(opts),
        )
        tracing.attach_trace(spec)
        worker.submit(spec)
        return ActorHandle(actor_id, self.__name__)

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"Actor class {self.__name__!r} cannot be instantiated directly; "
            f"use {self.__name__}.remote()."
        )
