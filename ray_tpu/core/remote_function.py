"""Remote functions: ``@ray_tpu.remote`` on a function.

Counterpart of /root/reference/python/ray/remote_function.py: holds task
options (resources, num_returns, retries, scheduling strategy), registers the
pickled function in the store-backed function registry once per session, and
builds TaskSpecs for submission.
"""

from __future__ import annotations

from typing import Any, Optional

import cloudpickle

from ray_tpu._private import ids
from ray_tpu._private import ref_tracker
from ray_tpu._private.runtime_env import package as package_runtime_env
from ray_tpu._private.task_spec import TASK, TaskSpec
from ray_tpu._private.worker import global_worker
from ray_tpu.core.object_ref import ObjectRef
from ray_tpu.util import tracing

def resolve_resources(options: dict, default_num_cpus: float = 1) -> dict:
    res = dict(options.get("resources") or {})
    num_cpus = options.get("num_cpus")
    num_tpus = options.get("num_tpus")
    if num_cpus is None:
        # Tasks default to 1 CPU; actors to 0 (they hold resources for their
        # whole lifetime, so a nonzero default would starve the pool —
        # matching the reference's actor defaults).
        num_cpus = default_num_cpus
    if num_cpus:
        res["CPU"] = float(num_cpus)
    if num_tpus:
        res["TPU"] = float(num_tpus)
    if options.get("memory"):
        res["memory"] = float(options["memory"])
    return res


def strategy_fields(options: dict) -> dict:
    """Extract pg routing / node affinity from a scheduling_strategy."""
    strategy = options.get("scheduling_strategy")
    pg = options.get("placement_group")
    bundle = options.get("placement_group_bundle_index")
    if strategy is not None and hasattr(strategy, "placement_group"):
        pg = strategy.placement_group
        bundle = strategy.placement_group_bundle_index
    if pg is not None:
        return {"pg_id": pg.id,
                "pg_bundle": 0 if bundle in (None, -1) else bundle}
    if strategy is not None and hasattr(strategy, "hard"):
        # NodeLabelSchedulingStrategy
        return {"label_selector": dict(strategy.hard) or None,
                "label_selector_soft": dict(strategy.soft) or None}
    if strategy is not None and hasattr(strategy, "node_id"):
        # NodeAffinitySchedulingStrategy: node_id is hex (as returned by
        # ray_tpu.nodes()) or raw bytes
        nid = strategy.node_id
        if isinstance(nid, str):
            nid = bytes.fromhex(nid)
        return {"node_affinity": nid,
                "affinity_soft": bool(getattr(strategy, "soft", False))}
    return {}


class RemoteFunction:
    def __init__(self, function, options: Optional[dict] = None):
        self._function = function
        self._options = options or {}
        self.__name__ = getattr(function, "__name__", "remote_fn")

    def options(self, **task_options) -> "RemoteFunction":
        merged = dict(self._options)
        merged.update(task_options)
        return RemoteFunction(self._function, merged)

    def remote(self, *args, **kwargs):
        return self._remote(args, kwargs, self._options)

    def _remote(self, args, kwargs, options: dict):
        worker = global_worker()
        fn_id = worker.register_function(self._function)
        task_id = ids.new_task_id()
        num_returns = options.get("num_returns", 1)
        return_ids = [ids.object_id_for_return(task_id, i)
                      for i in range(num_returns)]
        collect = getattr(worker, "collect_escaped_refs", None)
        if collect is not None:
            with collect() as deps:
                args_blob = cloudpickle.dumps((list(args), dict(kwargs)))
            dependencies = deps or None
        else:
            args_blob = cloudpickle.dumps((list(args), dict(kwargs)))
            dependencies = None
        spec = TaskSpec(
            task_id=task_id,
            kind=TASK,
            fn_id=fn_id,
            args_blob=args_blob,
            return_ids=return_ids,
            resources=resolve_resources(options),
            name=options.get("name") or self.__name__,
            max_retries=options.get("max_retries", 3),
            runtime_env=package_runtime_env(
                options.get("runtime_env"), worker),
            dependencies=dependencies,
            **strategy_fields(options),
        )
        tracing.attach_trace(spec)
        worker.submit(spec)
        # Owner-side lineage: lost outputs re-execute this spec (client
        # proxy contexts have no lineage store — getattr guard).
        record = getattr(worker, "record_lineage", None)
        if record is not None:
            record(spec)
        refs = [ObjectRef(oid) for oid in return_ids]
        for oid in return_ids:
            ref_tracker.annotate(oid, kind="task_return")
        return refs[0] if num_returns == 1 else refs

    def __call__(self, *args: Any, **kwargs: Any):
        raise TypeError(
            f"Remote function {self.__name__!r} cannot be called directly; "
            f"use {self.__name__}.remote()."
        )
