"""Client for the native shared-memory object store.

Counterpart of the reference's plasma client
(/root/reference/src/ray/object_manager/plasma/client.cc) re-designed for the
TPU build: the client mmaps the store's named POSIX shm segment directly, so
sealed objects are readable zero-copy as memoryviews / numpy arrays that can
feed ``jax.device_put`` without an intermediate host copy.  Control traffic is
a fixed 37-byte request / 17-byte response frame over a unix socket (see
shm_store.cc for the protocol).
"""

from __future__ import annotations

import mmap
import os
import socket
import struct
import subprocess
import threading
import time

from ray_tpu.native.build import binary_path

ID_LEN = 20
_REQ = struct.Struct("<B20sQQ")
_RESP = struct.Struct("<BQQ")

ST_OK = 0
ST_NOT_FOUND = 1
ST_EXISTS = 2
ST_OOM = 3
ST_TIMEOUT = 4
ST_NOT_SEALED = 5
ST_ERR = 6
ST_EVICTED = 7

_OP_CREATE, _OP_SEAL, _OP_GET, _OP_RELEASE = 1, 2, 3, 4
_OP_DELETE, _OP_CONTAINS, _OP_STATS, _OP_ABORT = 5, 6, 7, 8


class StoreFullError(Exception):
    pass


class ObjectNotFoundError(Exception):
    pass


class ObjectEvictedError(Exception):
    pass


class StoreServer:
    """Owns the store daemon process for a node."""

    def __init__(self, socket_path: str, shm_name: str, capacity: int,
                 spill_dir: str = ""):
        self.socket_path = socket_path
        self.shm_name = shm_name
        self.capacity = capacity
        self.spill_dir = spill_dir
        args = [binary_path("shm_store"), socket_path, shm_name,
                str(capacity)]
        if spill_dir:
            args.append(spill_dir)
        self._proc = subprocess.Popen(
            args,
            stdout=subprocess.PIPE,
        )
        line = self._proc.stdout.readline()
        if b"READY" not in line:
            raise RuntimeError(f"shm_store failed to start: {line!r}")

    def shutdown(self):
        if self._proc.poll() is None:
            self._proc.terminate()
            try:
                self._proc.wait(timeout=5)
            except subprocess.TimeoutExpired:
                self._proc.kill()
        try:
            os.unlink(self.socket_path)
        except OSError:
            pass
        shm_file = f"/dev/shm/{self.shm_name.lstrip('/')}"
        try:
            os.unlink(shm_file)
        except OSError:
            pass


class StoreClient:
    """Thread-safe client: a pool of sockets + one shm mapping.

    A pool (rather than one mutex-guarded socket) is required because GET can
    block server-side until an object is sealed; a concurrent PUT from
    another thread of the same client must not queue behind it — that would
    deadlock producer/consumer threads sharing a client.
    """

    def __init__(self, socket_path: str, shm_name: str, capacity: int):
        self._socket_path = socket_path
        self._client_id = os.urandom(ID_LEN)  # server-side ref bookkeeping key
        self._pool_lock = threading.Lock()
        self._pool: list[socket.socket] = [self._dial(timeout=10)]
        shm_file = f"/dev/shm/{shm_name.lstrip('/')}"
        fd = os.open(shm_file, os.O_RDWR)
        try:
            self._mm = mmap.mmap(fd, capacity)
        finally:
            os.close(fd)

    def _dial(self, timeout: float = 2.0) -> socket.socket:
        sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        deadline = time.monotonic() + timeout
        while True:
            try:
                sock.connect(self._socket_path)
                sock.sendall(self._client_id)  # handshake
                return sock
            except OSError:
                if time.monotonic() > deadline:
                    raise
                time.sleep(0.05)

    def _call(self, op: int, oid: bytes, arg0: int = 0, arg1: int = 0):
        req = _REQ.pack(op, oid, arg0, arg1)
        with self._pool_lock:
            sock = self._pool.pop() if self._pool else None
        if sock is None:
            sock = self._dial()
        try:
            sock.sendall(req)
            buf = b""
            while len(buf) < _RESP.size:
                chunk = sock.recv(_RESP.size - len(buf))
                if not chunk:
                    raise ConnectionError("object store connection closed")
                buf += chunk
        except BaseException:
            sock.close()
            raise
        with self._pool_lock:
            if len(self._pool) < 8:
                self._pool.append(sock)
            else:
                sock.close()
        return _RESP.unpack(buf)

    def create(self, oid: bytes, size: int) -> memoryview:
        """Allocate space; returns a writable view. Must seal() after writing."""
        status, offset, _ = self._call(_OP_CREATE, oid, size)
        if status == ST_OOM:
            raise StoreFullError(f"object store full allocating {size} bytes")
        if status == ST_EXISTS:
            raise FileExistsError(f"object {oid.hex()} already exists")
        if status != ST_OK:
            raise RuntimeError(f"create failed: status={status}")
        return memoryview(self._mm)[offset : offset + size]

    def seal(self, oid: bytes):
        status, _, _ = self._call(_OP_SEAL, oid)
        if status != ST_OK:
            raise RuntimeError(f"seal failed: status={status}")

    def put(self, oid: bytes, data) -> None:
        buf = self.create(oid, len(data))
        buf[:] = data
        self.seal(oid)

    def get(self, oid: bytes, timeout_ms: int = 0):
        """Return a zero-copy memoryview of a sealed object, or None.

        With timeout_ms == 0 this is a non-blocking probe; otherwise blocks in
        the store until the object is sealed or the timeout elapses.  The view
        pins the object (refcount) until ``release``.
        """
        status, offset, size = self._call(_OP_GET, oid, timeout_ms)
        if status in (ST_NOT_FOUND, ST_NOT_SEALED, ST_TIMEOUT):
            return None
        if status == ST_EVICTED:
            raise ObjectEvictedError(
                f"object {oid.hex()[:12]} was evicted from the store")
        if status != ST_OK:
            raise RuntimeError(f"get failed: status={status}")
        return memoryview(self._mm)[offset : offset + size]

    def release(self, oid: bytes):
        # Advisory unpin: zero-copy array views release via GC finalizers,
        # which can outlive the store daemon at interpreter exit — a dead
        # socket just means there is nothing left to unpin.
        try:
            self._call(_OP_RELEASE, oid)
        except (OSError, ValueError):
            pass

    def delete(self, oid: bytes):
        self._call(_OP_DELETE, oid)

    def abort(self, oid: bytes):
        self._call(_OP_ABORT, oid)

    def contains(self, oid: bytes) -> bool:
        status, sealed, _ = self._call(_OP_CONTAINS, oid)
        return status == ST_OK and sealed == 1

    def stats(self) -> dict:
        _, used, num_objects = self._call(_OP_STATS, b"\x00" * ID_LEN)
        return {"used_bytes": used, "num_objects": num_objects}

    def close(self):
        with self._pool_lock:
            socks, self._pool = self._pool, []
        for sock in socks:
            try:
                sock.close()
            except OSError:
                pass
