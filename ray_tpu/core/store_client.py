"""Client for the native shared-memory object store.

Counterpart of the reference's plasma client
(/root/reference/src/ray/object_manager/plasma/client.cc) re-designed for the
TPU build: the client mmaps the store's named POSIX shm segment directly, so
sealed objects are readable zero-copy as memoryviews / numpy arrays that can
feed ``jax.device_put`` without an intermediate host copy.  Control traffic is
a fixed 37-byte request / 17-byte response frame over a unix socket (see
shm_store.cc for the protocol).
"""

from __future__ import annotations

import mmap
import os
import socket
import struct
import subprocess
import threading
import time

from ray_tpu.exceptions import StoreDiedError
from ray_tpu.native.build import binary_path

ID_LEN = 20
_REQ = struct.Struct("<B20sQQ")
_RESP = struct.Struct("<BQQ")

ST_OK = 0
ST_NOT_FOUND = 1
ST_EXISTS = 2
ST_OOM = 3
ST_TIMEOUT = 4
ST_NOT_SEALED = 5
ST_ERR = 6
ST_EVICTED = 7
ST_VIEW = 8  # GET_INLINE: too big to inline; pin kept, (offset, size) back

_OP_CREATE, _OP_SEAL, _OP_GET, _OP_RELEASE = 1, 2, 3, 4
_OP_DELETE, _OP_CONTAINS, _OP_STATS, _OP_ABORT = 5, 6, 7, 8
_OP_PUT, _OP_GET_INLINE, _OP_PULL, _OP_PUSH = 9, 10, 11, 12

# Objects at or below this come back as inline bytes from GET_INLINE (one
# round trip, daemon-side copy, no pin/RELEASE); bigger ones come back as
# a pinned zero-copy mmap view in the SAME round trip (ST_VIEW).  The
# copy is cheaper than pin bookkeeping well past this size on a 1-core
# host, but views keep large reads zero-copy for jax.device_put.
# Env-tunable alongside RTPU_INLINE_PUT_MAX so put/get stay symmetric.
INLINE_GET_MAX = int(os.environ.get("RTPU_INLINE_GET_MAX", 64 * 1024))
# per-client daemon connection pool cap
_POOL_MAX = int(os.environ.get("RTPU_STORE_POOL_MAX", 8))
# reconnect budget after a dropped daemon connection: the client redials
# with backoff through a supervised daemon restart (sub-second) and only
# surfaces StoreDiedError past this, so in-flight puts/gets during a
# store crash resolve as retryable task failures, not worker crashes
_RETRY_BUDGET_S = float(os.environ.get("RTPU_STORE_RETRY_S", 15.0))


def _native_core():
    """The _rtpu_core extension (shared gating with the direct-call
    transport: disabled under RTPU_NATIVE_TRANSPORT=0 / RPC chaos so the
    Python fallback path stays exercised), or None."""
    try:
        from ray_tpu._private.direct import native_core

        return native_core()
    except Exception:
        return None


# Data-plane self-instrumentation (util/metrics): put/get/transfer latency
# + bytes, and the reconnect counter pairing PR 1's store-recovery plane.
# Created lazily on first client so importing this module stays side-effect
# free; process-wide singletons so repeated clients don't re-register.
_METRICS = None
_METRICS_LOCK = threading.Lock()


def _metrics():
    global _METRICS
    if _METRICS is None:
        with _METRICS_LOCK:
            if _METRICS is None:
                from ray_tpu.util.metrics import Counter, Histogram

                lat = (0.00005, 0.0002, 0.001, 0.005, 0.02, 0.1, 0.5, 2.0)
                _METRICS = {
                    "put_lat": Histogram(
                        "store_put_latency_s",
                        description="Object-store put latency (client-"
                                    "observed, includes reconnect retries)",
                        boundaries=lat),
                    "get_lat": Histogram(
                        "store_get_latency_s",
                        description="Object-store get latency (client-"
                                    "observed, includes seal waits)",
                        boundaries=lat),
                    "xfer_lat": Histogram(
                        "store_transfer_latency_s",
                        description="Daemon-to-daemon object transfer "
                                    "latency (OP_PULL/OP_PUSH)",
                        boundaries=(0.001, 0.005, 0.02, 0.1, 0.5, 2, 10)),
                    "put_bytes": Counter(
                        "store_put_bytes_total",
                        description="Bytes written to the object store by "
                                    "this process"),
                    "get_bytes": Counter(
                        "store_get_bytes_total",
                        description="Bytes read from the object store by "
                                    "this process"),
                    "xfer_bytes": Counter(
                        "store_transfer_bytes_total",
                        description="Bytes moved between store daemons on "
                                    "behalf of this process",
                        tag_keys=("op",)),
                    "reconnects": Counter(
                        "store_client_reconnects_total",
                        description="Store-client redials after a dropped "
                                    "daemon connection (daemon crash/"
                                    "restart recovery)"),
                }
    return _METRICS


class StoreFullError(Exception):
    pass


class ObjectNotFoundError(Exception):
    pass


class ObjectEvictedError(Exception):
    pass


class StoreServer:
    """Owns the store daemon process for a node.

    The daemon is restartable in place: after a crash ``restart()``
    respawns it on the SAME socket path and shm name with a bumped
    ``incarnation`` (the daemon itself shm_unlinks + recreates the
    segment and rebinds the socket at startup, so the identity is
    stable while the contents start empty — the node supervisor pairs
    this with dropping the node's object-directory entries so lineage
    rebuilds what was lost).
    """

    def __init__(self, socket_path: str, shm_name: str, capacity: int,
                 spill_dir: str = "", xfer_host: str = "",
                 cluster_token: str = ""):
        self.socket_path = socket_path
        self.shm_name = shm_name
        self.capacity = capacity
        self.spill_dir = spill_dir
        self.xfer_host = xfer_host
        self._cluster_token = cluster_token
        # bumped by restart(); lets observers tell apart daemon lifetimes
        self.incarnation = 0
        # daemon-to-daemon transfer listener port (0 = disabled)
        self.xfer_port = 0
        self._spawn()

    def _spawn(self):
        args = [binary_path("shm_store"), self.socket_path, self.shm_name,
                str(self.capacity)]
        if self.spill_dir or self.xfer_host:
            args.append(self.spill_dir)
        if self.xfer_host:
            args.append(self.xfer_host)
        env = dict(os.environ)
        if self._cluster_token:
            env["RTPU_STORE_TOKEN"] = self._cluster_token  # env, never argv
        self._proc = subprocess.Popen(
            args,
            stdout=subprocess.PIPE,
            env=env,
        )
        line = self._proc.stdout.readline()
        if b"READY" not in line:
            raise RuntimeError(f"shm_store failed to start: {line!r}")
        parts = line.split()
        self.xfer_port = 0
        if len(parts) > 1:
            try:
                self.xfer_port = int(parts[1])
            except ValueError:
                pass

    def poll(self):
        """Exit code of the daemon process, or None while it is alive."""
        return self._proc.poll()

    def restart(self) -> bool:
        """Respawn a dead daemon on the same socket/shm name.

        Returns True when a new incarnation was started (False when the
        current process is still alive).  Spill files belong to the dead
        incarnation's in-memory index and are unreadable by the new one,
        so they are swept first.
        """
        if self._proc.poll() is None:
            return False
        if self.spill_dir:
            try:
                for name in os.listdir(self.spill_dir):
                    try:
                        os.unlink(os.path.join(self.spill_dir, name))
                    except OSError:
                        pass
            except OSError:
                pass
        self.incarnation += 1
        self._spawn()
        return True

    def shutdown(self):
        if self._proc.poll() is None:
            self._proc.terminate()
            try:
                self._proc.wait(timeout=5)
            except subprocess.TimeoutExpired:
                self._proc.kill()
        try:
            os.unlink(self.socket_path)
        except OSError:
            pass
        shm_file = f"/dev/shm/{self.shm_name.lstrip('/')}"
        try:
            os.unlink(shm_file)
        except OSError:
            pass


class StoreClient:
    """Thread-safe client: a pool of sockets + one shm mapping.

    A pool (rather than one mutex-guarded socket) is required because GET can
    block server-side until an object is sealed; a concurrent PUT from
    another thread of the same client must not queue behind it — that would
    deadlock producer/consumer threads sharing a client.
    """

    def __init__(self, socket_path: str, shm_name: str, capacity: int):
        self._socket_path = socket_path
        self._shm_name = shm_name
        self._capacity = capacity
        self._client_id = os.urandom(ID_LEN)  # server-side ref bookkeeping key
        self._closed = False
        self._mm = None
        self._mm_key = None  # (st_dev, st_ino) of the mapped segment
        self._pool_lock = threading.Lock()
        # pool entries: (socket, native StoreConn | None).  The native conn
        # runs the per-op pack/send/recv in C with the GIL released
        # (native/core_worker.cc StoreConn); the Python path remains the
        # fallback when the extension is unavailable or chaos-disabled.
        self._pool: list = [self._dial(timeout=10)]
        shm_file = f"/dev/shm/{shm_name.lstrip('/')}"
        fd = os.open(shm_file, os.O_RDWR)
        try:
            st = os.fstat(fd)
            self._mm = mmap.mmap(fd, capacity)
            self._mm_key = (st.st_dev, st.st_ino)
        finally:
            os.close(fd)

    def _dial(self, timeout: float = 2.0):
        sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        deadline = time.monotonic() + timeout
        while True:
            try:
                sock.connect(self._socket_path)
                sock.sendall(self._client_id)  # handshake
                break
            except OSError:
                sock.close()
                if time.monotonic() > deadline:
                    raise
                time.sleep(0.05)
                sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        # a successful connect proves the live daemon's segment exists:
        # refresh the mapping if a restart replaced it underneath us
        self._maybe_remap()
        nc = None
        core = _native_core()
        if core is not None:
            nc = core.StoreConn(sock.fileno())
        return sock, nc

    def _flush_pool(self):
        """Drop every pooled connection (they all point at a daemon that
        just went away; fresh ops redial)."""
        with self._pool_lock:
            entries, self._pool = self._pool, []
        for sock, _ in entries:
            try:
                sock.close()
            except OSError:
                pass

    def _maybe_remap(self):
        """After a daemon restart the shm segment is a NEW inode: remap so
        new views land in the live segment.  Views handed out earlier keep
        the old mapping alive through their buffer references, so replacing
        ``self._mm`` never invalidates them."""
        if self._mm is None:
            return  # still constructing; __init__ maps explicitly
        shm_file = f"/dev/shm/{self._shm_name.lstrip('/')}"
        try:
            st = os.stat(shm_file)
        except OSError:
            return  # segment not recreated yet; the retry loop returns here
        if (st.st_dev, st.st_ino) == self._mm_key:
            return
        try:
            fd = os.open(shm_file, os.O_RDWR)
            try:
                mm = mmap.mmap(fd, self._capacity)
            finally:
                os.close(fd)
        except (OSError, ValueError):
            return  # racing the daemon's ftruncate; retried next attempt
        self._mm, self._mm_key = mm, (st.st_dev, st.st_ino)

    def _with_retry(self, attempt, what: str):
        """Run one store op, transparently redialing through daemon
        restarts.

        ``attempt(first)`` performs the op on a pooled/fresh connection and
        raises ConnectionError/OSError on transport failure (both the
        Python socket path and the native StoreConn do).  On failure every
        pooled connection is flushed and the op retried with backoff until
        the RTPU_STORE_RETRY_S budget, after which StoreDiedError
        surfaces — tasks treat that like a worker crash (retry + lineage)
        rather than a poisoned worker.
        """
        deadline = None
        delay = 0.05
        first = True
        while True:
            try:
                return attempt(first)
            except StoreDiedError:
                raise
            except (ConnectionError, OSError) as e:
                self._flush_pool()
                try:
                    _metrics()["reconnects"].inc()
                except Exception:
                    pass  # metrics must never break recovery (teardown)
                if self._closed:
                    raise
                now = time.monotonic()
                if deadline is None:
                    deadline = now + _RETRY_BUDGET_S
                elif now >= deadline:
                    raise StoreDiedError(
                        f"object store daemon unreachable for {what} "
                        f"after {_RETRY_BUDGET_S:.1f}s retry budget"
                    ) from e
                time.sleep(delay)
                delay = min(delay * 2, 0.5)
                first = False

    @staticmethod
    def _oid20(oid: bytes) -> bytes:
        # struct's "20s" silently truncates/pads; keep that behavior for
        # the native path too
        return oid if len(oid) == ID_LEN else oid[:ID_LEN].ljust(ID_LEN,
                                                                 b"\x00")

    def _checkout(self):
        with self._pool_lock:
            entry = self._pool.pop() if self._pool else None
        return entry if entry is not None else self._dial()

    def _checkin(self, entry):
        with self._pool_lock:
            if len(self._pool) < _POOL_MAX:
                self._pool.append(entry)
                return
        entry[0].close()

    @staticmethod
    def _recv_exact(sock, n: int) -> bytes:
        buf = b""
        while len(buf) < n:
            chunk = sock.recv(n - len(buf))
            if not chunk:
                raise ConnectionError("object store connection closed")
            buf += chunk
        return buf

    def _call_once(self, op: int, oid: bytes, arg0: int = 0, arg1: int = 0):
        entry = self._checkout()
        sock, nc = entry
        try:
            if nc is not None:
                out = nc.call(op, self._oid20(oid), arg0, arg1)
            else:
                sock.sendall(_REQ.pack(op, oid, arg0, arg1))
                out = _RESP.unpack(self._recv_exact(sock, _RESP.size))
        except BaseException:
            sock.close()
            raise
        self._checkin(entry)
        return out

    def _call(self, op: int, oid: bytes, arg0: int = 0, arg1: int = 0):
        return self._with_retry(
            lambda first: self._call_once(op, oid, arg0, arg1),
            f"op{op}")

    def create(self, oid: bytes, size: int) -> memoryview:
        """Allocate space; returns a writable view. Must seal() after writing."""
        def attempt(first):
            status, offset, _ = self._call_once(_OP_CREATE, oid, size)
            if status == ST_EXISTS and not first:
                # A dropped connection after the daemon applied CREATE
                # leaves our own unsealed extent behind; reclaim and
                # re-create.  Abort refuses (ST_ERR) on a genuinely sealed
                # object, so the re-create still reports EXISTS for those.
                self._call_once(_OP_ABORT, oid)
                status, offset, _ = self._call_once(_OP_CREATE, oid, size)
            return status, offset

        status, offset = self._with_retry(attempt, "create")
        if status == ST_OOM:
            raise StoreFullError(f"object store full allocating {size} bytes")
        if status == ST_EXISTS:
            raise FileExistsError(f"object {oid.hex()} already exists")
        if status != ST_OK:
            raise RuntimeError(f"create failed: status={status}")
        return memoryview(self._mm)[offset : offset + size]

    def seal(self, oid: bytes):
        status, _, _ = self._call(_OP_SEAL, oid)
        if status != ST_OK:
            raise RuntimeError(f"seal failed: status={status}")

    def put(self, oid: bytes, data) -> None:
        """Create + write + seal in ONE daemon round trip (OP_PUT): the
        payload rides the request stream and the daemon writes it into
        the fresh extent itself.  Two round trips (create, seal) were 83%
        of a small put's cost — each is a client<->daemon context switch
        on a 1-core host."""
        data = bytes(data) if not isinstance(data, (bytes, bytearray,
                                                    memoryview)) else data

        def attempt(first):
            entry = self._checkout()
            sock, nc = entry
            try:
                if nc is not None:
                    status = nc.put(self._oid20(oid), data)
                else:
                    req = _REQ.pack(_OP_PUT, oid, len(data), 0)
                    if len(data) <= 65536:
                        sock.sendall(req + bytes(data))  # one syscall
                    else:
                        sock.sendall(req)
                        sock.sendall(data)
                    status, _, _ = _RESP.unpack(
                        self._recv_exact(sock, _RESP.size))
            except BaseException:
                sock.close()
                raise
            self._checkin(entry)
            if status == ST_EXISTS and not first:
                # the lost reply's PUT committed before the conn dropped
                status = ST_OK
            return status

        t0 = time.perf_counter()
        status = self._with_retry(attempt, "put")
        if status == ST_OOM:
            raise StoreFullError(
                f"object store full allocating {len(data)} bytes")
        if status == ST_EXISTS:
            raise FileExistsError(f"object {oid.hex()} already exists")
        if status != ST_OK:
            raise RuntimeError(f"put failed: status={status}")
        m = _metrics()
        m["put_lat"].observe(time.perf_counter() - t0)
        m["put_bytes"].inc(len(data))

    def put_parts(self, oid: bytes, parts, total: int) -> None:
        """OP_PUT with a vectored payload: the parts stream straight onto
        the socket (no client-side scratch assembly), and the daemon's
        per-connection thread copies them into the fresh extent OUTSIDE
        the store lock — so concurrent large puts from many clients
        copy-in in parallel, against the daemon's always-warm mapping
        (a fresh client mapping pays a soft page fault per 4KB, which
        dominates large-put cost)."""
        parts = list(parts)  # replayable across reconnect retries

        def attempt(first):
            entry = self._checkout()
            sock, nc = entry
            try:
                # bypass the native conn's single-buffer put: sendall on the
                # same fd keeps framing; the conn is checked out exclusively
                sock.sendall(_REQ.pack(_OP_PUT, oid, total, 0))
                for part in parts:
                    sock.sendall(part)
                status, _, _ = _RESP.unpack(
                    self._recv_exact(sock, _RESP.size))
            except BaseException:
                sock.close()
                raise
            self._checkin(entry)
            if status == ST_EXISTS and not first:
                status = ST_OK  # committed before the conn dropped
            return status

        t0 = time.perf_counter()
        status = self._with_retry(attempt, "put")
        if status == ST_OOM:
            raise StoreFullError(
                f"object store full allocating {total} bytes")
        if status == ST_EXISTS:
            raise FileExistsError(f"object {oid.hex()} already exists")
        if status != ST_OK:
            raise RuntimeError(f"put failed: status={status}")
        m = _metrics()
        m["put_lat"].observe(time.perf_counter() - t0)
        m["put_bytes"].inc(total)

    def _transfer_op(self, op: int, oid: bytes, addr: str):
        """OP_PULL / OP_PUSH: ask the local daemon to move oid between its
        segment and the peer daemon at ``addr`` ("host:port") — the data
        plane never touches this process (see shm_store.cc transfer
        plane).  Returns (status, size)."""
        payload = addr.encode("utf-8")

        def attempt(first):
            entry = self._checkout()
            sock, nc = entry
            try:
                sock.sendall(_REQ.pack(op, oid, len(payload), 0) + payload)
                status, _, size = _RESP.unpack(
                    self._recv_exact(sock, _RESP.size))
            except BaseException:
                sock.close()
                raise
            self._checkin(entry)
            return status, size

        t0 = time.perf_counter()
        status, size = self._with_retry(attempt, "transfer")
        try:
            m = _metrics()
            m["xfer_lat"].observe(time.perf_counter() - t0)
            if status == ST_OK:
                m["xfer_bytes"].inc(size, tags={
                    "op": "pull" if op == _OP_PULL else "push"})
        except Exception:
            pass
        return status, size

    def pull_remote(self, oid: bytes, addr: str) -> bool:
        """Pull oid from the peer store daemon at addr into the local
        store (daemon-to-daemon stream).  True when the object is local
        (pulled now or already present) and sealed."""
        status, _ = self._transfer_op(_OP_PULL, oid, addr)
        return status == ST_OK

    def push_remote(self, oid: bytes, addr: str) -> bool:
        """Push a locally-sealed oid to the peer store daemon at addr.
        True when the peer holds the object afterwards (streamed now, or
        it already had a copy)."""
        status, _ = self._transfer_op(_OP_PUSH, oid, addr)
        return status == ST_OK

    def get_bytes(self, oid: bytes, timeout_ms: int = 0):
        """Like get() but always ONE round trip: small objects come back
        as bytes with NO pin (nothing to release); larger objects answer
        ST_VIEW with the pin kept and (offset, size), mapped here into
        the usual zero-copy view.

        Returns bytes | memoryview | None.  Callers must only release()
        when the result is a memoryview.
        """
        def attempt(first):
            entry = self._checkout()
            sock, nc = entry
            try:
                if nc is not None:
                    status, inline, size, data = nc.get_inline(
                        self._oid20(oid), timeout_ms, INLINE_GET_MAX)
                else:
                    sock.sendall(
                        _REQ.pack(_OP_GET_INLINE, oid, timeout_ms,
                                  INLINE_GET_MAX))
                    status, inline, size = _RESP.unpack(
                        self._recv_exact(sock, _RESP.size))
                    data = (self._recv_exact(sock, size)
                            if status == ST_OK and inline == 1 else None)
            except BaseException:
                sock.close()
                raise
            self._checkin(entry)
            return status, inline, size, data

        t0 = time.perf_counter()
        status, inline, size, data = self._with_retry(attempt, "get")
        if status in (ST_NOT_FOUND, ST_NOT_SEALED, ST_TIMEOUT):
            return None
        if status == ST_EVICTED:
            raise ObjectEvictedError(
                f"object {oid.hex()[:12]} was evicted from the store")
        if status == ST_VIEW:  # pinned view handed back in-round-trip
            m = _metrics()
            m["get_lat"].observe(time.perf_counter() - t0)
            m["get_bytes"].inc(size)
            return memoryview(self._mm)[inline : inline + size]
        if status != ST_OK:
            raise RuntimeError(f"get failed: status={status}")
        if inline:
            m = _metrics()
            m["get_lat"].observe(time.perf_counter() - t0)
            m["get_bytes"].inc(len(data))
            return data
        return self.get(oid, timeout_ms)

    def get(self, oid: bytes, timeout_ms: int = 0):
        """Return a zero-copy memoryview of a sealed object, or None.

        With timeout_ms == 0 this is a non-blocking probe; otherwise blocks in
        the store until the object is sealed or the timeout elapses.  The view
        pins the object (refcount) until ``release``.
        """
        t0 = time.perf_counter()
        status, offset, size = self._call(_OP_GET, oid, timeout_ms)
        if status in (ST_NOT_FOUND, ST_NOT_SEALED, ST_TIMEOUT):
            return None
        if status == ST_EVICTED:
            raise ObjectEvictedError(
                f"object {oid.hex()[:12]} was evicted from the store")
        if status != ST_OK:
            raise RuntimeError(f"get failed: status={status}")
        m = _metrics()
        m["get_lat"].observe(time.perf_counter() - t0)
        m["get_bytes"].inc(size)
        return memoryview(self._mm)[offset : offset + size]

    def release(self, oid: bytes):
        # Advisory unpin: zero-copy array views release via GC finalizers,
        # which can outlive the store daemon at interpreter exit — a dead
        # socket just means there is nothing left to unpin.  Single
        # attempt, no reconnect loop: a finalizer must never stall for the
        # retry budget, and a restarted daemon has no pin to drop anyway.
        try:
            self._call_once(_OP_RELEASE, oid)
        except (OSError, ValueError):
            pass

    def delete(self, oid: bytes):
        self._call(_OP_DELETE, oid)

    def abort(self, oid: bytes):
        self._call(_OP_ABORT, oid)

    def contains(self, oid: bytes) -> bool:
        status, sealed, _ = self._call(_OP_CONTAINS, oid)
        return status == ST_OK and sealed == 1

    def stats(self) -> dict:
        _, used, num_objects = self._call(_OP_STATS, b"\x00" * ID_LEN)
        return {"used_bytes": used, "num_objects": num_objects}

    def close(self):
        self._closed = True  # in-flight retries surface instead of spinning
        self._flush_pool()
