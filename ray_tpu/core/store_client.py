"""Client for the native shared-memory object store.

Counterpart of the reference's plasma client
(/root/reference/src/ray/object_manager/plasma/client.cc) re-designed for the
TPU build: the client mmaps the store's named POSIX shm segment directly, so
sealed objects are readable zero-copy as memoryviews / numpy arrays that can
feed ``jax.device_put`` without an intermediate host copy.  Control traffic is
a fixed 37-byte request / 17-byte response frame over a unix socket (see
shm_store.cc for the protocol).
"""

from __future__ import annotations

import json
import mmap
import os
import socket
import subprocess
import threading
import time

from ray_tpu.exceptions import StoreDiedError
from ray_tpu.native.build import binary_path

# Store protocol constants live in _private/wire_constants (the single
# Python anchor the drift pass compares against shm_store.cc).
from ray_tpu._private.wire_constants import (  # noqa: F401
    ST_ERR,
    ST_EVICTED,
    ST_EXISTS,
    ST_NOT_FOUND,
    ST_NOT_SEALED,
    ST_OK,
    ST_OOM,
    ST_TIMEOUT,
    ST_VIEW,
)
from ray_tpu._private import wire_constants as _wc

ID_LEN = _wc.OBJECT_ID_LEN
_REQ = _wc.STORE_REQ
_RESP = _wc.STORE_RESP

# Readable names for daemon statuses in error messages: ST_ERR and
# friends arrive as raw ints, and "status=6" in a raised error is
# useless at 3am.
_STATUS_NAMES = {
    ST_OK: "ST_OK", ST_NOT_FOUND: "ST_NOT_FOUND", ST_EXISTS: "ST_EXISTS",
    ST_OOM: "ST_OOM", ST_TIMEOUT: "ST_TIMEOUT",
    ST_NOT_SEALED: "ST_NOT_SEALED", ST_ERR: "ST_ERR",
    ST_EVICTED: "ST_EVICTED", ST_VIEW: "ST_VIEW",
}


def _status_name(status: int) -> str:
    return _STATUS_NAMES.get(status, f"status {status}")


_OP_CREATE, _OP_SEAL = _wc.OP_CREATE, _wc.OP_SEAL
_OP_GET, _OP_RELEASE = _wc.OP_GET, _wc.OP_RELEASE
_OP_DELETE, _OP_CONTAINS = _wc.OP_DELETE, _wc.OP_CONTAINS
_OP_STATS, _OP_ABORT = _wc.OP_STATS, _wc.OP_ABORT
_OP_PUT, _OP_GET_INLINE = _wc.OP_PUT, _wc.OP_GET_INLINE
_OP_PULL, _OP_PUSH = _wc.OP_PULL, _wc.OP_PUSH
_OP_AUDIT = _wc.OP_AUDIT

# Objects at or below this come back as inline bytes from GET_INLINE (one
# round trip, daemon-side copy, no pin/RELEASE); bigger ones come back as
# a pinned zero-copy mmap view in the SAME round trip (ST_VIEW).  The
# copy is cheaper than pin bookkeeping well past this size on a 1-core
# host, but views keep large reads zero-copy for jax.device_put.
# Env-tunable alongside RTPU_INLINE_PUT_MAX so put/get stay symmetric.
INLINE_GET_MAX = int(os.environ.get("RTPU_INLINE_GET_MAX", 64 * 1024))
# per-client daemon connection pool cap
_POOL_MAX = int(os.environ.get("RTPU_STORE_POOL_MAX", 8))
# reconnect budget after a dropped daemon connection: the client redials
# with backoff through a supervised daemon restart (sub-second) and only
# surfaces StoreDiedError past this, so in-flight puts/gets during a
# store crash resolve as retryable task failures, not worker crashes
_RETRY_BUDGET_S = float(os.environ.get("RTPU_STORE_RETRY_S", 15.0))
# Puts at or above this bypass OP_PUT's socket stream entirely: create →
# write straight into the client's shm mapping → seal (plasma's data
# plane — zero payload bytes on the control socket, no daemon memcpy, so
# N clients put at memory bandwidth instead of serializing on the
# daemon's read loop).  Below it the one-round-trip streamed OP_PUT
# still wins: two round trips dominate a small put's cost.
ZCOPY_PUT_MIN = int(os.environ.get("RTPU_ZCOPY_PUT_MIN", 256 * 1024))
# How much of the segment to pre-fault at map/remap time.  Faulting the
# whole capacity would materialize every page of a mostly-empty segment,
# so this bounds the cost while covering the allocator's hot prefix —
# the soft-page-fault bill that used to recur per put is paid once here.
_PREFAULT_BYTES = int(os.environ.get("RTPU_PREFAULT_BYTES",
                                     128 * 1024 * 1024))


def _native_core():
    """The _rtpu_core extension (shared gating with the direct-call
    transport: disabled under RTPU_NATIVE_TRANSPORT=0 / RPC chaos so the
    Python fallback path stays exercised), or None."""
    try:
        from ray_tpu._private.direct import native_core

        return native_core()
    except Exception:
        return None


# Data-plane self-instrumentation (util/metrics): put/get/transfer latency
# + bytes, and the reconnect counter pairing PR 1's store-recovery plane.
# Created lazily on first client so importing this module stays side-effect
# free; process-wide singletons so repeated clients don't re-register.
_METRICS = None
_METRICS_LOCK = threading.Lock()


def _metrics():
    global _METRICS
    if _METRICS is None:
        with _METRICS_LOCK:
            if _METRICS is None:
                from ray_tpu.util.metrics import Counter, Histogram

                lat = (0.00005, 0.0002, 0.001, 0.005, 0.02, 0.1, 0.5, 2.0)
                _METRICS = {
                    "put_lat": Histogram(
                        "store_put_latency_s",
                        description="Object-store put latency (client-"
                                    "observed, includes reconnect retries)",
                        boundaries=lat),
                    "get_lat": Histogram(
                        "store_get_latency_s",
                        description="Object-store get latency (client-"
                                    "observed, includes seal waits)",
                        boundaries=lat),
                    "xfer_lat": Histogram(
                        "store_transfer_latency_s",
                        description="Daemon-to-daemon object transfer "
                                    "latency (OP_PULL/OP_PUSH)",
                        boundaries=(0.001, 0.005, 0.02, 0.1, 0.5, 2, 10)),
                    "put_bytes": Counter(
                        "store_put_bytes_total",
                        description="Bytes written to the object store by "
                                    "this process"),
                    "get_bytes": Counter(
                        "store_get_bytes_total",
                        description="Bytes read from the object store by "
                                    "this process"),
                    "xfer_bytes": Counter(
                        "store_transfer_bytes_total",
                        description="Bytes moved between store daemons on "
                                    "behalf of this process",
                        tag_keys=("op",)),
                    "reconnects": Counter(
                        "store_client_reconnects_total",
                        description="Store-client redials after a dropped "
                                    "daemon connection (daemon crash/"
                                    "restart recovery)"),
                    "puts": Counter(
                        "store_puts_total",
                        description="Object-store puts by data path "
                                    "(zcopy = written directly into the "
                                    "client's shm mapping; streamed = "
                                    "payload over the daemon socket)",
                        tag_keys=("path",)),
                    "prefault_s": Histogram(
                        "store_prefault_latency_s",
                        description="Time to pre-fault the client's shm "
                                    "mapping at connect/remap",
                        boundaries=(0.0002, 0.001, 0.005, 0.02, 0.1,
                                    0.5)),
                }
    return _METRICS


class StoreFullError(Exception):
    pass


class ObjectNotFoundError(Exception):
    pass


class ObjectEvictedError(Exception):
    pass


class StoreServer:
    """Owns the store daemon process for a node.

    The daemon is restartable in place: after a crash ``restart()``
    respawns it on the SAME socket path and shm name with a bumped
    ``incarnation`` (the daemon itself shm_unlinks + recreates the
    segment and rebinds the socket at startup, so the identity is
    stable while the contents start empty — the node supervisor pairs
    this with dropping the node's object-directory entries so lineage
    rebuilds what was lost).
    """

    def __init__(self, socket_path: str, shm_name: str, capacity: int,
                 spill_dir: str = "", xfer_host: str = "",
                 cluster_token: str = ""):
        self.socket_path = socket_path
        self.shm_name = shm_name
        self.capacity = capacity
        self.spill_dir = spill_dir
        self.xfer_host = xfer_host
        self._cluster_token = cluster_token
        # bumped by restart(); lets observers tell apart daemon lifetimes
        self.incarnation = 0
        # daemon-to-daemon transfer listener port (0 = disabled)
        self.xfer_port = 0
        self._spawn()

    def _spawn(self):
        args = [binary_path("shm_store"), self.socket_path, self.shm_name,
                str(self.capacity)]
        if self.spill_dir or self.xfer_host:
            args.append(self.spill_dir)
        if self.xfer_host:
            args.append(self.xfer_host)
        env = dict(os.environ)
        if self._cluster_token:
            env["RTPU_STORE_TOKEN"] = self._cluster_token  # env, never argv
        self._proc = subprocess.Popen(
            args,
            stdout=subprocess.PIPE,
            env=env,
        )
        line = self._proc.stdout.readline()
        if b"READY" not in line:
            raise RuntimeError(f"shm_store failed to start: {line!r}")
        parts = line.split()
        self.xfer_port = 0
        if len(parts) > 1:
            try:
                self.xfer_port = int(parts[1])
            except ValueError:
                pass

    def poll(self):
        """Exit code of the daemon process, or None while it is alive."""
        return self._proc.poll()

    def restart(self) -> bool:
        """Respawn a dead daemon on the same socket/shm name.

        Returns True when a new incarnation was started (False when the
        current process is still alive).  Spill files belong to the dead
        incarnation's in-memory index and are unreadable by the new one,
        so they are swept first.
        """
        if self._proc.poll() is None:
            return False
        if self.spill_dir:
            try:
                for name in os.listdir(self.spill_dir):
                    try:
                        os.unlink(os.path.join(self.spill_dir, name))
                    except OSError:
                        pass
            except OSError:
                pass
        self.incarnation += 1
        self._spawn()
        return True

    def shutdown(self):
        if self._proc.poll() is None:
            self._proc.terminate()
            try:
                self._proc.wait(timeout=5)
            except subprocess.TimeoutExpired:
                self._proc.kill()
        try:
            os.unlink(self.socket_path)
        except OSError:
            pass
        shm_file = f"/dev/shm/{self.shm_name.lstrip('/')}"
        try:
            os.unlink(shm_file)
        except OSError:
            pass


class StoreClient:
    """Thread-safe client: a pool of sockets + one shm mapping.

    A pool (rather than one mutex-guarded socket) is required because GET can
    block server-side until an object is sealed; a concurrent PUT from
    another thread of the same client must not queue behind it — that would
    deadlock producer/consumer threads sharing a client.
    """

    def __init__(self, socket_path: str, shm_name: str, capacity: int):
        self._socket_path = socket_path
        self._shm_name = shm_name
        self._capacity = capacity
        self._client_id = os.urandom(ID_LEN)  # server-side ref bookkeeping key
        self._closed = False
        self._mm = None
        self._mm_key = None  # (st_dev, st_ino) of the mapped segment
        self._pool_lock = threading.Lock()
        # pool entries: (socket, native StoreConn | None).  The native conn
        # runs the per-op pack/send/recv in C with the GIL released
        # (native/core_worker.cc StoreConn); the Python path remains the
        # fallback when the extension is unavailable or chaos-disabled.
        self._pool: list = [self._dial(timeout=10)]
        shm_file = f"/dev/shm/{shm_name.lstrip('/')}"
        fd = os.open(shm_file, os.O_RDWR)
        try:
            st = os.fstat(fd)
            self._mm = mmap.mmap(fd, capacity)
            self._mm_key = (st.st_dev, st.st_ino)
        finally:
            os.close(fd)
        self._prefault(self._mm)

    @staticmethod
    def _prefault(mm) -> None:
        """Install PTEs for the mapping's hot prefix once, at map time.

        Without this every zero-copy put/get pays a soft page fault per
        4KB touched (~2560 for a 10MB object) because a fresh mapping
        shares pages with the daemon but not page-table entries.
        MADV_POPULATE_WRITE populates them writable in one syscall
        without altering page contents (safe against concurrent
        writers, unlike touching bytes by hand); kernels without it
        (<5.14) fall back to a read-touch per page, which on tmpfs also
        leaves the PTE usable for the later write."""
        n = min(len(mm), _PREFAULT_BYTES)
        if n <= 0:
            return
        t0 = time.perf_counter()
        populated = False
        adv = getattr(mmap, "MADV_POPULATE_WRITE", None)
        if adv is not None:
            try:
                mm.madvise(adv, 0, n)
                populated = True
            except (OSError, ValueError):
                pass
        if not populated:
            # This interpreter predates the MADV_POPULATE_* constants;
            # issue the same madvise through libc (value 23 is fixed in
            # the uapi headers).  Kernels < 5.14 answer EINVAL and we
            # fall through to the read-touch loop.
            try:
                import ctypes

                buf = (ctypes.c_char * len(mm)).from_buffer(mm)
                try:
                    libc = ctypes.CDLL(None, use_errno=True)
                    ret = libc.madvise(
                        ctypes.c_void_p(ctypes.addressof(buf)),
                        ctypes.c_size_t(n), 23)  # MADV_POPULATE_WRITE
                    populated = ret == 0
                finally:
                    del buf  # exported pointer would block mm.close()
            except Exception:
                pass
        if not populated:
            # Read-touch installs the page mappings (cheap) but the
            # first write per page still pays a dirtying fault.
            try:
                import numpy as np

                np.frombuffer(mm, dtype=np.uint8,
                              count=n)[:: mmap.PAGESIZE].max()
            except Exception:
                mv = memoryview(mm)
                for off in range(0, n, mmap.PAGESIZE):
                    mv[off]
                mv.release()
        try:
            _metrics()["prefault_s"].observe(time.perf_counter() - t0)
        except Exception:
            pass  # metrics must never break connect

    def _dial(self, timeout: float = 2.0):
        sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        deadline = time.monotonic() + timeout
        while True:
            try:
                sock.connect(self._socket_path)
                sock.sendall(self._client_id)  # handshake
                break
            except OSError:
                sock.close()
                if time.monotonic() > deadline:
                    raise
                time.sleep(0.05)
                sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        # a successful connect proves the live daemon's segment exists:
        # refresh the mapping if a restart replaced it underneath us
        self._maybe_remap()
        nc = None
        core = _native_core()
        if core is not None:
            nc = core.StoreConn(sock.fileno())
        return sock, nc

    def _flush_pool(self):
        """Drop every pooled connection (they all point at a daemon that
        just went away; fresh ops redial)."""
        with self._pool_lock:
            entries, self._pool = self._pool, []
        for sock, _ in entries:
            try:
                sock.close()
            except OSError:
                pass

    def _maybe_remap(self):
        """After a daemon restart the shm segment is a NEW inode: remap so
        new views land in the live segment.  Views handed out earlier keep
        the old mapping alive through their buffer references, so replacing
        ``self._mm`` never invalidates them."""
        if self._mm is None:
            return  # still constructing; __init__ maps explicitly
        shm_file = f"/dev/shm/{self._shm_name.lstrip('/')}"
        try:
            st = os.stat(shm_file)
        except OSError:
            return  # segment not recreated yet; the retry loop returns here
        if (st.st_dev, st.st_ino) == self._mm_key:
            return
        try:
            fd = os.open(shm_file, os.O_RDWR)
            try:
                mm = mmap.mmap(fd, self._capacity)
            finally:
                os.close(fd)
        except (OSError, ValueError):
            return  # racing the daemon's ftruncate; retried next attempt
        self._prefault(mm)  # re-fault: the new segment's pages are cold
        self._mm, self._mm_key = mm, (st.st_dev, st.st_ino)

    def _with_retry(self, attempt, what: str):
        """Run one store op, transparently redialing through daemon
        restarts.

        ``attempt(first)`` performs the op on a pooled/fresh connection and
        raises ConnectionError/OSError on transport failure (both the
        Python socket path and the native StoreConn do).  On failure every
        pooled connection is flushed and the op retried with backoff until
        the RTPU_STORE_RETRY_S budget, after which StoreDiedError
        surfaces — tasks treat that like a worker crash (retry + lineage)
        rather than a poisoned worker.
        """
        deadline = None
        delay = 0.05
        first = True
        while True:
            try:
                return attempt(first)
            except StoreDiedError:
                raise
            except (ConnectionError, OSError) as e:
                self._flush_pool()
                try:
                    _metrics()["reconnects"].inc()
                except Exception:
                    pass  # metrics must never break recovery (teardown)
                if os.environ.get("RTPU_TESTING_STORE_FAILURE"):
                    # Chaos attribution for the store lane: the injection
                    # itself lives in the C++ daemon (shm_store.cc), so
                    # the Python-side observer of its effect — a forced
                    # reconnect while the flag is armed — is what puts
                    # the incident on the `rtpu events` timeline.
                    try:
                        from ray_tpu.util import events

                        events.emit(
                            "chaos.store", severity="warning",
                            message=f"store connection lost during {what} "
                                    "with RTPU_TESTING_STORE_FAILURE "
                                    "armed",
                            data={"op": what}, coalesce_s=1.0)
                    except Exception:
                        pass
                if self._closed:
                    raise
                now = time.monotonic()
                if deadline is None:
                    deadline = now + _RETRY_BUDGET_S
                elif now >= deadline:
                    raise StoreDiedError(
                        f"object store daemon unreachable for {what} "
                        f"after {_RETRY_BUDGET_S:.1f}s retry budget"
                    ) from e
                time.sleep(delay)
                delay = min(delay * 2, 0.5)
                first = False

    @staticmethod
    def _oid20(oid: bytes) -> bytes:
        # struct's "20s" silently truncates/pads; keep that behavior for
        # the native path too
        return oid if len(oid) == ID_LEN else oid[:ID_LEN].ljust(ID_LEN,
                                                                 b"\x00")

    def _checkout(self):
        with self._pool_lock:
            entry = self._pool.pop() if self._pool else None
        return entry if entry is not None else self._dial()

    def _checkin(self, entry):
        with self._pool_lock:
            if len(self._pool) < _POOL_MAX:
                self._pool.append(entry)
                return
        entry[0].close()

    @staticmethod
    def _recv_exact(sock, n: int) -> bytes:
        buf = b""
        while len(buf) < n:
            chunk = sock.recv(n - len(buf))
            if not chunk:
                raise ConnectionError("object store connection closed")
            buf += chunk
        return buf

    def _call_once(self, op: int, oid: bytes, arg0: int = 0, arg1: int = 0):
        entry = self._checkout()
        sock, nc = entry
        try:
            if nc is not None:
                out = nc.call(op, self._oid20(oid), arg0, arg1)
            else:
                sock.sendall(_REQ.pack(op, oid, arg0, arg1))
                out = _RESP.unpack(self._recv_exact(sock, _RESP.size))
        except BaseException:
            sock.close()
            raise
        self._checkin(entry)
        return out

    def _call(self, op: int, oid: bytes, arg0: int = 0, arg1: int = 0):
        return self._with_retry(
            lambda first: self._call_once(op, oid, arg0, arg1),
            f"op{op}")

    def create(self, oid: bytes, size: int) -> memoryview:
        """Allocate space; returns a writable view. Must seal() after writing."""
        def attempt(first):
            status, offset, _ = self._call_once(_OP_CREATE, oid, size)
            if status == ST_EXISTS and not first:
                # A dropped connection after the daemon applied CREATE
                # leaves our own unsealed extent behind; reclaim and
                # re-create.  Abort refuses (ST_ERR) on a genuinely sealed
                # object, so the re-create still reports EXISTS for those.
                self._call_once(_OP_ABORT, oid)
                status, offset, _ = self._call_once(_OP_CREATE, oid, size)
            return status, offset

        status, offset = self._with_retry(attempt, "create")
        if status == ST_OOM:
            raise StoreFullError(f"object store full allocating {size} bytes")
        if status == ST_EXISTS:
            raise FileExistsError(f"object {oid.hex()} already exists")
        if status != ST_OK:
            raise RuntimeError(f"create failed: {_status_name(status)}")
        return memoryview(self._mm)[offset : offset + size]

    def seal(self, oid: bytes):
        status, _, _ = self._call(_OP_SEAL, oid)
        if status != ST_OK:
            raise RuntimeError(f"seal failed: {_status_name(status)}")

    @staticmethod
    def _byte_parts(parts) -> list:
        """Normalize payload parts to flat byte views without copying:
        buffer-protocol objects become ``memoryview(...).cast('B')``
        (non-contiguous ones pay the unavoidable flattening copy)."""
        out = []
        for p in parts:
            if not isinstance(p, (bytes, bytearray)):
                try:
                    p = memoryview(p).cast("B")
                except TypeError:
                    p = bytes(p)
            out.append(p)
        return out

    def _put_zcopy(self, oid: bytes, parts: list, total: int) -> int:
        """create → write into the client's own mapping → seal.  No
        payload bytes on the socket; the two control round trips are
        noise at these sizes.  Parts must already be flat byte views
        (``_byte_parts``) so the write loop is pure slice assignment.

        Composes with the restart path: each retry redials (which
        remaps onto a restarted daemon's fresh segment), so the write
        always lands in the mapping the CREATE's offset belongs to.  A
        seal that comes back non-OK after our own successful create
        means the daemon restarted between the two round trips — the
        offset belongs to a dead incarnation — so it is re-raised as a
        transport failure for the retry loop to redo cleanly."""
        def attempt(first):
            status, offset, _ = self._call_once(_OP_CREATE, oid, total)
            if status == ST_EXISTS and not first:
                # A dropped connection after the daemon applied CREATE
                # leaves our own unsealed extent behind; reclaim and
                # re-create.  Abort refuses (ST_ERR) on a genuinely
                # sealed object, so a second EXISTS means the lost
                # reply's put actually committed.
                self._call_once(_OP_ABORT, oid)
                status, offset, _ = self._call_once(_OP_CREATE, oid,
                                                    total)
                if status == ST_EXISTS:
                    return ST_OK
            if status != ST_OK:
                return status
            try:
                dst = memoryview(self._mm)
                pos = offset
                for p in parts:
                    n = len(p)
                    dst[pos : pos + n] = p
                    pos += n
                dst.release()
            except BaseException:
                try:
                    self._call_once(_OP_ABORT, oid)
                except (OSError, ValueError):
                    pass  # never leave a husk behind a failed write
                raise
            status, _, _ = self._call_once(_OP_SEAL, oid)
            if status != ST_OK:
                raise ConnectionError(
                    f"store restarted mid-put (seal {_status_name(status)})")
            return ST_OK

        return self._with_retry(attempt, "put")

    def put(self, oid: bytes, data) -> None:
        """Store ``data`` under ``oid``.

        Large payloads (>= RTPU_ZCOPY_PUT_MIN) take the zero-copy path:
        create + seal control round trips with the bytes written
        directly into the shared mapping.  Small ones use OP_PUT — one
        daemon round trip with the payload on the request stream; two
        round trips (create, seal) were 83% of a small put's cost, each
        being a client<->daemon context switch on a 1-core host.

        Buffer-protocol inputs (arrays, views) are never copied up
        front: they are wrapped as views and sized via nbytes, so the
        old eager ``bytes(data)`` double-buffer is gone."""
        if not isinstance(data, (bytes, bytearray, memoryview)):
            try:
                data = memoryview(data)
            except TypeError:
                data = bytes(data)  # no buffer protocol: must materialize
        if isinstance(data, memoryview) and (data.itemsize != 1
                                             or data.ndim != 1):
            try:
                data = data.cast("B")
            except TypeError:
                data = bytes(data)  # non-contiguous: flattening copy
        size = len(data)
        if size >= ZCOPY_PUT_MIN:
            t0 = time.perf_counter()
            self._finish_put(self._put_zcopy(oid, [data], size), size,
                             "zcopy", t0, oid)
            return

        def attempt(first):
            entry = self._checkout()
            sock, nc = entry
            try:
                if nc is not None:
                    status = nc.put(self._oid20(oid), data)
                else:
                    req = _REQ.pack(_OP_PUT, oid, len(data), 0)
                    if len(data) <= 65536:
                        sock.sendall(req + bytes(data))  # one syscall
                    else:
                        sock.sendall(req)
                        sock.sendall(data)
                    status, _, _ = _RESP.unpack(
                        self._recv_exact(sock, _RESP.size))
            except BaseException:
                sock.close()
                raise
            self._checkin(entry)
            if status == ST_EXISTS and not first:
                # the lost reply's PUT committed before the conn dropped
                status = ST_OK
            return status

        t0 = time.perf_counter()
        status = self._with_retry(attempt, "put")
        self._finish_put(status, size, "streamed", t0, oid)

    def _finish_put(self, status: int, total: int, path: str,
                    t0: float, oid: bytes = b"") -> None:
        """Shared put epilogue: raise on failure statuses, record the
        latency/bytes metrics and the per-data-path put counter."""
        if status == ST_OOM:
            raise StoreFullError(
                f"object store full allocating {total} bytes")
        if status == ST_EXISTS:
            raise FileExistsError(f"object {oid.hex()} already exists")
        if status != ST_OK:
            raise RuntimeError(f"put failed: {_status_name(status)}")
        try:
            m = _metrics()
            m["put_lat"].observe(time.perf_counter() - t0)
            m["put_bytes"].inc(total)
            m["puts"].inc(tags={"path": path})
        except Exception:
            pass  # metrics must never fail a committed put

    def put_parts(self, oid: bytes, parts, total: int) -> None:
        """Vectored put: parts are stored without client-side scratch
        assembly.

        At or above RTPU_ZCOPY_PUT_MIN the parts are written directly
        into the client's shm mapping between create and seal (zero
        payload bytes on the socket).  Below it they stream onto the
        OP_PUT request and the daemon's per-connection thread copies
        them into the fresh extent outside the store lock."""
        parts = self._byte_parts(parts)  # replayable across retries
        if total >= ZCOPY_PUT_MIN:
            t0 = time.perf_counter()
            self._finish_put(self._put_zcopy(oid, parts, total), total,
                             "zcopy", t0, oid)
            return

        def attempt(first):
            entry = self._checkout()
            sock, nc = entry
            try:
                # bypass the native conn's single-buffer put: sendall on the
                # same fd keeps framing; the conn is checked out exclusively
                sock.sendall(_REQ.pack(_OP_PUT, oid, total, 0))
                for part in parts:
                    sock.sendall(part)
                status, _, _ = _RESP.unpack(
                    self._recv_exact(sock, _RESP.size))
            except BaseException:
                sock.close()
                raise
            self._checkin(entry)
            if status == ST_EXISTS and not first:
                status = ST_OK  # committed before the conn dropped
            return status

        t0 = time.perf_counter()
        status = self._with_retry(attempt, "put")
        self._finish_put(status, total, "streamed", t0, oid)

    def _transfer_op(self, op: int, oid: bytes, addr: str):
        """OP_PULL / OP_PUSH: ask the local daemon to move oid between its
        segment and the peer daemon at ``addr`` ("host:port") — the data
        plane never touches this process (see shm_store.cc transfer
        plane).  Returns (status, size)."""
        payload = addr.encode("utf-8")

        def attempt(first):
            entry = self._checkout()
            sock, nc = entry
            try:
                sock.sendall(_REQ.pack(op, oid, len(payload), 0) + payload)
                status, _, size = _RESP.unpack(
                    self._recv_exact(sock, _RESP.size))
            except BaseException:
                sock.close()
                raise
            self._checkin(entry)
            return status, size

        t0 = time.perf_counter()
        status, size = self._with_retry(attempt, "transfer")
        try:
            m = _metrics()
            m["xfer_lat"].observe(time.perf_counter() - t0)
            if status == ST_OK:
                m["xfer_bytes"].inc(size, tags={
                    "op": "pull" if op == _OP_PULL else "push"})
        except Exception:
            pass
        return status, size

    def pull_remote(self, oid: bytes, addr: str) -> bool:
        """Pull oid from the peer store daemon at addr into the local
        store (daemon-to-daemon stream).  True when the object is local
        (pulled now or already present) and sealed."""
        status, _ = self._transfer_op(_OP_PULL, oid, addr)
        return status == ST_OK

    def push_remote(self, oid: bytes, addr: str) -> bool:
        """Push a locally-sealed oid to the peer store daemon at addr.
        True when the peer holds the object afterwards (streamed now, or
        it already had a copy)."""
        status, _ = self._transfer_op(_OP_PUSH, oid, addr)
        return status == ST_OK

    def get_bytes(self, oid: bytes, timeout_ms: int = 0):
        """Like get() but always ONE round trip: small objects come back
        as bytes with NO pin (nothing to release); larger objects answer
        ST_VIEW with the pin kept and (offset, size), mapped here into
        the usual zero-copy view.

        Returns bytes | memoryview | None.  Callers must only release()
        when the result is a memoryview.
        """
        def attempt(first):
            entry = self._checkout()
            sock, nc = entry
            try:
                if nc is not None:
                    status, inline, size, data = nc.get_inline(
                        self._oid20(oid), timeout_ms, INLINE_GET_MAX)
                else:
                    sock.sendall(
                        _REQ.pack(_OP_GET_INLINE, oid, timeout_ms,
                                  INLINE_GET_MAX))
                    status, inline, size = _RESP.unpack(
                        self._recv_exact(sock, _RESP.size))
                    data = (self._recv_exact(sock, size)
                            if status == ST_OK and inline == 1 else None)
            except BaseException:
                sock.close()
                raise
            self._checkin(entry)
            return status, inline, size, data

        t0 = time.perf_counter()
        status, inline, size, data = self._with_retry(attempt, "get")
        if status in (ST_NOT_FOUND, ST_NOT_SEALED, ST_TIMEOUT):
            return None
        if status == ST_EVICTED:
            raise ObjectEvictedError(
                f"object {oid.hex()[:12]} was evicted from the store")
        if status == ST_VIEW:  # pinned view handed back in-round-trip
            m = _metrics()
            m["get_lat"].observe(time.perf_counter() - t0)
            m["get_bytes"].inc(size)
            return memoryview(self._mm)[inline : inline + size]
        if status != ST_OK:
            raise RuntimeError(f"get failed: {_status_name(status)}")
        if inline:
            m = _metrics()
            m["get_lat"].observe(time.perf_counter() - t0)
            m["get_bytes"].inc(len(data))
            return data
        return self.get(oid, timeout_ms)

    def get(self, oid: bytes, timeout_ms: int = 0):
        """Return a zero-copy memoryview of a sealed object, or None.

        With timeout_ms == 0 this is a non-blocking probe; otherwise blocks in
        the store until the object is sealed or the timeout elapses.  The view
        pins the object (refcount) until ``release``.
        """
        t0 = time.perf_counter()
        status, offset, size = self._call(_OP_GET, oid, timeout_ms)
        if status in (ST_NOT_FOUND, ST_NOT_SEALED, ST_TIMEOUT):
            return None
        if status == ST_EVICTED:
            raise ObjectEvictedError(
                f"object {oid.hex()[:12]} was evicted from the store")
        if status != ST_OK:
            raise RuntimeError(f"get failed: {_status_name(status)}")
        m = _metrics()
        m["get_lat"].observe(time.perf_counter() - t0)
        m["get_bytes"].inc(size)
        return memoryview(self._mm)[offset : offset + size]

    def release(self, oid: bytes):
        # Advisory unpin: zero-copy array views release via GC finalizers,
        # which can outlive the store daemon at interpreter exit — a dead
        # socket just means there is nothing left to unpin.  Single
        # attempt, no reconnect loop: a finalizer must never stall for the
        # retry budget, and a restarted daemon has no pin to drop anyway.
        try:
            self._call_once(_OP_RELEASE, oid)
        except (OSError, ValueError):
            pass

    def delete(self, oid: bytes):
        self._call(_OP_DELETE, oid)

    def abort(self, oid: bytes):
        self._call(_OP_ABORT, oid)

    def contains(self, oid: bytes) -> bool:
        status, sealed, _ = self._call(_OP_CONTAINS, oid)
        return status == ST_OK and sealed == 1

    def stats(self) -> dict:
        _, used, num_objects = self._call(_OP_STATS, b"\x00" * ID_LEN)
        return {"used_bytes": used, "num_objects": num_objects}

    def audit(self, max_rows: int = 10000,
              max_tombstones: int = 4096) -> dict:
        """Point-in-time store audit: occupancy/fragmentation summary,
        one row per resident/spilled object (size, seal state, pin count,
        create age, idle time), and the newest eviction tombstones.

        Variable-length response, so it bypasses the native conn's
        fixed-frame ``call`` and speaks the wire protocol directly on the
        checked-out socket (the ``put_parts`` idiom)."""

        def attempt(first):
            entry = self._checkout()
            sock, nc = entry
            try:
                sock.sendall(_REQ.pack(_OP_AUDIT, b"\x00" * ID_LEN,
                                       max_rows, max_tombstones))
                status, length, _ = _RESP.unpack(
                    self._recv_exact(sock, _RESP.size))
                if status != ST_OK:
                    raise RuntimeError(f"audit failed: {_status_name(status)}")
                payload = self._recv_exact(sock, length)
            except BaseException:
                sock.close()
                raise
            self._checkin(entry)
            return payload

        payload = self._with_retry(attempt, "audit")
        doc = json.loads(payload.decode("utf-8"))
        s = doc.get("summary", {})
        cap = s.get("capacity") or 1
        # derived gauges computed client-side so every surface (metrics,
        # dashboard, CLI) agrees on the arithmetic
        s["occupancy"] = s.get("used", 0) / cap
        free = max(cap - s.get("used", 0), 0)
        s["fragmentation"] = (
            1.0 - s.get("largest_free", 0) / free if free else 0.0)
        return doc

    def close(self):
        self._closed = True  # in-flight retries surface instead of spinning
        self._flush_pool()
