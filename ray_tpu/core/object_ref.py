"""ObjectRef: a handle to an object in the distributed store.

Counterpart of the reference's ObjectRef
(/root/reference/python/ray/includes/object_ref.pxi): a 20-byte ID whose
payload lives in the shared-memory store (or will, once its producing task
finishes).  Pickling an ObjectRef transfers the ID only; the receiving process
resolves it against its own store client.
"""

from __future__ import annotations

# Called with the oid whenever a ref is pickled (it may leave this
# process): the worker context promotes memory-store-only values to the
# shm store so any receiver can resolve the ref.  A module-level hook
# (not a WorkerContext import) keeps this file dependency-free.
_escape_hook = None
# Local ref lifecycle (reference: ReferenceCounter local refs,
# /root/reference/src/ray/core_worker/reference_count.h:73): the worker
# context counts live ObjectRef instances per oid so in-process memory
# store entries can be released when the last local ref is dropped.
_on_ref_created = None
_on_ref_deleted = None


def set_escape_hook(hook) -> None:
    global _escape_hook
    _escape_hook = hook


def set_lifecycle_hooks(on_created, on_deleted) -> None:
    global _on_ref_created, _on_ref_deleted
    _on_ref_created = on_created
    _on_ref_deleted = on_deleted


class ObjectRef:
    __slots__ = ("_id",)

    def __init__(self, id_bytes: bytes):
        self._id = id_bytes
        if _on_ref_created is not None:
            _on_ref_created(id_bytes)

    def __del__(self):
        if _on_ref_deleted is not None:
            try:
                _on_ref_deleted(self._id)
            except Exception:
                pass  # interpreter shutdown: hooks may be half-torn-down

    def binary(self) -> bytes:
        return self._id

    def hex(self) -> str:
        return self._id.hex()

    def __reduce__(self):
        if _escape_hook is not None:
            _escape_hook(self._id)
        return (ObjectRef, (self._id,))

    def __hash__(self):
        return hash(self._id)

    def __eq__(self, other):
        return isinstance(other, ObjectRef) and other._id == self._id

    def __repr__(self):
        return f"ObjectRef({self._id.hex()[:16]})"
