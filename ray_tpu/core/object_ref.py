"""ObjectRef: a handle to an object in the distributed store.

Counterpart of the reference's ObjectRef
(/root/reference/python/ray/includes/object_ref.pxi): a 20-byte ID whose
payload lives in the shared-memory store (or will, once its producing task
finishes).  Pickling an ObjectRef transfers the ID only; the receiving process
resolves it against its own store client.
"""

from __future__ import annotations


class ObjectRef:
    __slots__ = ("_id",)

    def __init__(self, id_bytes: bytes):
        self._id = id_bytes

    def binary(self) -> bytes:
        return self._id

    def hex(self) -> str:
        return self._id.hex()

    def __reduce__(self):
        return (ObjectRef, (self._id,))

    def __hash__(self):
        return hash(self._id)

    def __eq__(self, other):
        return isinstance(other, ObjectRef) and other._id == self._id

    def __repr__(self):
        return f"ObjectRef({self._id.hex()[:16]})"
