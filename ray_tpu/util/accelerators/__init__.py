"""TPU topology helpers (``ray_tpu.util.accelerators.tpu``).

Counterpart of /root/reference/python/ray/util/accelerators/tpu.py (pod
helpers :7,:21) and the topology knowledge in
_private/accelerators/tpu.py:15-61 — written fresh from TPU generation
facts: chips per host and slice-shape math feed the scheduler's
ICI-aware gang placement (SURVEY §7).
"""

from ray_tpu.util.accelerators import tpu

__all__ = ["tpu"]
