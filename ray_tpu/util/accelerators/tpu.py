"""TPU generation/topology facts + pod environment helpers.

The scheduler treats a slice as an atomic, SHAPED gang (SURVEY §7 "hard
parts": 2x2x1 vs 4x2 are different machines even at equal chip counts);
these helpers centralize the generation facts that scheduling, the
autoscaler's node-type shapes, and mesh construction all need.

Reference parity: ray.util.accelerators.tpu pod helpers
(/root/reference/python/ray/util/accelerators/tpu.py) and the env-var
conventions of _private/accelerators/tpu.py.
"""

from __future__ import annotations

import os
from typing import Optional

# chips per host by generation: v2-v4 + v5p host 4 chips; v5e + v6e host 8
CHIPS_PER_HOST = {
    "v2": 4, "v3": 4, "v4": 4, "v5p": 4,
    "v5litepod": 8, "v5e": 8, "v6e": 8,
}
# tensorcores per chip: v5e/v6e are single-core; older gens dual-core
CORES_PER_CHIP = {
    "v2": 2, "v3": 2, "v4": 2, "v5p": 2,
    "v5litepod": 1, "v5e": 1, "v6e": 1,
}
VALID_TPU_TYPES = tuple(CHIPS_PER_HOST)

# environment set by the TPU runtime / GKE on pod workers
TPU_NAME_ENV = "TPU_NAME"
TPU_WORKER_ID_ENV = "TPU_WORKER_ID"
TPU_ACCELERATOR_TYPE_ENV = "TPU_ACCELERATOR_TYPE"
TPU_WORKER_HOSTNAMES_ENV = "TPU_WORKER_HOSTNAMES"
TPU_VISIBLE_CHIPS_ENV = "TPU_VISIBLE_CHIPS"


def parse_accelerator_type(accelerator_type: str) -> tuple[str, int]:
    """"v5litepod-16" -> ("v5litepod", 16). The count is in GCP's naming
    unit: TENSORCORES for dual-core generations (v2-v4, v5p) and CHIPS for
    single-core ones (v5e/v6e) — use chips_in_slice() for chip math."""
    gen, _, count = accelerator_type.partition("-")
    if gen not in CHIPS_PER_HOST or not count.isdigit():
        raise ValueError(
            f"invalid TPU accelerator type {accelerator_type!r}; expected "
            f"<generation>-<count> with generation in {VALID_TPU_TYPES}")
    return gen, int(count)


def chips_in_slice(accelerator_type: str) -> int:
    """Physical chips in a slice: "v4-16" = 16 cores = 8 chips;
    "v5litepod-16" = 16 chips."""
    gen, count = parse_accelerator_type(accelerator_type)
    return max(1, count // CORES_PER_CHIP[gen])


def num_chips_per_host(generation_or_type: str) -> int:
    gen = generation_or_type.partition("-")[0]
    try:
        return CHIPS_PER_HOST[gen]
    except KeyError:
        raise ValueError(f"unknown TPU generation {gen!r}") from None


def num_hosts_in_slice(accelerator_type: str) -> int:
    """Hosts a slice spans ("v5litepod-16" -> 2 hosts of 8 chips;
    "v4-16" -> 8 chips -> 2 hosts)."""
    gen, _ = parse_accelerator_type(accelerator_type)
    chips = chips_in_slice(accelerator_type)
    return max(1, -(-chips // CHIPS_PER_HOST[gen]))


def get_current_pod_name() -> Optional[str]:
    """The TPU pod/slice this process runs in (None off-TPU).

    Reference: ray.util.accelerators.tpu.get_current_pod_name.
    """
    return os.environ.get(TPU_NAME_ENV) or None


def get_current_pod_worker_count() -> Optional[int]:
    """Number of hosts in the current slice (None off-TPU)."""
    hostnames = os.environ.get(TPU_WORKER_HOSTNAMES_ENV)
    if hostnames:
        return len(hostnames.split(","))
    atype = os.environ.get(TPU_ACCELERATOR_TYPE_ENV)
    if atype:
        try:
            return num_hosts_in_slice(atype)
        except ValueError:
            return None
    return None


def get_num_tpu_chips_on_node() -> int:
    """Chips visible to this host (0 off-TPU)."""
    from ray_tpu._private.node import detect_num_tpu_chips

    return detect_num_tpu_chips()


def pod_head_resource(accelerator_type: str) -> str:
    """The marker resource name gang-scheduling uses to place one task per
    slice (reference: TPU-{version}-head, _private/accelerators/tpu.py:353).
    """
    gen, _ = parse_accelerator_type(accelerator_type)
    return f"TPU-{gen}-head"
