"""Goodput & step-anatomy telemetry: where training wall time actually goes.

Training throughput has been flat for rounds (ROADMAP item 4) while the
runtime instrumented only its control planes — traces, profiles, scheduler
metrics — and stayed blind inside the train step.  This module is the
missing layer: a per-step anatomy timer that splits every step into
data-wait / host-to-device / compute (block-until-ready bracket) /
checkpoint, tracks compile time and restarts separately, and attributes the
run's whole wall clock to goodput vs badput buckets that sum to elapsed
time by construction (idle is the remainder):

    goodput    — compute seconds inside steps (the block-until-ready span)
    compile    — jit/AOT compilation brackets
    data_stall — data-wait + host-to-device inside steps
    checkpoint — checkpoint save brackets inside steps
    recovery   — restart/recovery brackets (elastic re-gang, restore)
    idle       — everything unaccounted (framework overhead, between-step
                 host work, controller polling)

The tf.data service paper (PAPERS.md 2210.14826) is the motivation for the
data_stall split: input-wait routinely dominates step time and must be
measured per-step to be attacked.

Usage (see train/llama3.py for the production hook):

    gp = GoodputTracker(run="llama3-8b", tokens_per_step=B * S)
    with gp.compile_bracket():
        compiled = step.lower(state, batch).compile()
    gp.set_flops_per_step(*step_flops(compiled, n_params=n, tokens=B * S))
    for i in range(steps):
        with gp.step() as st:
            with st.phase("data"):
                batch_np = next(it)
            with st.phase("h2d"):
                batch = jax.device_put(batch_np)
            with st.phase("compute"):
                state, metrics = compiled(state, batch)
                jax.block_until_ready(metrics)
            if want_ckpt:
                with st.phase("checkpoint"):
                    save(state)
    report = gp.report()   # buckets sum to elapsed_s; MFU, steady tok/s
    gp.close()             # final goodput_push to the node scheduler

Records ride the existing push plane (``goodput_push`` — the same lane as
``spans_push``/``profiles_push``), are banked per node scheduler (bounded
by ``RTPU_GOODPUT_CAP``), and surface through ``state.get_goodput``, the
dashboard's ``/api/goodput``, and ``rtpu goodput``.

MFU accounting matches MFU_PROFILE.md / bench.py: counted FLOPs per step
come from the compiled program's ``cost_analysis()`` when available, else
the analytic dense-LM ``6 * n_params * tokens`` (attention inner products
and non-matmul work are NOT counted as useful flops), divided by
``RTPU_GOODPUT_PEAK_TFLOPS`` (default 197, the v5e bf16 peak — the same
denominator as bench.py's ``mfu_vs_v5e_peak``).
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from contextlib import contextmanager
from typing import Dict, List, Optional, Tuple

PHASES = ("data", "h2d", "compute", "checkpoint")
BUCKETS = ("goodput", "compile", "data_stall", "checkpoint", "recovery",
           "idle")

# ---------------------------------------------------------------------------
# process-global metric instruments (created once; every tracker shares them,
# distinguished by the "run" tag)

_metrics_lock = threading.Lock()
_METRICS: Optional[dict] = None

_STEP_BOUNDARIES = (0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0,
                    2.5, 5.0, 15.0, 60.0)


def _instruments() -> dict:
    global _METRICS
    with _metrics_lock:
        if _METRICS is None:
            from ray_tpu.util.metrics import Counter, Gauge, Histogram

            _METRICS = {
                "step": Histogram(
                    "train_step_s", "Wall time per training step",
                    boundaries=_STEP_BOUNDARIES, tag_keys=("run",)),
                "phase": Histogram(
                    "train_step_phase_s",
                    "Per-step anatomy: data / h2d / compute / checkpoint",
                    boundaries=_STEP_BOUNDARIES, tag_keys=("run", "phase")),
                "goodput_frac": Gauge(
                    "train_goodput_fraction",
                    "Fraction of run wall time spent in step compute",
                    tag_keys=("run",)),
                "badput": Gauge(
                    "train_badput_s",
                    "Cumulative badput seconds per bucket "
                    "(compile/data_stall/checkpoint/recovery/idle)",
                    tag_keys=("run", "bucket")),
                "mfu": Gauge(
                    "train_mfu",
                    "Model flops utilization vs RTPU_GOODPUT_PEAK_TFLOPS "
                    "(counted flops per MFU_PROFILE.md: 6*N*tokens or "
                    "compiled cost_analysis)", tag_keys=("run",)),
                "tflops": Gauge(
                    "train_model_tflops_per_s",
                    "Counted model TFLOP/s over steady-state steps",
                    tag_keys=("run",)),
                "tok_s": Gauge(
                    "train_tokens_per_sec",
                    "Steady-state (post-warmup) training throughput",
                    tag_keys=("run",)),
                "compile_s": Gauge(
                    "train_compile_s", "Cumulative compile seconds",
                    tag_keys=("run",)),
                "restarts": Counter(
                    "train_restarts_total",
                    "Training restarts/recoveries", tag_keys=("run",)),
            }
        return _METRICS


# ---------------------------------------------------------------------------
# FLOPs accounting

def analytic_step_flops(n_params: int, tokens: int) -> float:
    """Dense-LM counted flops for one step: 6*N*tokens (fwd 2N + bwd 4N per
    token; attention inner products excluded — MFU_PROFILE.md's rule)."""
    return 6.0 * float(n_params) * float(tokens)


def compiled_flops(compiled) -> Optional[float]:
    """Counted flops from a compiled executable's cost analysis, or None.

    Accepts anything with ``cost_analysis()`` (jax ``Compiled`` objects);
    tolerates the list-of-dicts shape older jax returns.
    """
    try:
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        flops = float(ca.get("flops", 0.0) or 0.0)
        return flops if flops > 0 else None
    except Exception:
        return None


def step_flops(compiled, n_params: int = 0,
               tokens: int = 0) -> Tuple[float, str]:
    """(flops_per_step, source): compiled ``cost_analysis()`` when it
    reports a usable number, else the analytic 6*N*tokens fallback."""
    flops = compiled_flops(compiled) if compiled is not None else None
    if flops is not None:
        return flops, "cost_analysis"
    return analytic_step_flops(n_params, tokens), "analytic"


def _peak_tflops() -> float:
    from ray_tpu._private import flags

    return float(flags.get("RTPU_GOODPUT_PEAK_TFLOPS"))


# ---------------------------------------------------------------------------
# the tracker

class _StepTimer:
    """Phase brackets for ONE step; handed out by GoodputTracker.step()."""

    def __init__(self):
        self.phases: Dict[str, float] = {}
        self.t0 = time.perf_counter()
        self.wall = 0.0

    @contextmanager
    def phase(self, name: str):
        if name not in PHASES:
            raise ValueError(f"unknown phase {name!r}; one of {PHASES}")
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.phases[name] = (self.phases.get(name, 0.0)
                                 + time.perf_counter() - t0)


class GoodputTracker:
    """Accumulates step anatomy + run-level goodput/badput for one run.

    Thread-compat: one tracker is driven by one training thread; report()
    and flush() may be called from that thread (the background metrics
    flusher reads only the shared Metric instruments, which lock
    themselves).
    """

    def __init__(self, run: str, tokens_per_step: int = 0,
                 flops_per_step: Optional[float] = None,
                 peak_tflops: Optional[float] = None,
                 warmup_steps: Optional[int] = None,
                 export_metrics: bool = True):
        from ray_tpu._private import flags

        self.run = str(run)
        self.tokens_per_step = int(tokens_per_step)
        self.flops_per_step = flops_per_step
        self.flops_source = "analytic" if flops_per_step is not None else None
        self.peak_tflops = (peak_tflops if peak_tflops is not None
                            else _peak_tflops())
        self.warmup_steps = (int(flags.get("RTPU_GOODPUT_WARMUP"))
                             if warmup_steps is None else int(warmup_steps))
        self._export = export_metrics
        self._flush_every = max(0.5, float(flags.get("RTPU_GOODPUT_FLUSH_S")))
        self._t_start = time.perf_counter()
        self._wall_start = time.time()
        self._phase_sum: Dict[str, float] = {p: 0.0 for p in PHASES}
        self._compile_s = 0.0
        self._recovery_s = 0.0
        self._restarts = 0
        self.steps = 0
        # post-warmup accounting for steady-state throughput
        self._steady_steps = 0
        self._steady_wall = 0.0
        # recent per-step anatomy ring for percentile reporting
        self._recent: "deque[dict]" = deque(maxlen=512)
        self._last_flush = 0.0
        self._closed = False
        _set_current(self)

    # -- brackets -----------------------------------------------------------

    @contextmanager
    def compile_bracket(self):
        """Bracket jit/AOT compilation; badput bucket 'compile'."""
        t0 = time.perf_counter()
        try:
            yield
        finally:
            dt = time.perf_counter() - t0
            self._compile_s += dt
            if self._export:
                _instruments()["compile_s"].set(
                    self._compile_s, tags={"run": self.run})

    @contextmanager
    def recovery(self):
        """Bracket a restart/restore; badput bucket 'recovery'."""
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.note_restart(time.perf_counter() - t0)

    def note_restart(self, seconds: float = 0.0):
        self._restarts += 1
        self._recovery_s += max(0.0, float(seconds))
        if self._export:
            _instruments()["restarts"].inc(tags={"run": self.run})

    @contextmanager
    def step(self):
        """Bracket one training step; yields the phase timer."""
        st = _StepTimer()
        try:
            yield st
        finally:
            st.wall = time.perf_counter() - st.t0
            self._absorb_step(st)

    # -- accounting ---------------------------------------------------------

    def _absorb_step(self, st: _StepTimer):
        self.steps += 1
        for p, dt in st.phases.items():
            self._phase_sum[p] += dt
        if self.steps > self.warmup_steps:
            self._steady_steps += 1
            self._steady_wall += st.wall
        rec = {p: st.phases.get(p, 0.0) for p in PHASES}
        rec["total"] = st.wall
        self._recent.append(rec)
        if self._export:
            m = _instruments()
            m["step"].observe(st.wall, tags={"run": self.run})
            for p, dt in st.phases.items():
                m["phase"].observe(dt, tags={"run": self.run, "phase": p})
            self._export_gauges()
        now = time.monotonic()
        if now - self._last_flush >= self._flush_every:
            self.flush()

    def set_flops_per_step(self, flops: float, source: str = "analytic"):
        self.flops_per_step = float(flops)
        self.flops_source = source

    def set_tokens_per_step(self, tokens: int):
        self.tokens_per_step = int(tokens)

    # -- derived numbers ----------------------------------------------------

    def _buckets(self, elapsed: float) -> Dict[str, float]:
        tracked = {
            "goodput": self._phase_sum["compute"],
            "compile": self._compile_s,
            "data_stall": self._phase_sum["data"] + self._phase_sum["h2d"],
            "checkpoint": self._phase_sum["checkpoint"],
            "recovery": self._recovery_s,
        }
        tracked["idle"] = max(0.0, elapsed - sum(tracked.values()))
        return tracked

    def tokens_per_sec_steady(self) -> Optional[float]:
        if not self.tokens_per_step or self._steady_wall <= 0:
            return None
        return self.tokens_per_step * self._steady_steps / self._steady_wall

    def model_tflops_per_s(self) -> Optional[float]:
        if not self.flops_per_step or self._steady_wall <= 0 \
                or not self._steady_steps:
            return None
        return (self.flops_per_step * self._steady_steps
                / self._steady_wall / 1e12)

    def mfu(self) -> Optional[float]:
        tf = self.model_tflops_per_s()
        if tf is None or not self.peak_tflops:
            return None
        return tf / self.peak_tflops

    def _export_gauges(self):
        m = _instruments()
        elapsed = time.perf_counter() - self._t_start
        buckets = self._buckets(elapsed)
        tags = {"run": self.run}
        if elapsed > 0:
            m["goodput_frac"].set(buckets["goodput"] / elapsed, tags=tags)
        for name in ("compile", "data_stall", "checkpoint", "recovery",
                     "idle"):
            m["badput"].set(buckets[name],
                            tags={"run": self.run, "bucket": name})
        tok_s = self.tokens_per_sec_steady()
        if tok_s is not None:
            m["tok_s"].set(tok_s, tags=tags)
        tf = self.model_tflops_per_s()
        if tf is not None:
            m["tflops"].set(tf, tags=tags)
        mfu = self.mfu()
        if mfu is not None:
            m["mfu"].set(mfu, tags=tags)

    @staticmethod
    def _pctiles(xs: List[float]) -> dict:
        if not xs:
            return {"mean_ms": 0.0, "p50_ms": 0.0, "p90_ms": 0.0}
        xs = sorted(xs)
        return {
            "mean_ms": round(sum(xs) / len(xs) * 1e3, 3),
            "p50_ms": round(xs[(len(xs) - 1) // 2] * 1e3, 3),
            "p90_ms": round(xs[int((len(xs) - 1) * 0.9)] * 1e3, 3),
        }

    def report(self) -> dict:
        """The goodput record: buckets sum to elapsed_s exactly."""
        elapsed = time.perf_counter() - self._t_start
        buckets = self._buckets(elapsed)
        anatomy = {p: self._pctiles([r[p] for r in self._recent])
                   for p in PHASES}
        anatomy["total"] = self._pctiles([r["total"] for r in self._recent])
        tok_s = self.tokens_per_sec_steady()
        tf = self.model_tflops_per_s()
        mfu = self.mfu()
        return {
            "run": self.run,
            "t0": self._wall_start,
            "ts": time.time(),
            "steps": self.steps,
            "warmup_steps": self.warmup_steps,
            "restarts": self._restarts,
            "elapsed_s": elapsed,
            "buckets": buckets,
            "fractions": {k: (v / elapsed if elapsed > 0 else 0.0)
                          for k, v in buckets.items()},
            "anatomy": anatomy,
            "phase_sum_s": dict(self._phase_sum),
            "compile_s": self._compile_s,
            "tokens_per_step": self.tokens_per_step,
            "tokens_per_sec_steady": tok_s,
            "flops_per_step": self.flops_per_step,
            "flops_source": self.flops_source,
            "model_tflops_per_s": tf,
            "peak_tflops": self.peak_tflops,
            "mfu": mfu,
        }

    # -- push plane ---------------------------------------------------------

    def flush(self) -> bool:
        """Push the current record to the node scheduler ("goodput_push",
        the spans_push/profiles_push lane).  Best-effort; returns whether
        the record landed."""
        self._last_flush = time.monotonic()
        from ray_tpu._private import worker as worker_mod

        ctx = worker_mod.global_worker_or_none()
        if ctx is None:
            return False
        rec = self.report()
        rec["source"] = (ctx.worker_id.hex()
                         if getattr(ctx, "worker_id", None) else "driver")
        rec["rank"] = _env_rank()
        try:
            ctx.rpc("goodput_push", {"records": [rec]})
            return True
        except Exception:
            return False

    def close(self):
        """Final gauge export + push; idempotent."""
        if self._closed:
            return
        self._closed = True
        if self._export:
            try:
                self._export_gauges()
            except Exception:
                pass
        self.flush()
        _clear_current(self)


def _env_rank() -> Optional[int]:
    # train workers run under a TrainContext; fall back to None elsewhere
    try:
        from ray_tpu.train.context import get_context

        return get_context().get_world_rank()
    except Exception:
        return None


# ---------------------------------------------------------------------------
# current-tracker registry (train/trainer.py's hook flushes on fn exit so a
# record lands even when the user loop never called close())

_current_lock = threading.Lock()
_current: Optional[GoodputTracker] = None


def _set_current(gp: GoodputTracker):
    global _current
    with _current_lock:
        _current = gp


def _clear_current(gp: GoodputTracker):
    global _current
    with _current_lock:
        if _current is gp:
            _current = None


def current_tracker() -> Optional[GoodputTracker]:
    return _current


def flush_current(final: bool = False) -> bool:
    """Flush (and with ``final=True`` close) the process's active tracker."""
    gp = current_tracker()
    if gp is None:
        return False
    if final:
        gp.close()
        return True
    return gp.flush()


# ---------------------------------------------------------------------------
# merge helpers shared by state.py, the dashboard, and the CLI (none of
# which may assume a driver context — same pattern as profiling.py)

def merge_goodput_rows(rows: List[dict]) -> List[dict]:
    """Dedupe per-(run, source) summary rows across nodes, newest first."""
    best: Dict[tuple, dict] = {}
    for r in rows:
        key = (r.get("run"), r.get("source"))
        cur = best.get(key)
        if cur is None or (r.get("ts") or 0) > (cur.get("ts") or 0):
            best[key] = r
    return sorted(best.values(), key=lambda r: r.get("ts") or 0,
                  reverse=True)


def merge_records(records: List[dict]) -> Optional[dict]:
    """Combine one run's per-process records into a run view.

    For the common single-process run the summary IS the record.  For
    SPMD multi-worker runs the workers proceed in lockstep, so: steps /
    elapsed / compile are max over ranks, buckets are averaged (each
    rank attributes its own wall clock), throughput sums (each rank
    feeds distinct tokens), and mfu averages (it is already per-chip).
    """
    records = [r for r in records if r]
    if not records:
        return None
    records = merge_goodput_rows(records)
    n = len(records)
    buckets = {k: sum((r.get("buckets") or {}).get(k, 0.0)
                      for r in records) / n for k in BUCKETS}
    elapsed = max(r.get("elapsed_s") or 0.0 for r in records)
    tok = [r.get("tokens_per_sec_steady") for r in records
           if r.get("tokens_per_sec_steady")]
    mfu = [r.get("mfu") for r in records if r.get("mfu")]
    primary = min(records, key=lambda r: (r.get("rank") is None,
                                          r.get("rank") or 0))
    return {
        "run": primary.get("run"),
        "num_sources": n,
        "records": records,
        "summary": {
            "steps": max(r.get("steps") or 0 for r in records),
            "restarts": sum(r.get("restarts") or 0 for r in records),
            "elapsed_s": elapsed,
            "buckets": buckets,
            "fractions": {k: (v / elapsed if elapsed > 0 else 0.0)
                          for k, v in buckets.items()},
            "compile_s": max(r.get("compile_s") or 0.0 for r in records),
            "tokens_per_sec_steady": sum(tok) if tok else None,
            "mfu": (sum(mfu) / len(mfu)) if mfu else None,
            "anatomy": primary.get("anatomy"),
        },
    }
