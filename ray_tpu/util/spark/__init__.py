"""Ray-on-Spark: launch a ray_tpu cluster across a Spark cluster's workers.

Counterpart of /root/reference/python/ray/util/spark/cluster_init.py
(``setup_ray_cluster``/``shutdown_ray_cluster``).  The reference starts one
``ray start`` worker per Spark task slot inside a barrier-mode Spark job
and wires them to a head on the Spark driver; this port does the same with
``rtpu start`` (scripts/cli.py) as the per-slot command.

pyspark is not in the TPU image, so the Spark-job half is gated on import:
the command construction (what each executor runs) is factored out and
unit-tested; ``setup_ray_cluster`` raises a clear ImportError without
pyspark rather than pretending.
"""

from __future__ import annotations

import shlex
import sys
from typing import List, Optional

_active: dict = {}


def _worker_start_command(head_address: str, *, num_cpus: int,
                          extra_resources: Optional[dict] = None
                          ) -> List[str]:
    """The per-Spark-task-slot node launch command (reference:
    cluster_init.py's ray-start arg assembly, on `rtpu start` flags)."""
    cmd = [sys.executable, "-m", "ray_tpu.scripts.cli", "start",
           "--address", head_address, "--num-cpus", str(num_cpus)]
    if extra_resources:
        import json

        cmd += ["--resources", json.dumps(extra_resources)]
    return cmd


def setup_ray_cluster(num_worker_nodes: int, *, num_cpus_per_node: int = 1,
                      **kwargs) -> str:
    """Start a ray_tpu cluster on the active Spark cluster.  Returns the
    head address.  Requires pyspark with an active SparkSession."""
    try:
        import pyspark  # noqa: F401
    except ImportError as e:
        raise ImportError(
            "setup_ray_cluster requires pyspark (not present in this "
            "image).  On a Spark cluster: pip install pyspark, then each "
            "Spark task slot runs: "
            + shlex.join(_worker_start_command("<head>:port",
                                               num_cpus=num_cpus_per_node))
        ) from e
    from pyspark.sql import SparkSession

    spark = SparkSession.getActiveSession()
    if spark is None:
        raise RuntimeError("no active SparkSession")
    import ray_tpu

    ray_tpu.init()
    import ray_tpu.api as api

    head_address = api._global_node.gcs_address
    cmds = [_worker_start_command(head_address,
                                  num_cpus=num_cpus_per_node)
            for _ in range(num_worker_nodes)]

    def _launch(it):
        import subprocess

        for cmd in it:
            subprocess.Popen(cmd)
        yield 0

    rdd = spark.sparkContext.parallelize(cmds, num_worker_nodes)
    rdd.barrier().mapPartitions(_launch).collect()
    _active["head"] = head_address
    return head_address


def _stop_worker_nodes() -> int:
    """Send shutdown_node to every alive non-head node (the `rtpu stop`
    path: only standalone `rtpu start` processes honor it — exactly what
    setup_ray_cluster launched on the executors).  Returns nodes asked."""
    import ray_tpu.api as api
    from ray_tpu._private import protocol

    if api._global_node is None:
        return 0
    n = 0
    for node in api._global_node.gcs.list_nodes():
        if not node.alive or node.is_head:
            continue
        try:
            conn = protocol.connect_addr(node.sched_socket)
            try:
                conn.send({"t": "rpc", "method": "shutdown_node",
                           "params": {}})
                conn.recv()
            finally:
                conn.close()
            n += 1
        except Exception:
            continue  # best-effort: a dead executor already took it down
    return n


def shutdown_ray_cluster() -> None:
    if not _active:
        return
    import ray_tpu

    _stop_worker_nodes()  # reap the Popen'd per-slot worker daemons
    ray_tpu.shutdown()
    _active.clear()


__all__ = ["setup_ray_cluster", "shutdown_ray_cluster"]
