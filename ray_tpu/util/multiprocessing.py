"""multiprocessing.Pool clone on the actor runtime.

Counterpart of /root/reference/python/ray/util/multiprocessing/pool.py:545
(``Pool``): the standard-library Pool surface (apply/apply_async, map/
map_async, starmap, imap/imap_unordered, with chunking) executed by a pool
of actors instead of forked processes.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Iterable, List, Optional

import ray_tpu
from ray_tpu.util.actor_pool import ActorPool


class _PoolActor:
    def __init__(self, initializer=None, initargs=None):
        if initializer is not None:
            initializer(*(initargs or ()))

    def run_chunk(self, func, chunk, star: bool):
        if star:
            return [func(*args) for args in chunk]
        return [func(args) for args in chunk]


class AsyncResult:
    def __init__(self, refs: List[Any], single: bool = False):
        self._refs = refs
        self._single = single

    def wait(self, timeout: Optional[float] = None):
        ray_tpu.wait(self._refs, num_returns=len(self._refs),
                     timeout=timeout)

    def get(self, timeout: Optional[float] = None):
        chunks = ray_tpu.get(self._refs, timeout=timeout)
        flat = [v for chunk in chunks for v in chunk]
        return flat[0] if self._single else flat

    def ready(self) -> bool:
        ready, _ = ray_tpu.wait(self._refs, num_returns=len(self._refs),
                                timeout=0)
        return len(ready) == len(self._refs)

    def successful(self) -> bool:
        if not self.ready():
            raise ValueError("result is not ready")
        try:
            ray_tpu.get(self._refs, timeout=0)
            return True
        except Exception:
            return False


class Pool:
    def __init__(self, processes: Optional[int] = None,
                 initializer: Optional[Callable] = None,
                 initargs: tuple = (),
                 maxtasksperchild: Optional[int] = None,
                 ray_remote_args: Optional[dict] = None):
        if not ray_tpu.is_initialized():
            ray_tpu.init(ignore_reinit_error=True)
        if processes is None:
            processes = max(1, int(
                ray_tpu.cluster_resources().get("CPU", 1)))
        self._processes = processes
        opts = dict(ray_remote_args or {})
        cls = ray_tpu.remote(_PoolActor)
        self._actors = [cls.options(**opts).remote(initializer, initargs)
                        for _ in range(processes)]
        self._closed = False
        self._lock = threading.Lock()
        self._apply_rr = 0  # round-robin cursor for apply_async

    def _check_running(self):
        if self._closed:
            raise ValueError("Pool not running")

    def _chunks(self, iterable: Iterable, chunksize: Optional[int]):
        items = list(iterable)
        if chunksize is None:
            chunksize = max(1, -(-len(items) // (self._processes * 4)))
        return [items[i:i + chunksize]
                for i in range(0, len(items), chunksize)], chunksize

    # -- apply -------------------------------------------------------------
    def apply(self, func, args: tuple = (), kwds: Optional[dict] = None):
        return self.apply_async(func, args, kwds).get()

    def apply_async(self, func, args: tuple = (),
                    kwds: Optional[dict] = None):
        self._check_running()
        kwds = kwds or {}
        with self._lock:
            actor = self._actors[self._apply_rr % self._processes]
            self._apply_rr += 1
        ref = actor.run_chunk.remote(
            lambda a: func(*a[0], **a[1]), [(args, kwds)], False)
        return AsyncResult([ref], single=True)

    # -- map ---------------------------------------------------------------
    def map(self, func, iterable: Iterable,
            chunksize: Optional[int] = None) -> List[Any]:
        return self.map_async(func, iterable, chunksize).get()

    def map_async(self, func, iterable: Iterable,
                  chunksize: Optional[int] = None) -> AsyncResult:
        self._check_running()
        chunks, _ = self._chunks(iterable, chunksize)
        refs = [self._actors[i % self._processes].run_chunk.remote(
            func, chunk, False) for i, chunk in enumerate(chunks)]
        return AsyncResult(refs)

    def starmap(self, func, iterable: Iterable,
                chunksize: Optional[int] = None) -> List[Any]:
        return self.starmap_async(func, iterable, chunksize).get()

    def starmap_async(self, func, iterable: Iterable,
                      chunksize: Optional[int] = None) -> AsyncResult:
        self._check_running()
        chunks, _ = self._chunks(iterable, chunksize)
        refs = [self._actors[i % self._processes].run_chunk.remote(
            func, chunk, True) for i, chunk in enumerate(chunks)]
        return AsyncResult(refs)

    # -- imap --------------------------------------------------------------
    def imap(self, func, iterable: Iterable,
             chunksize: Optional[int] = None):
        self._check_running()
        pool = ActorPool(self._actors)
        chunks, _ = self._chunks(iterable, chunksize)
        for value in pool.map(
                lambda a, chunk: a.run_chunk.remote(func, chunk, False),
                chunks):
            yield from value

    def imap_unordered(self, func, iterable: Iterable,
                       chunksize: Optional[int] = None):
        self._check_running()
        pool = ActorPool(self._actors)
        chunks, _ = self._chunks(iterable, chunksize)
        for value in pool.map_unordered(
                lambda a, chunk: a.run_chunk.remote(func, chunk, False),
                chunks):
            yield from value

    # -- lifecycle ---------------------------------------------------------
    def close(self):
        self._closed = True

    def terminate(self):
        self._closed = True
        for a in self._actors:
            try:
                ray_tpu.kill(a)
            except Exception:
                pass
        self._actors = []

    def join(self):
        if not self._closed:
            raise ValueError("Pool is still running")

    def __enter__(self):
        self._check_running()
        return self

    def __exit__(self, *exc):
        self.terminate()
