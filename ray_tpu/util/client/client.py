"""Client-side driver context: WorkerContext over one TCP connection.

Counterpart of /root/reference/python/ray/util/client/worker.py — but where
the reference re-implements a parallel API surface with proxy classes, here
the client context satisfies the same interface the in-cluster
WorkerContext does (put_object/get_object/submit/rpc/register_function), so
``ray_tpu.remote``/``ActorClass``/state API run over it untouched.
"""

from __future__ import annotations

import threading
from typing import Callable, Optional

import cloudpickle

from ray_tpu._private import protocol
from ray_tpu.core.object_ref import ObjectRef


class ClientContext:
    mode = "client"

    def __init__(self, host: str, port: int, token: Optional[str] = None):
        import os

        self._conn = protocol.connect_tcp(host, port)
        self._lock = threading.Lock()  # one in-flight request at a time
        self.worker_id = b"client"
        self.node = None
        self._fn_cache: dict[int, tuple[object, bytes]] = {}
        self._tls = threading.local()
        if token is None:
            token = os.environ.get("RTPU_CLIENT_TOKEN", "")
        # Raw-frame handshake (mirrors the server: no pickle pre-auth).
        self._conn.send_bytes(token.encode("utf-8"))
        if self._conn.recv_bytes() != b"OK":
            self._conn.close()
            raise ConnectionError("client auth handshake failed")
        if self._call({"op": "ping"}) != "pong":
            raise ConnectionError("client handshake failed")

    # -- transport ---------------------------------------------------------
    def _call(self, msg: dict):
        with self._lock:
            self._conn.send(msg)
            resp = self._conn.recv()
        if resp is None:
            raise ConnectionError("client connection closed by server")
        if not resp.get("ok"):
            raise cloudpickle.loads(resp["error"])
        return resp["result"]

    # -- WorkerContext surface --------------------------------------------
    @property
    def current_task_id(self) -> Optional[bytes]:
        return getattr(self._tls, "task_id", None)

    @property
    def current_actor_id(self) -> Optional[bytes]:
        return getattr(self._tls, "actor_id", None)

    def put_object(self, value, oid: Optional[bytes] = None) -> ObjectRef:
        if isinstance(value, ObjectRef):
            raise TypeError("passing an ObjectRef to put is not allowed")
        oid_out = self._call({"op": "put", "oid": oid,
                              "blob": cloudpickle.dumps(value)})
        return ObjectRef(oid_out)

    def get_object(self, ref: ObjectRef, timeout: Optional[float] = None):
        blob = self._call({"op": "get", "oid": ref.binary(),
                           "timeout": timeout})
        return cloudpickle.loads(blob)

    def register_function(self, fn) -> bytes:
        cached = self._fn_cache.get(id(fn))
        if cached is not None and cached[0] is fn:
            return cached[1]
        fn_id = self._call({"op": "register_function",
                            "blob": cloudpickle.dumps(fn)})
        self._fn_cache[id(fn)] = (fn, fn_id)
        return fn_id

    def submit(self, spec) -> None:
        self._call({"op": "submit", "spec": spec})

    def rpc(self, method: str, params: dict):
        return self._call({"op": "rpc", "method": method, "params": params})

    def wait(self, refs, num_returns=1, timeout=None, fetch_local=True):
        ready, pending = self._call({
            "op": "wait", "oids": [r.binary() for r in refs],
            "num_returns": num_returns, "timeout": timeout,
            "fetch_local": fetch_local})
        return ([ObjectRef(o) for o in ready],
                [ObjectRef(o) for o in pending])

    def close(self):
        self._conn.close()


def connect_client(address: str) -> ClientContext:
    """address: "rtpu://[token@]host:port" (token may also come from the
    RTPU_CLIENT_TOKEN env var)."""
    hostport = address[len("rtpu://"):]
    token = None
    if "@" in hostport:
        token, _, hostport = hostport.rpartition("@")
    host, _, port = hostport.rpartition(":")
    if not host or not port.isdigit():
        raise ValueError(f"bad client address {address!r}; expected "
                         f"rtpu://[token@]host:port")
    return ClientContext(host, int(port), token=token)
