"""Remote-driver client (``rtpu://host:port``).

Counterpart of Ray Client (/root/reference/python/ray/util/client/:
worker.py client-side proxies, server/server.py the gRPC proxy): a thin
driver that holds NO local node — every put/get/submit/rpc crosses one TCP
connection to a ClientServer running next to the cluster head, which
executes them through its own attached driver context. The client-side
object is a WorkerContext drop-in, so the entire public API (remote
functions, actors, placement groups, state API) works unchanged over it.
"""

from ray_tpu.util.client.client import ClientContext, connect_client
from ray_tpu.util.client.server import ClientServer

__all__ = ["ClientContext", "ClientServer", "connect_client"]
