"""ClientServer: the cluster-side proxy for remote drivers.

Counterpart of /root/reference/python/ray/util/client/server/server.py —
scope note: all clients share this server's single attached-driver context
(the reference proxies a worker PER client, util/client/server/proxier.py;
one shared driver is the deliberate first cut here and is safe because the
runtime's submission paths are thread-safe).
"""

from __future__ import annotations

import threading
import traceback
from typing import Optional

import cloudpickle

from ray_tpu._private import protocol
from ray_tpu._private import worker as worker_mod
from ray_tpu.core.object_ref import ObjectRef


class ClientServer:
    """Serve remote drivers on TCP. Must run in a process already attached
    to the cluster (ray_tpu.init done)."""

    def __init__(self, host: str = "0.0.0.0", port: int = 0):
        if worker_mod.global_worker_or_none() is None:
            raise RuntimeError("ClientServer requires ray_tpu.init() first")
        self._listener = protocol.listener_tcp(host, port)
        self.port = self._listener.getsockname()[1]
        self._shutdown = False
        self._thread = threading.Thread(
            target=self._accept_loop, name="client-server", daemon=True)
        self._thread.start()

    def _accept_loop(self):
        while not self._shutdown:
            try:
                sock, _ = self._listener.accept()
            except OSError:
                return
            conn = protocol.Connection(sock)
            threading.Thread(target=self._serve, args=(conn,),
                             daemon=True).start()

    def _serve(self, conn: protocol.Connection):
        ctx = worker_mod.global_worker()
        while True:
            msg = conn.recv()
            if msg is None:
                return
            try:
                result = self._handle(ctx, msg)
                conn.send({"ok": True, "result": result})
            except BaseException as e:  # noqa: BLE001 — ship to client
                try:
                    payload = cloudpickle.dumps(e)
                except Exception:
                    payload = cloudpickle.dumps(
                        RuntimeError(f"{type(e).__name__}: {e}"))
                try:
                    conn.send({"ok": False, "error": payload,
                               "traceback": traceback.format_exc()})
                except OSError:
                    return

    def _handle(self, ctx, msg: dict):
        op = msg["op"]
        if op == "put":
            value = cloudpickle.loads(msg["blob"])
            return ctx.put_object(value, oid=msg.get("oid")).binary()
        if op == "get":
            value = ctx.get_object(ObjectRef(msg["oid"]),
                                   timeout=msg.get("timeout"))
            return cloudpickle.dumps(value)
        if op == "register_function":
            fn = cloudpickle.loads(msg["blob"])
            return ctx.register_function(fn)
        if op == "submit":
            ctx.submit(msg["spec"])
            return True
        if op == "rpc":
            return ctx.rpc(msg["method"], msg["params"])
        if op == "wait":
            ready, pending = ctx.wait(
                [ObjectRef(o) for o in msg["oids"]],
                msg["num_returns"], msg.get("timeout"),
                msg.get("fetch_local", True))
            return ([r.binary() for r in ready],
                    [p.binary() for p in pending])
        if op == "ping":
            return "pong"
        raise ValueError(f"unknown client op {op!r}")

    def shutdown(self):
        self._shutdown = True
        try:
            self._listener.close()
        except OSError:
            pass
