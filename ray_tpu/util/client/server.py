"""ClientServer: the cluster-side proxy for remote drivers.

Counterpart of /root/reference/python/ray/util/client/server/server.py —
scope note: all clients share this server's single attached-driver context
(the reference proxies a worker PER client, util/client/server/proxier.py;
one shared driver is the deliberate first cut here and is safe because the
runtime's submission paths are thread-safe).
"""

from __future__ import annotations

import hmac
import secrets
import threading
import traceback
from typing import Optional

import cloudpickle

from ray_tpu._private import protocol
from ray_tpu._private import worker as worker_mod
from ray_tpu.core.object_ref import ObjectRef


class ClientServer:
    """Serve remote drivers on TCP. Must run in a process already attached
    to the cluster (ray_tpu.init done).

    Every op the server executes deserializes client-supplied pickles in the
    cluster-attached driver process, so connections are authenticated: the
    client must present ``token`` (auto-generated when not given; see
    ``self.address``) before any other op is accepted.  Pass ``token=""`` to
    disable authentication — only do that on a trusted, isolated network.
    The listener binds loopback by default; binding a routable interface is
    an explicit opt-in.
    """

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 token: Optional[str] = None):
        if worker_mod.global_worker_or_none() is None:
            raise RuntimeError("ClientServer requires ray_tpu.init() first")
        self.host = host
        self.token = secrets.token_hex(16) if token is None else token
        self._listener = protocol.listener_tcp(host, port)
        self.port = self._listener.getsockname()[1]
        self._shutdown = False
        self._thread = threading.Thread(
            target=self._accept_loop, name="client-server", daemon=True)
        self._thread.start()

    @property
    def address(self) -> str:
        """Connect string for ray_tpu.init (embeds the auth token).

        A wildcard bind is rewritten to this host's routable address, since
        "0.0.0.0" is not connectable from anywhere.
        """
        host = self.host
        if host in ("0.0.0.0", "::", ""):
            import socket as _socket
            try:
                host = _socket.gethostbyname(_socket.gethostname())
            except OSError:
                host = "127.0.0.1"
        if self.token:
            return f"rtpu://{self.token}@{host}:{self.port}"
        return f"rtpu://{host}:{self.port}"

    def _accept_loop(self):
        while not self._shutdown:
            try:
                sock, _ = self._listener.accept()
            except OSError:
                return
            conn = protocol.Connection(sock)
            threading.Thread(target=self._serve, args=(conn,),
                             daemon=True).start()

    def _serve(self, conn: protocol.Connection):
        ctx = worker_mod.global_worker()
        # First frame is the raw (never unpickled) token handshake: until it
        # matches, no byte from this peer reaches pickle.loads.
        raw = conn.recv_bytes()
        if raw is None:
            conn.close()
            return
        if self.token and not hmac.compare_digest(
                raw, self.token.encode("utf-8")):
            try:
                conn.send_bytes(b"NO")
            except OSError:
                pass
            conn.close()
            return
        try:
            conn.send_bytes(b"OK")
        except OSError:
            conn.close()
            return
        while True:
            msg = conn.recv()
            if msg is None:
                return
            try:
                result = self._handle(ctx, msg)
                conn.send({"ok": True, "result": result})
            except BaseException as e:  # noqa: BLE001 — ship to client
                try:
                    payload = cloudpickle.dumps(e)
                except Exception:
                    payload = cloudpickle.dumps(
                        RuntimeError(f"{type(e).__name__}: {e}"))
                try:
                    conn.send({"ok": False, "error": payload,
                               "traceback": traceback.format_exc()})
                except OSError:
                    return

    def _handle(self, ctx, msg: dict):
        op = msg["op"]
        if op == "put":
            value = cloudpickle.loads(msg["blob"])
            return ctx.put_object(value, oid=msg.get("oid")).binary()
        if op == "get":
            value = ctx.get_object(ObjectRef(msg["oid"]),
                                   timeout=msg.get("timeout"))
            return cloudpickle.dumps(value)
        if op == "register_function":
            fn = cloudpickle.loads(msg["blob"])
            return ctx.register_function(fn)
        if op == "submit":
            ctx.submit(msg["spec"])
            return True
        if op == "rpc":
            return ctx.rpc(msg["method"], msg["params"])
        if op == "wait":
            ready, pending = ctx.wait(
                [ObjectRef(o) for o in msg["oids"]],
                msg["num_returns"], msg.get("timeout"),
                msg.get("fetch_local", True))
            return ([r.binary() for r in ready],
                    [p.binary() for p in pending])
        if op == "ping":
            return "pong"
        raise ValueError(f"unknown client op {op!r}")

    def shutdown(self):
        self._shutdown = True
        try:
            self._listener.close()
        except OSError:
            pass
