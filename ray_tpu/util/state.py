"""State API: unified cluster introspection.

Counterpart of /root/reference/python/ray/util/state/api.py:110
(list_actors/list_tasks/list_nodes/list_objects/list_placement_groups,
summarize_tasks/actors) aggregating GCS tables + per-node scheduler
task-event logs, the way the reference's state aggregator combines GCS and
raylet sources (dashboard/state_aggregator.py).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

from ray_tpu._private import protocol
from ray_tpu._private.worker import global_worker


def _rpc(method: str, params: Optional[dict] = None):
    return global_worker().rpc(method, params or {})


def _node_rpc(sched_socket: str, method: str, params: Optional[dict] = None):
    """One-shot rpc against a specific node's scheduler."""
    conn = protocol.connect_addr(sched_socket)
    try:
        conn.send({"t": "rpc", "method": method, "params": params or {}})
        resp = conn.recv()
    finally:
        conn.close()
    if resp is None or not resp.get("ok"):
        raise RuntimeError(f"state rpc {method} failed: "
                           f"{resp.get('error') if resp else 'closed'}")
    return resp["result"]


def list_nodes() -> List[Dict[str, Any]]:
    return [{"node_id": n["node_id"].hex(), "alive": n["alive"],
             "is_head": n["is_head"], "resources": n["resources"],
             "available": n["available"]}
            for n in _rpc("list_nodes")]


def list_actors(detail: bool = False) -> List[Dict[str, Any]]:
    out = []
    for a in _rpc("list_actors"):
        row = {"actor_id": a["actor_id"].hex(), "state": a["state"],
               "class_name": a["class_name"], "name": a["name"],
               "node_id": a["node_id"].hex() if a["node_id"] else None}
        if detail:
            row.update(num_restarts=a["num_restarts"],
                       max_restarts=a["max_restarts"],
                       death_cause=a["death_cause"])
        out.append(row)
    return out


def _all_task_events() -> List[dict]:
    events: List[dict] = []
    for n in _rpc("list_nodes"):
        if not n["alive"]:
            continue
        try:
            evs = _node_rpc(n["sched_socket"], "list_task_events")
        except (OSError, RuntimeError):
            continue
        for e in evs:
            e["node_id"] = n["node_id"]
        events.extend(evs)
    return events


def list_tasks(filters: Optional[list] = None) -> List[Dict[str, Any]]:
    """One row per task event; filters are (key, '=', value) triples on
    the rendered rows (reference: list_tasks filter syntax subset).
    FORWARDED entries (a node handing a spec to a peer) are dropped — the
    executing node's row is the real lifecycle."""
    rows = []
    for e in _all_task_events():
        if e["state"] == "FORWARDED":
            continue
        rows.append({
            "task_id": e["task_id"].hex(),
            "name": e["name"],
            "type": e["kind"].upper(),
            "state": e["state"],
            "node_id": e["node_id"].hex(),
            "worker_id": e["worker_id"].hex() if e["worker_id"] else None,
            "actor_id": e["actor_id"].hex() if e["actor_id"] else None,
            "submitted_ts": e["submitted_ts"],
            "start_ts": e["start_ts"],
            "end_ts": e["end_ts"],
        })
    for key, op, value in (filters or ()):
        if op != "=":
            raise ValueError(f"unsupported filter op {op!r}")
        rows = [r for r in rows if r.get(key) == value]
    return rows


def list_refs() -> List[Dict[str, Any]]:
    """Merged per-process reference tables cluster-wide (refs_push lane):
    one record per worker/driver with its live ObjectRef rows (count,
    pin/lineage membership, and — when RTPU_RECORD_REF_CREATION_SITES is
    on — the creating call site, task and trace).  Flushes the driver's
    own table first so just-created refs are part of the answer."""
    from ray_tpu._private import ref_tracker

    ref_tracker.flush_refs()
    tables: List[dict] = []
    for n in _alive_nodes():
        try:
            tables.extend(_node_rpc(n["sched_socket"], "list_refs"))
        except (OSError, RuntimeError):
            continue
    for t in tables:
        if isinstance(t.get("node"), bytes):
            t["node"] = t["node"].hex()
    return tables


def store_audits(max_rows: Optional[int] = None,
                 max_tombstones: int = 4096) -> List[Dict[str, Any]]:
    """Per-node object-store audits (shm daemon OP_AUDIT): occupancy/
    fragmentation summary + per-object rows + recent eviction
    tombstones, stamped with the owning node id."""
    params: Dict[str, Any] = {"max_tombstones": max_tombstones}
    if max_rows is not None:
        params["max_rows"] = max_rows
    out: List[dict] = []
    for n in _alive_nodes():
        try:
            doc = _node_rpc(n["sched_socket"], "store_audit", params)
        except (OSError, RuntimeError):
            continue
        doc["node_id"] = n["node_id"].hex()
        out.append(doc)
    return out


def list_objects(filters: Optional[list] = None) -> List[Dict[str, Any]]:
    """One row per known object: the store audit (size, seal state, pin
    count, age, idle time) joined with the GCS location directory
    (primary copy) and the merged reference tables (holders: which
    process created/holds the ref, at which call site, under which
    task/trace).  Filters are (key, '=', value) triples on the rendered
    rows — the same syntax :func:`list_tasks` supports."""
    locs = _rpc("list_object_locations")
    loc_by_hex = {oid.hex(): [n.hex() for n in nodes]
                  for oid, nodes in locs.items()}
    out = merge_object_rows(store_audits(), list_refs(), loc_by_hex)
    for key, op, value in (filters or ()):
        if op != "=":
            raise ValueError(f"unsupported filter op {op!r}")
        out = [r for r in out
               if r.get(key) == value or str(r.get(key)) == str(value)]
    return out


def merge_object_rows(audits: List[dict], tables: List[dict],
                      loc_by_hex: Dict[str, list]) -> List[Dict[str, Any]]:
    """Pure join of per-node store audits + merged reference tables + the
    GCS location directory into :func:`list_objects` rows.  The CLI
    fetches the three inputs over raw scheduler RPC (it has no driver
    context) and reuses this merge."""
    holders: Dict[str, List[dict]] = {}
    sites: Dict[str, dict] = {}  # attribution even after refs died
    for table in tables:
        for r in table.get("refs") or ():
            oid = r["object_id"]
            # a real user site beats "<internal>" (a worker creating its
            # own return object records no user frame)
            if (r.get("site") and r["site"] != "<internal>"
                    and oid not in sites):
                sites[oid] = r
            if r.get("kind") == "dropped":
                continue  # attribution-only row, nothing holds the oid
            holders.setdefault(oid, []).append({
                "node": table.get("node"), "proc": table.get("proc"),
                "pid": table.get("pid"), "count": r.get("count", 0),
                "pinned": r.get("pinned", False),
                "lineage": r.get("lineage", False),
                "site": r.get("site"), "task": r.get("task"),
                "trace_id": r.get("trace_id"), "kind": r.get("kind"),
            })
    rows: Dict[str, dict] = {}
    for doc in audits:
        nid = doc["node_id"]
        for o in doc.get("objects") or ():
            oid = o["id"]
            row = rows.get(oid)
            if row is None:
                hs = holders.get(oid, [])
                src = (next((h for h in hs
                             if h.get("site")
                             and h["site"] != "<internal>"), None)
                       or sites.get(oid))
                locations = loc_by_hex.get(oid, [])
                row = rows[oid] = {
                    "object_id": oid,
                    "size_bytes": o.get("size", 0),
                    "seal_state": "SEALED" if o.get("sealed") else
                                  "CREATED",
                    "pinned": bool(o.get("refcount", 0) > 0),
                    "pin_count": o.get("refcount", 0),
                    "spilled": bool(o.get("spilled")),
                    "age_s": round(o.get("age_ms", 0) / 1e3, 3),
                    "idle_s": round(o.get("idle_ms", 0) / 1e3, 3),
                    "primary_copy": (locations[0] if locations else nid),
                    "locations": locations or [nid],
                    "nodes_resident": [],
                    "ref_count": sum(h["count"] for h in hs),
                    "holders": hs,
                    "site": src["site"] if src else None,
                    "task": src["task"] if src else None,
                    "trace_id": src["trace_id"] if src else None,
                }
            row["nodes_resident"].append(nid)
    # refs whose object is not resident anywhere (pending, inlined, or
    # lost): still one row each, so `rtpu memory` explains every holder
    for oid, hs in holders.items():
        if oid in rows:
            continue
        src = (next((h for h in hs if h.get("site")
                     and h["site"] != "<internal>"), None)
               or sites.get(oid))
        locations = loc_by_hex.get(oid, [])
        rows[oid] = {
            "object_id": oid, "size_bytes": 0, "seal_state": "ABSENT",
            "pinned": False, "pin_count": 0, "spilled": False,
            "age_s": None, "idle_s": None,
            "primary_copy": locations[0] if locations else None,
            "locations": locations, "nodes_resident": [],
            "ref_count": sum(h["count"] for h in hs), "holders": hs,
            "site": src["site"] if src else None,
            "task": src["task"] if src else None,
            "trace_id": src["trace_id"] if src else None,
        }
    return list(rows.values())


def detect_leaks(age_s: Optional[float] = None,
                 grace_s: float = 10.0) -> Dict[str, Any]:
    """Cross-reference store-resident objects against the merged
    reference tables and flag:

    - ``unreferenced``: sealed, unpinned bytes no process holds a ref to
      (and no lineage entry can recover a consumer for) — orphaned until
      LRU pressure happens to evict them.  A ``grace_s`` window skips
      objects younger than the refs flush interval.
    - ``age_outlier``: resident objects older than ``age_s`` (default
      RTPU_LEAK_AGE_S) that have not been read since creation.
    - ``held_lost``: refs still held on objects that are gone from every
      store (eviction tombstone) — attributed to their creating call
      site so the holder can be found even after a daemon restart.

    Tombstoned ids themselves are NEVER leaks: a tombstone means the
    store already reclaimed (or never kept) the bytes."""
    audits = store_audits()
    tables = list_refs()
    lost = lost_held_ids(audits, tables,
                         lambda oid: _rpc("object_lost", {"oid": oid}))
    return leak_report(audits, tables, age_s, grace_s, lost_ids=lost)


def lost_held_ids(audits: List[dict], tables: List[dict], query,
                  cap: int = 512) -> set:
    """GCS-lost ids among held-but-nowhere-resident refs.  The daemon's
    eviction-tombstone ring dies with the daemon, so after a store
    restart the durable GCS loss record is what lets ``held_lost``
    classification still fire; ``query(oid_bytes) -> bool`` is the
    caller's ``object_lost`` RPC (the CLI supplies its own transport)."""
    resident = {o["id"] for doc in audits
                for o in doc.get("objects") or ()}
    tomb = {t for doc in audits for t in doc.get("tombstone_ids") or ()}
    # live refs only: lost_ids feed held_lost classification, and a
    # lineage-only row on a lost object is reclamation, not a leak
    held = {r["object_id"] for table in tables
            for r in table.get("refs") or ()
            if r.get("count", 0) > 0}
    lost: set = set()
    for oid in sorted(held - resident - tomb)[:cap]:
        try:
            if query(bytes.fromhex(oid)):
                lost.add(oid)
        except Exception:
            break  # best-effort: a dead head just means no extra class
    return lost


def leak_report(audits: List[dict], tables: List[dict],
                age_s: Optional[float] = None,
                grace_s: float = 10.0,
                lost_ids: Optional[set] = None) -> Dict[str, Any]:
    """Pure leak cross-reference over already-fetched audits/ref tables
    (classes as documented on :func:`detect_leaks`).  ``lost_ids``
    extends the store tombstones with GCS-lost ids (``lost_held_ids``)
    so held refs on objects wiped by a daemon restart still classify."""
    from ray_tpu._private import flags

    if age_s is None:
        age_s = float(flags.get("RTPU_LEAK_AGE_S"))
    tombstones = set(lost_ids or ())
    for doc in audits:
        tombstones.update(doc.get("tombstone_ids") or ())
    held: Dict[str, List[dict]] = {}
    sites: Dict[str, dict] = {}  # attribution, incl. dropped-prov rows
    for table in tables:
        for r in table.get("refs") or ():
            if r.get("site") and r["object_id"] not in sites:
                sites[r["object_id"]] = r
            if r.get("count", 0) > 0 or r.get("lineage"):
                held.setdefault(r["object_id"], []).append(dict(
                    r, node=table.get("node"), proc=table.get("proc"),
                    pid=table.get("pid")))
    leaks: List[dict] = []
    resident: set = set()
    checked = 0
    for doc in audits:
        nid = doc["node_id"]
        for o in doc.get("objects") or ():
            oid = o["id"]
            resident.add(oid)
            checked += 1
            age = o.get("age_ms", 0) / 1e3
            idle = o.get("idle_ms", 0) / 1e3
            hs = held.get(oid)
            src = sites.get(oid) or {}
            site = next((h.get("site") for h in (hs or ())
                         if h.get("site")), None) or src.get("site")
            task = next((h.get("task") for h in (hs or ())
                         if h.get("task")), None) or src.get("task")
            if (hs is None and o.get("sealed")
                    and not o.get("refcount") and age > grace_s):
                leaks.append({
                    "kind": "unreferenced", "object_id": oid,
                    "node_id": nid, "size_bytes": o.get("size", 0),
                    "age_s": round(age, 3), "site": site, "task": task,
                    "detail": "no live ref in any process"})
            elif age > age_s and idle >= age - grace_s:
                leaks.append({
                    "kind": "age_outlier", "object_id": oid,
                    "node_id": nid, "size_bytes": o.get("size", 0),
                    "age_s": round(age, 3), "site": site, "task": task,
                    "detail": f"resident {age:.0f}s, never re-read"})
    for oid, hs in held.items():
        if oid in resident or oid not in tombstones:
            continue
        live = sum(h.get("count", 0) for h in hs)
        if live <= 0:
            # lineage bookkeeping only: no process can still read this
            # oid, so its loss is reclamation, not a leak
            continue
        src = next((h for h in hs if h.get("site")), hs[0])
        leaks.append({
            "kind": "held_lost", "object_id": oid,
            "node_id": src.get("node"), "size_bytes": 0,
            "age_s": src.get("age_s"), "site": src.get("site"),
            "task": src.get("task"),
            "detail": f"{live} live ref(s) on a store-evicted object"})
    leaks.sort(key=lambda r: r.get("size_bytes") or 0, reverse=True)
    return {"leaks": leaks, "checked_objects": checked,
            "nodes": len(audits),
            "thresholds": {"age_s": age_s, "grace_s": grace_s}}


def memory_summary() -> Dict[str, Any]:
    """The `ray memory` view: cluster objects grouped by creation call
    site (size totals, counts, ages, holder tasks), plus each node's
    occupancy/fragmentation summary and the leak report.  Shared by the
    dashboard's /api/memory and the `rtpu memory` CLI."""
    objects = list_objects()
    node_summaries = [dict((doc.get("summary") or {}),
                           node_id=doc["node_id"])
                      for doc in store_audits(max_rows=0)]
    return {"groups": group_objects_by_site(objects),
            "objects": len(objects),
            "nodes": node_summaries, "leak_report": detect_leaks()}


def group_objects_by_site(objects: List[dict]) -> List[Dict[str, Any]]:
    """Pure `ray memory`-style grouping of :func:`list_objects` rows by
    creation call site, largest total first."""
    groups: Dict[str, dict] = {}
    for r in objects:
        key = r.get("site") or "(no call site recorded)"
        g = groups.setdefault(key, {
            "site": key, "count": 0, "total_bytes": 0, "ref_count": 0,
            "pinned": 0, "max_age_s": 0.0, "tasks": set(), "kinds": set(),
            "example": r["object_id"]})
        g["count"] += 1
        g["total_bytes"] += r.get("size_bytes") or 0
        g["ref_count"] += r.get("ref_count") or 0
        g["pinned"] += 1 if r.get("pinned") else 0
        g["max_age_s"] = max(g["max_age_s"], r.get("age_s") or 0.0)
        if r.get("task"):
            g["tasks"].add(r["task"])
        for h in r.get("holders") or ():
            if h.get("kind"):
                g["kinds"].add(h["kind"])
    rows = []
    for g in groups.values():
        g["tasks"] = sorted(g["tasks"])
        g["kinds"] = sorted(g["kinds"])
        rows.append(g)
    rows.sort(key=lambda g: g["total_bytes"], reverse=True)
    return rows


def search_logs(task: Optional[str] = None, trace: Optional[str] = None,
                limit: int = 1000) -> List[Dict[str, Any]]:
    """Task-attributed worker-log lines cluster-wide (the log monitor's
    ring on each node), filtered by task name / task-id prefix and/or
    trace-id prefix, oldest first."""
    rows: List[dict] = []
    for n in _alive_nodes():
        try:
            part = _node_rpc(n["sched_socket"], "logs_search",
                             {"task": task or "", "trace": trace or "",
                              "limit": limit})
        except (OSError, RuntimeError):
            continue
        for r in part:
            if isinstance(r.get("node"), bytes):
                r["node"] = r["node"].hex()
        rows.extend(part)
    rows.sort(key=lambda r: r.get("ts") or 0.0)
    return rows[-limit:]


def list_placement_groups() -> List[Dict[str, Any]]:
    table = _rpc("pg_table")
    rows = []
    for pg_id, info in table.items():
        row = {"placement_group_id": pg_id.hex(), **info}
        # node IDs hex like every other row in this module (JSON-safe)
        if "assignment" in row:
            row["assignment"] = [
                n.hex() if isinstance(n, bytes) else n
                for n in row["assignment"]]
        rows.append(row)
    return rows


def summarize_events(events: List[dict]) -> Dict[str, Dict[str, int]]:
    """name -> state -> count over raw task events (shared with the CLI)."""
    summary: Dict[str, Dict[str, int]] = {}
    for e in events:
        if e["state"] == "FORWARDED":
            continue
        by_state = summary.setdefault(e["name"], {})
        by_state[e["state"]] = by_state.get(e["state"], 0) + 1
    return summary


def summarize_tasks() -> Dict[str, Any]:
    summary = summarize_events(_all_task_events())
    return {"cluster": {"summary": summary,
                        "total_tasks": sum(sum(v.values())
                                           for v in summary.values())}}


def summarize_actors() -> Dict[str, Any]:
    summary: Dict[str, Dict[str, int]] = {}
    for row in list_actors():
        by_state = summary.setdefault(row["class_name"], {})
        by_state[row["state"]] = by_state.get(row["state"], 0) + 1
    return {"cluster": {"summary": summary,
                        "total_actors": sum(sum(v.values())
                                            for v in summary.values())}}


def events_to_chrome_trace(events: List[dict]) -> List[dict]:
    """Raw task events -> chrome://tracing 'X' events (shared with CLI)."""
    import time as time_mod

    trace = []
    for e in events:
        if e["start_ts"] is None or e["state"] == "FORWARDED":
            continue
        end = e["end_ts"] or time_mod.time()
        trace.append({
            "name": e["name"],
            "cat": e["kind"],
            "ph": "X",
            "ts": e["start_ts"] * 1e6,
            "dur": (end - e["start_ts"]) * 1e6,
            "pid": e["node_id"].hex()[:8],
            "tid": e["worker_id"].hex()[:8] if e["worker_id"] else "?",
            "args": {"state": e["state"]},
        })
    return trace


def timeline(filename: Optional[str] = None) -> List[dict]:
    """Chrome-trace events for all finished/running tasks (reference:
    `ray timeline` via GcsTaskManager, scripts.py:2689).  Load the output
    in chrome://tracing or Perfetto."""
    events = events_to_chrome_trace(_all_task_events())
    if filename:
        import json

        with open(filename, "w") as f:
            json.dump(events, f)
    return events


def _all_trace_spans(trace_id: str) -> List[dict]:
    """Fan one trace's spans in from every alive node's scheduler (each
    node only holds spans its own workers/driver flushed)."""
    spans: List[dict] = []
    for n in _rpc("list_nodes"):
        if not n["alive"]:
            continue
        try:
            spans.extend(_node_rpc(n["sched_socket"], "get_trace_spans",
                                   {"trace_id": trace_id}))
        except (OSError, RuntimeError):
            continue
    return spans


def get_trace(trace_id) -> Dict[str, Any]:
    """Assemble one distributed trace cluster-wide: the span tree across
    every process it touched plus a critical-path summary (queue-wait vs.
    arg-fetch vs. run seconds per span).  ``trace_id`` is the hex string
    from ``Span.trace_id`` (bytes accepted).  Pass the result to
    ``tracing.export_trace_chrome_trace`` for a Perfetto view with
    cross-process flow arrows."""
    from ray_tpu.util import tracing

    if isinstance(trace_id, bytes):
        trace_id = trace_id.hex()
    # driver-side spans may still sit in the local buffer: flush first so
    # the root of a just-finished workload is part of the answer
    tracing.flush_spans()
    return tracing.assemble_trace(trace_id, _all_trace_spans(trace_id))


def list_traces() -> List[Dict[str, Any]]:
    """Known traces cluster-wide, most recent last_ts first."""
    from ray_tpu.util import tracing

    tracing.flush_spans()
    rows: Dict[str, dict] = {}
    for n in _rpc("list_nodes"):
        if not n["alive"]:
            continue
        try:
            node_rows = _node_rpc(n["sched_socket"], "list_traces")
        except (OSError, RuntimeError):
            continue
        for r in node_rows:
            agg = rows.get(r["trace_id"])
            if agg is None:
                rows[r["trace_id"]] = dict(r)
            else:
                agg["num_spans"] += r["num_spans"]
                agg["first_ts"] = min(agg["first_ts"], r["first_ts"])
                agg["last_ts"] = max(agg["last_ts"], r["last_ts"])
                if not agg.get("root"):
                    agg["root"] = r.get("root")
    return sorted(rows.values(), key=lambda r: r["last_ts"], reverse=True)


def _alive_nodes() -> List[dict]:
    return [n for n in _rpc("list_nodes") if n["alive"]]


def list_profiles() -> List[Dict[str, Any]]:
    """Known CPU profiles cluster-wide (always-on "continuous" plus any
    on-demand captures), most recent first, with the task names each
    profile attributed samples to."""
    from ray_tpu._private import profiling

    rows: List[dict] = []
    for n in _alive_nodes():
        try:
            rows.extend(_node_rpc(n["sched_socket"], "list_profiles"))
        except (OSError, RuntimeError):
            continue
    return profiling.merge_profile_rows(rows)


def get_profile(profile_id: str) -> Optional[Dict[str, Any]]:
    """Assemble one profile cluster-wide: folded stacks merged across
    every node, grouped by (task name, trace id).  Pass the result to
    ``profiling.profile_to_speedscope`` / ``profile_to_folded`` for
    flamegraph export, or fetch it rendered from the dashboard's
    ``/api/profile?id=...``."""
    from ray_tpu._private import profiling

    parts = []
    for n in _alive_nodes():
        try:
            parts.append(_node_rpc(n["sched_socket"], "get_profile",
                                   {"profile_id": profile_id}))
        except (OSError, RuntimeError):
            continue
    return profiling.merge_profiles(parts)


def list_goodput() -> List[Dict[str, Any]]:
    """Goodput/step-anatomy summary rows cluster-wide (one per run per
    reporting process), newest first.  Flushes the driver's own tracker
    first so a just-finished loop is part of the answer."""
    from ray_tpu.util import goodput

    goodput.flush_current()
    rows: List[dict] = []
    for n in _alive_nodes():
        try:
            rows.extend(_node_rpc(n["sched_socket"], "list_goodput"))
        except (OSError, RuntimeError):
            continue
    return goodput.merge_goodput_rows(rows)


def get_goodput(run: str) -> Optional[Dict[str, Any]]:
    """Assemble one run's goodput records cluster-wide: per-process
    records plus a merged summary whose badput buckets sum to elapsed
    wall time (see util/goodput.py for the bucket definitions)."""
    from ray_tpu.util import goodput

    goodput.flush_current()
    records: List[dict] = []
    for n in _alive_nodes():
        try:
            records.extend(_node_rpc(n["sched_socket"], "get_goodput",
                                     {"run": run}))
        except (OSError, RuntimeError):
            continue
    return goodput.merge_records(records)


def list_events(kind: str = "", severity: str = "",
                limit: int = 500) -> List[Dict[str, Any]]:
    """Cluster incident timeline (the event plane): every node's banked
    events merged and time-ordered — store-daemon restarts, replica
    deaths, chaos injections, spill/scale decisions, SLO alert
    transitions.  ``kind`` filters by prefix (e.g. "chaos."), each row
    carries its trace_id when the incident happened under a trace."""
    from ray_tpu.util import events as events_mod

    events_mod.flush_events()  # the driver's own buffered events first
    rows: List[dict] = []
    for n in _alive_nodes():
        try:
            rows.extend(_node_rpc(n["sched_socket"], "list_events", {
                "kind": kind, "severity": severity, "limit": limit}))
        except (OSError, RuntimeError):
            continue
    rows.sort(key=lambda e: e.get("ts", 0.0))
    return rows[-max(1, int(limit)):]


def _head_sock() -> str:
    for n in _alive_nodes():
        if n["is_head"]:
            return n["sched_socket"]
    raise RuntimeError("no alive head node")


def query_timeseries(family: str = "",
                     window_s: float = 300.0) -> Dict[str, Any]:
    """Windowed history from the head's ring TSDB: no ``family`` lists
    the known families; with one, the in-window raw points per series
    (same shape as the dashboard's /api/timeseries)."""
    return _node_rpc(_head_sock(), "query_timeseries",
                     {"family": family, "window_s": window_s})


def exemplars_for(family: str,
                  window_s: float = 300.0) -> Dict[str, Dict[int, str]]:
    """Exemplar trace ids banked on a histogram family's buckets: per
    series (keyed "tag=val,..." or "-"), bucket index -> the trace id of
    the last observation that landed there.  This answers "which request
    was the p99" — feed a returned id to :func:`get_trace` for the full
    router→replica→engine anatomy of that request."""
    doc = query_timeseries(family, window_s)
    out: Dict[str, Dict[int, str]] = {}
    for s in doc.get("series") or ():
        ex = s.get("exemplars")
        if not ex:
            continue
        key = ",".join(f"{k}={v}"
                       for k, v in sorted(s.get("tags", {}).items())) or "-"
        cur = out.setdefault(key, {})
        for b, tid in ex.items():
            cur[int(b)] = str(tid)
    return out


def slo_status() -> Dict[str, Any]:
    """The SLO engine's rule table: per-rule current value, fast/slow
    burn rates, firing state — plus the aggregate ``healthy`` bit the
    autoscaler consumes (same shape as /api/slo)."""
    return _node_rpc(_head_sock(), "slo_status")


def tsdb_overview(window_s: float = 60.0) -> List[Dict[str, Any]]:
    """One judged row per metric family over the window (what `rtpu top`
    renders): counters as rates, histograms as rate+p50/p90, gauges as
    latest/mean."""
    return _node_rpc(_head_sock(), "tsdb_overview",
                     {"window_s": window_s})


def record_profile(duration: float = 5.0, hz: float = 99.0,
                   profile_id: Optional[str] = None,
                   ) -> Optional[Dict[str, Any]]:
    """Record a high-rate CPU profile of the whole cluster for
    ``duration`` seconds and return it assembled (see
    :func:`get_profile`).  Every node's scheduler fans the start/stop to
    its workers over their profiler control channels, so busy workers are
    captured mid-task — which is the point."""
    import os as os_mod
    import time as time_mod

    if profile_id is None:
        profile_id = f"prof-{os_mod.urandom(4).hex()}"
    nodes = _alive_nodes()
    for n in nodes:
        try:
            _node_rpc(n["sched_socket"], "profile_start",
                      {"profile_id": profile_id, "hz": hz})
        except (OSError, RuntimeError):
            continue
    time_mod.sleep(duration)
    for n in nodes:
        try:
            _node_rpc(n["sched_socket"], "profile_stop",
                      {"profile_id": profile_id})
        except (OSError, RuntimeError):
            continue
    return get_profile(profile_id)


def dump_stacks(node_id: Optional[str] = None) -> List[Dict[str, Any]]:
    """Live thread stacks of every runtime process (scheduler/driver +
    workers), per node — what `rtpu stack` prints.  ``node_id`` (hex)
    restricts to one node."""
    out: List[dict] = []
    for n in _alive_nodes():
        nid = n["node_id"].hex()
        if node_id is not None and nid != node_id:
            continue
        try:
            entries = _node_rpc(n["sched_socket"], "profile_dump")
        except (OSError, RuntimeError):
            continue
        for e in entries:
            e["node_id"] = nid
        out.extend(entries)
    return out


def list_logs(node_id: Optional[str] = None) -> List[Dict[str, Any]]:
    """Worker log files on one node (reference: ray.util.state.list_logs
    served by the node's dashboard agent; here the node's scheduler plays
    the agent).  node_id is the hex id; None = the local/driver node."""
    if node_id is None:
        return _rpc("list_logs")
    for n in _rpc("list_nodes"):
        nid = n["node_id"].hex() if isinstance(n["node_id"], bytes) \
            else n["node_id"]
        if nid == node_id and n.get("alive", True):
            return _node_rpc(n["sched_socket"], "list_logs")
    raise ValueError(f"no alive node {node_id}")


def get_log(filename: str, node_id: Optional[str] = None,
            tail: int = 200) -> List[str]:
    """Tail one worker log file (reference: ray.util.state.get_log)."""
    params = {"file": filename, "tail": tail}
    if node_id is None:
        out = _rpc("read_log", params)
    else:
        out = None
        for n in _rpc("list_nodes"):
            nid = n["node_id"].hex() if isinstance(n["node_id"], bytes) \
                else n["node_id"]
            if nid == node_id and n.get("alive", True):
                out = _node_rpc(n["sched_socket"], "read_log", params)
                break
        if out is None:
            raise ValueError(f"no alive node {node_id}")
    if out.get("error"):
        raise FileNotFoundError(out["error"])
    return out["lines"]


def list_data_jobs() -> List[Dict[str, Any]]:
    """Status snapshots of every registered data-service job (reference
    shape: tf.data service dispatcher state).  Reads the coordinator's
    GCS KV snapshots, so it works from any driver — including ones that
    never touched the data service."""
    import json as _json

    out: List[Dict[str, Any]] = []
    for key in _rpc("kv_keys", {"namespace": "data_jobs"}) or []:
        blob = _rpc("kv_get", {"namespace": "data_jobs",
                               "key": bytes(key)})
        if blob is None:
            continue
        try:
            out.append(_json.loads(bytes(blob).decode()))
        except (ValueError, UnicodeDecodeError):
            continue
    return sorted(out, key=lambda j: j.get("name", ""))


def serve_routing_stats() -> List[Dict[str, Any]]:
    """Per-deployment request-routing snapshots (policy, replica queue
    depths, engine page/prefix-cache stats) published by the Serve
    controller's stats lane to the GCS KV (namespace serve_routing) —
    readable from any driver, like list_data_jobs."""
    import json as _json

    out: List[Dict[str, Any]] = []
    for key in _rpc("kv_keys", {"namespace": "serve_routing"}) or []:
        blob = _rpc("kv_get", {"namespace": "serve_routing",
                               "key": bytes(key)})
        if blob is None:
            continue
        try:
            out.append(_json.loads(bytes(blob).decode()))
        except (ValueError, UnicodeDecodeError):
            continue
    return sorted(out, key=lambda d: (d.get("app", ""),
                                      d.get("deployment", "")))
