"""State API: unified cluster introspection.

Counterpart of /root/reference/python/ray/util/state/api.py:110
(list_actors/list_tasks/list_nodes/list_objects/list_placement_groups,
summarize_tasks/actors) aggregating GCS tables + per-node scheduler
task-event logs, the way the reference's state aggregator combines GCS and
raylet sources (dashboard/state_aggregator.py).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

from ray_tpu._private import protocol
from ray_tpu._private.worker import global_worker


def _rpc(method: str, params: Optional[dict] = None):
    return global_worker().rpc(method, params or {})


def _node_rpc(sched_socket: str, method: str, params: Optional[dict] = None):
    """One-shot rpc against a specific node's scheduler."""
    conn = protocol.connect_addr(sched_socket)
    try:
        conn.send({"t": "rpc", "method": method, "params": params or {}})
        resp = conn.recv()
    finally:
        conn.close()
    if resp is None or not resp.get("ok"):
        raise RuntimeError(f"state rpc {method} failed: "
                           f"{resp.get('error') if resp else 'closed'}")
    return resp["result"]


def list_nodes() -> List[Dict[str, Any]]:
    return [{"node_id": n["node_id"].hex(), "alive": n["alive"],
             "is_head": n["is_head"], "resources": n["resources"],
             "available": n["available"]}
            for n in _rpc("list_nodes")]


def list_actors(detail: bool = False) -> List[Dict[str, Any]]:
    out = []
    for a in _rpc("list_actors"):
        row = {"actor_id": a["actor_id"].hex(), "state": a["state"],
               "class_name": a["class_name"], "name": a["name"],
               "node_id": a["node_id"].hex() if a["node_id"] else None}
        if detail:
            row.update(num_restarts=a["num_restarts"],
                       max_restarts=a["max_restarts"],
                       death_cause=a["death_cause"])
        out.append(row)
    return out


def _all_task_events() -> List[dict]:
    events: List[dict] = []
    for n in _rpc("list_nodes"):
        if not n["alive"]:
            continue
        try:
            evs = _node_rpc(n["sched_socket"], "list_task_events")
        except (OSError, RuntimeError):
            continue
        for e in evs:
            e["node_id"] = n["node_id"]
        events.extend(evs)
    return events


def list_tasks(filters: Optional[list] = None) -> List[Dict[str, Any]]:
    """One row per task event; filters are (key, '=', value) triples on
    the rendered rows (reference: list_tasks filter syntax subset).
    FORWARDED entries (a node handing a spec to a peer) are dropped — the
    executing node's row is the real lifecycle."""
    rows = []
    for e in _all_task_events():
        if e["state"] == "FORWARDED":
            continue
        rows.append({
            "task_id": e["task_id"].hex(),
            "name": e["name"],
            "type": e["kind"].upper(),
            "state": e["state"],
            "node_id": e["node_id"].hex(),
            "worker_id": e["worker_id"].hex() if e["worker_id"] else None,
            "actor_id": e["actor_id"].hex() if e["actor_id"] else None,
            "submitted_ts": e["submitted_ts"],
            "start_ts": e["start_ts"],
            "end_ts": e["end_ts"],
        })
    for key, op, value in (filters or ()):
        if op != "=":
            raise ValueError(f"unsupported filter op {op!r}")
        rows = [r for r in rows if r.get(key) == value]
    return rows


def list_objects() -> List[Dict[str, Any]]:
    locs = _rpc("list_object_locations")
    return [{"object_id": oid.hex(),
             "locations": [n.hex() for n in nodes]}
            for oid, nodes in locs.items()]


def list_placement_groups() -> List[Dict[str, Any]]:
    table = _rpc("pg_table")
    rows = []
    for pg_id, info in table.items():
        row = {"placement_group_id": pg_id.hex(), **info}
        # node IDs hex like every other row in this module (JSON-safe)
        if "assignment" in row:
            row["assignment"] = [
                n.hex() if isinstance(n, bytes) else n
                for n in row["assignment"]]
        rows.append(row)
    return rows


def summarize_events(events: List[dict]) -> Dict[str, Dict[str, int]]:
    """name -> state -> count over raw task events (shared with the CLI)."""
    summary: Dict[str, Dict[str, int]] = {}
    for e in events:
        if e["state"] == "FORWARDED":
            continue
        by_state = summary.setdefault(e["name"], {})
        by_state[e["state"]] = by_state.get(e["state"], 0) + 1
    return summary


def summarize_tasks() -> Dict[str, Any]:
    summary = summarize_events(_all_task_events())
    return {"cluster": {"summary": summary,
                        "total_tasks": sum(sum(v.values())
                                           for v in summary.values())}}


def summarize_actors() -> Dict[str, Any]:
    summary: Dict[str, Dict[str, int]] = {}
    for row in list_actors():
        by_state = summary.setdefault(row["class_name"], {})
        by_state[row["state"]] = by_state.get(row["state"], 0) + 1
    return {"cluster": {"summary": summary,
                        "total_actors": sum(sum(v.values())
                                            for v in summary.values())}}


def events_to_chrome_trace(events: List[dict]) -> List[dict]:
    """Raw task events -> chrome://tracing 'X' events (shared with CLI)."""
    import time as time_mod

    trace = []
    for e in events:
        if e["start_ts"] is None or e["state"] == "FORWARDED":
            continue
        end = e["end_ts"] or time_mod.time()
        trace.append({
            "name": e["name"],
            "cat": e["kind"],
            "ph": "X",
            "ts": e["start_ts"] * 1e6,
            "dur": (end - e["start_ts"]) * 1e6,
            "pid": e["node_id"].hex()[:8],
            "tid": e["worker_id"].hex()[:8] if e["worker_id"] else "?",
            "args": {"state": e["state"]},
        })
    return trace


def timeline(filename: Optional[str] = None) -> List[dict]:
    """Chrome-trace events for all finished/running tasks (reference:
    `ray timeline` via GcsTaskManager, scripts.py:2689).  Load the output
    in chrome://tracing or Perfetto."""
    events = events_to_chrome_trace(_all_task_events())
    if filename:
        import json

        with open(filename, "w") as f:
            json.dump(events, f)
    return events


def _all_trace_spans(trace_id: str) -> List[dict]:
    """Fan one trace's spans in from every alive node's scheduler (each
    node only holds spans its own workers/driver flushed)."""
    spans: List[dict] = []
    for n in _rpc("list_nodes"):
        if not n["alive"]:
            continue
        try:
            spans.extend(_node_rpc(n["sched_socket"], "get_trace_spans",
                                   {"trace_id": trace_id}))
        except (OSError, RuntimeError):
            continue
    return spans


def get_trace(trace_id) -> Dict[str, Any]:
    """Assemble one distributed trace cluster-wide: the span tree across
    every process it touched plus a critical-path summary (queue-wait vs.
    arg-fetch vs. run seconds per span).  ``trace_id`` is the hex string
    from ``Span.trace_id`` (bytes accepted).  Pass the result to
    ``tracing.export_trace_chrome_trace`` for a Perfetto view with
    cross-process flow arrows."""
    from ray_tpu.util import tracing

    if isinstance(trace_id, bytes):
        trace_id = trace_id.hex()
    # driver-side spans may still sit in the local buffer: flush first so
    # the root of a just-finished workload is part of the answer
    tracing.flush_spans()
    return tracing.assemble_trace(trace_id, _all_trace_spans(trace_id))


def list_traces() -> List[Dict[str, Any]]:
    """Known traces cluster-wide, most recent last_ts first."""
    from ray_tpu.util import tracing

    tracing.flush_spans()
    rows: Dict[str, dict] = {}
    for n in _rpc("list_nodes"):
        if not n["alive"]:
            continue
        try:
            node_rows = _node_rpc(n["sched_socket"], "list_traces")
        except (OSError, RuntimeError):
            continue
        for r in node_rows:
            agg = rows.get(r["trace_id"])
            if agg is None:
                rows[r["trace_id"]] = dict(r)
            else:
                agg["num_spans"] += r["num_spans"]
                agg["first_ts"] = min(agg["first_ts"], r["first_ts"])
                agg["last_ts"] = max(agg["last_ts"], r["last_ts"])
                if not agg.get("root"):
                    agg["root"] = r.get("root")
    return sorted(rows.values(), key=lambda r: r["last_ts"], reverse=True)


def _alive_nodes() -> List[dict]:
    return [n for n in _rpc("list_nodes") if n["alive"]]


def list_profiles() -> List[Dict[str, Any]]:
    """Known CPU profiles cluster-wide (always-on "continuous" plus any
    on-demand captures), most recent first, with the task names each
    profile attributed samples to."""
    from ray_tpu._private import profiling

    rows: List[dict] = []
    for n in _alive_nodes():
        try:
            rows.extend(_node_rpc(n["sched_socket"], "list_profiles"))
        except (OSError, RuntimeError):
            continue
    return profiling.merge_profile_rows(rows)


def get_profile(profile_id: str) -> Optional[Dict[str, Any]]:
    """Assemble one profile cluster-wide: folded stacks merged across
    every node, grouped by (task name, trace id).  Pass the result to
    ``profiling.profile_to_speedscope`` / ``profile_to_folded`` for
    flamegraph export, or fetch it rendered from the dashboard's
    ``/api/profile?id=...``."""
    from ray_tpu._private import profiling

    parts = []
    for n in _alive_nodes():
        try:
            parts.append(_node_rpc(n["sched_socket"], "get_profile",
                                   {"profile_id": profile_id}))
        except (OSError, RuntimeError):
            continue
    return profiling.merge_profiles(parts)


def list_goodput() -> List[Dict[str, Any]]:
    """Goodput/step-anatomy summary rows cluster-wide (one per run per
    reporting process), newest first.  Flushes the driver's own tracker
    first so a just-finished loop is part of the answer."""
    from ray_tpu.util import goodput

    goodput.flush_current()
    rows: List[dict] = []
    for n in _alive_nodes():
        try:
            rows.extend(_node_rpc(n["sched_socket"], "list_goodput"))
        except (OSError, RuntimeError):
            continue
    return goodput.merge_goodput_rows(rows)


def get_goodput(run: str) -> Optional[Dict[str, Any]]:
    """Assemble one run's goodput records cluster-wide: per-process
    records plus a merged summary whose badput buckets sum to elapsed
    wall time (see util/goodput.py for the bucket definitions)."""
    from ray_tpu.util import goodput

    goodput.flush_current()
    records: List[dict] = []
    for n in _alive_nodes():
        try:
            records.extend(_node_rpc(n["sched_socket"], "get_goodput",
                                     {"run": run}))
        except (OSError, RuntimeError):
            continue
    return goodput.merge_records(records)


def record_profile(duration: float = 5.0, hz: float = 99.0,
                   profile_id: Optional[str] = None,
                   ) -> Optional[Dict[str, Any]]:
    """Record a high-rate CPU profile of the whole cluster for
    ``duration`` seconds and return it assembled (see
    :func:`get_profile`).  Every node's scheduler fans the start/stop to
    its workers over their profiler control channels, so busy workers are
    captured mid-task — which is the point."""
    import os as os_mod
    import time as time_mod

    if profile_id is None:
        profile_id = f"prof-{os_mod.urandom(4).hex()}"
    nodes = _alive_nodes()
    for n in nodes:
        try:
            _node_rpc(n["sched_socket"], "profile_start",
                      {"profile_id": profile_id, "hz": hz})
        except (OSError, RuntimeError):
            continue
    time_mod.sleep(duration)
    for n in nodes:
        try:
            _node_rpc(n["sched_socket"], "profile_stop",
                      {"profile_id": profile_id})
        except (OSError, RuntimeError):
            continue
    return get_profile(profile_id)


def dump_stacks(node_id: Optional[str] = None) -> List[Dict[str, Any]]:
    """Live thread stacks of every runtime process (scheduler/driver +
    workers), per node — what `rtpu stack` prints.  ``node_id`` (hex)
    restricts to one node."""
    out: List[dict] = []
    for n in _alive_nodes():
        nid = n["node_id"].hex()
        if node_id is not None and nid != node_id:
            continue
        try:
            entries = _node_rpc(n["sched_socket"], "profile_dump")
        except (OSError, RuntimeError):
            continue
        for e in entries:
            e["node_id"] = nid
        out.extend(entries)
    return out


def list_logs(node_id: Optional[str] = None) -> List[Dict[str, Any]]:
    """Worker log files on one node (reference: ray.util.state.list_logs
    served by the node's dashboard agent; here the node's scheduler plays
    the agent).  node_id is the hex id; None = the local/driver node."""
    if node_id is None:
        return _rpc("list_logs")
    for n in _rpc("list_nodes"):
        nid = n["node_id"].hex() if isinstance(n["node_id"], bytes) \
            else n["node_id"]
        if nid == node_id and n.get("alive", True):
            return _node_rpc(n["sched_socket"], "list_logs")
    raise ValueError(f"no alive node {node_id}")


def get_log(filename: str, node_id: Optional[str] = None,
            tail: int = 200) -> List[str]:
    """Tail one worker log file (reference: ray.util.state.get_log)."""
    params = {"file": filename, "tail": tail}
    if node_id is None:
        out = _rpc("read_log", params)
    else:
        out = None
        for n in _rpc("list_nodes"):
            nid = n["node_id"].hex() if isinstance(n["node_id"], bytes) \
                else n["node_id"]
            if nid == node_id and n.get("alive", True):
                out = _node_rpc(n["sched_socket"], "read_log", params)
                break
        if out is None:
            raise ValueError(f"no alive node {node_id}")
    if out.get("error"):
        raise FileNotFoundError(out["error"])
    return out["lines"]


def list_data_jobs() -> List[Dict[str, Any]]:
    """Status snapshots of every registered data-service job (reference
    shape: tf.data service dispatcher state).  Reads the coordinator's
    GCS KV snapshots, so it works from any driver — including ones that
    never touched the data service."""
    import json as _json

    out: List[Dict[str, Any]] = []
    for key in _rpc("kv_keys", {"namespace": "data_jobs"}) or []:
        blob = _rpc("kv_get", {"namespace": "data_jobs",
                               "key": bytes(key)})
        if blob is None:
            continue
        try:
            out.append(_json.loads(bytes(blob).decode()))
        except (ValueError, UnicodeDecodeError):
            continue
    return sorted(out, key=lambda j: j.get("name", ""))
