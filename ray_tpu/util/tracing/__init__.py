"""Distributed tracing: trace propagation, span collection, chrome export.

Counterpart of /root/reference/python/ray/util/tracing/tracing_helper.py
(OpenTelemetry monkey-patching of submission/execution) — redesigned on
the runtime's own planes.  A trace context (``trace_id``, parent
``span_id``) is minted at ``.remote()`` submission, rides the ``TaskSpec``
into the worker, and is re-established around task execution so nested
submissions and actor calls parent correctly: one driver call yields one
connected cross-process tree.  Completed spans flush to the node scheduler
over the control socket (same pattern as ``metrics_push``);
``ray_tpu.util.state.get_trace`` fans out over the cluster and calls
:func:`assemble_trace` here to build the tree plus a critical-path summary
(queue-wait vs. arg-fetch vs. run time).  :func:`trace_to_chrome_events`
emits chrome-trace flow events (``ph:"s"/"f"``) so Perfetto draws the
cross-process arrows.  An OpenTelemetry exporter hook stays import-gated.
"""

from __future__ import annotations

import contextlib
import json
import os
import random
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

_spans: List[Dict[str, Any]] = []
_lock = threading.Lock()
_enabled = False

# Spans carrying a trace id queue here until pushed to the node scheduler
# ("spans_push").  Bounded: tracing is observability, not ground truth.
_remote_buf: List[Dict[str, Any]] = []
_REMOTE_BUF_CAP = 50_000

_tls = threading.local()

_flusher_started = False
_flush_stop = threading.Event()
_flush_gen = 0


def enable_tracing() -> None:
    """Turn on app-span collection in this process.  Workers don't need
    this: a spec arriving with a trace context is traced regardless."""
    global _enabled
    _enabled = True


def disable_tracing() -> None:
    """Stop minting new root traces here (in-flight contexts still
    propagate; already-buffered spans still flush)."""
    global _enabled
    _enabled = False


def is_tracing_enabled() -> bool:
    return _enabled


def new_trace_id() -> str:
    return os.urandom(16).hex()


def new_span_id() -> str:
    return os.urandom(8).hex()


def current_context() -> Optional[Tuple[str, Optional[str]]]:
    """The calling thread's (trace_id, span_id), or None outside a trace."""
    return getattr(_tls, "ctx", None)


def attach_trace(spec) -> None:
    """Stamp a submission-side trace context onto a TaskSpec.

    Inside an active span (driver ``trace_span`` block or a traced task's
    execution) the spec inherits that context; otherwise, when tracing is
    enabled in this process, each ``.remote()`` mints a fresh root trace.
    The stamped fields pickle through every submission lane — scheduler
    conn, native raylet frames, nested 0x10 submits, direct actor calls.
    """
    ctx = getattr(_tls, "ctx", None)
    if ctx is None:
        if not _enabled:
            return
        ctx = (new_trace_id(), None)
    spec.trace_id, spec.parent_span_id = ctx
    spec.trace_submit_ts = time.time()


class Span:
    """Handle yielded by :func:`trace_span`: exposes the ids so callers can
    look the trace up later (``state.get_trace(span.trace_id)``).  Mutating
    ``attrs`` inside the block adds attributes resolved mid-span (e.g. the
    router's chosen replica) to the recorded span."""

    __slots__ = ("trace_id", "span_id", "parent_id", "name", "attrs")

    def __init__(self, trace_id: str, span_id: str,
                 parent_id: Optional[str], name: str):
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.name = name
        self.attrs: Dict[str, Any] = {}

    def __repr__(self):
        return f"Span({self.name!r}, trace_id={self.trace_id})"


def _record(rec: Dict[str, Any]) -> None:
    with _lock:
        _spans.append({
            "name": rec["name"], "ph": "X", "pid": rec["pid"],
            "tid": threading.get_ident() % 1_000_000,
            "ts": rec["start_ts"] * 1e6,
            "dur": (rec["end_ts"] - rec["start_ts"]) * 1e6,
            "args": dict(rec.get("args") or {},
                         **({"trace_id": rec["trace_id"],
                             "span_id": rec["span_id"]}
                            if rec.get("trace_id") else {})),
        })
        if rec.get("trace_id"):
            if len(_remote_buf) < _REMOTE_BUF_CAP:
                _remote_buf.append(rec)
    if rec.get("trace_id"):
        _ensure_flusher()


@contextlib.contextmanager
def trace_span(name: str, **attributes):
    """Record one span.  Yields a :class:`Span` when a trace is active
    (tracing enabled here, or running inside a traced task) so nested
    ``.remote()`` calls parent under it; yields None when tracing is off
    (the historical no-op behavior)."""
    ctx = getattr(_tls, "ctx", None)
    if not _enabled and ctx is None:
        yield None
        return
    trace_id = ctx[0] if ctx else new_trace_id()
    parent_id = ctx[1] if ctx else None
    span = Span(trace_id, new_span_id(), parent_id, name)
    _tls.ctx = (trace_id, span.span_id)
    t0 = time.time()
    try:
        yield span
    finally:
        _tls.ctx = ctx
        _record({
            "trace_id": trace_id, "span_id": span.span_id,
            "parent_id": parent_id, "name": name, "kind": "user",
            "pid": os.getpid(), "start_ts": t0, "end_ts": time.time(),
            "queue_wait_s": 0.0, "arg_fetch_s": 0.0,
            "run_s": time.time() - t0, "ok": True,
            "args": dict(attributes, **span.attrs),
        })


def sample_request() -> bool:
    """Head-sampling decision for a new serving root trace
    (``RTPU_TRACE_SAMPLE``, default 1.0).  Children of an existing trace
    always inherit — sampling happens only where roots are minted, so a
    sampled request is traced end to end and a dropped one costs nothing."""
    from ray_tpu._private import flags

    p = float(flags.get("RTPU_TRACE_SAMPLE"))
    if p >= 1.0:
        return True
    if p <= 0.0:
        return False
    return random.random() < p


@contextlib.contextmanager
def serving_span(name: str, **attributes):
    """Root entry point for a serving request (OpenAI server, P/D router).

    Unlike :func:`trace_span`, this mints a root even when tracing was
    never enabled in this process — serving anatomy should be on by
    default — but each new root passes the ``RTPU_TRACE_SAMPLE`` head
    sampler first.  Inside an existing trace it nests exactly like
    ``trace_span``; sampled-out requests yield None and record nothing.
    """
    ctx = getattr(_tls, "ctx", None)
    if ctx is None and not sample_request():
        yield None
        return
    with trace_span(name, **attributes) as span:
        if span is not None:
            yield span
            return
        # no ambient context and tracing disabled: mint the root ourselves
        trace_id, parent_id = new_trace_id(), None
        span = Span(trace_id, new_span_id(), parent_id, name)
        _tls.ctx = (trace_id, span.span_id)
        t0 = time.time()
        try:
            yield span
        finally:
            _tls.ctx = ctx
            _record({
                "trace_id": trace_id, "span_id": span.span_id,
                "parent_id": parent_id, "name": name, "kind": "user",
                "pid": os.getpid(), "start_ts": t0, "end_ts": time.time(),
                "queue_wait_s": 0.0, "arg_fetch_s": 0.0,
                "run_s": time.time() - t0, "ok": True,
                "args": dict(attributes, **span.attrs),
            })


@contextlib.contextmanager
def use_context(ctx: Optional[Tuple[str, Optional[str]]]):
    """Re-establish a captured ``(trace_id, span_id)`` context on this
    thread — for work handed across threads or processes (SSE generators,
    the P/D prefill→decode handoff) that should parent under the capture
    point rather than wherever it happens to run."""
    prev = getattr(_tls, "ctx", None)
    _tls.ctx = ctx
    try:
        yield
    finally:
        _tls.ctx = prev


def record_span(trace_id: str, name: str, start_ts: float, end_ts: float, *,
                parent_id: Optional[str] = None,
                span_id: Optional[str] = None, kind: str = "engine",
                ok: bool = True,
                attrs: Optional[Dict[str, Any]] = None) -> str:
    """Record a span with an explicit context instead of thread-local
    state.  The engine's scheduler thread interleaves many requests, so it
    carries each request's ``(trace_id, span_id)`` and stamps phase spans
    (queue, kv-pull, prefill, decode) here as they complete."""
    sid = span_id or new_span_id()
    _record({
        "trace_id": trace_id, "span_id": sid, "parent_id": parent_id,
        "name": name, "kind": kind, "pid": os.getpid(),
        "start_ts": start_ts, "end_ts": end_ts,
        "queue_wait_s": 0.0, "arg_fetch_s": 0.0,
        "run_s": max(0.0, end_ts - start_ts), "ok": ok,
        "args": dict(attrs or {}),
    })
    return sid


# ---------------------------------------------------------------------------
# built-in task-execution spans (worker_main drives these)

def begin_task_span(spec, start_ts: Optional[float] = None) -> Optional[dict]:
    """Open the built-in execution span for a traced TaskSpec: establishes
    the thread's trace context (so nested submissions parent here) and
    returns a token for :func:`end_task_span`.  None for untraced specs."""
    trace_id = getattr(spec, "trace_id", None)
    if not trace_id:
        return None
    token = {
        "trace_id": trace_id, "span_id": new_span_id(),
        "parent_id": getattr(spec, "parent_span_id", None),
        "name": spec.name or (spec.method_name or spec.kind),
        "kind": spec.kind, "pid": os.getpid(),
        "submit_ts": getattr(spec, "trace_submit_ts", 0.0) or None,
        "start_ts": start_ts if start_ts is not None else time.time(),
        "arg_fetch_s": 0.0,
        "prev_ctx": getattr(_tls, "ctx", None),
        "prev_token": getattr(_tls, "task_token", None),
    }
    _tls.ctx = (trace_id, token["span_id"])
    _tls.task_token = token
    return token


def note_arg_fetch(seconds: float) -> None:
    """Charge dependency-resolution time to the current task span."""
    token = getattr(_tls, "task_token", None)
    if token is not None:
        token["arg_fetch_s"] += seconds


def end_task_span(token: Optional[dict], ok: bool = True,
                  flush: bool = True) -> None:
    """Close a task-execution span, restore the previous context, and (by
    default) flush pending spans to the node scheduler right away so the
    trace is queryable as soon as the task finishes."""
    if token is None:
        return
    _tls.ctx = token.pop("prev_ctx")
    _tls.task_token = token.pop("prev_token")
    end_ts = time.time()
    start_ts = token.pop("start_ts")
    submit_ts = token.pop("submit_ts")
    arg_fetch = token.pop("arg_fetch_s")
    queue_wait = max(0.0, start_ts - submit_ts) if submit_ts else 0.0
    _record(dict(token, submit_ts=submit_ts, start_ts=start_ts,
                 end_ts=end_ts, ok=ok,
                 queue_wait_s=queue_wait, arg_fetch_s=arg_fetch,
                 run_s=max(0.0, (end_ts - start_ts) - arg_fetch),
                 args={}))
    if flush:
        flush_spans()


# ---------------------------------------------------------------------------
# flush plane: spans -> node scheduler ("spans_push", like metrics_push)

def flush_spans() -> int:
    """Push queued spans to the node scheduler; returns how many landed.
    Best-effort: on failure the batch re-queues for the next attempt."""
    with _lock:
        if not _remote_buf:
            return 0
        batch = list(_remote_buf)
        del _remote_buf[:]
    from ray_tpu._private import worker as worker_mod

    ctx = worker_mod.global_worker_or_none()
    if ctx is None:
        with _lock:
            _remote_buf[:0] = batch
        return 0
    try:
        ctx.rpc("spans_push", {"spans": batch})
        return len(batch)
    except Exception:
        with _lock:
            _remote_buf[:0] = batch[:_REMOTE_BUF_CAP - len(_remote_buf)]
        return 0


def _flush_interval() -> float:
    from ray_tpu._private import flags

    return max(0.25, float(flags.get("RTPU_METRICS_FLUSH_S")))


def _ensure_flusher() -> None:
    global _flusher_started, _flush_gen
    with _lock:
        if _flusher_started:
            return
        _flusher_started = True
        _flush_gen += 1
        gen = _flush_gen
        _flush_stop.clear()
    threading.Thread(target=_flush_loop, args=(gen,), name="trace-flush",
                     daemon=True).start()


def _flush_loop(gen: int) -> None:
    global _flusher_started
    while True:
        stopped = _flush_stop.wait(_flush_interval())
        with _lock:
            if gen != _flush_gen:
                return  # superseded by a newer flusher
            if stopped:
                _flusher_started = False
                return
        try:
            flush_spans()
        except Exception:
            pass


def shutdown_flusher(flush: bool = False) -> None:
    """Stop the background span flusher (clean worker/driver shutdown);
    optionally pushing one final batch first."""
    if flush:
        try:
            flush_spans()
        except Exception:
            pass
    _flush_stop.set()


# ---------------------------------------------------------------------------
# trace assembly + critical path (pure functions: state.py, the dashboard,
# and the CLI all share them; the latter two have no driver context)

def assemble_trace(trace_id: str, spans: List[dict]) -> dict:
    """Merge per-node span lists into one tree with a critical-path
    summary.  Tolerates duplicates (flush retries) and orphans (parent
    span not yet flushed: the child becomes a root)."""
    by_id: Dict[str, dict] = {}
    for s in spans:
        sid = s.get("span_id")
        if sid and sid not in by_id:
            by_id[sid] = s
    flat = sorted(by_id.values(), key=lambda s: s.get("start_ts") or 0.0)
    children: Dict[str, List[dict]] = {}
    roots: List[dict] = []
    for s in flat:
        pid = s.get("parent_id")
        if pid and pid in by_id:
            children.setdefault(pid, []).append(s)
        else:
            roots.append(s)

    def _node(s: dict) -> dict:
        return dict(s, children=[_node(c)
                                 for c in children.get(s["span_id"], ())])

    tree = [_node(r) for r in roots]

    critical: List[dict] = []
    if flat:
        cur = max(roots, key=lambda s: s.get("end_ts") or 0.0)
        while cur is not None:
            critical.append(cur)
            kids = children.get(cur["span_id"])
            cur = max(kids, key=lambda s: s.get("end_ts") or 0.0) \
                if kids else None

    def _tot(key: str) -> float:
        return sum(s.get(key) or 0.0 for s in critical)

    summary = {
        "trace_id": trace_id,
        "num_spans": len(flat),
        "num_processes": len({(s.get("node"), s.get("pid")) for s in flat}),
        "wall_s": (max(s.get("end_ts") or 0.0 for s in flat)
                   - min(s.get("start_ts") or 0.0 for s in flat))
        if flat else 0.0,
        "queue_wait_s": _tot("queue_wait_s"),
        "arg_fetch_s": _tot("arg_fetch_s"),
        "run_s": _tot("run_s"),
        "critical_path": [{
            "name": s.get("name"), "span_id": s.get("span_id"),
            "kind": s.get("kind"), "node": s.get("node"),
            "pid": s.get("pid"),
            "dur_s": (s.get("end_ts") or 0.0) - (s.get("start_ts") or 0.0),
            "queue_wait_s": s.get("queue_wait_s") or 0.0,
            "arg_fetch_s": s.get("arg_fetch_s") or 0.0,
            "run_s": s.get("run_s") or 0.0,
        } for s in critical],
    }
    return {"trace_id": trace_id, "spans": flat, "tree": tree,
            "summary": summary}


def trace_to_chrome_events(spans: List[dict]) -> List[dict]:
    """Chrome-trace events for one trace: an "X" slice per span grouped by
    (node, pid), plus flow events (``ph:"s"/"f"``) wherever a child span
    runs in a different process than its parent — Perfetto renders those
    as cross-process arrows."""
    by_id = {s["span_id"]: s for s in spans if s.get("span_id")}
    events: List[dict] = []

    def _proc(s: dict) -> str:
        node = s.get("node") or "?"
        return f"{str(node)[:8]}/pid{s.get('pid')}"

    for s in by_id.values():
        start = s.get("start_ts") or 0.0
        end = s.get("end_ts") or start
        events.append({
            "name": s.get("name"), "cat": s.get("kind") or "span",
            "ph": "X", "pid": _proc(s), "tid": s.get("pid") or 0,
            "ts": start * 1e6, "dur": max(end - start, 1e-6) * 1e6,
            "args": {
                "span_id": s.get("span_id"),
                "parent_id": s.get("parent_id"),
                "queue_wait_s": s.get("queue_wait_s"),
                "arg_fetch_s": s.get("arg_fetch_s"),
                "run_s": s.get("run_s"), "ok": s.get("ok"),
            },
        })
        parent = by_id.get(s.get("parent_id") or "")
        if parent is None:
            continue
        if (parent.get("node"), parent.get("pid")) == \
                (s.get("node"), s.get("pid")):
            continue
        flow_id = int(s["span_id"][:8], 16)
        p_start = parent.get("start_ts") or 0.0
        p_end = parent.get("end_ts") or p_start
        s_ts = min(max(s.get("submit_ts") or start, p_start), p_end)
        events.append({"name": "submit", "cat": "flow", "ph": "s",
                       "id": flow_id, "pid": _proc(parent),
                       "tid": parent.get("pid") or 0, "ts": s_ts * 1e6})
        events.append({"name": "submit", "cat": "flow", "ph": "f",
                       "bp": "e", "id": flow_id, "pid": _proc(s),
                       "tid": s.get("pid") or 0, "ts": start * 1e6})
    events.sort(key=lambda e: e["ts"])
    return events


def export_trace_chrome_trace(trace: dict, path: str) -> int:
    """Write an assembled trace (from ``state.get_trace``) as a chrome
    trace with cross-process flow arrows; returns the event count."""
    events = trace_to_chrome_events(trace.get("spans") or [])
    with open(path, "w") as f:
        json.dump({"traceEvents": events}, f)
    return len(events)


# ---------------------------------------------------------------------------
# process-local exports (historical API)

def collected_spans() -> List[Dict[str, Any]]:
    with _lock:
        return list(_spans)


def export_chrome_trace(path: str, include_task_events: bool = True) -> int:
    """Write collected spans (+ the cluster task timeline) as a chrome
    trace; returns the event count. Open in chrome://tracing or Perfetto."""
    events = collected_spans()
    if include_task_events:
        try:
            from ray_tpu._private.worker import global_worker

            for e in global_worker().rpc("list_task_events", {}):
                # FORWARDED is a hand-off record on the forwarding node;
                # the executing node logs the same task again — skip, as
                # state.events_to_chrome_trace does, or every spilled task
                # shows up twice.
                if e.get("state") == "FORWARDED":
                    continue
                if e.get("start_ts") and e.get("end_ts"):
                    events.append({
                        "name": e["name"], "ph": "X", "pid": 1,
                        "tid": int.from_bytes(
                            e["task_id"][:4], "little") % 1_000_000,
                        "ts": e["start_ts"] * 1e6,
                        "dur": (e["end_ts"] - e["start_ts"]) * 1e6,
                        "args": {"state": e["state"]},
                    })
        except Exception:
            pass
    with open(path, "w") as f:
        json.dump({"traceEvents": events}, f)
    return len(events)


def export_otel_spans(tracer=None):
    """Replay collected spans into an OpenTelemetry tracer (import-gated
    like the reference's exporters, tracing_helper.py): each recorded span
    becomes an OTel span with its original timestamps and attributes.
    Returns the number of spans exported.  Without the opentelemetry
    package use export_chrome_trace() for local inspection."""
    try:
        from opentelemetry import trace as otel_trace
    except ImportError as e:
        raise ImportError(
            "opentelemetry is not in the TPU image; use "
            "export_chrome_trace() for local trace inspection") from e
    if tracer is None:
        provider = otel_trace.get_tracer_provider()
        if type(provider).__name__ in ("NoOpTracerProvider",
                                       "ProxyTracerProvider"):
            # no SDK configured: spans would be NonRecording and silently
            # vanish — misreporting them as exported helps nobody
            raise RuntimeError(
                "no OpenTelemetry TracerProvider is configured; call "
                "opentelemetry.trace.set_tracer_provider(...) first or "
                "pass an explicit tracer")
        tracer = otel_trace.get_tracer("ray_tpu")
    spans = collected_spans()
    for s in spans:
        start_ns = int(s["ts"] * 1e3)  # recorded in microseconds
        end_ns = int((s["ts"] + s["dur"]) * 1e3)
        span = tracer.start_span(s["name"], start_time=start_ns)
        for k, v in (s.get("args") or {}).items():
            # OTel silently drops non-primitive values (set_attribute
            # never raises): sanitize up front so nothing vanishes
            span.set_attribute(
                str(k), v if isinstance(v, (bool, str, int, float))
                else repr(v))
        span.end(end_time=end_ns)
    return len(spans)
