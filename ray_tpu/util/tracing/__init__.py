"""Task/actor tracing: span propagation + chrome-trace export.

Counterpart of /root/reference/python/ray/util/tracing/tracing_helper.py
(OpenTelemetry monkey-patching of submission/execution) — redesigned on
the runtime's own task-event timeline: every task already records
submitted/running/finished timestamps in the per-node scheduler
(ray timeline parity lives in scripts/cli.py `timeline`). This module adds
app-level spans: ``with trace_span("name"):`` records into the same
chrome-trace stream, and an OpenTelemetry exporter hook is import-gated.
"""

from __future__ import annotations

import contextlib
import json
import os
import threading
import time
from typing import Any, Dict, List, Optional

_spans: List[Dict[str, Any]] = []
_lock = threading.Lock()
_enabled = False


def enable_tracing() -> None:
    """Turn on app-span collection in this process."""
    global _enabled
    _enabled = True


def is_tracing_enabled() -> bool:
    return _enabled


@contextlib.contextmanager
def trace_span(name: str, **attributes):
    """Record one span (chrome-trace "X" event) if tracing is enabled."""
    if not _enabled:
        yield
        return
    t0 = time.time()
    try:
        yield
    finally:
        with _lock:
            _spans.append({
                "name": name, "ph": "X", "pid": os.getpid(),
                "tid": threading.get_ident() % 1_000_000,
                "ts": t0 * 1e6, "dur": (time.time() - t0) * 1e6,
                "args": attributes,
            })


def collected_spans() -> List[Dict[str, Any]]:
    with _lock:
        return list(_spans)


def export_chrome_trace(path: str, include_task_events: bool = True) -> int:
    """Write collected spans (+ the cluster task timeline) as a chrome
    trace; returns the event count. Open in chrome://tracing or Perfetto."""
    events = collected_spans()
    if include_task_events:
        try:
            from ray_tpu._private.worker import global_worker

            for e in global_worker().rpc("list_task_events", {}):
                if e.get("start_ts") and e.get("end_ts"):
                    events.append({
                        "name": e["name"], "ph": "X", "pid": 1,
                        "tid": int.from_bytes(
                            e["task_id"][:4], "little") % 1_000_000,
                        "ts": e["start_ts"] * 1e6,
                        "dur": (e["end_ts"] - e["start_ts"]) * 1e6,
                        "args": {"state": e["state"]},
                    })
        except Exception:
            pass
    with open(path, "w") as f:
        json.dump({"traceEvents": events}, f)
    return len(events)


def export_otel_spans(tracer=None):
    """Replay collected spans into an OpenTelemetry tracer (import-gated
    like the reference's exporters, tracing_helper.py): each recorded span
    becomes an OTel span with its original timestamps and attributes.
    Returns the number of spans exported.  Without the opentelemetry
    package use export_chrome_trace() for local inspection."""
    try:
        from opentelemetry import trace as otel_trace
    except ImportError as e:
        raise ImportError(
            "opentelemetry is not in the TPU image; use "
            "export_chrome_trace() for local trace inspection") from e
    if tracer is None:
        provider = otel_trace.get_tracer_provider()
        if type(provider).__name__ in ("NoOpTracerProvider",
                                       "ProxyTracerProvider"):
            # no SDK configured: spans would be NonRecording and silently
            # vanish — misreporting them as exported helps nobody
            raise RuntimeError(
                "no OpenTelemetry TracerProvider is configured; call "
                "opentelemetry.trace.set_tracer_provider(...) first or "
                "pass an explicit tracer")
        tracer = otel_trace.get_tracer("ray_tpu")
    spans = collected_spans()
    for s in spans:
        start_ns = int(s["ts"] * 1e3)  # recorded in microseconds
        end_ns = int((s["ts"] + s["dur"]) * 1e3)
        span = tracer.start_span(s["name"], start_time=start_ns)
        for k, v in (s.get("args") or {}).items():
            # OTel silently drops non-primitive values (set_attribute
            # never raises): sanitize up front so nothing vanishes
            span.set_attribute(
                str(k), v if isinstance(v, (bool, str, int, float))
                else repr(v))
        span.end(end_time=end_ns)
    return len(spans)
