"""Structured event export for external consumers.

Counterpart of the reference's export-event pipeline
(/root/reference/python/ray/_private/event/export_event_logger.py + the
export_*.proto schemas): when enabled, cluster lifecycle events stream to
JSONL files an external system can tail — one record per line, stable
``type``/``ts``/``data`` envelope.

Enable by pointing ``RTPU_EXPORT_EVENTS`` at a directory (the head node
starts the exporter).  Four files are written there:

- ``actor_events.jsonl``   — every actor state transition (from GCS pubsub)
- ``node_events.jsonl``    — node alive/dead transitions
- ``task_events.jsonl``    — task lifecycle records (exported by each
  node's scheduler as tasks finish)
- ``cluster_events.jsonl`` — the cluster event plane (below): the file
  exporter is ONE SUBSCRIBER of that plane (the scheduler forwards every
  banked event here), not a parallel path

Cluster event plane
-------------------
``emit()`` records a structured in-cluster incident — store-daemon
restarts, replica deaths, KV tier pulls/fallbacks, spill decisions,
preemptions, data-worker scale actions, every ``RTPU_TESTING_*`` chaos
injection — stamped with the current trace id when one is attached, so
incidents link into the trace tree.  Records buffer process-locally and a
background flusher pushes them to the node scheduler over the control
socket ("events_push", the incident lane next to metrics_push/spans_push/
goodput_push); the scheduler banks them in a capped ring
(``RTPU_EVENTS_CAP``) that ``rtpu events`` / ``state.list_events`` /
``/api/events`` read and the head's sampler drains.  Severity "error"/
"critical" (and ``flush=True`` — chaos sites that ``os._exit``) push
synchronously so the incident survives the process it describes.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Optional


class ExportEventLogger:
    """Exporter for one node.  Every node exports its scheduler's task
    events (enqueued, written by a dedicated thread — the sink is called
    under the scheduler's lock and must not do file I/O there); the HEAD
    additionally subscribes to the GCS actor/node channels so those
    cluster-wide transitions are written exactly once."""

    def __init__(self, out_dir: str, gcs_address: str,
                 subscribe: bool = True):
        import queue as queue_mod

        self.out_dir = out_dir
        os.makedirs(out_dir, exist_ok=True)
        self._gcs_address = gcs_address
        self._stop = threading.Event()
        self._files: dict[str, object] = {}
        self._lock = threading.Lock()
        self._queue: "queue_mod.Queue" = queue_mod.Queue()
        self._writer = threading.Thread(
            target=self._writer_loop, name="event-export-writer",
            daemon=True)
        self._writer.start()
        self._sub_thread = None
        if subscribe:
            self._sub_thread = threading.Thread(
                target=self._subscribe_loop, name="event-export-sub",
                daemon=True)
            self._sub_thread.start()

    def _write(self, stream: str, record: dict):
        """Serialize + append one record (writer/subscriber threads only).
        Unbuffered O_APPEND binary writes: one write(2) per line of ANY
        size, so concurrent exporters appending to the same file
        (multi-node, shared fs) stay line-atomic — a buffered text file
        would split records beyond its buffer into interleavable chunks."""
        line = json.dumps({"type": stream, "ts": time.time(),
                           "data": record}, default=_jsonable)
        with self._lock:
            f = self._files.get(stream)
            if f is None:
                f = open(os.path.join(self.out_dir,
                                      f"{stream}_events.jsonl"), "ab",
                         buffering=0)
                self._files[stream] = f
            f.write((line + "\n").encode())

    def export_task_event(self, record: dict):
        """Called by the scheduler (under its lock): enqueue only."""
        self._queue.put(("task", record))

    def export_cluster_event(self, record: dict):
        """Cluster-event-plane subscription (scheduler bank_events):
        enqueue only — the bank is called from RPC reader threads."""
        self._queue.put(("cluster", record))

    def _writer_loop(self):
        import queue as queue_mod

        while True:
            try:
                stream, record = self._queue.get(timeout=0.5)
            except queue_mod.Empty:
                if self._stop.is_set():
                    return  # queue fully drained
                continue
            try:
                self._write(stream, record)
            except Exception:
                pass  # export is best-effort

    def _subscribe_loop(self):
        from ray_tpu._private.gcs import GcsClient, GcsSubscriber

        sub = None
        while not self._stop.is_set():
            try:
                if sub is None:
                    sub = GcsSubscriber(self._gcs_address,
                                        ["actors", "nodes"])
                events, gap = sub.poll(timeout_s=5.0)
            except Exception:
                sub = None
                if self._stop.wait(0.5):
                    return
                continue
            # write what we HAVE before any snapshot re-read can fail —
            # a dropped DEAD transition is exactly what consumers need
            # most during GCS blips
            for e in events:
                ch = e.get("ch")
                if ch == "actors":
                    self._write("actor", e)
                elif ch == "nodes":
                    self._write("node", e)
            if gap:
                # subscriber contract: a gap (including the bootstrap
                # poll) means re-read table state — transitions published
                # before we subscribed surface as snapshot records
                try:
                    client = GcsClient(self._gcs_address)
                    for n in client.list_nodes():
                        self._write("node", {
                            "ch": "nodes", "node_id": n.node_id,
                            "alive": n.alive, "snapshot": True})
                    for a in client.list_actors():
                        self._write("actor", {
                            "ch": "actors", "actor_id": a.actor_id,
                            "state": a.state, "addr": a.addr,
                            "snapshot": True})
                except Exception:
                    pass  # next gap retries the snapshot

    def shutdown(self):
        """Stop, DRAINING queued task events first — short-lived drivers
        must not lose their final FINISHED records."""
        self._stop.set()
        self._writer.join(timeout=5)
        with self._lock:
            for f in self._files.values():
                try:
                    f.close()
                except OSError:
                    pass
            self._files.clear()
        global _exporter
        if _exporter is self:
            _exporter = None


def _jsonable(obj):
    if isinstance(obj, bytes):
        return obj.hex()
    return str(obj)


_exporter: Optional[ExportEventLogger] = None


def start_exporter(gcs_address: str,
                   subscribe: bool = True) -> Optional[ExportEventLogger]:
    """Start this node's exporter when RTPU_EXPORT_EVENTS names a
    directory.  subscribe=True (the head) additionally streams GCS
    actor/node transitions; other nodes export only their own task
    events."""
    global _exporter
    out_dir = os.environ.get("RTPU_EXPORT_EVENTS")
    if not out_dir:
        return None
    logger = ExportEventLogger(out_dir, gcs_address, subscribe=subscribe)
    # The process-global fallback serves schedulers that predate per-node
    # wiring; the FIRST exporter (the head's, in in-process multi-node
    # clusters) keeps it — a later worker Node must not hijack the head's
    # task events, nor leave a dead exporter behind on its shutdown.
    if _exporter is None:
        _exporter = logger
    return logger


def get_exporter() -> Optional[ExportEventLogger]:
    return _exporter


# -- cluster event plane (events_push lane) ------------------------------

_EV_BUF_MAX = 512  # process-local backlog; oldest dropped past this
_ev_lock = threading.Lock()
_ev_buf: list[dict] = []
_ev_recent: dict[str, list] = {}  # kind -> [ts, record] for coalescing
_ev_flusher_started = False
_ev_flush_stop = threading.Event()
_ev_tls = threading.local()


def emit(kind: str, message: str = "", severity: str = "info",
         data: Optional[dict] = None, trace_id: Optional[str] = None,
         flush: bool = False, coalesce_s: float = 0.0) -> dict:
    """Record one structured cluster event (see module docstring).

    coalesce_s > 0 merges a repeat of the same kind arriving within the
    window into the buffered record's ``count`` instead of appending —
    hot emitters (spills, preemptions, chaos frame drops) must not flood
    the ring or the control socket.  flush=True (and severity error/
    critical) pushes synchronously; everything else rides the background
    flusher.  Best-effort by design: with no driver/worker context the
    record waits in the process buffer until the node scheduler drains it
    (list_events / sample tick) or the process dies.
    """
    now = time.time()
    if trace_id is None:
        try:
            from ray_tpu.util import tracing

            ctx = tracing.current_context()
            trace_id = ctx[0] if ctx else ""
        except Exception:
            trace_id = ""
    rec = {"ts": now, "kind": str(kind), "severity": str(severity),
           "message": str(message), "data": dict(data or {}),
           "pid": os.getpid(), "trace_id": trace_id or ""}
    with _ev_lock:
        if coalesce_s > 0:
            recent = _ev_recent.get(rec["kind"])
            if (recent is not None and now - recent[0] < coalesce_s
                    and recent[1].get("_buffered")):
                merged = recent[1]
                merged["data"]["count"] = merged["data"].get("count", 1) + 1
                merged["ts"] = now
                return merged
            _ev_recent[rec["kind"]] = [now, rec]
        rec["_buffered"] = True
        _ev_buf.append(rec)
        if len(_ev_buf) > _EV_BUF_MAX:
            dropped = _ev_buf[:len(_ev_buf) - _EV_BUF_MAX]
            del _ev_buf[:len(_ev_buf) - _EV_BUF_MAX]
            for r in dropped:
                r.pop("_buffered", None)
    _ensure_ev_flusher()
    if flush or severity in ("error", "critical"):
        flush_events()
    return rec


def flush_events() -> None:
    """Push buffered events to the node scheduler now (best-effort; no-op
    without a driver/worker context).  Reentrancy-guarded: the push itself
    may traverse chaos-instrumented transport code that emits."""
    if getattr(_ev_tls, "flushing", False):
        return
    from ray_tpu._private import worker as worker_mod

    ctx = worker_mod.global_worker_or_none()
    if ctx is None:
        return
    with _ev_lock:
        if not _ev_buf:
            return
        batch = list(_ev_buf)
        del _ev_buf[:]
        for r in batch:
            r.pop("_buffered", None)
    _ev_tls.flushing = True
    try:
        ctx.rpc("events_push", {"events": batch})
    except Exception:
        pass  # node shutting down; events are best-effort
    finally:
        _ev_tls.flushing = False


def take_buffered() -> list[dict]:
    """Drain the process-local buffer for direct banking — called by a
    scheduler running in a process WITHOUT a driver/worker context (a
    standalone `rtpu start` node), where no flusher can deliver.  With a
    context present this returns [] and the flusher keeps ownership."""
    from ray_tpu._private import worker as worker_mod

    if worker_mod.global_worker_or_none() is not None:
        return []
    with _ev_lock:
        batch = list(_ev_buf)
        del _ev_buf[:]
        for r in batch:
            r.pop("_buffered", None)
    return batch


def _ev_flush_interval() -> float:
    from ray_tpu._private import flags

    return max(0.25, float(flags.get("RTPU_METRICS_FLUSH_S")))


def _ensure_ev_flusher() -> None:
    global _ev_flusher_started
    with _ev_lock:
        if _ev_flusher_started:
            return
        _ev_flusher_started = True
    threading.Thread(target=_ev_flush_loop, name="events-flush",
                     daemon=True).start()


def _ev_flush_loop() -> None:
    while not _ev_flush_stop.wait(_ev_flush_interval()):
        try:
            flush_events()
        except Exception:
            pass
