"""ActorPool: operate on a fixed pool of actors.

Counterpart of /root/reference/python/ray/util/actor_pool.py:13 — same
surface (map, map_unordered, submit, get_next, get_next_unordered,
has_next, has_free, pop_idle, push).
"""

from __future__ import annotations

from typing import Any, Callable, Iterable, Iterator, List, Optional

import ray_tpu


class ActorPool:
    def __init__(self, actors: List[Any]):
        self._idle_actors = list(actors)
        self._future_to_actor: dict = {}  # ref -> (index, actor)
        self._index_to_future: dict = {}
        self._next_task_index = 0
        self._next_return_index = 0
        self._pending_submits: list = []
        # indices consumed out-of-order by get_next_unordered, so the
        # ordered getter can skip them instead of waiting forever
        self._consumed: set = set()

    def map(self, fn: Callable, values: Iterable) -> Iterator:
        """Apply fn(actor, value) over values; yield results IN ORDER."""
        for v in values:
            self.submit(fn, v)
        while self.has_next():
            yield self.get_next()

    def map_unordered(self, fn: Callable, values: Iterable) -> Iterator:
        """Like map, but yields results as they complete."""
        for v in values:
            self.submit(fn, v)
        while self.has_next():
            yield self.get_next_unordered()

    def submit(self, fn: Callable, value: Any):
        """Schedule fn(actor, value) on an idle actor (or queue it)."""
        if self._idle_actors:
            actor = self._idle_actors.pop()
            future = fn(actor, value)
            self._future_to_actor[future] = (self._next_task_index, actor)
            self._index_to_future[self._next_task_index] = future
            self._next_task_index += 1
        else:
            self._pending_submits.append((fn, value))

    def has_next(self) -> bool:
        return bool(self._future_to_actor) or bool(self._pending_submits)

    def get_next(self, timeout: Optional[float] = None) -> Any:
        """Next result in SUBMISSION order."""
        # skip indices already consumed by get_next_unordered
        while self._next_return_index in self._consumed:
            self._consumed.discard(self._next_return_index)
            self._next_return_index += 1
        if not self.has_next():
            raise StopIteration("no more results to get")
        index = self._next_return_index
        # the future may not exist yet (task still queued behind busy actors)
        import time

        deadline = None if timeout is None else time.monotonic() + timeout
        while index not in self._index_to_future:
            if deadline is not None and time.monotonic() >= deadline:
                raise TimeoutError("timed out waiting for result")
            self._drain_one(remaining(deadline))
        future = self._index_to_future.pop(index)
        self._next_return_index += 1
        # return the actor BEFORE get: a task that raised must not leave
        # its actor marked busy forever (reference does the same)
        actor = self._future_to_actor.pop(future)[1]
        self._return_actor(actor)
        return ray_tpu.get(future, timeout=remaining(deadline))

    def get_next_unordered(self, timeout: Optional[float] = None) -> Any:
        """Next result in COMPLETION order."""
        if not self.has_next():
            raise StopIteration("no more results to get")
        import time

        deadline = None if timeout is None else time.monotonic() + timeout
        while not self._future_to_actor:
            self._flush_pending()
        ready, _ = ray_tpu.wait(list(self._future_to_actor),
                                num_returns=1,
                                timeout=remaining(deadline))
        if not ready:
            raise TimeoutError("timed out waiting for result")
        future = ready[0]
        index, actor = self._future_to_actor.pop(future)
        self._index_to_future.pop(index, None)
        # keep ordered bookkeeping consistent for later get_next calls
        if index == self._next_return_index:
            self._next_return_index += 1
        else:
            self._consumed.add(index)
        self._return_actor(actor)
        return ray_tpu.get(future)

    def has_free(self) -> bool:
        return bool(self._idle_actors) and not self._pending_submits

    def pop_idle(self) -> Optional[Any]:
        if self.has_free():
            return self._idle_actors.pop()
        return None

    def push(self, actor: Any):
        busy = {a for _, a in self._future_to_actor.values()}
        if actor in self._idle_actors or actor in busy:
            raise ValueError("actor already belongs to this pool")
        self._return_actor(actor)

    # -- internals ---------------------------------------------------------
    def _return_actor(self, actor):
        self._idle_actors.append(actor)
        self._flush_pending()

    def _flush_pending(self):
        while self._pending_submits and self._idle_actors:
            fn, value = self._pending_submits.pop(0)
            self.submit(fn, value)

    def _drain_one(self, timeout):
        """Wait for ANY in-flight future so a busy actor frees up."""
        self._flush_pending()
        if not self._future_to_actor:
            return
        ready, _ = ray_tpu.wait(list(self._future_to_actor),
                                num_returns=1, timeout=timeout)


def remaining(deadline):
    if deadline is None:
        return None
    import time

    return max(0.0, deadline - time.monotonic())
