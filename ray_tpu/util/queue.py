"""Distributed Queue backed by an actor.

Counterpart of /root/reference/python/ray/util/queue.py:21 — same surface
(put/get with block+timeout, *_nowait, *_nowait_batch, qsize/empty/full,
shutdown).  The actor runs with max_concurrency so blocked getters don't
starve puts (the reference uses an asyncio actor for the same reason).
"""

from __future__ import annotations

import queue as _queue
from typing import Any, Iterable, List, Optional

import ray_tpu


class Empty(_queue.Empty):
    pass


class Full(_queue.Full):
    pass


class _QueueActor:
    """All methods are NON-blocking: a blocking wait inside the actor would
    pin one of its max_concurrency threads, and enough blocked getters
    would starve the puts that could wake them (permanent deadlock).  The
    CLIENT polls instead — the reference avoids the same hazard with an
    asyncio actor."""

    def __init__(self, maxsize: int):
        self._q: _queue.Queue = _queue.Queue(maxsize=maxsize)

    def qsize(self) -> int:
        return self._q.qsize()

    def empty(self) -> bool:
        return self._q.empty()

    def full(self) -> bool:
        return self._q.full()

    def put(self, item) -> bool:
        try:
            self._q.put_nowait(item)
            return True
        except _queue.Full:
            return False

    def put_batch(self, items: list) -> bool:
        if (self._q.maxsize > 0
                and self._q.qsize() + len(items) > self._q.maxsize):
            return False
        for item in items:
            self._q.put_nowait(item)
        return True

    def get(self):
        try:
            return True, self._q.get_nowait()
        except _queue.Empty:
            return False, None

    def get_batch(self, num_items: int):
        if self._q.qsize() < num_items:
            return False, None
        return True, [self._q.get_nowait() for _ in range(num_items)]


class Queue:
    def __init__(self, maxsize: int = 0,
                 actor_options: Optional[dict] = None):
        self.maxsize = maxsize
        opts = dict(actor_options or {})
        opts.setdefault("max_concurrency", 8)
        self.actor = ray_tpu.remote(_QueueActor).options(**opts).remote(
            maxsize)

    def __len__(self) -> int:
        return self.size()

    def size(self) -> int:
        return ray_tpu.get(self.actor.qsize.remote())

    def qsize(self) -> int:
        return self.size()

    def empty(self) -> bool:
        return ray_tpu.get(self.actor.empty.remote())

    def full(self) -> bool:
        return ray_tpu.get(self.actor.full.remote())

    def put(self, item: Any, block: bool = True,
            timeout: Optional[float] = None):
        if timeout is not None and timeout < 0:
            raise ValueError("'timeout' must be a non-negative number")
        import time

        deadline = (None if timeout is None
                    else time.monotonic() + timeout)
        while True:
            if ray_tpu.get(self.actor.put.remote(item)):
                return
            if not block or (deadline is not None
                             and time.monotonic() >= deadline):
                raise Full
            time.sleep(0.01)

    def put_nowait(self, item: Any):
        self.put(item, block=False)

    def put_nowait_batch(self, items: Iterable):
        items = list(items)
        if not ray_tpu.get(self.actor.put_batch.remote(items)):
            raise Full(f"Cannot add {len(items)} items to queue of size "
                       f"{self.maxsize}")

    def get(self, block: bool = True, timeout: Optional[float] = None):
        if timeout is not None and timeout < 0:
            raise ValueError("'timeout' must be a non-negative number")
        import time

        deadline = (None if timeout is None
                    else time.monotonic() + timeout)
        while True:
            ok, item = ray_tpu.get(self.actor.get.remote())
            if ok:
                return item
            if not block or (deadline is not None
                             and time.monotonic() >= deadline):
                raise Empty
            time.sleep(0.01)

    def get_nowait(self):
        return self.get(block=False)

    def get_nowait_batch(self, num_items: int) -> List[Any]:
        ok, items = ray_tpu.get(self.actor.get_batch.remote(num_items))
        if not ok:
            raise Empty(f"Cannot get {num_items} items from queue of size "
                        f"{self.size()}")
        return items

    def shutdown(self, force: bool = False, grace_period_s: int = 5):
        if self.actor is not None:
            ray_tpu.kill(self.actor)
        self.actor = None
