"""Placement groups: gang reservation of resource bundles.

Counterpart of /root/reference/python/ray/util/placement_group.py:42,146 (the
GCS-side 2PC scheduler lives in gcs_placement_group_scheduler.cc).  On the
TPU build, bundles are how slices are gang-reserved: a v5e-16 training job
reserves 4 bundles of {"TPU": 4} (one per host) with STRICT_PACK so the mesh
lands on one ICI domain.  Bundles are assigned to cluster nodes by strategy
(PACK/SPREAD/STRICT_PACK/STRICT_SPREAD) and 2PC-reserved on each; tasks
using a bundle run on its node (scheduler routes by the GCS bundle map).
Creation is synchronous — ``ready``/``wait`` resolve immediately.
"""

from __future__ import annotations

import os
from typing import Optional

from ray_tpu._private.worker import global_worker
from ray_tpu.exceptions import PlacementGroupUnavailableError

PACK = "PACK"
SPREAD = "SPREAD"
STRICT_PACK = "STRICT_PACK"
STRICT_SPREAD = "STRICT_SPREAD"
VALID_STRATEGIES = (PACK, SPREAD, STRICT_PACK, STRICT_SPREAD)


class PlacementGroup:
    def __init__(self, pg_id: bytes, bundles: list[dict], strategy: str):
        self.id = pg_id
        self.bundle_specs = bundles
        self.strategy = strategy

    @property
    def bundle_count(self) -> int:
        return len(self.bundle_specs)

    def ready(self):
        """Return an ObjectRef resolvable once the group is reserved."""
        # Reservation is synchronous in this round; hand back a sealed ref.
        return global_worker().put_object(True)

    def wait(self, timeout_seconds: float = 30) -> bool:
        return True

    def __reduce__(self):
        return (PlacementGroup, (self.id, self.bundle_specs, self.strategy))


def placement_group(
    bundles: list[dict],
    strategy: str = PACK,
    name: str = "",
    lifetime: Optional[str] = None,
) -> PlacementGroup:
    if strategy not in VALID_STRATEGIES:
        raise ValueError(f"invalid strategy {strategy!r}")
    if not bundles or any(not b for b in bundles):
        raise ValueError("bundles must be a non-empty list of non-empty dicts")
    worker = global_worker()
    pg_id = os.urandom(16)
    ok = worker.rpc(
        "create_placement_group",
        {"pg_id": pg_id, "bundles": bundles, "strategy": strategy},
    )
    if not ok:
        raise PlacementGroupUnavailableError(
            f"cannot reserve bundles {bundles}: insufficient resources"
        )
    return PlacementGroup(pg_id, bundles, strategy)


def remove_placement_group(pg: PlacementGroup):
    global_worker().rpc("remove_placement_group", {"pg_id": pg.id})


def placement_group_table() -> dict:
    return global_worker().rpc("pg_table", {})
