"""Application metrics: Counter / Gauge / Histogram.

Counterpart of /root/reference/python/ray/util/metrics.py (Cython metric
bindings over the C++ OpenCensus registry, exported through the node metrics
agent to Prometheus). Here every process keeps a local registry; a
background flusher pushes snapshots over the node scheduler's control
socket ("metrics_push"), the scheduler aggregates per node, and the
dashboard's /metrics endpoint renders the cluster-wide Prometheus text
(ray_tpu.dashboard). Tag semantics match the reference: declared tag_keys,
default tags, per-call overrides.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Sequence, Tuple

_registry_lock = threading.Lock()
_registry: List["Metric"] = []
_flusher_started = False
_flush_stop = threading.Event()
_flush_gen = 0


def _flush_interval() -> float:
    # registered flag (RTPU_METRICS_FLUSH_S), not a hardcoded constant
    from ray_tpu._private import flags

    return max(0.25, float(flags.get("RTPU_METRICS_FLUSH_S")))


def _ensure_flusher():
    global _flusher_started, _flush_gen
    with _registry_lock:
        if _flusher_started:
            return
        _flusher_started = True
        _flush_gen += 1
        gen = _flush_gen
        _flush_stop.clear()
    threading.Thread(target=_flush_loop, args=(gen,), name="metrics-flush",
                     daemon=True).start()


def _flush_loop(gen: int):
    global _flusher_started
    while True:
        stopped = _flush_stop.wait(_flush_interval())
        with _registry_lock:
            if gen != _flush_gen:
                return  # superseded by a newer flusher
            if stopped:
                _flusher_started = False
                return  # clean exit on shutdown_flusher()
        _flush_once()


def _flush_once():
    from ray_tpu._private import worker as worker_mod

    ctx = worker_mod.global_worker_or_none()
    if ctx is None:
        return  # not initialized (yet/anymore)
    snap = snapshot()
    if not snap:
        return
    try:
        ctx.rpc("metrics_push", {
            "source": ctx.worker_id or b"driver",
            "metrics": snap,
        })
    except Exception:
        pass  # node shutting down; metrics are best-effort


def shutdown_flusher(flush: bool = False):
    """Stop the background flusher so worker/driver shutdown is clean
    instead of leaving the loop spinning forever; optionally pushing one
    final snapshot first."""
    if flush:
        try:
            _flush_once()
        except Exception:
            pass
    _flush_stop.set()


def resume_flusher():
    """Restart the flusher after a shutdown when metrics already exist
    (a fresh ray_tpu.init() in the same process re-uses the registry)."""
    with _registry_lock:
        empty = not _registry
    if not empty:
        _ensure_flusher()


def snapshot() -> List[dict]:
    with _registry_lock:
        metrics = list(_registry)
    return [m._snapshot() for m in metrics]


class Metric:
    _kind = "untyped"

    def __init__(self, name: str, description: str = "",
                 tag_keys: Optional[Sequence[str]] = None):
        if not name:
            raise ValueError("metric name is required")
        self._name = name
        self._description = description
        self._tag_keys = tuple(tag_keys or ())
        self._default_tags: Dict[str, str] = {}
        self._values: Dict[Tuple[str, ...], float] = {}
        self._lock = threading.Lock()
        with _registry_lock:
            _registry.append(self)
        _ensure_flusher()

    def set_default_tags(self, tags: Dict[str, str]) -> "Metric":
        bad = set(tags) - set(self._tag_keys)
        if bad:
            raise ValueError(f"tags {sorted(bad)} not in declared tag_keys "
                             f"{self._tag_keys}")
        self._default_tags = dict(tags)
        return self

    def _tag_tuple(self, tags: Optional[Dict[str, str]]) -> Tuple[str, ...]:
        merged = dict(self._default_tags)
        if tags:
            bad = set(tags) - set(self._tag_keys)
            if bad:
                raise ValueError(
                    f"tags {sorted(bad)} not in declared tag_keys "
                    f"{self._tag_keys}")
            merged.update(tags)
        return tuple(merged.get(k, "") for k in self._tag_keys)

    def _snapshot(self) -> dict:
        with self._lock:
            values = dict(self._values)
        return {"name": self._name, "kind": self._kind,
                "description": self._description,
                "tag_keys": self._tag_keys, "values": values}

    def clear(self) -> None:
        """Drop every recorded series (tag values and histogram state).
        For gauge families whose label sets churn — e.g. per-worker RSS —
        the reporter clears before re-setting each sample so series for
        dead workers don't linger on /metrics forever."""
        with self._lock:
            self._values.clear()
            hist = getattr(self, "_hist", None)
            if hist is not None:
                hist.clear()

    @property
    def info(self) -> dict:
        return {"name": self._name, "description": self._description,
                "tag_keys": self._tag_keys,
                "default_tags": dict(self._default_tags)}


class Counter(Metric):
    _kind = "counter"

    def inc(self, value: float = 1.0,
            tags: Optional[Dict[str, str]] = None):
        if value < 0:
            raise ValueError("counters only increase")
        key = self._tag_tuple(tags)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + value


class Gauge(Metric):
    _kind = "gauge"

    def set(self, value: float, tags: Optional[Dict[str, str]] = None):
        key = self._tag_tuple(tags)
        with self._lock:
            self._values[key] = float(value)


DEFAULT_BOUNDARIES = (0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1, 5, 10, 60)

# Serving-latency histogram families expected to carry exemplar trace ids
# (the bucket-indexed "which request landed here" links).  metrics_lint
# parses this literal and enforces that each family is registered as a
# Histogram — an exemplar on a counter/gauge would silently vanish.
EXEMPLAR_FAMILIES = (
    "llm_ttft_s",
    "llm_tpot_s",
    "llm_e2e_s",
    "llm_queue_wait_s",
    "llm_prefill_s",
    "serve_request_latency_s",
)


class Histogram(Metric):
    _kind = "histogram"

    def __init__(self, name: str, description: str = "",
                 boundaries: Optional[Sequence[float]] = None,
                 tag_keys: Optional[Sequence[str]] = None):
        super().__init__(name, description, tag_keys)
        self._boundaries = tuple(boundaries or DEFAULT_BOUNDARIES)
        # per tag tuple: [bucket counts..., +inf count, sum]
        self._hist: Dict[Tuple[str, ...], list] = {}
        # per tag tuple: {bucket index: last trace id to land there}
        self._exemplars: Dict[Tuple[str, ...], Dict[int, str]] = {}

    def observe(self, value: float, tags: Optional[Dict[str, str]] = None,
                exemplar: Optional[str] = None):
        if exemplar is None:
            # ambient pickup: an observe inside a traced request links the
            # bucket to that request without every call site threading ids
            from ray_tpu.util import tracing

            ctx = tracing.current_context()
            if ctx is not None:
                exemplar = ctx[0]
        key = self._tag_tuple(tags)
        with self._lock:
            h = self._hist.get(key)
            if h is None:
                h = self._hist[key] = [0] * (len(self._boundaries) + 1) + [0.0]
            for i, b in enumerate(self._boundaries):
                if value <= b:
                    bucket = i
                    break
            else:
                bucket = len(self._boundaries)
            h[bucket] += 1
            h[-1] += value
            if exemplar:
                self._exemplars.setdefault(key, {})[bucket] = str(exemplar)

    def clear(self) -> None:
        super().clear()
        with self._lock:
            self._exemplars.clear()

    def _snapshot(self) -> dict:
        with self._lock:
            hist = {k: list(v) for k, v in self._hist.items()}
            exemplars = {k: dict(v) for k, v in self._exemplars.items() if v}
        snap = {"name": self._name, "kind": self._kind,
                "description": self._description,
                "tag_keys": self._tag_keys,
                "boundaries": self._boundaries, "hist": hist}
        if exemplars:
            snap["exemplars"] = exemplars
        return snap
