"""ray_tpu.util.collective: host-plane collectives between actors/tasks.

In-program (ICI) collectives belong to jitted SPMD code via jax.lax — see
ray_tpu.parallel. This package coordinates across processes, the role the
reference's NCCL/Gloo groups play (/root/reference/python/ray/util/collective/).
"""

from ray_tpu.util.collective.collective import (
    ReduceOp,
    allgather,
    allreduce,
    barrier,
    broadcast,
    declare_collective_group,
    destroy_collective_group,
    get_collective_group_size,
    get_rank,
    init_collective_group,
    is_group_initialized,
    recv,
    reducescatter,
    send,
)

__all__ = [
    "ReduceOp",
    "allgather",
    "allreduce",
    "barrier",
    "broadcast",
    "declare_collective_group",
    "destroy_collective_group",
    "get_collective_group_size",
    "get_rank",
    "init_collective_group",
    "is_group_initialized",
    "recv",
    "reducescatter",
    "send",
]
