"""Actor/task-group collectives over the shared-memory object store.

Counterpart of the reference's collective library
(/root/reference/python/ray/util/collective/collective.py:145 init_collective_group,
:290 allreduce, plus allgather/reducescatter/broadcast/send/recv) — but where the
reference wraps NCCL/Gloo communicators, the TPU-native design has two planes:

1. **In-program (ICI) collectives** are *not here*: inside a jitted SPMD
   program they are ``jax.lax.psum/all_gather/ppermute`` over mesh axes —
   XLA emits ICI collectives directly (see ray_tpu.parallel.mesh).
2. **Host-plane collectives** (this module) coordinate *between actors or
   tasks* — different processes, possibly different hosts — the role NCCL
   groups play for the reference's `ray.util.collective`.  The data plane is
   the native shm object store (zero-copy numpy intra-node, chunked pulls
   across nodes); the rendezvous plane is the GCS KV, so there is no extra
   coordinator process or actor to place and no communicator state to leak.

Every participant calls ``init_collective_group(world_size, rank, group_name)``
once, then the verbs.  Each verb bumps a per-group sequence number that all
ranks advance in lockstep (same total order of collectives per group — the
same contract NCCL imposes), so keys never collide across rounds.
"""

from __future__ import annotations

import time
from typing import List, Optional, Sequence

import numpy as np

from ray_tpu._private import worker as worker_mod
from ray_tpu.core.object_ref import ObjectRef

_KV_NS = "collective"
_POLL_S = 0.002


class ReduceOp:
    SUM = "sum"
    PRODUCT = "product"
    MIN = "min"
    MAX = "max"
    MEAN = "mean"


_REDUCERS = {
    ReduceOp.SUM: lambda xs: sum(xs[1:], xs[0]),
    ReduceOp.PRODUCT: lambda xs: _fold(np.multiply, xs),
    ReduceOp.MIN: lambda xs: _fold(np.minimum, xs),
    ReduceOp.MAX: lambda xs: _fold(np.maximum, xs),
    ReduceOp.MEAN: lambda xs: sum(xs[1:], xs[0]) / len(xs),
}


def _fold(op, xs):
    acc = xs[0]
    for x in xs[1:]:
        acc = op(acc, x)
    return acc


class _GroupState:
    def __init__(self, world_size: int, rank: int, name: str, incarnation: int):
        if not (0 <= rank < world_size):
            raise ValueError(f"rank {rank} out of range for world {world_size}")
        self.world_size = world_size
        self.rank = rank
        self.name = name
        # Key prefix includes the incarnation so a destroy + re-init with the
        # same group name never reads the previous incarnation's stale keys.
        # All ranks perform the same init/destroy sequence (the same lockstep
        # contract the per-round seq already relies on), so per-process
        # incarnation counters agree across ranks.
        self.incarnation = incarnation
        self.seq = 0
        # p2p ordering is per (src, dst) pair, independent of the collective
        # seq: a rank that sends to two peers (or mixes p2p with collectives)
        # must not skew rendezvous counters for anyone else.
        self.p2p_send_seq: dict[int, int] = {}  # dst_rank -> next seq
        self.p2p_recv_seq: dict[int, int] = {}  # src_rank -> next seq
        # Keys/objects this rank published, per collective round, reclaimed
        # once every rank has stamped that round's done marker.
        self.round_pending: dict[int, list[tuple[str, bytes]]] = {}
        # Outstanding p2p sends: (key, oid) per dst, reclaimed once the
        # receiver has deleted the rendezvous key (absence == consumed).
        self.p2p_pending: dict[int, list[tuple[str, bytes]]] = {}

    def prefix(self) -> str:
        return f"{self.name}/i{self.incarnation}"


# group_name -> _GroupState, per process (each actor is its own process).
_groups: dict[str, _GroupState] = {}
# group_name -> number of times this process has initialized it.
_incarnations: dict[str, int] = {}


def _ctx():
    w = worker_mod.global_worker()
    if w is None:
        raise RuntimeError("ray_tpu is not initialized in this process")
    return w


def _kv_put(key: str, value: bytes):
    _ctx().rpc("kv_put", {"namespace": _KV_NS, "key": key.encode(),
                          "value": value})


def _kv_get(key: str) -> Optional[bytes]:
    return _ctx().rpc("kv_get", {"namespace": _KV_NS, "key": key.encode()})


def _kv_del(key: str):
    _ctx().rpc("kv_del", {"namespace": _KV_NS, "key": key.encode()})


def _wait_kv(key: str, timeout: float) -> bytes:
    deadline = time.monotonic() + timeout
    w = _ctx()
    if w.gcs_address:
        # Event-driven wait: subscribe to the collective KV channel and
        # sleep until the key's write event arrives (VERDICT round-2: the
        # 2ms rendezvous spin burned the very core the control plane runs
        # on).  Register BEFORE checking so a write between check and wait
        # cannot be lost; periodic re-checks guard against a dropped event
        # ring (gap wakes handle the common case).
        from ray_tpu._private import kv_watch

        watcher = kv_watch.get_watcher(w.gcs_address, _KV_NS)
        ev = watcher.register(key.encode())
        try:
            while True:
                v = _kv_get(key)
                if v is not None:
                    return v
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise TimeoutError(
                        f"collective rendezvous timed out on {key!r}")
                ev.wait(min(remaining, 2.0))
                ev.clear()
        finally:
            watcher.unregister(key.encode(), ev)
    # no GCS endpoint in this process (minimal embedded contexts): poll
    while True:
        v = _kv_get(key)
        if v is not None:
            return v
        if time.monotonic() > deadline:
            raise TimeoutError(f"collective rendezvous timed out on {key!r}")
        time.sleep(_POLL_S)


def init_collective_group(world_size: int, rank: int,
                          backend: str = "shm",
                          group_name: str = "default") -> None:
    """Join a collective group. Call once in every participating process.

    ``backend`` accepts "shm" (native) — "nccl"/"gloo" names from reference
    code are mapped to it so ported call-sites run unchanged.
    """
    if backend not in ("shm", "nccl", "gloo", "xla"):
        raise ValueError(f"unknown collective backend {backend!r}")
    if group_name in _groups:
        raise RuntimeError(f"collective group {group_name!r} already "
                           f"initialized in this process")
    inc = _incarnations.get(group_name, 0) + 1
    _incarnations[group_name] = inc
    _groups[group_name] = _GroupState(world_size, rank, group_name, inc)


def is_group_initialized(group_name: str = "default") -> bool:
    return group_name in _groups


def get_rank(group_name: str = "default") -> int:
    return _group(group_name).rank


def get_collective_group_size(group_name: str = "default") -> int:
    return _group(group_name).world_size


def destroy_collective_group(group_name: str = "default",
                             grace_s: float = 5.0) -> None:
    g = _groups.pop(group_name, None)
    if g is None:
        return
    # Best-effort farewell barrier: if every rank reaches destroy within the
    # grace period, all earlier rounds are provably finished cluster-wide
    # and this rank's leftovers can be reclaimed.  On timeout nothing is
    # deleted — yanking keys from under a straggler mid-collect is worse
    # than leaking a round of tiny keys (which the incarnation prefix keeps
    # from ever being misread).  The barrier round's own token is the one
    # thing knowingly left behind (~bytes per rank per incarnation).
    barrier_ok = False
    try:
        _publish(g, f"ag/{g.rank}", np.zeros((), np.int8))
        _collect(g, lambda r: f"ag/{r}", grace_s)
        _gc_rounds_before(g, g.seq)
        barrier_ok = True
    except Exception:
        pass
    # p2p: receiver deletes the rendezvous key on recv, so key-absence means
    # consumed (free our object).  A key still present after a SUCCESSFUL
    # farewell barrier is an unmatched send — a program error per the
    # lockstep contract — reclaim it outright.  If the barrier timed out a
    # straggler may still be about to recv, so only confirmed-consumed sends
    # are freed (same leave-it-in-place policy as the collective rounds).
    for entries in g.p2p_pending.values():
        for key, oid in entries:
            if barrier_ok or _kv_get(key) is None:
                _reclaim(key, oid)


def _reclaim(key: Optional[str], oid: Optional[bytes]) -> None:
    """Best-effort delete of a rendezvous key and its published object."""
    w = _ctx()
    if key is not None:
        try:
            _kv_del(key)
        except Exception:
            pass
    if oid is not None:
        try:
            w.store.delete(oid)
        except Exception:
            pass
        node = getattr(w, "node", None)
        nid = getattr(node, "node_id", None) if node is not None else None
        if nid:
            try:
                w.rpc("remove_object_location", {"oid": oid, "node_id": nid})
            except Exception:
                pass


def _gc_rounds_before(g: _GroupState, seq: int) -> None:
    """Reclaim this rank's published keys/objects for all rounds < seq.

    Only called once the caller has PROOF every rank finished those rounds:
    completing an all-publish collect at round ``seq`` means every rank
    published at ``seq``, which it does strictly after finishing every
    earlier round (including broadcast rounds where only the src published).
    A broadcast src that races ahead therefore never reclaims anything on
    its own authority — its pending rounds wait for the next all-publish
    round to confirm the stragglers caught up.
    """
    for s in [s for s in g.round_pending if s < seq]:
        for key, oid in g.round_pending.pop(s):
            _reclaim(key, oid)


def _group(group_name: str) -> _GroupState:
    g = _groups.get(group_name)
    if g is None:
        raise RuntimeError(
            f"collective group {group_name!r} is not initialized; call "
            f"init_collective_group(world_size, rank, group_name=...) first")
    return g


def _to_host(tensor) -> np.ndarray:
    # jax.Array / torch.Tensor / numpy all round-trip through the host for
    # the host-plane; in-program collectives never leave HBM (see module doc).
    if hasattr(tensor, "__array__"):
        return np.asarray(tensor)
    return np.asarray(tensor)


def _publish(g: _GroupState, tag: str, arr: np.ndarray) -> None:
    ref = _ctx().put_object(arr)
    key = f"{g.prefix()}/{g.seq}/{tag}"
    _kv_put(key, ref.binary())
    g.round_pending.setdefault(g.seq, []).append((key, ref.binary()))


def _collect(g: _GroupState, tag_of, timeout: float) -> List[np.ndarray]:
    from ray_tpu import api
    out = []
    for r in range(g.world_size):
        oid = _wait_kv(f"{g.prefix()}/{g.seq}/{tag_of(r)}", timeout)
        value = api.get(ObjectRef(oid), timeout=timeout)
        if isinstance(value, np.ndarray):
            # Own the bytes: the publisher reclaims the backing shm object
            # once a later round proves everyone has moved past this one.
            value = np.array(value)
        out.append(value)
    return out


def allgather(tensor, group_name: str = "default",
              timeout: float = 60.0) -> List[np.ndarray]:
    """Gather every rank's tensor; returns list indexed by rank."""
    g = _group(group_name)
    _publish(g, f"ag/{g.rank}", _to_host(tensor))
    vals = _collect(g, lambda r: f"ag/{r}", timeout)
    # Every rank published this round, so every earlier round is finished
    # cluster-wide: reclaim our stale keys/objects (bounds per-step growth).
    _gc_rounds_before(g, g.seq)
    g.seq += 1
    return vals


def allreduce(tensor, op: str = ReduceOp.SUM, group_name: str = "default",
              timeout: float = 60.0) -> np.ndarray:
    """Reduce across ranks; every rank returns the full reduced tensor."""
    if op not in _REDUCERS:
        raise ValueError(f"unknown reduce op {op!r}")
    vals = allgather(tensor, group_name=group_name, timeout=timeout)
    return _REDUCERS[op](vals)


def reducescatter(tensor, op: str = ReduceOp.SUM,
                  group_name: str = "default",
                  timeout: float = 60.0) -> np.ndarray:
    """Reduce across ranks, then return this rank's 1/world_size shard
    (along axis 0, which must divide evenly)."""
    g = _group(group_name)
    reduced = allreduce(tensor, op=op, group_name=group_name, timeout=timeout)
    n = g.world_size
    if reduced.shape[0] % n:
        raise ValueError(
            f"reducescatter dim0 {reduced.shape[0]} not divisible by "
            f"world_size {n}")
    shard = reduced.shape[0] // n
    return reduced[g.rank * shard:(g.rank + 1) * shard]


def broadcast(tensor, src_rank: int = 0, group_name: str = "default",
              timeout: float = 60.0) -> np.ndarray:
    """Every rank returns src_rank's tensor."""
    from ray_tpu import api
    g = _group(group_name)
    if g.rank == src_rank:
        _publish(g, f"bc/{src_rank}", _to_host(tensor))
    oid = _wait_kv(f"{g.prefix()}/{g.seq}/bc/{src_rank}", timeout)
    g.seq += 1
    value = api.get(ObjectRef(oid), timeout=timeout)
    if isinstance(value, np.ndarray):
        value = np.array(value)  # own the bytes (src reclaims later)
    return value


def send(tensor, dst_rank: int, group_name: str = "default") -> None:
    """Point-to-point send (pairs with recv on dst_rank).

    Ordered per (src, dst) pair — matching sends/recvs advance a dedicated
    counter, so interleaving sends to several peers or mixing p2p with
    collectives never skews anyone's rendezvous sequence.
    """
    g = _group(group_name)
    # Reclaim earlier sends to this peer the receiver has consumed: recv
    # deletes the rendezvous key after reading, so key-absence is the ack.
    still = []
    for key, oid in g.p2p_pending.get(dst_rank, []):
        if _kv_get(key) is None:
            _reclaim(None, oid)
        else:
            still.append((key, oid))
    if still:
        g.p2p_pending[dst_rank] = still
    else:
        g.p2p_pending.pop(dst_rank, None)
    n = g.p2p_send_seq.get(dst_rank, 0)
    ref = _ctx().put_object(_to_host(tensor))
    key = f"{g.prefix()}/p2p/{g.rank}->{dst_rank}/{n}"
    _kv_put(key, ref.binary())
    # Advance only after the publish succeeded, so a failed send can be
    # retried at the same sequence number.
    g.p2p_send_seq[dst_rank] = n + 1
    g.p2p_pending.setdefault(dst_rank, []).append((key, ref.binary()))


def recv(src_rank: int, group_name: str = "default",
         timeout: float = 60.0) -> np.ndarray:
    """Point-to-point receive from src_rank.

    Unlike the reference (which writes into a caller tensor), returns the
    received array — idiomatic for a functional JAX host program.
    """
    from ray_tpu import api
    g = _group(group_name)
    n = g.p2p_recv_seq.get(src_rank, 0)
    key = f"{g.prefix()}/p2p/{src_rank}->{g.rank}/{n}"
    oid = _wait_kv(key, timeout)
    value = api.get(ObjectRef(oid), timeout=timeout)
    if isinstance(value, np.ndarray):
        # Own the bytes before acking — the sender may free the backing shm
        # object the moment it observes the ack.
        value = np.array(value)
    # Advance only once the value is in hand: a timed-out recv may be
    # retried and must wait on the same sequence number.
    g.p2p_recv_seq[src_rank] = n + 1
    # Deleting the rendezvous key doubles as the consumption ack: the sender
    # frees the published object once it observes the key gone.
    _kv_del(key)
    return value


def barrier(group_name: str = "default", timeout: float = 60.0) -> None:
    """Block until every rank reaches the same barrier."""
    allgather(np.zeros((), np.int8), group_name=group_name, timeout=timeout)


def declare_collective_group(actors: Sequence, world_size: Optional[int] = None,
                             ranks: Optional[Sequence[int]] = None,
                             backend: str = "shm",
                             group_name: str = "default") -> None:
    """Driver-side convenience: initialize the group inside each actor.

    Uses the hidden ``__rtpu_apply__`` actor method (counterpart of the
    reference's ``__ray_call__``), so any actor class participates without
    declaring anything.
    """
    n = world_size if world_size is not None else len(actors)
    rks = list(ranks) if ranks is not None else list(range(len(actors)))
    from ray_tpu import api

    def _join(_self, world, rank, be, gname):
        init_collective_group(world, rank, backend=be, group_name=gname)

    refs = [
        a.__rtpu_apply__.remote(_join, n, r, backend, group_name)
        for a, r in zip(actors, rks)
    ]
    api.get(refs)
