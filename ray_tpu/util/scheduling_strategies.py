"""Scheduling strategies (counterpart of
/root/reference/python/ray/util/scheduling_strategies.py:15,41,135)."""

from __future__ import annotations

from typing import Optional


class PlacementGroupSchedulingStrategy:
    def __init__(
        self,
        placement_group,
        placement_group_bundle_index: int = -1,
        placement_group_capture_child_tasks: bool = False,
    ):
        self.placement_group = placement_group
        self.placement_group_bundle_index = placement_group_bundle_index
        self.placement_group_capture_child_tasks = (
            placement_group_capture_child_tasks
        )


class NodeAffinitySchedulingStrategy:
    def __init__(self, node_id, soft: bool = False):
        self.node_id = node_id
        self.soft = soft


class NodeLabelSchedulingStrategy:
    def __init__(self, hard: Optional[dict] = None, soft: Optional[dict] = None):
        self.hard = hard or {}
        self.soft = soft or {}


# Label-match operators for NodeLabelSchedulingStrategy.
class In:
    def __init__(self, *values):
        self.values = list(values)


class NotIn:
    def __init__(self, *values):
        self.values = list(values)


class Exists:
    pass


class DoesNotExist:
    pass


def labels_match(selector: dict, labels: dict) -> bool:
    """Evaluate a label selector against a node's labels (reference:
    NodeLabelSchedulingStrategy operators,
    python/ray/util/scheduling_strategies.py:135)."""
    labels = labels or {}
    for key, cond in (selector or {}).items():
        present = key in labels
        value = labels.get(key)
        if isinstance(cond, In):
            if not present or value not in cond.values:
                return False
        elif isinstance(cond, NotIn):
            if present and value in cond.values:
                return False
        elif isinstance(cond, Exists):
            if not present:
                return False
        elif isinstance(cond, DoesNotExist):
            if present:
                return False
        else:  # plain value: exact match
            if not present or value != cond:
                return False
    return True
