"""Dask-on-Ray: execute dask task graphs on the ray_tpu core runtime.

Counterpart of /root/reference/python/ray/util/dask/ (scheduler.py
``ray_dask_get``): a drop-in dask scheduler that turns each graph task into
a ray_tpu task, so the cluster's scheduler/object store replace dask's
local threadpool.  Works on raw dask-spec graphs (plain dicts of
``key -> (callable, *args)``) without dask installed — dask itself is only
needed for ``enable_dask_on_ray()``, which registers this as the default
scheduler via ``dask.config``.
"""

from ray_tpu.util.dask.scheduler import (
    disable_dask_on_ray,
    enable_dask_on_ray,
    ray_dask_get,
)

__all__ = ["ray_dask_get", "enable_dask_on_ray", "disable_dask_on_ray"]
