"""The Dask-on-Ray scheduler.

Dask graph spec (https://docs.dask.org/en/stable/spec.html, and the shapes
consumed by the reference's python/ray/util/dask/scheduler_utils.py):

  * a graph is a dict ``key -> computation``
  * a *task* is a tuple whose first element is callable
  * any hashable value that is itself a key of the graph is a reference to
    that key's result (including inside nested lists/tuples/dicts)
  * anything else is a literal

Each task becomes one ray_tpu task.  Dependencies are flattened to
TOP-LEVEL ObjectRef arguments (the worker resolves only top-level refs —
same constraint as the reference, whose ``dask_task_wrapper`` repacks
position-indexed refs; see /root/reference/python/ray/util/dask/
scheduler.py) and re-substituted inside the expression by placeholder
index before evaluation.
"""

from __future__ import annotations

from typing import Any, Dict, Hashable, List

import ray_tpu


class _Placeholder:
    """Marks a dependency slot inside a task expression; ``i`` indexes the
    flat ref list submitted as top-level args."""

    __slots__ = ("i",)

    def __init__(self, i: int):
        self.i = i

    def __reduce__(self):
        return (_Placeholder, (self.i,))


def _is_task(v: Any) -> bool:
    return isinstance(v, tuple) and len(v) > 0 and callable(v[0])


def _is_key(v: Any, dsk: dict) -> bool:
    if _is_task(v):
        return False
    try:
        return v in dsk
    except TypeError:  # unhashable → literal
        return False


def _toposort(dsk: dict) -> List[Hashable]:
    """DFS topological order with cycle detection."""
    WHITE, GRAY, BLACK = 0, 1, 2
    color: Dict[Hashable, int] = {k: WHITE for k in dsk}
    out: List[Hashable] = []

    def deps_of(expr, acc):
        if _is_key(expr, dsk):
            acc.append(expr)
        elif _is_task(expr):
            for a in expr[1:]:
                deps_of(a, acc)
        elif isinstance(expr, (list, tuple)):
            for a in expr:
                deps_of(a, acc)
        elif isinstance(expr, dict):
            for a in expr.values():
                deps_of(a, acc)
        return acc

    for start in dsk:
        if color[start] != WHITE:
            continue
        stack = [(start, iter(deps_of(dsk[start], [])))]
        color[start] = GRAY
        while stack:
            node, it = stack[-1]
            advanced = False
            for dep in it:
                if color[dep] == GRAY:
                    raise ValueError(f"cycle in dask graph at {dep!r}")
                if color[dep] == WHITE:
                    color[dep] = GRAY
                    stack.append((dep, iter(deps_of(dsk[dep], []))))
                    advanced = True
                    break
            if not advanced:
                color[node] = BLACK
                out.append(node)
                stack.pop()
    return out


def _substitute(expr: Any, dsk: dict, refs: dict, flat: list) -> Any:
    """Replace key references with placeholders, collecting their refs."""
    if _is_key(expr, dsk):
        flat.append(refs[expr])
        return _Placeholder(len(flat) - 1)
    if _is_task(expr):
        return tuple([expr[0]] + [_substitute(a, dsk, refs, flat)
                                  for a in expr[1:]])
    if isinstance(expr, list):
        return [_substitute(a, dsk, refs, flat) for a in expr]
    if isinstance(expr, tuple):
        return tuple(_substitute(a, dsk, refs, flat) for a in expr)
    if isinstance(expr, dict):
        return {k: _substitute(v, dsk, refs, flat)
                for k, v in expr.items()}
    return expr


def _evaluate(expr: Any, resolved: tuple) -> Any:
    if isinstance(expr, _Placeholder):
        return resolved[expr.i]
    if _is_task(expr):
        return expr[0](*[_evaluate(a, resolved) for a in expr[1:]])
    if isinstance(expr, list):
        return [_evaluate(a, resolved) for a in expr]
    if isinstance(expr, tuple):
        return tuple(_evaluate(a, resolved) for a in expr)
    if isinstance(expr, dict):
        return {k: _evaluate(v, resolved) for k, v in expr.items()}
    return expr


@ray_tpu.remote
def _dask_exec(expr, *resolved):
    """One dask graph task: resolved holds the (already-materialized)
    dependency values in _Placeholder order."""
    return _evaluate(expr, resolved)


def ray_dask_get(dsk: dict, keys, **kwargs):
    """A dask ``get``: compute ``keys`` (a key or arbitrarily nested lists
    of keys) from graph ``dsk`` on the ray_tpu cluster.

    Extra kwargs (dask passes e.g. ``num_workers``) are accepted and
    ignored — parallelism comes from the cluster scheduler.
    """
    refs: Dict[Hashable, Any] = {}
    for key in _toposort(dsk):
        expr = dsk[key]
        if _is_key(expr, dsk):          # alias: key -> other key
            refs[key] = refs[expr]
            continue
        flat: list = []
        sub = _substitute(expr, dsk, refs, flat)
        if not _is_task(expr) and not flat:
            refs[key] = ray_tpu.put(expr)  # literal
            continue
        refs[key] = _dask_exec.options(
            name=f"dask:{str(key)[:40]}").remote(sub, *flat)

    def pack(ks):
        if isinstance(ks, list):
            return [pack(k) for k in ks]
        return ray_tpu.get(refs[ks])

    return pack(keys)


_saved_config: list = []


def enable_dask_on_ray():
    """Register ray_dask_get as dask's default scheduler (requires dask)."""
    try:
        import dask
    except ImportError as e:
        raise ImportError(
            "enable_dask_on_ray() needs the `dask` package; "
            "ray_dask_get(dsk, keys) works on raw graphs without it"
        ) from e
    _saved_config.append(dask.config.get("scheduler", None))
    dask.config.set(scheduler=ray_dask_get)


def disable_dask_on_ray():
    import dask

    prev = _saved_config.pop() if _saved_config else None
    dask.config.set(scheduler=prev)
