"""Joblib ParallelBackend over ray_tpu tasks.

Counterpart of /root/reference/python/ray/util/joblib/ray_backend.py (which
subclasses the multiprocessing pool backend over Ray's Pool); here each
joblib batch maps directly to one task — simpler and equivalent for
joblib's call pattern (batches are sized by joblib itself).
"""

from __future__ import annotations

from joblib._parallel_backends import ParallelBackendBase, SequentialBackend

import ray_tpu


class _Result:
    def __init__(self, ref):
        self._ref = ref

    def get(self, timeout=None):
        return ray_tpu.get(self._ref, timeout=timeout)


# Module-level so the driver-side function cache registers it ONCE — a
# per-call closure would re-pickle and re-register for every joblib batch.
@ray_tpu.remote
def _run_batch(f):
    return f()


class RayTpuBackend(ParallelBackendBase):
    supports_timeout = True

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self._n_jobs = 1

    def configure(self, n_jobs: int = 1, parallel=None, **kwargs) -> int:
        if not ray_tpu.is_initialized():
            ray_tpu.init(ignore_reinit_error=True)
        self.parallel = parallel
        n_jobs = self.effective_n_jobs(n_jobs)
        self._n_jobs = n_jobs
        return n_jobs

    def effective_n_jobs(self, n_jobs: int) -> int:
        if n_jobs == 0:
            raise ValueError("n_jobs == 0 is not valid")
        if n_jobs < 0:
            cpus = ray_tpu.cluster_resources().get("CPU", 1) \
                if ray_tpu.is_initialized() else 1
            return max(1, int(cpus))
        return n_jobs

    def apply_async(self, func, callback=None):
        ref = _run_batch.remote(func)
        result = _Result(ref)
        if callback is not None:
            import threading

            def waiter():
                try:
                    callback(result.get())
                except Exception:
                    pass

            threading.Thread(target=waiter, daemon=True).start()
        return result

    def get_nested_backend(self):
        return SequentialBackend(nesting_level=self.nesting_level + 1), None

    def abort_everything(self, ensure_ready=True):
        pass
