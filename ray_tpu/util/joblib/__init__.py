"""Joblib backend: scikit-learn's n_jobs parallelism on the cluster.

Counterpart of /root/reference/python/ray/util/joblib/ (register_ray +
ray_backend.py): ``register_ray()`` then
``with joblib.parallel_backend("ray_tpu"): ...`` runs every joblib batch as
a cluster task.
"""

from __future__ import annotations

__all__ = ["register_ray"]


def register_ray() -> None:
    try:
        from joblib.parallel import register_parallel_backend
    except ImportError as e:
        raise ImportError("joblib is required for the ray_tpu joblib "
                          "backend") from e
    from ray_tpu.util.joblib.ray_backend import RayTpuBackend

    register_parallel_backend("ray_tpu", RayTpuBackend)
    # the reference registers under "ray"; accept that spelling too
    register_parallel_backend("ray", RayTpuBackend)
