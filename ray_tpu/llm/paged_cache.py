"""Paged KV cache: fixed-shape page pool + host-side page allocator.

TPU-native replacement for the paged attention the reference delegates to
vLLM (/root/reference/python/ray/llm/_internal/serve/deployments/llm/vllm/
vllm_engine.py:181 — engine kwargs `block_size`, `gpu_memory_utilization`):
KV lives in a static [n_layers, num_pages, page_size, n_kv, head_dim] pool
so every decode step has one compiled shape regardless of sequence lengths;
sequences map to pages through an integer page table.  The allocator is a
trivial host-side free list — allocation happens at admission time, never
inside the jitted step.

Prefix caching (ISSUE 10) layers two host-side structures on top:

- the allocator grows refcounts and a "cached-resident" set, so a page whose
  sequence finished can stay resident (its KV intact) until the pool needs
  it back, and a page shared by several sequences is only truly freed when
  the last one releases it;
- `PrefixCache` is a vLLM-style block index: a chain hash over FULL prompt
  pages maps token-block digests to resident pages, LRU-ordered, so a new
  request whose prompt shares a page-aligned prefix with earlier traffic
  skips recomputing (and re-storing) that prefix's KV.
"""

from __future__ import annotations

import hashlib
from collections import OrderedDict
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Set

import jax
import jax.numpy as jnp
import numpy as np


@dataclass
class CacheConfig:
    n_layers: int
    n_kv_heads: int
    head_dim: int
    num_pages: int = 256
    page_size: int = 16
    dtype: str = "bfloat16"

    @property
    def tokens_capacity(self) -> int:
        return self.num_pages * self.page_size


def init_cache(cfg: CacheConfig):
    shape = (cfg.n_layers, cfg.num_pages, cfg.page_size,
             cfg.n_kv_heads, cfg.head_dim)
    dt = jnp.dtype(cfg.dtype)
    return jnp.zeros(shape, dt), jnp.zeros(shape, dt)


class PageAllocator:
    """Host-side free list (reference analogue: vLLM's BlockManager).

    Three page states: FREE (on the free list), IN USE (refcount >= 1),
    and CACHED-RESIDENT (refcount 0 but registered in a PrefixCache —
    KV intact, reclaimable on demand).  allocate/free keep their original
    one-owner semantics when retain/mark_cached are never called, so code
    (and tests) that predate prefix caching see the old behavior.
    """

    def __init__(self, num_pages: int):
        # page 0 is reserved as the "null" page that padded page-table
        # entries point at; attention masks it out by position.
        self._free: List[int] = list(range(1, num_pages))
        self._rc: Dict[int, int] = {}
        self._cached: Set[int] = set()
        self.num_pages = num_pages

    def num_free(self) -> int:
        return len(self._free)

    def num_resident(self) -> int:
        """Cached pages with no live owner (reclaimable without preempting)."""
        return sum(1 for p in self._cached if self._rc.get(p, 0) <= 0)

    def can_allocate(self, n: int) -> bool:
        return len(self._free) >= n

    def allocate(self, n: int) -> List[int]:
        if n > len(self._free):
            raise MemoryError(f"needs {n} pages, {len(self._free)} free")
        out, self._free = self._free[:n], self._free[n:]
        for p in out:
            self._rc[p] = 1
        return out

    def retain(self, pages: List[int]) -> None:
        """Add a reference to already-resident pages (prefix-cache hit)."""
        for p in pages:
            if p != 0:
                self._rc[p] = self._rc.get(p, 0) + 1

    def refcount(self, page: int) -> int:
        return self._rc.get(page, 0)

    def free(self, pages: List[int]) -> None:
        """Release one reference; a page returns to the free list only when
        nothing references it AND it is not cached-resident."""
        for p in pages:
            if p == 0:
                continue
            rc = self._rc.get(p, 1) - 1
            if rc > 0:
                self._rc[p] = rc
                continue
            self._rc.pop(p, None)
            if p not in self._cached:
                self._free.append(p)

    def mark_cached(self, pages: List[int]) -> None:
        self._cached.update(p for p in pages if p != 0)

    def reclaim(self, page: int) -> None:
        """Cache eviction: drop residency; back to the free list if idle."""
        self._cached.discard(page)
        if self._rc.get(page, 0) <= 0:
            self._rc.pop(page, None)
            if page not in self._free:
                self._free.append(page)


@dataclass
class _Block:
    digest: bytes
    page: int


class PrefixCache:
    """Chain-hashed index of full prompt pages resident in the KV pool.

    Digest of block k = blake2b(digest of block k-1 || tokens of block k),
    so a digest identifies the entire prefix up to and including its page —
    matching is a walk from the root, never a per-page comparison (vLLM's
    block hash scheme).  LRU order doubles as the eviction order; eviction
    is driven by the allocator owner (engine) when the pool runs dry.
    """

    def __init__(self, page_size: int):
        self.page_size = page_size
        self._blocks: "OrderedDict[bytes, _Block]" = OrderedDict()
        self._by_page: Dict[int, bytes] = {}
        self.hit_tokens = 0
        self.lookup_tokens = 0
        self.evictions = 0

    # ------------------------- hashing -------------------------------

    @staticmethod
    def _chain(prev: bytes, tokens) -> bytes:
        h = hashlib.blake2b(prev, digest_size=8)
        h.update(np.asarray(tokens, np.int32).tobytes())
        return h.digest()

    @classmethod
    def digest_for(cls, tokens: List[int], page_size: int) -> Optional[str]:
        """Digest of the longest cacheable prefix of `tokens` (the P/D
        residency hint: two processes computing it agree byte-for-byte)."""
        n = len(tokens)
        blocks = max(0, (n - 1) // page_size)
        if blocks == 0:
            return None
        d = b""
        for k in range(blocks):
            d = cls._chain(d, tokens[k * page_size:(k + 1) * page_size])
        return d.hex()

    # ------------------------- index ops -----------------------------

    def __len__(self) -> int:
        return len(self._blocks)

    def match(self, tokens: List[int]) -> List[int]:
        """Longest chain of cached FULL pages covering a proper prefix.

        Capped at (n-1)//page_size blocks so at least one suffix token is
        always left to prefill (the logits that seed decode).  Pure lookup
        apart from LRU refresh — hit/lookup counters are committed by the
        caller only when the admission actually goes through, so a request
        that bounces off a full pool doesn't inflate the hit rate each
        retry.
        """
        ps = self.page_size
        n = len(tokens)
        pages: List[int] = []
        d = b""
        for k in range(max(0, (n - 1) // ps)):
            d = self._chain(d, tokens[k * ps:(k + 1) * ps])
            blk = self._blocks.get(d)
            if blk is None:
                break
            self._blocks.move_to_end(d)
            pages.append(blk.page)
        return pages

    def note_lookup(self, lookup_tokens: int, hit_tokens: int) -> None:
        self.lookup_tokens += lookup_tokens
        self.hit_tokens += hit_tokens

    def insert(self, tokens: List[int], pages: List[int]) -> List[int]:
        """Register every full page of `tokens` held in `pages`; returns the
        pages newly added to the index (callers mark those cached-resident).
        A digest that already maps to some other resident page keeps the
        existing mapping — identical content, and the old page may be
        shared by live sequences."""
        ps = self.page_size
        full = min(len(tokens) // ps, len(pages))
        d = b""
        new_pages: List[int] = []
        for k in range(full):
            d = self._chain(d, tokens[k * ps:(k + 1) * ps])
            blk = self._blocks.get(d)
            if blk is not None:
                self._blocks.move_to_end(d)
                continue
            page = pages[k]
            if page == 0 or page in self._by_page:
                continue
            self._blocks[d] = _Block(d, page)
            self._by_page[page] = d
            new_pages.append(page)
        return new_pages

    def evict_one(self, refcount: Callable[[int], int]) -> Optional[int]:
        """Drop the least-recently-used block nobody references; returns its
        page (caller reclaims it) or None if every block is pinned."""
        for d, blk in self._blocks.items():
            if refcount(blk.page) <= 0:
                del self._blocks[d]
                del self._by_page[blk.page]
                self.evictions += 1
                return blk.page
        return None

    def digests(self, limit: int = 16) -> List[str]:
        """Most-recently-used block digests (hex) — the resident-prefix
        advertisement the request router matches P/D hints against."""
        out = []
        for d in reversed(self._blocks):
            out.append(d.hex())
            if len(out) >= limit:
                break
        return out

    def stats(self) -> dict:
        return {
            "blocks": len(self._blocks),
            "hit_tokens": self.hit_tokens,
            "lookup_tokens": self.lookup_tokens,
            "evictions": self.evictions,
            "hit_rate": round(self.hit_tokens / self.lookup_tokens, 4)
            if self.lookup_tokens else 0.0,
        }
