"""Paged KV cache: fixed-shape page pool + host-side page allocator.

TPU-native replacement for the paged attention the reference delegates to
vLLM (/root/reference/python/ray/llm/_internal/serve/deployments/llm/vllm/
vllm_engine.py:181 — engine kwargs `block_size`, `gpu_memory_utilization`):
KV lives in a static [n_layers, num_pages, page_size, n_kv, head_dim] pool
so every decode step has one compiled shape regardless of sequence lengths;
sequences map to pages through an integer page table.  The allocator is a
trivial host-side free list — allocation happens at admission time, never
inside the jitted step.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np


@dataclass
class CacheConfig:
    n_layers: int
    n_kv_heads: int
    head_dim: int
    num_pages: int = 256
    page_size: int = 16
    dtype: str = "bfloat16"

    @property
    def tokens_capacity(self) -> int:
        return self.num_pages * self.page_size


def init_cache(cfg: CacheConfig):
    shape = (cfg.n_layers, cfg.num_pages, cfg.page_size,
             cfg.n_kv_heads, cfg.head_dim)
    dt = jnp.dtype(cfg.dtype)
    return jnp.zeros(shape, dt), jnp.zeros(shape, dt)


class PageAllocator:
    """Host-side free list (reference analogue: vLLM's BlockManager)."""

    def __init__(self, num_pages: int):
        # page 0 is reserved as the "null" page that padded page-table
        # entries point at; attention masks it out by position.
        self._free: List[int] = list(range(1, num_pages))
        self.num_pages = num_pages

    def num_free(self) -> int:
        return len(self._free)

    def can_allocate(self, n: int) -> bool:
        return len(self._free) >= n

    def allocate(self, n: int) -> List[int]:
        if n > len(self._free):
            raise MemoryError(f"needs {n} pages, {len(self._free)} free")
        out, self._free = self._free[:n], self._free[n:]
        return out

    def free(self, pages: List[int]) -> None:
        self._free.extend(p for p in pages if p != 0)
