"""Paged KV cache: fixed-shape page pool + host-side page allocator.

TPU-native replacement for the paged attention the reference delegates to
vLLM (/root/reference/python/ray/llm/_internal/serve/deployments/llm/vllm/
vllm_engine.py:181 — engine kwargs `block_size`, `gpu_memory_utilization`):
KV lives in a static [n_layers, num_pages, page_size, n_kv, head_dim] pool
so every decode step has one compiled shape regardless of sequence lengths;
sequences map to pages through an integer page table.  The allocator is a
trivial host-side free list — allocation happens at admission time, never
inside the jitted step.

Prefix caching (ISSUE 10) layers two host-side structures on top:

- the allocator grows refcounts and a "cached-resident" set, so a page whose
  sequence finished can stay resident (its KV intact) until the pool needs
  it back, and a page shared by several sequences is only truly freed when
  the last one releases it;
- `PrefixCache` is a vLLM-style block index: a chain hash over FULL prompt
  pages maps token-block digests to resident pages, so a new request whose
  prompt shares a page-aligned prefix with earlier traffic skips
  recomputing (and re-storing) that prefix's KV.

Converting locality into throughput (ISSUE 14) adds:

- per-family heat: every block belongs to the family of its chain's root
  digest; families track hit count, resident-block count, and last-hit
  time, and `evict_one` reclaims leaf-first inside the COLDEST family
  instead of walking a global LRU — a burst of unique traffic can no
  longer shred a hot shared root that queued requests are about to hit;
- partial-block (copy-on-write) matching: blocks remember their token
  content, so a prompt that diverges INSIDE a cached block still reuses
  the shared slots — the engine copies that single page and prefills only
  from the divergence point (`match_cow`).
"""

from __future__ import annotations

import hashlib
import os
import time
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Set, Tuple

import jax
import jax.numpy as jnp
import numpy as np

_FALSY = ("", "0", "false", "no", "off")


@dataclass
class CacheConfig:
    n_layers: int
    n_kv_heads: int
    head_dim: int
    num_pages: int = 256
    page_size: int = 16
    dtype: str = "bfloat16"

    @property
    def tokens_capacity(self) -> int:
        return self.num_pages * self.page_size


def init_cache(cfg: CacheConfig):
    shape = (cfg.n_layers, cfg.num_pages, cfg.page_size,
             cfg.n_kv_heads, cfg.head_dim)
    dt = jnp.dtype(cfg.dtype)
    return jnp.zeros(shape, dt), jnp.zeros(shape, dt)


class PageAllocator:
    """Host-side free list (reference analogue: vLLM's BlockManager).

    Three page states: FREE (on the free list), IN USE (refcount >= 1),
    and CACHED-RESIDENT (refcount 0 but registered in a PrefixCache —
    KV intact, reclaimable on demand).  allocate/free keep their original
    one-owner semantics when retain/mark_cached are never called, so code
    (and tests) that predate prefix caching see the old behavior.
    """

    def __init__(self, num_pages: int):
        # page 0 is reserved as the "null" page that padded page-table
        # entries point at; attention masks it out by position.
        self._free: List[int] = list(range(1, num_pages))
        self._rc: Dict[int, int] = {}
        self._cached: Set[int] = set()
        self.num_pages = num_pages
        # RTPU_DEBUG_ALLOCATOR: assert the page-state partition invariant
        # after every op (O(num_pages) — test/chaos runs only)
        self._debug = os.environ.get(
            "RTPU_DEBUG_ALLOCATOR", "").strip().lower() not in _FALSY

    def _check(self) -> None:
        """Every page is exactly one of {free-list, refcounted,
        cached-resident}: the free list is duplicate-free and disjoint
        from the other two states, refcount entries are strictly
        positive, and no page is lost (unreachable from all three) —
        the refcount-leak class ordinary tests can't see."""
        if not self._debug:
            return
        fs = set(self._free)
        assert len(fs) == len(self._free), \
            f"duplicate pages on the free list: {sorted(self._free)}"
        assert 0 not in fs, "null page 0 on the free list"
        for p, rc in self._rc.items():
            assert rc >= 1, f"page {p} holds refcount {rc} (should be gone)"
            assert p not in fs, f"page {p} is both free and refcounted"
        for p in self._cached:
            assert p not in fs, f"page {p} is both free and cached-resident"
        for p in range(1, self.num_pages):
            assert p in fs or self._rc.get(p, 0) > 0 or p in self._cached, \
                f"page {p} leaked: not free, not referenced, not cached"

    def num_free(self) -> int:
        return len(self._free)

    def num_resident(self) -> int:
        """Cached pages with no live owner (reclaimable without preempting)."""
        return sum(1 for p in self._cached if self._rc.get(p, 0) <= 0)

    def can_allocate(self, n: int) -> bool:
        return len(self._free) >= n

    def allocate(self, n: int) -> List[int]:
        if n > len(self._free):
            raise MemoryError(f"needs {n} pages, {len(self._free)} free")
        out, self._free = self._free[:n], self._free[n:]
        for p in out:
            self._rc[p] = 1
        self._check()
        return out

    def retain(self, pages: List[int]) -> None:
        """Add a reference to already-resident pages (prefix-cache hit)."""
        for p in pages:
            if p != 0:
                self._rc[p] = self._rc.get(p, 0) + 1
        self._check()

    def refcount(self, page: int) -> int:
        return self._rc.get(page, 0)

    def is_cached(self, page: int) -> bool:
        return page in self._cached

    def free(self, pages: List[int]) -> None:
        """Release one reference; a page returns to the free list only when
        nothing references it AND it is not cached-resident."""
        for p in pages:
            if p == 0:
                continue
            rc = self._rc.get(p, 1) - 1
            if rc > 0:
                self._rc[p] = rc
                continue
            self._rc.pop(p, None)
            if p not in self._cached:
                self._free.append(p)
        self._check()

    def mark_cached(self, pages: List[int]) -> None:
        self._cached.update(p for p in pages if p != 0)
        self._check()

    def reclaim(self, page: int) -> None:
        """Cache eviction: drop residency; back to the free list if idle."""
        self._cached.discard(page)
        if self._rc.get(page, 0) <= 0:
            self._rc.pop(page, None)
            if page not in self._free:
                self._free.append(page)
        self._check()


@dataclass
class _Block:
    digest: bytes
    page: int
    parent: bytes = b""   # digest of the previous block (b"" for roots)
    root: bytes = b""     # family identity: digest of the chain's block 0
    tokens: tuple = ()    # block content, for partial (COW) matching
    # ever reused after insertion (matched by a later lookup, or walked
    # through by a sibling chain's insert): True marks the shared SPINE
    # of a family; False marks a never-reused block (a request's unique
    # tail) — the junk eviction should drain first
    was_hit: bool = False


@dataclass
class _Family:
    """Per-family heat: one entry per resident root digest."""

    hits: int = 0          # admissions that reused at least one block
    blocks: int = 0        # resident blocks in this family
    last_hit: float = 0.0  # monotonic ts of the last reuse (0 = never)


class PrefixCache:
    """Chain-hashed index of full prompt pages resident in the KV pool.

    Digest of block k = blake2b(digest of block k-1 || tokens of block k),
    so a digest identifies the entire prefix up to and including its page —
    matching is a walk from the root, never a per-page comparison (vLLM's
    block hash scheme).  Eviction is driven by the allocator owner (engine)
    when the pool runs dry and is FAMILY-aware: drain never-reused leaves
    (unique request tails) coldest-family-first across the whole pool,
    then reclaim leaf-first within the family least recently hit, never
    a block whose child blocks are still resident — so unique traffic
    drains cold chains from the tip instead of cutting hot shared roots
    out from under queued requests.
    """

    def __init__(self, page_size: int):
        self.page_size = page_size
        self._blocks: "OrderedDict[bytes, _Block]" = OrderedDict()
        self._by_page: Dict[int, bytes] = {}
        # parent digest -> digests of its RESIDENT children (b"" = roots);
        # maintained on insert/evict, so the leaf test is one dict lookup
        self._children: Dict[bytes, Set[bytes]] = {}
        self._families: Dict[bytes, _Family] = {}
        # resident-digest advertisement cap (the router's exact-digest hit
        # path degrades to the n-gram tree past it)
        self.digest_limit = int(
            os.environ.get("RTPU_PREFIX_DIGESTS", "16") or 16)
        self.hit_tokens = 0
        self.lookup_tokens = 0
        self.evictions = 0
        self.evictions_cold_family = 0
        self.evictions_hot_root_forced = 0
        self.cow_hits = 0

    # ------------------------- hashing -------------------------------

    @staticmethod
    def _chain(prev: bytes, tokens) -> bytes:
        h = hashlib.blake2b(prev, digest_size=8)
        h.update(np.asarray(tokens, np.int32).tobytes())
        return h.digest()

    @classmethod
    def digest_for(cls, tokens: List[int], page_size: int) -> Optional[str]:
        """Digest of the longest cacheable prefix of `tokens` (the P/D
        residency hint: two processes computing it agree byte-for-byte)."""
        n = len(tokens)
        blocks = max(0, (n - 1) // page_size)
        if blocks == 0:
            return None
        d = b""
        for k in range(blocks):
            d = cls._chain(d, tokens[k * page_size:(k + 1) * page_size])
        return d.hex()

    @classmethod
    def root_digest_for(cls, tokens: List[int],
                        page_size: int) -> Optional[str]:
        """Digest of `tokens`' FIRST full block — the family identity the
        KV tier addresses spine objects by (same chain hash as
        digest_for, so every process derives the same address)."""
        if len(tokens) < page_size:
            return None
        return cls._chain(b"", tokens[:page_size]).hex()

    # ------------------------- index ops -----------------------------

    def __len__(self) -> int:
        return len(self._blocks)

    def _walk(self, tokens: List[int],
              refresh: bool = True) -> Tuple[List[int], bytes, int]:
        """Longest chain of cached FULL pages covering a proper prefix;
        returns (pages, digest of the last matched block or b"", blocks
        matched).  Capped at (n-1)//page_size blocks so at least one
        suffix token is always left to prefill (the logits that seed
        decode)."""
        ps = self.page_size
        n = len(tokens)
        pages: List[int] = []
        d = b""
        for k in range(max(0, (n - 1) // ps)):
            nd = self._chain(d, tokens[k * ps:(k + 1) * ps])
            blk = self._blocks.get(nd)
            if blk is None:
                break
            if refresh:
                self._blocks.move_to_end(nd)
                blk.was_hit = True
            d = nd
            pages.append(blk.page)
        return pages, d, len(pages)

    def _touch_family(self, d: bytes) -> None:
        """Record a reuse on the family owning block `d` (heat signal for
        eviction — updated at match time, unlike the hit/lookup counters
        the caller commits only on successful admission, because queued
        retries for a family ARE demand for its pages)."""
        blk = self._blocks.get(d)
        if blk is None:
            return
        fam = self._families.get(blk.root)
        if fam is not None:
            fam.hits += 1
            fam.last_hit = time.monotonic()

    def match(self, tokens: List[int]) -> List[int]:
        """Full-page prefix match (LRU refresh + family heat only; the
        hit/lookup counters are committed by the caller on admission, so a
        request bouncing off a full pool doesn't inflate the hit rate)."""
        pages, d, _ = self._walk(tokens)
        if pages:
            self._touch_family(d)
        return pages

    def match_cow(self, tokens: List[int]) -> Tuple[List[int],
                                                    Optional[int], int]:
        """Full-page match PLUS the copy-on-write boundary: returns
        (pages, cow_src_page, cow_len).  When the first uncovered block of
        `tokens` shares its leading cow_len tokens with a resident child
        block of the matched chain, cow_src_page is that child's page —
        the engine copies it into a fresh page and prefills only from the
        divergence point, instead of recomputing the whole block."""
        pages, d, k = self._walk(tokens)
        if pages:
            self._touch_family(d)
        ps = self.page_size
        want = tokens[k * ps:(k + 1) * ps]
        # at least one suffix token must remain to prefill
        limit = min(len(want), len(tokens) - 1 - k * ps)
        if limit <= 0:
            return pages, None, 0
        best_src, best_m = None, 0
        for cd in self._children.get(d, ()):
            blk = self._blocks.get(cd)
            if blk is None:
                continue
            m = 0
            for a, b in zip(blk.tokens[:limit], want[:limit]):
                if a != b:
                    break
                m += 1
            if m > best_m:
                best_src, best_m = blk, m
        if best_src is None or best_m <= 0:
            return pages, None, 0
        self._blocks.move_to_end(best_src.digest)
        best_src.was_hit = True
        self._touch_family(best_src.digest)
        self.cow_hits += 1
        return pages, best_src.page, best_m

    def peek_match_tokens(self, tokens: List[int]) -> int:
        """Matched-token count WITHOUT LRU refresh or heat updates — the
        hit-aware admission ranking signal (scanning the waiting queue
        must not reorder eviction)."""
        pages, d, k = self._walk(tokens, refresh=False)
        ps = self.page_size
        want = tokens[k * ps:(k + 1) * ps]
        limit = min(len(want), len(tokens) - 1 - k * ps)
        best_m = 0
        if limit > 0:
            for cd in self._children.get(d, ()):
                blk = self._blocks.get(cd)
                if blk is None:
                    continue
                m = 0
                for a, b in zip(blk.tokens[:limit], want[:limit]):
                    if a != b:
                        break
                    m += 1
                best_m = max(best_m, m)
        return k * ps + best_m

    def note_lookup(self, lookup_tokens: int, hit_tokens: int) -> None:
        self.lookup_tokens += lookup_tokens
        self.hit_tokens += hit_tokens

    def insert(self, tokens: List[int], pages: List[int]) -> List[int]:
        """Register every full page of `tokens` held in `pages`; returns the
        pages newly added to the index (callers mark those cached-resident).
        A digest that already maps to some other resident page keeps the
        existing mapping — identical content, and the old page may be
        shared by live sequences."""
        ps = self.page_size
        full = min(len(tokens) // ps, len(pages))
        d = b""
        root = b""
        new_pages: List[int] = []
        for k in range(full):
            prev = d
            d = self._chain(d, tokens[k * ps:(k + 1) * ps])
            if k == 0:
                root = d
            blk = self._blocks.get(d)
            if blk is not None:
                self._blocks.move_to_end(d)
                blk.was_hit = True  # a sibling chain runs through it
                continue
            page = pages[k]
            if page == 0 or page in self._by_page:
                continue
            self._blocks[d] = _Block(
                d, page, parent=prev, root=root,
                tokens=tuple(int(t) for t in tokens[k * ps:(k + 1) * ps]))
            self._by_page[page] = d
            self._children.setdefault(prev, set()).add(d)
            self._families.setdefault(root, _Family()).blocks += 1
            new_pages.append(page)
        return new_pages

    def _remove(self, blk: _Block) -> None:
        del self._blocks[blk.digest]
        del self._by_page[blk.page]
        sibs = self._children.get(blk.parent)
        if sibs is not None:
            sibs.discard(blk.digest)
            if not sibs:
                del self._children[blk.parent]
        fam = self._families.get(blk.root)
        if fam is not None:
            fam.blocks -= 1
            if fam.blocks <= 0:
                del self._families[blk.root]
        self.evictions += 1

    def _is_leaf(self, d: bytes) -> bool:
        return not self._children.get(d)

    def evict_one(self, refcount: Callable[[int], int]
                  ) -> Optional[Tuple[int, str]]:
        """Reclaim one block: leaf-first within the COLDEST family.

        Candidates are unreferenced blocks with no resident children;
        among them the family least recently hit loses a block (never-hit
        families sort before any family with a hit), LRU within ties —
        class "cold_family".  NEVER-REUSED leaves (a request's unique
        tail: no later lookup or sibling insert ever touched the block)
        are drained across ALL families before any reused spine block is
        cut — otherwise the momentarily-coldest hot family loses spine
        pages while hotter families sit on piles of junk.  Only when
        every evictable block still has resident children (its leaves are
        all pinned) is a chain cut at an interior block, oldest first —
        class "hot_root_forced", the event the bench counts as throwing
        locality away.  Returns (page, class) or None if every block is
        pinned."""
        for spine_ok in (False, True):
            best: Optional[_Block] = None
            best_heat: Optional[Tuple[float, int]] = None
            for d, blk in self._blocks.items():  # oldest-first = LRU
                if refcount(blk.page) > 0 or not self._is_leaf(d):
                    continue
                if blk.was_hit and not spine_ok:
                    continue
                fam = self._families.get(blk.root)
                heat = ((fam.last_hit, fam.hits) if fam is not None
                        else (0.0, 0))
                if best_heat is None or heat < best_heat:
                    best, best_heat = blk, heat
            if best is not None:
                self._remove(best)
                self.evictions_cold_family += 1
                return best.page, "cold_family"
        for d, blk in list(self._blocks.items()):
            if refcount(blk.page) <= 0:
                self._remove(blk)
                self.evictions_hot_root_forced += 1
                return blk.page, "hot_root_forced"
        return None

    def digests(self, limit: Optional[int] = None) -> List[str]:
        """Most-recently-used block digests (hex) — the resident-prefix
        advertisement the request router matches P/D hints against.
        Default cap: ``RTPU_PREFIX_DIGESTS`` (pools with more hot blocks
        than the cap degrade the router to its n-gram tree)."""
        if limit is None:
            limit = self.digest_limit
        out = []
        for d in reversed(self._blocks):
            out.append(d.hex())
            if len(out) >= limit:
                break
        return out

    def family_hits(self, root: bytes) -> int:
        """Hit count of the family rooted at `root`, -1 when the family
        has no resident blocks (the KV tier's seal gate)."""
        fam = self._families.get(root)
        return fam.hits if fam is not None else -1

    def spine(self, root: bytes) -> Tuple[List[int], List[int]]:
        """The family's shared spine: from the root block down while
        exactly ONE resident child was ever reused (was_hit) — the pages
        later requests actually re-walk, and exactly what a KV-tier seal
        captures.  Unique tails (was_hit=False) and fork points (two hot
        children — the shared prefix ends where tails diverge) stop the
        walk.  Returns (tokens, pages); empty when the root is gone."""
        blk = self._blocks.get(root)
        if blk is None:
            return [], []
        toks: List[int] = list(blk.tokens)
        pages: List[int] = [blk.page]
        d = root
        while True:
            hot = [cd for cd in self._children.get(d, ())
                   if (b := self._blocks.get(cd)) is not None and b.was_hit]
            if len(hot) != 1:
                break
            d = hot[0]
            b = self._blocks[d]
            toks.extend(b.tokens)
            pages.append(b.page)
        return toks, pages

    def family_stats(self) -> List[dict]:
        """Per-family heat rows, hottest first (debug/CLI view)."""
        rows = [{"root": root.hex(), "blocks": fam.blocks,
                 "hits": fam.hits,
                 "last_hit_age_s": round(
                     time.monotonic() - fam.last_hit, 3)
                 if fam.last_hit else None}
                for root, fam in self._families.items()]
        rows.sort(key=lambda r: (r["last_hit_age_s"] is None,
                                 r["last_hit_age_s"] or 0.0))
        return rows

    def stats(self) -> dict:
        return {
            "blocks": len(self._blocks),
            "families": len(self._families),
            "hit_tokens": self.hit_tokens,
            "lookup_tokens": self.lookup_tokens,
            "evictions": self.evictions,
            "evictions_cold_family": self.evictions_cold_family,
            "evictions_hot_root_forced": self.evictions_hot_root_forced,
            "cow_hits": self.cow_hits,
            "digest_limit": self.digest_limit,
            "hit_rate": round(self.hit_tokens / self.lookup_tokens, 4)
            if self.lookup_tokens else 0.0,
        }
