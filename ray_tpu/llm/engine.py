"""Continuous-batching LLM engine for TPU.

Counterpart of the vLLM engine the reference wraps
(/root/reference/python/ray/llm/_internal/serve/deployments/llm/vllm/
vllm_engine.py:181, engine start :312): an admission queue + slot table in
front of two compiled programs — a per-bucket prefill and ONE batched decode
step (llm/model.py).  The scheduler thread admits waiting requests into free
slots whenever pages are available (prefill), then advances every active
slot one token per iteration (decode), streaming tokens into per-request
queues.  Static shapes throughout: no recompiles after warmup.
"""

from __future__ import annotations

import os
import queue as queue_mod
import threading
import time
import uuid
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.llm import model as lm
from ray_tpu.llm.kv_tier import KVPullError
from ray_tpu.llm.paged_cache import (CacheConfig, PageAllocator, PrefixCache,
                                     init_cache)
from ray_tpu.models.llama import LlamaConfig
from ray_tpu.util import tracing

# Serving observability (ISSUE 8): the engine-local stats() dict stays the
# cheap in-process view, but the same events also feed util.metrics so
# TTFT/TPOT/e2e land on /metrics as real histograms and ride the existing
# metrics push plane.  Created lazily once per process; every engine in
# the process shares the instruments.
_METRICS = None
_metrics_lock = threading.Lock()


def _engine_metrics():
    global _METRICS
    with _metrics_lock:
        if _METRICS is None:
            from ray_tpu.util.metrics import Counter, Gauge, Histogram

            _METRICS = {
                "ttft": Histogram(
                    "llm_ttft_s", "Time to first token (submit -> first "
                    "emitted token)"),
                "tpot": Histogram(
                    "llm_tpot_s", "Time per output token after the first "
                    "(decode steady state)"),
                "e2e": Histogram(
                    "llm_e2e_s", "End-to-end request latency (submit -> "
                    "stream end)"),
                "queue_wait": Histogram(
                    "llm_queue_wait_s", "Submit -> admission wait (slot + "
                    "pages available)"),
                "prefill_t": Histogram(
                    "llm_prefill_s", "Prefill compute time per request"),
                "prefills": Counter(
                    "llm_prefills_total", "Prefill executions"),
                "decode_steps": Counter(
                    "llm_decode_steps_total", "Batched decode steps"),
                "tokens": Counter(
                    "llm_tokens_total", "Tokens emitted to callers"),
                "admitted": Counter(
                    "llm_admitted_total", "Requests admitted to slots"),
                "preempted": Counter(
                    "llm_preempted_total", "Requests preempted/evicted "
                    "from their slot"),
                "prefix_hit": Counter(
                    "llm_prefix_hit_tokens_total", "Prompt tokens served "
                    "from resident prefix-cache pages"),
                "prefix_lookup": Counter(
                    "llm_prefix_lookup_tokens_total", "Prompt tokens "
                    "looked up against the prefix cache"),
                "page_evictions": Counter(
                    "llm_page_evictions_total", "Prefix-cache pages "
                    "reclaimed to satisfy allocations"),
                "prefill_saved": Counter(
                    "llm_prefill_tokens_saved_total", "Prompt tokens whose "
                    "prefill compute was skipped via resident prefix pages "
                    "or a COW boundary page"),
                "cache_evictions": Counter(
                    "llm_cache_evictions_total", "Prefix-cache block "
                    "evictions by class: cold_family (leaf of the least "
                    "recently hit family) vs hot_root_forced (chain cut "
                    "while its leaves were pinned)",
                    tag_keys=("class",)),
                "cow_copies": Counter(
                    "llm_cow_page_copies_total", "Copy-on-write boundary "
                    "page duplications (partial-block prefix reuse)"),
                "kv_seals": Counter(
                    "llm_kv_seals_total", "Hot family spines sealed into "
                    "the store-backed KV tier"),
                "kv_pulls": Counter(
                    "llm_kv_pulls_total", "Family spines pulled from the "
                    "KV tier and hydrated into the page pool"),
                "kv_pull_pages": Counter(
                    "llm_kv_pull_pages_total", "KV pages hydrated from "
                    "tier pulls (cold prefill compute avoided)"),
                "kv_pull_fallbacks": Counter(
                    "llm_kv_pull_fallbacks_total", "KV tier pulls that "
                    "fell back to cold prefill, by typed failure reason "
                    "(miss/evicted/store_died/truncated/corrupt/no_pages)",
                    tag_keys=("reason",)),
                "prefix_resident": Gauge(
                    "llm_prefix_resident_pages", "Cached-resident KV "
                    "pages with no live owner"),
                "active_slots": Gauge(
                    "llm_active_slots", "Decode slots currently occupied"),
                "free_pages": Gauge(
                    "llm_free_pages", "Allocatable KV-cache pages free"),
                "page_occupancy": Gauge(
                    "llm_page_occupancy", "Fraction of allocatable KV "
                    "pages in use"),
                "waiting": Gauge(
                    "llm_waiting", "Requests queued awaiting admission"),
            }
        return _METRICS


def _inject_kv_pages_impl(cache_k, cache_v, idx, kv_k, kv_v):
    """Scatter shipped KV pages into the paged cache (P/D decode side).

    Donation makes this an in-place page write — without it every
    disaggregated admission would copy the whole multi-GiB cache.
    """
    return (cache_k.at[:, idx].set(kv_k), cache_v.at[:, idx].set(kv_v))


_inject_kv_pages = jax.jit(_inject_kv_pages_impl, donate_argnums=(0, 1))


@dataclass
class EngineConfig:
    max_slots: int = 8  # concurrent sequences in the decode batch
    num_pages: int = 512
    page_size: int = 16
    max_seq_len: int = 1024
    prefill_buckets: tuple = (32, 64, 128, 256, 512, 1024)

    def bucket_for(self, n: int) -> int:
        for b in self.prefill_buckets:
            if n <= b:
                return b
        raise ValueError(f"prompt length {n} exceeds largest bucket "
                         f"{self.prefill_buckets[-1]}")


@dataclass
class SamplingParams:
    max_tokens: int = 64
    temperature: float = 0.0  # 0 => greedy
    top_p: float = 1.0
    stop_token_ids: tuple = ()
    seed: Optional[int] = None


@dataclass
class _Request:
    request_id: str
    prompt_tokens: List[int]
    params: SamplingParams
    out_queue: queue_mod.Queue = field(default_factory=queue_mod.Queue)
    submitted_at: float = field(default_factory=time.monotonic)
    # P/D disaggregation (reference: serve prefill_decode_disagg.py):
    # "normal" | "prefill_only" (run prefill, ship KV pages + first token)
    # | "decode_kv" (inject shipped KV, skip prefill compute entirely)
    kind: str = "normal"
    first_token: Optional[int] = None  # decode_kv: token prefill sampled
    kv: Optional[tuple] = None  # decode_kv: (kv_k, kv_v) page arrays
    first_token_at: Optional[float] = None  # monotonic ts of first emit
    emitted: int = 0  # tokens delivered to the caller
    # Tokens produced toward max_tokens, surviving preemption/resume: a
    # preempted request folds its generated tokens into the prompt, so
    # len(slot.generated) restarts from zero while `produced` does not.
    produced: int = 0
    # Per-request trace anatomy (ISSUE 20): the submitting thread's
    # (trace_id, parent span_id) captured at submit; the scheduler thread
    # has no thread-local context, so every phase span it records carries
    # this explicitly.  span_id is the umbrella "llm.request" span phase
    # spans parent under; submitted_wall anchors it on the wall clock
    # (spans are wall-time; submitted_at stays monotonic for latency math).
    trace_ctx: Optional[tuple] = None
    span_id: Optional[str] = None
    submitted_wall: float = field(default_factory=time.time)
    preempts: int = 0


@dataclass
class _Slot:
    request: _Request
    pages: List[int]
    num_tokens: int  # tokens with KV in cache (prompt + generated)
    last_token: int
    generated: List[int] = field(default_factory=list)
    rng: Optional[np.random.Generator] = None


class LLMEngine:
    """Single-process engine; wrap in an actor for serving (server.py)."""

    def __init__(self, params, model_cfg: LlamaConfig,
                 cfg: Optional[EngineConfig] = None, kv_tier=None):
        self.cfg = cfg or EngineConfig()
        self.model_cfg = model_cfg
        self.params = params
        ccfg = CacheConfig(
            n_layers=model_cfg.n_layers, n_kv_heads=model_cfg.n_kv_heads,
            head_dim=model_cfg.head_dim, num_pages=self.cfg.num_pages,
            page_size=self.cfg.page_size, dtype=model_cfg.dtype)
        self.cache_k, self.cache_v = init_cache(ccfg)
        self.allocator = PageAllocator(self.cfg.num_pages)
        # Prefix caching (ISSUE 10): finished sequences leave their full
        # prompt pages resident; later prompts sharing a page-aligned
        # prefix skip that prefill compute.  A pure index over pages — all
        # page ownership still flows through self.allocator, so swapping
        # the allocator (tests do) starts from an empty, consistent state.
        self.prefix_cache: Optional[PrefixCache] = (
            PrefixCache(self.cfg.page_size)
            if os.environ.get("RTPU_PREFIX_CACHE", "1").lower()
            not in ("0", "false") else None)
        self.max_pages_per_seq = -(-self.cfg.max_seq_len
                                   // self.cfg.page_size)
        # Store-backed KV tier (ISSUE 16): hot family spines seal into
        # the shm store and failure/spill paths pull them back instead
        # of cold-prefilling.  All tier I/O (seal extraction, pull
        # hydration) runs on the scheduler thread — the single-writer
        # contract below covers it; kv_prehydrate() crosses threads only
        # through the thread-safe _hydrate_q.
        self.kv_tier = kv_tier
        self._hydrate_q: queue_mod.Queue = queue_mod.Queue()
        self._waiting: queue_mod.Queue = queue_mod.Queue()
        # Single-writer design: _slots, the allocator, and _stats are
        # mutated ONLY by the scheduler thread (_loop); other threads
        # submit through the thread-safe _waiting queue and read counters
        # via stats(), whose individual reads are GIL-atomic.  Do not add
        # cross-thread mutation without introducing a real lock.
        self._slots: List[Optional[_Slot]] = [None] * self.cfg.max_slots
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # decode-state host mirrors (device arrays rebuilt when they change)
        self._stats = {"prefills": 0, "decode_steps": 0,
                       "tokens_generated": 0, "preempted": 0,
                       "admitted": 0, "page_evictions": 0,
                       "prefill_tokens_saved": 0, "cow_copies": 0,
                       "kv_seals": 0, "kv_pulls": 0, "kv_pull_pages": 0,
                       "kv_pull_fallbacks": 0}
        # Hit-aware admission (ISSUE 14): under pool pressure prefer the
        # waiting request whose prefix is resident, but never once the
        # head of the queue has waited longer than this cap (seconds) —
        # bounded unfairness, misses can't starve.
        self._admit_age_cap_s = float(
            os.environ.get("RTPU_ADMIT_AGE_CAP_S", "0.25") or 0.25)
        # Queue/admission observability (VERDICT round-2: the serving
        # bench conflated queue wait with prefill; these separate them):
        # recent per-request queue waits (submit -> admission) and prefill
        # compute times, rings of the last 128.
        self._queue_waits: "deque[float]" = deque(maxlen=128)
        self._prefill_times: "deque[float]" = deque(maxlen=128)
        self._m = _engine_metrics()
        self._gauges_at = 0.0  # last gauge refresh (throttled in _loop)

    # ------------------------- public API ---------------------------------

    def start(self):
        if self._thread is None:
            self._thread = threading.Thread(target=self._loop, daemon=True)
            self._thread.start()

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)

    def submit(self, prompt_tokens: List[int],
               params: Optional[SamplingParams] = None) -> _Request:
        params = params or SamplingParams()
        total = len(prompt_tokens) + params.max_tokens
        if total > self.cfg.max_seq_len:
            raise ValueError(
                f"prompt+max_tokens = {total} exceeds max_seq_len "
                f"{self.cfg.max_seq_len}")
        # Page 0 is the reserved null page, so only num_pages-1 are ever
        # allocatable: an infeasible request would otherwise sit at the
        # queue head forever, wedging the engine for everyone behind it.
        n_pages = -(-total // self.cfg.page_size)
        if n_pages > self.cfg.num_pages - 1:
            raise ValueError(
                f"request needs {n_pages} KV pages but the cache has only "
                f"{self.cfg.num_pages - 1} allocatable pages")
        req = _Request(request_id=uuid.uuid4().hex[:12],
                       prompt_tokens=list(prompt_tokens), params=params)
        self._trace_init(req)
        self._waiting.put(req)
        return req

    def prefill_extract(self, prompt_tokens: List[int],
                        params: Optional[SamplingParams] = None,
                        timeout_s: float = 300.0):
        """P/D disaggregation, prefill side: run ONLY the prefill, sample
        the first token, and return (first_token, kv_k, kv_v, n_tokens) —
        the KV page arrays a decode engine injects via submit_with_kv.
        Pages are freed here immediately; this engine keeps no state."""
        self.start()
        params = params or SamplingParams()
        req = _Request(request_id=uuid.uuid4().hex[:12],
                       prompt_tokens=list(prompt_tokens), params=params,
                       kind="prefill_only")
        n_pages = -(-len(prompt_tokens) // self.cfg.page_size)
        if n_pages > self.cfg.num_pages - 1:
            raise ValueError(f"prompt needs {n_pages} KV pages > capacity")
        self._trace_init(req)
        self._waiting.put(req)
        item = req.out_queue.get(timeout=timeout_s)
        if isinstance(item, Exception):
            raise item
        tag, first, kv_k, kv_v = item
        assert tag == "prefill_done"
        req.out_queue.get(timeout=timeout_s)  # drain the None terminator
        return first, kv_k, kv_v, len(prompt_tokens)

    def submit_with_kv(self, prompt_tokens: List[int], first_token: int,
                       kv_k, kv_v,
                       params: Optional[SamplingParams] = None) -> _Request:
        """P/D disaggregation, decode side: admit a sequence whose prompt
        KV was computed elsewhere. No prefill compute happens here."""
        self.start()
        params = params or SamplingParams()
        total = len(prompt_tokens) + params.max_tokens
        if total > self.cfg.max_seq_len:
            raise ValueError(f"prompt+max_tokens {total} > max_seq_len")
        n_pages = -(-total // self.cfg.page_size)
        if n_pages > self.cfg.num_pages - 1:
            # same guard as submit(): an infeasible request would sit at
            # the queue head forever, wedging the engine
            raise ValueError(
                f"request needs {n_pages} KV pages but the cache has only "
                f"{self.cfg.num_pages - 1} allocatable pages")
        req = _Request(request_id=uuid.uuid4().hex[:12],
                       prompt_tokens=list(prompt_tokens), params=params,
                       kind="decode_kv", first_token=int(first_token),
                       kv=(kv_k, kv_v))
        self._trace_init(req)
        self._waiting.put(req)
        return req

    def generate(self, prompt_tokens: List[int],
                 params: Optional[SamplingParams] = None,
                 timeout_s: float = 300.0) -> List[int]:
        """Blocking convenience: submit + drain to completion."""
        self.start()
        req = self.submit(prompt_tokens, params)
        out: List[int] = []
        deadline = time.monotonic() + timeout_s
        while True:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise TimeoutError(f"generation {req.request_id} timed out")
            item = req.out_queue.get(timeout=remaining)
            if item is None:
                return out
            if isinstance(item, Exception):
                raise item
            out.append(item)

    def stats(self) -> dict:
        active = sum(s is not None for s in self._slots)

        def _pctile(ring, frac):
            # the scheduler thread appends concurrently; a mid-iteration
            # append at maxlen pops the head and invalidates the iterator
            for _ in range(4):
                try:
                    xs = sorted(ring)
                    break
                except RuntimeError:
                    continue
            else:
                return None
            return round(xs[int((len(xs) - 1) * frac)] * 1e3, 2) \
                if xs else None

        pc = self.prefix_cache
        # per-family heat rows (root digest hex + hits + resident blocks):
        # the controller's KV replication policy ranks families across
        # replicas from these.  family_stats iterates a dict the scheduler
        # thread mutates — retry like _pctile.
        kv_families: List[dict] = []
        if pc is not None:
            for _ in range(4):
                try:
                    kv_families = pc.family_stats()[:8]
                    break
                except RuntimeError:
                    continue
        return {**self._stats, "active_slots": active,
                "kv_families": kv_families,
                "kv_tier": (self.kv_tier.stats()
                            if self.kv_tier is not None else None),
                "free_pages": self.allocator.num_free(),
                "waiting": self._waiting.qsize(),
                # prefix-cache plane (ISSUE 10): hit/miss + resident pages
                # + recent block digests — the router's KV-locality signal
                "prefix_cache": pc.stats() if pc is not None else None,
                "resident_pages": self.allocator.num_resident(),
                "prefix_digests": pc.digests() if pc is not None else [],
                # admission observability: time requests spent queued
                # before a slot/pages freed up, vs pure prefill compute
                "p50_queue_wait_ms": _pctile(self._queue_waits, 0.5),
                "p90_queue_wait_ms": _pctile(self._queue_waits, 0.9),
                "p50_prefill_ms": _pctile(self._prefill_times, 0.5),
                "p90_prefill_ms": _pctile(self._prefill_times, 0.9)}

    # ------------------------- scheduler loop ------------------------------

    def _loop(self):
        while not self._stop.is_set():
            try:
                hydrated = self._drain_hydrations()
                admitted = self._admit()
                stepped = self._decode_all()
            except Exception as e:  # noqa: BLE001 — a dead scheduler
                # thread would hang every generate() forever; fail the
                # in-flight requests loudly instead and keep serving.
                import traceback

                traceback.print_exc()
                for i, s in enumerate(self._slots):
                    if s is not None:
                        s.request.out_queue.put(e)
                        s.request.out_queue.put(None)
                        self.allocator.free(s.pages)
                        self._slots[i] = None
                while True:
                    try:
                        req = self._waiting.get_nowait()
                    except queue_mod.Empty:
                        break
                    req.out_queue.put(e)
                    req.out_queue.put(None)
                continue
            now = time.monotonic()
            if now - self._gauges_at >= 0.25:
                self._gauges_at = now
                self._refresh_gauges()
            if not admitted and not stepped and not hydrated:
                time.sleep(0.002)

    def _refresh_gauges(self):
        m = self._m
        free = self.allocator.num_free()
        allocatable = self.cfg.num_pages - 1  # page 0 is the null page
        m["active_slots"].set(sum(s is not None for s in self._slots))
        m["free_pages"].set(free)
        if allocatable > 0:
            m["page_occupancy"].set(1.0 - free / allocatable)
        m["waiting"].set(self._waiting.qsize())
        m["prefix_resident"].set(self.allocator.num_resident())

    # -------------------- per-request trace anatomy (ISSUE 20) -------------

    def _trace_init(self, req: _Request) -> None:
        """Capture the submitting thread's trace context onto the request
        so the scheduler thread can stamp phase spans for it."""
        ctx = tracing.current_context()
        if ctx is not None:
            req.trace_ctx = ctx
            req.span_id = tracing.new_span_id()

    def _span(self, req: _Request, name: str, t0: float, t1: float,
              ok: bool = True, **attrs) -> None:
        """One phase span under the request's umbrella span."""
        if req.trace_ctx is None:
            return
        tracing.record_span(
            req.trace_ctx[0], name, t0, t1, parent_id=req.span_id,
            kind="engine", ok=ok,
            attrs=dict(attrs, request_id=req.request_id))

    def _close_request_span(self, req: _Request, ok: bool = True,
                            **attrs) -> None:
        """Close the umbrella "llm.request" span (submit -> stream end),
        parented under whatever the submitter was doing (replica task
        span, SSE generator, P/D decode span)."""
        if req.trace_ctx is None or req.span_id is None:
            return
        tracing.record_span(
            req.trace_ctx[0], "llm.request", req.submitted_wall,
            time.time(), parent_id=req.trace_ctx[1], span_id=req.span_id,
            kind="engine", ok=ok,
            attrs=dict(attrs, request_id=req.request_id,
                       req_kind=req.kind, preempts=req.preempts))
        req.span_id = None  # closed exactly once

    def _finish_request(self, req: _Request):
        """Latency histograms at stream end (successful finishes only;
        prefill_only requests are half a request and are skipped)."""
        if req.kind == "prefill_only":
            return
        now = time.monotonic()
        tid = req.trace_ctx[0] if req.trace_ctx else None
        self._m["e2e"].observe(now - req.submitted_at, exemplar=tid)
        if req.first_token_at is not None and req.emitted > 1:
            self._m["tpot"].observe(
                (now - req.first_token_at) / (req.emitted - 1),
                exemplar=tid)
        if req.trace_ctx is not None:
            w_now = time.time()
            if req.first_token_at is not None:
                # decode aggregate: first token -> stream end (per-step
                # spans would be noise; contention shows up as the gap
                # between this span's rate and the prefill-adjacent TPOT)
                self._span(req, "llm.decode",
                           w_now - max(0.0, now - req.first_token_at),
                           w_now, tokens=req.emitted,
                           preempts=req.preempts)
            self._close_request_span(req, ok=True, tokens=req.emitted)

    def _pick_waiting(self) -> Optional[_Request]:
        """Next request to admit: FIFO normally; under pool pressure (the
        head's pages aren't free) prefer the waiting request with the most
        prefix tokens resident — admitting a hit costs fewer fresh pages
        and zero evictions, so it unblocks the queue faster than forcing
        the head in.  Bounded: once the head has waited RTPU_ADMIT_AGE_CAP_S
        it goes next regardless, so misses can't starve.  Scans only the
        first 8 waiters via peek (no LRU refresh — ranking must not
        reorder eviction)."""
        q = self._waiting.queue  # type: ignore[attr-defined]
        if not q:
            return None
        head = q[0]
        pc = self.prefix_cache
        pressure = False
        if pc is not None and head.kind == "normal":
            need = len(head.prompt_tokens) // self.cfg.page_size + 1
            pressure = self.allocator.num_free() < need
        if (not pressure or time.monotonic() - head.submitted_at
                >= self._admit_age_cap_s):
            try:
                return self._waiting.get_nowait()
            except queue_mod.Empty:
                return None
        best_i, best_m = 0, -1
        for i in range(min(8, len(q))):
            r = q[i]
            if r.kind != "normal":
                continue
            m = pc.peek_match_tokens(r.prompt_tokens)
            if m > best_m:
                best_i, best_m = i, m
        try:
            req = q[best_i]
            del q[best_i]
        except IndexError:  # drained between len() and del (benign)
            return None
        return req

    def _admit(self) -> bool:
        """Move waiting requests into free slots while pages last
        (vLLM analogue: Scheduler admitting to the running batch)."""
        admitted = False
        while True:
            req = self._pick_waiting()
            if req is None:
                return admitted
            # prefill_only completes inline and occupies no decode slot, so
            # it is admitted even with all slots busy (only pages gate it)
            if req.kind != "prefill_only":
                free_slot = next((i for i, s in enumerate(self._slots)
                                  if s is None), None)
                if free_slot is None:
                    self._waiting.queue.appendleft(req)  # type: ignore[attr-defined]
                    return admitted
            if req.kind == "prefill_only":
                # KV only lives for the prefill compute+extract; afterwards
                # the full prompt pages stay CACHED-RESIDENT (not freed),
                # so repeat prefills of shared prompts and the P/D decode
                # hand-back both find warm pages.
                n_pages = -(-len(req.prompt_tokens) // self.cfg.page_size)
                if not self._reserve(n_pages):
                    self._waiting.queue.appendleft(req)  # type: ignore[attr-defined]
                    return admitted
                pages = self.allocator.allocate(n_pages)
                rng = (np.random.default_rng(req.params.seed)
                       if req.params.temperature > 0 else None)
                try:
                    last = self._prefill(req, pages, rng)
                    idx = np.asarray(pages)
                    kv_k = np.asarray(self.cache_k[:, idx])
                    kv_v = np.asarray(self.cache_v[:, idx])
                    req.out_queue.put(("prefill_done", last, kv_k, kv_v))
                    req.out_queue.put(None)
                    self._register_blocks(req.prompt_tokens, pages)
                    # P/D tier handoff: seal regardless of family heat —
                    # the sealed spine IS the page transfer the decode
                    # engine pulls (pd_disagg ships only the digest)
                    self._maybe_seal(req.prompt_tokens, force=True)
                    self._close_request_span(req)
                except Exception as e:  # noqa: BLE001
                    req.out_queue.put(e)
                    req.out_queue.put(None)
                    self._close_request_span(req, ok=False)
                finally:
                    self.allocator.free(pages)
                admitted = True
                continue
            # Lazy allocation (ISSUE 10): admit with just the pages the
            # prompt + the first decode write need; _ensure_capacity grows
            # the slot as decode advances, evicting cache LRU or preempting
            # when the pool runs dry.  Admitting lazily is what lets the
            # pool oversubscribe — the load wall the serving bench climbs.
            n = len(req.prompt_tokens)
            matched: List[int] = []
            cow_src: Optional[int] = None
            cow_len = 0
            if self.prefix_cache is not None and req.kind == "normal":
                # KV tier pull (ISSUE 16): if this prompt's family has a
                # deeper spine sealed in the store than is locally
                # resident (imbalance shed, P/D tier handoff, failover
                # from a killed replica), hydrate it FIRST so match_cow
                # below finds warm pages instead of cold-prefilling.
                if self.kv_tier is not None:
                    t_pull = time.time()
                    outcome, pulled = self._maybe_tier_pull(
                        req.prompt_tokens, req=req)
                    if outcome is not None:
                        self._span(req, "llm.kv_pull", t_pull, time.time(),
                                   ok=outcome in ("resident", "hydrated"),
                                   outcome=outcome, pages=pulled)
                matched, cow_src, cow_len = \
                    self.prefix_cache.match_cow(req.prompt_tokens)
            need_total = n // self.cfg.page_size + 1
            # pin matched pages — and the COW source, which eviction in
            # _reserve would otherwise happily reclaim before the copy —
            # BEFORE eviction can consider them
            pin = matched + ([cow_src] if cow_src is not None else [])
            self.allocator.retain(pin)
            if not self._reserve(need_total - len(matched)):
                self.allocator.free(pin)  # unpin; stays resident
                self._waiting.queue.appendleft(req)  # type: ignore[attr-defined]
                return admitted
            pages = matched + self.allocator.allocate(
                need_total - len(matched))
            prefix_len = len(matched) * self.cfg.page_size
            rng = (np.random.default_rng(req.params.seed)
                   if req.params.temperature > 0 else None)
            try:
                if req.kind == "decode_kv":
                    # Inject the shipped KV pages; skip prefill compute.
                    # Donated jitted scatter: in-place page update, not a
                    # whole-cache copy per admission. Shapes are padded to
                    # max_pages_per_seq so ONE compilation serves every
                    # request (page 0 is the scratch/null page; writing it
                    # matches prefill's existing padded-position behavior).
                    kv_k, kv_v = req.kv
                    req.kv = None  # free the host copy promptly
                    src = kv_k.shape[1]
                    P = self.max_pages_per_seq
                    idx = np.zeros(P, np.int32)
                    idx[:src] = pages[:src]
                    pad = ((0, 0), (0, P - src), (0, 0), (0, 0), (0, 0))
                    kv_k = np.pad(kv_k, pad) if src < P else kv_k
                    kv_v = np.pad(kv_v, pad) if src < P else kv_v
                    self.cache_k, self.cache_v = _inject_kv_pages(
                        self.cache_k, self.cache_v, jnp.asarray(idx),
                        jnp.asarray(kv_k, self.cache_k.dtype),
                        jnp.asarray(kv_v, self.cache_v.dtype))
                    last = int(req.first_token)
                    # no prefill here, so stamp the admission wait itself
                    qw = max(0.0, time.monotonic() - req.submitted_at)
                    self._span(req, "llm.queue", req.submitted_wall,
                               req.submitted_wall + qw,
                               wait_s=round(qw, 6))
                else:
                    if cow_src is not None:
                        # COW boundary page: duplicate the diverging
                        # block's page into this sequence's first fresh
                        # page, then prefill only past the shared slots.
                        # Slots >= cow_len hold the OTHER sequence's KV,
                        # but the suffix prefill overwrites every one of
                        # them before attention reads it (null-page
                        # invariant).
                        dst = pages[len(matched)]
                        self.cache_k, self.cache_v = lm.copy_page(
                            self.cache_k, self.cache_v,
                            jnp.int32(cow_src), jnp.int32(dst))
                        prefix_len += cow_len
                        self._stats["cow_copies"] += 1
                        self._m["cow_copies"].inc()
                    last = self._prefill(req, pages, rng, prefix_len)
            except Exception as e:  # noqa: BLE001 — surface to caller
                self.allocator.free(pages)
                req.out_queue.put(e)
                req.out_queue.put(None)
                self._close_request_span(req, ok=False, error=repr(e))
                continue
            finally:
                if cow_src is not None:
                    self.allocator.free([cow_src])  # drop the copy pin
            if self.prefix_cache is not None and req.kind == "normal":
                # commit hit/lookup accounting only on successful admission
                # (a request bouncing off a full pool retries its match)
                self.prefix_cache.note_lookup(n, prefix_len)
                self._m["prefix_lookup"].inc(n)
                self._stats["prefill_tokens_saved"] += prefix_len
                if prefix_len:
                    self._m["prefix_hit"].inc(prefix_len)
                    self._m["prefill_saved"].inc(prefix_len)
            # every full prompt page — freshly computed or injected — is
            # now index-able for later prompts sharing the prefix
            self._register_blocks(req.prompt_tokens, pages)
            slot = _Slot(request=req, pages=pages,
                         num_tokens=len(req.prompt_tokens),
                         last_token=last, rng=rng)
            if last in req.params.stop_token_ids:
                self._finish_request(req)
                req.out_queue.put(None)
                self.allocator.free(pages)
            else:
                slot.generated.append(last)
                if req.kind == "decode_kv":
                    # the prefill engine already delivered this token to
                    # the caller; count it, don't re-emit
                    self._stats["tokens_generated"] += 1
                    req.produced += 1
                else:
                    self._emit(slot, last)
                if req.produced >= req.params.max_tokens:
                    self._finish_request(req)
                    req.out_queue.put(None)
                    self.allocator.free(pages)
                else:
                    self._slots[free_slot] = slot
            admitted = True

    def _prefill(self, req: _Request, pages: List[int],
                 rng: Optional[np.random.Generator],
                 prefix_len: int = 0) -> int:
        n = len(req.prompt_tokens)
        ps = self.cfg.page_size
        t0 = time.monotonic()
        if prefix_len > 0:
            # prefix-cache hit: pages[:prefix_len//ps] already hold the
            # prefix KV; compute only the suffix, attending through the
            # full page table (suffix writes never touch shared pages —
            # every write position is >= prefix_len)
            suffix = req.prompt_tokens[prefix_len:]
            ls = len(suffix)
            bucket = self.cfg.bucket_for(ls)
            tokens = np.zeros(bucket, np.int32)
            tokens[:ls] = suffix
            positions = prefix_len + np.arange(bucket, dtype=np.int32)
            page_rows = np.zeros(bucket, np.int32)
            for i in range(bucket):
                pi = (prefix_len + i) // ps
                page_rows[i] = pages[pi] if pi < len(pages) else 0
            slot_positions = positions % ps
            table = np.zeros(self.max_pages_per_seq, np.int32)
            table[:len(pages)] = pages
            logits, self.cache_k, self.cache_v = lm.prefill_with_prefix(
                self.params, jnp.asarray(tokens), self.cache_k,
                self.cache_v, jnp.asarray(page_rows), jnp.int32(ls),
                jnp.asarray(slot_positions), jnp.asarray(table),
                jnp.asarray(positions), self.model_cfg)
        else:
            bucket = self.cfg.bucket_for(n)
            tokens = np.zeros(bucket, np.int32)
            tokens[:n] = req.prompt_tokens
            # map each padded position to (page, slot); positions beyond
            # the allocated pages land in the null page (masked out of
            # attention)
            page_rows = np.zeros(bucket, np.int32)
            for i in range(bucket):
                pi = i // ps
                page_rows[i] = pages[pi] if pi < len(pages) else 0
            slot_positions = np.arange(bucket, dtype=np.int32) % ps
            logits, self.cache_k, self.cache_v = lm.prefill(
                self.params, jnp.asarray(tokens), self.cache_k,
                self.cache_v, jnp.asarray(page_rows), jnp.int32(n),
                jnp.asarray(slot_positions), self.model_cfg)
        out = self._sample_one(np.asarray(logits), req.params, rng)
        self._stats["prefills"] += 1
        dt = time.monotonic() - t0
        self._prefill_times.append(dt)
        self._queue_waits.append(t0 - req.submitted_at)
        self._stats["admitted"] += 1
        self._m["prefills"].inc()
        self._m["admitted"].inc()
        tid = req.trace_ctx[0] if req.trace_ctx else None
        self._m["prefill_t"].observe(dt, exemplar=tid)
        qw = max(0.0, t0 - req.submitted_at)
        self._m["queue_wait"].observe(qw, exemplar=tid)
        if req.trace_ctx is not None:
            w_end = time.time()
            self._span(req, "llm.queue", req.submitted_wall,
                       req.submitted_wall + qw, wait_s=round(qw, 6))
            self._span(req, "llm.prefill", w_end - dt, w_end, tokens=n,
                       prefix_len=prefix_len, resumed=bool(req.preempts))
        if req.preempts:
            try:
                from ray_tpu.util import events

                events.emit(
                    "llm.resume",
                    message=f"request {req.request_id} resumed after "
                            f"preemption (prefix_len={prefix_len})",
                    data={"request_id": req.request_id,
                          "preempts": req.preempts,
                          "prefix_len": prefix_len},
                    trace_id=tid)
            except Exception:
                pass
        return out

    def _reserve(self, n: int) -> bool:
        """Make n pages allocatable, reclaiming LRU prefix-cache pages as
        needed.  Returns False (leaving partial reclaims in place — they
        were the coldest blocks anyway) if the pool can't cover it."""
        if n <= 0:
            return True
        pc = self.prefix_cache
        while self.allocator.num_free() < n:
            hit = pc.evict_one(self.allocator.refcount) \
                if pc is not None else None
            if hit is None:
                return False
            page, klass = hit
            self.allocator.reclaim(page)
            self._stats["page_evictions"] += 1
            self._m["page_evictions"].inc()
            self._m["cache_evictions"].inc(1, {"class": klass})
        return True

    def _register_blocks(self, tokens: List[int], pages: List[int]) -> None:
        if self.prefix_cache is None:
            return
        cached = self.prefix_cache.insert(tokens, pages)
        self.allocator.mark_cached(cached)
        self._maybe_seal(tokens)

    # ------------------------- KV tier (ISSUE 16) --------------------------

    def kv_prehydrate(self, roots: List[str]) -> None:
        """Ask the engine to pull these family spines from the KV tier
        (controller replication fan-out / warm restart).  Thread-safe:
        roots queue through _hydrate_q and the scheduler thread performs
        the actual pool mutation in _drain_hydrations."""
        self.start()
        for r in roots or ():
            self._hydrate_q.put(str(r))

    def _tier_expect(self) -> dict:
        return {"page_size": self.cfg.page_size,
                "layers": self.model_cfg.n_layers,
                "kv_heads": self.model_cfg.n_kv_heads,
                "head_dim": self.model_cfg.head_dim,
                "dtype": str(np.dtype(self.cache_k.dtype))}

    def _kv_fallback(self, reason: str,
                     req: Optional[_Request] = None) -> None:
        self._stats["kv_pull_fallbacks"] += 1
        self._m["kv_pull_fallbacks"].inc(tags={"reason": reason})
        try:
            from ray_tpu.util import events

            data: Dict[str, Any] = {"reason": reason}
            if req is not None:
                data["request_id"] = req.request_id
            events.emit("kv.pull_fallback", severity="warning",
                        message=f"KV tier pull fell back to cold prefill "
                                f"({reason})", data=data,
                        trace_id=(req.trace_ctx[0]
                                  if req is not None and req.trace_ctx
                                  else None),
                        # identity-bearing events must not merge
                        coalesce_s=0.0 if req is not None else 1.0)
        except Exception:
            pass

    def _note_kv_pull(self, pages: int,
                      req: Optional[_Request] = None) -> None:
        self._stats["kv_pulls"] += 1
        self._stats["kv_pull_pages"] += pages
        self._m["kv_pulls"].inc()
        self._m["kv_pull_pages"].inc(pages)
        try:
            from ray_tpu.util import events

            data: Dict[str, Any] = {"pages": pages}
            if req is not None:
                data["request_id"] = req.request_id
            events.emit("kv.pull",
                        message=f"hydrated {pages} KV pages from the "
                                f"store tier", data=data,
                        trace_id=(req.trace_ctx[0]
                                  if req is not None and req.trace_ctx
                                  else None),
                        coalesce_s=0.0 if req is not None else 1.0)
        except Exception:
            pass

    def _extract_pages(self, pages: List[int]):
        """Host copies of the given pages' KV (seal extraction).  Runs on
        the scheduler thread; registered full pages are append-only (COW
        duplicates into fresh pages, suffix prefill writes positions past
        the registered prefix), so the read is not torn."""
        idx = np.asarray(pages)
        return (np.asarray(self.cache_k[:, idx]),
                np.asarray(self.cache_v[:, idx]))

    def _maybe_seal(self, tokens: List[int], force: bool = False) -> None:
        tier, pc = self.kv_tier, self.prefix_cache
        if tier is None or pc is None:
            return
        if tier.maybe_seal(pc, self._extract_pages, tokens, force=force):
            self._stats["kv_seals"] += 1
            self._m["kv_seals"].inc()

    def _maybe_tier_pull(self, tokens: List[int],
                         req: Optional[_Request] = None):
        """Admission-path pull: hydrate this prompt's family spine from
        the tier when the store holds more of it than the local pool.
        Every failure is a typed fallback to cold prefill, never an
        admission error.  Returns ``(outcome, pages_hydrated)`` where
        outcome is None (prompt too short to ever pull), "miss" (family
        never sealed), "resident" (pool already covers the blob),
        "hydrated", or the typed KVPullError reason — the admission path
        stamps it on the request's kv-pull span."""
        tier, pc = self.kv_tier, self.prefix_cache
        ps = self.cfg.page_size
        cap = (len(tokens) - 1) // ps  # ≥1 suffix token stays to prefill
        if cap <= 0:
            return None, 0
        root_hex = pc.root_digest_for(tokens, ps)
        rec = tier.lookup_for_pull(root_hex)
        if rec is None:
            # never sealed: plain cold traffic, not a fallback
            return "miss", 0
        local = pc.peek_match_tokens(tokens) // ps
        if min(int(rec.get("blocks", 0)), cap) <= local:
            return "resident", 0  # the pool already covers the blob
        try:
            spine, kv_k, kv_v = tier.pull(root_hex, rec=rec,
                                          expect=self._tier_expect())
        except KVPullError as e:
            self._kv_fallback(e.reason, req=req)
            return e.reason, 0
        n = self._hydrate_spine(spine, kv_k, kv_v, limit_tokens=tokens,
                                req=req)
        if n is None:
            return "no_pages", 0  # _hydrate_spine already logged fallback
        if n > 0:
            self._note_kv_pull(n, req=req)
            return "hydrated", n
        return "resident", 0

    def _drain_hydrations(self) -> bool:
        """Scheduler-thread half of kv_prehydrate: pull queued family
        roots and hydrate their full spines."""
        tier, pc = self.kv_tier, self.prefix_cache
        did = False
        while tier is not None and pc is not None:
            try:
                root_hex = self._hydrate_q.get_nowait()
            except queue_mod.Empty:
                break
            rec = tier.lookup(root_hex)
            if rec is None:
                continue  # nothing sealed under that root (yet)
            try:
                spine, kv_k, kv_v = tier.pull(root_hex, rec=rec,
                                              expect=self._tier_expect())
            except KVPullError as e:
                self._kv_fallback(e.reason)
                continue
            n = self._hydrate_spine(spine, kv_k, kv_v)
            if n:
                did = True
                self._note_kv_pull(n)
        return did

    def _hydrate_spine(self, spine: List[int], kv_k, kv_v,
                       limit_tokens: Optional[List[int]] = None,
                       req: Optional[_Request] = None) -> Optional[int]:
        """Scatter a pulled spine's missing blocks into fresh pages and
        register them cached-resident; returns pages hydrated (0 = all
        resident / nothing usable, None = the pool couldn't cover the
        scatter — a "no_pages" fallback).  With ``limit_tokens``
        (admission path) only the blocks that are a true prefix of that
        prompt are hydrated, capped so ≥1 suffix token remains to
        prefill."""
        pc = self.prefix_cache
        ps = self.cfg.page_size
        nblk = int(kv_k.shape[1])
        m = min(nblk, self.max_pages_per_seq)
        if limit_tokens is not None:
            cap = min(m, (len(limit_tokens) - 1) // ps)
            m = 0
            while (m < cap and list(spine[m * ps:(m + 1) * ps])
                   == [int(t) for t in limit_tokens[m * ps:(m + 1) * ps]]):
                m += 1
        if m <= 0:
            return 0
        probe = list(spine[:m * ps]) + [0]  # sentinel suffix token: _walk
        # caps at (n-1)//ps, so this matches exactly the m spine blocks
        resident = pc.match(probe)
        k_res = len(resident)
        if k_res >= m:
            return 0
        need = m - k_res
        # pin the resident prefix BEFORE reserving — eviction inside
        # _reserve must not reclaim the chain we're extending
        self.allocator.retain(resident)
        if not self._reserve(need):
            self.allocator.free(resident)
            self._kv_fallback("no_pages", req=req)
            return None
        fresh = self.allocator.allocate(need)
        P = self.max_pages_per_seq
        idx = np.zeros(P, np.int32)
        idx[:need] = fresh
        sel_k = np.ascontiguousarray(kv_k[:, k_res:m])
        sel_v = np.ascontiguousarray(kv_v[:, k_res:m])
        if need < P:
            pad = ((0, 0), (0, P - need), (0, 0), (0, 0), (0, 0))
            sel_k = np.pad(sel_k, pad)
            sel_v = np.pad(sel_v, pad)
        # same donated jitted scatter (and compiled shape) as decode_kv
        # admission: padded rows land in the null page 0
        self.cache_k, self.cache_v = _inject_kv_pages(
            self.cache_k, self.cache_v, jnp.asarray(idx),
            jnp.asarray(sel_k, self.cache_k.dtype),
            jnp.asarray(sel_v, self.cache_v.dtype))
        cached = pc.insert(list(spine[:m * ps]), resident + fresh)
        self.allocator.mark_cached(cached)
        # release both the fresh allocation and the resident pins: every
        # spine page ends cached-resident, exactly like a finished
        # sequence's pages — the next match_cow retains them as a hit
        self.allocator.free(fresh)
        self.allocator.free(resident)
        return need

    def _preempt(self, i: int, s: _Slot) -> None:
        """Evict a running sequence (vLLM's recompute preemption): accepted
        tokens fold into the prompt and the request requeues at the FRONT.
        Its full pages are registered in the prefix cache first, so the
        resume prefill usually restarts from a long prefix hit rather than
        from scratch."""
        req = s.request
        seq = req.prompt_tokens + s.generated
        # KV is resident exactly for positions < num_tokens
        self._register_blocks(seq[:s.num_tokens], s.pages)
        req.prompt_tokens = seq
        req.kind = "normal"
        req.kv = None
        req.first_token = None
        self.allocator.free(s.pages)
        self._slots[i] = None
        self._stats["preempted"] += 1
        self._m["preempted"].inc()
        req.preempts += 1
        if req.trace_ctx is not None:
            now_w = time.time()
            self._span(req, "llm.preempt", now_w, now_w, ok=False,
                       tokens=s.num_tokens, produced=req.produced)
        try:
            from ray_tpu.util import events

            # identity, not an anonymous count: `rtpu events --trace`
            # shows this preemption inside the request's own tree
            events.emit("llm.preempt",
                        message=f"request {req.request_id} evicted from "
                                f"its slot (recompute preemption, "
                                f"{s.num_tokens} tokens resident)",
                        data={"tokens": s.num_tokens,
                              "request_id": req.request_id,
                              "produced": req.produced},
                        trace_id=(req.trace_ctx[0]
                                  if req.trace_ctx else None))
        except Exception:
            pass
        self._waiting.queue.appendleft(req)  # type: ignore[attr-defined]

    def _shared_pages(self, s: _Slot) -> int:
        """Pages of slot `s` also held by another sequence or by the
        prefix cache — KV that survives this slot's preemption for free."""
        alloc = self.allocator
        return sum(1 for p in s.pages
                   if alloc.refcount(p) > 1 or alloc.is_cached(p))

    def _ensure_capacity(self, steps: int) -> None:
        """Grow each slot's page list to cover the next `steps` decode
        writes (lazy allocation's other half).  Earliest-submitted slots
        grow first; when the pool is dry even after cache eviction, the
        victim is the slot holding the FEWEST shared (refcount>1 or
        cached-resident) pages — its resume prefill recomputes the most
        from scratch either way, so preempting it throws away the least
        reusable KV.  Ties fall to the latest-submitted slot (FCFS)."""
        ps = self.cfg.page_size
        order = sorted(
            ((i, s) for i, s in enumerate(self._slots) if s is not None),
            key=lambda t: t[1].request.submitted_at)
        for i, s in order:
            while self._slots[i] is s:
                sp = s.request.params
                remaining = max(1, sp.max_tokens - s.request.produced)
                k = min(steps, remaining)
                need = min((s.num_tokens + k - 1) // ps + 1,
                           self.max_pages_per_seq)
                delta = need - len(s.pages)
                if delta <= 0:
                    break
                if self._reserve(delta):
                    s.pages.extend(self.allocator.allocate(delta))
                    break
                victim = min(
                    ((j, t) for j, t in enumerate(self._slots)
                     if t is not None),
                    key=lambda t: (self._shared_pages(t[1]),
                                   -t[1].request.submitted_at))
                self._preempt(*victim)
                # if we preempted ourselves the while condition exits

    def _decode_all(self) -> bool:
        active_slots = [(i, s) for i, s in enumerate(self._slots)
                        if s is not None]
        if not active_slots:
            return False
        all_greedy = all(s.request.params.temperature <= 0
                         for _, s in active_slots)
        # Burst decode: chain several device-fed greedy steps and fetch
        # once.  The host round trip (PCIe/tunnel) costs many times the
        # decode compute itself; each step's argmax token feeds the
        # next step ON DEVICE.  Overshoot is safe: a slot that finishes
        # mid-burst keeps writing into its own (or the null) pages and
        # the extra tokens are simply not emitted.
        # Stay responsive to admissions only when one could actually
        # happen: work waiting, a slot to put it in, AND enough pool
        # headroom (free + reclaimable cache pages) for the head-of-queue
        # request's lazy admission (mirrors _admit's own checks) —
        # otherwise burst; admission is impossible until a sequence
        # finishes anyway.
        can_admit = False
        if any(s is None for s in self._slots):
            try:
                head = self._waiting.queue[0]  # type: ignore[attr-defined]
                n = len(head.prompt_tokens)
                if head.kind == "prefill_only":
                    n_pages = -(-n // self.cfg.page_size)
                else:
                    n_pages = n // self.cfg.page_size + 1
                can_admit = (self.allocator.num_free()
                             + self.allocator.num_resident()) >= n_pages
            except IndexError:
                pass
        burst = 8 if (all_greedy and not can_admit) else 1
        # lazy allocation's second half: cover the burst's decode writes,
        # preempting under pool pressure — slots may vanish here
        self._ensure_capacity(burst)
        active_slots = [(i, s) for i, s in enumerate(self._slots)
                        if s is not None]
        if not active_slots:
            return True  # everything preempted; _admit resumes them
        B = self.cfg.max_slots
        P = self.max_pages_per_seq
        tokens = np.zeros(B, np.int32)
        positions = np.zeros(B, np.int32)
        tables = np.zeros((B, P), np.int32)
        active = np.zeros(B, bool)
        for i, s in active_slots:
            tokens[i] = s.last_token
            positions[i] = s.num_tokens  # position of the new token
            tables[i, :len(s.pages)] = s.pages
            active[i] = True
        if all_greedy:
            toks_dev = jnp.asarray(tokens)
            pos_dev = jnp.asarray(positions)
            tables_dev = jnp.asarray(tables)
            active_dev = jnp.asarray(active)
            steps = []
            for j in range(burst):
                toks_dev, self.cache_k, self.cache_v = \
                    lm.decode_step_greedy(
                        self.params, toks_dev, self.cache_k, self.cache_v,
                        tables_dev, pos_dev + j, active_dev,
                        self.model_cfg)
                steps.append(toks_dev)
            # ONE host round trip for the whole burst (stack on device)
            rows = np.asarray(jnp.stack(steps)) if burst > 1 else [
                np.asarray(steps[0])]
            self._stats["decode_steps"] += burst
            self._m["decode_steps"].inc(burst)
            for row in rows:
                for i, s in active_slots:
                    if self._slots[i] is not s:
                        continue  # finished earlier in this burst
                    self._accept_token(i, s, int(row[i]))
            return True
        logits, self.cache_k, self.cache_v = lm.decode_step(
            self.params, jnp.asarray(tokens), self.cache_k,
            self.cache_v, jnp.asarray(tables), jnp.asarray(positions),
            jnp.asarray(active), self.model_cfg)
        logits_np = np.asarray(logits)
        self._stats["decode_steps"] += 1
        self._m["decode_steps"].inc()
        for i, s in active_slots:
            tok = self._sample_one(logits_np[i], s.request.params, s.rng)
            self._accept_token(i, s, tok)
        return True

    def _accept_token(self, i: int, s: _Slot, tok: int):
        """Record one sampled token for slot i: emit, finish, or continue."""
        s.num_tokens += 1  # last_token's KV is now in the cache
        sp = s.request.params
        if tok in sp.stop_token_ids:
            self._release_slot(i, s)
            return
        s.generated.append(tok)
        self._emit(s, tok)
        if s.request.produced >= sp.max_tokens:
            self._release_slot(i, s)
        else:
            s.last_token = tok

    def _release_slot(self, i: int, s: _Slot) -> None:
        """Finish a sequence: register its full pages (prompt AND generated
        KV — a follow-up turn extending this conversation hits them) and
        release; cached pages stay resident until the pool reclaims them."""
        self._finish_request(s.request)
        s.request.out_queue.put(None)
        seq = s.request.prompt_tokens + s.generated
        self._register_blocks(seq[:s.num_tokens], s.pages)
        self.allocator.free(s.pages)
        self._slots[i] = None

    def _emit(self, slot: _Slot, token: int):
        self._stats["tokens_generated"] += 1
        req = slot.request
        req.emitted += 1
        req.produced += 1  # survives preemption (len(generated) does not)
        if req.first_token_at is None:
            req.first_token_at = time.monotonic()
            self._m["ttft"].observe(
                req.first_token_at - req.submitted_at,
                exemplar=req.trace_ctx[0] if req.trace_ctx else None)
        self._m["tokens"].inc()
        req.out_queue.put(int(token))

    def _sample_one(self, logits: np.ndarray, params: SamplingParams,
                    rng: Optional[np.random.Generator]) -> int:
        if params.temperature <= 0 or rng is None:
            return int(np.argmax(logits))
        probs = logits / params.temperature
        probs = np.exp(probs - probs.max())
        probs /= probs.sum()
        if params.top_p < 1.0:
            order = np.argsort(-probs)
            csum = np.cumsum(probs[order])
            cut = np.searchsorted(csum, params.top_p) + 1
            keep = order[:cut]
            mask = np.zeros_like(probs)
            mask[keep] = probs[keep]
            probs = mask / mask.sum()
        return int(rng.choice(len(probs), p=probs))
