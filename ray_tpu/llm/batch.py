"""Batch LLM inference over ray_tpu.data (the reference's ray.data.llm).

Counterpart of /root/reference/python/ray/llm/_internal/batch/processor/
(vllm_engine_proc.py + stages/): build_llm_processor returns a
Dataset -> Dataset callable whose stages are map_batches ops — tokenize →
engine generate (actor pool, one engine per actor) → detokenize.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Optional

from ray_tpu.llm.engine import EngineConfig, LLMEngine, SamplingParams
from ray_tpu.llm.tokenizer import get_tokenizer


@dataclass
class ProcessorConfig:
    """Reference: batch/processor/__init__.py ProcessorConfig lineage."""

    model_loader: Callable = None  # () -> (params, LlamaConfig)
    tokenizer: Optional[str] = None
    engine_config: EngineConfig = field(default_factory=EngineConfig)
    concurrency: int = 1  # engine actors
    batch_size: int = 16
    sampling: Dict[str, Any] = field(default_factory=dict)
    num_tpus: Optional[float] = None
    # wrap each prompt in the tokenizer's chat template (reference:
    # batch/stages/chat_template_stage.py)
    apply_chat_template: bool = False


class _EngineUDF:
    """Actor-pool UDF hosting one engine (reference:
    vllm_engine_proc.py engine stage)."""

    def __init__(self, config: ProcessorConfig):
        params, model_cfg = config.model_loader()
        self._tok = get_tokenizer(config.tokenizer)
        self._engine = LLMEngine(params, model_cfg, config.engine_config)
        self._engine.start()
        self._sampling = config.sampling
        self._config = config

    def __call__(self, batch: Dict[str, Any]) -> Dict[str, Any]:
        import numpy as np

        prompts = [str(p) for p in batch["prompt"]]
        if self._config.apply_chat_template:
            prompts = [self._tok.apply_chat_template(
                [{"role": "user", "content": p}]) for p in prompts]
        reqs = []
        eos = getattr(self._tok, "eos_id", None)
        sp = dict(self._sampling)
        if eos is not None:
            # ALWAYS stop at eos, including when the user supplied extra
            # stop ids — matching serve-side behavior (server.py)
            sp["stop_token_ids"] = tuple(
                sp.get("stop_token_ids", ())) + (eos,)
        for p in prompts:
            reqs.append(self._engine.submit(
                self._tok.encode(p), SamplingParams(**sp)))
        texts, token_lists = [], []
        for r in reqs:
            toks = []
            while True:
                item = r.out_queue.get(timeout=600)
                if item is None:
                    break
                if isinstance(item, Exception):
                    raise item
                toks.append(item)
            token_lists.append(toks)
            texts.append(self._tok.decode(toks))
        out_batch = dict(batch)
        out_batch["generated_text"] = texts
        out_batch["generated_tokens"] = np.array(
            [np.asarray(t, np.int64) for t in token_lists], dtype=object)
        return out_batch


def build_llm_processor(config: ProcessorConfig,
                        preprocess: Optional[Callable] = None,
                        postprocess: Optional[Callable] = None):
    """Returns Dataset -> Dataset.  Rows need a "prompt" column (or supply
    ``preprocess`` to create one)."""

    def processor(ds):
        if preprocess is not None:
            # row-wise hook, as in the reference's build_llm_processor
            ds = ds.map(preprocess)
        ds = ds.map_batches(
            _EngineUDF,
            fn_constructor_args=(config,),
            concurrency=config.concurrency,
            batch_size=config.batch_size,
            num_tpus=config.num_tpus,
            batch_format="numpy")
        if postprocess is not None:
            ds = ds.map(postprocess)
        return ds

    return processor
